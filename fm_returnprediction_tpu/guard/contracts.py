"""Declarative invariant contracts at the pipeline's stage boundaries.

The resilience layer (PR 2) recovers from failures that THROW; this module
is the tripwire for failures that stay silent — a duplicated permno, a
non-monotone calendar, a NaN-flooded cross-section, a characteristic
scaled into f32-overflow territory — which would otherwise flow straight
into Table 2 t-stats. A contract is a named :class:`Rule` with a declared
severity, evaluated against a stage's product:

- ``fail``       → raise :class:`ContractViolationError` (stop the run:
  the data is wrong and every downstream number would be too);
- ``quarantine`` → the artifact/month is dropped and the run continues
  degraded — the serving front-end's existing quarantine machinery
  (:class:`IngestRejectedError` → last-known-good state keeps quoting)
  and the pipeline's optional-artifact screen both consume this rung;
- ``warn``       → :class:`GuardWarning` + an audit entry (the invariant
  is a convention, not a correctness requirement — e.g. a coherently
  permuted firm vocabulary changes no statistic).

Evaluation short-circuits at the first ``fail``/``quarantine`` violation
(later rules may assume the earlier invariant — a bounds check cannot run
on a mis-shaped array); ``warn`` violations collect and evaluation
continues. Every violation lands in the run's :class:`AuditRecord`, which
also absorbs the numerical sentinel counters (``guard.checks``) and the
serving quarantine ledger — ONE place that answers "what did the guards
see this run".

Panel contracts reduce the (T, N, K) panel ON DEVICE through one fused
probe program (tiny per-column moment vectors cross the host boundary, not
the panel) and the probe doubles as the drift sentinel's panel summary
(``guard.drift``), so the contract layer prices one small program — not a
panel pull — per guarded run.
"""

from __future__ import annotations

import dataclasses
import warnings
from collections import Counter
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from fm_returnprediction_tpu.resilience.errors import (
    ContractViolationError,
    IngestRejectedError,
)

__all__ = [
    "GuardWarning",
    "Violation",
    "Rule",
    "AuditRecord",
    "evaluate",
    "enforce",
    "screen_artifact",
    "panel_probe",
    "panel_rules",
    "check_panel",
    "frame_rules",
    "check_frame",
    "cross_section_rules",
    "serving_state_rules",
    "VALUE_BOUND",
]

SEVERITIES = ("fail", "quarantine", "warn")

# |characteristic| beyond this is treated as corruption, not data: nothing
# in the panel (log-scales, ratios, returns, raw $M market equity) comes
# within orders of magnitude, while values past ~1.8e19 overflow an f32
# Gram contraction (x² > f32 max 3.4e38) — the bound trips well before the
# numerics silently saturate.
VALUE_BOUND = 1e15


class GuardWarning(UserWarning):
    """A warn-severity contract violation (recorded, never raised)."""


@dataclasses.dataclass(frozen=True)
class Violation:
    """One named contract breach: which rule, how bad, what it saw."""

    rule: str
    severity: str
    detail: str

    def __str__(self) -> str:
        return f"[{self.severity}] {self.rule}: {self.detail}"


@dataclasses.dataclass(frozen=True)
class Rule:
    """A named invariant with a declared severity.

    ``check(subject)`` returns ``None`` when the invariant holds, else a
    human-readable detail string. A check that CRASHES is itself reported
    as a violation at the rule's severity — a contract that cannot even
    evaluate means an upstream invariant it assumed is broken."""

    name: str
    severity: str
    check: Callable[[object], Optional[str]]

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"severity for {self.name!r} must be one of {SEVERITIES}, "
                f"got {self.severity!r}"
            )


@dataclasses.dataclass
class AuditRecord:
    """The run-level guard ledger: contract violations, numerical sentinel
    counters, and artifacts/months quarantined. Attached to
    ``PipelineResult.audit`` and serialized into the drift manifest."""

    violations: List[Violation] = dataclasses.field(default_factory=list)
    counters: Counter = dataclasses.field(default_factory=Counter)
    quarantined: List[str] = dataclasses.field(default_factory=list)

    def record(self, violations: Sequence[Violation]) -> None:
        self.violations.extend(violations)

    def record_counters(self, counts: Dict[str, int]) -> None:
        for name, count in counts.items():
            if count:
                self.counters[name] += int(count)

    def names(self) -> List[str]:
        return [v.rule for v in self.violations]

    def ok(self) -> bool:
        return not self.violations and not self.counters

    def as_dict(self) -> dict:
        return {
            "violations": [dataclasses.asdict(v) for v in self.violations],
            "counters": dict(self.counters),
            "quarantined": list(self.quarantined),
        }

    def report(self) -> str:
        lines = [str(v) for v in self.violations]
        lines += [f"[counter] {k} = {v}" for k, v in sorted(self.counters.items())]
        lines += [f"[quarantined] {name}" for name in self.quarantined]
        return "\n".join(lines) if lines else "guards: clean"


def evaluate(rules: Sequence[Rule], subject) -> List[Violation]:
    """Run the rules in order against ``subject``.

    Short-circuits after the first blocking (``fail``/``quarantine``)
    violation; ``warn`` findings accumulate and evaluation continues."""
    out: List[Violation] = []
    for rule in rules:
        try:
            detail = rule.check(subject)
        except Exception as exc:  # noqa: BLE001 — a crashed check IS a finding
            detail = f"contract check crashed: {exc!r}"
        if detail:
            out.append(Violation(rule.name, rule.severity, str(detail)))
            if rule.severity != "warn":
                break
    return out


def enforce(
    violations: Sequence[Violation],
    audit: Optional[AuditRecord] = None,
    context: str = "",
) -> List[Violation]:
    """Apply the severity ladder: record everything, warn the warns, raise
    the worst blocking severity (``fail`` → :class:`ContractViolationError`,
    ``quarantine`` → :class:`IngestRejectedError` for the caller's
    quarantine machinery to absorb)."""
    violations = list(violations)
    if audit is not None:
        audit.record(violations)
    for v in violations:
        if v.severity == "warn":
            warnings.warn(GuardWarning(str(v)), stacklevel=2)
    prefix = f"{context}: " if context else ""
    fails = [v for v in violations if v.severity == "fail"]
    if fails:
        raise ContractViolationError(
            prefix + "; ".join(str(v) for v in fails)
        )
    quars = [v for v in violations if v.severity == "quarantine"]
    if quars:
        raise IngestRejectedError(
            prefix + "; ".join(str(v) for v in quars)
        )
    return violations


def screen_artifact(
    name: str,
    artifact,
    rules: Sequence[Rule],
    audit: Optional[AuditRecord] = None,
):
    """Contract gate for an OPTIONAL pipeline artifact: on a
    quarantine-severity violation the artifact is dropped (returns ``None``)
    and the run continues degraded — the pipeline-side analog of the
    serving quarantine; ``fail`` still raises."""
    if artifact is None:
        return None
    violations = evaluate(rules, artifact)
    try:
        enforce(violations, audit=audit, context=name)
    except IngestRejectedError as exc:
        if audit is not None:
            audit.quarantined.append(name)
        warnings.warn(
            GuardWarning(f"artifact {name!r} quarantined: {exc}"),
            stacklevel=2,
        )
        return None
    return artifact


# -- panel contracts -------------------------------------------------------


def _probe_program(values, mask):
    """The fused panel reduction behind :func:`panel_probe` (module-level
    jit: ONE cached executable per panel shape, not one per call)."""
    import jax.numpy as jnp

    finite = jnp.isfinite(values)
    cnt = finite.sum(axis=(0, 1))
    inf_cnt = jnp.isinf(values).sum(axis=(0, 1))
    vz = jnp.where(finite, values, 0.0)
    total = vz.sum(axis=(0, 1))
    total2 = jnp.sum(vz * vz, axis=(0, 1))
    vmax = jnp.max(jnp.where(finite, values, -jnp.inf), axis=(0, 1))
    vmin = jnp.min(jnp.where(finite, values, jnp.inf), axis=(0, 1))
    return cnt, inf_cnt, total, total2, vmin, vmax, mask.sum(axis=1)


_PROBE_JIT = None


def panel_probe(panel) -> dict:
    """One fused device reduction of the (T, N, K) panel into the small
    host-side summary every panel rule (and the drift sentinel) consumes:
    per-column finite counts / moments / extrema, per-month mask counts.
    The panel itself never crosses the host boundary."""
    global _PROBE_JIT
    import jax
    import jax.numpy as jnp

    if _PROBE_JIT is None:
        _PROBE_JIT = jax.jit(_probe_program)
    cnt, inf_cnt, total, total2, vmin, vmax, mask_counts = jax.device_get(
        _PROBE_JIT(jnp.asarray(panel.values), jnp.asarray(panel.mask))
    )
    cnt = cnt.astype(np.int64)
    safe = np.maximum(cnt, 1).astype(np.float64)
    mean = total.astype(np.float64) / safe
    var = np.maximum(total2.astype(np.float64) / safe - mean * mean, 0.0)
    columns = {}
    for k, name in enumerate(panel.var_names):
        columns[str(name)] = {
            "finite": int(cnt[k]),
            "inf": int(inf_cnt[k]),
            "mean": float(mean[k]) if cnt[k] else None,
            "std": float(np.sqrt(var[k])) if cnt[k] else None,
            "min": float(vmin[k]) if cnt[k] else None,
            "max": float(vmax[k]) if cnt[k] else None,
        }
    t, n, k = (int(s) for s in panel.values.shape)
    return {
        "kind": "panel",
        "shape": [t, n, k],
        "dtype": str(np.dtype(panel.values.dtype)),
        "mask_total": int(np.asarray(mask_counts).sum()),
        "mask_min_month": int(np.asarray(mask_counts).min()) if t else 0,
        "columns": columns,
    }


def panel_rules(
    dtype=None,
    value_bound: float = VALUE_BOUND,
    return_col: str = "retx",
    ret_high: float = 30.0,
) -> List[Rule]:
    """The dense-panel stage-boundary contract.

    Subject: ``(panel, probe)`` — a ``DensePanel`` plus its
    :func:`panel_probe` summary."""

    def _schema(sub):
        panel, probe = sub
        t, n, k = probe["shape"]
        if np.asarray(panel.values).ndim != 3:
            return f"values must be (T, N, K), got ndim {np.asarray(panel.values).ndim}"
        if tuple(np.asarray(panel.mask).shape) != (t, n):
            return f"mask shape {np.asarray(panel.mask).shape} != (T, N) = {(t, n)}"
        if len(panel.months) != t or len(panel.ids) != n:
            return (
                f"axis vocabularies disagree with values: months "
                f"{len(panel.months)} vs T={t}, ids {len(panel.ids)} vs N={n}"
            )
        if len(panel.var_names) != k:
            return f"{len(panel.var_names)} var_names for K={k} columns"
        if not np.issubdtype(np.asarray(panel.values).dtype, np.floating):
            return f"values dtype {np.asarray(panel.values).dtype} is not floating"
        return None

    def _dtype(sub):
        panel, probe = sub
        if dtype is None:
            return None
        got = np.dtype(np.asarray(panel.values).dtype)
        if got != np.dtype(dtype):
            return f"values dtype {got} != configured {np.dtype(dtype)}"
        return None

    def _calendar(sub):
        panel, _ = sub
        months = np.asarray(panel.months).astype("datetime64[ns]")
        if len(months) > 1 and not (np.diff(months.astype(np.int64)) > 0).all():
            bad = int(np.argmin(np.diff(months.astype(np.int64)) > 0))
            return (
                f"months are not strictly increasing at index {bad + 1} "
                f"({months[bad]} -> {months[bad + 1]}): a stale or "
                f"duplicated month entered the calendar"
            )
        return None

    def _key_unique(sub):
        panel, _ = sub
        ids = np.asarray(panel.ids)
        if len(np.unique(ids)) != len(ids):
            uniq, counts = np.unique(ids, return_counts=True)
            dups = uniq[counts > 1][:5]
            return (
                f"{len(ids) - len(np.unique(ids))} duplicated firm id(s) "
                f"(permno appears twice in one month's cross-section): "
                f"e.g. {list(dups)!r}"
            )
        return None

    def _ids_sorted(sub):
        panel, _ = sub
        ids = np.asarray(panel.ids)
        if len(ids) > 1 and not (ids[:-1] <= ids[1:]).all():
            return (
                "firm vocabulary is not sorted (the long_to_dense contract): "
                "the firm axis was permuted — statistics are unaffected by a "
                "coherent relabeling, but positional consumers (serving "
                "states, cached masks) must not mix vocabularies"
            )
        return None

    def _mask_sanity(sub):
        panel, probe = sub
        if np.asarray(panel.mask).dtype != np.bool_:
            return f"mask dtype {np.asarray(panel.mask).dtype} is not bool"
        if probe["mask_total"] == 0:
            return "mask is empty: no firm-month exists anywhere"
        if probe["mask_min_month"] == 0:
            return (
                "a month has zero existing rows — the month vocabulary is "
                "derived from observed rows, so an empty month means a "
                "corrupted calendar or mask"
            )
        return None

    def _value_bounds(sub):
        _, probe = sub
        # literal ±inf entries are ALREADY-overflowed values, not missing
        # data — the finite-moment scan would never see them
        infected = {
            name: col["inf"]
            for name, col in probe["columns"].items() if col.get("inf")
        }
        if infected:
            return (
                f"infinite entries in {sorted(infected)} (counts "
                f"{infected}): already-overflowed or divide-by-zero values"
            )
        offenders = {
            name: col["max"] if abs(col["max"] or 0) >= abs(col["min"] or 0)
            else col["min"]
            for name, col in probe["columns"].items()
            if col["finite"]
            and max(abs(col["min"]), abs(col["max"])) > value_bound
        }
        if offenders:
            return (
                f"|value| exceeds the guard bound {value_bound:g} in "
                f"{sorted(offenders)} (worst: {offenders}); magnitudes this "
                f"large overflow an f32 Gram contraction"
            )
        return None

    def _return_bounds_low(sub):
        _, probe = sub
        col = probe["columns"].get(return_col)
        if col and col["finite"] and col["min"] is not None and col["min"] < -1.0 - 1e-9:
            return (
                f"{return_col} has a return below -100% (min "
                f"{col['min']:.6g}): impossible for a simple return — "
                f"corrupted data"
            )
        return None

    def _return_bounds_high(sub):
        _, probe = sub
        col = probe["columns"].get(return_col)
        if col and col["finite"] and col["max"] is not None and col["max"] > ret_high:
            return (
                f"{return_col} max {col['max']:.6g} exceeds the plausibility "
                f"bound {ret_high:g} ({ret_high:.0%})"
            )
        return None

    def _nan_budget(sub):
        _, probe = sub
        dead = [n for n, c in probe["columns"].items() if c["finite"] == 0]
        if dead:
            return (
                f"{len(dead)} all-NaN column(s): {sorted(dead)} — every "
                f"downstream regression silently drops them"
            )
        return None

    return [
        Rule("panel.schema", "fail", _schema),
        Rule("panel.dtype", "fail", _dtype),
        Rule("panel.calendar_monotone", "fail", _calendar),
        Rule("panel.key_unique", "fail", _key_unique),
        Rule("panel.ids_sorted", "warn", _ids_sorted),
        Rule("panel.mask_sanity", "fail", _mask_sanity),
        Rule("panel.value_bounds", "fail", _value_bounds),
        Rule("panel.return_bounds_low", "fail", _return_bounds_low),
        Rule("panel.return_bounds_high", "warn", _return_bounds_high),
        Rule("panel.nan_budget", "warn", _nan_budget),
    ]


def check_panel(
    panel,
    dtype=None,
    audit: Optional[AuditRecord] = None,
    context: str = "panel",
    probe: Optional[dict] = None,
) -> dict:
    """Probe + evaluate + enforce the panel contract; returns the probe
    (reused by the drift sentinel as the ``panel_stats`` summary).

    A panel the probe cannot even reduce (wrong rank, mismatched axes —
    e.g. a torn checkpoint) is itself a schema violation: it surfaces as
    the TYPED ``ContractViolationError`` the taskgraph's failure ledger
    expects, never a raw numpy/jax unpacking error."""
    if probe is None:
        try:
            probe = panel_probe(panel)
        except Exception as exc:  # noqa: BLE001 — unreadable IS the finding
            violation = Violation(
                "panel.schema", "fail",
                f"panel is structurally unreadable by the probe: {exc!r}",
            )
            if audit is not None:
                audit.record([violation])
            raise ContractViolationError(
                f"{context}: {violation}"
            ) from exc
    enforce(evaluate(panel_rules(dtype=dtype), (panel, probe)),
            audit=audit, context=context)
    return probe


# -- report-frame contracts ------------------------------------------------


def frame_rules(name: str, blocking: str = "fail") -> List[Rule]:
    """Stage-boundary contract for a reporting DataFrame (works on both
    numeric frames and the formatted string tables — values are coerced).

    ``blocking`` is the severity of the structural rules: ``"fail"`` for
    core artifacts (Table 1/2 — the run IS those tables), ``"quarantine"``
    for optional ones the pipeline can complete without (the
    :func:`screen_artifact` path drops them and continues degraded)."""

    def _coerce(df):
        import pandas as pd

        return df.apply(pd.to_numeric, errors="coerce")

    def _nonempty(df):
        if df is None or df.shape[0] == 0 or df.shape[1] == 0:
            shape = None if df is None else df.shape
            return f"frame is empty (shape {shape})"
        return None

    def _not_flooded(df):
        num = _coerce(df)
        if num.size and not np.isfinite(num.to_numpy(dtype=float)).any():
            return "no finite value anywhere in the frame"
        return None

    def _dead_columns(df):
        num = _coerce(df)
        vals = num.to_numpy(dtype=float)
        if not vals.size:
            return None
        dead = [
            str(col) for col, finite in
            zip(num.columns, np.isfinite(vals).any(axis=0))
            if not finite
        ]
        # the formatted Table 2 legitimately carries all-blank R²/t-stat
        # sub-columns on N rows; flag only a majority-dead frame
        if dead and len(dead) > num.shape[1] // 2:
            return f"{len(dead)}/{num.shape[1]} columns have no finite value"
        return None

    return [
        Rule(f"{name}.nonempty", blocking, _nonempty),
        Rule(f"{name}.nonfinite_flood", blocking, _not_flooded),
        Rule(f"{name}.dead_columns", "warn", _dead_columns),
    ]


def check_frame(
    frame, name: str, audit: Optional[AuditRecord] = None
) -> None:
    enforce(evaluate(frame_rules(name), frame), audit=audit, context=name)


def backtest_rules(blocking: str = "quarantine") -> List[Rule]:
    """Stage-boundary contract for the backtest cell frame — the generic
    frame rules plus the metric-range invariants the backtest schema
    promises: the required per-cell columns exist, finite ``oos_r2`` never
    exceeds 1 (R² vs ANY benchmark is bounded above by a perfect fit),
    ICs are correlations in [−1, 1], and one-way turnover of a normalized
    long-short book lives in [0, 1] per leg."""
    required = ("cell", "scheme", "set", "universe", "weighting",
                "oos_r2", "ic_mean", "spread", "spread_tstat",
                "spread_turnover", "n_months")

    def _has_columns(df):
        missing = [c for c in required if c not in df.columns]
        if missing:
            return f"backtest frame lacks required columns {missing}"
        return None

    def _in_band(col, lo, hi):
        def check(df):
            if col not in df.columns:  # presence is _has_columns's call
                return None
            vals = np.asarray(df[col], dtype=float)
            vals = vals[np.isfinite(vals)]
            if vals.size and ((vals < lo).any() or (vals > hi).any()):
                return (f"{col} outside [{lo}, {hi}]: "
                        f"range [{vals.min():.4g}, {vals.max():.4g}]")
            return None

        return check

    return frame_rules("backtest", blocking) + [
        Rule("backtest.columns", blocking, _has_columns),
        Rule("backtest.oos_r2_bound", blocking,
             _in_band("oos_r2", -np.inf, 1.0)),
        Rule("backtest.ic_band", blocking, _in_band("ic_mean", -1.0, 1.0)),
        Rule("backtest.rank_ic_band", blocking,
             _in_band("rank_ic_mean", -1.0, 1.0)),
        Rule("backtest.turnover_band", blocking,
             _in_band("spread_turnover", 0.0, 1.0)),
    ]


# -- serving cross-section contracts ---------------------------------------


def cross_section_rules(
    state, month=None, value_bound: float = VALUE_BOUND
) -> List[Rule]:
    """The ONE definition of a valid ingest cross-section, shared by the
    batch and serving paths (``serving.ingest.validate_cross_section`` is
    a thin wrapper). Subject: the coerced ``(y, x, mask)`` triple.

    All severities are ``quarantine``: the serving front-end's degraded
    mode (keep quoting last-known-good, ledger the month) is exactly the
    right blast radius for one bad month."""

    def _shape(sub):
        _, x, _ = sub
        if x.ndim != 2:
            return f"x must be (N, P), got shape {x.shape}"
        if x.shape[-1] != state.n_predictors:
            return (
                f"expected {state.n_predictors} predictors ({state.xvars}), "
                f"got {x.shape[-1]}"
            )
        return None

    def _length(sub):
        y, x, mask = sub
        if not (y.shape == mask.shape == x.shape[:1]):
            return (
                f"length mismatch: y {y.shape}, x {x.shape}, mask {mask.shape}"
            )
        return None

    def _nan_flood(sub):
        y, x, mask = sub
        if mask.any() and not np.isfinite(x[mask]).any():
            return (
                "all-NaN cross-section: no finite predictor in any masked row"
            )
        return None

    def _y_bounds(sub):
        y, x, mask = sub
        if mask.any() and np.isinf(y[mask]).any():
            return "infinite realized return in y"
        return None

    def _value_bounds(sub):
        y, x, mask = sub
        if not mask.any():
            return None
        xm = x[mask]
        finite = np.isfinite(xm)
        if finite.any():
            worst = float(np.abs(np.where(finite, xm, 0.0)).max())
            if worst > value_bound:
                return (
                    f"predictor magnitude {worst:.3g} exceeds the guard "
                    f"bound {value_bound:g} (f32 Gram overflow territory)"
                )
        return None

    def _stale_repeat(sub):
        if month is None or state.n_months == 0:
            return None
        stamp = np.datetime64(month, "ns")
        if stamp == state.months[-1]:
            return None  # a merge re-offer of the SAME month is legal
        y, x, mask = sub
        from fm_returnprediction_tpu.serving.state import _support_bounds

        lo, hi = _support_bounds(
            np.asarray(x)[None], np.asarray(mask, dtype=bool)[None]
        )
        lo, hi = lo[0], hi[0]
        if not (np.isfinite(lo).any() or np.isfinite(hi).any()):
            return None  # an empty/thin month carries no repeat evidence
        same = (
            np.array_equal(lo, state.x_lo[-1])
            and np.array_equal(hi, state.x_hi[-1])
        )
        if same:
            return (
                f"stale repeated month: the cross-section offered as "
                f"{stamp} is bit-identical (per-column support bounds) to "
                f"the state's last month {state.months[-1]} — the upstream "
                f"feed looks stuck"
            )
        return None

    return [
        Rule("cs.shape", "quarantine", _shape),
        Rule("cs.length", "quarantine", _length),
        Rule("cs.nan_flood", "quarantine", _nan_flood),
        Rule("cs.y_bounds", "quarantine", _y_bounds),
        Rule("cs.value_bounds", "quarantine", _value_bounds),
        Rule("cs.stale_repeat", "quarantine", _stale_repeat),
    ]


# -- serving-state contracts -----------------------------------------------


def serving_state_rules() -> List[Rule]:
    """Sanity contract over a fitted ``ServingState`` before it is
    persisted/published. Quarantine severity: a pipeline run can complete
    (degraded) without its serving artifact, and the taskgraph's
    ``serve_state`` task fails alone under ``keep_going``."""

    def _schema(st):
        t, q = st.coef.shape
        p = st.n_predictors
        if q != p + 1:
            return f"coef width {q} != n_predictors + 1 = {p + 1}"
        bad = [
            name for name, arr, shape in (
                ("months", st.months, (t,)),
                ("month_valid", st.month_valid, (t,)),
                ("slopes_bar", st.slopes_bar, (t, p)),
                ("intercept_bar", st.intercept_bar, (t,)),
                ("x_lo", st.x_lo, (t, p)),
                ("x_hi", st.x_hi, (t, p)),
                ("gram", st.gram, (t, q, q)),
                ("moment", st.moment, (t, q)),
                ("n_obs", st.n_obs, (t,)),
            ) if tuple(np.shape(arr)) != shape
        ]
        if bad:
            return f"leaf shapes inconsistent with T={t}, P={p}: {bad}"
        return None

    def _calendar(st):
        if st.n_months > 1:
            stamps = st.months.astype("datetime64[ns]").astype(np.int64)
            if not (np.diff(stamps) > 0).all():
                return "state months are not strictly increasing"
        return None

    def _stats_finite(st):
        bad = int((~np.isfinite(st.gram)).sum() + (~np.isfinite(st.moment)).sum())
        if bad:
            return (
                f"{bad} non-finite sufficient-statistic entries: a poisoned "
                f"or overflowed month is baked into the state"
            )
        return None

    def _window(st):
        if st.window <= 0 or st.min_periods <= 0 or st.min_periods > st.window:
            return (
                f"window/min_periods ({st.window}/{st.min_periods}) are not "
                f"a valid rolling configuration"
            )
        return None

    return [
        Rule("serving_state.schema", "quarantine", _schema),
        Rule("serving_state.calendar_monotone", "quarantine", _calendar),
        Rule("serving_state.stats_finite", "quarantine", _stats_finite),
        Rule("serving_state.window", "quarantine", _window),
    ]
