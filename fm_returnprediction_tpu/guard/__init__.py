"""Data-integrity guardrail layer: contracts, drift sentinels, numerical guards.

The robustness gap PR 2's resilience layer left open is failures that stay
SILENT — a duplicated permno-month, a stale or permuted cross-section, an
f32 overflow inside a fused Gram contraction — which flow straight into
Table 2 t-stats without a tripwire. Three pieces close it:

- :mod:`.contracts` — declarative invariant contracts (schema/dtype, key
  uniqueness, calendar monotonicity, value/return bounds, NaN budgets,
  mask sanity) evaluated at every stage boundary of ``run_pipeline`` and
  the task graph, with a ``fail``/``quarantine``/``warn`` severity ladder
  that reuses the resilience layer's typed errors and the serving
  quarantine machinery. The run-level :class:`~.contracts.AuditRecord`
  collects every violation and counter.
- :mod:`.drift` — tolerance-banded comparison of each persisted artifact
  (dense panel stats, tables, ``specgrid_scenarios``, ``serving_state``)
  against the previous run's audit manifest (sha256 + summary moments), so
  a change that silently moves slopes beyond band fails loudly with a
  per-column report (``run_pipeline(audit_dir=...)`` / ``--audit-dir``).
- :mod:`.checks` — jit-safe numerical sentinels (finite/overflow counters,
  condition-number taps) riding inside the OLS/FM/NW/Gram programs as
  extra integer outputs: byte-for-byte no-ops when ``FMRP_GUARD=off``,
  zero extra programs/retraces when on.

Everything is free to leave enabled: contracts price one fused probe
program per guarded stage, sentinels a few integer reductions inside
programs that already exist (measured by ``bench.py``'s ``guard_*``
section), and a clean run's artifacts are bit-identical guarded or not.
"""

from fm_returnprediction_tpu.guard.checks import (
    counters,
    drain,
    guard_active,
    guards,
    reset,
    set_guard,
)
from fm_returnprediction_tpu.guard.contracts import (
    AuditRecord,
    GuardWarning,
    Rule,
    Violation,
    check_frame,
    check_panel,
    cross_section_rules,
    enforce,
    evaluate,
    frame_rules,
    panel_probe,
    panel_rules,
    screen_artifact,
    serving_state_rules,
)
from fm_returnprediction_tpu.guard.drift import (
    DriftBand,
    DriftSentinel,
    compare_summaries,
    summarize_arrays,
    summarize_frame,
)
from fm_returnprediction_tpu.resilience.errors import (
    ContractViolationError,
    DriftDetectedError,
)

__all__ = [
    "AuditRecord",
    "ContractViolationError",
    "DriftBand",
    "DriftDetectedError",
    "DriftSentinel",
    "GuardWarning",
    "Rule",
    "Violation",
    "check_frame",
    "check_panel",
    "compare_summaries",
    "counters",
    "cross_section_rules",
    "drain",
    "enforce",
    "evaluate",
    "frame_rules",
    "guard_active",
    "guards",
    "panel_probe",
    "panel_rules",
    "reset",
    "screen_artifact",
    "serving_state_rules",
    "set_guard",
    "summarize_arrays",
    "summarize_frame",
]
