"""Jit-safe numerical sentinels for the OLS/FM/NW/Gram hot paths.

The silent numerical failures the contracts layer cannot see from the host
— an f32 Gram contraction overflowing to ``inf``, a month whose solve went
non-finite, a design whose conditioning ate the answer — happen INSIDE
compiled programs. The sentinels here ride along in those programs as
extra (tiny, integer) outputs and fold into the process-wide audit
counters at the host boundary:

- when guards are OFF the sentinel helpers are never traced at all — the
  hot-path modules gate on :func:`guard_active` at TRACE time, so the
  guard-off jaxpr is byte-for-byte the unguarded program (verified by the
  ``guard`` property tests, which also pin bit-identical outputs and
  unchanged trace counts either way);
- when guards are ON the counters are computed inside the SAME compiled
  program (no extra programs, no callbacks, no host syncs) and recorded
  lazily as device scalars; :func:`drain` pulls them in one
  ``device_get`` when the audit record is assembled;
- a guarded entry point called INSIDE another trace (``fama_macbeth``'s
  program calls ``monthly_cs_ols``) sees tracer counters and skips the
  record — the outermost host boundary owns the accounting and the inner
  counter math is dead code the compiler eliminates. That is what makes
  the sentinels safe to leave in jitted code unconditionally.

The switch is ``FMRP_GUARD`` (default on; ``off``/``0``/``false``
disables), overridable per call via the ``guard=`` parameter the
instrumented entry points expose and per block via :func:`guards`.
Because the flag is a STATIC argument of the instrumented programs,
toggling it selects a different cached executable instead of silently
serving a stale trace.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
from typing import Dict, Optional

__all__ = [
    "guard_active",
    "guards",
    "set_guard",
    "record",
    "record_cs_host",
    "record_fm_host",
    "drain",
    "counters",
    "reset",
    "nonfinite_count",
    "cs_counters",
    "fm_counters",
    "cond_limit",
]


def _env_default() -> bool:
    raw = os.environ.get("FMRP_GUARD", "on").strip().lower()
    return raw not in ("off", "0", "false", "no")


_ENABLED: bool = _env_default()
_LOCK = threading.Lock()
# (site, {counter_name: scalar}) pairs; values may be live device scalars —
# folded (one device_get) by drain(). Bounded: record() folds eagerly past
# _PENDING_CAP so a long guarded run cannot hoard device buffers.
_PENDING: list = []
_PENDING_CAP = 1024
_COUNTERS: collections.Counter = collections.Counter()


def guard_active() -> bool:
    """Whether numerical sentinels are armed (trace-time read)."""
    return _ENABLED


def set_guard(enabled: bool) -> None:
    global _ENABLED
    _ENABLED = bool(enabled)


@contextlib.contextmanager
def guards(enabled: bool):
    """Force sentinels on/off for a block (``run_pipeline``'s ``guard=`` and
    the bench's guarded-vs-unguarded comparison both use this)."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(enabled)
    try:
        yield
    finally:
        _ENABLED = prev


# -- the audit accumulator -------------------------------------------------


def record(site: str, values: Dict[str, object]) -> None:
    """Queue one guarded call's counters under ``site``.

    ``values`` maps counter name → scalar (device array, numpy, or int).
    Tracer values mean the caller is being inlined inside an outer trace —
    the outer host boundary owns the accounting, so the record is skipped
    (and the counter math is unused → dead-code-eliminated)."""
    import jax

    if any(isinstance(v, jax.core.Tracer) for v in values.values()):
        return
    with _LOCK:
        _PENDING.append((site, values))
        overflow = len(_PENDING) >= _PENDING_CAP
    if overflow:
        drain()


def record_cs_host(site: str, cs) -> None:
    """Host-side solve sentinel over a device-pulled (numpy-leaf)
    ``CSRegressionResult`` — the accounting for FUSED sweep programs whose
    inner ``monthly_cs_ols`` records were skipped under the outer trace
    (the figure/decile sweep, the stacked Table 2 route). Handles extra
    leading batch axes (subset-stacked leaves)."""
    if not guard_active():
        return
    import numpy as np

    valid = np.asarray(cs.month_valid)
    bad = np.any(~np.isfinite(np.asarray(cs.slopes)), axis=-1) | ~np.isfinite(
        np.asarray(cs.intercept)
    )
    record(site, {
        "nonfinite_solve_months": int((valid & bad).sum()),
        "nonfinite_r2_months": int(
            (valid & ~np.isfinite(np.asarray(cs.r2))).sum()
        ),
    })


def record_fm_host(site: str, fm) -> None:
    """Host-side NW tap over a device-pulled ``FamaMacbethSummary`` (same
    counting rule as :func:`fm_counters`: INFINITE t-stats only)."""
    if not guard_active():
        return
    import numpy as np

    record(site, {
        "infinite_tstat_cols": int(np.isinf(np.asarray(fm.tstat)).sum()),
    })


def drain() -> Dict[str, int]:
    """Fold every pending record into the process counters (ONE
    ``device_get`` for all pending device scalars) and return a snapshot.
    Counter keys are ``"<site>.<name>"``; zero counts are dropped — the
    audit record lists violations, not visits."""
    import jax

    with _LOCK:
        pending, _PENDING[:] = list(_PENDING), []
    if pending:
        pulled = jax.device_get([v for _, v in pending])
        fresh: Dict[str, int] = {}
        with _LOCK:
            for (site, _), values in zip(pending, pulled):
                for name, val in values.items():
                    count = int(val)
                    if count:
                        key = f"{site}.{name}"
                        _COUNTERS[key] += count
                        fresh[key] = fresh.get(key, 0) + count
        if fresh:
            # mirror into the process metrics registry so the sentinel
            # trips export alongside every other counter (the guard's own
            # _COUNTERS stays the audit-record source of truth)
            from fm_returnprediction_tpu.telemetry import event, registry

            for key, count in fresh.items():
                registry().counter(
                    "fmrp_guard_sentinel_total",
                    help="numerical sentinel trips by site.counter",
                    sentinel=key,
                ).inc(count)
                event("guard.sentinel", cat="guard", sentinel=key,
                      count=count)
    with _LOCK:
        return dict(_COUNTERS)


def counters() -> Dict[str, int]:
    """Snapshot of the accumulated sentinel counters (drains first)."""
    return drain()


def reset() -> None:
    """Clear pending records and accumulated counters (test isolation)."""
    with _LOCK:
        _PENDING[:] = []
        _COUNTERS.clear()


# -- traced counter helpers (call only from inside guarded programs) -------


def cond_limit(dtype) -> float:
    """The shared conditioning threshold: ``1/sqrt(eps)`` of the compute
    dtype — beyond it a Gram/QR solve has lost half the mantissa
    (same policy as the specgrid referee's f64 tier)."""
    import math

    import jax.numpy as jnp

    return 1.0 / math.sqrt(float(jnp.finfo(dtype).eps))


def nonfinite_count(x):
    """Number of non-finite entries of ``x`` (overflow/poison sentinel)."""
    import jax.numpy as jnp

    return jnp.sum(~jnp.isfinite(x))


def cs_counters(cs) -> Dict[str, object]:
    """Sentinels over a ``CSRegressionResult``: months that RAN but whose
    solve or R² came back non-finite (a month skipped for thinness is
    legal and not counted)."""
    import jax.numpy as jnp

    valid = cs.month_valid
    bad_solve = jnp.any(~jnp.isfinite(cs.slopes), axis=-1) | ~jnp.isfinite(
        cs.intercept
    )
    return {
        "nonfinite_solve_months": jnp.sum(valid & bad_solve),
        "nonfinite_r2_months": jnp.sum(valid & ~jnp.isfinite(cs.r2)),
    }


def fm_counters(fm) -> Dict[str, object]:
    """Sentinel over a ``FamaMacbethSummary`` (the NW-path tap): INFINITE
    t-stats — a zero long-run variance, i.e. a degenerate slope series
    (the signature a stale repeated cross-section leaves behind). NaN
    t-stats are deliberately NOT counted: a negative small-sample HAC
    variance estimate legally yields NaN (the reference's blank cell)."""
    import jax.numpy as jnp

    return {
        "infinite_tstat_cols": jnp.sum(jnp.isinf(fm.tstat)),
    }
