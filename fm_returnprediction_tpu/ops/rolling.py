"""Masked trailing-window reductions along the time axis.

The reference's rolling-window kernel family (SURVEY §2.1 ★ rows):

- 11-month product of gross returns (momentum, ``calc_return_12_2``,
  ``src/calc_Lewellen_2014.py:180-186``);
- 24-month sum of log returns (``calc_log_return_13_36``, ``:302-307``);
- 12-month dividend sum with ``min_periods=1`` (``calc_dy``, ``:274-279``);
- 252-day std with ``min_periods=100`` (``calc_std_12``, ``:448-453``);
- 120-month slope mean with ``min_periods=60`` (Figure 1, ``:926``).

All are pandas ``rolling(window, min_periods)`` trailing windows: the window
covers the trailing ``window`` ROWS (truncated at the series start), NaN
entries occupy window positions but are excluded from the reduction, and the
result is NaN until ``min_periods`` non-NaN entries are present.

TPU design: windowed sums are O(T) cumulative-sum differences (one scan per
reduction, HBM-friendly); the windowed product uses ``lax.reduce_window``
with a multiply reducer (window ≤ 36 in this pipeline, so the O(T·w) cost is
trivial and exact — no log/exp detour that would break sign/zero handling).
Everything operates on axis 0 of (T, N) arrays with firms independent along
N, so the firm axis shards with no communication.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = [
    "ROLLING_ROUTES",
    "resolve_rolling_route",
    "windowed_sum",
    "windowed_count",
    "finalize_sum",
    "finalize_mean",
    "finalize_std",
    "rolling_sum",
    "rolling_mean",
    "rolling_std",
    "rolling_prod",
]


def windowed_sum(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """Exact trailing-window sum (window truncated at the start) of a
    NaN-free array via cumulative-sum difference."""
    cs = jnp.cumsum(x, axis=0)
    shifted = jnp.concatenate(
        [jnp.zeros((window,) + x.shape[1:], dtype=cs.dtype), cs[:-window]], axis=0
    )[: x.shape[0]]
    return cs - shifted


def windowed_count(finite: jnp.ndarray, window: int) -> jnp.ndarray:
    """Trailing-window count of True entries."""
    return windowed_sum(finite.astype(jnp.int32), window)


def _gate(value: jnp.ndarray, count: jnp.ndarray, min_periods: int) -> jnp.ndarray:
    return jnp.where(count >= min_periods, value, jnp.nan)


def rolling_sum(
    x: jnp.ndarray, window: int, min_periods: int,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """pandas ``.rolling(window, min_periods).sum()`` on axis 0.

    On TPU 2-D inputs dispatch to the fused pallas kernel by default
    (``ops.pallas_kernels.rolling_sum_fused`` — same one-read/one-write
    structure as the std kernel); ``use_pallas``/``FMRP_ROLLING_ROUTE``
    override, other platforms stay on the XLA cumsum path."""
    if use_pallas is None:
        use_pallas = x.ndim == 2 and _pallas_default(x)
    if use_pallas:
        from fm_returnprediction_tpu.ops.pallas_kernels import rolling_sum_fused

        return rolling_sum_fused(x, window, min_periods)
    finite = jnp.isfinite(x)
    total = windowed_sum(jnp.where(finite, x, 0.0), window)
    return finalize_sum(total, windowed_count(finite, window), min_periods)


def rolling_mean(
    x: jnp.ndarray, window: int, min_periods: int,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """pandas ``.rolling(window, min_periods).mean()`` on axis 0.

    Route dispatch as in ``rolling_sum``."""
    if use_pallas is None:
        use_pallas = x.ndim == 2 and _pallas_default(x)
    if use_pallas:
        from fm_returnprediction_tpu.ops.pallas_kernels import (
            rolling_mean_fused,
        )

        return rolling_mean_fused(x, window, min_periods)
    finite = jnp.isfinite(x)
    total = windowed_sum(jnp.where(finite, x, 0.0), window)
    return finalize_mean(total, windowed_count(finite, window), min_periods)


ROLLING_ROUTES = ("xla", "pallas")


def resolve_rolling_route(x=None, route: str | None = None) -> str:
    """Which route the rolling family dispatches: ``"xla"`` or ``"pallas"``.

    Precedence: explicit ``route`` argument > ``FMRP_ROLLING_ROUTE`` env
    (``auto``/``xla``/``pallas``) > the legacy ``FMRP_PALLAS`` boolean
    (kept as a back-compat alias) > platform default. The platform default
    is pallas on TPU — the rebuilt fully fused kernel (one HBM read, one
    write — ``ops.pallas_kernels``) measured **2.81×** over the XLA cumsum
    path on hardware (``BENCH_r04_self.json``: ``rolling_std_pallas_ms``
    8.337 vs ``rolling_std_xla_ms`` 23.389 on a (12608, 4096) f32 strip,
    TPU v5e) — and xla elsewhere: the kernels are TPU-only by construction
    and interpret mode is a correctness harness, not a fast path.
    ``bench.py`` keeps measuring both paths every TPU round so a
    regression shows up in the artifact.

    The platform is read from ``x``'s committed placement when it has one
    — a process with a TPU backend can still run host-side parity checks
    on CPU-placed arrays (``jax.default_device`` / ``device_put``), and
    those must not dispatch the TPU-only kernel. Traced values and bare
    numpy inputs fall back to the default backend, which is where they
    will land."""
    import os

    if route is None:
        env = os.environ.get("FMRP_ROLLING_ROUTE", "").strip().lower()
        route = env or "auto"
    if route in ROLLING_ROUTES:
        return route
    if route != "auto":
        raise ValueError(
            f"rolling route must be one of {('auto',) + ROLLING_ROUTES}, "
            f"got {route!r}"
        )
    flag = os.environ.get("FMRP_PALLAS")
    if flag is not None:
        on = flag.strip().lower() in ("1", "true", "yes", "on")
        return "pallas" if on else "xla"
    import jax

    platform = None
    if x is not None:
        sharding = getattr(x, "sharding", None)  # absent on tracers/numpy
        if sharding is not None:
            # PUBLIC device API (jax.sharding.Sharding.device_set) — the
            # previous private ``_device_assignment`` read degraded to a
            # silent None on a jax rename, which would have disarmed
            # exactly the protection this exists for (a CPU-committed
            # array dispatching the TPU-only kernel in a TPU-default
            # process)
            device_set = getattr(sharding, "device_set", None)
            if device_set:
                platform = next(iter(device_set)).platform
    if platform is None:
        platform = jax.devices()[0].platform
    return "pallas" if platform == "tpu" else "xla"


def _pallas_default(x=None) -> bool:
    """Back-compat boolean view of ``resolve_rolling_route`` (the original
    ``rolling_std``-only dispatch predicate; callers and tests keep it)."""
    return resolve_rolling_route(x) == "pallas"


def finalize_sum(s1, count, min_periods: int) -> jnp.ndarray:
    """Windowed sum + count → gated rolling sum (shared finalization)."""
    return _gate(s1, count, min_periods)


def finalize_mean(s1, count, min_periods: int) -> jnp.ndarray:
    """Windowed sum + count → gated rolling mean (shared finalization)."""
    mean = s1 / jnp.maximum(count, 1).astype(s1.dtype)
    return _gate(mean, count, min_periods)


def finalize_std(s1, s2, count, min_periods: int) -> jnp.ndarray:
    """Windowed moments → pandas rolling std (ddof=1) with gating.

    With ``finalize_sum``/``finalize_mean``, the ONE home for the
    finalization semantics (count>=2 rule, clamped variance, min_periods
    gates): the single-device paths here and the time-sharded paths
    (``parallel.time_sharded``) all call these, so their promised exact
    parity holds by construction, not by transcription.
    """
    cf = count.astype(s1.dtype)
    denom = jnp.maximum(cf - 1.0, 1.0)
    var = jnp.maximum(s2 - s1 * s1 / jnp.maximum(cf, 1.0), 0.0) / denom
    out = jnp.sqrt(var)
    return _gate(jnp.where(count >= 2, out, jnp.nan), count, min_periods)


def rolling_std(
    x: jnp.ndarray, window: int, min_periods: int, use_pallas: bool | None = None
) -> jnp.ndarray:
    """pandas ``.rolling(window, min_periods).std()`` (ddof=1) on axis 0.

    On TPU this dispatches to the fully fused pallas kernel by default
    (``ops.pallas_kernels.rolling_std_fused``): one HBM read of ``x`` and
    one write of the finished std, vs the several masked/squared/counted
    intermediates plus windowed differencing of the XLA cumsum path —
    measured 2.81× on hardware (BENCH_r04_self.json; the round-2 three-output
    version measured 0.95× and was rebuilt to fuse the differencing and
    finalization too). ``use_pallas``/``FMRP_PALLAS`` override; other
    platforms stay on the XLA path.
    """
    if use_pallas is None:
        use_pallas = x.ndim == 2 and _pallas_default(x)
    if use_pallas:
        from fm_returnprediction_tpu.ops.pallas_kernels import rolling_std_fused

        return rolling_std_fused(x, window, min_periods)
    finite = jnp.isfinite(x)
    xz = jnp.where(finite, x, 0.0)
    count = windowed_count(finite, window)
    s1 = windowed_sum(xz, window)
    s2 = windowed_sum(xz * xz, window)
    return finalize_std(s1, s2, count, min_periods)


def rolling_prod(x: jnp.ndarray, window: int, min_periods: int) -> jnp.ndarray:
    """pandas ``.rolling(window, min_periods).apply(np.prod)`` on axis 0.

    Exact windowed product via ``lax.reduce_window`` with a multiply reducer
    (no cumulative-division trick, so zeros and sign changes are exact). NaNs
    PROPAGATE through the product — pandas calls ``np.prod`` on the raw window
    once ``min_periods`` non-NaN entries are present, and ``np.prod`` of a
    window containing NaN is NaN.
    """
    finite = jnp.isfinite(x)
    prod = jax.lax.reduce_window(
        x,
        jnp.ones((), dtype=x.dtype),
        jax.lax.mul,
        window_dimensions=(window,) + (1,) * (x.ndim - 1),
        window_strides=(1,) * x.ndim,
        padding=((window - 1, 0),) + ((0, 0),) * (x.ndim - 1),
    )
    return _gate(prod, windowed_count(finite, window), min_periods)
