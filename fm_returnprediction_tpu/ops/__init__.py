"""Pure-JAX compute core: batched OLS, FM/NW reductions, rolling windows,
compaction, masked quantiles. Everything here is jit-friendly (static shapes,
masks, ``lax`` control flow) and dtype-polymorphic (f64 for CPU parity runs,
f32 for TPU)."""

from fm_returnprediction_tpu.ops.compaction import (
    Compaction,
    compact,
    lag,
    make_compaction,
    scatter_back,
)
from fm_returnprediction_tpu.ops.fama_macbeth import (
    FamaMacbethSummary,
    fama_macbeth,
    fama_macbeth_summary,
)
from fm_returnprediction_tpu.ops.newey_west import compact_front, nw_mean_se
from fm_returnprediction_tpu.ops.ols import CSRegressionResult, monthly_cs_ols, row_validity
from fm_returnprediction_tpu.ops.quantiles import masked_quantile, winsorize_cs
from fm_returnprediction_tpu.ops.rolling import (
    rolling_mean,
    rolling_prod,
    rolling_std,
    rolling_sum,
    windowed_count,
    windowed_sum,
)

__all__ = [
    "Compaction", "compact", "lag", "make_compaction", "scatter_back",
    "FamaMacbethSummary", "fama_macbeth", "fama_macbeth_summary",
    "compact_front", "nw_mean_se",
    "CSRegressionResult", "monthly_cs_ols", "row_validity",
    "masked_quantile", "winsorize_cs",
    "rolling_mean", "rolling_prod", "rolling_std", "rolling_sum",
    "windowed_count", "windowed_sum",
]
