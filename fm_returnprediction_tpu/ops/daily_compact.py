"""Compacted-ingest daily kernels — the transfer-lean path to full scale.

The dense (D, N) daily panel is mostly padding at real CRSP sparsity (~70-90M
firm-day rows over a 12,608-day × ~25k-firm grid ≈ 20-25% fill), and on a
single chip the daily stage is bound by host→device transfer, not compute
(measured round 2: moving the dense panel takes tens of seconds; the compute
is sub-second per strip). This module ingests each firm's rows ALREADY
COMPACTED — ``values`` (H, N) with each firm's observed rows packed to the
front in chronological order, plus ``pos`` (H, N) int day indices (int16 on
the wire: D < 32,768) — cutting bytes moved to ~6 per observed row and
eliminating the host argsort compaction plan entirely (the round-1 VERDICT's
first memory target, ``ops/compaction.py:44-57``).

On device, ONE fused strip program computes both daily characteristics:

- vol-252 (reference ``calc_std_12``, ``src/calc_Lewellen_2014.py:438-465``):
  the compacted rows ARE pandas' per-firm row windows, so ``rolling_std``
  runs directly on the ingested layout — no compaction step at all.
- The calendar-indexed steps (last-observation-per-month sampling, weekly
  beta, ``src/calc_Lewellen_2014.py:344-434``) run on a dense (D, N) strip
  reconstructed device-side by scatter, sharing the existing dense kernels
  (``ops.daily_kernels``) — so compact vs dense is the same code, not a
  parallel implementation. Measured on TPU v5e: 2D scatter ≈ 290 ms and
  shared-id ``segment_sum`` ≈ 70 ms per (13312, 2432) strip, an order of
  magnitude faster than per-column binary-search formulations (vmapped
  ``searchsorted`` ≈ 1.7 s) that avoid reconstruction.

Padding rows carry ``pos == n_days``; the scatter target has one trash row
at index ``n_days`` that is sliced off, so padding vanishes without masks.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from fm_returnprediction_tpu.ops.daily_kernels import (
    last_obs_per_month,
    weekly_rolling_beta_monthly,
)
from fm_returnprediction_tpu.ops.rolling import rolling_std

__all__ = ["daily_compact_strip", "daily_compact_strip_contiguous"]


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_days", "n_weeks", "n_months",
        "window", "min_periods", "window_weeks", "use_pallas",
    ),
)
def daily_compact_strip_contiguous(
    comp_ret: jnp.ndarray,
    starts: jnp.ndarray,
    counts: jnp.ndarray,
    mkt_d: jnp.ndarray,
    mkt_present: jnp.ndarray,
    day_month_id: jnp.ndarray,
    week_id: jnp.ndarray,
    week_month_id: jnp.ndarray,
    n_days: int,
    n_weeks: int,
    n_months: int,
    window: int = 252,
    min_periods: int = 100,
    window_weeks: int = 156,
    use_pallas: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``daily_compact_strip`` for strips whose firms' rows are DAY-
    CONTIGUOUS (the norm in CRSP: rows exist for every trading day while
    listed, null returns are NaN VALUES on present rows). The (H, C) int16
    position rectangle then carries no information beyond per-firm
    ``starts``/``counts`` — two (C,) int32 vectors cut a third of the
    strip's transfer bytes.

    Contiguity also changes WHICH primitive rebuilds the calendar layout:
    ``dense[d, k] = comp_ret[d - starts[k], k]`` is a pure offset GATHER,
    where the general path needs a scatter through the ``pos`` rectangle.
    XLA's CPU scatter emitter is effectively serial — measured 2.4-4.0 s
    per (13 k, 2.4 k) strip reconstruction on a 24-core box, three of them
    per strip = the entire daily-stage wall at real shape (BENCH_r05's
    30 s / 46 s) — while the offset gather runs the same reconstruction in
    ~0.1 s and row-validity becomes index arithmetic (no gather at all
    for the mask). Outputs are bit-identical to the scatter path (pinned
    by ``tests/test_daily_chunked.py``); on TPU both forms are a single
    fast HLO (measured scatter ≈ 290 ms per strip, ``ops.daily_compact``
    module note), so the gather form is used unconditionally here.
    """
    h = comp_ret.shape[0]
    counts = counts.astype(jnp.int32)
    starts = starts.astype(jnp.int32)
    row = jnp.arange(h, dtype=jnp.int32)[:, None]
    row_present = row < counts[None, :]

    # vol: rolling over the firm's observed rows — already the ingested
    # layout, no reconstruction needed
    vol_rows = rolling_std(
        jnp.where(row_present, comp_ret, jnp.nan), window, min_periods,
        use_pallas=use_pallas,
    ) * jnp.sqrt(jnp.asarray(float(window), dtype=comp_ret.dtype))

    # calendar reconstruction by offset gather: day d of firm k is row
    # (d - starts[k]) when inside [0, counts[k])
    day = jnp.arange(n_days, dtype=jnp.int32)[:, None]
    idx = day - starts[None, :]
    mask = (idx >= 0) & (idx < counts[None, :])
    idx_c = jnp.clip(idx, 0, h - 1)

    def to_cal(x):
        return jnp.where(
            mask, jnp.take_along_axis(x, idx_c, axis=0), jnp.nan
        )

    vol = last_obs_per_month(to_cal(vol_rows), mask, day_month_id, n_months)
    beta = weekly_rolling_beta_monthly(
        to_cal(comp_ret), mask, mkt_d, week_id, n_weeks, week_month_id,
        n_months, window_weeks=window_weeks, mkt_present=mkt_present,
    )
    return vol, beta


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_days", "n_weeks", "n_months",
        "window", "min_periods", "window_weeks", "use_pallas",
    ),
)
def daily_compact_strip(
    comp_ret: jnp.ndarray,
    pos: jnp.ndarray,
    mkt_d: jnp.ndarray,
    mkt_present: jnp.ndarray,
    day_month_id: jnp.ndarray,
    week_id: jnp.ndarray,
    week_month_id: jnp.ndarray,
    n_days: int,
    n_weeks: int,
    n_months: int,
    window: int = 252,
    min_periods: int = 100,
    window_weeks: int = 156,
    use_pallas: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """vol-252 and weekly beta for one compacted firm strip.

    comp_ret : (H, C) firm rows packed to the front (chronological); padding
               slots hold anything (gated by ``pos``).
    pos      : (H, C) int day index of each row, sorted per column;
               ``n_days`` marks padding.
    Remaining args are the shared per-day/per-week vectors of the dense
    kernels. Returns ``(vol, beta)``, each (n_months, C).
    """
    pos = pos.astype(jnp.int32)
    row_present = pos < n_days
    cols = jnp.broadcast_to(
        jnp.arange(comp_ret.shape[1])[None, :], comp_ret.shape
    )

    def to_dense(x, fill):
        out = jnp.full((n_days + 1, x.shape[1]) , fill, dtype=x.dtype)
        return out.at[pos, cols].set(x)[:n_days]  # padding → trash row n_days

    mask = to_dense(row_present, False)

    # vol: rolling over the firm's observed rows — already the ingested layout
    vol_rows = rolling_std(
        jnp.where(row_present, comp_ret, jnp.nan), window, min_periods,
        use_pallas=use_pallas,
    ) * jnp.sqrt(jnp.asarray(float(window), dtype=comp_ret.dtype))
    vol_cal = to_dense(jnp.where(row_present, vol_rows, jnp.nan), jnp.nan)
    vol = last_obs_per_month(vol_cal, mask, day_month_id, n_months)

    # beta: dense reconstruction feeds the exact dense weekly kernel
    ret_cal = to_dense(jnp.where(row_present, comp_ret, jnp.nan), jnp.nan)
    beta = weekly_rolling_beta_monthly(
        ret_cal, mask, mkt_d, week_id, n_weeks, week_month_id, n_months,
        window_weeks=window_weeks, mkt_present=mkt_present,
    )
    return vol, beta
