"""Masked batched cross-sectional OLS — the compute core.

Replaces the reference's per-month Python loop over ``sm.OLS`` fits
(``src/regressions.py:43-72``: ~600 months × 3 subsets × 3 models ≈ 5,400
LAPACK calls) with ONE batched solve over the dense ``(T, N, P)`` panel:

- complete-case row validity (the reference dropna's over the regressand and
  all predictors before the loop, ``src/regressions.py:39``);
- months with fewer valid rows than ``P + 1`` regressors are skipped
  (``src/regressions.py:52``);
- slopes, intercept, cross-sectional R² (centered, as ``mod.rsquared``) and
  the per-month row count N are returned for every month with a validity
  flag instead of a ragged result list.

TPU mapping: the default solver ("qr") Householder-QR-compresses each
month's ``[X | y]`` to its tiny R factor on the MXU and SVD-solves the
compressed system — the same minimum-norm solution as a direct SVD lstsq
(statsmodels/pinv parity, proof at ``_solve_month``), measured 3× faster at
real shape on CPU and matmul-bound instead of decomposition-bound on TPU.
``solver="lstsq"`` is the direct batched SVD (the canonical definition the
QR path is tested against); ``solver="normal"`` forms Gram matrices with
one big MXU einsum + tiny batched pinv — fastest, but squares the condition
number, so ill-conditioned months can drift. ``precision=HIGHEST`` keeps
f32 matmuls out of bf16 truncation so single-chip f32 runs stay within the
1e-4 parity budget.
"""

from __future__ import annotations

import collections
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from fm_returnprediction_tpu.guard import checks as _guard

__all__ = [
    "CSRegressionResult",
    "NormalStats",
    "monthly_cs_ols",
    "row_validity",
    "augment_design",
    "sufficient_stats",
    "solve_from_stats",
    "gram_pinv",
]

_PRECISION = jax.lax.Precision.HIGHEST

# name -> jit traces since process start (trace ≈ compile for a fixed shape
# signature) — the guard property tests pin that arming the sentinels does
# not retrace the hot path (same counting discipline as
# ``specgrid.solve.PROGRAM_TRACES``).
TRACES: collections.Counter = collections.Counter()


class CSRegressionResult(NamedTuple):
    """Batched analog of the reference's per-month result rows
    (``src/regressions.py:68-72``)."""

    slopes: jnp.ndarray       # (T, P) slope per predictor; NaN-free, gate on month_valid
    intercept: jnp.ndarray    # (T,)
    r2: jnp.ndarray           # (T,) centered cross-sectional R²
    n_obs: jnp.ndarray        # (T,) valid rows per month
    month_valid: jnp.ndarray  # (T,) bool: month had >= P+1 valid rows


def row_validity(y: jnp.ndarray, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Complete-case validity: row exists and regressand + all predictors are
    finite (reference ``.dropna()`` over the selected columns,
    ``src/regressions.py:39``)."""
    return mask & jnp.isfinite(y) & jnp.all(jnp.isfinite(x), axis=-1)


class NormalStats(NamedTuple):
    """Normal-equation sufficient statistics for a batch of cross-sections.

    These are exactly the quantities that are ADDITIVE over disjoint firm
    subsets, so the multi-chip path (``parallel.fm_sharded``) computes them
    per device shard and combines with one ``psum``.
    """

    gram: jnp.ndarray    # (..., Q, Q) XᵀX with intercept column, Q = P+1
    moment: jnp.ndarray  # (..., Q)    Xᵀy
    n: jnp.ndarray       # (...)       valid rows
    ysum: jnp.ndarray    # (...)       Σy over valid rows
    yy: jnp.ndarray      # (...)       Σy² over valid rows


def augment_design(y: jnp.ndarray, x: jnp.ndarray, valid: jnp.ndarray):
    """Masked design with intercept column: ``(x_aug, y_z, v)`` where invalid
    rows are exact zeros. The intercept column is prepended first, as the
    reference builds its design at ``src/regressions.py:49``."""
    v = valid.astype(x.dtype)
    ones = jnp.ones_like(y)
    x_aug = jnp.concatenate(
        [ones[..., None], jnp.where(valid[..., None], x, 0.0)], axis=-1
    )
    x_aug = x_aug * v[..., None]
    y_z = jnp.where(valid, y, 0.0)
    return x_aug, y_z, v


def sufficient_stats(y: jnp.ndarray, x: jnp.ndarray, valid: jnp.ndarray) -> NormalStats:
    """Contract a masked cross-section batch into normal-equation stats.

    Shapes: y (..., N), x (..., N, P), valid (..., N) bool.
    """
    x_aug, y_z, v = augment_design(y, x, valid)
    gram = jnp.einsum("...np,...nq->...pq", x_aug, x_aug, precision=_PRECISION)
    moment = jnp.einsum("...np,...n->...p", x_aug, y_z, precision=_PRECISION)
    return NormalStats(gram, moment, v.sum(-1), y_z.sum(-1), jnp.sum(y_z * y_z, -1))


def gram_pinv(stats: NormalStats):
    """Pseudo-inverse of the (safe) Gram matrices plus the month gate.

    Shared by the one-shot normal solve and the sharded path's
    ``n_refine=0`` Gram fast path (``parallel.fm_sharded``)."""
    gram, _, n, _, _ = stats
    q = gram.shape[-1]
    month_valid = n >= q
    eye = jnp.eye(q, dtype=gram.dtype)
    safe_gram = jnp.where(month_valid[..., None, None], gram, eye)
    with jax.default_matmul_precision("highest"):
        pinv = jnp.linalg.pinv(safe_gram)
    return pinv, month_valid


def solve_from_stats(stats: NormalStats):
    """Per-month OLS from sufficient statistics (the "normal" solver).

    Skipped months (n < Q, the reference guard ``src/regressions.py:52``)
    carry zero slopes/R² with ``month_valid=False``. R² is the centered
    statsmodels ``rsquared`` reconstructed as 1 − SSE/SST with
    SSE = yᵀy − 2βᵀ(Xᵀy) + βᵀ(XᵀX)β.

    Returns ``(slopes (..., P), intercept (...), r2 (...), n (...),
    month_valid (...))`` — ``CSRegressionResult`` leaves with batch dims.
    """
    gram, moment, n, ysum, yy = stats
    pinv, month_valid = gram_pinv(stats)
    beta = jnp.einsum("...pq,...q->...p", pinv, moment, precision=_PRECISION)
    beta = jnp.where(month_valid[..., None], beta, 0.0)

    bg = jnp.einsum("...p,...pq,...q->...", beta, gram, beta, precision=_PRECISION)
    bm = jnp.einsum("...p,...p->...", beta, moment, precision=_PRECISION)
    sse = yy - 2.0 * bm + bg
    sst = yy - ysum * ysum / jnp.maximum(n, 1.0)
    r2 = jnp.where(sst > 0, 1.0 - sse / jnp.where(sst > 0, sst, 1.0), 0.0)
    r2 = jnp.where(month_valid, r2, 0.0)
    return beta[..., 1:], beta[..., 0], r2, n, month_valid


def _solve_month(y, x, valid, solver="qr", guard=False):
    """One month's masked OLS. Shapes: y (N,), x (N, P), valid (N,) bool.

    ``solver="lstsq"``: SVD least squares on the zero-padded design
    matrix — the minimum-norm solution, numerically identical to
    numpy ``lstsq``/statsmodels' pinv-based OLS even for ill-conditioned or
    rank-deficient months. The reference's gate ``n >= P+1`` admits months
    with exactly as many rows as design columns (intercept + P), which are
    square and often NEARLY singular (observed cond(X) ~ 1e6 on synthetic
    data); the Gram route squares that condition number and visibly drifts
    from the reference there, while direct SVD does not. Zero rows leave
    singular values/V untouched, so the padded solve equals the subset solve
    exactly.

    ``solver="qr"`` (default): QR-compress ``[X | y]`` to its R factor,
    then the SAME SVD lstsq on the tiny compressed system — the single-chip
    analog of the sharded path's TSQR (``parallel.fm_sharded._tsqr_lstsq``,
    same proof): ``RᵀR = [X|y]ᵀ[X|y]`` gives ``‖R_xβ − r_y‖ = ‖Xβ − y‖``
    for every β, so the compressed minimum-norm solution IS the global one,
    and ``cond(R_x) = cond(X)`` — no condition-number squaring. The tall
    N×(Q+1) factorization is Householder QR (MXU-friendly panel matmuls)
    instead of an N-row iterative SVD, which is the difference between
    matmul-bound and decomposition-bound on TPU. ``rcond`` is pinned to the
    GLOBAL row count so truncation thresholds match the direct solve.

    ``solver="normal"``: Gram pseudo-inverse (X⁺ = (XᵀX)⁺Xᵀ) via the shared
    ``sufficient_stats``/``solve_from_stats`` route (the same code the
    multi-chip path psums). One big MXU einsum + tiny (P+1)² pinv — much
    faster, but squares the condition number, so ill-conditioned months can
    drift from the reference.

    ``guard`` (trace-time static) appends a dict of numerical-sentinel
    scalars — non-finite Gram entries on the normal route, a triangular
    condition proxy ``max|r_ii|/min|r_ii|`` on the QR route — consumed by
    the guarded ``monthly_cs_ols`` program (``guard.checks``). With
    ``guard=False`` nothing here changes: the jaxpr is the unguarded one.
    """
    if solver == "normal":
        stats = sufficient_stats(y, x, valid)
        out = solve_from_stats(stats)
        if guard:
            extras = {
                "gram_nonfinite": _guard.nonfinite_count(stats.gram)
                + _guard.nonfinite_count(stats.moment),
                "cond_proxy": jnp.zeros((), x.dtype),
            }
            return (*out, extras)
        return out
    if solver not in ("lstsq", "qr"):
        raise ValueError(f"Unknown solver: {solver}")

    n = valid.sum()
    p_aug = x.shape[-1] + 1

    x_aug, y_z, v = augment_design(y, x, valid)

    month_valid = n >= p_aug
    # default_matmul_precision keeps the lstsq SVD and the residual matmuls
    # below off the bf16 MXU path on TPU f32 runs (1e-4 parity budget).
    with jax.default_matmul_precision("highest"):
        if solver == "qr":
            m = jnp.concatenate([x_aug, y_z[:, None]], axis=-1)
            r = jnp.linalg.qr(m, mode="r")  # (Q+2, Q+2)
            rcond = jnp.finfo(x_aug.dtype).eps * max(x_aug.shape[0], p_aug)
            beta, _, _, _ = jnp.linalg.lstsq(
                r[:, :-1], r[:, -1], rcond=rcond
            )
        else:
            beta, _, _, _ = jnp.linalg.lstsq(x_aug, y_z)
    # Skipped months carry zeros; a non-finite solve on a month that RAN is
    # left as NaN — the reference's statsmodels would also emit NaN slopes
    # and a NaN R² there, and the FM layer drops them per-column (.dropna()
    # semantics) and skips the month's R² in the mean.
    beta = jnp.where(month_valid, beta, 0.0)

    with jax.default_matmul_precision("highest"):
        resid = (y_z - x_aug @ beta) * v
    sse = jnp.sum(resid * resid)
    ybar = jnp.where(n > 0, jnp.sum(y_z) / jnp.maximum(n, 1), 0.0)
    sst = jnp.sum(v * (y_z - ybar) ** 2)
    r2 = jnp.where(sst > 0, 1.0 - sse / jnp.where(sst > 0, sst, 1.0), 0.0)
    r2 = jnp.where(month_valid, r2, 0.0)  # NaN sse (non-finite solve) flows

    if guard:
        if solver == "qr":
            # triangular condition proxy: cond(R_x) ≥ max|r_ii|/min|r_ii|
            # — the design's conditioning, priced from the R factor the
            # solve already computed
            rd = jnp.abs(jnp.diagonal(r[:, :-1]))
            tiny = jnp.asarray(jnp.finfo(x_aug.dtype).tiny, x_aug.dtype)
            cond_proxy = rd.max() / jnp.maximum(rd.min(), tiny)
        else:
            cond_proxy = jnp.zeros((), x_aug.dtype)
        extras = {
            "gram_nonfinite": jnp.zeros((), jnp.int32),
            "cond_proxy": cond_proxy,
        }
        return beta[1:], beta[0], r2, n, month_valid, extras
    return beta[1:], beta[0], r2, n, month_valid


@functools.partial(jax.jit, static_argnames=("solver", "guard"))
def _monthly_cs_ols(
    y: jnp.ndarray,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    solver: str = "qr",
    guard: bool = False,
):
    """The compiled program behind :func:`monthly_cs_ols`. ``guard`` is a
    STATIC argument, so arming the sentinels selects a different cached
    executable (one trace per configuration) instead of silently reusing a
    sentinel-less trace; with ``guard=False`` the jaxpr is byte-for-byte
    the unguarded program (pinned by the guard property tests)."""
    TRACES["monthly_cs_ols"] += 1  # trace-time side effect
    from fm_returnprediction_tpu.telemetry import record_trace

    record_trace("monthly_cs_ols")  # compile-event hook (registry + span)
    valid = row_validity(y, x, mask)
    out = jax.vmap(
        lambda yy, xx, vv: _solve_month(yy, xx, vv, solver=solver, guard=guard)
    )(y, x, valid)
    if guard:
        slopes, intercept, r2, n_obs, month_valid, extras = out
        cs = CSRegressionResult(slopes, intercept, r2, n_obs, month_valid)
        limit = _guard.cond_limit(x.dtype)
        counters = {
            **_guard.cs_counters(cs),
            "gram_nonfinite_entries": extras["gram_nonfinite"].sum(),
            "cond_exceeded_months": jnp.sum(
                month_valid & (extras["cond_proxy"] > limit)
            ),
        }
        return cs, counters
    slopes, intercept, r2, n_obs, month_valid = out
    return CSRegressionResult(slopes, intercept, r2, n_obs, month_valid)


def monthly_cs_ols(
    y: jnp.ndarray,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    solver: str = "qr",
    guard=None,
) -> CSRegressionResult:
    """Run every month's cross-sectional regression in one batched call
    (jitted: one compiled program, one dispatch — library calls stay off the
    eager per-op path, which dominates wall-clock on remote TPU backends).

    Parameters
    ----------
    y : (T, N) returns per month × firm slot.
    x : (T, N, P) lagged predictors.
    mask : (T, N) bool, firm-month row exists.
    guard : arm the numerical sentinels (``guard.checks``): non-finite
        solves/R², Gram overflow, condition-proxy exceedances accumulate
        into the process audit counters. ``None`` follows the global
        ``FMRP_GUARD`` switch. Sentinels ride the same compiled program as
        extra integer outputs — results are bit-identical either way, and
        recording is skipped (counter math dead-code-eliminated) when this
        call is inlined inside an outer trace.

    Returns
    -------
    CSRegressionResult with (T, ...) leaves; invalid months carry zeros and
    ``month_valid=False`` (downstream reductions gate on it, mirroring the
    reference's "skip month" continue at ``src/regressions.py:52-54``).
    """
    guard = _guard.guard_active() if guard is None else bool(guard)
    out = _monthly_cs_ols(y, x, mask, solver=solver, guard=guard)
    if guard:
        cs, counters = out
        _guard.record("ols.monthly_cs_ols", counters)
        return cs
    return out


# jit-object conveniences forwarded for callers that manage the cache
# (e.g. compile-count tests); both names address the SAME executable cache
monthly_cs_ols.clear_cache = _monthly_cs_ols.clear_cache
monthly_cs_ols._cache_size = _monthly_cs_ols._cache_size
