"""Masked batched cross-sectional OLS — the compute core.

Replaces the reference's per-month Python loop over ``sm.OLS`` fits
(``src/regressions.py:43-72``: ~600 months × 3 subsets × 3 models ≈ 5,400
LAPACK calls) with ONE batched solve over the dense ``(T, N, P)`` panel:

- complete-case row validity (the reference dropna's over the regressand and
  all predictors before the loop, ``src/regressions.py:39``);
- months with fewer valid rows than ``P + 1`` regressors are skipped
  (``src/regressions.py:52``);
- slopes, intercept, cross-sectional R² (centered, as ``mod.rsquared``) and
  the per-month row count N are returned for every month with a validity
  flag instead of a ragged result list.

TPU mapping: the Gram matrices ``XᵀX`` are one ``(T, N, P+1) × (T, N, P+1)``
einsum that XLA tiles onto the MXU; the ``(P+1, P+1)`` solves are batched.
``precision=HIGHEST`` keeps f32 matmuls out of bf16 truncation so single-chip
f32 runs stay within the 1e-4 parity budget.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = ["CSRegressionResult", "monthly_cs_ols", "row_validity"]

_PRECISION = jax.lax.Precision.HIGHEST


class CSRegressionResult(NamedTuple):
    """Batched analog of the reference's per-month result rows
    (``src/regressions.py:68-72``)."""

    slopes: jnp.ndarray       # (T, P) slope per predictor; NaN-free, gate on month_valid
    intercept: jnp.ndarray    # (T,)
    r2: jnp.ndarray           # (T,) centered cross-sectional R²
    n_obs: jnp.ndarray        # (T,) valid rows per month
    month_valid: jnp.ndarray  # (T,) bool: month had >= P+1 valid rows


def row_validity(y: jnp.ndarray, x: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Complete-case validity: row exists and regressand + all predictors are
    finite (reference ``.dropna()`` over the selected columns,
    ``src/regressions.py:39``)."""
    return mask & jnp.isfinite(y) & jnp.all(jnp.isfinite(x), axis=-1)


def _solve_month(y, x, valid):
    """One month's masked OLS via normal equations. Shapes: y (N,), x (N, P),
    valid (N,) bool."""
    n = valid.sum()
    p_aug = x.shape[-1] + 1

    v = valid.astype(y.dtype)
    ones = jnp.ones_like(y)
    x_aug = jnp.concatenate([ones[:, None], jnp.where(valid[:, None], x, 0.0)], axis=1)
    x_aug = x_aug * v[:, None]
    y_z = jnp.where(valid, y, 0.0)

    gram = jnp.einsum("np,nq->pq", x_aug, x_aug, precision=_PRECISION)
    moment = jnp.einsum("np,n->p", x_aug, y_z, precision=_PRECISION)

    month_valid = n >= p_aug
    safe_gram = jnp.where(month_valid, gram, jnp.eye(p_aug, dtype=gram.dtype))
    # Pseudo-inverse of the Gram matrix: X⁺ = (XᵀX)⁺Xᵀ, so this equals the
    # minimum-norm least-squares solution statsmodels' pinv-based OLS returns —
    # finite even for singular months (e.g. a predictor constant across the
    # cross-section in a thin subset), which a plain solve would turn into
    # NaNs that poison the FM mean_R². The matrices are (P+1, P+1), so the
    # batched SVD is negligible next to the Gram einsum.
    beta = jnp.einsum(
        "pq,q->p", jnp.linalg.pinv(safe_gram), moment, precision=_PRECISION
    )
    beta = jnp.where(month_valid, beta, 0.0)

    resid = (y_z - x_aug @ beta) * v
    sse = jnp.sum(resid * resid)
    ybar = jnp.where(n > 0, jnp.sum(y_z) / jnp.maximum(n, 1), 0.0)
    sst = jnp.sum(v * (y_z - ybar) ** 2)
    r2 = jnp.where(sst > 0, 1.0 - sse / jnp.where(sst > 0, sst, 1.0), 0.0)
    r2 = jnp.where(month_valid, r2, 0.0)

    return beta[1:], beta[0], r2, n, month_valid


def monthly_cs_ols(
    y: jnp.ndarray, x: jnp.ndarray, mask: jnp.ndarray
) -> CSRegressionResult:
    """Run every month's cross-sectional regression in one batched call.

    Parameters
    ----------
    y : (T, N) returns per month × firm slot.
    x : (T, N, P) lagged predictors.
    mask : (T, N) bool, firm-month row exists.

    Returns
    -------
    CSRegressionResult with (T, ...) leaves; invalid months carry zeros and
    ``month_valid=False`` (downstream reductions gate on it, mirroring the
    reference's "skip month" continue at ``src/regressions.py:52-54``).
    """
    valid = row_validity(y, x, mask)
    slopes, intercept, r2, n_obs, month_valid = jax.vmap(_solve_month)(y, x, valid)
    return CSRegressionResult(slopes, intercept, r2, n_obs, month_valid)
