"""MXU-tiled pallas kernel for the masked per-month Gram contraction.

The spec-grid's hottest device loop is ``specgrid.grams.contract_spec_grams``:
for every spec s and month t it contracts the (T, N, P) union panel into the
augmented normal-equation statistics

    G_s[t] = Σ_n  w_s[t,n] · x̃[t,n,:] x̃[t,n,:]ᵀ ,   x̃ = [1 | X − c_t]

The XLA route (retained as the differential oracle, ``specgrid.grams``)
re-reads the panel once per spec: each spec's weighted design is a separate
einsum over the same (T, chunk, Q) tile. This kernel restructures the
contraction around the memory hierarchy instead:

- the grid is (T months, N-firm blocks) with the firm axis innermost and
  sequential; each step DMAs ONE (P, BN) panel tile into VMEM and serves
  ALL S specs from it — the panel is read once total, not once per spec;
- the output tile is the whole augmented (QE, QE) Gram per (spec, month)
  (QE = P + 2: intercept column first, the regressand appended last — one
  symmetric product yields gram, moment, n, Σy and Σy² in a single MXU
  contraction, see ``_split_stats``), held in VMEM across the firm blocks
  and accumulated in f32 (f64 for f64 panels) — the "blocked over firms ×
  the Q×Q output tile" shape of the blocked normal-equation update
  algorithms in "Large-scale linear regression" (PAPERS.md);
- the row-validity mask is FUSED into the tile load: finiteness of y and of
  each spec's selected columns, the universe ∧ window mask (one int8
  tensor), and the optional coreset row weights are applied in VMEM —
  no (S, T, N) float weight tensor ever materializes in HBM.

The panel arrives TRANSPOSED to (T, P, N) — firms on lanes — so every
in-kernel broadcast is a (1, BN)-row against a (K, BN) tile and the kernel
needs no transposes or lane/sublane reshapes; the one-time host transpose
is a single XLA copy amortized over the whole spec batch.

The kernel is TPU-only by construction; ``interpret=True`` runs it on CPU
for the differential suite (``tests/test_gram_kernels.py`` pins it against
the XLA oracle at 1e-6 relative for f32 and at the few-ulp level — 1e-13
relative, exact counts — for f64; the two routes block their reductions
differently, so exact bitwise equality is not promised). Route selection
(``FMRP_GRAM_ROUTE``) lives in ``specgrid.grams``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from fm_returnprediction_tpu.ops.pallas_kernels import _CompilerParams, _fit_block

__all__ = ["gram_contract_pallas"]


def _gram_kernel(s_specs, has_rw, acc_dtype, *refs):
    """One (month t, firm block j) step: load the (P, BN) panel tile once,
    build the augmented design ``xa = [1 | X − c_t | y]`` in VMEM, and
    accumulate every spec's masked symmetric product into its (QE, QE)
    output tile. The firm-block axis is sequential, so ``out_ref`` persists
    in VMEM across j and is written back once per month."""
    if has_rw:
        xt_ref, y_ref, m8_ref, selt_ref, centert_ref, rw_ref, out_ref = refs
    else:
        xt_ref, y_ref, m8_ref, selt_ref, centert_ref, out_ref = refs
        rw_ref = None
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    x = xt_ref[0]                                   # (P, BN)
    dtype = x.dtype
    finx = jnp.isfinite(x)
    xz = jnp.where(finx, x - centert_ref[...], 0.0)  # centerT tile is (P, 1)
    y = y_ref[...]                                   # (1, BN)
    finy = jnp.isfinite(y)
    yz = jnp.where(finy, y, 0.0)
    notfin = (~finx).astype(dtype)                   # (P, BN)
    xa = jnp.concatenate([jnp.ones_like(yz), xz, yz], axis=0)   # (QE, BN)
    base = m8_ref[:, 0, :]                           # (S, BN) int8 uni∧window
    finyf = finy.astype(dtype)
    rw = rw_ref[...] if has_rw else None             # (1, BN)

    for s in range(s_specs):                         # static: S is a shape
        # rows invalid for spec s: any SELECTED column non-finite — a tiny
        # (P,1)·(P,BN) contraction, exact for integer counts ≤ P
        bad = jax.lax.dot_general(
            selt_ref[:, s : s + 1], notfin,
            (((0,), (0,)), ((), ())),
        )                                            # (1, BN)
        w = ((base[s : s + 1, :] != 0) & (bad == 0)).astype(dtype) * finyf
        if has_rw:
            w = w * rw
        bw = xa * w                                  # lane-wise row weights
        out_ref[s, 0] += jax.lax.dot_general(
            bw, xa, (((1,), (1,)), ((), ())),
            preferred_element_type=acc_dtype,
        )


def _split_stats(out: jnp.ndarray, p: int):
    """The augmented (S, T, QE, QE) product → the five SpecGramStats
    moments. Column layout of x̃⁺ = [1 | X − c | y]: gram is the leading
    (Q, Q) block, the y column holds moment / Σwy / Σwy², and the
    intercept-intercept entry is Σw (the valid-row count)."""
    q = p + 1
    gram = out[:, :, :q, :q]
    moment = out[:, :, :q, q]
    n = out[:, :, 0, 0]
    ysum = out[:, :, 0, q]
    yy = out[:, :, q, q]
    return gram, moment, n, ysum, yy


@functools.partial(
    jax.jit, static_argnames=("block_n", "interpret")
)
def gram_contract_pallas(
    y: jnp.ndarray,
    x: jnp.ndarray,
    valid: jnp.ndarray,
    col_sel: jnp.ndarray,
    center: jnp.ndarray,
    row_weights=None,
    block_n: int = 512,
    interpret: bool = False,
):
    """Masked per-month Gram contraction, one panel read for all specs.

    Parameters mirror the XLA oracle's internals (``specgrid.grams``):
    ``y`` (T, N); ``x`` (T, N, P) in the contraction dtype (bf16 inputs
    accumulate in f32); ``valid`` (S, T, N) bool — universe ∧ window (y/x
    finiteness is fused in-kernel); ``col_sel`` (S, P) bool; ``center``
    (T, P); ``row_weights`` optional (T, N). Returns the five stats arrays
    in the accumulation dtype (f64 panels accumulate in f64, everything
    else in f32): ``(gram, moment, n, ysum, yy)``.
    """
    t, n_firms, p = x.shape
    s_specs = col_sel.shape[0]
    qe = p + 2
    dtype = x.dtype
    acc_dtype = jnp.float64 if dtype == jnp.float64 else jnp.float32

    bn = _fit_block(n_firms, block_n, 128)
    pad = (-n_firms) % bn
    xt = jnp.swapaxes(x, 1, 2)                       # (T, P, N): firms on lanes
    centert = center.astype(dtype).T                 # (P, T)
    selt = col_sel.astype(dtype).T                   # (P, S)
    m8 = valid.astype(jnp.int8)
    if pad:
        xt = jnp.pad(xt, ((0, 0), (0, 0), (0, pad)))
        y = jnp.pad(y, ((0, 0), (0, pad)), constant_values=jnp.nan)
        m8 = jnp.pad(m8, ((0, 0), (0, 0), (0, pad)))
        if row_weights is not None:
            row_weights = jnp.pad(row_weights, ((0, 0), (0, pad)))
    has_rw = row_weights is not None

    in_specs = [
        pl.BlockSpec((1, p, bn), lambda it, j: (it, 0, j)),        # xt
        pl.BlockSpec((1, bn), lambda it, j: (it, j)),              # y
        pl.BlockSpec((s_specs, 1, bn), lambda it, j: (0, it, j)),  # mask
        pl.BlockSpec((p, s_specs), lambda it, j: (0, 0)),          # selT
        pl.BlockSpec((p, 1), lambda it, j: (0, it)),               # centerT
    ]
    args = [xt, y.astype(dtype), m8, selt, centert]
    if has_rw:
        in_specs.append(pl.BlockSpec((1, bn), lambda it, j: (it, j)))
        args.append(jnp.asarray(row_weights, dtype))

    out = pl.pallas_call(
        functools.partial(_gram_kernel, s_specs, has_rw, acc_dtype),
        grid=(t, (n_firms + pad) // bn),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (s_specs, 1, qe, qe), lambda it, j: (0, it, 0, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((s_specs, t, qe, qe), acc_dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(*args)
    return _split_stats(out, p)
