"""Newey-West standard error of a time-series mean.

Vectorized re-provision of the reference's ``newey_west_mean_se``
(``src/regressions.py:78-100``), including its NON-textbook Bartlett weight:
the reference uses ``w_k = 1 - k/T`` where ``T`` is the number of valid
months in the series — not the conventional ``1 - k/(L+1)``. With T≈600 the
weights are ≈1 (nearly unweighted autocovariances up to lag 4). Parity to the
reference requires this exact formula (SURVEY §2.2.9), so it is the default;
the textbook kernel is available behind ``weight="textbook"``.

Validity handling: the reference computes NW on ``.dropna()``'d slope
series — autocovariance lag k pairs ADJACENT SURVIVING months, not calendar
neighbors (``fama_macbeth_summary``, ``src/regressions.py:113``). The masked
version therefore compacts valid entries to the front (stable chronological
order) before forming lagged products, which reproduces that semantics
exactly under static shapes.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["nw_mean_se", "nw_mean_se_np", "compact_front",
           "clustered_mean_se", "clustered_mean_se_np"]


def compact_front(x: jnp.ndarray, valid: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Stable-partition ``x`` so valid entries come first in original order.

    Returns (compacted values with invalid tail zeroed, count of valid).
    """
    order = jnp.argsort(~valid, stable=True)
    n = valid.sum()
    xc = jnp.where(jnp.arange(x.shape[0]) < n, x[order], 0.0)
    return xc, n


def nw_mean_se(
    x: jnp.ndarray,
    valid: jnp.ndarray,
    lags: int = 4,
    weight: str = "reference",
) -> jnp.ndarray:
    """NW standard error for the mean of the valid entries of ``x``.

    ``var(mean) = (γ₀ + 2 Σ_{k=1..L} w_k γ_k) / n²`` with
    ``γ_k = Σ_i u_i u_{i-k}`` over demeaned compacted values, and
    ``w_k = max(1 - k/n, 0)`` (reference) or ``1 - k/(L+1)`` (textbook).
    Series with fewer than 2 valid entries return NaN
    (``src/regressions.py:84-85``).
    """
    xc, n = compact_front(x, valid)
    nf = n.astype(xc.dtype)
    in_range = jnp.arange(xc.shape[0]) < n

    mean = jnp.where(n > 0, xc.sum() / jnp.maximum(nf, 1.0), 0.0)
    u = jnp.where(in_range, xc - mean, 0.0)

    gamma0 = jnp.dot(u, u)
    acc = jnp.zeros((), dtype=xc.dtype)
    for k in range(1, lags + 1):
        gamma_k = jnp.dot(u[k:], u[:-k]) if k < u.shape[0] else jnp.zeros((), xc.dtype)
        if weight == "reference":
            w = jnp.maximum(1.0 - k / jnp.maximum(nf, 1.0), 0.0)
        elif weight == "textbook":
            w = jnp.asarray(1.0 - k / (lags + 1.0), dtype=xc.dtype)
        else:
            raise ValueError(f"Unknown NW weight scheme: {weight}")
        acc = acc + w * gamma_k

    var_mean = (gamma0 + 2.0 * acc) / jnp.maximum(nf, 1.0) ** 2
    return jnp.where(n >= 2, jnp.sqrt(var_mean), jnp.nan)


def clustered_mean_se(
    x: jnp.ndarray,
    valid: jnp.ndarray,
    cluster_ids: jnp.ndarray,
) -> jnp.ndarray:
    """Cluster-robust standard error for the mean of the valid entries of
    ``x`` — the FM estimator family's ``se="cluster"`` kernel
    (``specgrid.estimators``): instead of the NW kernel's lag-windowed
    autocovariances, ALL within-cluster covariance counts, with zero
    leakage across clusters:

        var(mean) = (Σ_g S_g²) / n²,   S_g = Σ_{i∈g} (x_i − x̄)

    over the valid entries (``cluster_ids`` are CALENDAR groupings — e.g.
    ``month // 12`` for by-year blocks — so clusters follow the calendar,
    not the compacted survivor order the NW kernel uses). Like the NW
    kernel: fewer than 2 valid entries → NaN. Unlike HAC, the clustered
    variance is a sum of squares and can never go negative."""
    valid = valid.astype(bool)
    nf = valid.sum().astype(x.dtype)
    mean = jnp.where(nf > 0, jnp.where(valid, x, 0.0).sum()
                     / jnp.maximum(nf, 1.0), 0.0)
    u = jnp.where(valid, x - mean, 0.0)
    n_seg = x.shape[0]  # ≤ one cluster per entry; ids are in [0, T)
    s_g = jnp.zeros(n_seg, x.dtype).at[cluster_ids].add(u)
    var_mean = (s_g * s_g).sum() / jnp.maximum(nf, 1.0) ** 2
    return jnp.where(nf >= 2, jnp.sqrt(var_mean), jnp.nan)


def clustered_mean_se_np(vals: np.ndarray, clusters: np.ndarray) -> float:
    """Numpy mirror of :func:`clustered_mean_se` on an already-compacted
    valid series with its cluster labels — the host oracle
    (``tests/test_estimators.py``)."""
    vals = np.asarray(vals, float)
    clusters = np.asarray(clusters)
    n = vals.size
    if n < 2:
        return float("nan")
    u = vals - vals.mean()
    s_g = np.array([u[clusters == g].sum() for g in np.unique(clusters)])
    return float(np.sqrt((s_g ** 2).sum() / n ** 2))


def nw_mean_se_np(vals: np.ndarray, lags: int = 4,
                  weight: str = "reference") -> float:
    """Numpy mirror of :func:`nw_mean_se` on an ALREADY-compacted valid
    series — the host-route oracle of the spec-grid bootstrap aggregation
    (``specgrid.boot``; historically ``specgrid.engine._nw_se_np``, moved
    here so the jax kernel and its host mirror live behind one
    differential-pinned home, ``tests/test_boot_device.py``).

    Same contracts as the jax path: fewer than 2 entries → NaN, and a
    negative small-sample HAC variance is legal and reads as NaN (the
    guard/checks NW-tap note).
    """
    vals = np.asarray(vals, float)
    n = vals.size
    if n < 2:
        return float("nan")
    u = vals - vals.mean()
    gamma0 = float(u @ u)
    acc = 0.0
    for k in range(1, lags + 1):
        gamma_k = float(u[k:] @ u[:-k]) if k < n else 0.0
        if weight == "reference":
            w = max(1.0 - k / n, 0.0)
        elif weight == "textbook":
            w = 1.0 - k / (lags + 1.0)
        else:
            raise ValueError(f"Unknown NW weight scheme: {weight}")
        acc += w * gamma_k
    var_mean = (gamma0 + 2.0 * acc) / n**2
    return float(np.sqrt(var_mean)) if var_mean >= 0 else float("nan")
