"""Daily-data kernels: weekly-grid rolling beta and 252-day volatility.

These are the two largest-volume computations in the pipeline (SURVEY §3.5:
daily CRSP 1964-2013 is O(10⁷-10⁸) rows).

Rolling beta (reference ``calculate_rolling_beta``,
``src/calc_Lewellen_2014.py:344-434``): the reference inner-joins daily stock
and index returns, takes log gross returns, and runs polars
``group_by_dynamic(every="1w", period="156w", by="permno")`` to get rolling
partial sums, from which ``beta = (ΣRiRm − ΣRiΣRm/n)/(ΣRm² − (ΣRm)²/n)``.
The polars window semantics replicated here (best-effort transcription —
polars is not installed in this environment; semantics documented from the
polars 1.x API contract):

- window starts lie on the global Monday lattice (polars ``truncate("1w")``);
- each window is label-LEFT and forward: ``[start, start + 156 weeks)`` —
  note this makes the reference's "beta over months -36..-1" actually a
  FORWARD-looking window (SURVEY flags this; parity targets the reference's
  behavior, not the paper's);
- per firm, windows are emitted for week-starts from its first to its last
  observation week;
- the weekly rows are then stamped with the month-end of the window START
  and deduplicated keep-last per (firm, month).

TPU design: daily obs → weekly partial sums via ``segment_sum`` (one pass
over the (D, N) panel), then 156-week FORWARD windowed sums via reversed
cumsum-difference along the ~2,600-week axis, then a ``segment_max`` pick of
the last valid week per month. Everything is per-firm independent along N.

252-day volatility (reference ``calc_std_12``, ``:438-465``): per-firm
252-row rolling std (min 100 obs) of daily retx, annualized by √252, sampled
at the last observed day of each month.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from fm_returnprediction_tpu.ops.compaction import compact, make_compaction, scatter_back
from fm_returnprediction_tpu.ops.rolling import rolling_std, windowed_sum

__all__ = [
    "last_obs_per_month",
    "beta_from_weekly_sums",
    "rolling_vol_252_monthly",
    "weekly_partial_sums",
    "weekly_rolling_beta_monthly",
]


def _forward_windowed_sum(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """Sum over [j, j+window) along axis 0 (the mirror of the trailing
    window): reverse, trailing-window sum, reverse."""
    return windowed_sum(x[::-1], window)[::-1]


def last_obs_per_month(
    values: jnp.ndarray,
    present: jnp.ndarray,
    month_id: jnp.ndarray,
    n_months: int,
) -> jnp.ndarray:
    """Per (month, firm): the value at the firm's LAST present row of that
    month — the dense analog of ``drop_duplicates(['permno','jdate'],
    keep='last')`` on row-sorted daily data (``src/calc_Lewellen_2014.py:430,461``).

    Parameters
    ----------
    values : (D, N); present : (D, N) bool; month_id : (D,) int in
    [0, n_months] where ``n_months`` is a trash segment for out-of-panel
    months. Returns (n_months, N) with NaN where a firm has no row in a month.
    """
    day_pos = jnp.arange(values.shape[0])[:, None]
    pos = jnp.where(present, day_pos, -1)
    last_pos = jax.ops.segment_max(
        pos, month_id, num_segments=n_months + 1
    )[:n_months]
    has = last_pos >= 0
    picked = jnp.take_along_axis(values, jnp.maximum(last_pos, 0), axis=0)
    return jnp.where(has, picked, jnp.nan)


def rolling_vol_252_monthly(
    ret_d: jnp.ndarray,
    mask_d: jnp.ndarray,
    month_id: jnp.ndarray,
    n_months: int,
    window: int = 252,
    min_periods: int = 100,
    use_pallas: bool = None,
) -> jnp.ndarray:
    """Annualized 252-row rolling std of daily returns, sampled at each
    firm-month's last observed day. Returns (n_months, N).

    ``use_pallas`` forwards to ``rolling_std``; callers tracing this inside
    an SPMD-partitioned program (``parallel.daily_sharded``) must pass
    ``False`` — GSPMD cannot partition the pallas custom-call. The
    None-default resolves the FMRP_PALLAS/platform dispatch HERE, outside
    the jit cache, so flipping the env var mid-process takes effect."""
    if use_pallas is None:
        from fm_returnprediction_tpu.ops.rolling import _pallas_default

        use_pallas = _pallas_default(ret_d)
    return _rolling_vol_252_monthly(
        ret_d, mask_d, month_id, n_months, window, min_periods, use_pallas
    )


@functools.partial(
    jax.jit, static_argnames=("n_months", "window", "min_periods", "use_pallas")
)
def _rolling_vol_252_monthly(
    ret_d, mask_d, month_id, n_months, window, min_periods, use_pallas
):
    plan = make_compaction(mask_d)
    comp_ret = jnp.where(plan.valid, compact(ret_d, plan), jnp.nan)
    vol = rolling_std(comp_ret, window, min_periods, use_pallas=use_pallas) * jnp.sqrt(
        jnp.asarray(float(window), dtype=ret_d.dtype)
    )
    vol_cal = scatter_back(vol, plan)
    return last_obs_per_month(vol_cal, mask_d, month_id, n_months)


@functools.partial(
    jax.jit, static_argnames=("n_weeks", "n_months", "window_weeks")
)
def weekly_rolling_beta_monthly(
    ret_d: jnp.ndarray,
    mask_d: jnp.ndarray,
    mkt_d: jnp.ndarray,
    week_id: jnp.ndarray,
    n_weeks: int,
    week_month_id: jnp.ndarray,
    n_months: int,
    window_weeks: int = 156,
    mkt_present: jnp.ndarray = None,
) -> jnp.ndarray:
    """Rolling beta on the weekly Monday lattice, one value per (month, firm).

    Parameters
    ----------
    ret_d : (D, N) daily stock returns (retx); NaN values follow the
        reference's polars semantics: ``pl.DataFrame(pandas_df)`` converts
        NaN→null (``nan_to_null=True`` default), polars aggregate sums SKIP
        nulls, but ``pl.count()`` counts ALL rows — so each partial sum
        covers its non-null rows while the denominator n is the window's row
        count (``src/calc_Lewellen_2014.py:376,404-410``).
    mask_d : (D, N) bool, firm-day row present.
    mkt_d : (D,) daily market return (vwretx).
    mkt_present : (D,) bool, the index table HAS a row for the day — days it
        lacks are dropped by the reference's inner join (``:380``) and
        contribute no rows at all.
    week_id : (D,) int, Monday-lattice week index of each day (0..n_weeks-1).
    week_month_id : (n_weeks,) int month index of each week's Monday in the
        monthly panel vocabulary, ``n_months`` for out-of-panel months.
    Returns (n_months, N) betas, NaN where no valid window start in month.
    """
    sums = weekly_partial_sums(
        ret_d, mask_d, mkt_d, week_id, n_weeks, mkt_present=mkt_present
    )
    return beta_from_weekly_sums(
        *sums, week_month_id, n_months, window_weeks,
    )


def weekly_partial_sums(
    ret_d, mask_d, mkt_d, week_id, n_weeks: int, mkt_present=None
):
    """Daily rows → the six weekly partial-sum arrays (n_weeks, N).

    The ingest-side half of the beta kernel, factored out so every layout
    shares it by construction: the single-device path above, and the
    time-sharded path (``parallel.time_sharded``) where each shard
    aggregates ITS days into the global week segments and one ``psum``
    merges the partials — segment sums are linear, so partial-per-shard +
    sum-over-shards equals the single-device aggregation exactly.
    """
    if mkt_present is None:
        mkt_present = jnp.isfinite(mkt_d)
    present = mask_d & mkt_present[:, None]          # row exists in the join
    ri_valid = present & jnp.isfinite(ret_d)
    rm_valid = present & jnp.isfinite(mkt_d)[:, None]
    log_ri = jnp.where(ri_valid, jnp.log1p(ret_d), 0.0)
    log_rm = jnp.where(rm_valid, jnp.log1p(mkt_d)[:, None], 0.0)

    seg = lambda a: jax.ops.segment_sum(
        a, week_id, num_segments=n_weeks
    )
    w_ri, w_rm = seg(log_ri), seg(log_rm)
    w_rirm = seg(jnp.where(ri_valid & rm_valid, log_ri * log_rm, 0.0))
    w_rm2 = seg(log_rm * log_rm)
    w_cnt = seg(present.astype(log_ri.dtype))        # pl.count(): all rows
    w_rm_cnt = seg(rm_valid.astype(log_ri.dtype))    # rows with market data
    return w_ri, w_rm, w_rirm, w_rm2, w_cnt, w_rm_cnt


def beta_from_weekly_sums(
    w_ri, w_rm, w_rirm, w_rm2, w_cnt, w_rm_cnt, week_month_id, n_months,
    window_weeks,
):
    """Weekly partial sums (n_weeks, N) → (n_months, N) betas.

    The representation-independent half of the beta kernel, factored out so
    every ingest layout reduces to the same windowing/validity/labeling
    logic (``ops.daily_compact`` reconstructs a dense strip and calls
    ``weekly_rolling_beta_monthly``, which lands here).
    """
    s_ri = _forward_windowed_sum(w_ri, window_weeks)
    s_rm = _forward_windowed_sum(w_rm, window_weeks)
    s_rirm = _forward_windowed_sum(w_rirm, window_weeks)
    s_rm2 = _forward_windowed_sum(w_rm2, window_weeks)
    n = _forward_windowed_sum(w_cnt, window_weeks)
    n_rm = _forward_windowed_sum(w_rm_cnt, window_weeks)

    n_safe = jnp.maximum(n, 1.0)
    cov = s_rirm - s_ri * s_rm / n_safe
    var = s_rm2 - s_rm * s_rm / n_safe
    # Degenerate windows where cov and var are EXACTLY zero in real
    # arithmetic (n <= 1, or no row in the window carries a market return)
    # give 0/0 = null in polars — gate them explicitly, because the
    # cumulative-sum-difference windowed sums leave tiny nonzero residuals
    # where real arithmetic gives exact zeros, which would otherwise turn
    # 0/0 into an arbitrary finite beta. For non-degenerate windows,
    # var == 0 still flows to ±inf/NaN exactly as in polars.
    beta = jnp.where((n >= 2.0) & (n_rm >= 1.0), cov / var, jnp.nan)

    # Window starts are emitted per firm from its first to its last obs week.
    n_weeks = w_cnt.shape[0]
    week_pos = jnp.arange(n_weeks)[:, None]
    has = w_cnt > 0
    first = jnp.min(jnp.where(has, week_pos, n_weeks), axis=0)
    last = jnp.max(jnp.where(has, week_pos, -1), axis=0)
    win_valid = (week_pos >= first[None, :]) & (week_pos <= last[None, :]) & (n >= 1)

    return last_obs_per_month(beta, win_valid, week_month_id, n_months)
