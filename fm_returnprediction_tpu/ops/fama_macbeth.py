"""Fama-MacBeth aggregation of monthly cross-sectional regressions.

Batched re-provision of the reference's ``fama_macbeth_summary``
(``src/regressions.py:102-130``):

- mean slope per predictor over the months whose regression ran AND whose
  slope is finite (the reference's per-column ``.dropna()``);
- predictors with fewer than ``min_months`` valid months report NaN
  coefficient and t-stat (``src/regressions.py:114-117``);
- t-stat = mean / NW-SE with the reference's ``1 - k/n`` Bartlett weight by
  default (see ``ops.newey_west``);
- mean R² and mean N over all months that ran (``src/regressions.py:128-129``).

Combined with ``ops.ols.monthly_cs_ols`` this is the whole hot path of
Table 2 (call stack SURVEY §3.4) in two fused device computations.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from fm_returnprediction_tpu.guard import checks as _guard
from fm_returnprediction_tpu.ops.newey_west import nw_mean_se
from fm_returnprediction_tpu.ops.ols import CSRegressionResult, monthly_cs_ols

__all__ = ["FamaMacbethSummary", "fama_macbeth_summary", "fama_macbeth"]


class FamaMacbethSummary(NamedTuple):
    coef: jnp.ndarray     # (P,) mean slope per predictor
    tstat: jnp.ndarray    # (P,) mean / NW-SE
    nw_se: jnp.ndarray    # (P,) NW standard error of the mean slope
    mean_r2: jnp.ndarray  # () mean cross-sectional R² over run months
    mean_n: jnp.ndarray   # () mean per-month N over run months
    n_months: jnp.ndarray # () number of months that ran


def fama_macbeth_summary(
    cs: CSRegressionResult,
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
) -> FamaMacbethSummary:
    """Aggregate a batched cross-sectional regression result."""
    month_valid = cs.month_valid
    mf = month_valid.astype(cs.slopes.dtype)
    n_months = month_valid.sum()

    # Per-predictor validity: month ran and slope is finite.
    slope_valid = month_valid[:, None] & jnp.isfinite(cs.slopes)     # (T, P)
    count = slope_valid.sum(axis=0)                                   # (P,)
    slopes_z = jnp.where(slope_valid, cs.slopes, 0.0)
    mean_slope = slopes_z.sum(axis=0) / jnp.maximum(count, 1).astype(cs.slopes.dtype)

    se = jax.vmap(
        lambda s, v: nw_mean_se(s, v, lags=nw_lags, weight=weight),
        in_axes=(1, 1),
    )(cs.slopes, slope_valid)                                          # (P,)

    enough = count >= min_months
    coef = jnp.where(enough, mean_slope, jnp.nan)
    tstat = jnp.where(enough, mean_slope / se, jnp.nan)

    # mean R² over months that ran AND have a finite R² (pandas .mean()
    # skips NaN — a non-finite solve's R² must not poison the average);
    # both means are NaN when no month ran (empty-frame .mean() is NaN,
    # which Table 2 renders as a blank cell).
    r2_valid = month_valid & jnp.isfinite(cs.r2)
    r2_count = r2_valid.sum()
    mean_r2 = jnp.where(
        r2_count > 0,
        jnp.sum(jnp.where(r2_valid, cs.r2, 0.0))
        / jnp.maximum(r2_count, 1).astype(cs.r2.dtype),
        jnp.nan,
    )
    mean_n = jnp.where(
        n_months > 0,
        jnp.sum(cs.n_obs.astype(cs.r2.dtype) * mf)
        / jnp.maximum(n_months, 1).astype(cs.r2.dtype),
        jnp.nan,
    )

    return FamaMacbethSummary(coef, tstat, se, mean_r2, mean_n, n_months)


@functools.partial(
    jax.jit, static_argnames=("nw_lags", "min_months", "weight", "solver", "guard")
)
def _fama_macbeth(
    y: jnp.ndarray,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
    solver: str = "qr",
    guard: bool = False,
):
    """The compiled program behind :func:`fama_macbeth`. ``guard`` is
    static: the sentinel counters (OLS solve finiteness + the NW-path
    t-stat tap) ride along as extra integer outputs; with ``guard=False``
    the jaxpr is the unguarded program."""
    cs = monthly_cs_ols(y, x, mask, solver=solver)
    fm = fama_macbeth_summary(
        cs, nw_lags=nw_lags, min_months=min_months, weight=weight
    )
    if guard:
        return cs, fm, {**_guard.cs_counters(cs), **_guard.fm_counters(fm)}
    return cs, fm


def fama_macbeth(
    y: jnp.ndarray,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
    solver: str = "qr",
    guard=None,
) -> tuple[CSRegressionResult, FamaMacbethSummary]:
    """End-to-end FM: batched monthly OLS + aggregation, one jittable call.

    ``guard=None`` follows the global ``FMRP_GUARD`` switch
    (``guard.checks``): when armed, non-finite solves and NW t-stat
    failures accumulate into the process audit counters — same program,
    bit-identical estimates, recording skipped under an outer trace."""
    guard = _guard.guard_active() if guard is None else bool(guard)
    out = _fama_macbeth(
        y, x, mask, nw_lags=nw_lags, min_months=min_months, weight=weight,
        solver=solver, guard=guard,
    )
    if guard:
        cs, fm, counters = out
        _guard.record("ols.fama_macbeth", counters)
        return cs, fm
    return out


# jit-object conveniences forwarded for callers that manage the cache
# (``tests/test_reporting.py`` pins the split route's compile count)
fama_macbeth.clear_cache = _fama_macbeth.clear_cache
fama_macbeth._cache_size = _fama_macbeth._cache_size
