"""Firm-axis chunking for the daily kernels — full-CRSP scale on one chip.

The reference streams O(10⁷-10⁸) daily rows through polars' out-of-core
engine (``src/calc_Lewellen_2014.py:396-410``); the dense TPU design instead
materializes a (D, N) daily panel, which at real 1964-2013 CRSP shape
(D≈12,600 trading days × N≈25-30k permnos) is ~1.3 GB per f32 array — and
the vol/beta kernels keep roughly a dozen (D, N)-sized intermediates live
(compaction plan int arrays, cumsums, log-return products), several times a
single chip's HBM at full scale.

Firms are independent in every daily kernel (rolling windows and weekly
segment-sums run along days WITHIN a firm column), so scale on one device is
a host loop over fixed-width firm strips: slice (D, C) from host memory, run
the jitted kernels (one compilation — every strip has the same static
shape; the last strip is padded), pull back the small (n_months, C) results.
Peak device memory is set by C, not N. This is the single-chip counterpart
of ``parallel.daily_sharded`` (which splits the same axis across a mesh).
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "auto_firm_chunk",
    "daily_characteristics_chunked",
    "daily_characteristics_compact_chunked",
]

# Peak live (D, C)-shaped arrays inside the vol+beta kernels, measured on the
# compiled programs (compaction plan: order/inv_order int + valid/mask bool;
# compacted returns, rolling cumsums, scatter-back; beta's masked logs and
# products before the weekly segment reduction). Deliberately a little high —
# the budget is a guardrail, not a high-water-mark tuning knob.
_WORKSPACE_ARRAYS = 12


def _default_budget_bytes() -> int:
    """Device workspace budget: ~60% of the accelerator's memory limit when
    the backend reports one, else a conservative 4 GiB."""
    env = os.environ.get("FMRP_DAILY_BUDGET_BYTES")
    if env:
        return int(env)
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit * 0.6)
    except Exception:
        pass
    return 4 << 30


def auto_firm_chunk(
    n_days: int,
    n_firms: int,
    itemsize: int,
    budget_bytes: Optional[int] = None,
) -> Optional[int]:
    """Firm-strip width that keeps the daily kernels' working set under the
    device budget, or None when the whole panel already fits (no chunking —
    small panels keep the exact single-call path)."""
    if budget_bytes is None:
        budget_bytes = _default_budget_bytes()
    per_firm = n_days * itemsize * _WORKSPACE_ARRAYS
    if per_firm * n_firms <= budget_bytes:
        return None
    chunk = int(budget_bytes // max(per_firm, 1))
    chunk = max((chunk // 128) * 128, 128)
    return min(chunk, n_firms)


def daily_characteristics_chunked(
    ret_d,
    mask_d,
    mkt_d,
    month_id,
    week_id,
    week_month_id,
    n_months: int,
    n_weeks: int,
    mkt_present=None,
    window: int = 252,
    min_periods: int = 100,
    window_weeks: int = 156,
    firm_chunk: Optional[int] = None,
    use_pallas: Optional[bool] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """vol-252 and weekly beta over firm strips; returns numpy (n_months, N) pairs.

    Inputs stay host-side numpy; each strip transfers (D, C) to the device,
    so the device never holds more than one strip's working set.
    ``firm_chunk=None`` = auto budget heuristic (None result = single call).
    Matches ``ops.daily_kernels`` outputs exactly — chunking is a pure
    execution-schedule choice, verified by tests against the unchunked path.
    """
    from fm_returnprediction_tpu.ops.daily_kernels import (
        rolling_vol_252_monthly,
        weekly_rolling_beta_monthly,
    )

    ret_d = np.asarray(ret_d)
    mask_d = np.asarray(mask_d)
    mkt_d = np.asarray(mkt_d)
    if mkt_present is None:
        mkt_present = np.isfinite(mkt_d)
    mkt_present = np.asarray(mkt_present)
    d_days, n_firms = ret_d.shape

    if firm_chunk is None:
        firm_chunk = auto_firm_chunk(d_days, n_firms, ret_d.dtype.itemsize)

    import jax.numpy as jnp

    def run(ret_np, mask_np):
        ret_j = jnp.asarray(ret_np)
        mask_j = jnp.asarray(mask_np)
        vol = rolling_vol_252_monthly(
            ret_j, mask_j, month_j, n_months,
            window=window, min_periods=min_periods, use_pallas=use_pallas,
        )
        beta = weekly_rolling_beta_monthly(
            ret_j, mask_j, mkt_j, week_j, n_weeks, week_month_j, n_months,
            window_weeks=window_weeks, mkt_present=mkt_present_j,
        )
        return np.asarray(vol), np.asarray(beta)

    # Per-day vectors are shared by every strip — move them once.
    month_j = jnp.asarray(np.asarray(month_id))
    week_j = jnp.asarray(np.asarray(week_id))
    week_month_j = jnp.asarray(np.asarray(week_month_id))
    mkt_j = jnp.asarray(mkt_d)
    mkt_present_j = jnp.asarray(mkt_present)

    if firm_chunk is None or firm_chunk >= n_firms:
        return run(ret_d, mask_d)

    vol_out = np.empty((n_months, n_firms), dtype=ret_d.dtype)
    beta_out = np.empty((n_months, n_firms), dtype=ret_d.dtype)
    c = int(firm_chunk)
    for start in range(0, n_firms, c):
        stop = min(start + c, n_firms)
        ret_s = ret_d[:, start:stop]
        mask_s = mask_d[:, start:stop]
        if stop - start < c:  # pad the last strip: one static shape = one compile
            pad = c - (stop - start)
            ret_s = np.pad(ret_s, ((0, 0), (0, pad)), constant_values=np.nan)
            mask_s = np.pad(mask_s, ((0, 0), (0, pad)), constant_values=False)
        vol_s, beta_s = run(ret_s, mask_s)
        vol_out[:, start:stop] = vol_s[:, : stop - start]
        beta_out[:, start:stop] = beta_s[:, : stop - start]
    return vol_out, beta_out


@functools.lru_cache(maxsize=16)
def _mesh_strip_fn(mesh, axis_name: str, n_days: int, n_weeks: int,
                   n_months: int, window: int, min_periods: int,
                   window_weeks: int, contiguous: bool = False):
    """shard_map'd strip program: the firm axis is split EXPLICITLY, so
    every op inside is device-local by construction — no reliance on GSPMD
    inferring that the per-column scatter needs no communication (it
    conservatively all-gathers the scatter indices otherwise).
    ``contiguous=True`` selects the starts/counts ingest variant."""
    import jax
    from jax.sharding import PartitionSpec as P

    from fm_returnprediction_tpu.ops.daily_compact import (
        daily_compact_strip,
        daily_compact_strip_contiguous,
    )
    from fm_returnprediction_tpu.parallel.mesh import shard_map

    kernel = functools.partial(
        daily_compact_strip_contiguous if contiguous else daily_compact_strip,
        n_days=n_days, n_weeks=n_weeks, n_months=n_months,
        window=window, min_periods=min_periods, window_weeks=window_weeks,
        # GSPMD/shard_map cannot partition the pallas custom-call; the XLA
        # cumsum path is firm-local.
        use_pallas=False,
    )
    if contiguous:
        in_specs = (P(None, axis_name), P(axis_name), P(axis_name),
                    P(), P(), P(), P(), P())
    else:
        in_specs = (P(None, axis_name), P(None, axis_name),
                    P(), P(), P(), P(), P())
    return jax.jit(
        shard_map(
            kernel,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(None, axis_name), P(None, axis_name)),
        )
    )


def daily_characteristics_compact_chunked(
    row_values,
    row_pos,
    offsets,
    mkt_d,
    mkt_present,
    day_month_id,
    week_id,
    week_month_id,
    n_days: int,
    n_weeks: int,
    n_months: int,
    window: int = 252,
    min_periods: int = 100,
    window_weeks: int = 156,
    firm_chunk: Optional[int] = None,
    use_pallas: Optional[bool] = None,
    height_bucket: int = 1024,
    mesh=None,
    axis_name: str = "firms",
) -> Tuple[np.ndarray, np.ndarray]:
    """vol-252 and weekly beta from the compacted (CSR) daily layout.

    The transfer-lean driver (see ``ops.daily_compact``): firms are ordered
    by row count DESCENDING and cut into fixed-width strips, so each
    strip's rectangle is only as tall as its longest-lived firm — total
    bytes moved tracks observed rows, not the dense (D, N) grid. Strip
    heights round up to ``height_bucket`` multiples to bound the number of
    distinct compiled shapes. Outputs return in the ORIGINAL firm order,
    (n_months, N) numpy each.

    With ``mesh``, each strip's firm axis is sharded over the mesh
    (round-2 VERDICT item 5: the multi-chip daily path consumes the SAME
    compact ingest — the dense (D, N) grid is never materialized on host or
    device). The strip program is per-firm-column throughout, so XLA's
    SPMD partitioner runs it collective-free; strips widen by the device
    count so every device gets full tiles.
    """
    from fm_returnprediction_tpu.ops.daily_compact import daily_compact_strip

    row_values = np.asarray(row_values)
    row_pos = np.asarray(row_pos)
    offsets = np.asarray(offsets)
    counts = np.diff(offsets)
    n_firms = len(counts)
    dtype = row_values.dtype

    if mesh is not None:
        # shard_map cannot partition the pallas custom-call; the XLA cumsum
        # path is firm-local. An explicit request would be silently dropped,
        # so reject it rather than ignore it.
        if use_pallas:
            raise ValueError("use_pallas=True is not supported with a mesh")
        use_pallas = False
    if use_pallas is None:
        from fm_returnprediction_tpu.ops.rolling import _pallas_default

        use_pallas = _pallas_default()

    def bucket(h: int) -> int:
        return max(-(-int(h) // height_bucket) * height_bucket, height_bucket)

    n_shards = 1 if mesh is None else int(mesh.shape[axis_name])
    if firm_chunk is None:
        # Narrow strips, not memory-budget strips: with firms sorted by row
        # count, a strip's rectangle is efficient only if its width is small
        # enough that the strip's max height tracks its firms' counts — wide
        # strips degenerate to the dense grid's transfer volume. Target
        # ~2^25 slots per strip (~200 MB f32+int16 on the wire) PER DEVICE,
        # well under any device budget, and cheap per-strip dispatch keeps
        # the loop overhead negligible.
        h_max = bucket(int(counts.max(initial=1)))
        firm_chunk = max(((1 << 25) // h_max) // 128 * 128, 128) * n_shards
    c = min(int(firm_chunk), n_firms)
    c = -(-c // n_shards) * n_shards  # full tiles on every device

    order = np.argsort(-counts, kind="stable")

    import jax
    import jax.numpy as jnp

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from fm_returnprediction_tpu.parallel.mesh import place_global

        strip_sharding = NamedSharding(mesh, P(None, axis_name))
        firm_sharding = NamedSharding(mesh, P(axis_name))
        rep = NamedSharding(mesh, P())
        # placement straight from numpy: each device fetches only its shard
        # from host memory (a jnp.asarray first would commit the full strip
        # to device 0 and then reshard — double the transfer). place_global
        # rather than device_put: the strips are NaN-padded, which the
        # cross-process device_put value check cannot compare.
        place_strip = lambda a: place_global(a, strip_sharding)
        place_firm = lambda a: place_global(a, firm_sharding)
        place_rep = lambda a: place_global(np.asarray(a), rep)
    else:
        place_strip = place_firm = place_rep = jnp.asarray

    mkt_j = place_rep(np.asarray(mkt_d))
    mkt_present_j = place_rep(np.asarray(mkt_present))
    month_j = place_rep(np.asarray(day_month_id))
    week_j = place_rep(np.asarray(week_id))
    week_month_j = place_rep(np.asarray(week_month_id))

    # Per-firm day-contiguity (positions strictly increase per firm, so a
    # firm is contiguous iff its position span equals count-1). Contiguous
    # strips ship per-firm starts/counts instead of the (H, C) int16
    # position rectangle — a third of the strip's bytes, and the rectangle
    # assembly memcpy disappears. CRSP rows exist for every trading day
    # while a firm is listed, so this is the common case.
    if n_firms and len(row_pos):
        cap = len(row_pos) - 1
        fi = np.minimum(offsets[:-1], cap)   # clamp: zero-count firms index
        li = np.clip(offsets[1:] - 1, 0, cap)  # a neighbor, gated below
        first_pos = np.where(counts > 0, row_pos[fi].astype(np.int64), 0)
        last_pos = np.where(counts > 0, row_pos[li].astype(np.int64), -1)
        # count 0: (-1) - 0 == counts - 1, so empty firms count as contiguous
        # with start 0 / count 0 → every pos slot is padding, as before
        firm_contiguous = (last_pos - first_pos) == (counts - 1)
    else:
        first_pos = np.zeros(n_firms, np.int64)
        firm_contiguous = np.zeros(n_firms, bool)

    def strip_fn(contiguous: bool):
        if mesh is not None:
            return _mesh_strip_fn(
                mesh, axis_name, int(n_days), int(n_weeks), int(n_months),
                int(window), int(min_periods), int(window_weeks),
                contiguous=contiguous,
            )
        from fm_returnprediction_tpu.ops.daily_compact import (
            daily_compact_strip_contiguous,
        )

        kernel = daily_compact_strip_contiguous if contiguous else daily_compact_strip
        return functools.partial(
            kernel, n_days=n_days, n_weeks=n_weeks, n_months=n_months,
            window=window, min_periods=min_periods,
            window_weeks=window_weeks, use_pallas=use_pallas,
        )

    vol_out = np.empty((n_months, n_firms), dtype=dtype)
    beta_out = np.empty((n_months, n_firms), dtype=dtype)
    # Pipelined schedule: dispatch ahead of the pulls (jax dispatch is
    # async, so strip i+1's host assembly and host→device transfer overlap
    # strip i's device compute) but keep at most ``max_inflight`` strips
    # un-pulled — the pull is the execution barrier that bounds how many
    # strips' input buffers are live on the device at once. Pulling inside
    # the loop with no lookahead would serialize transfer and compute;
    # never pulling until the end would let queued strips pin the whole
    # compact volume in device memory.
    max_inflight = 2
    pending = []

    def drain_one():
        firms_d, vol_d, beta_d = pending.pop(0)
        vol_out[:, firms_d] = np.asarray(vol_d)[:, : len(firms_d)]
        beta_out[:, firms_d] = np.asarray(beta_d)[:, : len(firms_d)]

    for start in range(0, n_firms, c):
        firms = order[start : start + c]
        h = bucket(int(counts[firms].max(initial=1)))
        rect_vals = np.full((h, c), np.nan, dtype=dtype)
        for k, f in enumerate(firms):
            a, b = offsets[f], offsets[f + 1]
            rect_vals[: b - a, k] = row_values[a:b]
        if len(firms) and bool(firm_contiguous[firms].all()):
            starts_arr = np.zeros(c, dtype=np.int32)
            counts_arr = np.zeros(c, dtype=np.int32)  # width-padding firms: 0 rows
            starts_arr[: len(firms)] = first_pos[firms]
            counts_arr[: len(firms)] = counts[firms]
            vol_s, beta_s = strip_fn(True)(
                place_strip(rect_vals), place_firm(starts_arr),
                place_firm(counts_arr),
                mkt_j, mkt_present_j, month_j, week_j, week_month_j,
            )
        else:
            rect_pos = np.full((h, c), n_days, dtype=row_pos.dtype)
            for k, f in enumerate(firms):
                a, b = offsets[f], offsets[f + 1]
                rect_pos[: b - a, k] = row_pos[a:b]
            vol_s, beta_s = strip_fn(False)(
                place_strip(rect_vals), place_strip(rect_pos),
                mkt_j, mkt_present_j, month_j, week_j, week_month_j,
            )
        pending.append((firms, vol_s, beta_s))
        if len(pending) >= max_inflight:
            drain_one()
    while pending:
        drain_one()
    return vol_out, beta_out
