"""Pallas TPU kernels for the hot rolling-reduction family.

The largest data volume in the pipeline is the daily (D, N) panel
(D≈12,600 trading days × N≈10⁴ firms — the reference's polars beta kernel
and 252-day rolling std, SURVEY §3.5). The rolling ops are memory-bound:
the XLA path materializes separate full-size intermediates for the masked
values, their squares, and the finite counts, then runs three cumulative
sums — ~6 full HBM round-trips of the (D, N) array. The fused kernel here
reads ``x`` ONCE and emits all three inclusive cumulative moments
(Σx, Σx², Σ1{finite}) in a single pass, with the block-local cumulative sum
computed as a lower-triangular matmul on the MXU and a (1, block) carry row
propagated across the sequential time-grid dimension.

Windowed reductions (rolling std/mean/sum) then follow from cumulative-sum
differences exactly as in ``ops.rolling`` — same numerics, one HBM read.

The kernel is TPU-only by construction; ``interpret=True`` runs it on CPU
for the parity test suite.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["masked_cumulative_moments", "rolling_std_fused"]


def _moments_kernel(x_ref, csum_ref, csumsq_ref, ccnt_ref, carry_ref):
    """One (BT, BN) tile: fused mask + three block cumsums + carry update.

    Grid is (N-strips, T-blocks) with the T axis sequential (minormost), so
    ``carry_ref`` — the running total at the end of the previous T block for
    this firm strip — persists across T steps and resets at t-block 0.
    """
    it = pl.program_id(1)

    @pl.when(it == 0)
    def _reset():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    x = x_ref[...]
    bt, bn = x.shape
    finite = jnp.isfinite(x)
    xz = jnp.where(finite, x, 0.0)

    # stacked (BT, 3·BN): [values | squares | counts] → ONE triangular
    # matmul on the MXU produces all three inclusive block-cumsums.
    stacked = jnp.concatenate([xz, xz * xz, finite.astype(x.dtype)], axis=1)
    row = jax.lax.broadcasted_iota(jnp.int32, (bt, bt), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (bt, bt), 1)
    tri = (col <= row).astype(x.dtype)
    cs = jax.lax.dot(tri, stacked, precision=jax.lax.Precision.HIGHEST)

    cs = cs + carry_ref[0:1, :]
    carry_ref[0:1, :] = cs[bt - 1 : bt, :]

    csum_ref[...] = cs[:, 0:bn]
    csumsq_ref[...] = cs[:, bn : 2 * bn]
    ccnt_ref[...] = cs[:, 2 * bn : 3 * bn]


@functools.partial(
    jax.jit, static_argnames=("block_t", "block_n", "interpret")
)
def masked_cumulative_moments(
    x: jnp.ndarray,
    block_t: int = 256,
    block_n: int = 512,
    interpret: bool = False,
):
    """Inclusive cumulative (Σx, Σx², count) over axis 0, NaN-masked.

    x : (T, N). Non-finite entries contribute zero to sums and squares and
    zero to the count — exactly the masking ``ops.rolling`` applies before
    its cumulative sums. Returns three (T, N) arrays.
    """
    t, n = x.shape
    pt, pn = (-t) % block_t, (-n) % block_n
    xp = jnp.pad(x, ((0, pt), (0, pn)), constant_values=jnp.nan)
    tp, np_ = t + pt, n + pn
    grid = (np_ // block_n, tp // block_t)

    spec = pl.BlockSpec((block_t, block_n), lambda i_n, i_t: (i_t, i_n))
    out_shape = jax.ShapeDtypeStruct((tp, np_), x.dtype)
    csum, csumsq, ccnt = pl.pallas_call(
        _moments_kernel,
        grid=grid,
        in_specs=[spec],
        out_specs=[spec, spec, spec],
        out_shape=[out_shape, out_shape, out_shape],
        scratch_shapes=[pltpu.VMEM((1, 3 * block_n), x.dtype)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(xp)
    return csum[:t, :n], csumsq[:t, :n], ccnt[:t, :n]


def rolling_std_fused(
    x: jnp.ndarray,
    window: int,
    min_periods: int,
    block_t: int = 256,
    block_n: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Trailing-window sample std via the fused moments kernel.

    Pandas ``rolling(window, min_periods).std()`` semantics, matching
    ``ops.rolling.rolling_std`` (ddof=1; NaN until ``min_periods`` finite
    entries in the window; NaN entries occupy window rows but are excluded
    from the reduction — ``src/calc_Lewellen_2014.py:448-453``).
    """
    csum, csumsq, ccnt = masked_cumulative_moments(
        x, block_t=block_t, block_n=block_n, interpret=interpret
    )

    def windowed(c):
        if c.shape[0] <= window:
            return c  # every trailing window is truncated at the start
        lag = jnp.concatenate(
            [jnp.zeros((window, c.shape[1]), c.dtype), c[:-window]], axis=0
        )
        return c - lag

    s = windowed(csum)
    s2 = windowed(csumsq)
    cnt = windowed(ccnt)

    cnt_safe = jnp.maximum(cnt, 2.0)
    mean = s / jnp.maximum(cnt, 1.0)
    var = (s2 - cnt * mean * mean) / (cnt_safe - 1.0)
    std = jnp.sqrt(jnp.maximum(var, 0.0))
    return jnp.where(cnt >= max(min_periods, 2), std, jnp.nan)
