"""Pallas TPU kernels for the hot rolling-reduction family.

The largest data volume in the pipeline is the daily (D, N) panel
(D≈12,600 trading days × N≈10⁴ firms — the reference's polars beta kernel
and 252-day rolling std, SURVEY §3.5). The rolling ops are memory-bound:
the XLA path materializes separate full-size intermediates for the masked
values, their squares, and the finite counts, then runs three cumulative
sums and the windowed differencing — many full HBM round-trips of the
(D, N) array.

``rolling_std_fused`` is the end-to-end fused kernel: it reads ``x`` ONCE
and writes the finished rolling std ONCE — mask, the three cumulative
moments (Σx, Σx², Σ1{finite}), the trailing-``window`` differencing, and
the variance finalization all happen in VMEM. The block-local cumulative
sum is a lower-triangular matmul on the MXU; two scratch buffers carry
state across the sequential time-grid dimension: a (1, 3·BN) running-total
row and a (window, 3·BN) history of the last ``window`` cumulative-moment
rows, which supplies the ``t-window`` lag for the windowed difference
without re-reading HBM. (The round-2 version wrote the three cumulative
moments back to HBM and left differencing to XLA — measured 0.95× vs XLA
because total HBM traffic was not actually lower.)

Block sizes snap to divisors of the input shape when one exists (e.g.
T=12,608 → BT=64), so production shapes avoid the pre-kernel pad copy — an
extra full HBM round-trip of the largest array — entirely; ragged shapes
fall back to a NaN pad.

``masked_cumulative_moments`` (the three-output building block) remains for
callers that need the raw cumulative moments.

The kernel is TPU-only by construction; ``interpret=True`` runs it on CPU
for the parity test suite.

DECISION RULE, RESOLVED (round-3 verdict item 3): the kernel stayed
gated off until a bench artifact recorded the fused kernel > 1× vs the
XLA path on real TPU hardware. Round 4 reached hardware and measured
**2.81×** (``BENCH_r04_self.json``: ``rolling_std_pallas_ms`` 8.337 vs
``rolling_std_xla_ms`` 23.389, (12608, 4096) f32, TPU v5e), so the
default is now ON for TPU (``ops.rolling._pallas_default``);
``bench.py`` keeps measuring both paths every TPU round so a
regression shows up in the artifact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "masked_cumulative_moments",
    "rolling_std_fused",
    "rolling_sum_fused",
    "rolling_mean_fused",
]

# version-compat shim (the parallel.mesh shard_map pattern): pallas renamed
# ``TPUCompilerParams`` → ``CompilerParams``; accept whichever this jax
# ships so the kernels (and their CPU interpret-mode tests) run on both
# sides of the rename
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def _fit_block(dim: int, preferred: int, step: int) -> int:
    """Largest multiple-of-``step`` divisor of ``dim`` that is <= ``preferred``
    (so the grid tiles the array exactly and no pad copy is needed); falls
    back to ``preferred`` when none exists (the pad path)."""
    top = min(preferred, max(dim - dim % step, step))
    for b in range(top, step - 1, -step):
        if dim % b == 0:
            return b
    return preferred


def _tiles(x: jnp.ndarray, block_t: int, block_n: int):
    """Shared launch scaffolding: snap blocks to divisors, pad only if
    ragged, and build the (N-strips, T-blocks) grid + block spec."""
    t, n = x.shape
    block_t = _fit_block(t, block_t, 8)
    block_n = _fit_block(n, block_n, 128)
    pt, pn = (-t) % block_t, (-n) % block_n
    xp = jnp.pad(x, ((0, pt), (0, pn)), constant_values=jnp.nan) if pt or pn else x
    grid = ((n + pn) // block_n, (t + pt) // block_t)
    spec = pl.BlockSpec((block_t, block_n), lambda i_n, i_t: (i_t, i_n))
    return xp, grid, spec, block_t, block_n


def _masked_block_cumsum(x, carry_ref):
    """One (BT, BN) tile: NaN mask, then the three inclusive block cumsums
    (Σx, Σx², count) stacked as (BT, 3·BN) — ONE lower-triangular matmul on
    the MXU — plus the running-total carry update across T blocks."""
    bt, bn = x.shape
    finite = jnp.isfinite(x)
    xz = jnp.where(finite, x, 0.0)
    stacked = jnp.concatenate([xz, xz * xz, finite.astype(x.dtype)], axis=1)
    row = jax.lax.broadcasted_iota(jnp.int32, (bt, bt), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (bt, bt), 1)
    tri = (col <= row).astype(x.dtype)
    cs = jax.lax.dot(tri, stacked, precision=jax.lax.Precision.HIGHEST)
    cs = cs + carry_ref[0:1, :]
    carry_ref[0:1, :] = cs[bt - 1 : bt, :]
    return cs


def _moments_kernel(x_ref, csum_ref, csumsq_ref, ccnt_ref, carry_ref):
    """Grid is (N-strips, T-blocks) with the T axis sequential (minormost),
    so ``carry_ref`` — the running total at the end of the previous T block
    for this firm strip — persists across T steps and resets at t-block 0.
    """
    it = pl.program_id(1)

    @pl.when(it == 0)
    def _reset():
        carry_ref[...] = jnp.zeros_like(carry_ref)

    x = x_ref[...]
    bn = x.shape[1]
    cs = _masked_block_cumsum(x, carry_ref)
    csum_ref[...] = cs[:, 0:bn]
    csumsq_ref[...] = cs[:, bn : 2 * bn]
    ccnt_ref[...] = cs[:, 2 * bn : 3 * bn]


@functools.partial(
    jax.jit, static_argnames=("block_t", "block_n", "interpret")
)
def masked_cumulative_moments(
    x: jnp.ndarray,
    block_t: int = 256,
    block_n: int = 512,
    interpret: bool = False,
):
    """Inclusive cumulative (Σx, Σx², count) over axis 0, NaN-masked.

    x : (T, N). Non-finite entries contribute zero to sums and squares and
    zero to the count — exactly the masking ``ops.rolling`` applies before
    its cumulative sums. Returns three (T, N) arrays.
    """
    t, n = x.shape
    xp, grid, spec, block_t, block_n = _tiles(x, block_t, block_n)
    out_shape = jax.ShapeDtypeStruct(xp.shape, x.dtype)
    csum, csumsq, ccnt = pl.pallas_call(
        _moments_kernel,
        grid=grid,
        in_specs=[spec],
        out_specs=[spec, spec, spec],
        out_shape=[out_shape, out_shape, out_shape],
        scratch_shapes=[pltpu.VMEM((1, 3 * block_n), x.dtype)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(xp)
    return csum[:t, :n], csumsq[:t, :n], ccnt[:t, :n]


def _windowed_reduce_kernel(window, min_periods, kind,
                            x_ref, out_ref, carry_ref, hist_ref):
    """One (BT, BN) tile: mask → block cumsums → windowed diff → finalize.

    ``hist_ref`` holds the last ``window`` rows of the (carried) cumulative
    moments from preceding T blocks, so the ``t-window`` lag is a static
    VMEM slice for ANY window/block_t combination; it starts at zero, which
    is exactly the "cumsum before the series start" value trailing truncated
    windows need.

    ``kind`` (trace-time static) selects the finalization — ``"sum"`` /
    ``"mean"`` / ``"std"`` — transcribing ``ops.rolling``'s
    ``finalize_sum``/``finalize_mean``/``finalize_std`` semantics exactly.
    Sum and mean ride the same three-column (Σx, Σx², count) cumsum as std:
    the extra column is VMEM-local MXU work on a kernel whose cost is the
    HBM read of ``x`` and write of the result, and one kernel body keeps
    one set of carry semantics to verify.
    """
    it = pl.program_id(1)

    @pl.when(it == 0)
    def _reset():
        carry_ref[...] = jnp.zeros_like(carry_ref)
        hist_ref[...] = jnp.zeros_like(hist_ref)

    x = x_ref[...]
    bt, bn = x.shape
    cs = _masked_block_cumsum(x, carry_ref)

    # full[i] is the cumulative moment at global row (block_start - window + i),
    # so rows [0, bt) are exactly the t-window lags for this block.
    full = jnp.concatenate([hist_ref[...], cs], axis=0)  # (window + bt, 3·BN)
    hist_ref[...] = full[bt : bt + window, :]
    w = cs - full[0:bt, :]

    s, s2, cnt = w[:, 0:bn], w[:, bn : 2 * bn], w[:, 2 * bn : 3 * bn]
    if kind == "sum":
        out_ref[...] = jnp.where(cnt >= min_periods, s, jnp.nan)
    elif kind == "mean":
        mean = s / jnp.maximum(cnt, 1.0)
        out_ref[...] = jnp.where(cnt >= min_periods, mean, jnp.nan)
    else:  # std (ddof=1, count>=2 rule)
        cnt_safe = jnp.maximum(cnt, 2.0)
        mean = s / jnp.maximum(cnt, 1.0)
        var = (s2 - cnt * mean * mean) / (cnt_safe - 1.0)
        std = jnp.sqrt(jnp.maximum(var, 0.0))
        out_ref[...] = jnp.where(cnt >= max(min_periods, 2), std, jnp.nan)


@functools.partial(
    jax.jit,
    static_argnames=("window", "min_periods", "kind", "block_t", "block_n",
                     "interpret"),
)
def _rolling_reduce_fused(
    x: jnp.ndarray,
    window: int,
    min_periods: int,
    kind: str,
    block_t: int = 256,
    block_n: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Shared launch for the fused trailing-window family (one HBM read of
    ``x``, one write of the finished reduction)."""
    t, n = x.shape
    xp, grid, spec, block_t, block_n = _tiles(x, block_t, block_n)
    out = pl.pallas_call(
        functools.partial(_windowed_reduce_kernel, window, min_periods, kind),
        grid=grid,
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(xp.shape, x.dtype),
        scratch_shapes=[
            pltpu.VMEM((1, 3 * block_n), x.dtype),
            pltpu.VMEM((window, 3 * block_n), x.dtype),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(xp)
    return out[:t, :n]


def rolling_std_fused(
    x: jnp.ndarray,
    window: int,
    min_periods: int,
    block_t: int = 256,
    block_n: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Trailing-window sample std, fully fused: one HBM read, one write.

    Pandas ``rolling(window, min_periods).std()`` semantics, matching
    ``ops.rolling.rolling_std`` (ddof=1; NaN until ``min_periods`` finite
    entries in the window; NaN entries occupy window rows but are excluded
    from the reduction — ``src/calc_Lewellen_2014.py:448-453``).
    """
    return _rolling_reduce_fused(x, window, min_periods, "std",
                                 block_t=block_t, block_n=block_n,
                                 interpret=interpret)


def rolling_sum_fused(
    x: jnp.ndarray,
    window: int,
    min_periods: int,
    block_t: int = 256,
    block_n: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Trailing-window masked sum, fully fused (``ops.rolling.rolling_sum``
    semantics: NaN entries occupy rows but are excluded; NaN until
    ``min_periods`` finite entries)."""
    return _rolling_reduce_fused(x, window, min_periods, "sum",
                                 block_t=block_t, block_n=block_n,
                                 interpret=interpret)


def rolling_mean_fused(
    x: jnp.ndarray,
    window: int,
    min_periods: int,
    block_t: int = 256,
    block_n: int = 512,
    interpret: bool = False,
) -> jnp.ndarray:
    """Trailing-window masked mean, fully fused
    (``ops.rolling.rolling_mean`` semantics)."""
    return _rolling_reduce_fused(x, window, min_periods, "mean",
                                 block_t=block_t, block_n=block_n,
                                 interpret=interpret)
