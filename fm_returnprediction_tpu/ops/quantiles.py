"""Masked cross-sectional quantiles and winsorization.

Two consumers in the pipeline (SURVEY §7 hard part (a): quantiles over
masked data are the subtle one):

- NYSE size breakpoints: monthly 20th/50th percentiles of NYSE market equity
  (pandas ``.quantile``, linear interpolation — ``src/calc_Lewellen_2014.py:74-82``);
- per-month winsorization at [1%, 99%] per variable, skipping months with
  fewer than 5 valid observations (``np.percentile``, also linear —
  ``src/calc_Lewellen_2014.py:505-529``).

Both reduce to one masked-quantile primitive: sort each month's cross-section
with invalid entries pushed to +inf, then linearly interpolate at rank
``q · (n_valid − 1)``. Sorting is per-month along the firm axis — a batched
``sort`` XLA handles natively on TPU.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = ["masked_quantile", "winsorize_cs", "winsorize_cs_batched"]


def masked_quantile(values: jnp.ndarray, valid: jnp.ndarray, q) -> jnp.ndarray:
    """Linear-interpolated quantile(s) of the valid entries of each row.

    Parameters
    ----------
    values : (T, N) — quantiles are taken along the last axis.
    valid : (T, N) bool.
    q : scalar or (Q,) quantiles in [0, 1].

    Returns (T,) for scalar q, else (T, Q); rows with no valid entries give
    NaN. Matches ``np.percentile``/``pd.Series.quantile`` 'linear'
    interpolation exactly.
    """
    q_arr = jnp.atleast_1d(jnp.asarray(q, dtype=values.dtype))
    big = jnp.asarray(jnp.inf, dtype=values.dtype)
    data = jnp.where(valid & jnp.isfinite(values), values, big)
    data = jnp.sort(data, axis=-1)                          # (T, N)
    n = (valid & jnp.isfinite(values)).sum(axis=-1)         # (T,)

    rank = q_arr[None, :] * jnp.maximum(n - 1, 0)[:, None].astype(values.dtype)
    lo = jnp.floor(rank).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, jnp.maximum(n - 1, 0)[:, None].astype(jnp.int32))
    frac = rank - lo.astype(values.dtype)

    take = lambda idx: jnp.take_along_axis(data, idx, axis=-1)
    out = take(lo) * (1.0 - frac) + take(hi) * frac          # (T, Q)
    out = jnp.where((n > 0)[:, None], out, jnp.nan)
    return out[:, 0] if jnp.ndim(q) == 0 else out


def _interp_rank(asc_at, n, q, dtype):
    """Linear interpolation at rank ``q·(n−1)`` given ``asc_at(j) -> (T,)``,
    the j-th ASCENDING order statistic per row — the same arithmetic as
    ``masked_quantile``, just with a different way of reaching the values."""
    nm1 = jnp.maximum(n - 1, 0)
    rank = q * nm1.astype(dtype)
    lo = jnp.floor(rank).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, nm1.astype(jnp.int32))
    frac = rank - lo.astype(dtype)
    out = asc_at(lo) * (1.0 - frac) + asc_at(hi) * frac
    return jnp.where(n > 0, out, jnp.nan)


def _edge_quantiles(values, ok, q_lo: float, q_hi: float, k: int):
    """Both tail quantiles from two ``lax.top_k`` calls instead of a full
    sort — the ranks touched by q near 0/1 live in the outer ``k`` order
    statistics, and top_k is ~35x cheaper than sort at the winsorize shape
    (measured (600, 26000) f32: 7.7 s sort vs 0.21 s top_k on one CPU core;
    the selection is exact, so numerics match the sort path bit-for-bit)."""
    dtype = values.dtype
    n = ok.sum(axis=-1)
    neg = jnp.asarray(-jnp.inf, dtype=dtype)

    top = jax.lax.top_k(jnp.where(ok, values, neg), k)[0]     # (T, k) desc
    bot = jax.lax.top_k(jnp.where(ok, -values, neg), k)[0]    # -(asc order)

    def take(mat, idx):
        return jnp.take_along_axis(mat, jnp.maximum(idx, 0)[:, None], axis=-1)[:, 0]

    # ascending rank j == descending index (n-1-j) of `top`; for the lower
    # tail, ascending rank j == -bot[:, j]
    high = _interp_rank(lambda j: take(top, n - 1 - j), n, q_hi, dtype)
    low = _interp_rank(lambda j: -take(bot, j), n, q_lo, dtype)
    return low, high


def winsorize_cs(
    values: jnp.ndarray,
    valid: jnp.ndarray,
    lower_percentile: float = 1.0,
    upper_percentile: float = 99.0,
    min_obs: int = 5,
) -> jnp.ndarray:
    """Per-month cross-sectional clip at the given percentiles.

    Months with fewer than ``min_obs`` valid observations pass through
    unclipped (``src/calc_Lewellen_2014.py:520-521``). NaN entries stay NaN
    (clip of NaN is NaN, as in pandas ``.clip``).
    """
    q_lo = lower_percentile / 100.0
    q_hi = upper_percentile / 100.0
    ok = valid & jnp.isfinite(values)
    n_cols = values.shape[-1]
    k = int(math.ceil(max(q_lo, 1.0 - q_hi) * max(n_cols - 1, 1))) + 2
    if 4 * k < n_cols:
        low, high = _edge_quantiles(values, ok, q_lo, q_hi, k)
        low, high = low[:, None], high[:, None]
    else:  # tails too deep for a top-k win — full masked sort
        qs = masked_quantile(values, valid, jnp.asarray([q_lo, q_hi]))
        low, high = qs[:, 0][:, None], qs[:, 1][:, None]
    n = ok.sum(axis=-1)
    clipped = jnp.clip(values, low, high)
    apply = (n >= min_obs)[:, None]
    return jnp.where(apply, clipped, values)


def winsorize_cs_batched(
    values: jnp.ndarray,
    valid: jnp.ndarray,
    lower_percentile: float = 1.0,
    upper_percentile: float = 99.0,
    min_obs: int = 5,
) -> jnp.ndarray:
    """``winsorize_cs`` over a stack of variables in ONE batched launch.

    ``values`` is (V, T, N) — V independent variables sharing the (T, N)
    validity mask. The per-variable Python loop compiled V separate
    top-k/sort kernel instances into the program; the vmap form batches
    them into one (``lax.top_k`` batches leading axes natively), which is
    the same shape of win as the r5 compaction-gather batching. Numerics
    are identical to the per-column path — the differential test in
    ``tests/test_specgrid.py`` pins bit-equality.
    """
    return jax.vmap(
        lambda v: winsorize_cs(
            v, valid, lower_percentile, upper_percentile, min_obs
        )
    )(values)
