"""Masked cross-sectional quantiles and winsorization.

Two consumers in the pipeline (SURVEY §7 hard part (a): quantiles over
masked data are the subtle one):

- NYSE size breakpoints: monthly 20th/50th percentiles of NYSE market equity
  (pandas ``.quantile``, linear interpolation — ``src/calc_Lewellen_2014.py:74-82``);
- per-month winsorization at [1%, 99%] per variable, skipping months with
  fewer than 5 valid observations (``np.percentile``, also linear —
  ``src/calc_Lewellen_2014.py:505-529``).

Both reduce to one masked-quantile primitive: sort each month's cross-section
with invalid entries pushed to +inf, then linearly interpolate at rank
``q · (n_valid − 1)``. Sorting is per-month along the firm axis — a batched
``sort`` XLA handles natively on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["masked_quantile", "winsorize_cs"]


def masked_quantile(values: jnp.ndarray, valid: jnp.ndarray, q) -> jnp.ndarray:
    """Linear-interpolated quantile(s) of the valid entries of each row.

    Parameters
    ----------
    values : (T, N) — quantiles are taken along the last axis.
    valid : (T, N) bool.
    q : scalar or (Q,) quantiles in [0, 1].

    Returns (T,) for scalar q, else (T, Q); rows with no valid entries give
    NaN. Matches ``np.percentile``/``pd.Series.quantile`` 'linear'
    interpolation exactly.
    """
    q_arr = jnp.atleast_1d(jnp.asarray(q, dtype=values.dtype))
    big = jnp.asarray(jnp.inf, dtype=values.dtype)
    data = jnp.where(valid & jnp.isfinite(values), values, big)
    data = jnp.sort(data, axis=-1)                          # (T, N)
    n = (valid & jnp.isfinite(values)).sum(axis=-1)         # (T,)

    rank = q_arr[None, :] * jnp.maximum(n - 1, 0)[:, None].astype(values.dtype)
    lo = jnp.floor(rank).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, jnp.maximum(n - 1, 0)[:, None].astype(jnp.int32))
    frac = rank - lo.astype(values.dtype)

    take = lambda idx: jnp.take_along_axis(data, idx, axis=-1)
    out = take(lo) * (1.0 - frac) + take(hi) * frac          # (T, Q)
    out = jnp.where((n > 0)[:, None], out, jnp.nan)
    return out[:, 0] if jnp.ndim(q) == 0 else out


def winsorize_cs(
    values: jnp.ndarray,
    valid: jnp.ndarray,
    lower_percentile: float = 1.0,
    upper_percentile: float = 99.0,
    min_obs: int = 5,
) -> jnp.ndarray:
    """Per-month cross-sectional clip at the given percentiles.

    Months with fewer than ``min_obs`` valid observations pass through
    unclipped (``src/calc_Lewellen_2014.py:520-521``). NaN entries stay NaN
    (clip of NaN is NaN, as in pandas ``.clip``).
    """
    qs = masked_quantile(
        values, valid, jnp.asarray([lower_percentile / 100.0, upper_percentile / 100.0])
    )                                                        # (T, 2)
    low, high = qs[:, 0][:, None], qs[:, 1][:, None]
    n = (valid & jnp.isfinite(values)).sum(axis=-1)
    clipped = jnp.clip(values, low, high)
    apply = (n >= min_obs)[:, None]
    return jnp.where(apply, clipped, values)
