"""Per-firm time compaction — pandas row semantics on dense arrays.

The reference computes every lag and rolling window with pandas
``groupby("permno").shift/rolling`` on row-sorted long frames
(``src/calc_Lewellen_2014.py:137-341``). Those are ROW operations: a firm
with a month gap sees its previous *row*, which may be several calendar
months earlier (SURVEY §7 hard part (b)). On the dense ``(T, N)`` panel the
equivalent is: stably compact each firm's observed rows to the front of the
time axis, run the window op on the compacted axis, and scatter results back
to the original slots. All steps are gather/scatter-free ``argsort`` +
``take_along_axis`` — static shapes, jit- and shard-friendly (firms are
independent, so the N axis shards cleanly).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "Compaction",
    "make_compaction",
    "compact",
    "scatter_back",
    "lag",
    "rolling_over_valid_rows",
]


class Compaction(NamedTuple):
    """Reusable per-firm compaction plan for one (T, N) mask."""

    order: jnp.ndarray      # (T, N) row permutation putting valid rows first
    inv_order: jnp.ndarray  # (T, N) inverse permutation
    count: jnp.ndarray      # (N,) valid rows per firm
    valid: jnp.ndarray      # (T, N) bool: compacted slot j < count[n]
    mask: jnp.ndarray       # (T, N) original mask


def make_compaction(mask: jnp.ndarray) -> Compaction:
    """Build the compaction plan for a (T, N) validity mask. ``stable=True``
    preserves chronological order within each firm, matching the reference's
    ``sort_values(["permno", "mthcaldt"])`` row order."""
    order = jnp.argsort(~mask, axis=0, stable=True)
    inv_order = jnp.argsort(order, axis=0, stable=True)
    count = mask.sum(axis=0)
    valid = jnp.arange(mask.shape[0])[:, None] < count[None, :]
    return Compaction(order, inv_order, count, valid, mask)


def compact(values: jnp.ndarray, plan: Compaction) -> jnp.ndarray:
    """Gather a (T, N) variable into compacted row order (invalid tail slots
    hold whatever the masked-out rows held; gate on ``plan.valid``)."""
    return jnp.take_along_axis(values, plan.order, axis=0)


def scatter_back(comp_values: jnp.ndarray, plan: Compaction, fill=jnp.nan) -> jnp.ndarray:
    """Inverse of :func:`compact`: place compacted-row results back at their
    original calendar slots; absent rows get ``fill``."""
    out = jnp.take_along_axis(comp_values, plan.inv_order, axis=0)
    return jnp.where(plan.mask, out, fill)


def lag(comp_values: jnp.ndarray, k: int, fill=jnp.nan) -> jnp.ndarray:
    """Row-shift by ``k`` on the compacted axis — the dense equivalent of
    ``groupby("permno")[col].shift(k)`` (e.g. ``src/calc_Lewellen_2014.py:144``).
    The first ``k`` compacted slots of each firm become ``fill``."""
    if k == 0:
        return comp_values
    pad = jnp.full((k,) + comp_values.shape[1:], fill, dtype=comp_values.dtype)
    return jnp.concatenate([pad, comp_values[:-k]], axis=0)[: comp_values.shape[0]]


@functools.partial(
    jax.jit, static_argnames=("window", "min_periods", "row_lag", "fill_invalid")
)
def rolling_over_valid_rows(
    values: jnp.ndarray,
    valid: jnp.ndarray,
    window: int,
    min_periods: int,
    row_lag: int = 0,
    fill_invalid: bool = False,
) -> jnp.ndarray:
    """Rolling mean over the SURVIVING rows of a (T, K) series, scattered
    back to calendar slots.

    The idiom shared by Figure 1's 120-month slope means
    (``src/calc_Lewellen_2014.py:926`` rolls over the slope FRAME's rows,
    i.e. consecutive surviving months, not calendar months) and the
    out-of-sample forecast's lagged coefficient means: stably compact rows
    where ``valid`` (T,) holds to the front, roll over the compacted axis,
    optionally shift by ``row_lag`` rows (strictly-prior information), and
    scatter back — invalid calendar slots give NaN.

    ``fill_invalid=True`` (requires ``row_lag > 0``) instead gives EVERY
    calendar slot the lagged mean its position would see — for an invalid
    slot, the window ending at the last surviving row before it. A slot's
    lagged mean depends only on strictly-prior surviving rows, so it is
    well-defined whether or not the slot itself survives; the serving
    layer needs it to quote E[r] for a month whose own cross-section
    cannot contribute a row yet. At surviving slots the two modes agree
    exactly (an invalid slot's compacted index IS the count of surviving
    rows before it).
    """
    from fm_returnprediction_tpu.ops.rolling import rolling_mean

    order = jnp.argsort(~valid, stable=True)
    inv_order = jnp.argsort(order, stable=True)
    in_range = (jnp.arange(valid.shape[0]) < valid.sum())[:, None]
    comp = jnp.where(in_range, values[order], jnp.nan)
    rolled = rolling_mean(comp, window, min_periods)
    if row_lag:
        pad = jnp.full((row_lag, rolled.shape[1]), jnp.nan, rolled.dtype)
        rolled = jnp.concatenate([pad, rolled[:-row_lag]], axis=0)
    if fill_invalid:
        if not row_lag:
            raise ValueError("fill_invalid requires row_lag > 0")
        # surviving rows strictly before each slot == the compacted index
        # the slot's lagged window ends at (for surviving slots this equals
        # inv_order, so the gather is a strict superset of the scatter)
        k = jnp.cumsum(valid) - valid
        return rolled[k]
    return jnp.where(valid[:, None], rolled[inv_order], jnp.nan)
