"""One topology controller over every supervised member kind.

``TopologySpec`` declares WHAT should be running (router, thread or
process replicas + transport, grid workers, broker) as journal-able
data; ``TopologyController`` supervises the live inventory against that
declaration — distinct killed/hung/ring-stalled classification, repair
verbs that reuse the fleet/pool machinery, fd+segment hygiene sweeps,
and exactly-once recovery of ANY declared shape from the request
journal's topology marks.
"""

from fm_returnprediction_tpu.topology.controller import (
    Member,
    TopologyController,
)
from fm_returnprediction_tpu.topology.spec import TopologySpec

__all__ = ["Member", "TopologyController", "TopologySpec"]
