"""Declarative topology shape: WHAT should be running, as data.

One frozen dataclass names every supervised member kind — router/ingress
(the fleet's submit path), replicas (thread or process, with the process
data plane's transport), the grid worker pool, and the exchange broker —
so the controller, the journal's topology marks, and crash-restart
recovery all speak the same shape language. The spec is the unit that
rides the journal (``to_mark``/``from_mark`` round-trip through plain
JSON-able dicts), which is what lets ``TopologyController.recover``
rebuild ANY declared shape from the marks alone.

Env resolution (``from_env``): ``FMRP_TOPO_REPLICAS``,
``FMRP_TOPO_REPLICA_MODE`` (thread|process),
``FMRP_TOPO_TRANSPORT`` (shm|socket, process mode's data plane),
``FMRP_TOPO_GRID_PROCS`` (0 = no grid pool),
``FMRP_TOPO_GRID_TRANSPORT`` (shm|frames).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Mapping, Optional

__all__ = ["TopologySpec"]

_REPLICA_MODES = ("thread", "process")
_FLEET_TRANSPORTS = (None, "shm", "socket")
_GRID_TRANSPORTS = (None, "shm", "frames")


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """The declared inventory: counts per member kind + transports.

    ``replicas`` — serving replicas behind the router (>= 1).
    ``replica_mode`` — ``thread`` (in-process) or ``process`` (spawned
    children; the mode every SIGKILL/liveness story needs).
    ``transport`` — process-replica data plane: ``shm`` rings or the
    ``socket`` oracle; ``None`` defers to ``FMRP_FLEET_TRANSPORT``.
    ``grid_procs`` — spec-grid contraction workers (0 = no pool; a pool
    also implies ONE embedded exchange broker, rank 0 in the parent).
    ``grid_transport`` — the pool's data plane (``shm``/``frames``;
    ``None`` defers to ``FMRP_GRID_TRANSPORT``).
    """

    replicas: int = 2
    replica_mode: str = "thread"
    transport: Optional[str] = None
    grid_procs: int = 0
    grid_transport: Optional[str] = None

    def __post_init__(self):
        if int(self.replicas) < 1:
            raise ValueError("a topology needs at least one replica")
        if self.replica_mode not in _REPLICA_MODES:
            raise ValueError(
                f"replica_mode {self.replica_mode!r} is not "
                f"{'|'.join(_REPLICA_MODES)}"
            )
        if self.transport not in _FLEET_TRANSPORTS:
            raise ValueError(
                f"transport {self.transport!r} is not shm|socket|None"
            )
        if self.transport is not None and self.replica_mode != "process":
            raise ValueError(
                "transport only applies to process replicas"
            )
        if int(self.grid_procs) < 0:
            raise ValueError("grid_procs must be >= 0")
        if self.grid_transport not in _GRID_TRANSPORTS:
            raise ValueError(
                f"grid_transport {self.grid_transport!r} is not "
                f"shm|frames|None"
            )

    # -- the member inventory (what the controller supervises) -----------

    @property
    def brokers(self) -> int:
        """Embedded exchange brokers: one per grid pool (rank 0)."""
        return 1 if self.grid_procs else 0

    def counts(self) -> Dict[str, int]:
        """kind → declared count (the inventory table's first column)."""
        return {
            "router": 1,
            f"replica_{self.replica_mode}": int(self.replicas),
            "grid_worker": int(self.grid_procs),
            "broker": self.brokers,
        }

    # -- journal round-trip ----------------------------------------------

    def to_mark(self) -> Dict[str, object]:
        """Plain JSON-able dict for the journal's ``topology`` mark."""
        return {
            "replicas": int(self.replicas),
            "replica_mode": self.replica_mode,
            "transport": self.transport,
            "grid_procs": int(self.grid_procs),
            "grid_transport": self.grid_transport,
        }

    @classmethod
    def from_mark(cls, mark: Mapping[str, object]) -> "TopologySpec":
        return cls(
            replicas=int(mark.get("replicas", 1)),
            replica_mode=str(mark.get("replica_mode", "thread")),
            transport=mark.get("transport") or None,
            grid_procs=int(mark.get("grid_procs", 0)),
            grid_transport=mark.get("grid_transport") or None,
        )

    @classmethod
    def from_env(cls, environ: Optional[Mapping[str, str]] = None
                 ) -> "TopologySpec":
        env = os.environ if environ is None else environ

        def _get(key: str, default: str) -> str:
            return (env.get(key, "") or "").strip() or default

        return cls(
            replicas=int(_get("FMRP_TOPO_REPLICAS", "2")),
            replica_mode=_get("FMRP_TOPO_REPLICA_MODE", "thread").lower(),
            transport=_get("FMRP_TOPO_TRANSPORT", "").lower() or None,
            grid_procs=int(_get("FMRP_TOPO_GRID_PROCS", "0")),
            grid_transport=(_get("FMRP_TOPO_GRID_TRANSPORT", "").lower()
                            or None),
        )
