"""One topology controller: the supervised inventory behind one pane.

The serving fleet supervises replicas; the grid pool supervises its
workers and broker; this module treats ALL of them — router, thread
replicas, process replicas (shm|socket), grid workers, the exchange
broker — as ONE declared inventory (:class:`TopologySpec`) with one
liveness ladder, one set of repair verbs, and one journal record that
crash-restart recovery can rebuild ANY shape from.

Liveness ladder (process replicas; each probe classifies DISTINCTLY):

1. ``killed`` — the OS pid is gone (``Popen.poll()`` non-None). A
   SIGKILL'd child.
2. ``ring_stalled`` — pid alive, but the shm request ring shows a
   committed-vs-consumed backlog that did not drain between two probe
   samples (``ShmRing.watermark()``). The data plane is wedged even if
   the pid looks healthy.
3. ``hung`` — pid alive, ring clean, but the control-plane ping did not
   answer inside ``FMRP_TOPO_PING_TIMEOUT_S``. A process that exists
   but no longer serves verbs.

Repair verbs reuse the machinery that already exists rather than
inventing a second lifecycle: a dead/hung/ring-stalled replica is
killed (which tears down and unlinks its shm rings + doorbells) and
replaced through ``ServingFleet.replace`` — compile-free from the
registry warm pool when armed — with a ``respawn`` mark in the journal;
a dead grid worker is the pool's own disclosed degraded N−1 respawn; a
dead broker is the pool's re-election. ``sweep()`` closes the hygiene
loop: any shm segment or doorbell fd the teardown hooks missed is
reclaimed and counted (``fmrp_topology_leaked_segments_total`` /
``fmrp_topology_leaked_fds_total``).

Exactly-once across a whole-controller crash: every topology change
writes a ``topology`` mark (the spec as JSON) into the fleet's request
journal; :meth:`TopologyController.recover` reads the LAST such mark
(``recover_journal``'s ``last_topology``), closes out in-flight
requests to typed retriable terminals, and rebuilds the declared shape
through ``ServingFleet.recover`` — clean replay, zero fresh compiles
with a populated registry, any shape.

The PR-12 autoscaler routes through here when attached: the controller
sets ``fleet.topology = self`` and the supervisor's scale verbs prefer
that attribute, so elasticity updates the declared shape (and its
journal record) instead of drifting away from it.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Dict, List, Optional, Tuple

from fm_returnprediction_tpu import telemetry
from fm_returnprediction_tpu.topology.spec import TopologySpec

__all__ = ["Member", "TopologyController"]

# classifications the repair verb acts on
_REPAIRABLE = ("killed", "hung", "ring_stalled")


@dataclasses.dataclass(frozen=True)
class Member:
    """One row of the live inventory."""

    kind: str                  # router | replica_thread | replica_process
    #                          # | grid_worker | broker
    ident: str                 # rid / shard id / "router" / "exchange"
    pid: Optional[int]         # OS pid (None: in-process member)
    status: str                # live | killed | hung | ring_stalled |
    #                          # draining | dead | degraded | closed
    detail: str = ""


class TopologyController:
    """Supervise a :class:`ServingFleet` (router + replicas) and an
    optional :class:`SpecGridWorkerPool` (grid workers + broker) as one
    declared inventory. See the module docstring for the ladder/verbs."""

    def __init__(self, spec: TopologySpec, *, fleet, pool=None,
                 ping_timeout_s: Optional[float] = None):
        self.spec = spec
        self.fleet = fleet
        self.pool = pool
        if ping_timeout_s is None:
            ping_timeout_s = float(os.environ.get(
                "FMRP_TOPO_PING_TIMEOUT_S", "2.0"))
        self.ping_timeout_s = float(ping_timeout_s)
        # rid → (produced, consumed) from the previous probe: the
        # ring-stall classifier needs TWO samples to tell "backlog being
        # drained" from "backlog frozen"
        self._ring_marks: Dict[str, Tuple[int, int]] = {}
        self.last_probe: Dict[str, str] = {}
        reg = telemetry.registry()
        self._m_respawns = reg.counter(
            "fmrp_topology_respawns_total",
            help="members respawned by the topology controller",
        )
        self._g_respawn_s = reg.gauge(
            "fmrp_topology_last_respawn_s",
            help="seconds from classification to completed respawn "
                 "(last repair)",
        )
        # the autoscaler's scale verbs route through the controller so
        # elasticity keeps the declared shape (and its journal record)
        # current instead of silently diverging from it
        fleet.topology = self
        self._mark_topology()

    # -- journal record ----------------------------------------------------

    def _mark_topology(self) -> None:
        self.fleet._jrnl_mark(
            "topology",
            topo=json.dumps(self.spec.to_mark(), sort_keys=True),
            size=int(self.spec.replicas),
        )

    def _mark(self, label: str, **fields) -> None:
        self.fleet._jrnl_mark(label, **fields)

    # -- post-mortem flight harvest ----------------------------------------

    def flight(self, rid: str) -> Optional[dict]:
        """The flight-recorder tail the member mirrored into its shm
        annex: the fleet's cached harvest for departed replicas (the
        kill path harvests through SIGKILL), a live read otherwise.
        None when the member never mirrored (annex off / thread
        replica)."""
        cached = getattr(self.fleet, "flights", {}).get(rid)
        if cached is not None:
            return cached
        rep = self.fleet.replica(rid)
        harvest = getattr(rep.service, "harvest_flight", None) \
            if rep is not None else None
        return harvest() if harvest is not None else None

    def _flight_detail(self, rid: str) -> str:
        """Compact ``flight=...`` clause for :meth:`members` /
        ``respawn`` marks — last mirrored reason + last span name, the
        two facts a post-mortem reader wants before opening the full
        harvest."""
        flight = self.flight(rid)
        if not flight:
            return ""
        spans = flight.get("spans") or []
        last = spans[-1].get("name") if spans else None
        out = f"flight={flight.get('reason', '?')}"
        if last:
            out += f" last_span={last}"
        return out + f" spans={len(spans)}"

    # -- the live inventory ------------------------------------------------

    def members(self) -> List[Member]:
        rows = [Member(
            kind="router", ident="router", pid=os.getpid(),
            status="crashed" if getattr(self.fleet, "_crashed", False)
            else "live",
            detail=f"replicas={len(self.fleet.replica_states())}",
        )]
        probe = self.last_probe
        for rid, state in sorted(self.fleet.replica_states().items()):
            rep = self.fleet.replica(rid)
            svc = rep.service if rep is not None else None
            proc = getattr(svc, "proc", None)
            kind = "replica_process" if proc is not None else \
                "replica_thread"
            status = probe.get(rid, state if state != "healthy"
                               else "live")
            detail = f"transport={getattr(svc, 'transport', 'thread')}"
            if status in _REPAIRABLE or status == "dead":
                # the probe verdict carries its post-mortem: the flight
                # tail harvested from the member's shm annex (survives
                # SIGKILL — the mirror protocol is commit-last)
                fl = self._flight_detail(rid)
                if fl:
                    detail += f" {fl}"
            rows.append(Member(
                kind=kind, ident=rid,
                pid=getattr(svc, "pid", None),
                status=status,
                detail=detail,
            ))
        pool = self.pool
        if pool is not None:
            for shard, w in zip(pool._shard_ranks, pool.workers):
                rc = w.poll()
                rows.append(Member(
                    kind="grid_worker", ident=f"g{shard}", pid=w.pid,
                    status="live" if rc is None else "killed",
                    detail=f"rc={rc}" if rc is not None else "",
                ))
            for shard in pool.degraded_ranks:
                rows.append(Member(
                    kind="grid_worker", ident=f"g{shard}", pid=None,
                    status="degraded",
                    detail="shard lost; merges are disclosed "
                           "partial sums over survivors",
                ))
            rows.append(Member(
                kind="broker", ident="exchange", pid=os.getpid(),
                status="live",
                detail=f"rounds={pool.exchange._m_rounds.value}",
            ))
        return rows

    # -- the liveness ladder -----------------------------------------------

    def _ring_watermark(self, svc) -> Optional[Tuple[int, int]]:
        chan = getattr(svc, "_channel", None)
        if chan is None:
            return None
        try:
            return chan.req_ring.watermark()
        except Exception:  # noqa: BLE001 — a torn ring reads as absent
            return None

    def probe(self) -> Dict[str, str]:
        """Classify every replica: live | killed | ring_stalled | hung
        (process mode; thread replicas report the fleet's own state).
        One call = one watermark sample — ``ring_stalled`` needs two
        probes so a backlog being DRAINED is never misread as a stall."""
        out: Dict[str, str] = {}
        states = self.fleet.replica_states()
        for rid, state in states.items():
            rep = self.fleet.replica(rid)
            if rep is None or state == "dead":
                out[rid] = "dead"
                continue
            svc = rep.service
            proc = getattr(svc, "proc", None)
            if proc is None:
                # thread replica: in-process by construction — liveness
                # IS the fleet state
                out[rid] = "live" if state == "healthy" else state
                continue
            if proc.poll() is not None:
                out[rid] = "killed"
                self._ring_marks.pop(rid, None)
                continue
            wm = self._ring_watermark(svc)
            if wm is not None:
                prev = self._ring_marks.get(rid)
                self._ring_marks[rid] = wm
                produced, consumed = wm
                if (prev is not None and produced > consumed
                        and consumed == prev[1]):
                    out[rid] = "ring_stalled"
                    continue
            try:
                svc._call("ping", timeout=self.ping_timeout_s)
                out[rid] = "live"
            except _FutureTimeout:
                out[rid] = "hung"
            except Exception:  # noqa: BLE001 — dead socket = corpse
                out[rid] = "killed"
        self.last_probe = out
        return out

    # -- repair verbs ------------------------------------------------------

    def repair(self, probe: Optional[Dict[str, str]] = None) -> List[str]:
        """Respawn every non-live replica through the existing fleet
        machinery (kill → shm rings/doorbells torn down and unlinked →
        warm-pool replace → ``respawn`` journal mark). Grid-worker and
        broker deaths repair themselves inside ``pool.contract`` (the
        degraded N−1 / re-election paths); here they are disclosed via
        :meth:`members`. Returns the action log."""
        status = probe if probe is not None else self.probe()
        actions: List[str] = []
        for rid, st in sorted(status.items()):
            if st not in _REPAIRABLE:
                continue
            t0 = time.perf_counter()
            self.fleet.kill_replica(rid, reason=f"topology:{st}")
            new_rid = self.fleet.replace(rid, reason=f"topology:{st}")
            took = time.perf_counter() - t0
            self._ring_marks.pop(rid, None)
            self._mark("respawn", replica=rid, replacement=new_rid,
                       cause=st, flight=self._flight_detail(rid) or None)
            self._m_respawns.inc()
            self._g_respawn_s.set(took)
            actions.append(f"respawn:{rid}->{new_rid}:{st}")
        if actions:
            self._mark_topology()
        return actions

    def sweep(self) -> Dict[str, object]:
        """Reclaim anything the member teardown hooks missed: leaked shm
        segments (unlinked + counted) and doorbell eventfds (closed +
        counted). Call AFTER teardown — a live topology's segments are
        supposed to exist and would be reclaimed from under it."""
        from fm_returnprediction_tpu.parallel.shm import sweep_segments
        from fm_returnprediction_tpu.serving.shm import sweep_doorbells

        leaked_segs = sweep_segments()
        leaked_fds = sweep_doorbells()
        return {"segments": leaked_segs, "fds": leaked_fds}

    # -- elasticity (the autoscaler routes through here) -------------------

    def scale_out(self, n: int = 1, reason: str = "pressure") -> List[str]:
        rids = self.fleet.scale_out(n, reason=reason)
        if rids:
            self.spec = dataclasses.replace(
                self.spec, replicas=self.spec.replicas + len(rids))
            self._mark_topology()
        return rids

    def scale_in(self, reason: str = "relief") -> Optional[str]:
        rid = self.fleet.scale_in(reason=reason)
        if rid is not None and self.spec.replicas > 1:
            self.spec = dataclasses.replace(
                self.spec, replicas=self.spec.replicas - 1)
            self._mark_topology()
        return rid

    # -- crash-restart recovery --------------------------------------------

    @classmethod
    def recover(cls, journal, *, state=None, registry_dir=None,
                panel=None, spec: Optional[TopologySpec] = None,
                **fleet_kwargs):
        """Rebuild ANY declared shape from the journal alone.

        Reads the last ``topology`` mark (falling back to the plain
        ``size=`` marks for pre-topology journals), repairs + closes out
        the crashed session (``recover_journal`` — clean replay, typed
        retriable terminals), and rebuilds the fleet through
        ``ServingFleet.recover`` with the declared replica mode and
        transport — warm-pool spawns, zero fresh compiles with a
        populated registry. A declared grid pool is rebuilt only when
        the caller supplies ``panel=(y, x, universes)`` (panels are
        data, not journal state); otherwise it is disclosed as pending
        in the returned report. Returns ``(controller, RecoveryReport)``.
        """
        from fm_returnprediction_tpu.serving.fleet import ServingFleet
        from fm_returnprediction_tpu.serving.recovery import (
            recover_journal,
        )

        jrec = recover_journal(journal)
        if spec is None:
            if jrec.last_topology is not None:
                spec = TopologySpec.from_mark(jrec.last_topology)
            else:
                spec = TopologySpec(replicas=jrec.last_size or 1)
        fleet, report = ServingFleet.recover(
            journal, registry_dir=registry_dir, state=state,
            n_replicas=spec.replicas, replica_mode=spec.replica_mode,
            transport=spec.transport, **fleet_kwargs,
        )
        pool = None
        if spec.grid_procs and panel is not None:
            from fm_returnprediction_tpu.specgrid.multiproc import (
                SpecGridWorkerPool,
            )

            y, x, universes = panel
            pool = SpecGridWorkerPool(
                spec.grid_procs, y, x, universes,
                transport=spec.grid_transport,
            )
        ctl = cls(spec, fleet=fleet, pool=pool)
        telemetry.event("topology.recovered", cat="topology",
                        replicas=spec.replicas,
                        replica_mode=spec.replica_mode,
                        grid_procs=spec.grid_procs,
                        grid_rebuilt=pool is not None)
        return ctl, report

    # -- lifecycle ---------------------------------------------------------

    def close(self, *, close_fleet: bool = True,
              close_pool: bool = True) -> None:
        if close_pool and self.pool is not None:
            self.pool.close()
        if close_fleet:
            self.fleet.close()

    def __enter__(self) -> "TopologyController":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
