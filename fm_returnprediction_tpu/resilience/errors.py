"""Typed failure taxonomy for the resilience layer.

One exception class per recovery path, so a handler can catch exactly the
failure it knows how to recover from — retry wrappers catch
``InjectedFault``/``OSError`` allowlists, the checkpoint resume path
catches ``CorruptArtifactError`` and rebuilds, the microbatcher delivers
``DispatchTimeoutError`` to the in-flight bucket's futures, and the task
engine records ``TaskTimeoutError``/``RetryExhaustedError`` in its sqlite
failure log. Nothing here imports anything — this module sits at the
bottom of the dependency graph so ``utils.cache`` and ``taskgraph.engine``
can both name these types without a cycle.
"""

from __future__ import annotations

__all__ = [
    "ResilienceError",
    "RetryExhaustedError",
    "TaskTimeoutError",
    "DispatchTimeoutError",
    "CorruptArtifactError",
    "IngestRejectedError",
    "ContractViolationError",
    "DriftDetectedError",
    "InjectedFault",
]


class ResilienceError(RuntimeError):
    """Base class for failures the resilience layer raises itself."""


class RetryExhaustedError(ResilienceError):
    """A retried call failed on every attempt; ``__cause__`` is the last
    underlying error."""


class TaskTimeoutError(ResilienceError):
    """A task action exceeded its ``timeout_s`` wall-clock budget."""


class DispatchTimeoutError(ResilienceError):
    """A serving bucket dispatch exceeded the executor's watchdog budget.

    Delivered to the in-flight batch's futures so the microbatcher keeps
    draining instead of hanging behind a stalled runner."""


class CorruptArtifactError(ResilienceError):
    """A persisted artifact failed its content checksum (or is structurally
    unreadable). The resume path catches this and REBUILDS the artifact
    instead of crashing with a cryptic numpy/zipfile error."""


class IngestRejectedError(ResilienceError):
    """An ingest month failed validation (NaN cross-section, shape
    mismatch, merge divergence beyond tolerance). The serving front-end
    quarantines the month and keeps quoting from the last-known-good
    state."""


class ContractViolationError(ResilienceError):
    """A fail-severity data-integrity contract was breached at a stage
    boundary (``guard.contracts``): the stage's product is structurally or
    numerically wrong (duplicated keys, non-monotone calendar, values in
    overflow territory), so downstream estimates cannot be trusted. The
    message carries every named violation."""


class DriftDetectedError(ContractViolationError):
    """A persisted artifact moved beyond its tolerance band relative to the
    previous run's audit manifest (``guard.drift``). The trusted manifest
    is left unmodified so the regression remains reproducible against it."""


class InjectedFault(OSError):
    """The default exception a ``FaultPlan`` raises at a fault site.

    Subclasses ``OSError`` so injected faults exercise the same handler
    paths a real transient IO error would (retry allowlists include
    ``OSError`` by default).
    """
