"""Typed failure taxonomy for the resilience layer.

One exception class per recovery path, so a handler can catch exactly the
failure it knows how to recover from — retry wrappers catch
``InjectedFault``/``OSError`` allowlists, the checkpoint resume path
catches ``CorruptArtifactError`` and rebuilds, the microbatcher delivers
``DispatchTimeoutError`` to the in-flight bucket's futures, and the task
engine records ``TaskTimeoutError``/``RetryExhaustedError`` in its sqlite
failure log. Nothing here imports anything — this module sits at the
bottom of the dependency graph so ``utils.cache`` and ``taskgraph.engine``
can both name these types without a cycle.
"""

from __future__ import annotations

__all__ = [
    "ResilienceError",
    "RetryExhaustedError",
    "TaskTimeoutError",
    "DispatchTimeoutError",
    "CorruptArtifactError",
    "IngestRejectedError",
    "ContractViolationError",
    "DriftDetectedError",
    "ServiceOverloadError",
    "ReplicaDeadError",
    "RecoveredInFlightError",
    "StateRolloverError",
    "DegradedWorldError",
    "InjectedFault",
]


class ResilienceError(RuntimeError):
    """Base class for failures the resilience layer raises itself."""


class RetryExhaustedError(ResilienceError):
    """A retried call failed on every attempt; ``__cause__`` is the last
    underlying error."""


class TaskTimeoutError(ResilienceError):
    """A task action exceeded its ``timeout_s`` wall-clock budget."""


class DispatchTimeoutError(ResilienceError):
    """A serving bucket dispatch exceeded the executor's watchdog budget.

    Delivered to the in-flight batch's futures so the microbatcher keeps
    draining instead of hanging behind a stalled runner."""


class CorruptArtifactError(ResilienceError):
    """A persisted artifact failed its content checksum (or is structurally
    unreadable). The resume path catches this and REBUILDS the artifact
    instead of crashing with a cryptic numpy/zipfile error."""


class IngestRejectedError(ResilienceError):
    """An ingest month failed validation (NaN cross-section, shape
    mismatch, merge divergence beyond tolerance). The serving front-end
    quarantines the month and keeps quoting from the last-known-good
    state."""


class ContractViolationError(ResilienceError):
    """A fail-severity data-integrity contract was breached at a stage
    boundary (``guard.contracts``): the stage's product is structurally or
    numerically wrong (duplicated keys, non-monotone calendar, values in
    overflow territory), so downstream estimates cannot be trusted. The
    message carries every named violation."""


class DriftDetectedError(ContractViolationError):
    """A persisted artifact moved beyond its tolerance band relative to the
    previous run's audit manifest (``guard.drift``). The trusted manifest
    is left unmodified so the regression remains reproducible against it."""


class ServiceOverloadError(ResilienceError):
    """The serving fleet shed this request at admission (429-style).

    RETRIABLE by contract: the request was refused before any replica saw
    it, so a resubmit can never double-serve. Carries the shed decision's
    evidence so callers and SLO burn attribution need not re-derive it:

    - ``retry_after_s`` — the admission controller's hint for when capacity
      should exist again (token-bucket refill time, or the estimated queue
      drain time);
    - ``reason``        — ``"token_bucket"`` | ``"queue_occupancy"`` |
      ``"replica_backpressure"`` | ``"no_healthy_replicas"``;
    - ``queue_depth`` / ``queue_ceiling`` — aggregate pending requests vs
      the fleet's total queue capacity at decision time (None when the
      reason carries no queue evidence).
    """

    def __init__(self, message: str, *, retry_after_s: float = 0.0,
                 reason: str = "overload", queue_depth=None,
                 queue_ceiling=None):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.reason = str(reason)
        self.queue_depth = queue_depth
        self.queue_ceiling = queue_ceiling

    @property
    def occupancy(self):
        """Queue fill fraction at decision time (None without evidence)."""
        if not self.queue_ceiling:
            return None
        return self.queue_depth / self.queue_ceiling


class ReplicaDeadError(ResilienceError):
    """A serving replica died (killed, crashed, or failed its health
    probe) with this request still queued on it. The fleet front tier
    catches this and REQUEUES the request on a healthy replica; it only
    reaches a caller when every requeue attempt is exhausted."""


class RecoveredInFlightError(ResilienceError):
    """A request was admitted but still in flight when the fleet process
    died; crash-restart recovery (``serving.recovery``) closed it out
    with this outcome in the journal. RETRIABLE by contract: quoting is
    read-only and the original future died with the process, so a
    resubmit can never double-serve — the same stance as
    :class:`ServiceOverloadError`, one failure mode harder."""


class StateRolloverError(ResilienceError):
    """A fleet-wide versioned state rollover aborted during the PREPARE
    phase (validation failure, poisoned candidate state, or a warm-up
    error on some replica). By protocol nothing has flipped yet — every
    replica is still serving the previous version — so the fleet remains
    consistent; the error names the replica and cause."""


class DegradedWorldError(ResilienceError):
    """A grid worker died and the run is configured exact-world-only
    (``FMRP_TOPO_DEGRADED_GRID=0``): the pool REFUSES the disclosed N−1
    merge rather than silently serving a partial sum. Carries the dead
    shard ranks so the operator knows exactly which slice is missing;
    with the knob at its default the pool degrades (exactly, by Gram
    additivity over survivors) and discloses instead of raising."""

    def __init__(self, message: str, *, dead_ranks=()):
        super().__init__(message)
        self.dead_ranks = tuple(dead_ranks)


class InjectedFault(OSError):
    """The default exception a ``FaultPlan`` raises at a fault site.

    Subclasses ``OSError`` so injected faults exercise the same handler
    paths a real transient IO error would (retry allowlists include
    ``OSError`` by default).
    """
