"""Fault-tolerance layer: retries, timeouts, checkpoint-resume, degraded-
mode serving, and deterministic fault injection.

The production-scale stance (ROADMAP north star): a transient failure
anywhere — a flaky WRDS pull, a torn ``.npz``, a stalled serving runner,
one failed taskgraph node — costs a retry, a quarantine, or one stage of
recompute, never the whole run. Four pieces:

- :mod:`.retry`     — ``RetryPolicy`` + ``call_with_retry`` (exponential
  backoff, deterministic jitter, exception allowlist); applied to the
  WRDS pull and per-``Task`` actions.
- :mod:`.checkpoint`— ``StageCheckpointer``: fingerprint-keyed,
  checksum-verified per-stage artifacts so ``run_pipeline`` resumes at
  the last completed stage.
- :mod:`.faults`    — ``FaultPlan``/``fault_site``: deterministic chaos
  injection at named production sites (free when inactive).
- :mod:`.errors`    — the typed failure taxonomy the recovery paths
  dispatch on.

Degraded-mode serving lives with the service itself
(``serving.service.ERService.ingest_month``); the engine-side retry/
timeout/keep-going semantics live in ``taskgraph.engine``.
"""

from fm_returnprediction_tpu.resilience.errors import (
    ContractViolationError,
    CorruptArtifactError,
    DegradedWorldError,
    DispatchTimeoutError,
    DriftDetectedError,
    IngestRejectedError,
    InjectedFault,
    ResilienceError,
    RetryExhaustedError,
    TaskTimeoutError,
)
from fm_returnprediction_tpu.resilience.faults import (
    FaultPlan,
    FaultSpec,
    chaos_env,
    fault_site,
    install_plan_from_env,
    truncate_file,
)
from fm_returnprediction_tpu.resilience.retry import (
    RetryPolicy,
    call_with_retry,
    retrying,
)
from fm_returnprediction_tpu.resilience.checkpoint import StageCheckpointer

__all__ = [
    "ResilienceError",
    "RetryExhaustedError",
    "TaskTimeoutError",
    "DispatchTimeoutError",
    "CorruptArtifactError",
    "IngestRejectedError",
    "ContractViolationError",
    "DriftDetectedError",
    "DegradedWorldError",
    "InjectedFault",
    "FaultPlan",
    "FaultSpec",
    "fault_site",
    "chaos_env",
    "install_plan_from_env",
    "truncate_file",
    "RetryPolicy",
    "call_with_retry",
    "retrying",
    "StageCheckpointer",
]
