"""Per-stage pipeline checkpoints — resume a crashed run, don't refit it.

The prepared-inputs checkpoint (``data.prepared``) already covers the host
ingest; this covers the REPORTING stages: ``run_pipeline`` registers each
completed stage artifact (Table 1, Table 2, decile table, serving state)
here, and a rerun after a crash loads the completed stages and recomputes
only from the failure point on. At real shape each FM sweep stage is tens
of seconds of device compute — a crash in ``serving_state`` must not
re-pay ``table_2``.

Contract:

- One directory per run family, keyed by a FINGERPRINT (panel identity +
  raw-cache fingerprint + flags). A mismatched fingerprint invalidates
  every recorded stage — a checkpoint can never leak across datasets.
- Every artifact is written atomically (tmp + ``os.replace``) and recorded
  in the manifest with its file sha256. Load verifies the hash; any
  mismatch or unreadable artifact degrades to "recompute this stage" with
  a warning — checkpoints are an accelerant, never a correctness gate
  (same stance as ``data.prepared``).
- The manifest itself is written last and atomically, so a crash mid-save
  leaves the previous consistent manifest, never a half-recorded one.
"""

from __future__ import annotations

import json
import os
import warnings
from pathlib import Path
from typing import Callable, Optional

from fm_returnprediction_tpu import telemetry
from fm_returnprediction_tpu.resilience.errors import CorruptArtifactError

__all__ = ["StageCheckpointer"]

_MANIFEST = "manifest.json"


def _checkpoint_counter(outcome: str):
    return telemetry.registry().counter(
        "fmrp_checkpoint_total",
        help="stage checkpoint-resume outcomes by kind",
        outcome=outcome,
    )


def _file_sha256(path: Path) -> str:
    # the ONE streaming file-hash definition (shared with the prepared
    # checkpoint and the registry planes)
    from fm_returnprediction_tpu.registry.integrity import file_sha256

    return file_sha256(path)


class StageCheckpointer:
    """Fingerprint-keyed, checksum-verified stage artifact store."""

    def __init__(self, checkpoint_dir, fingerprint: str):
        self.dir = Path(checkpoint_dir)
        self.fingerprint = str(fingerprint)
        self.dir.mkdir(parents=True, exist_ok=True)
        self._stages = {}
        manifest = self.dir / _MANIFEST
        try:
            meta = json.loads(manifest.read_text())
            if meta.get("fingerprint") == self.fingerprint:
                self._stages = dict(meta.get("stages", {}))
        except (OSError, ValueError):
            pass  # absent or torn manifest → start empty

    # -- manifest ----------------------------------------------------------

    def _write_manifest(self) -> None:
        tmp = self.dir / f".{_MANIFEST}.tmp-{os.getpid()}"
        tmp.write_text(json.dumps(
            {"fingerprint": self.fingerprint, "stages": self._stages},
            indent=2, sort_keys=True,
        ))
        os.replace(tmp, self.dir / _MANIFEST)

    def completed(self, name: str) -> bool:
        """Cheap probe: stage recorded and its file present (content is
        verified at load time)."""
        rec = self._stages.get(name)
        return rec is not None and (self.dir / rec["file"]).exists()

    def stages(self) -> tuple:
        return tuple(sorted(self._stages))

    # -- generic stage -----------------------------------------------------

    def stage(
        self,
        name: str,
        compute: Callable[[], object],
        *,
        saver: Callable[[object, Path], None],
        loader: Callable[[Path], object],
        suffix: str,
    ):
        """Load stage ``name`` if recorded and intact, else compute, persist
        atomically, record, and return. The compute path runs OUTSIDE any
        lock or transaction — a crash inside it leaves prior stages
        recorded and this one absent, which is exactly resume-at-last-
        completed-stage."""
        got = self._load(name, loader)
        if got is not None:
            return got
        obj = compute()
        try:
            self._save(name, obj, saver, suffix)
        except OSError as exc:  # read-only dir, disk full: keep the result
            warnings.warn(
                f"stage checkpoint {name!r} not written: {exc!r}",
                stacklevel=2,
            )
        return obj

    def _load(self, name: str, loader: Callable[[Path], object]):
        rec = self._stages.get(name)
        if rec is None:
            _checkpoint_counter("miss").inc()
            telemetry.event("checkpoint.miss", cat="resilience", stage=name)
            return None
        path = self.dir / rec["file"]
        try:
            if not path.exists():
                raise CorruptArtifactError(f"checkpoint file {path} missing")
            if _file_sha256(path) != rec["sha256"]:
                raise CorruptArtifactError(
                    f"checkpoint {name!r} failed its content hash"
                )
            got = loader(path)
            _checkpoint_counter("hit").inc()
            telemetry.event("checkpoint.hit", cat="resilience", stage=name)
            return got
        except Exception as exc:  # noqa: BLE001 — any unreadable artifact rebuilds
            _checkpoint_counter("corrupt").inc()
            telemetry.event(
                "checkpoint.corrupt", cat="resilience",
                stage=name, error=repr(exc)[:200],
            )
            warnings.warn(
                f"stage checkpoint {name!r} unreadable, recomputing: {exc!r}",
                stacklevel=3,
            )
            # drop the record so completed() stops advertising it
            self._stages.pop(name, None)
            try:
                self._write_manifest()
            except OSError:
                pass
            return None

    def _save(self, name, obj, saver, suffix) -> None:
        final = self.dir / f"{name}{suffix}"
        tmp = self.dir / f".{name}.tmp-{os.getpid()}{suffix}"
        try:
            saver(obj, tmp)
            os.replace(tmp, final)
        finally:
            tmp.unlink(missing_ok=True)
        self._stages[name] = {
            "file": final.name, "sha256": _file_sha256(final)
        }
        self._write_manifest()
        _checkpoint_counter("save").inc()
        telemetry.event("checkpoint.save", cat="resilience", stage=name)

    # -- pandas convenience ------------------------------------------------

    def frame(self, name: str, compute: Callable[[], object]):
        """DataFrame stage: pickle on disk (tables carry MultiIndex shapes
        parquet cannot), integrity guarded by the manifest's file sha256 —
        the same no-silent-corruption contract as the npz bundles."""
        import pandas as pd

        return self.stage(
            name, compute,
            saver=lambda df, path: pd.to_pickle(df, path),
            loader=pd.read_pickle,
            suffix=".pkl",
        )
