"""Deterministic fault injection — the chaos harness behind the chaos tests.

Production code is instrumented with NAMED FAULT SITES::

    from fm_returnprediction_tpu.resilience.faults import fault_site
    ...
    fault_site("wrds.query")                 # may raise / stall
    rows = fault_site("serving.ingest", payload=rows)   # may poison
    fault_site("cache.save_array_bundle", path=written) # may corrupt

With no :class:`FaultPlan` installed, ``fault_site`` is ONE module-global
read and an immediate return — no locks, no clocks, no randomness — so the
hooks are free on the serving hot path (pinned by the bench's p50 numbers).

A test (or the bench's resilience section) installs a plan::

    with FaultPlan({"wrds.query": FaultSpec(times=2, exc=ConnectionError)}):
        pull_CRSP_stock(...)        # first two connection attempts fail

Determinism: a spec triggers by CALL COUNT (``skip`` then ``times``), or by
a seeded counter-keyed hash when ``probability`` is set — never by wall
clock or global RNG state, so a failing chaos test replays exactly. The
plan records every site visit (``calls``) and every triggered fault
(``fired``) for assertions.

CROSS-PROCESS propagation: the declarative subset of a plan (counts,
probability, delay, sigkill, exc-by-type-name, corrupt=True — everything
except live callables) serializes into ``FMRP_CHAOS_PLAN`` /
``FMRP_CHAOS_SEED`` env vars via :func:`chaos_env`; every process spawner
(``serving.replica_proc``, ``parallel.distributed.worker_env``) merges
these into the child env, and each child entrypoint calls
:func:`install_plan_from_env` before serving, so ``fault_site`` fires
INSIDE replica / grid / broker processes with the same count-gated
determinism. ``FaultSpec.proc`` targets one member of a spawned pool: a
spec only installs in the child whose ``FMRP_DIST_PROC_ID`` /
``FMRP_PROC_INDEX`` matches, so a pool-wide env kills exactly one rank.
"""

from __future__ import annotations

import builtins
import dataclasses
import hashlib
import json
import os
import signal
import threading
import time
from collections import Counter
from pathlib import Path
from typing import Callable, Dict, Mapping, Optional, Union

from fm_returnprediction_tpu.resilience.errors import InjectedFault

__all__ = [
    "FaultSpec",
    "FaultPlan",
    "fault_site",
    "chaos_env",
    "install_plan_from_env",
    "truncate_file",
    "poison_nan_flood",
    "poison_scale_spike",
    "corrupt_panel_duplicate_id",
    "corrupt_panel_permute_firms",
    "corrupt_panel_stale_month",
    "corrupt_panel_scale_spike",
    "fleet_kill_routed",
    "fleet_stall_replica",
    "fleet_trigger_staged_rollover",
    "fleet_hard_crash",
    "poison_serving_state_nan",
    "tear_journal_tail",
]

# The installed plan. Plain module global on purpose: the inactive-path
# cost must be one read. Installation is guarded by _INSTALL_LOCK; per-site
# counters are guarded by the plan's own lock.
_ACTIVE: Optional["FaultPlan"] = None
_INSTALL_LOCK = threading.Lock()


def truncate_file(path: Union[str, Path]) -> None:
    """Default corruption: keep the first half of the file — the torn-write
    shape a crash mid-``write()`` leaves behind."""
    path = Path(path)
    data = path.read_bytes()
    path.write_bytes(data[: max(len(data) // 2, 1)])


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """What happens when a named site triggers.

    times       : trigger on this many calls, then heal (-1 = every call).
    skip        : let this many calls through untouched first.
    probability : instead of count-gating, trigger each eligible call with
                  this probability, decided by a seeded hash of
                  (plan seed, site, call number) — deterministic replay.
    exc         : exception to raise — a type, an instance, or a zero-arg
                  factory. ``None`` with no other effect raises
                  :class:`InjectedFault`.
    delay_s     : stall this long BEFORE any other effect (slow/stalled
                  runner; a watchdogged caller times out mid-stall).
    corrupt     : called with the site's ``path`` operand (artifact
                  corruption; ``True`` selects :func:`truncate_file`).
    mutate      : called with the site's ``payload`` operand, returns the
                  poisoned payload (e.g. NaN rows into an ingest).
    sigkill     : SIGKILL the CURRENT process at the site — the real
                  no-cleanup death (no finally blocks, no atexit). Only
                  meaningful inside a spawned child (via env propagation);
                  the site's placement picks the torn state left behind.
    proc        : restrict env-propagated installation to the child whose
                  process identity (``FMRP_DIST_PROC_ID`` for grid ranks,
                  ``FMRP_PROC_INDEX`` for process replicas) equals this
                  string — one member of a pool-wide env dies, the rest
                  never see the spec.
    """

    times: int = 1
    skip: int = 0
    probability: Optional[float] = None
    exc: Union[None, BaseException, type, Callable[[], BaseException]] = None
    delay_s: float = 0.0
    corrupt: Union[None, bool, Callable[[Path], None]] = None
    mutate: Optional[Callable] = None
    sigkill: bool = False
    proc: Optional[str] = None

    def _make_exc(self, site: str) -> BaseException:
        if self.exc is None:
            return InjectedFault(f"injected fault at {site!r}")
        if isinstance(self.exc, BaseException):
            return self.exc
        made = self.exc()  # type or factory
        if not isinstance(made, BaseException):
            raise TypeError(f"FaultSpec.exc for {site!r} produced {made!r}")
        return made


class FaultPlan:
    """A set of site → :class:`FaultSpec` rules, installed as a context.

    Plans nest: entering a plan shadows the previously installed one and
    ``__exit__`` restores it. Counters (``calls`` — every visit to an
    instrumented site, ``fired`` — visits that triggered) live on the plan,
    so a test asserts exactly what its chaos did.
    """

    def __init__(self, specs: Dict[str, FaultSpec], seed: int = 0):
        for site, spec in specs.items():
            if not isinstance(spec, FaultSpec):
                raise TypeError(f"spec for {site!r} must be a FaultSpec")
        self.specs = dict(specs)
        self.seed = int(seed)
        self.calls: Counter = Counter()
        self.fired: Counter = Counter()
        self._lock = threading.Lock()
        self._prev: Optional[FaultPlan] = None

    # -- installation ------------------------------------------------------

    def __enter__(self) -> "FaultPlan":
        global _ACTIVE
        with _INSTALL_LOCK:
            self._prev = _ACTIVE
            _ACTIVE = self
        return self

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        with _INSTALL_LOCK:
            _ACTIVE = self._prev
            self._prev = None

    # -- trigger decision --------------------------------------------------

    def _should_fire(self, spec: FaultSpec, call_no: int, site: str) -> bool:
        """``call_no`` is 1-based. Count-gated unless ``probability`` is
        set; either way a pure function of (plan, site, call_no)."""
        if call_no <= spec.skip:
            return False
        if spec.probability is not None:
            digest = hashlib.sha256(
                f"{self.seed}|{site}|{call_no}".encode()
            ).digest()
            frac = int.from_bytes(digest[:8], "big") / 2**64
            return frac < spec.probability
        if spec.times < 0:
            return True
        return call_no - spec.skip <= spec.times

    def _apply(self, site: str, payload, path):
        spec = self.specs.get(site)
        with self._lock:
            # count every visit, matched or not, so tests can assert a site
            # was exercised even when its spec belongs to another plan run
            self.calls[site] += 1
            call_no = self.calls[site]
            if spec is None or not self._should_fire(spec, call_no, site):
                return payload
            self.fired[site] += 1
        # effects OUTSIDE the lock: a delay must not serialize other sites
        if spec.delay_s:
            time.sleep(spec.delay_s)
        if spec.sigkill:
            os.kill(os.getpid(), signal.SIGKILL)  # no cleanup, by design
        if spec.corrupt is not None and path is not None:
            corruptor = truncate_file if spec.corrupt is True else spec.corrupt
            corruptor(Path(path))
        if spec.mutate is not None:
            payload = spec.mutate(payload)
            if spec.exc is None:
                return payload  # a pure poisoning site returns, not raises
        if spec.exc is not None or (spec.mutate is None and spec.corrupt is None
                                    and not spec.delay_s):
            raise spec._make_exc(site)
        return payload


# -- cross-process propagation ----------------------------------------------
#
# A FaultPlan is a parent-process object; spawned children (process
# replicas, grid workers, broker hosts) import a FRESH module with no plan
# installed. The pair below closes that gap: ``chaos_env()`` serializes the
# declarative subset of the active plan into two env vars, every spawner
# merges them into its child env, and each child entrypoint calls
# ``install_plan_from_env()`` first thing — so the SAME count-gated
# determinism holds inside the child. Live callables (mutate, custom
# corruptors, exception factories) cannot ride env and stay parent-only;
# a spec that carries one is silently skipped by serialization, never
# half-shipped.

_ENV_PLAN = "FMRP_CHAOS_PLAN"
_ENV_SEED = "FMRP_CHAOS_SEED"


def _spec_to_wire(spec: FaultSpec) -> Optional[dict]:
    """The env-serializable subset of one spec, or None when it cannot
    ride (live callables don't serialize; such specs stay parent-only)."""
    if spec.mutate is not None:
        return None
    if spec.corrupt is not None and spec.corrupt is not True:
        return None
    exc_name: Optional[str] = None
    if spec.exc is not None:
        if not (isinstance(spec.exc, type)
                and issubclass(spec.exc, BaseException)):
            return None
        exc_name = spec.exc.__name__
    return {
        "times": spec.times,
        "skip": spec.skip,
        "probability": spec.probability,
        "delay_s": spec.delay_s,
        "corrupt": spec.corrupt is True,
        "sigkill": spec.sigkill,
        "proc": spec.proc,
        "exc": exc_name,
    }


def _resolve_exc(name: str) -> type:
    """Exception type by name: builtins first (ConnectionError, OSError,
    ...), then the resilience taxonomy (InjectedFault, ReplicaDeadError,
    ...)."""
    got = getattr(builtins, name, None)
    if isinstance(got, type) and issubclass(got, BaseException):
        return got
    from fm_returnprediction_tpu.resilience import errors as _errors

    got = getattr(_errors, name, None)
    if isinstance(got, type) and issubclass(got, BaseException):
        return got
    raise ValueError(f"unknown exception type in chaos env: {name!r}")


def chaos_env(plan: Optional[FaultPlan] = None) -> Dict[str, str]:
    """Serialize ``plan`` (default: the active plan) into the env-var pair
    spawners merge into a child env. Empty dict when no plan is active or
    nothing in it serializes — so every spawner can
    ``env.update(chaos_env())`` unconditionally at zero cost."""
    plan = _ACTIVE if plan is None else plan
    if plan is None:
        return {}
    wire = {
        site: w
        for site, spec in plan.specs.items()
        if (w := _spec_to_wire(spec)) is not None
    }
    if not wire:
        return {}
    return {
        _ENV_PLAN: json.dumps(wire, sort_keys=True),
        _ENV_SEED: str(plan.seed),
    }


def install_plan_from_env(
    environ: Optional[Mapping[str, str]] = None,
) -> Optional[FaultPlan]:
    """Child-process entrypoint hook: rebuild and install (for the process
    lifetime) the plan the parent serialized with :func:`chaos_env`.

    Specs carrying ``proc`` are filtered against THIS process's identity
    (``FMRP_DIST_PROC_ID``, then ``FMRP_PROC_INDEX``); non-matching specs
    are dropped, so a pool-wide env targets exactly one rank. Returns the
    installed plan, or None when the env carries nothing for this process.
    The plan is deliberately never exited — chaos lasts until the child
    dies, which is the contract the campaign tests assert against.
    """
    env = os.environ if environ is None else environ
    raw = env.get(_ENV_PLAN, "").strip()
    if not raw:
        return None
    seed = int(env.get(_ENV_SEED, "0") or "0")
    me = env.get("FMRP_DIST_PROC_ID") or env.get("FMRP_PROC_INDEX")
    specs: Dict[str, FaultSpec] = {}
    for site, w in json.loads(raw).items():
        if w.get("proc") is not None and w["proc"] != me:
            continue
        specs[site] = FaultSpec(
            times=int(w.get("times", 1)),
            skip=int(w.get("skip", 0)),
            probability=w.get("probability"),
            exc=_resolve_exc(w["exc"]) if w.get("exc") else None,
            delay_s=float(w.get("delay_s", 0.0)),
            corrupt=True if w.get("corrupt") else None,
            sigkill=bool(w.get("sigkill", False)),
            proc=w.get("proc"),
        )
    if not specs:
        return None
    plan = FaultPlan(specs, seed=seed)
    plan.__enter__()  # process-lifetime install; exited only by death
    return plan


# -- data-corruption payload mutators --------------------------------------
#
# The chaos suite's second fault class: sites that inject BAD DATA rather
# than exceptions — the silent failures the guard layer (``guard.contracts``
# / ``guard.checks``) exists to catch. Each mutator is deterministic (no
# global RNG) and returns a NEW object, so a replayed plan corrupts
# identically. Used as ``FaultSpec(mutate=...)`` against the payload sites
# ``"pipeline.panel"`` (a DensePanel) and ``"serving.ingest"`` (a
# ``(y, x, mask)`` triple); each is asserted caught at its DECLARED
# severity in ``tests/test_chaos.py``.


def poison_nan_flood(payload):
    """(y, x, mask) → every predictor NaN while the mask claims full
    presence — the broken-upstream-join shape. Declared catch:
    ``cs.nan_flood`` at QUARANTINE."""
    import numpy as np

    y, x, mask = payload
    x = np.asarray(x)
    return (
        np.full(np.asarray(y).shape, np.nan, dtype=x.dtype),
        np.full(x.shape, np.nan, dtype=x.dtype),
        np.ones(np.asarray(mask).shape, dtype=bool),
    )


def poison_scale_spike(column: int = 0, scale: float = 1e20):
    """Mutator factory: (y, x, mask) with one predictor column scaled into
    f32-Gram-overflow territory (a unit bug upstream — dollars where
    log-dollars belong). Declared catch: ``cs.value_bounds`` at
    QUARANTINE."""

    def mutate(payload):
        import numpy as np

        y, x, mask = payload
        x = np.array(x, copy=True)
        x[..., column] = x[..., column] * x.dtype.type(scale)
        return y, x, mask

    return mutate


def _panel_replace(panel, **overrides):
    import dataclasses as _dc

    return _dc.replace(panel, **overrides)


def corrupt_panel_duplicate_id(panel):
    """A duplicated permno in the firm vocabulary (an upstream dedup
    regression: one firm's rows land in two slots). Declared catch:
    ``panel.key_unique`` at FAIL."""
    import numpy as np

    ids = np.array(np.asarray(panel.ids), copy=True)
    if len(ids) > 1:
        ids[-1] = ids[0]
    return _panel_replace(panel, ids=ids)


def corrupt_panel_permute_firms(panel, seed: int = 0):
    """The firm axis coherently permuted (ids, values and mask together —
    a shuffled vocabulary upstream). No statistic moves under a coherent
    relabeling, but the sorted-vocabulary convention positional consumers
    rely on is broken. Declared catch: ``panel.ids_sorted`` at WARN."""
    import numpy as np

    n = len(panel.ids)
    perm = np.random.default_rng(seed).permutation(n)
    if n > 1 and (perm == np.arange(n)).all():  # pragma: no cover - seed-dependent
        perm = np.roll(perm, 1)
    return _panel_replace(
        panel,
        ids=np.asarray(panel.ids)[perm],
        values=np.asarray(panel.values)[:, perm, :],
        mask=np.asarray(panel.mask)[:, perm],
    )


def corrupt_panel_stale_month(panel):
    """The last calendar entry overwritten with the previous month's stamp
    (a stuck feed re-labeling stale data). Declared catch:
    ``panel.calendar_monotone`` at FAIL."""
    import numpy as np

    months = np.array(
        np.asarray(panel.months).astype("datetime64[ns]"), copy=True
    )
    if len(months) > 1:
        months[-1] = months[-2]
    return _panel_replace(panel, months=months)


def corrupt_panel_scale_spike(panel, column: int = -1, scale: float = 1e20):
    """One characteristic column scaled past the guard's value bound —
    magnitudes that overflow an f32 Gram contraction. Declared catch:
    ``panel.value_bounds`` at FAIL (before the numerics silently
    saturate; the in-program overflow sentinels are the second fence)."""
    import numpy as np

    values = np.array(np.asarray(panel.values), copy=True)
    values[:, :, column] = values[:, :, column] * values.dtype.type(scale)
    return _panel_replace(panel, values=values)


# -- fleet fault mutators ----------------------------------------------------
#
# The serving fleet's fault sites (``serving.fleet``) carry LIVE OBJECTS as
# payloads — the fleet itself, or (fleet, routed replica id) — so a chaos
# plan can act on fleet topology at a deterministic point in the request
# stream (the spec's skip/times counters pick WHICH request). Each mutator
# returns the payload unchanged: these sites poison the WORLD, not the data.
#
#   fleet.replica_kill    — visited after a request lands in flight on its
#                           routed replica; ``fleet_kill_routed`` kills that
#                           replica mid-flight (the requeue path under test)
#   fleet.replica_stall   — visited at each replica dispatch with its id;
#                           ``fleet_stall_replica`` stalls ONE replica so the
#                           dispatch watchdog + supervisor see a stall
#   fleet.swap_mid_flight — visited inside the admitted-submit path;
#                           ``fleet_trigger_staged_rollover`` fires the
#                           staged version swap between two specific requests
#   fleet.poison_state    — visited per replica during rollover PREPARE;
#                           ``poison_serving_state_nan`` corrupts the
#                           candidate so validation must abort with 0 flips
#   fleet.hard_crash      — visited inside the admitted-submit path;
#                           ``fleet_hard_crash`` abandons the whole fleet
#                           as a process death would (no drain, no journal
#                           terminals) — the crash-restart recovery path
#   fleet.journal_torn_tail — visited with the journal PATH as the file
#                           handle drops during a hard crash;
#                           ``tear_journal_tail`` (corrupt=) cuts the
#                           final line mid-write, the torn-WAL shape
#                           recovery must repair


def fleet_kill_routed(rid: Optional[str] = None):
    """Mutator factory for ``fleet.replica_kill``: kill the replica the
    triggering request was just routed to (payload ``(fleet, routed_rid)``)
    — or only when it is ``rid``, for targeted kills."""

    def mutate(payload):
        fleet, routed = payload
        if rid is None or routed == rid:
            fleet.kill_replica(routed, reason="chaos: fleet.replica_kill")
        return payload

    return mutate


def fleet_stall_replica(rid: str, delay_s: float):
    """Mutator factory for ``fleet.replica_stall``: stall exactly one
    replica's dispatches (payload is the dispatching replica's id) — the
    shape a wedged device runner presents to the PR-2 watchdog and the
    supervisor's timeout-rate probe."""

    def mutate(payload):
        if payload == rid:
            time.sleep(delay_s)
        return payload

    return mutate


def fleet_trigger_staged_rollover(payload):
    """Mutator for ``fleet.swap_mid_flight``: fire the fleet's staged
    state rollover NOW, from inside the submit path — the swap window
    lands deterministically between two known requests."""
    payload.trigger_staged_rollover()
    return payload


def fleet_hard_crash(payload):
    """Mutator for ``fleet.hard_crash``: abandon the fleet mid-load the
    way a process death would (payload is the fleet) — no drain, no
    journal terminals; the spec's skip/times counters pick exactly which
    admitted request the crash lands between. ``ServingFleet.recover``
    is the path under test."""
    payload.hard_crash()
    return payload


def tear_journal_tail(path: Union[str, Path]) -> None:
    """Corruptor for ``fleet.journal_torn_tail``: cut the journal's FINAL
    line in half — the torn-write shape a crash mid-``append`` leaves in
    a WAL (contrast :func:`truncate_file`, which halves the whole file).
    Recovery must truncate exactly this line and nothing else."""
    path = Path(path)
    data = path.read_bytes().rstrip(b"\n")
    if not data:
        return
    nl = data.rfind(b"\n")
    last = data[nl + 1:]
    keep = data[: nl + 1] + last[: max(len(last) // 2, 1)]
    path.write_bytes(keep)


def poison_serving_state_nan(state):
    """A rollover candidate whose every lagged coefficient is NaN — the
    poisoned-refit shape. Declared catch: the fleet's candidate
    validation rejects it during PREPARE (``StateRolloverError``, zero
    replicas flipped)."""
    import dataclasses as _dc

    import numpy as np

    return _dc.replace(
        state,
        slopes_bar=np.full_like(np.asarray(state.slopes_bar), np.nan),
        intercept_bar=np.full_like(np.asarray(state.intercept_bar), np.nan),
    )


def fault_site(site: str, payload=None, path=None):
    """The production-side hook. Returns ``payload`` (possibly poisoned by
    the active plan); may raise or stall per the plan's spec. With no plan
    installed this is one global read — free on hot paths."""
    plan = _ACTIVE
    if plan is None:
        return payload
    return plan._apply(site, payload, path)
