"""Retry with exponential backoff and deterministic jitter.

The one retry implementation for every layer: the WRDS network pull
(``data.wrds_pull._wrds_query``), task-graph actions (``Task.retries``),
and anything the bench or a caller wraps ad hoc. Policy decisions live in
a frozen :class:`RetryPolicy`; the loop lives in :func:`call_with_retry`.

Determinism: jitter comes from a sha256 of ``(seed, label, attempt)`` —
not the global RNG, not the clock — so two runs of the same policy produce
the same delay schedule and a chaos test can assert exact behavior. The
``sleep`` callable is injectable so tests pay zero wall-clock.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Callable, Optional, Tuple, Type

from fm_returnprediction_tpu import telemetry
from fm_returnprediction_tpu.resilience.errors import RetryExhaustedError

__all__ = ["RetryPolicy", "call_with_retry", "retrying"]


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How to retry: attempt budget, backoff curve, exception allowlist.

    max_attempts : total tries (1 = no retry).
    backoff_s    : delay before the FIRST retry; grows by ``multiplier``
                   each further retry, capped at ``max_backoff_s``.
    jitter       : ± fraction applied to each delay, deterministically
                   derived from ``(seed, label, attempt)`` — spreads
                   concurrent retriers without wall-clock randomness.
    retry_on     : exception types worth retrying; anything else
                   propagates immediately (a shape error will not fix
                   itself on attempt 3).
    """

    max_attempts: int = 3
    backoff_s: float = 0.1
    multiplier: float = 2.0
    max_backoff_s: float = 30.0
    jitter: float = 0.1
    retry_on: Tuple[Type[BaseException], ...] = (OSError, ConnectionError, TimeoutError)
    seed: int = 0

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")

    def delay_s(self, attempt: int, label: str = "") -> float:
        """Delay before retry number ``attempt`` (1-based). Pure function
        of (policy, label, attempt)."""
        base = min(
            self.backoff_s * self.multiplier ** (attempt - 1),
            self.max_backoff_s,
        )
        if not self.jitter or not base:
            return base
        digest = hashlib.sha256(
            f"{self.seed}|{label}|{attempt}".encode()
        ).digest()
        frac = int.from_bytes(digest[:8], "big") / 2**64  # [0, 1)
        return base * (1.0 + self.jitter * (2.0 * frac - 1.0))


def call_with_retry(
    fn: Callable[[], object],
    policy: Optional[RetryPolicy] = None,
    *,
    label: str = "",
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """Run ``fn`` under ``policy``; return its result.

    Retries only exceptions matching ``policy.retry_on``; others propagate
    untouched. When the attempt budget is spent, raises
    :class:`RetryExhaustedError` with the last error as ``__cause__``.
    ``on_retry(attempt, err)`` fires before each backoff sleep (logging,
    counters); ``sleep`` is injectable for zero-wall-clock tests.
    """
    policy = policy or RetryPolicy()
    last_err: Optional[BaseException] = None
    for attempt in range(1, policy.max_attempts + 1):
        try:
            # each attempt is its own span (telemetry off: shared no-op),
            # so a trace shows attempt 3's wall next to attempts 1-2
            with telemetry.span(
                f"retry:{label}" if label else "retry",
                cat="retry", attempt=attempt,
            ):
                return fn()
        except policy.retry_on as err:
            last_err = err
            telemetry.registry().counter(
                "fmrp_retry_attempts_total",
                help="retryable attempt failures across every layer",
            ).inc()
            telemetry.event(
                "retry.attempt", cat="retry", label=label,
                attempt=attempt, error=repr(err)[:200],
            )
            if attempt == policy.max_attempts:
                break
            if on_retry is not None:
                on_retry(attempt, err)
            delay = policy.delay_s(attempt, label)
            telemetry.event(
                "retry.backoff", cat="retry", label=label,
                attempt=attempt, delay_s=round(delay, 6),
            )
            sleep(delay)
    telemetry.registry().counter(
        "fmrp_retry_exhausted_total",
        help="calls that failed after their full attempt budget",
    ).inc()
    telemetry.event(
        "retry.exhausted", cat="retry", label=label,
        attempts=policy.max_attempts,
    )
    raise RetryExhaustedError(
        f"{label or getattr(fn, '__name__', 'call')} failed "
        f"after {policy.max_attempts} attempts"
    ) from last_err


def retrying(policy: RetryPolicy, **kwargs):
    """Decorator form of :func:`call_with_retry` for fixed call sites."""

    def wrap(fn):
        import functools

        @functools.wraps(fn)
        def inner(*a, **kw):
            return call_with_retry(
                lambda: fn(*a, **kw),
                policy,
                label=kwargs.get("label", fn.__name__),
                **{k: v for k, v in kwargs.items() if k != "label"},
            )

        return inner

    return wrap
