"""Static documentation site builder.

Capability parity with the reference's Sphinx/jupyter-book docs build
(``docs_src/conf.py``, ``dodo.py:257-300`` — vestigial template machinery
there): render the repo's markdown docs plus the executed-notebook HTML
into one self-contained static site. Sphinx is not installed in this
environment, so the renderer is the stdlib-adjacent ``markdown`` package
inside a minimal HTML shell — no template project baggage, same artifact
(a browsable ``docs/site/`` suitable for GitHub Pages, ``.nojekyll``
included as the reference's ``dodo.py:300`` does).
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Dict, List

__all__ = ["build_docs_site"]

_PAGE = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>{title}</title>
<style>
body {{ font: 16px/1.55 system-ui, sans-serif; margin: 0; color: #1a1a1a; }}
nav {{ background: #15243b; padding: .6rem 1.2rem; }}
nav a {{ color: #cfe0ff; margin-right: 1.1rem; text-decoration: none; }}
nav a:hover {{ text-decoration: underline; }}
main {{ max-width: 54rem; margin: 0 auto; padding: 1.5rem; }}
pre {{ background: #f5f6f8; padding: .8rem; overflow-x: auto; border-radius: 6px; }}
code {{ background: #f5f6f8; padding: .1rem .25rem; border-radius: 4px; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #d8dce3; padding: .3rem .6rem; }}
</style>
</head>
<body>
<nav>{nav}</nav>
<main>{body}</main>
</body>
</html>
"""


def _render_markdown(text: str) -> str:
    import markdown

    return markdown.markdown(
        text, extensions=["tables", "fenced_code", "toc"]
    )


def build_docs_site(
    base_dir: Path,
    site_dir: Path,
    pages: Dict[str, Path] | None = None,
) -> List[Path]:
    """Render ``pages`` (title → markdown path) plus any notebook HTML under
    ``docs/notebooks`` into ``site_dir``. Returns the written paths."""
    base_dir = Path(base_dir)
    site_dir = Path(site_dir)
    site_dir.mkdir(parents=True, exist_ok=True)

    if pages is None:
        pages = {"Overview": base_dir / "README.md"}
        for md in sorted((base_dir / "docs").glob("*.md")):
            title = md.stem.replace("_", " ").title()
            if title in pages:  # never clobber an earlier page (e.g. the README)
                title = f"{title} ({md.stem})"
            pages[title] = md
    pages = {title: path for title, path in pages.items() if Path(path).is_file()}

    notebooks = sorted((base_dir / "docs" / "notebooks").glob("*.html"))

    # "index" is reserved for the Overview/README landing page; any other
    # title whose slug collides with one already taken gets a numeric suffix
    slugs: Dict[str, str] = {}
    taken = set()
    for title in pages:
        s = "index" if title == "Overview" else title.lower().replace(" ", "-")
        if s == "index" and title != "Overview":
            s = "index-page"
        base_slug, k = s, 2
        while s in taken:
            s = f"{base_slug}-{k}"
            k += 1
        taken.add(s)
        slugs[title] = s

    nav = "".join(
        f'<a href="{slugs[t]}.html">{t}</a>' for t in pages
    ) + "".join(f'<a href="notebooks/{nb.name}">{nb.stem}</a>' for nb in notebooks)

    written = []
    for title, path in pages.items():
        html = _PAGE.format(
            title=title, nav=nav, body=_render_markdown(Path(path).read_text())
        )
        out = site_dir / f"{slugs[title]}.html"
        out.write_text(html)
        written.append(out)

    if notebooks:
        nb_dir = site_dir / "notebooks"
        nb_dir.mkdir(exist_ok=True)
        for nb in notebooks:
            shutil.copy2(nb, nb_dir / nb.name)
            written.append(nb_dir / nb.name)

    # GitHub Pages marker, as the reference writes (dodo.py:300)
    nojekyll = site_dir / ".nojekyll"
    nojekyll.write_text("")
    written.append(nojekyll)
    return written
