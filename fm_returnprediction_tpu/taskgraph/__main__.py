"""Task-graph CLI — the ``doit`` command surface (``README.md:27-31``).

    python -m fm_returnprediction_tpu.taskgraph                 # run all
    python -m fm_returnprediction_tpu.taskgraph reports          # run up to a task
    python -m fm_returnprediction_tpu.taskgraph --list           # show tasks
    python -m fm_returnprediction_tpu.taskgraph --forget         # drop state
    python -m fm_returnprediction_tpu.taskgraph --synthetic      # fake-WRDS backend
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from fm_returnprediction_tpu.settings import apply_backend, config, enable_compilation_cache
from fm_returnprediction_tpu.taskgraph.engine import TaskRunner, write_timing_log
from fm_returnprediction_tpu.taskgraph.tasks import build_notebook_tasks, build_tasks


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="fm_returnprediction_tpu.taskgraph")
    parser.add_argument("tasks", nargs="*", help="tasks to run (default: all)")
    parser.add_argument("--list", action="store_true", help="list tasks and exit")
    parser.add_argument("--forget", action="store_true", help="drop recorded state")
    parser.add_argument("--force", action="store_true", help="ignore up-to-date state")
    parser.add_argument("--keep-going", action="store_true",
                        help="continue independent subgraphs after a failure "
                             "(failures recorded in the state DB)")
    parser.add_argument("--synthetic", action="store_true",
                        help="use the synthetic fake-WRDS backend")
    parser.add_argument("--specgrid-cells", type=int, default=None,
                        metavar="N",
                        help="scale the specgrid task's sweep to at least "
                             "N cells (bootstrap-draw dimension grows; "
                             "tiles stream so memory stays bounded)")
    parser.add_argument("--specgrid-sink", default=None,
                        choices=["frame", "topk", "summary", "parquet"],
                        help="specgrid task streaming sink (default "
                             "follows FMRP_SPECGRID_SINK, else the full "
                             "tidy frame)")
    parser.add_argument("--specgrid-estimator", default=None,
                        metavar="SPEC",
                        help="run the specgrid sweep under an estimator "
                             "cell instead of OLS@NW — grammar "
                             "'fwl:c1+c2[@se]' | 'absorb:fe1+fe2' | "
                             "'iv:endog~z1+z2' | 'pooled[:se]' (default "
                             "follows FMRP_SPECGRID_ESTIMATOR; the "
                             "Table-2/figure parity surfaces keep "
                             "rejecting non-OLS loudly)")
    parser.add_argument("--backtest-schemes", default=None, metavar="LIST",
                        help="backtest task estimation-path schemes, a "
                             "comma list like 'expanding,rolling120' "
                             "(default follows FMRP_BACKTEST_SCHEMES)")
    parser.add_argument("--backtest-route", default=None,
                        choices=["auto", "scan", "refit"],
                        help="backtest coefficient-path route: prefix-sum "
                             "scan program or the per-origin full-refit "
                             "differential oracle (default follows "
                             "FMRP_BACKTEST_ROUTE)")
    parser.add_argument("--backtest-quantiles", type=int, default=None,
                        metavar="D",
                        help="backtest portfolio sort buckets, >= 2 "
                             "(default follows FMRP_BACKTEST_QUANTILES)")
    parser.add_argument("--backtest-sink", default=None,
                        choices=["frame", "topk", "summary", "parquet",
                                 "metrics"],
                        help="backtest task streaming sink (default "
                             "follows FMRP_BACKTEST_SINK, else the full "
                             "per-cell frame)")
    parser.add_argument("--notebooks", action="store_true",
                        help="include the notebook conversion/execution tasks")
    parser.add_argument("--db", default=None, help="state db path")
    parser.add_argument("--backend", choices=["cpu", "tpu"], default=None,
                        help="override the BACKEND setting")
    parser.add_argument("--trace-dir", default=None,
                        help="arm telemetry: export task/stage spans to "
                             "events.jsonl + trace.json in this directory "
                             "(default follows FMRP_TRACE_DIR)")
    parser.add_argument("--profile-dir", default=None,
                        help="capture a jax.profiler device trace of the "
                             "run into this directory (host spans "
                             "annotate the device timeline)")
    parser.add_argument("--registry-dir", default=None,
                        help="arm the artifact/executable registry at "
                             "this root for every task (AOT executables "
                             "and fitted artifacts fetch instead of "
                             "compile/rebuild); default follows "
                             "FMRP_REGISTRY_DIR")
    parser.add_argument("--fleet-size", type=int, default=None, metavar="N",
                        help="after the DAG completes, stand up an "
                             "N-replica serving fleet on the produced "
                             "serving_state.npz and run the admission-"
                             "controlled query smoke (default follows "
                             "FMRP_FLEET_SIZE when that is set; "
                             "FMRP_FLEET_RATE/_BURST/_SHED_OCCUPANCY "
                             "shape admission, FMRP_FLEET_JOURNAL arms "
                             "the request journal)")
    parser.add_argument("--replica-mode", choices=("thread", "process"),
                        default=None,
                        help="fleet smoke replica boundary: in-process "
                             "threads or spawned child processes behind "
                             "the socket transport; default follows "
                             "FMRP_FLEET_REPLICA_MODE (thread)")
    args = parser.parse_args(argv)

    from fm_returnprediction_tpu.parallel.distributed import (
        initialize_distributed,
    )
    from fm_returnprediction_tpu.parallel.multihost import initialize_multihost

    # join a multi-process run when FMRP_DIST_* is set (host exchange +
    # telemetry identity) — a no-op otherwise; must precede backend init
    initialize_distributed()
    initialize_multihost()  # no-op unless FMRP_MULTIHOST=1; must precede backend init
    apply_backend(args.backend)
    enable_compilation_cache()

    tasks = build_tasks(synthetic=args.synthetic,
                        specgrid_cells=args.specgrid_cells,
                        specgrid_sink=args.specgrid_sink,
                        specgrid_estimator=args.specgrid_estimator,
                        backtest_schemes=args.backtest_schemes,
                        backtest_route=args.backtest_route,
                        backtest_quantiles=args.backtest_quantiles,
                        backtest_sink=args.backtest_sink)
    if args.notebooks:
        tasks += build_notebook_tasks()
    db = args.db or Path(config("BASE_DIR")) / ".fmrp-task-db.sqlite"

    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.registry.store import using_registry
    from contextlib import ExitStack

    with ExitStack() as stack:
        stack.enter_context(using_registry(args.registry_dir))
        stack.enter_context(telemetry.tracing(args.trace_dir))
        stack.enter_context(telemetry.profiling(args.profile_dir))
        runner = stack.enter_context(TaskRunner(tasks, db_path=db))
        if args.list:
            for t in tasks:
                state = "up-to-date" if runner.is_up_to_date(t) else "stale"
                print(f"{t.name:<14} [{state}] {t.doc}")
            return 0
        if args.forget:
            runner.forget(args.tasks or None)
            print("state forgotten")
            return 0
        import time

        t_run = time.time()
        ok = runner.run(args.tasks or None, force=args.force,
                        keep_going=args.keep_going)
        if not ok and args.keep_going:
            # THIS run's failures only — the ledger also holds rows from
            # prior still-unhealed runs
            for entry in runner.failures():
                if entry["ts"] >= t_run:
                    print(f"FAILED {entry['task']}: {entry['error']}",
                          file=sys.stderr)
        write_timing_log(runner, Path(config("OUTPUT_DIR")) / "task_timings.json")
        import os as _os

        fleet_size = args.fleet_size
        if fleet_size is None and _os.environ.get("FMRP_FLEET_SIZE"):
            fleet_size = int(_os.environ["FMRP_FLEET_SIZE"])
        if ok and fleet_size:
            # guarded: a smoke failure must not fail an already-green DAG
            try:
                import json as _json

                from fm_returnprediction_tpu.serving.fleet import fleet_smoke

                state_path = (
                    Path(config("PROCESSED_DATA_DIR")) / "serving_state.npz"
                )
                if state_path.exists():
                    smoke = fleet_smoke(
                        state_path, fleet_size,
                        registry_dir=args.registry_dir,
                        replica_mode=args.replica_mode,
                    )
                    print("serving fleet smoke: "
                          + _json.dumps(smoke, sort_keys=True))
                else:
                    print(f"fleet smoke skipped: {state_path} not built "
                          "(run the serve_state task)", file=sys.stderr)
            except Exception as exc:  # noqa: BLE001 — disclosed, not fatal
                print(f"fleet smoke failed (DAG result unaffected): "
                      f"{exc!r}", file=sys.stderr)
        return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
