"""Orchestration layer (L7): incremental task graph + the pipeline DAG.

Replaces the reference's doit build system (``dodo.py``) with an in-package
engine (sqlite state, content-hash deps, green/SLURM reporters) and the
Lewellen pipeline expressed as six tasks with dense-panel and warmed
serving-state checkpoints.
"""

from fm_returnprediction_tpu.taskgraph.engine import (
    GreenReporter,
    PlainReporter,
    Task,
    TaskRunner,
)
from fm_returnprediction_tpu.taskgraph.tasks import build_tasks

__all__ = [
    "GreenReporter",
    "PlainReporter",
    "Task",
    "TaskRunner",
    "build_tasks",
]
