"""Incremental task-graph engine — the L7 orchestration layer.

Re-provides the capability surface of the reference's doit-based build
(``dodo.py:1-300``) without the doit dependency (not in this image):

- tasks with actions, ``file_dep``/``targets``/``task_dep``/``uptodate``
  semantics (``dodo.py:115-206``);
- persistent execution state in sqlite (the reference's
  ``.doit-db.sqlite`` backend, ``dodo.py:51-57``) keyed by file content
  hashes, so unchanged inputs skip work across processes;
- a green console reporter with SLURM detection switching to plain output
  (``dodo.py:31-48`` — the reference's only cluster awareness);
- per-task wall-clock timing persisted alongside state (SURVEY §5: the
  headline metric is wall-clock, so the runner records stage timings).

Python actions run in-process (no ``jupyter nbconvert`` subprocess hop —
the driver is a plain function, ``pipeline.run_pipeline``), which keeps the
TPU runtime initialized once across tasks instead of re-dialing per stage.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sqlite3
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Union

from fm_returnprediction_tpu import telemetry
from fm_returnprediction_tpu.resilience.errors import TaskTimeoutError

__all__ = ["Task", "TaskRunner", "Reporter", "GreenReporter", "PlainReporter"]

Action = Union[str, Callable[[], object]]


@dataclasses.dataclass
class Task:
    """One node of the graph. Mirrors doit's task dict contract
    (``dodo.py:115-129``): run ``actions`` when any ``file_dep`` content
    changed, a ``target`` is missing, an ``uptodate`` check fails, or the
    task has never run.

    Resilience knobs (``resilience`` layer):

    - ``retries``         — re-run the whole action list this many times
      after a failure (exponential backoff from ``retry_backoff_s``,
      deterministic jitter). A flaky WRDS pull costs a retry, not the run.
    - ``timeout_s``       — per-ACTION wall-clock budget. A stalled action
      fails with :class:`TaskTimeoutError` instead of hanging the graph
      (python actions run on a watchdogged worker thread that is abandoned
      on timeout; shell actions get ``subprocess`` timeouts).
    """

    name: str
    actions: Sequence[Action]
    file_dep: Sequence[Union[str, Path]] = ()
    targets: Sequence[Union[str, Path]] = ()
    task_dep: Sequence[str] = ()
    uptodate: Sequence[Callable[[], bool]] = ()
    doc: str = ""
    verbosity: int = 1
    retries: int = 0
    retry_backoff_s: float = 0.5
    timeout_s: Optional[float] = None


class Reporter:
    def start(self, task: Task) -> None: ...
    def skip(self, task: Task) -> None: ...
    def skip_failed(self, task: Task, dep: str) -> None: ...
    def retry(self, task: Task, attempt: int, err: Exception) -> None: ...
    def done(self, task: Task, seconds: float) -> None: ...
    def fail(self, task: Task, err: Exception) -> None: ...


class PlainReporter(Reporter):
    """No ANSI color — selected automatically under SLURM, where escape
    codes pollute job logs (reference behavior, ``dodo.py:31-34``)."""

    out = sys.stdout

    def start(self, task: Task) -> None:
        print(f".  {task.name}", file=self.out, flush=True)

    def skip(self, task: Task) -> None:
        print(f"-- {task.name} (up to date)", file=self.out, flush=True)

    def skip_failed(self, task: Task, dep: str) -> None:
        print(f"## {task.name} (skipped: dependency {dep} failed)",
              file=self.out, flush=True)

    def retry(self, task: Task, attempt: int, err: Exception) -> None:
        print(f"~~ {task.name} retry {attempt}: {err}",
              file=self.out, flush=True)

    def done(self, task: Task, seconds: float) -> None:
        print(f"   {task.name} ok [{seconds:.2f}s]", file=self.out, flush=True)

    def fail(self, task: Task, err: Exception) -> None:
        print(f"!! {task.name} FAILED: {err}", file=self.out, flush=True)


class GreenReporter(PlainReporter):
    """Green task lines on a TTY (reference ``GreenReporter``,
    ``dodo.py:37-48``)."""

    GREEN, RED, RESET = "\033[32m", "\033[31m", "\033[0m"

    def start(self, task: Task) -> None:
        print(f"{self.GREEN}.  {task.name}{self.RESET}", file=self.out, flush=True)

    def skip(self, task: Task) -> None:
        print(
            f"{self.GREEN}-- {task.name} (up to date){self.RESET}",
            file=self.out,
            flush=True,
        )

    def done(self, task: Task, seconds: float) -> None:
        print(
            f"{self.GREEN}   {task.name} ok [{seconds:.2f}s]{self.RESET}",
            file=self.out,
            flush=True,
        )

    def fail(self, task: Task, err: Exception) -> None:
        print(f"{self.RED}!! {task.name} FAILED: {err}{self.RESET}", file=self.out)


def default_reporter() -> Reporter:
    """SLURM jobs get the plain reporter (``dodo.py:31-34``)."""
    if os.environ.get("SLURM_JOB_ID"):
        return PlainReporter()
    return GreenReporter()


def _hash_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class TaskRunner:
    """Executes a task list in dependency order with sqlite-backed state.

    State schema: one row per (task, dep file) content hash plus a row per
    task recording success and timing. A task is up to date iff it succeeded
    before, every file_dep hash matches, every target exists, and every
    ``uptodate`` callable returns True.
    """

    def __init__(
        self,
        tasks: Iterable[Task],
        db_path: Optional[Union[str, Path]] = None,
        reporter: Optional[Reporter] = None,
    ):
        self.tasks: Dict[str, Task] = {}
        for t in tasks:
            if t.name in self.tasks:
                raise ValueError(f"Duplicate task name: {t.name}")
            self.tasks[t.name] = t
        if db_path is None:
            # Anchor at BASE_DIR, not cwd — stray sqlite state in whatever
            # directory the caller happens to run from is repo litter.
            from fm_returnprediction_tpu.settings import config

            db_path = Path(config("BASE_DIR")) / ".fmrp-task-db.sqlite"
        self.db_path = Path(db_path)
        self.reporter = reporter or default_reporter()
        # Generous busy timeout + WAL so two concurrent runners sharing the
        # state DB queue behind each other instead of raising "database is
        # locked" and recording a spurious task failure (ADVICE r1).
        self._db = sqlite3.connect(self.db_path, timeout=60.0)
        try:
            self._db.execute("PRAGMA journal_mode=WAL")
        except sqlite3.DatabaseError:
            pass  # e.g. WAL unsupported on a network filesystem — fall back
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS dep_hash"
            " (task TEXT, path TEXT, hash TEXT, size INTEGER, mtime REAL,"
            "  PRIMARY KEY (task, path))"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS run_state"
            " (task TEXT PRIMARY KEY, ok INTEGER, seconds REAL, ts REAL)"
        )
        # the failure ledger ``keep_going`` runs append to: one row per
        # failed task (or dependency-skip), so a partially-failed graph is
        # inspectable after the fact instead of reconstructed from logs
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS failure_log"
            " (task TEXT, error TEXT, ts REAL)"
        )
        self._db.commit()
        self._closed = False

    # -- state ------------------------------------------------------------
    def _stored_deps(self, task: Task) -> Dict[str, tuple]:
        rows = self._db.execute(
            "SELECT path, hash, size, mtime FROM dep_hash WHERE task=?",
            (task.name,),
        ).fetchall()
        return {path: (h, size, mtime) for path, h, size, mtime in rows}

    def _record_success(self, task: Task, seconds: float) -> None:
        # a success heals the ledger: failure rows describe the CURRENT
        # state of the graph, not dead history (history is the run log)
        self._db.execute("DELETE FROM failure_log WHERE task=?", (task.name,))
        self._db.execute("DELETE FROM dep_hash WHERE task=?", (task.name,))
        for dep in task.file_dep:
            p = Path(dep)
            if p.exists():
                st = p.stat()
                self._db.execute(
                    "INSERT OR REPLACE INTO dep_hash VALUES (?,?,?,?,?)",
                    (task.name, str(p), _hash_file(p), st.st_size, st.st_mtime),
                )
        self._db.execute(
            "INSERT OR REPLACE INTO run_state VALUES (?,?,?,?)",
            (task.name, 1, seconds, time.time()),
        )
        self._db.commit()

    def is_up_to_date(self, task: Task) -> bool:
        row = self._db.execute(
            "SELECT ok FROM run_state WHERE task=?", (task.name,)
        ).fetchone()
        if not row or not row[0]:
            return False
        for tgt in task.targets:
            if not Path(tgt).exists():
                return False
        stored = self._stored_deps(task)
        for dep in task.file_dep:
            p = Path(dep)
            if not p.exists() or str(p) not in stored:
                return False
            h, size, mtime = stored[str(p)]
            st = p.stat()
            if st.st_size == size and st.st_mtime == mtime:
                continue  # metadata unchanged → trust the stored hash
            if h != _hash_file(p):
                return False
            # Content identical but metadata drifted (touch/copy): refresh
            # the metadata so the next check short-circuits again.
            self._db.execute(
                "UPDATE dep_hash SET size=?, mtime=? WHERE task=? AND path=?",
                (st.st_size, st.st_mtime, task.name, str(p)),
            )
            self._db.commit()
        for check in task.uptodate:
            if not check():
                return False
        # A task with nothing to compare is always stale (doit semantics for
        # bare tasks) unless an uptodate check said otherwise.
        if not task.targets and not list(task.file_dep) and not task.uptodate:
            return False
        return True

    def forget(self, names: Optional[Sequence[str]] = None) -> None:
        """Drop recorded state (doit ``forget``) for ``names`` or all —
        including the failure ledger, so a forgotten task re-runs with a
        clean record."""
        for name in names or list(self.tasks):
            self._db.execute("DELETE FROM dep_hash WHERE task=?", (name,))
            self._db.execute("DELETE FROM run_state WHERE task=?", (name,))
            self._db.execute("DELETE FROM failure_log WHERE task=?", (name,))
        self._db.commit()

    def failures(self) -> List[dict]:
        """The recorded failure ledger, oldest first: one entry per failed
        task or dependency-skip (``{"task", "error", "ts"}``)."""
        rows = self._db.execute(
            "SELECT task, error, ts FROM failure_log ORDER BY ts, rowid"
        ).fetchall()
        return [{"task": t, "error": e, "ts": ts} for t, e, ts in rows]

    def timings(self) -> Dict[str, float]:
        """Last SUCCESSFUL wall-clock seconds per task."""
        rows = self._db.execute(
            "SELECT task, seconds FROM run_state WHERE seconds IS NOT NULL"
        ).fetchall()
        return dict(rows)

    # -- execution --------------------------------------------------------
    def _toposort(self, names: Sequence[str]) -> List[str]:
        order: List[str] = []
        seen: Dict[str, int] = {}  # 0=visiting, 1=done

        def visit(name: str) -> None:
            if name not in self.tasks:
                raise KeyError(f"Unknown task: {name}")
            state = seen.get(name)
            if state == 1:
                return
            if state == 0:
                raise ValueError(f"Task dependency cycle at {name}")
            seen[name] = 0
            for dep in self.tasks[name].task_dep:
                visit(dep)
            seen[name] = 1
            order.append(name)

        for name in names:
            visit(name)
        return order

    @staticmethod
    def _consensus(flag: bool, reduce) -> bool:
        """Cross-process reduction of a local boolean; identity when the
        distributed runtime is not up.

        Two uses keep the engine's collective sequence aligned on pods:

        - STALENESS (``reduce=np.any``): the skip decision is per-process
          (local state DB, local clocks), but multi-host task actions
          contain cross-process BARRIERS (``tasks._primary_writes``) — if
          one process skips a task another runs, the runner deadlocks
          inside the action. If ANY process finds a task stale, everyone
          runs it (writes are process-0-gated, so redundant runs are
          compute-only).
        - SUCCESS (``reduce=np.all``): a one-sided failure must stop all
          processes together — the failed process makes no further
          collective calls, so survivors marching into the next staleness
          allgather would hang there, masking the real traceback.

        The single-process probe is ``distributed_client_active`` —
        NOT ``jax.process_count()``, which would initialize the XLA
        backends (pinning the platform, dialing remote runtimes) on the
        very first skip check of a plain local run.
        """
        # transport ladder: the host exchange first (answers on every
        # backend and never initializes XLA), then the device-collective
        # runtime when it is up
        from fm_returnprediction_tpu.parallel import distributed as _dist

        ex = _dist.host_exchange()
        if ex is not None:
            import numpy as _np

            flags = ex.allgather_obj(bool(flag))
            return bool(reduce(_np.asarray(flags)))

        from fm_returnprediction_tpu.parallel.multihost import (
            distributed_client_active,
        )

        if not distributed_client_active():
            return flag
        import jax

        if jax.process_count() == 1:
            return flag
        import numpy as _np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            _np.asarray([1 if flag else 0], _np.int32)
        )
        return bool(reduce(_np.asarray(flags)))

    # -- action execution (retry / timeout / fault isolation) -------------

    def _run_action(self, task: Task, action: Action) -> None:
        """One action under the task's ``timeout_s`` budget. The fault site
        lets the chaos harness inject failures/stalls per task name."""
        from fm_returnprediction_tpu.resilience.faults import fault_site

        fault_site(f"taskgraph.{task.name}")
        if isinstance(action, str):
            try:
                subprocess.run(
                    action, shell=True, check=True, timeout=task.timeout_s
                )
            except subprocess.TimeoutExpired as exc:
                raise TaskTimeoutError(
                    f"task {task.name!r} shell action exceeded "
                    f"{task.timeout_s}s"
                ) from exc
            return
        if task.timeout_s is None:
            action()
            return
        # Python actions cannot be killed; run on a daemon worker and
        # ABANDON it on timeout — the graph fails the node and moves on
        # (the documented trade: a leaked sleeping thread beats a hung
        # build). Callers whose actions must run on the main thread
        # (signal handlers) should not set timeout_s.
        result: Dict[str, object] = {}
        # the worker thread does not inherit this thread's context — hand
        # the task span across explicitly so everything the action records
        # (pipeline stages, retries) stays in the task's trace
        parent = telemetry.capture()

        def target() -> None:
            try:
                with telemetry.attach(parent):
                    result["ok"] = action()
            except BaseException as exc:  # noqa: BLE001 — relayed below
                result["err"] = exc

        worker = threading.Thread(
            target=target, daemon=True, name=f"fmrp-task-{task.name}"
        )
        worker.start()
        worker.join(task.timeout_s)
        if worker.is_alive():
            telemetry.event(
                "task.timeout", cat="taskgraph",
                task=task.name, timeout_s=task.timeout_s,
            )
            # flight recorder: freeze the last spans/events + cost ledger
            # at the moment the watchdog fired (no-op without a trace dir)
            telemetry.dump_flight(f"task.timeout:{task.name}")
            raise TaskTimeoutError(
                f"task {task.name!r} action exceeded {task.timeout_s}s "
                "(worker abandoned)"
            )
        if "err" in result:
            raise result["err"]  # type: ignore[misc]

    def _execute_actions(self, task: Task) -> None:
        """The whole action list, re-run up to ``task.retries`` extra times
        on failure (shared backoff policy, deterministic jitter; retries
        restart from the FIRST action — actions are assumed idempotent,
        which the file_dep/target contract already requires)."""
        from fm_returnprediction_tpu.resilience.retry import (
            RetryPolicy,
            call_with_retry,
        )

        def once() -> None:
            for action in task.actions:
                self._run_action(task, action)

        if task.retries <= 0:
            once()  # no wrapper: the original traceback stays primary
            return
        call_with_retry(
            once,
            RetryPolicy(
                max_attempts=task.retries + 1,
                backoff_s=task.retry_backoff_s,
                retry_on=(Exception,),
            ),
            label=task.name,
            on_retry=lambda attempt, err: self.reporter.retry(
                task, attempt, err
            ),
        )

    def _record_failure(self, task: Task, error: str, ran: bool = True) -> None:
        """Append to the failure ledger; a task that actually RAN is also
        marked stale (PRESERVING the last successful timing — the timing
        log is the wall-clock record, not the failure log). A dependency-
        skip leaves run_state untouched: the task itself never executed."""
        if ran:
            self._db.execute(
                "INSERT INTO run_state VALUES (?,0,NULL,?)"
                " ON CONFLICT(task) DO UPDATE SET ok=0, ts=excluded.ts",
                (task.name, time.time()),
            )
        self._db.execute(
            "INSERT INTO failure_log VALUES (?,?,?)",
            (task.name, error, time.time()),
        )
        self._db.commit()
        # the structured twin of the sqlite ledger row — the trace and the
        # failure_log must agree (differential-tested in test_telemetry)
        telemetry.event(
            "task.failure", cat="taskgraph",
            task=task.name, error=error, ran=ran,
        )
        if ran:  # dependency-skips carry no new evidence worth a dump
            telemetry.dump_flight(f"task.failure:{task.name}")

    def run(
        self,
        names: Optional[Sequence[str]] = None,
        force: bool = False,
        keep_going: bool = False,
    ) -> bool:
        """Run ``names`` (default: all tasks) and their deps. Returns True
        if everything succeeded.

        ``keep_going`` (make's ``-k``): a failed node fails its DEPENDENT
        subgraph — dependents are marked skipped in the failure ledger —
        but independent subgraphs keep running, so one flaky stage does
        not strand unrelated work. Without it, the first failure halts
        the run (prior behavior).

        An abort (KeyboardInterrupt/SystemExit) is recorded like a
        failure, then the sqlite connection is CLOSED before re-raising —
        an interrupted run must not leave a locked state DB behind.
        """
        import numpy as _np

        order = self._toposort(list(names or self.tasks))
        ok_all = True
        dead: set = set()  # failed, or skipped because a dependency failed
        for name in order:
            task = self.tasks[name]
            if dead:
                bad = next((d for d in task.task_dep if d in dead), None)
                if bad is not None:
                    self.reporter.skip_failed(task, bad)
                    self._record_failure(
                        task, f"skipped: dependency {bad!r} failed", ran=False
                    )
                    dead.add(name)
                    continue
            stale = force or not self.is_up_to_date(task)
            if not self._consensus(stale, _np.any):
                self.reporter.skip(task)
                telemetry.event(
                    "task.skip", cat="taskgraph", task=name,
                    reason="up-to-date",
                )
                continue
            self.reporter.start(task)
            start = time.perf_counter()
            err: Optional[BaseException] = None
            try:
                # one span per executed task: retries (retry:<name> child
                # spans), the watchdogged worker, and everything the action
                # itself records nest under it in the exported trace
                with telemetry.span(
                    f"task:{name}", cat="task", task=name,
                    keep_going=keep_going,
                ):
                    self._execute_actions(task)
            except BaseException as exc:  # noqa: BLE001 — recorded below
                err = exc
            if not self._consensus(err is None, _np.all):
                if err is None:  # a PEER failed; this process's task was fine
                    err = RuntimeError(
                        "task failed on another process (see its log)"
                    )
                self.reporter.fail(task, err)
                self._record_failure(task, repr(err))
                if isinstance(err, (KeyboardInterrupt, SystemExit)):
                    self.close()
                    raise err
                if not keep_going:
                    return False
                ok_all = False
                dead.add(name)
                continue
            seconds = time.perf_counter() - start
            self._record_success(task, seconds)
            self.reporter.done(task, seconds)
        return ok_all

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._db.close()

    def __enter__(self) -> "TaskRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_timing_log(runner: TaskRunner, path: Union[str, Path]) -> None:
    """Dump per-task timings as JSON (SURVEY §5: keep a per-task timing log
    since the headline metric is wall-clock)."""
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        json.dump(runner.timings(), f, indent=2, sort_keys=True)
