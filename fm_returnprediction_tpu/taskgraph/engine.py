"""Incremental task-graph engine — the L7 orchestration layer.

Re-provides the capability surface of the reference's doit-based build
(``dodo.py:1-300``) without the doit dependency (not in this image):

- tasks with actions, ``file_dep``/``targets``/``task_dep``/``uptodate``
  semantics (``dodo.py:115-206``);
- persistent execution state in sqlite (the reference's
  ``.doit-db.sqlite`` backend, ``dodo.py:51-57``) keyed by file content
  hashes, so unchanged inputs skip work across processes;
- a green console reporter with SLURM detection switching to plain output
  (``dodo.py:31-48`` — the reference's only cluster awareness);
- per-task wall-clock timing persisted alongside state (SURVEY §5: the
  headline metric is wall-clock, so the runner records stage timings).

Python actions run in-process (no ``jupyter nbconvert`` subprocess hop —
the driver is a plain function, ``pipeline.run_pipeline``), which keeps the
TPU runtime initialized once across tasks instead of re-dialing per stage.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sqlite3
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Union

__all__ = ["Task", "TaskRunner", "Reporter", "GreenReporter", "PlainReporter"]

Action = Union[str, Callable[[], object]]


@dataclasses.dataclass
class Task:
    """One node of the graph. Mirrors doit's task dict contract
    (``dodo.py:115-129``): run ``actions`` when any ``file_dep`` content
    changed, a ``target`` is missing, an ``uptodate`` check fails, or the
    task has never run."""

    name: str
    actions: Sequence[Action]
    file_dep: Sequence[Union[str, Path]] = ()
    targets: Sequence[Union[str, Path]] = ()
    task_dep: Sequence[str] = ()
    uptodate: Sequence[Callable[[], bool]] = ()
    doc: str = ""
    verbosity: int = 1


class Reporter:
    def start(self, task: Task) -> None: ...
    def skip(self, task: Task) -> None: ...
    def done(self, task: Task, seconds: float) -> None: ...
    def fail(self, task: Task, err: Exception) -> None: ...


class PlainReporter(Reporter):
    """No ANSI color — selected automatically under SLURM, where escape
    codes pollute job logs (reference behavior, ``dodo.py:31-34``)."""

    out = sys.stdout

    def start(self, task: Task) -> None:
        print(f".  {task.name}", file=self.out, flush=True)

    def skip(self, task: Task) -> None:
        print(f"-- {task.name} (up to date)", file=self.out, flush=True)

    def done(self, task: Task, seconds: float) -> None:
        print(f"   {task.name} ok [{seconds:.2f}s]", file=self.out, flush=True)

    def fail(self, task: Task, err: Exception) -> None:
        print(f"!! {task.name} FAILED: {err}", file=self.out, flush=True)


class GreenReporter(PlainReporter):
    """Green task lines on a TTY (reference ``GreenReporter``,
    ``dodo.py:37-48``)."""

    GREEN, RED, RESET = "\033[32m", "\033[31m", "\033[0m"

    def start(self, task: Task) -> None:
        print(f"{self.GREEN}.  {task.name}{self.RESET}", file=self.out, flush=True)

    def skip(self, task: Task) -> None:
        print(
            f"{self.GREEN}-- {task.name} (up to date){self.RESET}",
            file=self.out,
            flush=True,
        )

    def done(self, task: Task, seconds: float) -> None:
        print(
            f"{self.GREEN}   {task.name} ok [{seconds:.2f}s]{self.RESET}",
            file=self.out,
            flush=True,
        )

    def fail(self, task: Task, err: Exception) -> None:
        print(f"{self.RED}!! {task.name} FAILED: {err}{self.RESET}", file=self.out)


def default_reporter() -> Reporter:
    """SLURM jobs get the plain reporter (``dodo.py:31-34``)."""
    if os.environ.get("SLURM_JOB_ID"):
        return PlainReporter()
    return GreenReporter()


def _hash_file(path: Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


class TaskRunner:
    """Executes a task list in dependency order with sqlite-backed state.

    State schema: one row per (task, dep file) content hash plus a row per
    task recording success and timing. A task is up to date iff it succeeded
    before, every file_dep hash matches, every target exists, and every
    ``uptodate`` callable returns True.
    """

    def __init__(
        self,
        tasks: Iterable[Task],
        db_path: Optional[Union[str, Path]] = None,
        reporter: Optional[Reporter] = None,
    ):
        self.tasks: Dict[str, Task] = {}
        for t in tasks:
            if t.name in self.tasks:
                raise ValueError(f"Duplicate task name: {t.name}")
            self.tasks[t.name] = t
        if db_path is None:
            # Anchor at BASE_DIR, not cwd — stray sqlite state in whatever
            # directory the caller happens to run from is repo litter.
            from fm_returnprediction_tpu.settings import config

            db_path = Path(config("BASE_DIR")) / ".fmrp-task-db.sqlite"
        self.db_path = Path(db_path)
        self.reporter = reporter or default_reporter()
        # Generous busy timeout + WAL so two concurrent runners sharing the
        # state DB queue behind each other instead of raising "database is
        # locked" and recording a spurious task failure (ADVICE r1).
        self._db = sqlite3.connect(self.db_path, timeout=60.0)
        try:
            self._db.execute("PRAGMA journal_mode=WAL")
        except sqlite3.DatabaseError:
            pass  # e.g. WAL unsupported on a network filesystem — fall back
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS dep_hash"
            " (task TEXT, path TEXT, hash TEXT, size INTEGER, mtime REAL,"
            "  PRIMARY KEY (task, path))"
        )
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS run_state"
            " (task TEXT PRIMARY KEY, ok INTEGER, seconds REAL, ts REAL)"
        )
        self._db.commit()

    # -- state ------------------------------------------------------------
    def _stored_deps(self, task: Task) -> Dict[str, tuple]:
        rows = self._db.execute(
            "SELECT path, hash, size, mtime FROM dep_hash WHERE task=?",
            (task.name,),
        ).fetchall()
        return {path: (h, size, mtime) for path, h, size, mtime in rows}

    def _record_success(self, task: Task, seconds: float) -> None:
        self._db.execute("DELETE FROM dep_hash WHERE task=?", (task.name,))
        for dep in task.file_dep:
            p = Path(dep)
            if p.exists():
                st = p.stat()
                self._db.execute(
                    "INSERT OR REPLACE INTO dep_hash VALUES (?,?,?,?,?)",
                    (task.name, str(p), _hash_file(p), st.st_size, st.st_mtime),
                )
        self._db.execute(
            "INSERT OR REPLACE INTO run_state VALUES (?,?,?,?)",
            (task.name, 1, seconds, time.time()),
        )
        self._db.commit()

    def is_up_to_date(self, task: Task) -> bool:
        row = self._db.execute(
            "SELECT ok FROM run_state WHERE task=?", (task.name,)
        ).fetchone()
        if not row or not row[0]:
            return False
        for tgt in task.targets:
            if not Path(tgt).exists():
                return False
        stored = self._stored_deps(task)
        for dep in task.file_dep:
            p = Path(dep)
            if not p.exists() or str(p) not in stored:
                return False
            h, size, mtime = stored[str(p)]
            st = p.stat()
            if st.st_size == size and st.st_mtime == mtime:
                continue  # metadata unchanged → trust the stored hash
            if h != _hash_file(p):
                return False
            # Content identical but metadata drifted (touch/copy): refresh
            # the metadata so the next check short-circuits again.
            self._db.execute(
                "UPDATE dep_hash SET size=?, mtime=? WHERE task=? AND path=?",
                (st.st_size, st.st_mtime, task.name, str(p)),
            )
            self._db.commit()
        for check in task.uptodate:
            if not check():
                return False
        # A task with nothing to compare is always stale (doit semantics for
        # bare tasks) unless an uptodate check said otherwise.
        if not task.targets and not list(task.file_dep) and not task.uptodate:
            return False
        return True

    def forget(self, names: Optional[Sequence[str]] = None) -> None:
        """Drop recorded state (doit ``forget``) for ``names`` or all."""
        for name in names or list(self.tasks):
            self._db.execute("DELETE FROM dep_hash WHERE task=?", (name,))
            self._db.execute("DELETE FROM run_state WHERE task=?", (name,))
        self._db.commit()

    def timings(self) -> Dict[str, float]:
        """Last SUCCESSFUL wall-clock seconds per task."""
        rows = self._db.execute(
            "SELECT task, seconds FROM run_state WHERE seconds IS NOT NULL"
        ).fetchall()
        return dict(rows)

    # -- execution --------------------------------------------------------
    def _toposort(self, names: Sequence[str]) -> List[str]:
        order: List[str] = []
        seen: Dict[str, int] = {}  # 0=visiting, 1=done

        def visit(name: str) -> None:
            if name not in self.tasks:
                raise KeyError(f"Unknown task: {name}")
            state = seen.get(name)
            if state == 1:
                return
            if state == 0:
                raise ValueError(f"Task dependency cycle at {name}")
            seen[name] = 0
            for dep in self.tasks[name].task_dep:
                visit(dep)
            seen[name] = 1
            order.append(name)

        for name in names:
            visit(name)
        return order

    @staticmethod
    def _consensus(flag: bool, reduce) -> bool:
        """Cross-process reduction of a local boolean; identity when the
        distributed runtime is not up.

        Two uses keep the engine's collective sequence aligned on pods:

        - STALENESS (``reduce=np.any``): the skip decision is per-process
          (local state DB, local clocks), but multi-host task actions
          contain cross-process BARRIERS (``tasks._primary_writes``) — if
          one process skips a task another runs, the runner deadlocks
          inside the action. If ANY process finds a task stale, everyone
          runs it (writes are process-0-gated, so redundant runs are
          compute-only).
        - SUCCESS (``reduce=np.all``): a one-sided failure must stop all
          processes together — the failed process makes no further
          collective calls, so survivors marching into the next staleness
          allgather would hang there, masking the real traceback.

        The single-process probe is ``distributed_client_active`` —
        NOT ``jax.process_count()``, which would initialize the XLA
        backends (pinning the platform, dialing remote runtimes) on the
        very first skip check of a plain local run.
        """
        from fm_returnprediction_tpu.parallel.multihost import (
            distributed_client_active,
        )

        if not distributed_client_active():
            return flag
        import jax

        if jax.process_count() == 1:
            return flag
        import numpy as _np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            _np.asarray([1 if flag else 0], _np.int32)
        )
        return bool(reduce(_np.asarray(flags)))

    def run(self, names: Optional[Sequence[str]] = None, force: bool = False) -> bool:
        """Run ``names`` (default: all tasks) and their deps. Returns True
        if everything succeeded."""
        import numpy as _np

        order = self._toposort(list(names or self.tasks))
        for name in order:
            task = self.tasks[name]
            stale = force or not self.is_up_to_date(task)
            if not self._consensus(stale, _np.any):
                self.reporter.skip(task)
                continue
            self.reporter.start(task)
            start = time.perf_counter()
            err = None
            try:
                for action in task.actions:
                    if isinstance(action, str):
                        subprocess.run(action, shell=True, check=True)
                    else:
                        action()
            except Exception as exc:  # noqa: BLE001 — report and halt
                err = exc
            if not self._consensus(err is None, _np.all):
                if err is None:  # a PEER failed; this process's task was fine
                    err = RuntimeError(
                        "task failed on another process (see its log)"
                    )
                self.reporter.fail(task, err)
                # Mark stale but PRESERVE the last successful timing — the
                # timing log is the wall-clock record, not the failure log.
                self._db.execute(
                    "INSERT INTO run_state VALUES (?,0,NULL,?)"
                    " ON CONFLICT(task) DO UPDATE SET ok=0, ts=excluded.ts",
                    (task.name, time.time()),
                )
                self._db.commit()
                return False
            seconds = time.perf_counter() - start
            self._record_success(task, seconds)
            self.reporter.done(task, seconds)
        return True

    def close(self) -> None:
        self._db.close()

    def __enter__(self) -> "TaskRunner":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def write_timing_log(runner: TaskRunner, path: Union[str, Path]) -> None:
    """Dump per-task timings as JSON (SURVEY §5: keep a per-task timing log
    since the headline metric is wall-clock)."""
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w") as f:
        json.dump(runner.timings(), f, indent=2, sort_keys=True)
