"""The Lewellen pipeline as a task graph.

Re-provides the reference's doit DAG (``dodo.py:115-206``: config →
convert/run notebooks → artifacts) with explicit data-stage tasks instead of
notebook subprocesses, and adds the dense-panel checkpoint between the
panel-build and report stages (SURVEY §5: the reference recomputes every
intermediate from raw parquet each run; the panel npz makes the FM/report
stage resumable on its own).

Stages (task name → targets):

- ``config``      → the `_data`/`_output` directory tree
  (reference ``task_config`` → ``settings.create_dirs``,
  ``dodo.py:115-122``, ``src/settings.py:96-105``)
- ``pull_data``   → the five raw parquet files (WRDS when credentials are
  configured, synthetic otherwise — the hermetic fake-WRDS backend)
- ``build_panel`` → ``lewellen_panel.npz`` + ``factors_dict.json`` in
  PROCESSED_DATA_DIR (the checkpoint)
- ``reports``     → Table 1/2 pickles + ``.tex`` + ``figure_1.pdf`` +
  ``data_saved.marker`` in OUTPUT_DIR (contract of ``save_data``,
  ``src/calc_Lewellen_2014.py:959-1005``)
- ``serve_state`` → ``serving_state.npz`` in PROCESSED_DATA_DIR — the
  warmed online-serving state (``serving.state``), rebuilt only when the
  panel checkpoint changes
- ``specgrid``    → ``specgrid_scenarios.csv`` in OUTPUT_DIR — the
  Gram-contraction robustness sweep (``specgrid.run_scenarios``)
- ``backtest``    → ``backtest.csv`` in OUTPUT_DIR — the rolling-origin
  backtest sweep on the Gram bank (``backtest.run_backtest_scenarios``)
- ``latex``       → compiled report PDF (``pdflatex`` run twice,
  continue-on-error, ``src/calc_Lewellen_2014.py:1197-1209``)

Run: ``python -m fm_returnprediction_tpu.taskgraph [task ...]``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import List, Optional

from fm_returnprediction_tpu.settings import config, create_dirs
from fm_returnprediction_tpu.taskgraph.engine import Task

__all__ = [
    "build_tasks", "build_notebook_tasks",
    "PANEL_FILE", "FACTORS_FILE", "SERVING_FILE", "SPECGRID_FILE",
    "BACKTEST_FILE",
]

PANEL_FILE = "lewellen_panel.npz"
FACTORS_FILE = "factors_dict.json"
SERVING_FILE = "serving_state.npz"
SPECGRID_FILE = "specgrid_scenarios.csv"
BACKTEST_FILE = "backtest.csv"


def _raw_paths(raw_dir: Path) -> List[Path]:
    from fm_returnprediction_tpu.pipeline import RAW_FILE_NAMES

    return [raw_dir / name for name in RAW_FILE_NAMES.values()]


BACKEND_MARKER = "_data_backend.txt"


def _is_primary() -> bool:
    """True on process 0 (or single-process). Multi-host taskgraph runs
    execute every task on every process — compute is replicated, but only
    one process may write shared-filesystem artifacts (same gating as
    ``run_pipeline``; concurrent multi-GB npz writes tear).

    The host-exchange identity (``parallel.distributed``) is consulted
    FIRST: it answers without initializing the XLA backends, and it is
    the only answer on a backend whose device collectives are missing
    (the CPU gap) — ``jax.process_index()`` remains the device-runtime
    path."""
    from fm_returnprediction_tpu.parallel import distributed as _dist

    if _dist.dist_active():
        return _dist.process_index() == 0
    import jax

    return jax.process_index() == 0


def _sync_processes(tag: str) -> None:
    """Barrier after a primary-only write so other processes cannot read a
    half-written artifact in the next task. No-op single-process.

    Transport ladder: the host exchange when armed (works on every
    backend, and its tag check turns a program-order divergence into a
    raise instead of a silent hang), else ``sync_global_devices`` (the
    device-collective path pods use)."""
    from fm_returnprediction_tpu.parallel import distributed as _dist

    ex = _dist.host_exchange()
    if ex is not None:
        ex.barrier(tag)
        return
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(tag)


def _primary_writes(tag: str, fn) -> None:
    """Run ``fn`` on process 0, then barrier everyone.

    The primary's exception is re-raised AFTER the barrier: raising before
    it would leave the other processes blocked in ``sync_global_devices``
    forever (a failed WRDS pull must fail the pod, not deadlock it).
    Non-primaries then fail fast downstream on the missing artifact."""
    err = None
    if _is_primary():
        try:
            fn()
        except BaseException as exc:  # noqa: BLE001 - re-raised below
            err = exc
    _sync_processes(tag)
    if err is not None:
        raise err


def _backend_name(synthetic: bool) -> str:
    return "synthetic" if synthetic else "wrds"


def _backend_matches(raw_dir: Path, synthetic: bool) -> bool:
    """Uptodate check: the cached raw data must come from the requested
    backend — without this, toggling --synthetic would silently reuse the
    other backend's parquet (targets exist, hashes unchanged)."""
    marker = raw_dir / BACKEND_MARKER
    return marker.exists() and marker.read_text().strip() == _backend_name(synthetic)


def _pull_data(raw_dir: Path, synthetic: bool, synthetic_config=None) -> None:
    """Multi-host: process 0 writes the raw caches (one WRDS pull, no torn
    parquet), everyone barriers before build_panel reads them."""
    _primary_writes(
        "pull_data_saved",
        lambda: _pull_data_primary(raw_dir, synthetic, synthetic_config),
    )


def _pull_data_primary(raw_dir: Path, synthetic: bool, synthetic_config=None) -> None:
    from fm_returnprediction_tpu.utils.cache import save_cache_data

    raw_dir.mkdir(parents=True, exist_ok=True)
    (raw_dir / BACKEND_MARKER).write_text(_backend_name(synthetic))
    if synthetic:
        from fm_returnprediction_tpu.data.synthetic import generate_synthetic_wrds
        from fm_returnprediction_tpu.pipeline import RAW_FILE_NAMES

        data = generate_synthetic_wrds(synthetic_config)
        for key, name in RAW_FILE_NAMES.items():
            save_cache_data(data[key], raw_dir, file_name=name)
        return

    from fm_returnprediction_tpu.data.wrds_pull import (
        pull_Compustat,
        pull_CRSP_Comp_link_table,
        pull_CRSP_index,
        pull_CRSP_stock,
    )

    user = config("WRDS_USERNAME")
    start, end = config("START_DATE"), config("END_DATE")
    pull_CRSP_stock(freq="D", start_date=start, end_date=end, wrds_username=user,
                    data_dir=raw_dir, file_name="CRSP_stock_d.parquet")
    pull_CRSP_stock(freq="M", start_date=start, end_date=end, wrds_username=user,
                    data_dir=raw_dir, file_name="CRSP_stock_m.parquet")
    pull_Compustat(start_date=start, end_date=end, wrds_username=user,
                   data_dir=raw_dir, file_name="Compustat_fund.parquet")
    pull_CRSP_Comp_link_table(wrds_username=user, data_dir=raw_dir,
                              file_name="CRSP_Comp_Link_Table.parquet")
    pull_CRSP_index(freq="D", start_date=start, end_date=end, wrds_username=user,
                    data_dir=raw_dir, file_name="CRSP_index_d.parquet")


def _guard_panel(panel, context: str, expect_dtype: bool = False) -> None:
    """Stage-boundary panel contract for the task graph (gated on the
    global ``FMRP_GUARD`` switch): a fail-severity violation raises the
    typed ``ContractViolationError``, which the engine's failure machinery
    records in its sqlite ledger like any other task failure — and
    ``keep_going`` runs keep disjoint subgraphs alive around it.

    ``expect_dtype`` pins the configured compute dtype — only at BUILD
    time (a checkpoint legitimately predates a dtype reconfiguration; the
    consumer tasks check structure, not provenance)."""
    from fm_returnprediction_tpu.guard import checks, contracts

    if not checks.guard_active():
        return
    dtype = None
    if expect_dtype:
        from fm_returnprediction_tpu.pipeline import resolve_dtype

        dtype = resolve_dtype()
    contracts.check_panel(panel, dtype=dtype, context=context)


def _build_panel(raw_dir: Path, processed_dir: Path) -> None:
    import os

    from fm_returnprediction_tpu.pipeline import load_or_build_panel
    from fm_returnprediction_tpu.utils.timing import trace

    # FMRP_TRACE=<dir> wraps the compute tasks in a jax.profiler trace
    # (SURVEY §5 tracing prescription; round-2 VERDICT item 8).
    # load_or_build_panel is checkpoint-aware (data.prepared), so a re-run
    # whose task state was invalidated but whose raw files are unchanged
    # still skips the host ingest; dtype resolves inside the shared entry.
    with trace(os.environ.get("FMRP_TRACE")):
        panel, factors_dict = load_or_build_panel(raw_dir)
    # contract boundary BEFORE the checkpoint write: a corrupted panel must
    # not become the trusted artifact every downstream task consumes
    _guard_panel(panel, "build_panel", expect_dtype=True)

    def save():
        panel.save(processed_dir / PANEL_FILE)
        with open(processed_dir / FACTORS_FILE, "w") as f:
            json.dump(factors_dict, f, indent=2)

    _primary_writes("build_panel_saved", save)


def _reports(processed_dir: Path, output_dir: Path) -> None:
    import os

    from fm_returnprediction_tpu.utils.timing import trace

    with trace(os.environ.get("FMRP_TRACE")):
        return _reports_traced(processed_dir, output_dir)


def _reports_traced(processed_dir: Path, output_dir: Path) -> None:
    from fm_returnprediction_tpu.panel.dense import DensePanel
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.reporting.deciles import (
        build_decile_table,
        save_decile_table,
    )
    from fm_returnprediction_tpu.reporting.figure1 import create_figure_1, figure_cs
    from fm_returnprediction_tpu.reporting.latex import save_data
    from fm_returnprediction_tpu.reporting.table1 import build_table_1
    from fm_returnprediction_tpu.reporting.table2 import build_table_2

    panel = DensePanel.load(processed_dir / PANEL_FILE)
    # the checkpoint passed its file checksum; the CONTRACT catches the
    # semantic corruptions a checksum cannot (the file faithfully stores
    # duplicated permnos too)
    _guard_panel(panel, "reports")
    with open(processed_dir / FACTORS_FILE) as f:
        factors_dict = json.load(f)
    masks = compute_subset_masks(panel)
    table_1 = build_table_1(panel, masks, factors_dict)
    from fm_returnprediction_tpu.parallel import pipeline_mesh

    # same mesh policy as run_pipeline: 2-D hierarchy on a pod, MESH_DEVICES
    # opt-in single-process
    table_2 = build_table_2(panel, masks, factors_dict, mesh=pipeline_mesh())
    from fm_returnprediction_tpu.guard import checks as _guard_checks
    from fm_returnprediction_tpu.guard import contracts as _contracts

    if _guard_checks.guard_active():
        _contracts.check_frame(table_1, "table_1")
        _contracts.check_frame(table_2, "table_2")
    cs_cache = {name: figure_cs(panel, m) for name, m in masks.items()}
    figure_1 = create_figure_1(panel, masks, cs_cache=cs_cache)
    decile_table = build_decile_table(panel, masks, cs_cache=cs_cache)

    def save():  # tables computed everywhere, written once
        save_data(table_1, table_2, figure_1, output_dir)
        save_decile_table(decile_table, output_dir)

    _primary_writes("reports_saved", save)


def _serve_state(processed_dir: Path) -> None:
    """Build and WARM the online-serving state from the panel checkpoint.

    The warm-up compiles every query bucket through the same
    ``BucketedExecutor`` the service uses, so the persistent XLA
    compilation cache (when enabled) already holds the serving programs
    when the first service process starts — build-and-warm is one task,
    not a query-time surprise."""
    from fm_returnprediction_tpu.panel.dense import DensePanel
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.serving.executor import BucketedExecutor
    from fm_returnprediction_tpu.serving.state import (
        build_serving_state_from_panel,
    )

    panel = DensePanel.load(processed_dir / PANEL_FILE)
    _guard_panel(panel, "serve_state")
    masks = compute_subset_masks(panel)
    state = build_serving_state_from_panel(panel, masks["All stocks"])

    from fm_returnprediction_tpu.guard import checks as _guard_checks
    from fm_returnprediction_tpu.guard import contracts as _contracts

    if _guard_checks.guard_active():
        # fail the TASK (typed, ledgered, keep_going-compatible) rather
        # than persist a state the service would have to quarantine
        _contracts.enforce(
            _contracts.evaluate(_contracts.serving_state_rules(), state),
            context="serve_state",
        )
    # the warm-up rides timed_aot_compile: with FMRP_REGISTRY_DIR armed
    # the bucket executables fetch from (or publish into) the registry's
    # executable plane, so a later serving replica starts compile-free
    BucketedExecutor(state).warmup()

    def _save() -> None:
        state.save(processed_dir / SERVING_FILE)
        from fm_returnprediction_tpu.registry import artifacts as _rart
        from fm_returnprediction_tpu.registry.store import active_registry

        if active_registry() is not None:
            # artifact-plane publish: warm_from_registry resolves the
            # state from here (fingerprint = the panel checkpoint's
            # content hash, so the entry answers "the state FOR this
            # panel"). Register the npz JUST saved above — re-serializing
            # through put_serving_state would write the multi-hundred-MB
            # bundle twice at real shape
            fp = _panel_content_fp(processed_dir / PANEL_FILE)
            _rart.put_files(
                _rart.SERVING_STATE_NAME, fp,
                [processed_dir / SERVING_FILE],
                meta={"n_months": int(state.n_months),
                      "n_predictors": int(state.n_predictors)},
            )

    _primary_writes("serve_state_saved", _save)


# (path, size, mtime_ns) → sha256[:32] of the panel checkpoint: the
# serve_state uptodate check and the publish both need the same content
# fingerprint, and the file is hundreds of MB at real shape — one hash
# per (file state, process), not one per consumer
_PANEL_FP_MEMO: dict = {}


def _panel_content_fp(panel: Path) -> str:
    from fm_returnprediction_tpu.registry.integrity import file_sha256

    st = panel.stat()
    key = (str(panel), st.st_size, st.st_mtime_ns)
    hit = _PANEL_FP_MEMO.get(key)
    if hit is None:
        hit = file_sha256(panel)[:32]
        _PANEL_FP_MEMO.clear()  # one live panel per process is the shape
        _PANEL_FP_MEMO[key] = hit
    return hit


def _serve_state_registry_current(processed_dir: Path) -> bool:
    """``uptodate`` component for the serve_state task: with the registry
    armed, the task's effective target set also includes the
    artifact-plane serving-state entry for the CURRENT panel checkpoint —
    a newly armed (or emptied/foreign) registry makes the task stale, so
    ``--registry-dir`` on an up-to-date DAG publishes instead of silently
    no-opping (the same knob-staleness contract as the specgrid sidecar
    below). Registry off, or panel checkpoint absent (the file_dep
    machinery owns that case): no opinion, report current."""
    from fm_returnprediction_tpu.registry import artifacts as _rart
    from fm_returnprediction_tpu.registry.store import active_registry

    reg = active_registry()
    panel = processed_dir / PANEL_FILE
    if reg is None or not panel.exists():
        return True
    return _rart.get_entry_dir(
        _rart.SERVING_STATE_NAME, _panel_content_fp(panel), registry=reg
    ) is not None


SPECGRID_KNOBS_FILE = "specgrid_scenarios.knobs.json"


def _specgrid_effective_knobs(cells: Optional[int],
                              sink: Optional[str],
                              estimator: Optional[str] = None) -> dict:
    """The knobs that shape the artifact: cell count + RESOLVED sink name
    (CLI argument or ``FMRP_SPECGRID_SINK``) + RESOLVED estimator cell
    (``--specgrid-estimator`` or ``FMRP_SPECGRID_ESTIMATOR`` — a
    partialled/absorbed/IV frame must never be served as an OLS one).
    Tile width is excluded deliberately; tiling is pinned bit-identical
    on the frame."""
    from fm_returnprediction_tpu.specgrid.estimators import (
        resolve_estimator,
    )
    from fm_returnprediction_tpu.specgrid.sinks import resolve_sink_name

    est = resolve_estimator(estimator)
    return {"cells": cells, "sink": resolve_sink_name(sink),
            "estimator": f"{est.label}@{est.se}"}


def _specgrid_knobs_unchanged(output_dir: Path, cells: Optional[int],
                              sink: Optional[str],
                              estimator: Optional[str] = None) -> bool:
    """``uptodate`` check: the cached CSV only counts as current when the
    knobs it was BUILT under (sidecar written by ``_specgrid``) match this
    invocation's effective knobs — a knob change in either direction
    (default→topk or topk→default) re-runs; without this, a leaderboard
    CSV would be served as the tidy scenario frame by a later default
    run. A missing sidecar reads as a default-knob build (pre-sidecar
    artifacts were only ever default)."""
    want = _specgrid_effective_knobs(cells, sink, estimator)
    try:
        with open(Path(output_dir) / SPECGRID_KNOBS_FILE) as f:
            have = json.load(f)
    except (OSError, ValueError):
        have = {"cells": None, "sink": "frame"}
    have.setdefault("estimator", "ols@nw")
    return have == want


def _specgrid(processed_dir: Path, output_dir: Path,
              cells: Optional[int] = None,
              sink: Optional[str] = None,
              estimator: Optional[str] = None) -> None:
    """Panel checkpoint → spec-grid robustness sweep CSV.

    Runs the scenario grids (``specgrid.run_scenarios``: subperiod halves
    × the three size universes × all models) through the lazy tile engine
    and writes the sink's result frame. ``cells`` scales the sweep to a
    pod-scale cell count via bootstrap draws; ``sink`` picks the streaming
    aggregation; ``estimator`` (``--specgrid-estimator`` /
    ``FMRP_SPECGRID_ESTIMATOR`` grammar, e.g. ``"fwl:beme@iid"``) runs
    the sweep under an estimator-subsystem cell instead of OLS@NW (rows
    then carry estimator/se_family disclosure columns). Compute is
    replicated on every process (same contract as ``_reports``); only
    the primary writes."""
    from fm_returnprediction_tpu.panel.dense import DensePanel
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.specgrid import run_scenarios
    from fm_returnprediction_tpu.specgrid.estimators import (
        resolve_estimator,
    )

    panel = DensePanel.load(processed_dir / PANEL_FILE)
    _guard_panel(panel, "specgrid")
    with open(processed_dir / FACTORS_FILE) as f:
        factors_dict = json.load(f)
    masks = compute_subset_masks(panel)
    est = resolve_estimator(estimator)
    estimators = None if (est.kind == "ols" and est.se == "nw") else (est,)
    frame = run_scenarios(panel, masks, factors_dict, cells=cells,
                          sink=sink, output_dir=output_dir,
                          estimators=estimators)

    from fm_returnprediction_tpu.guard import checks as _guard_checks
    from fm_returnprediction_tpu.guard import contracts as _contracts
    from fm_returnprediction_tpu.specgrid.sinks import resolve_sink_name

    if _guard_checks.guard_active() and resolve_sink_name(sink) == "frame":
        # non-frame sinks (argument- OR env-selected) emit their own
        # schema (leaderboard, moment table, part manifest) — only the
        # tidy frame carries the contract
        _contracts.check_frame(frame, "specgrid_scenarios")
    output_dir.mkdir(parents=True, exist_ok=True)

    def _save() -> None:
        frame.to_csv(output_dir / SPECGRID_FILE, index=False)
        # sidecar: the knobs this artifact was built under, read by the
        # task's uptodate check (_specgrid_knobs_unchanged)
        with open(output_dir / SPECGRID_KNOBS_FILE, "w") as f:
            json.dump(_specgrid_effective_knobs(cells, sink, estimator), f)

    _primary_writes("specgrid_saved", _save)


BACKTEST_KNOBS_FILE = "backtest.knobs.json"


def _backtest_effective_knobs(schemes: Optional[str],
                              route: Optional[str],
                              quantiles: Optional[int],
                              sink: Optional[str]) -> dict:
    """The knobs that shape the backtest artifact, RESOLVED the same way
    the sweep resolves them (argument > ``FMRP_BACKTEST_*`` env >
    default) — a route change swaps the program family, a scheme or
    quantile change changes every number, a sink change changes the
    schema. Tile width is excluded (tiling is pinned bit-identical)."""
    from fm_returnprediction_tpu.backtest import (
        resolve_backtest_route,
        resolve_backtest_sink_name,
        resolve_quantiles,
        resolve_schemes,
    )

    return {
        "schemes": [name for name, _ in resolve_schemes(schemes)],
        "route": resolve_backtest_route(route),
        "quantiles": resolve_quantiles(quantiles),
        "sink": resolve_backtest_sink_name(sink),
    }


def _backtest_knobs_unchanged(output_dir: Path,
                              schemes: Optional[str],
                              route: Optional[str],
                              quantiles: Optional[int],
                              sink: Optional[str]) -> bool:
    """``uptodate`` check (the specgrid sidecar pattern): the cached CSV
    only counts as current when the knobs it was BUILT under match this
    invocation's effective knobs — a change in either direction re-runs.
    A missing sidecar reads as a default-knob build."""
    want = _backtest_effective_knobs(schemes, route, quantiles, sink)
    try:
        with open(Path(output_dir) / BACKTEST_KNOBS_FILE) as f:
            have = json.load(f)
    except (OSError, ValueError):
        have = _default_backtest_knobs()
    return have == want


def _default_backtest_knobs() -> dict:
    """What a pre-sidecar artifact must be assumed to be: built under the
    library defaults, NOT under whatever env happens to be set now."""
    from fm_returnprediction_tpu.backtest.paths import (
        DEFAULT_QUANTILES,
        DEFAULT_SCHEMES,
    )

    return {
        "schemes": [s.strip() for s in DEFAULT_SCHEMES.split(",")],
        "route": "auto",
        "quantiles": DEFAULT_QUANTILES,
        "sink": "frame",
    }


def _backtest(processed_dir: Path, output_dir: Path,
              schemes: Optional[str] = None,
              route: Optional[str] = None,
              quantiles: Optional[int] = None,
              sink: Optional[str] = None) -> None:
    """Panel checkpoint → rolling-origin backtest sweep CSV.

    Contracts the scenario panel once into a Gram bank, then answers the
    scheme × model × universe × weighting backtest product from it
    (``backtest.run_backtest_scenarios`` — coefficient paths via the
    prefix-sum scan route, quantile portfolios, OOS R²/IC/spread/turnover
    per cell). Compute is replicated on every process (same contract as
    ``_reports``); only the primary writes."""
    from fm_returnprediction_tpu.backtest import run_backtest_scenarios
    from fm_returnprediction_tpu.backtest.sinks import (
        resolve_backtest_sink_name,
    )
    from fm_returnprediction_tpu.panel.dense import DensePanel
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks

    panel = DensePanel.load(processed_dir / PANEL_FILE)
    _guard_panel(panel, "backtest")
    with open(processed_dir / FACTORS_FILE) as f:
        factors_dict = json.load(f)
    masks = compute_subset_masks(panel)
    frame = run_backtest_scenarios(
        panel, masks, factors_dict, schemes=schemes, route=route,
        n_quantiles=quantiles, sink=sink, output_dir=output_dir,
    )

    from fm_returnprediction_tpu.guard import checks as _guard_checks
    from fm_returnprediction_tpu.guard import contracts as _contracts

    if _guard_checks.guard_active() \
            and resolve_backtest_sink_name(sink) == "frame":
        _contracts.enforce(
            _contracts.evaluate(_contracts.backtest_rules(), frame),
            context="backtest",
        )
    output_dir.mkdir(parents=True, exist_ok=True)

    def _save() -> None:
        frame.to_csv(output_dir / BACKTEST_FILE, index=False)
        # sidecar: the knobs this artifact was built under, read by the
        # task's uptodate check (_backtest_knobs_unchanged)
        with open(output_dir / BACKTEST_KNOBS_FILE, "w") as f:
            json.dump(_backtest_effective_knobs(
                schemes, route, quantiles, sink), f)

    _primary_writes("backtest_saved", _save)


def _parity(raw_dir: Path, output_dir: Path) -> None:
    """Real-cache Table 1 vs the published Lewellen oracle; records the full
    diff, then raises on any out-of-tolerance cell."""
    from fm_returnprediction_tpu.reporting.published import run_parity_check

    output_dir.mkdir(parents=True, exist_ok=True)
    diff = run_parity_check(raw_dir, strict=False)
    # diff computed everywhere, report written once
    _primary_writes(
        "parity_saved",
        lambda: diff.to_csv(output_dir / "parity_report.csv", index=False),
    )
    bad = diff[~diff["ok"]]
    if len(bad):
        raise AssertionError(
            f"Table 1 parity failed on {len(bad)} of {len(diff)} cells "
            f"(see {output_dir / 'parity_report.csv'}):\n"
            + bad.to_string(index=False)
        )


def _latex(output_dir: Path) -> None:
    from fm_returnprediction_tpu.reporting.latex import (
        compile_latex_document,
        create_latex_document,
    )

    if not _is_primary():  # one pdflatex, not one per host
        return
    tex = create_latex_document(output_dir)
    if tex is not None:
        compile_latex_document(tex)


def build_tasks(
    synthetic: bool = False,
    synthetic_config=None,
    raw_dir: Optional[Path] = None,
    processed_dir: Optional[Path] = None,
    output_dir: Optional[Path] = None,
    specgrid_cells: Optional[int] = None,
    specgrid_sink: Optional[str] = None,
    specgrid_estimator: Optional[str] = None,
    backtest_schemes: Optional[str] = None,
    backtest_route: Optional[str] = None,
    backtest_quantiles: Optional[int] = None,
    backtest_sink: Optional[str] = None,
) -> List[Task]:
    """Assemble the DAG against the configured directory tree."""
    raw_dir = Path(raw_dir or config("RAW_DATA_DIR"))
    processed_dir = Path(processed_dir or config("PROCESSED_DATA_DIR"))
    output_dir = Path(output_dir or config("OUTPUT_DIR"))
    raw = _raw_paths(raw_dir)

    return [
        Task(
            name="config",
            actions=[create_dirs],
            targets=[raw_dir, processed_dir, output_dir],
            doc="Create the _data/_output directory tree",
        ),
        Task(
            name="pull_data",
            actions=[lambda: _pull_data(raw_dir, synthetic, synthetic_config)],
            targets=raw,
            task_dep=["config"],
            uptodate=[lambda: _backend_matches(raw_dir, synthetic)],
            doc="Pull WRDS data (or generate the synthetic universe)",
        ),
        Task(
            name="build_panel",
            actions=[lambda: _build_panel(raw_dir, processed_dir)],
            file_dep=raw,
            targets=[processed_dir / PANEL_FILE, processed_dir / FACTORS_FILE],
            task_dep=["pull_data"],
            doc="Raw parquet → dense characteristic panel checkpoint",
        ),
        Task(
            name="reports",
            actions=[lambda: _reports(processed_dir, output_dir)],
            file_dep=[processed_dir / PANEL_FILE, processed_dir / FACTORS_FILE],
            targets=[
                output_dir / "table_1.pkl",
                output_dir / "table_2.pkl",
                output_dir / "figure_1.pdf",
                output_dir / "decile_sorts.pkl",
                output_dir / "data_saved.marker",
            ],
            task_dep=["build_panel"],
            doc="Panel checkpoint → Table 1/2, Figure 1, artifacts",
        ),
        Task(
            name="serve_state",
            actions=[lambda: _serve_state(processed_dir)],
            # depends on the ONE fitted artifact it reads — the panel
            # checkpoint — so the warmed state rebuilds only when that
            # changes (a factors-only refresh must not re-warm)
            file_dep=[processed_dir / PANEL_FILE],
            targets=[processed_dir / SERVING_FILE],
            task_dep=["build_panel"],
            # registry-aware staleness: an armed registry missing this
            # panel's serving-state entry re-runs the task (publish),
            # instead of --registry-dir silently no-opping on an
            # up-to-date DAG
            uptodate=[
                lambda: _serve_state_registry_current(processed_dir)
            ],
            doc="Panel checkpoint → warmed online-serving state",
        ),
        Task(
            name="specgrid",
            actions=[lambda: _specgrid(processed_dir, output_dir,
                                       cells=specgrid_cells,
                                       sink=specgrid_sink,
                                       estimator=specgrid_estimator)],
            # reads only the panel checkpoint — a reports-only refresh
            # must not re-run the scenario sweep
            file_dep=[processed_dir / PANEL_FILE, processed_dir / FACTORS_FILE],
            targets=[output_dir / SPECGRID_FILE],
            task_dep=["build_panel"],
            # knob-aware staleness: the artifact only counts as current
            # when the knobs it was built under (sidecar) match this
            # invocation's effective --specgrid-cells/--specgrid-sink/
            # FMRP_SPECGRID_SINK — a change in EITHER direction re-runs
            uptodate=[
                lambda: _specgrid_knobs_unchanged(
                    output_dir, specgrid_cells, specgrid_sink,
                    specgrid_estimator,
                )
            ],
            doc="Panel checkpoint → Gram spec-grid robustness sweep CSV",
        ),
        Task(
            name="backtest",
            actions=[lambda: _backtest(processed_dir, output_dir,
                                       schemes=backtest_schemes,
                                       route=backtest_route,
                                       quantiles=backtest_quantiles,
                                       sink=backtest_sink)],
            # reads only the panel checkpoint + factors — a reports-only
            # refresh must not re-run the backtest sweep
            file_dep=[processed_dir / PANEL_FILE, processed_dir / FACTORS_FILE],
            targets=[output_dir / BACKTEST_FILE],
            task_dep=["build_panel"],
            # knob-aware staleness (the specgrid sidecar pattern): the
            # artifact only counts as current when the knobs it was built
            # under match this invocation's effective --backtest-*/
            # FMRP_BACKTEST_* knobs — a change in EITHER direction re-runs
            uptodate=[
                lambda: _backtest_knobs_unchanged(
                    output_dir, backtest_schemes, backtest_route,
                    backtest_quantiles, backtest_sink,
                )
            ],
            doc="Panel checkpoint → rolling-origin backtest sweep CSV",
        ),
        Task(
            name="latex",
            actions=[lambda: _latex(output_dir)],
            file_dep=[output_dir / "table_1.pkl", output_dir / "table_2.pkl"],
            task_dep=["reports"],
            doc="Generate + compile the LaTeX report",
        ),
    ] + (
        [] if synthetic else [
            Task(
                name="parity",
                actions=[lambda: _parity(raw_dir, output_dir)],
                file_dep=raw,
                targets=[output_dir / "parity_report.csv"],
                task_dep=["pull_data"],
                doc="Assert Table 1 parity against the published Lewellen oracle",
            ),
        ]
    )


def _notebook_paths(notebooks_dir: Path) -> List[Path]:
    """Auto-discover driver notebooks (reference ``dodo.py:132-137``)."""
    return sorted(Path(notebooks_dir).glob("*.ipynb"))


def build_notebook_tasks(
    notebooks_dir: Optional[Path] = None,
    output_dir: Optional[Path] = None,
    docs_dir: Optional[Path] = None,
) -> List[Task]:
    """Notebook conversion/execution tasks (reference ``dodo.py:140-206``,
    docs copy ``:257-300``), gated on nbconvert being importable.

    - ``convert_notebooks``: each notebook → a cleared ``.py`` script under
      OUTPUT_DIR/notebooks (the reference's change-detection artifact);
    - ``run_notebooks``: execute in place to OUTPUT_DIR and render HTML,
      copied into ``docs/notebooks`` for a static site.
    """
    try:
        import nbconvert  # noqa: F401
    except ImportError:  # pragma: no cover - environment-dependent
        return []

    notebooks_dir = Path(notebooks_dir or config("BASE_DIR") / "notebooks")
    output_dir = Path(output_dir or config("OUTPUT_DIR"))
    docs_dir = Path(docs_dir or config("BASE_DIR") / "docs" / "notebooks")
    notebooks = _notebook_paths(notebooks_dir)
    if not notebooks:
        return []

    script_dir = output_dir / "notebooks"
    scripts = [script_dir / f"{nb.stem}.py" for nb in notebooks]
    html = [output_dir / f"{nb.stem}.html" for nb in notebooks]

    import shlex

    q = shlex.quote
    convert_cmds = [
        f"jupyter nbconvert --to script --output-dir {q(str(script_dir))} {q(str(nb))}"
        for nb in notebooks
    ]
    run_cmds = [
        f"jupyter nbconvert --execute --to html --output-dir {q(str(output_dir))} {q(str(nb))}"
        for nb in notebooks
    ]

    def _copy_docs() -> None:
        import shutil

        docs_dir.mkdir(parents=True, exist_ok=True)
        for page in html:
            if page.exists():
                shutil.copy2(page, docs_dir / page.name)

    def _build_site() -> None:
        from fm_returnprediction_tpu.taskgraph.docs_site import build_docs_site

        base = Path(config("BASE_DIR"))
        build_docs_site(base, base / "docs" / "site")

    base_dir = Path(config("BASE_DIR"))
    site_sources = [p for p in [base_dir / "README.md"] if p.is_file()]
    site_sources += sorted((base_dir / "docs").glob("*.md"))
    try:
        import markdown  # noqa: F401

        have_markdown = True
    except ImportError:  # pragma: no cover - environment-dependent
        have_markdown = False

    tasks = [
        Task(
            name="convert_notebooks",
            actions=convert_cmds,
            file_dep=notebooks,
            targets=scripts,
            doc="Notebooks → cleared scripts (change detection)",
        ),
        Task(
            name="run_notebooks",
            # Depend on the CLEARED scripts, not the raw .ipynb: output and
            # metadata churn in a notebook must not re-trigger execution
            # (the reference's change-detection contract, dodo.py:191-193).
            actions=run_cmds + [_copy_docs],
            file_dep=scripts,
            targets=html,
            task_dep=["convert_notebooks"],
            doc="Execute driver notebooks, render HTML into docs",
        ),
    ]
    if have_markdown:  # skip the site task where the renderer is absent
        tasks.append(
            Task(
                name="docs_site",
                actions=[_build_site],
                # depend on the rendered SOURCES too, not just the notebook
                # HTML — an edited README must rebuild the site
                file_dep=html + site_sources,
                targets=[base_dir / "docs" / "site" / "index.html"],
                task_dep=["run_notebooks"],
                doc="Render markdown docs + notebook HTML into a static site "
                    "(reference docs_src/conf.py equivalent)",
            )
        )
    return tasks
