"""Artifact plane: one schema-versioned store for fitted artifacts.

The pipeline's array/frame products historically flowed through ad-hoc
paths — ``serving_state.npz`` wherever the caller pointed, specgrid
frames as loose CSV/parquet, audit manifests under ``--audit-dir`` — each
with its own (or no) integrity story. The artifact plane gives them one
address (``<registry>/artifacts/<name>/<fingerprint>/``) and ONE
integrity layer: every entry's ``meta.json`` carries the
:mod:`.integrity` sha256+size manifest over its payload files, the same
manifest shape the guard audit and prepared checkpoint already use.

``fingerprint`` is the caller's data-provenance key (the pipeline passes
its ``_pipeline_fingerprint``), so an entry answers "the serving state
FOR this panel+dtype+raw-data", not just "a serving state". ``latest``
resolution (newest entry by write time) serves the warm-pool path, where
a fresh replica wants "whatever the last publish was".
"""

from __future__ import annotations

import time
import warnings
from pathlib import Path
from typing import List, Optional, Union

from fm_returnprediction_tpu.registry import integrity
from fm_returnprediction_tpu.registry.store import Registry, active_registry

__all__ = [
    "put_files",
    "put_serving_state",
    "get_entry_dir",
    "get_file",
    "load_serving_state",
    "list_entries",
]

SERVING_STATE_NAME = "serving_state"
SERVING_STATE_FILE = "serving_state.npz"


def put_files(
    name: str,
    fingerprint: str,
    paths: List[Union[Path, str]],
    registry: Optional[Registry] = None,
    meta: Optional[dict] = None,
) -> Optional[Path]:
    """Register existing payload files as one artifact entry (copied in,
    manifest built, meta published last). Returns the entry dir, or None
    when the registry is off or the write failed (warned — artifact
    registration is an accelerant, never a correctness gate)."""
    registry = registry or active_registry()
    if registry is None:
        return None
    try:
        import jax

        if jax.process_index() != 0:
            return None  # one writer per pod
        return registry.write_entry_from_paths(
            registry.artifact_dir(name, fingerprint),
            [Path(p) for p in paths],
            {
                "kind": "artifact",
                "name": name,
                "fingerprint": str(fingerprint),
                "files": [Path(p).name for p in paths],
                "created_at": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                ),
                **(meta or {}),
            },
        )
    except Exception as exc:  # noqa: BLE001 — see docstring
        warnings.warn(
            f"artifact registration failed for {name!r} ({exc!r})",
            stacklevel=2,
        )
        return None


def put_serving_state(
    state,
    fingerprint: str,
    registry: Optional[Registry] = None,
) -> Optional[Path]:
    """Publish a fitted ``ServingState`` into the artifact plane (saved
    via its own no-pickle npz contract, then registered)."""
    registry = registry or active_registry()
    if registry is None:
        return None
    import tempfile

    try:
        with tempfile.TemporaryDirectory() as td:
            path = state.save(Path(td) / SERVING_STATE_FILE)
            return put_files(
                SERVING_STATE_NAME, fingerprint, [path], registry=registry,
                meta={"n_months": int(state.n_months),
                      "n_predictors": int(state.n_predictors)},
            )
    except Exception as exc:  # noqa: BLE001 — accelerant, never a gate
        warnings.warn(
            f"serving-state registration failed ({exc!r})", stacklevel=2
        )
        return None


def list_entries(
    name: str, registry: Optional[Registry] = None
) -> List[Path]:
    """Readable entries for one artifact name, oldest → newest by
    recorded write time (torn/schema-skewed entries excluded)."""
    registry = registry or active_registry()
    if registry is None:
        return []
    root = registry.artifacts_root / name
    if not root.is_dir():
        return []
    stamped = []
    for entry in root.iterdir():
        meta = registry.read_meta(entry)
        if meta is not None:
            stamped.append((meta.get("created_at") or "", entry))
    return [e for _, e in sorted(stamped)]


def get_entry_dir(
    name: str,
    fingerprint: Optional[str] = None,
    registry: Optional[Registry] = None,
) -> Optional[Path]:
    """One artifact entry: by exact fingerprint, else the newest readable
    entry. None when absent (callers rebuild)."""
    registry = registry or active_registry()
    if registry is None:
        return None
    if fingerprint is not None:
        entry = registry.artifact_dir(name, str(fingerprint))
        return entry if registry.read_meta(entry) is not None else None
    entries = list_entries(name, registry=registry)
    return entries[-1] if entries else None


def get_file(
    name: str,
    filename: str,
    fingerprint: Optional[str] = None,
    registry: Optional[Registry] = None,
    deep: bool = False,
) -> Optional[Path]:
    """Resolve one payload file inside an artifact entry, verified
    against the entry manifest (size always; content hash when ``deep``).
    Corruption surfaces as the typed ``CorruptArtifactError`` — the
    caller's rebuild contract, same as every checkpoint path."""
    registry = registry or active_registry()
    if registry is None:
        return None
    entry = get_entry_dir(name, fingerprint, registry=registry)
    if entry is None:
        return None
    meta = registry.read_meta(entry) or {}
    path = entry / filename
    manifest_rec = meta.get("manifest", {}).get(filename)
    if manifest_rec is None:
        raise integrity.CorruptArtifactError(
            f"artifact {name}/{entry.name} has no manifest entry for "
            f"{filename}"
        )
    integrity.verify_entry(path, manifest_rec, deep=deep)
    return path


def load_serving_state(
    fingerprint: Optional[str] = None,
    registry: Optional[Registry] = None,
):
    """The registered ``ServingState`` (by fingerprint, else newest), or
    None when the plane holds none. Bundle-level corruption raises the
    bundle's own typed error (``utils.cache.load_array_bundle``)."""
    path = get_file(
        SERVING_STATE_NAME, SERVING_STATE_FILE, fingerprint,
        registry=registry,
    )
    if path is None:
        return None
    from fm_returnprediction_tpu.serving.state import ServingState

    return ServingState.load(path)
