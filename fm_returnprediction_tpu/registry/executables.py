"""Executable plane: serialized AOT-compiled programs, fetch-not-compile.

At real shape a cold process pays lowering+compile for every program the
warm process already owns (BENCH_r05: 203 s cold vs 89 s warm) — and the
persistent XLA cache only shortens the *compile* half, per process, after
a lowering/trace it still pays. This plane closes the rest of the gap:
``telemetry.perf.timed_aot_compile`` (the one AOT entry the serving
bucket programs, the specgrid fused program, and the panel
characteristics program share) first asks the registry for the finished
executable and only lowers+compiles on a miss, storing the result for
the next process.

Key. An entry is addressed by a digest over:

- the logical ``program`` name and its shape/dtype/static ``signature``
  (what jit would key on);
- the ENVIRONMENT: backend platform + device kind, jax/jaxlib versions,
  and the x64 flag — a compiled executable is an opaque device binary,
  valid only for the stack that produced it;
- a CODE SALT: one hash over every ``.py`` file in this package — the
  conservative stand-in for a per-program jaxpr fingerprint that needs
  NO trace to compute, so a registry HIT costs zero traces and zero
  compiles. Any source change invalidates every entry (a fresh compile
  and re-store, not a stale answer). The store path, which has the
  lowered module in hand anyway, additionally records the true module
  fingerprint (``jaxpr_sha256``) in the entry meta for disclosure.

Payload. ``jax.experimental.serialize_executable`` (un)flattens the
``Compiled`` object; the payload is a pickle, so entries are loaded ONLY
after the meta's sha256+size manifest verifies DEEP (the registry is a
trusted local cache directory, same trust level as the persistent XLA
cache it layers on).

Degradation. Every failure — absent entry, torn meta, manifest
mismatch, deserialize error, version skew — returns ``None`` and the
caller compiles fresh; the miss and its reason are disclosed in the cost
ledger / metrics, never raised.
"""

from __future__ import annotations

import hashlib
import json
import pickle
import threading
import time
import warnings
from pathlib import Path
from typing import NamedTuple, Optional

from fm_returnprediction_tpu.registry import integrity
from fm_returnprediction_tpu.registry.store import Registry, active_registry

__all__ = [
    "environment_key",
    "code_salt",
    "executable_key",
    "store_executable",
    "load_executable",
    "LoadedExecutable",
]

PAYLOAD_FILE = "payload.bin"

_SALT_LOCK = threading.Lock()
_SALT: Optional[str] = None


def environment_key() -> dict:
    """The fields an executable is only valid under. ``unknown`` entries
    (no devices yet, exotic jax) still key consistently — two processes in
    the same container agree, which is the contract that matters."""
    import jax

    try:
        dev = jax.devices()[0]
        backend, device_kind = dev.platform, dev.device_kind
    except Exception:  # noqa: BLE001 — keying must never break a compile
        backend, device_kind = "unknown", "unknown"
    try:
        import jaxlib

        jaxlib_version = jaxlib.__version__
    except Exception:  # noqa: BLE001
        jaxlib_version = "unknown"
    return {
        "backend": backend,
        "device_kind": device_kind,
        "jax": jax.__version__,
        "jaxlib": jaxlib_version,
        "x64": bool(jax.config.jax_enable_x64),
    }


def code_salt() -> str:
    """One digest over every ``.py`` source file in this package,
    memoized per process (~1-2 MB of reads, once). The crude-but-safe
    jaxpr stand-in: any code change — kernel math, masking discipline,
    static-arg plumbing — invalidates every stored executable, trading
    occasional unnecessary recompiles for the impossibility of a stale
    executable answering with old math."""
    global _SALT
    if _SALT is None:
        with _SALT_LOCK:
            if _SALT is None:
                pkg_root = Path(__file__).resolve().parent.parent
                _SALT = integrity.hash_files(pkg_root.rglob("*.py"))
    return _SALT


def executable_key(program: str, signature: str) -> str:
    """Content address of one executable entry (the entry directory
    name): digest over program, signature, environment, and code salt."""
    payload = json.dumps(
        {
            "program": program,
            "signature": signature,
            "env": environment_key(),
            "code_salt": code_salt(),
        },
        sort_keys=True,
    )
    return hashlib.sha256(payload.encode()).hexdigest()[:32]


class LoadedExecutable(NamedTuple):
    """The fetch result: the live executable, its entry meta, and the
    verify+deserialize wall seconds (the ledger's ``compile_s`` twin)."""

    compiled: object
    meta: dict
    load_s: float


def _module_text(lowered, compiled) -> Optional[str]:
    """The program's module text (StableHLO from the lowering when the
    caller has it, else the compiled HLO); None when neither prints."""
    for obj in (lowered, compiled):
        if obj is None:
            continue
        try:
            return obj.as_text()
        except Exception:  # noqa: BLE001 — printing is best-effort
            continue
    return None


def _cpu_unserializable(text: Optional[str]) -> bool:
    """True when a CPU executable must NOT be stored: XLA CPU lowers
    linalg (eigh/qr/svd — LAPACK) and several other ops to CUSTOM CALLS
    whose serialized form embeds raw host function POINTERS, valid only
    in the producing process (ASLR) — a consumer process calling one
    segfaults. TPU custom calls resolve by name in the runtime and are
    unaffected. Unknown module text is treated as unserializable on CPU
    (a skipped store costs a persistent-cache compile; a bad store costs
    a crash)."""
    return text is None or "custom_call" in text or "custom-call" in text


def _jaxpr_sha256(text: Optional[str]) -> Optional[str]:
    """Fingerprint of the module text (disclosure field, computed on the
    STORE path only — the fetch path never lowers)."""
    if text is None:
        return None
    return hashlib.sha256(text.encode()).hexdigest()


def store_executable(
    program: str,
    signature: str,
    compiled,
    registry: Optional[Registry] = None,
    bucket: Optional[int] = None,
    lowered=None,
    compile_s: Optional[float] = None,
) -> Optional[Path]:
    """Serialize ``compiled`` into the registry; returns the entry dir, or
    None when the registry is off / the program is unserializable / the
    write failed (warned, never raised — the caller already holds a
    working executable, persistence is an accelerant)."""
    registry = registry or active_registry()
    if registry is None:
        return None
    try:
        import jax

        if jax.process_index() != 0:
            return None  # one writer per pod; peers fetch
        env = environment_key()
        text = _module_text(lowered, compiled)
        if env["backend"] == "cpu" and _cpu_unserializable(text):
            # disclosed skip, not a failure: the program still rides the
            # persistent XLA cache; storing it would hand the next
            # process a pointer-baked executable that segfaults
            from fm_returnprediction_tpu.telemetry import metrics as _m

            _m.registry().counter(
                "fmrp_registry_store_skipped_total",
                help="executables not stored (CPU custom-call programs "
                     "serialize host pointers; see registry.executables)",
                program=program,
            ).inc()
            return None
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(compiled)
        blob = pickle.dumps((payload, in_tree, out_tree))
        meta = {
            "kind": "executable",
            "program": program,
            "signature": signature,
            "bucket": bucket,
            "created_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "jaxpr_sha256": _jaxpr_sha256(text),
            # the lowering+compile seconds this entry cost at store time —
            # every later fetch reports them as its saved_s (the bench's
            # compile-seconds-saved series)
            "compile_s": round(compile_s, 6) if compile_s is not None
            else None,
            **env,
        }
        entry = registry.executable_dir(executable_key(program, signature))
        registry.write_entry(entry, {PAYLOAD_FILE: blob}, meta)
        return entry
    except Exception as exc:  # noqa: BLE001 — see docstring
        warnings.warn(
            f"registry store failed for {program!r} ({exc!r}); "
            "the compiled program is unaffected",
            stacklevel=2,
        )
        return None


def load_executable(
    program: str,
    signature: str,
    registry: Optional[Registry] = None,
) -> Optional[LoadedExecutable]:
    """Fetch one executable: key lookup → DEEP manifest verification →
    environment check → deserialize. Any failure returns None (fresh
    compile); corruption additionally drops the entry so the next process
    does not re-pay the failed verification."""
    registry = registry or active_registry()
    if registry is None:
        return None
    entry = registry.executable_dir(executable_key(program, signature))
    meta = registry.read_meta(entry)
    if meta is None:
        return None
    env = environment_key()
    if any(meta.get(k) != v for k, v in env.items()):
        # defense-in-depth, not the primary gate: the entry ADDRESS
        # already embeds the environment (a skewed stack computes a
        # different key and misses at read_meta), so this fires only for
        # tampered or hash-colliding meta — and still as a metadata-only
        # miss, before the deep payload hash
        return None
    t0 = time.perf_counter()
    try:
        # deep: the payload is unpickled below — bytes must prove
        # themselves against the manifest first
        integrity.verify_manifest(entry, meta.get("manifest", {}), deep=True)
    except integrity.CorruptArtifactError:
        # heal the tree — but re-read first: a concurrent writer may
        # have re-published this key between our meta read and the
        # verify (meta unlinked, payload rewritten, new meta sealed);
        # dropping THEIR valid entry would cost the fleet a recompile.
        # Only drop when the meta we verified against is still live.
        if registry.read_meta(entry) == meta:
            registry.drop(entry)
        return None
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = pickle.loads(
            (entry / PAYLOAD_FILE).read_bytes()
        )
        compiled = _se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception:  # noqa: BLE001 — undeserializable ⇒ miss, not crash
        return None
    return LoadedExecutable(compiled, meta, time.perf_counter() - t0)
