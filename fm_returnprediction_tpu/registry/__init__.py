"""One artifact plane: versioned AOT-executable + state registry.

The cross-cutting layer ROADMAP items 1-3 reduce to "fetch from the
registry" (item 5): a content-addressed tree under ``FMRP_REGISTRY_DIR``
holding

- serialized AOT-compiled EXECUTABLES (:mod:`.executables`), fetched by
  ``telemetry.perf.timed_aot_compile`` before any lowering happens — the
  serving bucket programs, the specgrid fused program, and the panel
  characteristics program all ride it;
- schema-versioned ARTIFACTS (:mod:`.artifacts`) — serving states,
  specgrid frames, audit manifests — and the prepared-inputs panel
  checkpoint slots, all integrity-guarded by the ONE manifest layer
  (:mod:`.integrity`) the prepared checkpoint, ``save_array_bundle`` and
  the guard audit already share;
- the WARM-POOL protocol (:mod:`.warm`): ``warm_from_registry()`` starts
  a quoting-ready serving replica with zero process-local compiles.

Maintenance: ``python -m fm_returnprediction_tpu.registry {ls,verify,gc}``.
Off unless ``FMRP_REGISTRY_DIR`` (or ``--registry-dir``) is set; every
failure degrades to the compute path that existed before this layer.
"""

from __future__ import annotations

from fm_returnprediction_tpu.registry.artifacts import (
    get_entry_dir,
    get_file,
    list_entries,
    load_serving_state,
    put_files,
    put_serving_state,
)
from fm_returnprediction_tpu.registry.executables import (
    code_salt,
    environment_key,
    executable_key,
    load_executable,
    store_executable,
)
from fm_returnprediction_tpu.registry.integrity import (
    CorruptArtifactError,
    array_bundle_digest,
    file_sha256,
)
from fm_returnprediction_tpu.registry.store import (
    REGISTRY_ENV,
    Registry,
    active_registry,
    registry_dir,
    using_registry,
)
from fm_returnprediction_tpu.registry.warm import WarmReport, warm_from_registry

__all__ = [
    "REGISTRY_ENV",
    "Registry",
    "CorruptArtifactError",
    "WarmReport",
    "active_registry",
    "array_bundle_digest",
    "code_salt",
    "environment_key",
    "executable_key",
    "file_sha256",
    "get_entry_dir",
    "get_file",
    "list_entries",
    "load_executable",
    "load_serving_state",
    "put_files",
    "put_serving_state",
    "registry_dir",
    "store_executable",
    "using_registry",
    "warm_from_registry",
]
