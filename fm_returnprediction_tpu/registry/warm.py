"""Warm-pool protocol: a fresh process reaches quoting-ready with ZERO
process-local compiles.

The serving story before this module: warm-up compiles every bucket in
the starting process (``ERService(warm=True)``), and the persistent XLA
cache shortens — but does not remove — what the NEXT process pays (each
replica still traces, lowers, and round-trips the cache). With a
populated registry, :func:`warm_from_registry` builds a fully-warmed
:class:`~fm_returnprediction_tpu.serving.service.ERService` whose bucket
executables all arrive via the executable plane's deserialize path —
zero jit traces, zero XLA compiles — and whose state comes from the
artifact plane (or an explicit path/state). The returned
:class:`WarmReport` carries the evidence: the ledger records of the
warm-up window split by provenance and the ``fmrp_jit_traces_total``
growth, both asserted zero-fresh in ``tests/test_registry.py``, and
differentially pinned bit-identical to the in-process warm-up path.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional, Tuple

__all__ = ["WarmReport", "warm_from_registry"]

# serializes the evidence window: two warms racing in one process used to
# cross-attribute each other's ledger records and trace growth (disclosed
# as a caveat since PR 9). The overload-survival layer multiplied the
# spawn sites — autoscaler scale-out, failover, crash recovery — so the
# window is now locked: warm-ups queue, reports stay per-service honest.
_WARM_LOCK = threading.Lock()


@dataclasses.dataclass(frozen=True)
class WarmReport:
    """Evidence of how a warm-up was paid for."""

    wall_s: float
    deserialized: int          # bucket programs fetched from the registry
    fresh_compiles: int        # programs that had to lower+compile
    trace_growth: int          # fmrp_jit_traces_total growth in the window
    programs: Tuple[str, ...]  # "program@provenance" per ledger record
    saved_s: float             # store-time compile seconds NOT paid

    @property
    def zero_compile(self) -> bool:
        """The warm-pool contract: nothing lowered, nothing compiled."""
        return self.fresh_compiles == 0 and self.trace_growth == 0


_PROGRAM = "serving_bucket"  # the program warm-up compiles/fetches


def _trace_total() -> int:
    """The serving-bucket jit-trace count — scoped to THIS program so a
    concurrent compile elsewhere in the process (another subsystem
    tracing ols/specgrid) cannot falsify the report."""
    from fm_returnprediction_tpu.telemetry import metrics as _metrics

    series = _metrics.registry().collect().get("fmrp_jit_traces_total", {})
    return int(sum(
        v for key, v in series.items()
        if dict(key).get("program") == _PROGRAM
    ))


def warm_from_registry(
    state=None,
    registry_dir=None,
    fingerprint: Optional[str] = None,
    strict: bool = False,
    service_cls=None,
    **service_kwargs,
):
    """Build a quoting-ready ``ERService`` from the registry.

    ``state`` may be a fitted ``ServingState``, a path to a saved
    ``serving_state.npz``, or None — in which case the artifact plane
    resolves it (by ``fingerprint``, else the newest registered entry).
    ``registry_dir`` overrides ``FMRP_REGISTRY_DIR`` for the warm-up.
    Every bucket the service warms rides ``timed_aot_compile``'s
    registry fetch; with a populated executable plane nothing in the
    window traces or compiles.

    Returns ``(service, report)``. ``strict=True`` raises when the
    zero-compile contract was missed (a partial registry is otherwise a
    legitimate degraded start: the misses compiled fresh and were stored
    for the next replica). ``service_cls`` lets a caller substitute an
    ``ERService`` subclass — the serving FLEET fans its replicas out
    through here with its replica-aware service class, so every failover
    replacement starts compile-free too."""
    from pathlib import Path

    from fm_returnprediction_tpu.registry import artifacts as _artifacts
    from fm_returnprediction_tpu.registry.store import using_registry
    from fm_returnprediction_tpu.serving.service import ERService
    from fm_returnprediction_tpu.serving.state import ServingState
    from fm_returnprediction_tpu.telemetry import cost_ledger

    with using_registry(registry_dir) as reg:
        if state is None:
            if reg is None:
                raise ValueError(
                    "warm_from_registry needs a state, a registry_dir, or "
                    "FMRP_REGISTRY_DIR set"
                )
            state = _artifacts.load_serving_state(fingerprint, registry=reg)
            if state is None:
                raise FileNotFoundError(
                    f"no serving_state artifact in registry {reg.root}"
                )
        elif isinstance(state, (str, Path)):
            state = ServingState.load(state)

        cls = service_cls if service_cls is not None else ERService
        ledger = cost_ledger()
        # evidence is scoped to the serving program (other subsystems
        # compiling concurrently must not falsify this service's report)
        # and the window is serialized by _WARM_LOCK (two racing warms
        # would otherwise attribute each other's bucket fetches)
        with _WARM_LOCK:
            seq0 = ledger.last_seq
            traces0 = _trace_total()
            t0 = time.perf_counter()
            service = cls(state, warm=True, **service_kwargs)
            wall = time.perf_counter() - t0
            window: List = [
                r for r in ledger.since(seq0) if r.program == _PROGRAM
            ]
            trace_growth = _trace_total() - traces0
        report = WarmReport(
            wall_s=wall,
            deserialized=sum(
                1 for r in window if r.provenance == "deserialized"
            ),
            fresh_compiles=sum(
                1 for r in window if r.provenance != "deserialized"
            ),
            trace_growth=trace_growth,
            programs=tuple(f"{r.program}@{r.provenance}" for r in window),
            saved_s=sum(
                r.saved_s for r in window
                if r.provenance == "deserialized" and r.saved_s is not None
            ),
        )
    if strict and not report.zero_compile:
        service.close()
        raise RuntimeError(
            "warm_from_registry(strict=True): warm-up was not compile-free "
            f"(fresh_compiles={report.fresh_compiles}, "
            f"trace_growth={report.trace_growth}, "
            f"programs={list(report.programs)})"
        )
    return service, report
