"""Registry maintenance CLI.

    python -m fm_returnprediction_tpu.registry ls                 # list entries
    python -m fm_returnprediction_tpu.registry verify             # deep-check
    python -m fm_returnprediction_tpu.registry verify --shallow   # sizes only
    python -m fm_returnprediction_tpu.registry gc --keep 4        # collect
    python -m fm_returnprediction_tpu.registry gc --dry-run

The root resolves from ``--registry-dir`` or ``FMRP_REGISTRY_DIR``.
``verify`` exits 1 when any entry fails its manifest (the corrupt rows
are printed; a later fetch of a corrupt entry would heal it by dropping
and recompiling, ``verify`` just finds them eagerly). ``gc`` applies the
documented retention policy: newest ``--keep`` per executable
(program, signature) / artifact name, torn entries always dropped;
``--drop-skewed`` additionally removes executables compiled under
another jax/jaxlib/backend (opt-in — skew is judged against the CURRENT
process's stack, so run it from the consumers' node, not a login box).
"""

from __future__ import annotations

import argparse
import sys

from fm_returnprediction_tpu.registry.store import Registry, registry_dir


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n / 1:.1f}{unit}"
        n /= 1024
    return f"{n}B"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="fm_returnprediction_tpu.registry",
        description="Inspect and maintain the AOT-executable/artifact "
                    "registry.",
    )
    parser.add_argument("--registry-dir", default=None,
                        help="registry root (default: FMRP_REGISTRY_DIR)")
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("ls", help="list every entry")
    p_verify = sub.add_parser("verify", help="verify entry manifests")
    p_verify.add_argument("--shallow", action="store_true",
                          help="sizes/structure only (skip content hashes)")
    p_gc = sub.add_parser("gc", help="apply the retention policy")
    p_gc.add_argument("--keep", type=int, default=4,
                      help="entries retained per program/artifact name "
                           "(default 4)")
    p_gc.add_argument("--drop-skewed", action="store_true",
                      help="also drop executables compiled under another "
                           "jax/jaxlib/backend — run this from the "
                           "CONSUMERS' stack: skew is judged against the "
                           "current process, so a login node or locally "
                           "upgraded jax would wipe other stacks' live "
                           "entries")
    p_gc.add_argument("--dry-run", action="store_true",
                      help="report what would be dropped, drop nothing")
    args = parser.parse_args(argv)

    root = args.registry_dir or registry_dir()
    if root is None:
        print("no registry root: pass --registry-dir or set "
              "FMRP_REGISTRY_DIR", file=sys.stderr)
        return 2
    reg = Registry(root)

    if args.command == "ls":
        rows = reg.ls()
        if not rows:
            print(f"registry {reg.root}: empty")
            return 0
        for row in rows:
            label = row.get("program") or row.get("name") or ""
            extra = " ".join(
                f"{k}={row[k]}" for k in ("backend", "jax", "created_at")
                if k in row
            )
            flag = "" if row["readable"] else "  [TORN]"
            print(f"{row['kind']:<10} {label:<24} "
                  f"{_fmt_bytes(row['bytes']):>9}  {row['path']}"
                  f"{('  ' + extra) if extra else ''}{flag}")
        total = sum(r["bytes"] for r in rows)
        print(f"{len(rows)} entries, {_fmt_bytes(total)}")
        return 0

    if args.command == "verify":
        bad = reg.verify(deep=not args.shallow)
        for row in bad:
            print(f"CORRUPT {row['path']}: {row['error']}", file=sys.stderr)
        print(f"{'FAILED' if bad else 'ok'}: {len(bad)} corrupt entr"
              f"{'y' if len(bad) == 1 else 'ies'}")
        return 1 if bad else 0

    if args.command == "gc":
        dropped = reg.gc(keep=args.keep,
                         drop_skewed=args.drop_skewed,
                         dry_run=args.dry_run)
        verb = "would drop" if args.dry_run else "dropped"
        for row in dropped:
            print(f"{verb} {row['path']}: {row['reason']}")
        print(f"{verb} {len(dropped)} entr"
              f"{'y' if len(dropped) == 1 else 'ies'}")
        return 0

    return 2  # unreachable: sub.required


if __name__ == "__main__":
    sys.exit(main())
