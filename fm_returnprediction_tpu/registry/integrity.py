"""The ONE integrity layer for persisted artifacts.

Before this module the repo had three parallel integrity implementations
growing in three corners: the prepared-v3 checkpoint's ``meta.json``
sha256+size manifest (``data.prepared``), the array-bundle content digest
``utils.cache.save_array_bundle`` embeds and verifies, and the guard
drift sentinel's per-artifact content hashes (``guard.drift``). All three
answer the same question — *are these bytes the bytes that were written*
— with the same answer shape (sha256) and the same failure contract (a
typed :class:`CorruptArtifactError` the caller degrades on). This module
is their single home; the registry's executable and artifact planes build
their manifests from the same helpers, so every persisted thing in the
repo fails corruption the same way.

Digest definitions are FROZEN: :func:`file_sha256` hashes raw file bytes
and :func:`array_bundle_digest` reproduces the historical bundle/drift
digest byte for byte (``name|dtype|shape|`` framing over sorted names) —
moving the implementations here must not invalidate a single existing
manifest, bundle checksum, or audit baseline.
"""

from __future__ import annotations

import hashlib
from pathlib import Path
from typing import Dict, Iterable, Union

import numpy as np

from fm_returnprediction_tpu.resilience.errors import CorruptArtifactError

__all__ = [
    "CorruptArtifactError",
    "file_sha256",
    "array_bundle_digest",
    "manifest_entry",
    "build_manifest",
    "verify_entry",
    "verify_manifest",
    "hash_files",
]

_CHUNK = 1 << 22


def file_sha256(path: Union[Path, str]) -> str:
    """Streaming sha256 over a file's bytes (the prepared-checkpoint and
    registry manifest content hash)."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(_CHUNK), b""):
            h.update(block)
    return h.hexdigest()


def array_bundle_digest(arrays: Dict[str, np.ndarray]) -> str:
    """Order-independent content hash over (name, dtype, shape, bytes) of
    every array — the integrity contract ``utils.cache.load_array_bundle``
    verifies and the drift sentinel's array-artifact identity hash."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        arr = np.ascontiguousarray(np.asarray(arrays[name]))
        h.update(f"{name}|{arr.dtype.str}|{arr.shape}|".encode())
        h.update(arr.data)
    return h.hexdigest()


def manifest_entry(path: Union[Path, str]) -> dict:
    """One file's manifest record: ``{"sha256": ..., "size": ...}`` — the
    shape the prepared checkpoint, the audit manifest, and the registry
    planes all store."""
    path = Path(path)
    return {"sha256": file_sha256(path), "size": path.stat().st_size}


def build_manifest(paths: Iterable[Union[Path, str]]) -> Dict[str, dict]:
    """Manifest over several files, keyed by file NAME (the registry and
    prepared-checkpoint layout stores payloads flat in one directory)."""
    return {Path(p).name: manifest_entry(p) for p in paths}


def verify_entry(
    path: Union[Path, str], entry: dict, deep: bool = False
) -> None:
    """Check one payload file against its manifest record.

    Structure and size always verify (one ``stat``); the full content
    re-hash is ``deep`` opt-in because it costs the IO that mmap'd loads
    exist to avoid. Any mismatch or unreadable file raises the typed
    :class:`CorruptArtifactError` every resume/degrade path catches."""
    path = Path(path)
    name = path.name
    if not isinstance(entry, dict):
        raise CorruptArtifactError(f"{name} has no manifest entry")
    try:
        size = path.stat().st_size
    except OSError as exc:
        raise CorruptArtifactError(f"{name} unreadable: {exc!r}") from exc
    if size != entry.get("size"):
        raise CorruptArtifactError(
            f"{name} is {size} bytes, manifest says {entry.get('size')}"
        )
    if deep:
        try:
            digest = file_sha256(path)
        except OSError as exc:  # EIO, perms, concurrent replace — degrade
            raise CorruptArtifactError(
                f"{name} unreadable during verify: {exc!r}"
            ) from exc
        if digest != entry.get("sha256"):
            raise CorruptArtifactError(f"{name} failed its content sha256")


def verify_manifest(
    directory: Union[Path, str], manifest: Dict[str, dict], deep: bool = False
) -> None:
    """Verify every manifest entry against the files in ``directory``."""
    directory = Path(directory)
    for name, entry in manifest.items():
        verify_entry(directory / name, entry, deep=deep)


def hash_files(paths: Iterable[Union[Path, str]]) -> str:
    """One digest over several files' (name, bytes) — the executable
    plane's code-version salt (any source change invalidates)."""
    h = hashlib.sha256()
    for p in sorted(Path(p) for p in paths):
        h.update(p.name.encode())
        h.update(b"|")
        h.update(p.read_bytes())
    return h.hexdigest()
