"""The versioned, content-addressed registry root.

One directory tree (``FMRP_REGISTRY_DIR``) holds everything a fresh
process needs to reach quoting-ready without recomputing or recompiling:

- ``executables/<key>/``  — serialized AOT-compiled programs
  (:mod:`.executables`): ``payload.bin`` + ``meta.json``;
- ``artifacts/<name>/<fingerprint>/`` — schema-versioned artifacts
  (:mod:`.artifacts`): payload files + ``meta.json``;
- ``prepared/<slot>/``    — the prepared-inputs panel checkpoint slots
  (``data.prepared`` writes its own columnar layout there when the
  registry is armed).

Every entry directory follows the same crash-consistency contract as the
prepared checkpoint: payloads first, ``meta.json`` LAST (tmp +
``os.replace``), carrying a sha256+size manifest over the payloads from
:mod:`.integrity` — a torn write is indistinguishable from an absent
entry, and bit-rot surfaces as the typed ``CorruptArtifactError`` that
every consumer degrades on (re-compile / re-build), never a crash.

The registry is OFF unless ``FMRP_REGISTRY_DIR`` is set (or a CLI passes
``--registry-dir``, which sets it for the process): an unarmed process
behaves exactly as before this layer existed.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
from pathlib import Path
from typing import Dict, List, Optional, Union

from fm_returnprediction_tpu.registry import integrity

__all__ = [
    "REGISTRY_ENV",
    "SCHEMA_VERSION",
    "registry_dir",
    "active_registry",
    "using_registry",
    "Registry",
]

REGISTRY_ENV = "FMRP_REGISTRY_DIR"
#: bump when the on-disk entry layout changes — an old tree must read as
#: absent to a new process, not as a half-compatible hit
SCHEMA_VERSION = 1

META_FILE = "meta.json"
LOCK_FILE = ".publish.lock"
_EXE_DIRNAME = "executables"
_ART_DIRNAME = "artifacts"
_PREPARED_DIRNAME = "prepared"


class _publish_lock:
    """Advisory, blocking, cross-process exclusive lock on one entry
    directory (``fcntl.flock``; auto-released on close AND on process
    death). The lock file is a SIBLING of the entry
    (``.<entry>.publish.lock``), not inside it: ``drop()``/``gc()``
    rmtree entry dirs, and an in-dir lock would let delete+recreate mint
    a fresh inode while a publisher still holds the old one — two
    writers holding "the" lock at once. A sibling inode survives entry
    deletion, so publishers and maintenance serialize on one file.
    Dot-prefixed and a plain file, so entry scans (directories) never
    see it."""

    def __init__(self, entry_dir: Path):
        entry_dir = Path(entry_dir)
        self._path = entry_dir.parent / f".{entry_dir.name}{LOCK_FILE}"
        self._fh = None

    def __enter__(self) -> "_publish_lock":
        try:
            import fcntl
        except ImportError:  # non-POSIX: historical unlocked protocol
            return self
        self._path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self._path, "a+")
        fcntl.flock(self._fh.fileno(), fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc) -> None:
        if self._fh is not None:
            try:
                import fcntl

                fcntl.flock(self._fh.fileno(), fcntl.LOCK_UN)
            finally:
                self._fh.close()
                self._fh = None


def registry_dir() -> Optional[Path]:
    """The armed registry root, or None. Resolved LIVE from the
    environment (the repo-wide knob discipline: tests and benches flip
    routes per call via ``monkeypatch.setenv``)."""
    raw = os.environ.get(REGISTRY_ENV, "").strip()
    return Path(raw) if raw else None


_CACHE_LOCK = threading.Lock()
_CACHED: Optional[tuple] = None  # (root_str, Registry)


def active_registry() -> Optional["Registry"]:
    """The process's :class:`Registry` for the armed root, or None when
    the registry is off. One instance per root (cheap to re-resolve, and
    a changed env var mid-process — tests — picks up the new root)."""
    global _CACHED
    root = registry_dir()
    if root is None:
        return None
    key = str(root)
    with _CACHE_LOCK:
        if _CACHED is not None and _CACHED[0] == key:
            return _CACHED[1]
        reg = Registry(root)
        _CACHED = (key, reg)
        return reg


class using_registry:
    """Context manager arming ``FMRP_REGISTRY_DIR`` for a block (the
    ``run_pipeline(registry_dir=...)`` plumbing — env-based so every
    live-resolving consumer in the process sees the same root)."""

    def __init__(self, root: Optional[Union[Path, str]]):
        self.root = root
        self._prev: Optional[str] = None

    def __enter__(self) -> Optional["Registry"]:
        if self.root is None:
            return active_registry()
        self._prev = os.environ.get(REGISTRY_ENV)
        os.environ[REGISTRY_ENV] = str(self.root)
        return active_registry()

    def __exit__(self, *exc) -> None:
        if self.root is None:
            return
        if self._prev is None:
            os.environ.pop(REGISTRY_ENV, None)
        else:
            os.environ[REGISTRY_ENV] = self._prev


class Registry:
    """Filesystem-backed registry over one root directory.

    Entry directories are written by :meth:`write_entry` (payloads,
    then manifest-bearing meta — atomic publish) and read by
    :meth:`read_meta` / :meth:`verify_entry`. The maintenance surface
    (:meth:`ls` / :meth:`verify` / :meth:`gc`) backs the
    ``python -m fm_returnprediction_tpu.registry`` CLI.
    """

    def __init__(self, root: Union[Path, str]):
        self.root = Path(root)

    # -- layout ------------------------------------------------------------

    @property
    def executables_root(self) -> Path:
        return self.root / _EXE_DIRNAME

    @property
    def artifacts_root(self) -> Path:
        return self.root / _ART_DIRNAME

    def prepared_root(self, slot: str) -> Path:
        """The prepared-inputs checkpoint slot for one raw directory
        (``data.prepared`` owns the layout inside)."""
        return self.root / _PREPARED_DIRNAME / slot

    def executable_dir(self, key: str) -> Path:
        return self.executables_root / key

    def artifact_dir(self, name: str, fingerprint: str) -> Path:
        return self.artifacts_root / name / fingerprint

    # -- entry IO ----------------------------------------------------------

    def write_entry(self, entry_dir: Path, payloads: Dict[str, bytes],
                    meta: dict) -> Path:
        """Publish one entry atomically: payload files, then ``meta.json``
        (tmp + rename) carrying the integrity manifest. An existing entry
        is invalidated first (meta removed) so a crash mid-rewrite leaves
        an absent entry, never a stale-manifest one."""
        def emit(entry: Path) -> list:
            names = []
            for name, blob in payloads.items():
                path = entry / name
                tmp = entry / f".{name}.tmp-{os.getpid()}"
                try:
                    tmp.write_bytes(blob)
                    os.replace(tmp, path)
                finally:
                    tmp.unlink(missing_ok=True)
                names.append(path)
            return names

        return self._publish_entry(entry_dir, list(payloads), emit, meta)

    def write_entry_from_paths(self, entry_dir: Path, paths, meta: dict
                               ) -> Path:
        """:meth:`write_entry` for payloads that already exist as files —
        copied in (streaming, no whole-file round-trip through memory)
        under the same atomic-publish protocol."""
        paths = [Path(p) for p in paths]

        def emit(entry: Path) -> list:
            names = []
            for src in paths:
                dst = entry / src.name
                tmp = entry / f".{src.name}.tmp-{os.getpid()}"
                try:
                    shutil.copyfile(src, tmp)
                    os.replace(tmp, dst)
                finally:
                    tmp.unlink(missing_ok=True)
                names.append(dst)
            return names

        return self._publish_entry(
            entry_dir, [p.name for p in paths], emit, meta
        )

    def _publish_entry(self, entry_dir: Path, payload_names, emit,
                       meta: dict) -> Path:
        """The ONE crash-consistency protocol both entry writers share:
        reserved-name guard, advisory cross-PROCESS publish lock, meta
        invalidation BEFORE payloads, per-file tmp+rename,
        manifest-bearing meta LAST.

        The lock (``fcntl.flock`` on the entry's sibling
        ``.<entry>.publish.lock``) serializes concurrent publishers: N
        processes warming the same
        registry simultaneously — the multi-process spec-grid workers,
        fleet replica spawns — would otherwise interleave their per-file
        renames and publish file A from one writer under file B's
        manifest (a half-renamed entry a reader sees as corruption).
        Readers need no lock: meta is still written last, so mid-publish
        they observe an ABSENT entry (degrade to a fresh compile), never
        a torn one. Advisory flocks release on process death, so a
        crashed publisher cannot wedge the registry; on platforms
        without ``fcntl`` the lock degrades to the historical unlocked
        protocol."""
        if META_FILE in payload_names:
            raise ValueError(f"payload name {META_FILE!r} is reserved")
        entry_dir = Path(entry_dir)
        entry_dir.mkdir(parents=True, exist_ok=True)
        with _publish_lock(entry_dir):
            meta_path = entry_dir / META_FILE
            meta_path.unlink(missing_ok=True)  # invalidate before payloads
            written = emit(entry_dir)
            meta = dict(meta)
            meta["schema"] = SCHEMA_VERSION
            meta["manifest"] = integrity.build_manifest(written)
            tmp = entry_dir / f".{META_FILE}.tmp-{os.getpid()}"
            try:
                tmp.write_text(json.dumps(meta, sort_keys=True))
                os.replace(tmp, meta_path)
            finally:
                tmp.unlink(missing_ok=True)
        return entry_dir

    def read_meta(self, entry_dir: Path) -> Optional[dict]:
        """The entry's meta, or None when absent/torn/schema-skewed —
        absence and unreadability are the same answer (rebuild)."""
        try:
            meta = json.loads((Path(entry_dir) / META_FILE).read_text())
        except (OSError, ValueError):
            return None
        if meta.get("schema") != SCHEMA_VERSION:
            return None
        return meta

    def verify_entry(self, entry_dir: Path, deep: bool = False) -> dict:
        """Meta + manifest verification for one entry; raises the typed
        ``CorruptArtifactError`` on any mismatch."""
        meta = self.read_meta(entry_dir)
        if meta is None:
            raise integrity.CorruptArtifactError(
                f"registry entry {entry_dir} has no readable meta"
            )
        integrity.verify_manifest(entry_dir, meta.get("manifest", {}),
                                  deep=deep)
        return meta

    # -- maintenance surface (the __main__ CLI) ----------------------------

    def _entry_dirs(self) -> List[Path]:
        out: List[Path] = []
        if self.executables_root.is_dir():
            out.extend(sorted(
                p for p in self.executables_root.iterdir() if p.is_dir()
            ))
        if self.artifacts_root.is_dir():
            for name_dir in sorted(self.artifacts_root.iterdir()):
                if name_dir.is_dir():
                    out.extend(sorted(
                        p for p in name_dir.iterdir() if p.is_dir()
                    ))
        return out

    def _prepared_slots(self) -> List[Path]:
        root = self.root / _PREPARED_DIRNAME
        if not root.is_dir():
            return []
        return sorted(p for p in root.iterdir() if p.is_dir())

    def _prepared_meta(self, slot: Path) -> Optional[dict]:
        """A prepared slot's meta.json (``data.prepared`` owns the format
        — no registry schema field, but the SAME manifest shape)."""
        try:
            meta = json.loads((slot / "meta.json").read_text())
        except (OSError, ValueError):
            return None
        return meta if isinstance(meta.get("manifest"), dict) else None

    def ls(self) -> List[dict]:
        """One row per entry — executables, artifacts, AND prepared
        checkpoint slots: kind, key/name, payload bytes, and the salient
        meta fields (program/backend/jax for executables)."""
        rows: List[dict] = []
        for entry in self._entry_dirs():
            meta = self.read_meta(entry)
            manifest = (meta or {}).get("manifest", {})
            size = sum(int(e.get("size", 0)) for e in manifest.values())
            kind = ("executable"
                    if entry.parent == self.executables_root else "artifact")
            row = {
                "kind": kind,
                "path": str(entry.relative_to(self.root)),
                "bytes": size,
                "readable": meta is not None,
            }
            if meta:
                for field in ("program", "signature", "name", "backend",
                              "jax", "created_at", "fingerprint"):
                    if field in meta:
                        row[field] = meta[field]
            rows.append(row)
        for slot in self._prepared_slots():
            meta = self._prepared_meta(slot)
            manifest = (meta or {}).get("manifest", {})
            rows.append({
                "kind": "prepared",
                "name": slot.name,
                "path": str(slot.relative_to(self.root)),
                "bytes": sum(
                    int(e.get("size", 0)) for e in manifest.values()
                ),
                "readable": meta is not None,
            })
        return rows

    def verify(self, deep: bool = True) -> List[dict]:
        """Verify every entry — including prepared checkpoint slots, the
        tree's largest payloads; returns one row per CORRUPT entry (empty
        list = clean tree). Never raises — the CLI reports and exits 1."""
        bad: List[dict] = []
        for entry in self._entry_dirs():
            try:
                self.verify_entry(entry, deep=deep)
            except integrity.CorruptArtifactError as exc:
                bad.append({
                    "path": str(entry.relative_to(self.root)),
                    "error": str(exc),
                })
        for slot in self._prepared_slots():
            meta = self._prepared_meta(slot)
            if meta is None:
                bad.append({
                    "path": str(slot.relative_to(self.root)),
                    "error": "prepared slot has no readable meta",
                })
                continue
            try:
                integrity.verify_manifest(slot, meta["manifest"], deep=deep)
            except integrity.CorruptArtifactError as exc:
                bad.append({
                    "path": str(slot.relative_to(self.root)),
                    "error": str(exc),
                })
        return bad

    def drop(self, entry_dir: Path) -> None:
        """Remove one entry (meta first, so a concurrent reader sees an
        absent entry rather than payload-less meta). Serialized on the
        entry's publish lock: deleting the dir out from under a
        mid-publish writer would both fail its emit and — were the lock
        inside the dir — hand the lock's identity to the next writer."""
        entry_dir = Path(entry_dir)
        with _publish_lock(entry_dir):
            (entry_dir / META_FILE).unlink(missing_ok=True)
            shutil.rmtree(entry_dir, ignore_errors=True)

    def gc(self, keep: int = 4, drop_skewed: bool = False,
           dry_run: bool = False) -> List[dict]:
        """Garbage-collect the tree; returns the dropped entries.

        Policy (documented in ``docs/architecture.md``): per executable
        (program, SIGNATURE) keep the ``keep`` newest entries — one
        signature per live shape, so a complete current executable set
        (e.g. all nine serving buckets) is never thinned by maintenance —
        per artifact *name* keep the ``keep`` newest fingerprints.
        ``drop_skewed`` additionally drops executables compiled under
        another jax/jaxlib/backend; it is OPT-IN because skew is judged
        against THIS process's stack — on a shared registry, maintenance
        run from a login node or after a local jax upgrade would
        otherwise wipe every other stack's (perfectly live) executables.
        Run it from the consumers' stack, where a skewed entry really can
        never load. Prepared checkpoint slots self-overwrite in place
        (one slot per raw dir) and are retained unless torn.
        Unreadable/torn entries are always dropped."""
        env = None
        if drop_skewed:
            # environment_key() imports jax and initializes a backend —
            # only pay (and only contend with a live device runtime) when
            # the skew policy actually needs the comparison
            from fm_returnprediction_tpu.registry import executables as _exe

            env = _exe.environment_key()
        dropped: List[dict] = []

        def _drop(entry: Path, why: str) -> None:
            dropped.append({
                "path": str(entry.relative_to(self.root)), "reason": why,
            })
            if not dry_run:
                self.drop(entry)

        groups: Dict[tuple, List[tuple]] = {}
        for entry in self._entry_dirs():
            meta = self.read_meta(entry)
            if meta is None:
                _drop(entry, "unreadable meta")
                continue
            if entry.parent == self.executables_root:
                if env is not None and {
                    k: meta.get(k) for k in env
                } != env:
                    _drop(entry, "environment skew")
                    continue
                # key per (program, signature): distinct signatures are
                # distinct live programs, not history of one another
                group = ("executable",
                         f"{meta.get('program', '?')}"
                         f"@{meta.get('signature', '?')}")
            else:
                group = ("artifact", entry.parent.name)
            groups.setdefault(group, []).append(
                (meta.get("created_at") or "", entry)
            )
        for group, entries in groups.items():
            entries.sort(key=lambda kv: kv[0])
            for _, entry in entries[:-keep] if keep > 0 else entries:
                _drop(entry, f"beyond keep={keep} for {group[1]}")
        for slot in self._prepared_slots():
            if self._prepared_meta(slot) is None:
                _drop(slot, "torn prepared slot")
        return dropped
