"""Zero-copy shared-memory primitives: SPSC frame rings + mapped segments.

``parallel.distributed`` gave the repo ONE wire format — length-prefixed
pickle frames over TCP. That is the right shape for control-plane verbs
(rare, structured, trusted) and the WRONG shape for the data plane:
BENCH_r08 measured the process fleet at 0.643× the thread fleet and the
multiproc spec-grid shipping 8.6 MB of pickle per grid at p4 — the
transport, not the solve, is the bottleneck (the PAPERS.md out-of-core
regression result: at scale the algorithm is data movement). This module
is the data plane's home:

:class:`ShmRing`
    A fixed-slot single-producer/single-consumer frame ring over ONE
    ``multiprocessing.shared_memory`` segment, crossing exactly one
    process boundary. Fixed-width binary frames, no pickle on the hot
    path, and a sequence/commit protocol that makes a torn frame read
    as ABSENT (the crash-safety contract the fleet journal's
    exactly-once proof leans on):

    - every slot carries a ``commit`` word holding the GLOBAL sequence
      number of the frame it contains; the writer copies payload bytes
      and the length FIRST and writes ``commit`` LAST, so a writer that
      dies mid-frame leaves ``commit`` at the previous lap's value and
      the reader simply never observes the frame;
    - the reader acknowledges consumption by publishing its cumulative
      ``tail`` sequence; the writer refuses to lap it, so a slot is
      never overwritten before its bytes were copied out;
    - ring-full is BACKPRESSURE, not corruption: the writer stalls
      (counted, ``fmrp_transport_ring_full_stalls_total``) and raises
      typed :class:`RingFullError` past its deadline — the serving
      layer maps that to the retriable ``ServiceOverloadError``.

:class:`ShmArraySpec` / :func:`publish_array` / :func:`attach_array`
    Numpy arrays published once into a named segment and MAPPED by the
    consumer — the multiproc spec-grid's panel and Gram-stats path: a
    worker maps the (T,N,P) panel instead of receiving panel bytes in
    frames, and returns its additive Gram stats as a raw buffer the
    parent sums in place.

Python-3.10 wart, handled here once: attaching to an existing segment
registers it with the ATTACHING process's ``resource_tracker``, whose
exit would unlink a segment it does not own (bpo-38119). ``attach_*``
therefore unregisters immediately — the CREATOR owns the name and
unlinks it; everyone else is a guest.

Atomicity note: the commit word is an aligned 8-byte store written by
one thread after the payload stores. CPython's GIL hand-offs and the
x86 TSO store order make "commit visible ⇒ payload visible" hold in
practice; a torn commit read can only misread as NOT-committed (the
reader retries), never as a committed frame with torn payload, because
all differing low bytes of the new value land before any byte of the
commit word is observed equal to the expected sequence.
"""

from __future__ import annotations

import os
import secrets
import select
import struct
import threading
import time
from dataclasses import dataclass
from multiprocessing import shared_memory
from typing import List, Optional, Tuple

from fm_returnprediction_tpu.resilience.faults import fault_site

__all__ = [
    "RingFullError",
    "ShmArraySpec",
    "ShmRing",
    "attach_array",
    "attach_ring",
    "owned_segments",
    "publish_array",
    "release_segment",
    "shm_available",
    "sweep_segments",
    "transport_instruments",
]

_MAGIC = 0x464D5250_53484D31  # "FMRPSHM1"
# magic, nslots, slot_bytes, tail, want_bell (reader-blocked flag), pad
_HDR = struct.Struct("<QQQQQ3Q")
_SLOT_HDR = struct.Struct("<QI4x")      # commit seq, payload length, pad
HEADER_BYTES = _HDR.size
SLOT_HEADER_BYTES = _SLOT_HDR.size
_TAIL_OFF = 24                          # offset of the tail word in _HDR
_WANT_BELL_OFF = 32                     # reader sets 1 before blocking


class RingFullError(RuntimeError):
    """The writer could not place a frame before its deadline: the
    reader has not released enough slots (transport backpressure). The
    serving layer translates this into the typed retriable
    ``ServiceOverloadError`` — a ring-full data plane is an overloaded
    replica, not a protocol failure."""

    def __init__(self, message: str, stalled_s: float = 0.0):
        super().__init__(message)
        self.stalled_s = float(stalled_s)


def shm_available() -> bool:
    """Whether POSIX shared memory is usable here (the transport
    resolvers' capability probe — e.g. a read-only /dev/shm would make
    ``shm`` resolution fall back to the socket/frames oracle)."""
    try:
        seg = shared_memory.SharedMemory(create=True, size=16)
    except (OSError, ValueError):
        return False
    seg.close()
    seg.unlink()
    return True


def _unregister(name: str) -> None:
    """Drop a segment from THIS process's resource tracker (attach-side
    only — see the module docstring's bpo-38119 note)."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister("/" + name, "shared_memory")
    except Exception:  # noqa: BLE001 — tracker variance across minors
        pass


# -- owned-segment ledger (the fd/segment hygiene audit) ---------------------
#
# Every segment THIS process creates (ring or mapped array) is entered in a
# module ledger at creation and struck at unlink. Normal teardown strikes
# every entry; anything still listed after a crash path is a LEAK — a name
# in /dev/shm with no owner left to unlink it. ``sweep_segments`` (the
# topology controller's post-repair sweep) reaps those and counts them into
# ``fmrp_topology_leaked_segments_total``, which the chaos suite asserts
# stays zero across every kill/repair cycle.

_SEG_LOCK = threading.Lock()
_OWNED: set = set()


def _ledger_add(name: str) -> None:
    with _SEG_LOCK:
        _OWNED.add(name)


def _ledger_drop(name: str) -> None:
    with _SEG_LOCK:
        _OWNED.discard(name)


def owned_segments() -> Tuple[str, ...]:
    """Snapshot of segments this process created and has not yet
    unlinked — live transports plus any leaks-in-waiting."""
    with _SEG_LOCK:
        return tuple(sorted(_OWNED))


def release_segment(seg: shared_memory.SharedMemory) -> None:
    """Owner-side disposal of a published segment: close, unlink, strike
    the ledger entry. The one call every owner teardown path uses, so the
    ledger's residue is exactly the leak set."""
    name = seg.name
    try:
        seg.close()
    except (OSError, BufferError):
        pass
    try:
        seg.unlink()
    except OSError:
        _unregister(name)  # already gone: drop OUR tracker entry too
    _ledger_drop(name)


def sweep_segments() -> List[str]:
    """Reap every still-ledgered segment: unlink the ones that still
    exist and count them as leaks. Call AFTER tearing down everything
    you own (the controller does, post-repair / post-campaign) — a live
    fleet's segments read as leaks to this function by design, because
    at sweep time nothing should be live."""
    with _SEG_LOCK:
        names = sorted(_OWNED)
        _OWNED.clear()
    leaked: List[str] = []
    for name in names:
        try:
            seg = shared_memory.SharedMemory(name=name)
        except (FileNotFoundError, OSError):
            continue  # owner unlinked it without striking: not a leak
        _unregister(name)
        try:
            seg.close()
            seg.unlink()
        except OSError:
            continue
        leaked.append(name)
    if leaked:
        from fm_returnprediction_tpu import telemetry

        telemetry.registry().counter(
            "fmrp_topology_leaked_segments_total",
            help="shm segments still linked when the topology sweep ran",
        ).inc(len(leaked))
    return leaked


def transport_instruments(transport: str, replica: str = "") -> dict:
    """The transport observability contract in ONE place: byte/frame/
    stall counters and the batch-occupancy histogram, labelled by
    transport (``shm``/``socket``/``grid_shm``/``grid_frames``) and
    replica/rank. Both the shm rings and the socket replica transport
    report through these, so the bench's socket-vs-shm comparison reads
    one family."""
    from fm_returnprediction_tpu import telemetry

    reg = telemetry.registry()
    labels = {"transport": transport}
    if replica:
        labels["replica"] = replica
    return {
        "bytes_out": reg.counter(
            "fmrp_transport_bytes_total",
            help="data-plane payload bytes by transport and direction",
            direction="sent", **labels,
        ),
        "bytes_in": reg.counter(
            "fmrp_transport_bytes_total",
            help="data-plane payload bytes by transport and direction",
            direction="received", **labels,
        ),
        "frames": reg.counter(
            "fmrp_transport_frames_total",
            help="data-plane frames by transport",
            **labels,
        ),
        "stalls": reg.counter(
            "fmrp_transport_ring_full_stalls_total",
            help="writer stalls waiting on a full ring (backpressure)",
            **labels,
        ),
        "batch_rows": reg.histogram(
            "fmrp_transport_batch_rows",
            help="rows coalesced per data-plane frame",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256),
            **labels,
        ),
    }


class ShmRing:
    """One direction of a data plane: a fixed-slot SPSC frame ring.

    Exactly one WRITER process and one READER process (each side may
    serialize its own threads through the internal lock). Created by
    the owner (``create=True``), attached by the guest via
    :func:`attach_ring`; the owner unlinks.
    """

    def __init__(self, name: Optional[str] = None, *, slots: int = 64,
                 slot_bytes: int = 65536, create: bool = False,
                 instruments: Optional[dict] = None,
                 doorbell_fd: Optional[int] = None):
        if create:
            if slots < 2 or slot_bytes <= SLOT_HEADER_BYTES:
                raise ValueError("ring needs ≥2 slots and room for payload")
            name = name or f"fmrp{os.getpid():x}{secrets.token_hex(4)}"
            size = HEADER_BYTES + slots * slot_bytes
            self._seg = shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
            _ledger_add(self._seg.name)
            _HDR.pack_into(self._seg.buf, 0, _MAGIC, slots, slot_bytes,
                           0, 0, 0, 0, 0)
        else:
            if name is None:
                raise ValueError("attaching needs the ring's name")
            self._seg = shared_memory.SharedMemory(name=name)
            _unregister(self._seg.name)
            magic, slots, slot_bytes = _HDR.unpack_from(
                self._seg.buf, 0)[:3]
            if magic != _MAGIC:
                self._seg.close()
                raise ValueError(f"segment {name!r} is not an fmrp ring")
        self.name = self._seg.name
        self.owner = bool(create)
        self.slots = int(slots)
        self.slot_bytes = int(slot_bytes)
        self.payload_capacity = self.slot_bytes - SLOT_HEADER_BYTES
        self._buf = self._seg.buf
        self._lock = threading.Lock()
        self._wseq = 0   # last committed write sequence (writer side)
        self._rseq = 0   # last consumed sequence (reader side)
        self._closed = False
        self._inst = instruments or {}
        # doorbell: an (inherited) eventfd the writer rings after every
        # commit and the reader blocks on — boundary-crossing latency is
        # then one kernel wakeup (~10 µs) instead of a sleep-poll tick.
        # None (no eventfd on this platform / not wired) = poll fallback.
        self._bell = doorbell_fd

    # -- header words ------------------------------------------------------

    def _tail(self) -> int:
        return struct.unpack_from("<Q", self._buf, _TAIL_OFF)[0]

    def _set_tail(self, seq: int) -> None:
        struct.pack_into("<Q", self._buf, _TAIL_OFF, seq)

    def _want_bell(self) -> int:
        return struct.unpack_from("<Q", self._buf, _WANT_BELL_OFF)[0]

    def _set_want_bell(self, v: int) -> None:
        struct.pack_into("<Q", self._buf, _WANT_BELL_OFF, v)

    def _slot_off(self, seq: int) -> int:
        return HEADER_BYTES + ((seq - 1) % self.slots) * self.slot_bytes

    def watermark(self) -> Tuple[int, int]:
        """(produced, consumed) as visible from THIS side: the local
        write/read sequence paired with the shared tail word. On a writer
        handle that is (frames committed, frames the peer acknowledged) —
        the liveness probe's ring-progress watermark: a gap that fails to
        drain between two probe samples classifies the peer as
        RING-STALLED (pid alive, control plane answering, data plane
        wedged), distinctly from killed or hung."""
        with self._lock:
            if self._closed or self._buf is None:
                return (max(self._wseq, self._rseq), self._rseq)
            return (max(self._wseq, self._rseq), self._tail())

    # -- writer ------------------------------------------------------------

    def send(self, payload: bytes, timeout_s: float = 5.0) -> None:
        """Place one frame; raises :class:`RingFullError` if the reader
        does not release a slot within ``timeout_s``. The commit word is
        written LAST — a writer death anywhere before that line leaves a
        frame that reads as absent."""
        n = len(payload)
        if n > self.payload_capacity:
            raise ValueError(
                f"frame of {n} B exceeds slot payload capacity "
                f"{self.payload_capacity} B"
            )
        with self._lock:
            if self._closed:
                raise RingFullError("ring is closed")
            seq = self._wseq + 1
            if seq - self._tail() > self.slots:
                # backpressure: stall (counted once per episode), then
                # typed failure past the deadline
                inst = self._inst.get("stalls")
                if inst is not None:
                    inst.inc()
                deadline = time.monotonic() + timeout_s
                while seq - self._tail() > self.slots:
                    if self._closed:
                        raise RingFullError("ring closed while stalled")
                    now = time.monotonic()
                    if now >= deadline:
                        raise RingFullError(
                            f"ring {self.name} full for {timeout_s:.3f}s "
                            f"(reader at {self._tail()}, writer at {seq})",
                            stalled_s=timeout_s,
                        )
                    time.sleep(1e-4)  # stall is the rare path: plain poll
            off = self._slot_off(seq)
            data_off = off + SLOT_HEADER_BYTES
            self._buf[data_off:data_off + n] = payload
            struct.pack_into("<I", self._buf, off + 8, n)
            # the exactly-once seam: payload and length are down, commit
            # is not — a SIGKILL landing at this site (chaos campaign)
            # must leave a frame the reader never observes
            fault_site("shm.ring.commit")
            # commit LAST: the frame exists only once this word reads seq
            struct.pack_into("<Q", self._buf, off, seq)
            self._wseq = seq
            # ring the doorbell only when the reader says it is blocked
            # (want_bell, set before it enters select and re-checks the
            # commit word — the flag protocol can delay a wakeup to the
            # bounded select timeout only if the flag store itself loses
            # the race, which the reader's re-check closes). An awake
            # reader in its greedy drain sees the commit without a
            # syscall; the eventfd write is ~35 µs when it wakes a
            # blocked peer, the dominant cost of a per-frame bell.
            if self._bell is not None and self._want_bell():
                try:
                    os.eventfd_write(self._bell, 1)
                except OSError:
                    pass  # reader gone; its own death path owns cleanup
        bo = self._inst.get("bytes_out")
        if bo is not None:
            bo.inc(n)
        fr = self._inst.get("frames")
        if fr is not None:
            fr.inc()

    # -- reader ------------------------------------------------------------

    def recv(self, timeout_s: float = 0.2,
             spin_s: float = 0.0) -> Optional[bytes]:
        """The next frame's payload (copied out), or None when no frame
        commits within ``timeout_s`` — which is also exactly what a torn
        frame looks like: its commit word never reaches the expected
        sequence, so the reader simply keeps not seeing it.

        ``spin_s``: busy-poll the commit word that long before blocking
        on the doorbell — for readers whose CPU is otherwise idle (the
        replica child), a short spin catches the next frame without
        costing the WRITER an eventfd wakeup syscall."""
        with self._lock:
            seq = self._rseq + 1
            off = self._slot_off(seq)
            deadline = time.monotonic() + timeout_s
            spin_until = time.monotonic() + spin_s if spin_s else 0.0
            delay = 2e-5
            while True:
                if self._closed:
                    return None
                (commit,) = struct.unpack_from("<Q", self._buf, off)
                if commit == seq:
                    break
                now = time.monotonic()
                if now >= deadline:
                    return None
                if now < spin_until:
                    continue  # hot spin: idle-CPU readers only
                if self._bell is not None:
                    # flag → re-check → block: the writer rings only for
                    # a reader that declared itself blocked, and the
                    # re-check closes the flag/commit race (a frame
                    # committed before the flag store is seen here, not
                    # slept through)
                    try:
                        self._set_want_bell(1)
                        (commit,) = struct.unpack_from(
                            "<Q", self._buf, off)
                        if commit == seq:
                            self._set_want_bell(0)
                            break
                        r, _, _ = select.select(
                            [self._bell], [], [],
                            min(deadline - now, 0.05),
                        )
                        self._set_want_bell(0)
                        if r:
                            os.read(self._bell, 8)
                    except (OSError, ValueError):
                        return None  # fd closed under us: ring is down
                else:
                    time.sleep(delay)
                    delay = min(delay * 2, 2e-4)
            (n,) = struct.unpack_from("<I", self._buf, off + 8)
            data_off = off + SLOT_HEADER_BYTES
            out = bytes(self._buf[data_off:data_off + n])
            # release the slot only after the copy-out
            self._set_tail(seq)
            self._rseq = seq
        bi = self._inst.get("bytes_in")
        if bi is not None:
            bi.inc(len(out))
        return out

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        # the flag is set BEFORE taking the lock: a sender stalled in
        # its ring-full loop (or a reader polling) holds the lock for up
        # to its full timeout, checks ``_closed`` every iteration, and
        # must observe the close promptly — waiting for the lock here
        # would serialize teardown behind the very stall being torn down
        self._closed = True
        with self._lock:
            self._buf = None
        try:
            self._seg.close()
        except (OSError, BufferError):
            pass
        if self.owner:
            try:
                self._seg.unlink()
            except OSError:
                # already gone (a crashed peer's resource tracker beat
                # us to it) — still drop OUR tracker entry, or it warns
                # about a "leaked" segment at interpreter exit
                _unregister(self._seg.name)
            _ledger_drop(self._seg.name)

    def __del__(self):  # best-effort: rings must not outlive the session
        try:
            self.close()
        except Exception:  # noqa: BLE001
            pass


def attach_ring(name: str, instruments: Optional[dict] = None,
                doorbell_fd: Optional[int] = None) -> ShmRing:
    """Guest-side handle on an existing ring (geometry read from the
    segment header; never unlinks). ``doorbell_fd`` is the creator's
    inherited eventfd number (``pass_fds``), or None for poll mode."""
    return ShmRing(name, create=False, instruments=instruments,
                   doorbell_fd=doorbell_fd)


# -- mapped numpy segments ----------------------------------------------------


@dataclass(frozen=True)
class ShmArraySpec:
    """What a consumer needs to map a published array: segment name +
    layout. Serializes as a plain dict (the job-frame control plane)."""

    name: str
    shape: Tuple[int, ...]
    dtype: str

    def to_meta(self) -> dict:
        return {"name": self.name, "shape": list(self.shape),
                "dtype": self.dtype}

    @classmethod
    def from_meta(cls, meta: dict) -> "ShmArraySpec":
        return cls(name=str(meta["name"]),
                   shape=tuple(int(s) for s in meta["shape"]),
                   dtype=str(meta["dtype"]))


def publish_array(arr, name: Optional[str] = None
                  ) -> Tuple[shared_memory.SharedMemory, ShmArraySpec]:
    """Copy ``arr`` once into a named segment; the caller owns the
    handle (keep it referenced, :func:`release_segment` when done — it
    strikes the hygiene ledger along with the unlink)."""
    import numpy as np

    arr = np.ascontiguousarray(arr)
    name = name or f"fmrp{os.getpid():x}{secrets.token_hex(4)}"
    seg = shared_memory.SharedMemory(
        name=name, create=True, size=max(int(arr.nbytes), 1)
    )
    _ledger_add(seg.name)
    view = np.ndarray(arr.shape, dtype=arr.dtype, buffer=seg.buf)
    view[...] = arr
    del view
    return seg, ShmArraySpec(seg.name, tuple(arr.shape), str(arr.dtype))


def attach_array(spec: ShmArraySpec
                 ) -> Tuple[shared_memory.SharedMemory, "object"]:
    """Map a published array in place (zero copy). Returns the segment
    handle (hold it as long as the view lives, ``close()`` after —
    never unlink: the publisher owns the name) and the numpy view."""
    import numpy as np

    seg = shared_memory.SharedMemory(name=spec.name)
    _unregister(seg.name)
    view = np.ndarray(spec.shape, dtype=np.dtype(spec.dtype), buffer=seg.buf)
    return seg, view
