"""Device mesh construction and panel sharding — the one place topology lives.

The reference has no distributed layer at all (SURVEY §2.1 "Distributed
communication backend: Absent"; the only process boundaries are WRDS TCP and
jupyter/pdflatex subprocesses, ``src/pull_crsp.py:238``, ``dodo.py:178``).
The TPU-native replacement is a named module owning the ``jax.sharding.Mesh``
so every sharded computation (firm-axis FM, replicate-axis bootstrap) draws
its topology from here and nowhere else.

Axis conventions:

- ``"firms"``  — the N axis of the dense ``(T, N, K)`` panel. Months are
  independent in the cross-sectional stage and firms are independent in the
  rolling stage, so the firm axis shards with zero communication except the
  per-month Gram-matrix ``psum`` (SURVEY §5 "Long-context" note).
- ``"boot"``   — the replicate axis of the block-bootstrap engine;
  embarrassingly parallel, one ``psum`` at the end for the moment sums.

A single 1-D mesh is used for both (the two stages run sequentially, so they
can reuse the same devices under different axis names via ``Mesh`` re-wrap).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "default_mesh",
    "make_mesh",
    "pad_to_multiple",
    "pipeline_mesh",
    "place_global",
    "shard_map",
    "shard_panel",
    "host_local_mesh",
]

# ``shard_map`` import-compat shim — the ONE place the API's location is
# resolved. Newer JAX exposes it as ``jax.shard_map``; the versions this
# container ships keep it at ``jax.experimental.shard_map.shard_map``.
# Every sharded program in the repo imports the symbol from here, so a
# JAX upgrade (or downgrade) never turns into six scattered
# ``AttributeError: module 'jax' has no attribute 'shard_map'`` sites
# (the disclosed mesh8 bench failure of BENCH_r03-r05).
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on exactly one of the two JAX APIs
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


def place_global(a, sharding: NamedSharding) -> jax.Array:
    """Place ``a`` with ``sharding``, working across process boundaries.

    ``jax.device_put`` onto a sharding that spans processes runs a
    same-value-everywhere assertion that compares host arrays with ``==`` —
    which trips on NaN (NaN != NaN), and every panel this framework places
    is NaN-padded. Discovered by the two-process test
    (``tests/test_multiprocess.py``): the ``place=True`` paths crashed on
    any real pod. For multi-process shardings, build the global array from
    local shards with ``make_array_from_callback`` instead — no value
    check, and each process touches only its addressable slice. The
    single-process fast path keeps the plain ``device_put``.
    """
    if sharding.is_fully_addressable:
        return jax.device_put(a, sharding)
    if isinstance(a, jax.Array) and not a.is_fully_addressable:
        # already a global array spanning processes (e.g. another jit's
        # output): device_put reshards on-device with no host value check,
        # and np.asarray would raise on the non-addressable shards anyway
        return jax.device_put(a, sharding)
    if isinstance(a, jax.Array) and jnp.issubdtype(a.dtype, jax.dtypes.prng_key):
        # typed PRNG keys (the bootstrap's replicate keys) have no numpy
        # view, and device_put onto a non-addressable sharding is rejected
        # outright by this JAX version — place the uint32 key data instead
        # (its trailing impl dims replicate) and re-wrap.
        data = jax.random.key_data(a)
        spec = P(*(tuple(sharding.spec) + (None,) * (data.ndim - a.ndim)))
        placed = place_global(
            np.asarray(data), NamedSharding(sharding.mesh, spec)
        )
        return jax.random.wrap_key_data(placed, impl=jax.random.key_impl(a))
    if not isinstance(a, np.ndarray):
        a = np.asarray(a)
    return jax.make_array_from_callback(a.shape, sharding, lambda idx: a[idx])


def make_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = "firms",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 1-D mesh over ``n_devices`` (default: all local devices).

    On a real v4-8 slice the 1-D layout keeps every collective on ICI; on the
    CPU test backend (``xla_force_host_platform_device_count``) it produces
    the virtual 8-device mesh used by the multi-chip tests (SURVEY §4d).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    if n_devices > len(devices):
        raise ValueError(
            f"Requested {n_devices} devices but only {len(devices)} available"
        )
    return Mesh(np.asarray(devices[:n_devices]), axis_names=(axis_name,))


def host_local_mesh(axis_name: str = "firms") -> Mesh:
    """All addressable devices of this host as a 1-D mesh (multi-host safe:
    uses ``jax.local_devices()`` so DCN never carries panel shards)."""
    return Mesh(np.asarray(jax.local_devices()), axis_names=(axis_name,))


def pad_to_multiple(arr: jax.Array, axis: int, multiple: int, fill=0.0) -> jax.Array:
    """Pad ``arr`` along ``axis`` up to the next multiple of ``multiple``.

    Sharding a panel over D devices needs N % D == 0; padded firm slots carry
    ``mask=False`` so they are exact no-ops in every masked kernel (the
    ragged→dense discipline of SURVEY §7 hard part (a) extends to padding).
    """
    size = arr.shape[axis]
    target = math.ceil(size / multiple) * multiple
    if target == size:
        return arr
    widths = [(0, 0)] * arr.ndim
    widths[axis] = (0, target - size)
    return jnp.pad(arr, widths, constant_values=fill)


def shard_panel(y, x, mask, mesh: Mesh, axis_name: str = "firms"):
    """Pad the firm axis to the mesh size and place each array with a
    firm-sharded ``NamedSharding``.

    Returns ``(y, x, mask)`` device arrays sharded as
    ``y: (T, N/D) per device``, ``x: (T, N/D, P)``, ``mask: (T, N/D)``.
    Padded slots have ``mask=False`` and NaN values, so validity logic
    (``ops.ols.row_validity``) drops them without special cases.
    """
    d = mesh.shape[axis_name]
    y = pad_to_multiple(jnp.asarray(y), axis=1, multiple=d, fill=jnp.nan)
    x = pad_to_multiple(jnp.asarray(x), axis=1, multiple=d, fill=jnp.nan)
    mask = pad_to_multiple(jnp.asarray(mask), axis=1, multiple=d, fill=False)

    s2 = NamedSharding(mesh, P(None, axis_name))
    s3 = NamedSharding(mesh, P(None, axis_name, None))
    return (
        place_global(y, s2),
        place_global(x, s3),
        place_global(mask, s2),
    )


def default_mesh(axis_name: str = "firms"):
    """The configured compute mesh, or None for single-device execution.

    Honors ``MESH_DEVICES``: 1 (the default) returns None — multi-chip is
    OPT-IN, so default numerics use the SVD parity solver regardless of how
    many devices the machine happens to expose; 0 = all available devices;
    N = exactly min(N, available). Single-device results return None so
    callers fall back to the plain batched kernels.
    """
    from fm_returnprediction_tpu.settings import config

    want = int(config("MESH_DEVICES"))
    n = len(jax.devices()) if want == 0 else want
    if n <= 1:
        return None
    # make_mesh raises when N exceeds the available devices — "exactly N"
    # is the contract, not a silent cap.
    return make_mesh(n_devices=n, axis_name=axis_name)


def pipeline_mesh():
    """The ONE mesh policy for pipeline-level entry points.

    Multi-process (FMRP_MULTIHOST launcher): the months×firms 2-D hierarchy,
    built unconditionally — MESH_DEVICES=1 must not leave every host running
    a redundant full single-device copy. Single-process: ``default_mesh``'s
    MESH_DEVICES opt-in. Both ``run_pipeline`` and the task graph's report
    stage draw from here so a pod run shards consistently across stages.
    """
    if jax.process_count() > 1:
        from fm_returnprediction_tpu.parallel.multihost import make_mesh_2d

        return make_mesh_2d()
    return default_mesh()
