"""Multi-chip layer: device mesh, sharded FM/bootstrap, multi-host hierarchy.

The reference is single-process serial (SURVEY §2.1 rows "Data parallelism",
"Distributed communication backend": Absent). This package is the TPU-native
replacement: a named home for the ``jax.sharding.Mesh`` plus the sharded
stages of the north-star workload — distributed-TSQR cross-sectional OLS
over the firm axis (``fm_sharded``), the 10k moving-block bootstrap over
the replicate axis (``bootstrap``), firm-sharded daily kernels
(``daily_sharded``), and the multi-host months×firms hierarchy with
``jax.distributed`` bring-up (``multihost``).
"""

from fm_returnprediction_tpu.parallel.bootstrap import (
    BootstrapResult,
    block_bootstrap_se,
    bootstrap_replicate_means,
)
from fm_returnprediction_tpu.parallel.daily_sharded import (
    daily_characteristics_sharded,
)
from fm_returnprediction_tpu.parallel.fm_sharded import (
    fama_macbeth_sharded,
    monthly_cs_ols_sharded,
)
from fm_returnprediction_tpu.parallel.mesh import (
    default_mesh,
    host_local_mesh,
    make_mesh,
    pad_to_multiple,
    pipeline_mesh,
    place_global,
    shard_map,
    shard_panel,
)
from fm_returnprediction_tpu.parallel.time_sharded import (
    rolling_mean_time_sharded,
    rolling_moments_time_sharded,
    rolling_std_time_sharded,
    rolling_sum_time_sharded,
    weekly_rolling_beta_time_sharded,
)
from fm_returnprediction_tpu.parallel.distributed import (
    DistConfig,
    HostExchange,
    dist_active,
    host_exchange,
    initialize_distributed,
    shutdown_distributed,
)
from fm_returnprediction_tpu.parallel.multihost import (
    as_flat_mesh,
    fama_macbeth_hier,
    initialize_multihost,
    make_mesh_2d,
)

__all__ = [
    "BootstrapResult",
    "DistConfig",
    "HostExchange",
    "as_flat_mesh",
    "dist_active",
    "host_exchange",
    "initialize_distributed",
    "shutdown_distributed",
    "block_bootstrap_se",
    "bootstrap_replicate_means",
    "daily_characteristics_sharded",
    "default_mesh",
    "fama_macbeth_hier",
    "fama_macbeth_sharded",
    "initialize_multihost",
    "make_mesh_2d",
    "monthly_cs_ols_sharded",
    "host_local_mesh",
    "make_mesh",
    "pad_to_multiple",
    "pipeline_mesh",
    "place_global",
    "rolling_mean_time_sharded",
    "rolling_moments_time_sharded",
    "rolling_std_time_sharded",
    "rolling_sum_time_sharded",
    "weekly_rolling_beta_time_sharded",
    "shard_map",
    "shard_panel",
]
