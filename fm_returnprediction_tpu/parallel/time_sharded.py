"""Sequence/context parallelism: rolling reductions over a TIME-sharded axis.

The firm-sharded daily kernels (``parallel.daily_sharded``) scale the panel
by splitting the embarrassingly parallel firm axis. This module covers the
opposite regime — the long-context one, where the SEQUENCE is the large
axis (minute bars, decades of daily data, few series): the time axis itself
shards across devices, and the trailing-window reductions of ``ops.rolling``
run with two collectives per call, the block-wise-exchange pattern of
ring-attention-style context parallelism:

1. **distributed prefix-sum** — each shard cumsums its local block of the
   masked moments (Σx, Σx², Σ1{finite}); an ``all_gather`` of the p per-shard
   totals (3·N floats each — tiny) gives every shard the exclusive prefix
   offset that turns local cumsums into global ones;
2. **halo exchange** — the trailing-window difference ``c[t] − c[t−w]``
   needs the previous shard's last ``window`` cumsum rows for a shard's
   first ``window`` outputs; one ``ppermute`` shifts exactly that boundary
   block forward along the mesh axis (device 0 receives zeros — which IS
   the correct shifted-cumsum value for global ``t < window``).

Communication per call: ``p·3·N`` values gathered + ``window·3·N`` values
permuted (two float moment channels plus an EXACT int32 count channel — a
float count loses integer exactness past 2^24 cumulative rows in f32,
flipping the ``min_periods`` gates on exactly the long sequences this
module exists for) — independent of the sequence length D, so the pattern
scales to
arbitrarily long sequences exactly like ring attention's per-block exchange
(the public scaling-book recipe: shard the long axis, exchange only the
boundary state). Window semantics match ``ops.rolling`` (pandas
``rolling(window, min_periods)``: NaNs occupy positions but are excluded;
NaN until ``min_periods`` finite entries) to float rounding — the windowed
sums are the same cumsum differences, just computed from shard-local
pieces.

``window`` must fit within one shard (``window <= D_padded / p``); the real
shapes satisfy this by an order of magnitude (252-day window vs ~1,576-day
shards on 8 devices), and a multi-hop halo for pathological cases would buy
generality nothing here — the constraint raises instead.

Scope boundary (deliberate): the COMPACTION-based monthly vol
(``ops.daily_kernels.rolling_vol_252_monthly``) has no time-sharded
variant. Its window counts each firm's PRESENT rows — compaction is a
global, data-dependent permutation along exactly the axis this module
shards, so a faithful port would ship per-firm variable halos for no
production need (the pipeline's panel has N≫p; it firm-shards). The
time-sharded family covers the calendar-window semantics (sum/mean/std/
moments) plus the weekly beta, whose segment sums are permutation-free.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fm_returnprediction_tpu.parallel.mesh import (
    make_mesh,
    pad_to_multiple,
    place_global,
    shard_map,
)

__all__ = [
    "rolling_moments_time_sharded",
    "rolling_sum_time_sharded",
    "rolling_mean_time_sharded",
    "rolling_std_time_sharded",
    "weekly_rolling_beta_time_sharded",
]


@functools.lru_cache(maxsize=16)
def _jitted_rolling(mesh: Mesh, axis_name: str, window: int, stat: str,
                    min_periods: int):
    """One compiled time-sharded rolling program per (mesh, config)."""
    p = mesh.shape[axis_name]

    def _windowed(cs):
        """Local cumsums (a PYTREE: float moments + exact int32 count) →
        global windowed differences, with ONE collective round: the
        distributed prefix-sum (all_gather of shard totals → exclusive
        offset) plus the single-``ppermute`` halo of the previous shard's
        last ``window`` global-cumsum rows. Device 0 has no halo source and
        receives zeros — the correct ``c[t−w]`` for global ``t < window``
        (series-start truncation). Both collectives take the whole pytree,
        so the count channel's exactness costs no extra exchange round."""
        idx = jax.lax.axis_index(axis_name)
        totals = jax.lax.all_gather(jax.tree.map(lambda c: c[-1], cs), axis_name)

        def to_global(c, tot):  # tot: (p, ...) shard totals per leaf
            before = jnp.arange(p).reshape((p,) + (1,) * (tot.ndim - 1)) < idx
            return c + jnp.sum(jnp.where(before, tot, 0), axis=0)[None]

        c = jax.tree.map(to_global, cs, totals)
        halo = jax.lax.ppermute(
            jax.tree.map(lambda g: g[-window:], c), axis_name,
            [(i, i + 1) for i in range(p - 1)],
        )

        def diff(g, h):
            return g - jnp.concatenate([h, g], axis=0)[: g.shape[0]]

        return jax.tree.map(diff, c, halo)

    def kernel(x_l):
        finite = jnp.isfinite(x_l)
        xz = jnp.where(finite, x_l, 0.0)
        # the x² channel exists only for the stats that consume it — sum and
        # mean skip its cumsum and its share of the exchanged boundary state
        need_s2 = stat in ("moments", "std")
        chans = [xz, xz * xz] if need_s2 else [xz]
        # count rides its own int32 cumsum: a float count channel loses
        # integer exactness once the cumulative count passes 2^24 in f32,
        # flipping the min_periods/ddof gates on exactly the long sequences
        # this module exists for
        s, count = _windowed((
            jnp.cumsum(jnp.stack(chans, -1), axis=0),
            jnp.cumsum(finite.astype(jnp.int32), axis=0),
        ))
        s1 = s[..., 0]
        s2 = s[..., 1] if need_s2 else None
        if stat == "moments":
            return s1, s2, count
        # SHARED finalizations — parity with the single-device kernels
        # holds by construction, not by transcription
        from fm_returnprediction_tpu.ops.rolling import (
            finalize_mean,
            finalize_std,
            finalize_sum,
        )

        if stat == "sum":
            return finalize_sum(s1, count, min_periods)
        if stat == "mean":
            return finalize_mean(s1, count, min_periods)
        return finalize_std(s1, s2, count, min_periods)

    out_specs = (
        (P(axis_name, None),) * 3 if stat == "moments" else P(axis_name, None)
    )
    return jax.jit(
        shard_map(
            kernel, mesh=mesh, in_specs=P(axis_name, None), out_specs=out_specs
        )
    )


def _prepare(x, window: int, mesh: Optional[Mesh], axis_name: str):
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    p = mesh.shape[axis_name]
    t = x.shape[0]
    x = pad_to_multiple(jnp.asarray(x), axis=0, multiple=p, fill=jnp.nan)
    shard_len = x.shape[0] // p
    if window > shard_len:
        raise ValueError(
            f"window={window} exceeds the per-shard sequence length "
            f"{shard_len} ({x.shape[0]} rows over {p} '{axis_name}' shards); "
            "the single-hop halo carries at most one shard of history"
        )
    x = place_global(x, NamedSharding(mesh, P(axis_name, None)))
    return x, t, mesh


def rolling_moments_time_sharded(
    x, window: int, mesh: Optional[Mesh] = None, axis_name: str = "time",
):
    """Trailing-window (Σx, Σx², count) with the TIME axis sharded.

    ``x``: (D, N); returns three (D, N) arrays, time-sharded on the mesh.
    Trailing NaN padding (ragged D) never leaks: trailing windows only look
    backward, and padded rows are trimmed from the result.
    """
    x, t, mesh = _prepare(x, window, mesh, axis_name)
    run = _jitted_rolling(mesh, axis_name, int(window), "moments", 0)
    s1, s2, cnt = run(x)
    return s1[:t], s2[:t], cnt[:t]


def rolling_sum_time_sharded(
    x, window: int, min_periods: int, mesh: Optional[Mesh] = None,
    axis_name: str = "time",
):
    """``ops.rolling.rolling_sum`` with the time axis sharded across devices."""
    x, t, mesh = _prepare(x, window, mesh, axis_name)
    run = _jitted_rolling(mesh, axis_name, int(window), "sum", int(min_periods))
    return run(x)[:t]


def rolling_mean_time_sharded(
    x, window: int, min_periods: int, mesh: Optional[Mesh] = None,
    axis_name: str = "time",
):
    """``ops.rolling.rolling_mean`` with the time axis sharded."""
    x, t, mesh = _prepare(x, window, mesh, axis_name)
    run = _jitted_rolling(mesh, axis_name, int(window), "mean", int(min_periods))
    return run(x)[:t]


def rolling_std_time_sharded(
    x, window: int, min_periods: int, mesh: Optional[Mesh] = None,
    axis_name: str = "time",
):
    """``ops.rolling.rolling_std`` (ddof=1) with the time axis sharded."""
    x, t, mesh = _prepare(x, window, mesh, axis_name)
    run = _jitted_rolling(mesh, axis_name, int(window), "std", int(min_periods))
    return run(x)[:t]


@functools.lru_cache(maxsize=8)
def _jitted_beta(mesh: Mesh, axis_name: str, n_weeks: int, n_months: int,
                 window_weeks: int):
    """One compiled time-sharded weekly-beta program per (mesh, config)."""
    from fm_returnprediction_tpu.ops.daily_kernels import (
        beta_from_weekly_sums,
        weekly_partial_sums,
    )

    def kernel(ret_l, mask_l, mkt_l, mkt_present_l, week_id_l, week_month_id):
        # Each shard aggregates ITS days into the GLOBAL week segments
        # (week ids are global indices); segment sums are linear, so one
        # psum of the six (n_weeks, N) partials reproduces the
        # single-device aggregation exactly. Weeks straddling a shard seam
        # need no halo — their partial rows simply come from two shards.
        sums = weekly_partial_sums(
            ret_l, mask_l, mkt_l, week_id_l, n_weeks,
            mkt_present=mkt_present_l,
        )
        sums = jax.lax.psum(sums, axis_name)
        # the windowing/validity/labeling half runs replicated: it is
        # O(n_weeks·N), ~1/5 of the daily volume
        return beta_from_weekly_sums(
            *sums, week_month_id, n_months, window_weeks
        )

    return jax.jit(
        shard_map(
            kernel,
            mesh=mesh,
            in_specs=(
                P(axis_name, None), P(axis_name, None), P(axis_name),
                P(axis_name), P(axis_name), P(),
            ),
            out_specs=P(),
        )
    )


def weekly_rolling_beta_time_sharded(
    ret_d,
    mask_d,
    mkt_d,
    week_id,
    n_weeks: int,
    week_month_id,
    n_months: int,
    window_weeks: int = 156,
    mkt_present=None,
    mesh: Optional[Mesh] = None,
    axis_name: str = "time",
):
    """``ops.daily_kernels.weekly_rolling_beta_monthly`` with the DAY axis
    sharded across devices — the long-context layout for the reference's
    heaviest kernel (SURVEY §3.5).

    The daily-volume work (masked log returns, per-week segment sums) runs
    shard-local; one ``psum`` of the six (n_weeks, N) weekly partials is
    the only communication, and the weekly windowing half runs replicated.
    Returns a fully replicated (n_months, N) array equal to the
    single-device kernel to rounding.
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    p = mesh.shape[axis_name]
    t = ret_d.shape[0]
    ret_d = pad_to_multiple(jnp.asarray(ret_d), axis=0, multiple=p, fill=jnp.nan)
    mask_d = pad_to_multiple(jnp.asarray(mask_d), axis=0, multiple=p, fill=False)
    mkt_d = pad_to_multiple(jnp.asarray(mkt_d), axis=0, multiple=p, fill=jnp.nan)
    if mkt_present is None:
        # mkt_d is already NaN-padded, and isfinite(NaN) is False — the
        # padding conventions compose with no extra slice/repad
        mkt_present = jnp.isfinite(mkt_d)
    else:
        mkt_present = pad_to_multiple(
            jnp.asarray(mkt_present), axis=0, multiple=p, fill=False
        )
    # padded rows carry mask/mkt_present False → every scattered value is 0,
    # so any in-range week id is safe for them
    week_id = pad_to_multiple(
        jnp.asarray(week_id).astype(jnp.int32), axis=0, multiple=p, fill=0
    )

    row = NamedSharding(mesh, P(axis_name))
    strip = NamedSharding(mesh, P(axis_name, None))
    rep = NamedSharding(mesh, P())
    run = _jitted_beta(mesh, axis_name, int(n_weeks), int(n_months),
                       int(window_weeks))
    return run(
        place_global(ret_d, strip),
        place_global(mask_d, strip),
        place_global(mkt_d, row),
        place_global(mkt_present, row),
        place_global(week_id, row),
        place_global(jnp.asarray(week_month_id).astype(jnp.int32), rep),
    )
