"""Firm-sharded Fama-MacBeth — explicit-collective SPMD over the device mesh.

The reference's hot loop (``src/regressions.py:43-72``; call stack SURVEY
§3.4) is serial per month. The single-chip replacement batches it with
``vmap`` (``ops.ols.monthly_cs_ols``); THIS module is the multi-chip path:
the firm axis N of the dense ``(T, N, P)`` panel is sharded over the mesh's
``"firms"`` axis with ``shard_map``, each device contracts its local firm
slice into per-month Gram matrices ``Xᵀdiag(v)X`` and moments ``Xᵀdiag(v)y``
(one MXU einsum each), and a single ``psum`` over ICI produces the global
sufficient statistics. The tiny ``(P+1)²`` solves, R² reconstruction, and
Newey-West aggregation then run replicated on every device — they are
O(T·P²), negligible next to the O(T·N·P²) contraction.

Communication cost per FM run: the default TSQR path (below) psums the
offset-placed R stack, ``T·D·(Q+1)²`` floats (~10 MB at T=600, D=8, Q=15);
the ``n_refine=0`` Gram fast path psums only the sufficient statistics,
``T·(Q² + Q + 3)`` floats (~150 KB). Either way the cross-section is
embarrassingly parallel up to one small collective, as SURVEY §5 predicts.

Numerics note: a pure normal-equation route (sufficient statistics are the
obvious thing collectives can sum) squares the design's condition number —
and the reference's ``n >= P+1`` gate (``src/regressions.py:52``) admits
(near-)rank-deficient boundary months where NO amount of Gram-side work can
recover the minimum-norm solution that ``lstsq``/statsmodels-pinv returns
(residual-correction refinement shrinks the residual but leaves the
near-null-space component unpinned — measured drift 2.4e+1 in round 2).
The default path is therefore DISTRIBUTED TSQR: each device computes a
thin-QR R factor of its local masked ``[X | y]`` block (one batched
``(T, N/D, Q+1)`` QR), the tiny ``(Q+1)×(Q+1)`` R factors are gathered
over ICI (as a psum of offset-placed blocks), and the replicated ``lstsq``
on the stacked R ``G`` solves the ORIGINAL problem exactly:
``GᵀG = [X|y]ᵀ[X|y]`` implies ``‖G_x β − g_y‖² = ‖Xβ − y‖²`` for every β,
so the minimum-norm least-squares solution of the compressed system IS the
global one, and ``cond(G_x) = cond(X)`` — no condition-number squaring.
When a local block has no more
rows than ``Q+1`` (the boundary-month regime), the QR step is skipped and
raw rows are stacked instead — the gathered system is then EXACTLY the
global one. Measured in ``tests/test_parallel.py``: near-singular months
that drift ~1e-4..1e+1 under the one-shot Gram route agree with
single-chip ``lstsq`` to ~1e-15 in the raw-stack regime and ~2e-6 at
cond 1e6 in the QR-compressed regime (f64) — both far inside the 1e-4
parity budget. ``n_refine=0`` selects the one-shot Gram fast path (one MXU
einsum + psum of sufficient statistics) for callers that know their months
are well-conditioned. R² is always recomputed from raw residuals rather
than reconstructed from rounded sufficient statistics.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.fama_macbeth import (
    FamaMacbethSummary,
    fama_macbeth_summary,
)
from fm_returnprediction_tpu.ops.ols import (
    CSRegressionResult,
    augment_design,
    gram_pinv,
    row_validity,
    sufficient_stats,
)
from fm_returnprediction_tpu.parallel.mesh import make_mesh, shard_map, shard_panel

__all__ = ["cs_ols_kernel", "monthly_cs_ols_sharded", "fama_macbeth_sharded"]

_PRECISION = jax.lax.Precision.HIGHEST


def _tsqr_lstsq(x_aug, y_z, axis_name: str, n_shards: int):
    """Distributed minimum-norm least squares via TSQR compression.

    Per month: QR the local masked ``[X | y]`` block, gather the small R
    factors, stack to ``G`` with ``GᵀG = [X|y]ᵀ[X|y]``, and solve the
    compressed system with the SAME ``jnp.linalg.lstsq`` (SVD) the
    single-chip parity path uses (``ops.ols._solve_month``) — identical
    objective, no condition-number squaring (module docstring). ``rcond``
    is passed explicitly as ``eps·(global padded row count)``: lstsq's
    default scales with the row count of the matrix it is GIVEN, and the
    compressed stack has ~D·(Q+1) rows where the single-chip design has N —
    without this, months with cond(X) between the two thresholds would be
    truncated on one path and solved on the other, blowing the parity
    budget. The gather is a psum of offset-placed blocks rather than
    ``all_gather`` so shard_map's replication checker can statically prove
    the stacked ``G`` (and hence the solution) is replicated.
    """
    n_rows_global = n_shards * x_aug.shape[1]
    rcond = jnp.finfo(x_aug.dtype).eps * max(n_rows_global, x_aug.shape[-1] + 1)
    m = jnp.concatenate([x_aug, y_z[..., None]], axis=-1)
    with jax.default_matmul_precision("highest"):
        if m.shape[1] <= m.shape[2]:
            # QR of a wide/square block is the same size as the block — no
            # compression, only rounding. Stack the raw rows instead: the
            # gathered G is then exactly the global [X | y] (contiguous firm
            # shards preserve row order), so the solve below is bit-identical
            # in exact arithmetic to the single-chip lstsq. This is the
            # boundary-month regime (few rows per shard) where parity
            # matters most.
            r_local = m
        else:
            r_local = jnp.linalg.qr(m, mode="r")  # (T, Q+1, Q+1)
        t, k, q1 = r_local.shape
        stack = jnp.zeros((t, n_shards * k, q1), r_local.dtype)
        offset = jax.lax.axis_index(axis_name) * k
        zero = jnp.zeros((), offset.dtype)
        stack = jax.lax.dynamic_update_slice(stack, r_local, (zero, offset, zero))
        g = jax.lax.psum(stack, axis_name)
        beta = jax.vmap(lambda a, b: jnp.linalg.lstsq(a, b, rcond=rcond)[0])(
            g[..., :-1], g[..., -1]
        )
    return beta


def cs_ols_kernel(y_l, x_l, mask_l, axis_name: str, n_shards: int, n_refine: int):
    """The per-device cross-sectional OLS body, for use INSIDE ``shard_map``.

    ``y_l (T, N/D)``, ``x_l (T, N/D, P)``, ``mask_l (T, N/D)`` are the local
    firm shard; the only collectives are psums over ``axis_name`` (the firm
    axis), so a caller may map additional mesh axes over the month dimension
    with zero extra communication (``parallel.multihost``). Returns a
    ``CSRegressionResult`` whose leaves are replicated over ``axis_name``.
    """
    valid = row_validity(y_l, x_l, mask_l)
    x_aug, y_z, v = augment_design(y_l, x_l, valid)
    if n_refine == 0:
        # Sufficient stats are additive over firm shards (ops.ols
        # docstring), so local contraction + one psum == global.
        stats = jax.lax.psum(sufficient_stats(y_l, x_l, valid), axis_name)
        n, ysum, yy = stats.n, stats.ysum, stats.yy
        pinv, month_valid = gram_pinv(stats)
        beta = jnp.einsum("tpq,tq->tp", pinv, stats.moment, precision=_PRECISION)
    else:
        n, ysum, yy = jax.lax.psum(
            (v.sum(-1), y_z.sum(-1), jnp.sum(y_z * y_z, -1)), axis_name
        )
        month_valid = n >= x_aug.shape[-1]
        beta = _tsqr_lstsq(x_aug, y_z, axis_name, n_shards)
    beta = jnp.where(month_valid[:, None], beta, 0.0)

    # R² from raw residuals of the solved coefficients (centered, as
    # statsmodels' rsquared) — not the rounded Gram reconstruction.
    resid = (
        y_z - jnp.einsum("tnq,tq->tn", x_aug, beta, precision=_PRECISION)
    ) * v
    sse = jax.lax.psum(jnp.sum(resid * resid, axis=1), axis_name)
    sst = yy - ysum * ysum / jnp.maximum(n, 1.0)
    r2 = jnp.where(sst > 0, 1.0 - sse / jnp.where(sst > 0, sst, 1.0), 0.0)
    r2 = jnp.where(month_valid, r2, 0.0)
    return CSRegressionResult(beta[:, 1:], beta[:, 0], r2, n, month_valid)


def monthly_cs_ols_sharded(
    y, x, mask, mesh: Mesh, axis_name: str = "firms", n_refine: int = 2
) -> CSRegressionResult:
    """Cross-sectional OLS for every month, firm axis sharded over ``mesh``.

    Inputs must already be firm-sharded/padded (see ``mesh.shard_panel``).
    Result leaves are replicated across devices. ``n_refine >= 1`` (default)
    selects the distributed TSQR solve with single-chip ``lstsq`` parity on
    every month including (near-)rank-deficient ones; ``n_refine=0``
    restores the one-shot Gram solve, which is faster (one MXU einsum) but
    drifts on ill-conditioned months (module docstring). The parameter name
    is kept from the retired residual-refinement design for API
    compatibility; the step count beyond 1 is ignored.
    """

    def kernel(y_l, x_l, mask_l):
        return cs_ols_kernel(
            y_l, x_l, mask_l, axis_name, mesh.shape[axis_name], n_refine
        )

    shard = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name, None), P(None, axis_name)),
        out_specs=CSRegressionResult(P(), P(), P(), P(), P()),
    )
    return shard(y, x, mask)


@functools.lru_cache(maxsize=32)
def _jitted_fm(mesh: Mesh, nw_lags: int, min_months: int, weight: str,
               axis_name: str, n_refine: int):
    """One compiled sharded-FM program per (mesh, hyperparameter) combo.

    ``jax.jit``'s cache is keyed on the function object, so defining the
    closure inside ``fama_macbeth_sharded`` would retrace and recompile on
    every call — 9× the 20-40 s XLA compile over a 3-model × 3-subset sweep.
    ``Mesh`` is hashable, so it keys the lru_cache directly.
    """

    @jax.jit
    def run(y, x, mask):
        cs = monthly_cs_ols_sharded(
            y, x, mask, mesh, axis_name=axis_name, n_refine=n_refine
        )
        summary = fama_macbeth_summary(
            cs, nw_lags=nw_lags, min_months=min_months, weight=weight
        )
        return cs, summary

    return run


def fama_macbeth_sharded(
    y,
    x,
    mask,
    mesh: Optional[Mesh] = None,
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
    axis_name: str = "firms",
    place: bool = True,
    n_refine: int = 2,
) -> tuple[CSRegressionResult, FamaMacbethSummary]:
    """End-to-end multi-chip FM: shard the panel, contract + psum, aggregate.

    ``place=True`` pads the firm axis and device_puts with a firm-sharded
    ``NamedSharding`` first; pass ``place=False`` when the caller already
    laid the arrays out (e.g. inside a larger pjit program).
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    if place:
        y, x, mask = shard_panel(y, x, mask, mesh, axis_name=axis_name)
    # Only the 0-vs-nonzero distinction changes the program (TSQR vs Gram),
    # so normalize to keep the compile cache at two entries per mesh.
    run = _jitted_fm(mesh, nw_lags, min_months, weight, axis_name, min(n_refine, 1))
    return run(y, x, mask)
