"""Firm-sharded Fama-MacBeth — explicit-collective SPMD over the device mesh.

The reference's hot loop (``src/regressions.py:43-72``; call stack SURVEY
§3.4) is serial per month. The single-chip replacement batches it with
``vmap`` (``ops.ols.monthly_cs_ols``); THIS module is the multi-chip path:
the firm axis N of the dense ``(T, N, P)`` panel is sharded over the mesh's
``"firms"`` axis with ``shard_map``, each device contracts its local firm
slice into per-month Gram matrices ``Xᵀdiag(v)X`` and moments ``Xᵀdiag(v)y``
(one MXU einsum each), and a single ``psum`` over ICI produces the global
sufficient statistics. The tiny ``(P+1)²`` solves, R² reconstruction, and
Newey-West aggregation then run replicated on every device — they are
O(T·P²), negligible next to the O(T·N·P²) contraction.

Communication cost per FM run: one psum of ``T·(P+1)² + T·(P+1) + 3T``
floats — for the full Lewellen panel (T≈600, P=14) that is ~150 KB, i.e.
the cross-section is embarrassingly parallel exactly as SURVEY §5 predicts.

Numerics note: the distributed path necessarily uses the normal-equation
route (sufficient statistics are what collectives can sum), which squares
the design's condition number — and the reference's ``n >= P+1`` gate
(``src/regressions.py:52``) admits near-singular boundary months where a
one-shot Gram solve visibly drifts from the SVD parity path. The fallback
is ITERATIVE REFINEMENT entirely inside SPMD: after the Gram solve, each
step recomputes residuals from the RAW sharded rows (not from the rounded
Gram product), psums the correction moment ``Xᵀr``, and re-solves against
the cached Gram pseudo-inverse. Each step costs one extra O(T·N·P/D)
contraction + one O(T·P) psum and recovers the accuracy the Gram route
lost (measured in ``tests/test_parallel.py``: near-singular months that
drift ~1e-4 one-shot agree with lstsq to ~1e-9 after two steps in f64).
R² is likewise recomputed from raw residuals rather than reconstructed
from rounded sufficient statistics.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.fama_macbeth import (
    FamaMacbethSummary,
    fama_macbeth_summary,
)
from fm_returnprediction_tpu.ops.ols import (
    CSRegressionResult,
    augment_design,
    gram_pinv,
    row_validity,
    sufficient_stats,
)
from fm_returnprediction_tpu.parallel.mesh import make_mesh, shard_panel

__all__ = ["monthly_cs_ols_sharded", "fama_macbeth_sharded"]

_PRECISION = jax.lax.Precision.HIGHEST


def monthly_cs_ols_sharded(
    y, x, mask, mesh: Mesh, axis_name: str = "firms", n_refine: int = 2
) -> CSRegressionResult:
    """Cross-sectional OLS for every month, firm axis sharded over ``mesh``.

    Inputs must already be firm-sharded/padded (see ``mesh.shard_panel``).
    Result leaves are replicated across devices. ``n_refine`` iterative-
    refinement steps (module docstring) pull near-singular months back to
    the SVD parity solution; 0 restores the one-shot Gram solve.
    """

    def kernel(y_l, x_l, mask_l):
        valid = row_validity(y_l, x_l, mask_l)
        x_aug, y_z, v = augment_design(y_l, x_l, valid)
        # Sufficient stats are additive over firm shards (ops.ols docstring),
        # so the local contraction + one psum == the global contraction.
        stats = jax.lax.psum(
            sufficient_stats(y_l, x_l, valid), axis_name
        )  # one ICI collective
        pinv, month_valid = gram_pinv(stats)
        beta = jnp.einsum("tpq,tq->tp", pinv, stats.moment, precision=_PRECISION)
        beta = jnp.where(month_valid[:, None], beta, 0.0)

        def residual(b):
            return (
                y_z - jnp.einsum("tnq,tq->tn", x_aug, b, precision=_PRECISION)
            ) * v

        for _ in range(n_refine):
            # Correction moment from RAW rows — the quantity the one-shot
            # Gram product rounds away; one psum of T·(P+1) floats per step.
            corr = jax.lax.psum(
                jnp.einsum("tnq,tn->tq", x_aug, residual(beta), precision=_PRECISION),
                axis_name,
            )
            delta = jnp.einsum("tpq,tq->tp", pinv, corr, precision=_PRECISION)
            beta = beta + jnp.where(month_valid[:, None], delta, 0.0)

        # R² from raw residuals of the refined solution (centered, as
        # statsmodels' rsquared) — not the rounded Gram reconstruction.
        resid = residual(beta)
        sse = jax.lax.psum(jnp.sum(resid * resid, axis=1), axis_name)
        sst = stats.yy - stats.ysum * stats.ysum / jnp.maximum(stats.n, 1.0)
        r2 = jnp.where(sst > 0, 1.0 - sse / jnp.where(sst > 0, sst, 1.0), 0.0)
        r2 = jnp.where(month_valid, r2, 0.0)
        return CSRegressionResult(beta[:, 1:], beta[:, 0], r2, stats.n, month_valid)

    shard = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name, None), P(None, axis_name)),
        out_specs=CSRegressionResult(P(), P(), P(), P(), P()),
    )
    return shard(y, x, mask)


@functools.lru_cache(maxsize=32)
def _jitted_fm(mesh: Mesh, nw_lags: int, min_months: int, weight: str,
               axis_name: str, n_refine: int):
    """One compiled sharded-FM program per (mesh, hyperparameter) combo.

    ``jax.jit``'s cache is keyed on the function object, so defining the
    closure inside ``fama_macbeth_sharded`` would retrace and recompile on
    every call — 9× the 20-40 s XLA compile over a 3-model × 3-subset sweep.
    ``Mesh`` is hashable, so it keys the lru_cache directly.
    """

    @jax.jit
    def run(y, x, mask):
        cs = monthly_cs_ols_sharded(
            y, x, mask, mesh, axis_name=axis_name, n_refine=n_refine
        )
        summary = fama_macbeth_summary(
            cs, nw_lags=nw_lags, min_months=min_months, weight=weight
        )
        return cs, summary

    return run


def fama_macbeth_sharded(
    y,
    x,
    mask,
    mesh: Optional[Mesh] = None,
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
    axis_name: str = "firms",
    place: bool = True,
    n_refine: int = 2,
) -> tuple[CSRegressionResult, FamaMacbethSummary]:
    """End-to-end multi-chip FM: shard the panel, contract + psum, aggregate.

    ``place=True`` pads the firm axis and device_puts with a firm-sharded
    ``NamedSharding`` first; pass ``place=False`` when the caller already
    laid the arrays out (e.g. inside a larger pjit program).
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    if place:
        y, x, mask = shard_panel(y, x, mask, mesh, axis_name=axis_name)
    run = _jitted_fm(mesh, nw_lags, min_months, weight, axis_name, n_refine)
    return run(y, x, mask)
