"""Firm-sharded Fama-MacBeth — explicit-collective SPMD over the device mesh.

The reference's hot loop (``src/regressions.py:43-72``; call stack SURVEY
§3.4) is serial per month. The single-chip replacement batches it with
``vmap`` (``ops.ols.monthly_cs_ols``); THIS module is the multi-chip path:
the firm axis N of the dense ``(T, N, P)`` panel is sharded over the mesh's
``"firms"`` axis with ``shard_map``, each device contracts its local firm
slice into per-month Gram matrices ``Xᵀdiag(v)X`` and moments ``Xᵀdiag(v)y``
(one MXU einsum each), and a single ``psum`` over ICI produces the global
sufficient statistics. The tiny ``(P+1)²`` solves, R² reconstruction, and
Newey-West aggregation then run replicated on every device — they are
O(T·P²), negligible next to the O(T·N·P²) contraction.

Communication cost per FM run: one psum of ``T·(P+1)² + T·(P+1) + 3T``
floats — for the full Lewellen panel (T≈600, P=14) that is ~150 KB, i.e.
the cross-section is embarrassingly parallel exactly as SURVEY §5 predicts.

Numerics note: the distributed path necessarily uses the normal-equation
route (sufficient statistics are what collectives can sum), which matches
``ops.ols`` ``solver="normal"``. Months that are nearly singular can drift
from the SVD path; the parity suite pins both against the numpy oracle on
well-conditioned panels, and degenerate months remain gated by
``month_valid`` (reference guard ``src/regressions.py:52``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P

from fm_returnprediction_tpu.ops.fama_macbeth import (
    FamaMacbethSummary,
    fama_macbeth_summary,
)
from fm_returnprediction_tpu.ops.ols import (
    CSRegressionResult,
    row_validity,
    solve_from_stats,
    sufficient_stats,
)
from fm_returnprediction_tpu.parallel.mesh import make_mesh, shard_panel

__all__ = ["monthly_cs_ols_sharded", "fama_macbeth_sharded"]


def monthly_cs_ols_sharded(
    y, x, mask, mesh: Mesh, axis_name: str = "firms"
) -> CSRegressionResult:
    """Cross-sectional OLS for every month, firm axis sharded over ``mesh``.

    Inputs must already be firm-sharded/padded (see ``mesh.shard_panel``).
    Result leaves are replicated across devices.
    """

    def kernel(y_l, x_l, mask_l):
        # Sufficient stats are additive over firm shards (ops.ols docstring),
        # so the local contraction + one psum == the global contraction.
        stats = sufficient_stats(y_l, x_l, row_validity(y_l, x_l, mask_l))
        stats = jax.lax.psum(stats, axis_name)  # one ICI collective
        return CSRegressionResult(*solve_from_stats(stats))

    shard = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name, None), P(None, axis_name)),
        out_specs=CSRegressionResult(P(), P(), P(), P(), P()),
    )
    return shard(y, x, mask)


@functools.lru_cache(maxsize=32)
def _jitted_fm(mesh: Mesh, nw_lags: int, min_months: int, weight: str, axis_name: str):
    """One compiled sharded-FM program per (mesh, hyperparameter) combo.

    ``jax.jit``'s cache is keyed on the function object, so defining the
    closure inside ``fama_macbeth_sharded`` would retrace and recompile on
    every call — 9× the 20-40 s XLA compile over a 3-model × 3-subset sweep.
    ``Mesh`` is hashable, so it keys the lru_cache directly.
    """

    @jax.jit
    def run(y, x, mask):
        cs = monthly_cs_ols_sharded(y, x, mask, mesh, axis_name=axis_name)
        summary = fama_macbeth_summary(
            cs, nw_lags=nw_lags, min_months=min_months, weight=weight
        )
        return cs, summary

    return run


def fama_macbeth_sharded(
    y,
    x,
    mask,
    mesh: Optional[Mesh] = None,
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
    axis_name: str = "firms",
    place: bool = True,
) -> tuple[CSRegressionResult, FamaMacbethSummary]:
    """End-to-end multi-chip FM: shard the panel, contract + psum, aggregate.

    ``place=True`` pads the firm axis and device_puts with a firm-sharded
    ``NamedSharding`` first; pass ``place=False`` when the caller already
    laid the arrays out (e.g. inside a larger pjit program).
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    if place:
        y, x, mask = shard_panel(y, x, mask, mesh, axis_name=axis_name)
    run = _jitted_fm(mesh, nw_lags, min_months, weight, axis_name)
    return run(y, x, mask)
