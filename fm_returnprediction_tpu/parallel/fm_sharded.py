"""Firm-sharded Fama-MacBeth — explicit-collective SPMD over the device mesh.

The reference's hot loop (``src/regressions.py:43-72``; call stack SURVEY
§3.4) is serial per month. The single-chip replacement batches it with
``vmap`` (``ops.ols.monthly_cs_ols``); THIS module is the multi-chip path:
the firm axis N of the dense ``(T, N, P)`` panel is sharded over the mesh's
``"firms"`` axis with ``shard_map``, each device contracts its local firm
slice into per-month Gram matrices ``Xᵀdiag(v)X`` and moments ``Xᵀdiag(v)y``
(one MXU einsum each), and a single ``psum`` over ICI produces the global
sufficient statistics. The tiny ``(P+1)²`` solves, R² reconstruction, and
Newey-West aggregation then run replicated on every device — they are
O(T·P²), negligible next to the O(T·N·P²) contraction.

Communication cost per FM run: one psum of ``T·(P+1)² + T·(P+1) + 3T``
floats — for the full Lewellen panel (T≈600, P=14) that is ~150 KB, i.e.
the cross-section is embarrassingly parallel exactly as SURVEY §5 predicts.

Numerics note: the distributed path necessarily uses the normal-equation
route (sufficient statistics are what collectives can sum), which matches
``ops.ols`` ``solver="normal"``. Months that are nearly singular can drift
from the SVD path; the parity suite pins both against the numpy oracle on
well-conditioned panels, and degenerate months remain gated by
``month_valid`` (reference guard ``src/regressions.py:52``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from fm_returnprediction_tpu.ops.fama_macbeth import (
    FamaMacbethSummary,
    fama_macbeth_summary,
)
from fm_returnprediction_tpu.ops.ols import CSRegressionResult, row_validity
from fm_returnprediction_tpu.parallel.mesh import make_mesh, shard_panel

__all__ = ["monthly_cs_ols_sharded", "fama_macbeth_sharded"]

_PRECISION = jax.lax.Precision.HIGHEST


def _local_sufficient_stats(y, x, mask):
    """Per-device contraction of the local firm slice into month-wise
    sufficient statistics. Shapes (local): y (T, Nl), x (T, Nl, P).

    Returns (gram (T,Q,Q), moment (T,Q), n (T,), ysum (T,), yy (T,)) with
    Q = P + 1 (intercept column first, as the reference builds
    ``sm.add_constant``-style designs at ``src/regressions.py:49``).
    """
    valid = row_validity(y, x, mask)
    v = valid.astype(x.dtype)
    ones = jnp.ones_like(y)
    x_aug = jnp.concatenate(
        [ones[..., None], jnp.where(valid[..., None], x, 0.0)], axis=-1
    )
    x_aug = x_aug * v[..., None]
    y_z = jnp.where(valid, y, 0.0)

    gram = jnp.einsum("tnp,tnq->tpq", x_aug, x_aug, precision=_PRECISION)
    moment = jnp.einsum("tnp,tn->tp", x_aug, y_z, precision=_PRECISION)
    n = v.sum(axis=1)
    ysum = y_z.sum(axis=1)
    yy = jnp.sum(y_z * y_z, axis=1)
    return gram, moment, n, ysum, yy


def _solve_from_stats(gram, moment, n, ysum, yy) -> CSRegressionResult:
    """Replicated month solves from globally-summed sufficient statistics.

    Reproduces ``ops.ols._solve_month`` (solver="normal") semantics:
    skipped months carry zero slopes/R² and ``month_valid=False``; R² is the
    centered statsmodels ``rsquared`` (``src/regressions.py:60-66``),
    reconstructed as 1 − SSE/SST with SSE = yᵀy − 2βᵀb + βᵀGβ.
    """
    q = gram.shape[-1]
    month_valid = n >= q
    eye = jnp.eye(q, dtype=gram.dtype)
    safe_gram = jnp.where(month_valid[:, None, None], gram, eye)
    with jax.default_matmul_precision("highest"):
        beta = jnp.einsum(
            "tpq,tq->tp", jnp.linalg.pinv(safe_gram), moment, precision=_PRECISION
        )
    beta = jnp.where(month_valid[:, None], beta, 0.0)

    bg = jnp.einsum("tp,tpq,tq->t", beta, gram, beta, precision=_PRECISION)
    bm = jnp.einsum("tp,tp->t", beta, moment, precision=_PRECISION)
    sse = yy - 2.0 * bm + bg
    nf = jnp.maximum(n, 1.0)
    sst = yy - ysum * ysum / nf
    r2 = jnp.where(sst > 0, 1.0 - sse / jnp.where(sst > 0, sst, 1.0), 0.0)
    r2 = jnp.where(month_valid, r2, 0.0)
    return CSRegressionResult(beta[:, 1:], beta[:, 0], r2, n, month_valid)


def monthly_cs_ols_sharded(
    y, x, mask, mesh: Mesh, axis_name: str = "firms"
) -> CSRegressionResult:
    """Cross-sectional OLS for every month, firm axis sharded over ``mesh``.

    Inputs must already be firm-sharded/padded (see ``mesh.shard_panel``).
    Result leaves are replicated across devices.
    """

    def kernel(y_l, x_l, mask_l):
        stats = _local_sufficient_stats(y_l, x_l, mask_l)
        stats = jax.lax.psum(stats, axis_name)  # one ICI collective
        return _solve_from_stats(*stats)

    shard = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, axis_name), P(None, axis_name, None), P(None, axis_name)),
        out_specs=CSRegressionResult(P(), P(), P(), P(), P()),
    )
    return shard(y, x, mask)


@functools.lru_cache(maxsize=32)
def _jitted_fm(mesh: Mesh, nw_lags: int, min_months: int, weight: str, axis_name: str):
    """One compiled sharded-FM program per (mesh, hyperparameter) combo.

    ``jax.jit``'s cache is keyed on the function object, so defining the
    closure inside ``fama_macbeth_sharded`` would retrace and recompile on
    every call — 9× the 20-40 s XLA compile over a 3-model × 3-subset sweep.
    ``Mesh`` is hashable, so it keys the lru_cache directly.
    """

    @jax.jit
    def run(y, x, mask):
        cs = monthly_cs_ols_sharded(y, x, mask, mesh, axis_name=axis_name)
        summary = fama_macbeth_summary(
            cs, nw_lags=nw_lags, min_months=min_months, weight=weight
        )
        return cs, summary

    return run


def fama_macbeth_sharded(
    y,
    x,
    mask,
    mesh: Optional[Mesh] = None,
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
    axis_name: str = "firms",
    place: bool = True,
) -> tuple[CSRegressionResult, FamaMacbethSummary]:
    """End-to-end multi-chip FM: shard the panel, contract + psum, aggregate.

    ``place=True`` pads the firm axis and device_puts with a firm-sharded
    ``NamedSharding`` first; pass ``place=False`` when the caller already
    laid the arrays out (e.g. inside a larger pjit program).
    """
    if mesh is None:
        mesh = make_mesh(axis_name=axis_name)
    if place:
        y, x, mask = shard_panel(y, x, mask, mesh, axis_name=axis_name)
    run = _jitted_fm(mesh, nw_lags, min_months, weight, axis_name)
    return run(y, x, mask)
