"""Sharded moving-block bootstrap for Fama-MacBeth standard errors.

The reference reports only Newey-West analytic SEs (``src/regressions.py:
78-100``); the north-star workload (BASELINE.json configs[4]) adds a
10k-replicate block bootstrap of the monthly slope series, sharded across
the chip mesh. Replicates are embarrassingly parallel: each device draws its
own replicate slice with a folded PRNG key, computes local replicate means,
and contributes moment sums to one final ``psum`` — communication is
O(P) floats regardless of replicate count.

Design (matching the FM layer's validity semantics):

- Each predictor's slope series is compacted to its valid months in
  chronological order (exactly how ``nw_mean_se`` pairs adjacent SURVIVING
  months, ``src/regressions.py:113`` + SURVEY §2.2.8) of length ``n_p``.
- A replicate resamples the compacted series with a moving-block bootstrap:
  position ``j`` of the pseudo-series takes block ``j // L`` at offset
  ``j % L`` from a uniformly drawn start in ``[0, n_p − L]``; the replicate
  statistic is the mean of the first ``n_p`` positions. With static shapes
  this is a gather — no dynamic control flow, jit/TPU friendly.
- Bootstrap SE per predictor = std (ddof=1) of replicate means. On a mesh,
  each device reduces its local replicate means to first/second moment sums
  and ONE psum of 2·P floats combines them — communication is O(P)
  regardless of replicate count.

Block length defaults to ``nw_lags + 1 = 5`` months, the standard choice for
matching a lag-L Newey-West horizon.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fm_returnprediction_tpu.ops.newey_west import compact_front
from fm_returnprediction_tpu.parallel.mesh import shard_map

__all__ = ["BootstrapResult", "block_bootstrap_se", "bootstrap_replicate_means"]


class BootstrapResult(NamedTuple):
    se: jnp.ndarray          # (P,) bootstrap SE of the mean slope
    mean: jnp.ndarray        # (P,) mean of replicate means (bias diagnostic)
    n_replicates: int        # B actually drawn
    block_length: int


def _replicate_means_one_predictor(series, n_valid, keys, block_length):
    """Replicate means for ONE predictor's compacted slope series.

    series : (T,) compacted values (valid entries first, tail zeroed)
    n_valid: () number of valid entries
    keys   : (B,) typed PRNG keys, one per replicate
    Returns (B,) replicate means. Predictors with n_valid <= block_length
    yield NaN: with at most one distinct block start every replicate is the
    exact sample mean, which would report a spuriously ~0 SE (ADVICE r1).
    """
    t_max = series.shape[0]
    n = jnp.maximum(n_valid, 1)
    # Highest valid block start: n - L (clamped at 0 when the series is
    # shorter than one block — the block then wraps within the valid region
    # via the index clamp below).
    max_start = jnp.maximum(n - block_length, 0)
    n_blocks = -(-t_max // block_length)  # ceil over the static axis

    def one_rep(key):
        starts = jax.random.randint(key, (n_blocks,), 0, max_start + 1)
        j = jnp.arange(t_max)
        idx = starts[j // block_length] + (j % block_length)
        idx = jnp.minimum(idx, n - 1)  # clamp inside the valid region
        pseudo = series[idx]
        w = (j < n_valid).astype(series.dtype)
        return jnp.sum(pseudo * w) / jnp.maximum(n_valid, 1).astype(series.dtype)

    means = jax.vmap(one_rep)(keys)
    return jnp.where(n_valid > block_length, means, jnp.nan)


def bootstrap_replicate_means(
    slopes: jnp.ndarray,
    slope_valid: jnp.ndarray,
    keys: jnp.ndarray,
    block_length: int,
) -> jnp.ndarray:
    """(B, P) replicate means for every predictor. Pure function of the
    replicate keys — the unit the mesh shards over."""
    series, counts = jax.vmap(compact_front, in_axes=(1, 1))(slopes, slope_valid)
    return jax.vmap(
        lambda s, c: _replicate_means_one_predictor(s, c, keys, block_length),
        out_axes=1,
    )(series, counts)


@functools.lru_cache(maxsize=32)
def _jitted_bootstrap_moments(mesh: Optional[Mesh], block_length: int, axis_name: str):
    """One compiled bootstrap program per (mesh, block length).

    Like ``fm_sharded._jitted_fm``: a closure freshly defined per call would
    defeat jit's function-identity cache and retrace/recompile the
    10k-replicate program on every invocation of a 3×3 model sweep.

    Returns a jitted ``(keys, slopes, slope_valid) -> (Σd, Σd²)`` where
    ``d = replicate mean − pilot mean`` and the pilot is the full-sample
    mean slope (deterministic, identical on every device). Centering before
    the moment reduction keeps f32 runs away from the E[x²]−μ² catastrophic
    cancellation (replicate spread can be orders of magnitude below the
    mean). Both outputs are (P,), so the mesh version psums exactly 2·P
    floats and replicates the result.
    """

    def moments(keys, slopes, slope_valid):
        v = slope_valid.astype(slopes.dtype)
        pilot = jnp.sum(jnp.where(slope_valid, slopes, 0.0), axis=0) / jnp.maximum(
            v.sum(axis=0), 1.0
        )
        means = bootstrap_replicate_means(slopes, slope_valid, keys, block_length)
        d = means - pilot[None, :]
        return d.sum(axis=0), jnp.sum(d * d, axis=0), pilot

    if mesh is None:
        return jax.jit(moments)

    def kernel(keys_l, slopes_r, valid_r):
        s1, s2, pilot = moments(keys_l, slopes_r, valid_r)
        # pilot is a pure function of the replicated slopes — identical on
        # every device, so it is NOT psummed.
        s1, s2 = jax.lax.psum((s1, s2), axis_name)  # 2·P floats over ICI
        return s1, s2, pilot

    return jax.jit(
        shard_map(
            kernel,
            mesh=mesh,
            in_specs=(P(axis_name), P(), P()),
            out_specs=(P(), P(), P()),
        )
    )


def block_bootstrap_se(
    slopes: jnp.ndarray,
    slope_valid: jnp.ndarray,
    key: jax.Array,
    n_replicates: int = 10_000,
    block_length: int = 5,
    mesh: Optional[Mesh] = None,
    axis_name: str = "boot",
) -> BootstrapResult:
    """Moving-block bootstrap SE of the mean slope, per predictor.

    Parameters
    ----------
    slopes      : (T, P) monthly slope estimates (from ``monthly_cs_ols``).
    slope_valid : (T, P) bool — month ran AND slope finite.
    key         : PRNG key.
    n_replicates: total replicates B (rounded up to a mesh multiple).
    mesh        : optional 1-D mesh; replicates shard over ``axis_name``.
                  None = single-device vmap.
    """
    if n_replicates < 2:
        raise ValueError(
            f"n_replicates must be >= 2 for a ddof=1 variance, got {n_replicates}"
        )
    slopes = jnp.asarray(slopes)
    slope_valid = jnp.asarray(slope_valid)

    if mesh is None:
        b = n_replicates
        keys = jax.random.split(key, b)
    else:
        from fm_returnprediction_tpu.parallel.mesh import place_global

        d = mesh.shape[axis_name]
        b = -(-n_replicates // d) * d
        keys = place_global(
            jax.random.split(key, b), NamedSharding(mesh, P(axis_name))
        )
        # Replicate the (small) slope series across the mesh so the jitted
        # shard_map sees consistent placements even when slopes arrived
        # committed to a single device (e.g. as another jit's output).
        # place_global, not device_put: slopes carry NaN months, which the
        # cross-process device_put value check cannot compare.
        slopes = place_global(slopes, NamedSharding(mesh, P()))
        slope_valid = place_global(slope_valid, NamedSharding(mesh, P()))

    run = _jitted_bootstrap_moments(mesh, block_length, axis_name)
    s1, s2, pilot = run(keys, slopes, slope_valid)

    # Moments are of deviations from the pilot mean: mean = pilot + Σd/B,
    # var = (Σd² − (Σd)²/B)/(B−1) — numerically safe because d is small.
    bf = jnp.asarray(b, dtype=slopes.dtype)
    mean = pilot + s1 / bf
    var = (s2 - s1 * s1 / bf) / (bf - 1.0)
    return BootstrapResult(jnp.sqrt(jnp.maximum(var, 0.0)), mean, b, block_length)
