"""Firm-sharded daily kernels — scaling the largest data volume.

Daily CRSP 1964-2013 is O(10⁷-10⁸) firm-day rows, the reference's heaviest
computation (the polars beta kernel + 252-day rolling std, SURVEY §3.5).
On the dense (D, N) daily panel every kernel in ``ops.daily_kernels`` is
independent along the firm axis N (rolling windows and weekly segment sums
run along days *within* a firm column), so the whole daily stage shards
over the mesh's ``"firms"`` axis with ZERO collectives: each device holds a
(D, N/d) strip, per-day vectors (market return, week/month ids) are
replicated, and the (n_months, N/d) outputs come back firm-sharded, ready
for the firm-sharded FM stage.

This is the framework's long-context story (SURVEY §5 "Long-context /
sequence parallelism"): the time axis stays on-device as scans/windowed
reductions; the embarrassingly-parallel firm axis is what crosses chips.

Implementation: inputs are placed with firm-sharded ``NamedSharding`` and
the jitted kernels run under XLA's SPMD partitioner, which confirms the
zero-communication partition (no collectives are in the compiled program —
asserted by the test suite via compiled-HLO inspection).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fm_returnprediction_tpu.ops.daily_kernels import (
    rolling_vol_252_monthly,
    weekly_rolling_beta_monthly,
)
from fm_returnprediction_tpu.parallel.mesh import pad_to_multiple, place_global

__all__ = ["daily_characteristics_sharded"]


@functools.lru_cache(maxsize=8)
def _jitted_daily(mesh: Mesh, axis_name: str, n_months: int, n_weeks: int,
                  window: int, min_periods: int, window_weeks: int):
    """One compiled firm-sharded daily program per (mesh, static config)."""

    @functools.partial(jax.jit, static_argnames=())
    def run(ret_d, mask_d, mkt_d, mkt_present, month_id, week_id, week_month_id):
        vol = rolling_vol_252_monthly(
            ret_d, mask_d, month_id, n_months,
            window=window, min_periods=min_periods,
            # GSPMD has no partitioning rule for the pallas custom-call; the
            # XLA cumsum path partitions collective-free over the firm axis.
            use_pallas=False,
        )
        beta = weekly_rolling_beta_monthly(
            ret_d, mask_d, mkt_d, week_id, n_weeks, week_month_id, n_months,
            window_weeks=window_weeks, mkt_present=mkt_present,
        )
        return vol, beta

    return run


def daily_characteristics_sharded(
    ret_d,
    mask_d,
    mkt_d,
    month_id,
    week_id,
    week_month_id,
    n_months: int,
    n_weeks: int,
    mesh: Mesh,
    mkt_present=None,
    window: int = 252,
    min_periods: int = 100,
    window_weeks: int = 156,
    axis_name: str = "firms",
):
    """Compute vol-252 and weekly beta with the firm axis sharded.

    Returns (vol, beta), each (n_months, N_padded) firm-sharded on the mesh
    (slice ``[:, :N]`` on the host to drop the padding columns).
    """
    d = mesh.shape[axis_name]
    ret_d = pad_to_multiple(jnp.asarray(ret_d), axis=1, multiple=d, fill=jnp.nan)
    mask_d = pad_to_multiple(jnp.asarray(mask_d), axis=1, multiple=d, fill=False)
    if mkt_present is None:
        mkt_present = jnp.isfinite(jnp.asarray(mkt_d))

    strip = NamedSharding(mesh, P(None, axis_name))
    rep = NamedSharding(mesh, P())
    ret_d = place_global(ret_d, strip)          # NaN-padded: see place_global
    mask_d = place_global(mask_d, strip)
    mkt_d = place_global(jnp.asarray(mkt_d), rep)
    mkt_present = place_global(jnp.asarray(mkt_present), rep)
    month_id = place_global(jnp.asarray(month_id), rep)
    week_id = place_global(jnp.asarray(week_id), rep)
    week_month_id = place_global(jnp.asarray(week_month_id), rep)

    run = _jitted_daily(
        mesh, axis_name, int(n_months), int(n_weeks),
        int(window), int(min_periods), int(window_weeks),
    )
    return run(ret_d, mask_d, mkt_d, mkt_present, month_id, week_id, week_month_id)
