"""Multi-host execution: distributed runtime init + months×firms 2-D mesh.

The reference has no distributed layer (SURVEY §2.1 "Distributed
communication backend: Absent"); its nearest analog is a SLURM job-ID check
that recolors the console (``dodo.py:31-34``). The TPU-native multi-host
design follows the standard JAX recipe — one process per host, every
process runs the same program, `jax.distributed.initialize` wires the
coordination service, and meshes span the GLOBAL device set — with the mesh
laid out so each collective rides the right interconnect:

- **months → hosts (DCN).** Cross-sectional months are independent
  (SURVEY §5): the per-month OLS needs NO cross-month communication, so the
  time axis shards across hosts and DCN carries only the final slope
  gather, ``T·(P+1)`` floats (~40 KB for the full panel) once per FM run.
- **firms → intra-host devices (ICI).** The firm-axis TSQR/Gram psum
  (``fm_sharded``: ~10 MB / ~150 KB per run) stays inside each host's ICI
  domain, never touching DCN.

This is the "shard the collective-heavy axis over ICI, the embarrassingly
parallel axis over DCN" layout of the public scaling playbook, applied to
the panel workload. The bootstrap stage is already communication-minimal
(2·P floats), so it flattens the same devices into a 1-D replicate mesh
(``as_flat_mesh``) rather than needing its own hierarchy.

Single-host virtual meshes (``xla_force_host_platform_device_count``)
exercise the exact same code: ``make_mesh_2d(month_shards=2)`` on 8 CPU
devices builds the (2, 4) mesh the tests and the driver dryrun use, and
the collectives compile to the same HLO they would on a pod — only the
physical transport differs.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fm_returnprediction_tpu.ops.fama_macbeth import (
    FamaMacbethSummary,
    fama_macbeth_summary,
)
from fm_returnprediction_tpu.ops.ols import CSRegressionResult
from fm_returnprediction_tpu.parallel.fm_sharded import cs_ols_kernel
from fm_returnprediction_tpu.parallel.mesh import (
    pad_to_multiple,
    place_global,
    shard_map,
)

__all__ = [
    "distributed_client_active",
    "initialize_multihost",
    "make_mesh_2d",
    "as_flat_mesh",
    "fama_macbeth_hier",
]


def distributed_client_active() -> bool:
    """True when the JAX distributed runtime is already initialized.

    Probes the distributed client directly instead of ``process_count()``:
    a device/process query INITIALIZES the XLA backends, after which
    ``jax.distributed.initialize`` permanently raises — the probe must not
    be the thing that breaks the initialization it guards. Private API;
    degrade to "not initialized" (and let ``initialize`` itself raise on a
    true double call) if the attribute moves across JAX versions.
    """
    try:
        from jax._src import distributed

        return distributed.global_state.client is not None
    except Exception:
        return False


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> tuple[int, int]:
    """Bring up the JAX distributed runtime when a multi-process run is
    configured; no-op otherwise. Returns ``(process_index, process_count)``.

    Configuration, in precedence order:

    1. explicit arguments (manual clusters / tests);
    2. ``FMRP_MULTIHOST=1`` in the environment — triggers
       ``jax.distributed.initialize()`` with no arguments, which
       auto-detects the topology on Cloud TPU pods and SLURM/GKE clusters.
       The pipeline and taskgraph CLIs call this at startup, so setting the
       env var is all a pod launcher needs;
    3. neither: single-process, return ``(0, 1)`` without touching the
       distributed runtime (the safe default for laptops and CI).

    Call ONCE per process, before any other JAX computation — a device or
    process query initializes the XLA backends, after which the distributed
    runtime can no longer be brought up (``jax.distributed.initialize``
    raises; that error propagates rather than being masked here).
    Idempotent: when the distributed client is already up, the call just
    returns the current process coordinates.
    """
    explicit = coordinator_address is not None or num_processes is not None
    wanted = explicit or os.environ.get("FMRP_MULTIHOST", "0") == "1"
    if not wanted:
        # Do NOT query process coordinates here: jax.process_count()
        # initializes the XLA backends, which (a) would pin the platform
        # before apply_backend() gets a say and (b) dials remote
        # accelerator runtimes at CLI startup even for pure --list
        # invocations. Single-process is the documented answer.
        return 0, 1
    if not distributed_client_active():
        if explicit:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            jax.distributed.initialize()
    return jax.process_index(), jax.process_count()


def make_mesh_2d(
    month_shards: Optional[int] = None,
    month_axis: str = "months",
    firm_axis: str = "firms",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the (month_shards, n_devices // month_shards) hierarchical mesh.

    ``month_shards`` defaults to ``jax.process_count()`` so each mesh ROW is
    one host's devices: the month axis then crosses hosts (DCN) and the
    firm axis stays within a host (ICI). Devices are ordered by
    ``(process_index, id)`` to guarantee that alignment. On a single
    process, pass ``month_shards`` explicitly to carve a virtual hierarchy
    out of the local devices (tests, dryrun).
    """
    if devices is None:
        devices = jax.devices()
    devices = sorted(devices, key=lambda d: (d.process_index, d.id))
    h = jax.process_count() if month_shards is None else month_shards
    if h < 1:
        raise ValueError(f"month_shards must be >= 1, got {h}")
    d, rem = divmod(len(devices), h)
    if rem or d == 0:
        raise ValueError(
            f"{len(devices)} devices do not factor into {h} month shards"
        )
    return Mesh(
        np.asarray(devices).reshape(h, d), axis_names=(month_axis, firm_axis)
    )


def as_flat_mesh(mesh: Mesh, axis_name: str = "boot") -> Mesh:
    """The same devices as a 1-D mesh (for the replicate-sharded bootstrap:
    its one psum is 2·P floats, cheap even over DCN, so every device in the
    hierarchy contributes replicates)."""
    return Mesh(mesh.devices.reshape(-1), axis_names=(axis_name,))


def _gather_month_shards(tree, month_axis: str, n_shards: int):
    """Rebuild full (T, ...) arrays from contiguous month shards, as a psum
    of offset-placed blocks — the same trick as ``fm_sharded._tsqr_lstsq``,
    and for the same reason: ``all_gather`` output defeats shard_map's
    static replication checker, while a psum provably replicates. Bool
    leaves ride as int8 (psum has no bool) and cast back."""

    def gather(a):
        as_bool = a.dtype == jnp.bool_
        v = a.astype(jnp.int8) if as_bool else a
        t_l = v.shape[0]
        full = jnp.zeros((n_shards * t_l,) + v.shape[1:], v.dtype)
        offset = jax.lax.axis_index(month_axis) * t_l
        zero = jnp.zeros((), offset.dtype)
        starts = (offset,) + (zero,) * (v.ndim - 1)
        full = jax.lax.psum(
            jax.lax.dynamic_update_slice(full, v, starts), month_axis
        )
        return full.astype(jnp.bool_) if as_bool else full

    return jax.tree.map(gather, tree)


@functools.lru_cache(maxsize=32)
def _jitted_fm_hier(mesh: Mesh, month_axis: str, firm_axis: str,
                    nw_lags: int, min_months: int, weight: str, n_refine: int):
    """One compiled hierarchical-FM program per (mesh, hyperparams) combo
    (same function-identity-cache rationale as ``fm_sharded._jitted_fm``)."""
    n_firm_shards = mesh.shape[firm_axis]
    n_month_shards = mesh.shape[month_axis]

    def kernel(y_l, x_l, mask_l):
        # Per-month OLS on the local (T/H, N/D) block: collectives only over
        # the firm axis (ICI). Months never communicate here.
        cs_local = cs_ols_kernel(
            y_l, x_l, mask_l, firm_axis, n_firm_shards, n_refine
        )
        # One gather over the month axis (DCN) rebuilds the full (T, ...)
        # slope series on every device; contiguous month shards concatenate
        # back in chronological order. ~T·(P+1) floats.
        cs_full = _gather_month_shards(cs_local, month_axis, n_month_shards)
        # NW/FM aggregation is O(T·P) — replicated everywhere, like the
        # single-mesh path.
        summary = fama_macbeth_summary(
            cs_full, nw_lags=nw_lags, min_months=min_months, weight=weight
        )
        return cs_full, summary

    return jax.jit(
        shard_map(
            kernel,
            mesh=mesh,
            in_specs=(
                P(month_axis, firm_axis),
                P(month_axis, firm_axis, None),
                P(month_axis, firm_axis),
            ),
            out_specs=(
                CSRegressionResult(P(), P(), P(), P(), P()),
                FamaMacbethSummary(P(), P(), P(), P(), P(), P()),
            ),
        )
    )


def fama_macbeth_hier(
    y,
    x,
    mask,
    mesh: Optional[Mesh] = None,
    month_axis: str = "months",
    firm_axis: str = "firms",
    nw_lags: int = 4,
    min_months: int = 10,
    weight: str = "reference",
    n_refine: int = 2,
    place: bool = True,
) -> tuple[CSRegressionResult, FamaMacbethSummary]:
    """Multi-host FM on a 2-D (months × firms) mesh.

    Semantically identical to ``fama_macbeth`` / ``fama_macbeth_sharded``
    (the firm-axis solve is the same ``cs_ols_kernel``); only the layout
    differs. Months pad up to a mesh-row multiple with ``mask=False`` rows —
    padded months fail the ``n >= P+1`` gate exactly like the reference's
    skipped months (``src/regressions.py:52``) and are trimmed from the
    returned per-month result.
    """
    if mesh is None:
        mesh = make_mesh_2d(month_axis=month_axis, firm_axis=firm_axis)
    t = y.shape[0]
    h = mesh.shape[month_axis]
    d = mesh.shape[firm_axis]
    if place:
        y = pad_to_multiple(jnp.asarray(y), axis=0, multiple=h, fill=jnp.nan)
        x = pad_to_multiple(jnp.asarray(x), axis=0, multiple=h, fill=jnp.nan)
        mask = pad_to_multiple(jnp.asarray(mask), axis=0, multiple=h, fill=False)
        y = pad_to_multiple(y, axis=1, multiple=d, fill=jnp.nan)
        x = pad_to_multiple(x, axis=1, multiple=d, fill=jnp.nan)
        mask = pad_to_multiple(mask, axis=1, multiple=d, fill=False)
        s2 = NamedSharding(mesh, P(month_axis, firm_axis))
        s3 = NamedSharding(mesh, P(month_axis, firm_axis, None))
        y = place_global(y, s2)
        x = place_global(x, s3)
        mask = place_global(mask, s2)
    run = _jitted_fm_hier(
        mesh, month_axis, firm_axis, nw_lags, min_months, weight,
        min(n_refine, 1),
    )
    cs, summary = run(y, x, mask)
    if cs.slopes.shape[0] != t:  # trim month padding
        cs = CSRegressionResult(*(leaf[:t] for leaf in cs))
    return cs, summary
