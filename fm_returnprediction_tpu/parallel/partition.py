"""Declarative partition rules — regex → ``PartitionSpec``, in one table.

Every sharded program so far threaded its ``PartitionSpec``s by hand at the
call site (``fm_sharded.monthly_cs_ols_sharded`` builds its ``in_specs``
tuple inline, ``shard_panel`` hard-codes three specs). That scales to three
arrays; the pod-scale spec-grid path shards a *pytree* of panel inputs and
an (S, T, Q, Q) sufficient-statistics tree along two different axes, and
hand-threading specs per call site is exactly how layouts drift apart.

This module adopts the ``match_partition_rules`` shape from SNIPPETS.md [2]
(the fmengine/EasyLM idiom used to shard transformer TrainStates): a rule
table of ``(regex, PartitionSpec)`` pairs is matched against the '/'-joined
tree path of every leaf, scalars are never partitioned, and an unmatched
leaf is an ERROR — a new tensor added to a sharded program must be placed
deliberately, not silently replicated.

Two rule tables ship here and are the single source of truth for the
spec-grid mesh path (``specgrid.sharded``):

- ``SPECGRID_PANEL_RULES``  — the contraction side: the dense panel shards
  over FIRMS (the axis with proven Gram additivity, ``tests/test_specgrid``),
  tiny per-spec index/selector arrays replicate.
- ``SPECGRID_STATS_RULES``  — the solve side: the (S, T, Q, Q) Gram stats
  and everything downstream of them shard over the SPEC (cell) axis — the
  solve is vmapped per spec, so the partition is communication-free.

Both tables use one mesh axis (default name ``"cells"``): the two stages
run sequentially, so the same devices carry firms during contraction and
cells during the solve — the same axis-reuse discipline as ``mesh.py``'s
firms/boot note.
"""

from __future__ import annotations

import re
from typing import Any, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import jax

__all__ = [
    "match_partition_rules",
    "named_tree_paths",
    "tree_shardings",
    "SPECGRID_PANEL_RULES",
    "SPECGRID_STATS_RULES",
    "specgrid_axis",
    "specgrid_panel_rules",
    "specgrid_stats_rules",
]

#: the one mesh-axis name of the spec-grid path (firms during contraction,
#: cells during the solve — sequential stages reuse the same devices)
SPECGRID_AXIS = "cells"


def specgrid_axis() -> str:
    """The spec-grid mesh axis name (one definition, no string literals
    scattered across call sites)."""
    return SPECGRID_AXIS


def named_tree_paths(tree: Any, sep: str = "/"):
    """``[(path, leaf), ...]`` with dict keys / NamedTuple fields /
    sequence indices joined by ``sep`` — the names the rule regexes see."""
    out = []

    def walk(prefix: str, node: Any) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}{sep}{k}" if prefix else str(k), v)
        elif hasattr(node, "_fields"):  # NamedTuple
            for k, v in zip(node._fields, node):
                walk(f"{prefix}{sep}{k}" if prefix else str(k), v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                walk(f"{prefix}{sep}{i}" if prefix else str(i), v)
        else:
            out.append((prefix, node))

    walk("", tree)
    return out


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree: Any):
    """Map a pytree of arrays to a same-structure pytree of PartitionSpecs.

    Each leaf's '/'-joined tree path is matched against ``rules`` in order
    (``re.search``, first hit wins — SNIPPETS.md [2]); scalar leaves get
    ``P()`` without consulting the table; a leaf no rule matches raises —
    silent replication of a new tensor is how sharded programs rot.
    """

    def get_spec(name: str, leaf: Any) -> P:
        shape = np.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return P()  # never partition scalars
        for rule, spec in rules:
            if re.search(rule, name) is not None:
                return spec
        raise ValueError(f"partition rule not found for leaf: {name!r}")

    # rebuild the tree shape with the SAME walker that names the leaves —
    # round-tripping through jax treedefs would reorder dict keys (they
    # flatten sorted) out from under the insertion-ordered names
    def rebuild(prefix: str, node: Any):
        if isinstance(node, dict):
            return {
                k: rebuild(f"{prefix}/{k}" if prefix else str(k), v)
                for k, v in node.items()
            }
        if hasattr(node, "_fields"):  # NamedTuple
            return type(node)(*(
                rebuild(f"{prefix}/{k}" if prefix else str(k), v)
                for k, v in zip(node._fields, node)
            ))
        if isinstance(node, (list, tuple)):
            vals = [
                rebuild(f"{prefix}/{i}" if prefix else str(i), v)
                for i, v in enumerate(node)
            ]
            return type(node)(vals) if isinstance(node, list) else tuple(vals)
        return get_spec(prefix, node)

    return rebuild("", tree)


def tree_shardings(mesh: Mesh, rules: Sequence[Tuple[str, P]], tree: Any):
    """``match_partition_rules`` with every spec wrapped in a
    ``NamedSharding`` on ``mesh`` — the form ``jax.device_put`` and
    ``jit(in_shardings=...)`` consume."""
    specs = match_partition_rules(rules, tree)
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda s: isinstance(s, P),
    )


# -- the spec-grid tables ---------------------------------------------------


def specgrid_panel_rules(axis: str = SPECGRID_AXIS) -> Tuple[Tuple[str, P], ...]:
    """Contraction side: (T, N)-shaped panel tensors shard over firms on
    axis 1, the (U, T, N) universe stack on axis 2; per-spec index/selector
    arrays (uidx, col_sel, window, sel_aug) and the (T, P) center replicate
    — they are KBs against the panel's GBs and every shard reads all of
    them."""
    return (
        (r"(^|/)(y|mask|row_weights)$", P(None, axis)),
        (r"(^|/)x$", P(None, axis, None)),
        (r"(^|/)universes$", P(None, None, axis)),
        (r"(^|/)(uidx|col_sel|window|sel_aug|center)$", P()),
    )


def specgrid_stats_rules(axis: str = SPECGRID_AXIS) -> Tuple[Tuple[str, P], ...]:
    """Solve side: every leaf of ``SpecGramStats`` with a leading spec axis
    (and the per-spec selectors) shards over cells — the solve is vmapped
    per spec, so the partition is communication-free; the shared (T, P)
    center replicates."""
    return (
        (r"(^|/)(gram|moment|n|ysum|yy)$", P(axis)),
        (r"(^|/)(sel_aug|uidx|col_sel|window)$", P(axis)),
        (r"(^|/)center$", P()),
    )


#: the default-axis instantiations, for callers/tests that read the tables
SPECGRID_PANEL_RULES: Tuple[Tuple[str, P], ...] = specgrid_panel_rules()
SPECGRID_STATS_RULES: Tuple[Tuple[str, P], ...] = specgrid_stats_rules()
