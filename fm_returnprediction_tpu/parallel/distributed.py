"""Multi-process runtime bootstrap + host-side sufficient-stats exchange.

Everything below this module, process count is a DEPLOYMENT KNOB: the
spec-grid contraction, the taskgraph barriers, and the serving fleet all
ask *this* module "who am I, how many of us are there, and how do I merge
with the others" instead of assuming one process.

Two transports, one fallback ladder (disclosed, ``docs/architecture.md``):

1. **Device collectives** (``jax.distributed``): the TPU-pod path. The
   bootstrap wires ``jax.distributed.initialize`` from the same
   ``FMRP_DIST_*`` coordinates, ``multihost.make_mesh_2d`` then spans the
   GLOBAL device set, and psums ride ICI/DCN. Opt-in via
   ``FMRP_DIST_JAX=1`` (or ``auto`` on a non-CPU platform) because this
   container's jaxlib CPU backend refuses cross-process collectives
   outright ("Multiprocess computations aren't implemented on the CPU
   backend") — the named environment gap ``tests/test_multiprocess.py``
   still probes for.
2. **Host-side exchange** (:class:`HostExchange`, this module): a small
   length-prefixed TCP allgather among the processes, rank 0 embedding
   the server. Per-process Gram shards are ADDITIVE (the PR-3 property),
   so ``sum_tree`` — allgather + rank-ordered tree summation computed
   identically on every rank — is a drop-in for the device ``psum``:
   deterministic, and differentially pinned against the single-process
   contraction (``tests/test_multiprocess.py``). This is the route that
   works on ANY backend, device collectives or not.

Wire format: every frame is an 8-byte big-endian length followed by a
pickled payload (trusted intra-cluster links only — the same stance as
the registry's pickled executables). One allgather ROUND is: every rank
sends ``(rank, seq, bytes)``, the server buffers until all ``world``
ranks posted that ``seq``, then sends each rank the rank-ordered list.
Rounds complete strictly in ``seq`` order, so a rank that runs ahead
never observes reordered replies. Byte and round counters land in the
metrics registry (``fmrp_dist_exchange_*``) — the bench's
``multiproc_transport_*`` series reads them.

Configuration (``FMRP_DIST_*``, mirrored by :class:`DistConfig`):

- ``FMRP_DIST_COORDINATOR`` — ``host:port`` of rank 0's exchange server;
- ``FMRP_DIST_PROCS``       — world size;
- ``FMRP_DIST_PROC_ID``     — this process's rank;
- ``FMRP_DIST_JAX``         — ``0``/``1``/``auto``: also bring up the
  ``jax.distributed`` device-collective runtime (auto: only off-CPU).

``initialize_distributed()`` is idempotent and a no-op when the env is
not set — the safe default for laptops and CI. It also stamps the
process's telemetry identity (``telemetry.identity``) so merged traces
and Prometheus exports from N processes stay attributable.
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional

from fm_returnprediction_tpu.resilience.errors import InjectedFault
from fm_returnprediction_tpu.resilience.faults import fault_site

__all__ = [
    "DistConfig",
    "DistributedError",
    "HostExchange",
    "dist_active",
    "free_port",
    "host_exchange",
    "initialize_distributed",
    "process_count",
    "process_index",
    "recv_frame",
    "send_frame",
    "shutdown_distributed",
    "worker_env",
]

_LEN = struct.Struct(">Q")

# round-frame seq announcing a graceful client departure (vs a death,
# which arrives as bare EOF and tears the whole exchange down)
_BYE_SEQ = -1


class DistributedError(RuntimeError):
    """A host-exchange protocol failure (timeout, peer death, tag skew)."""


def free_port() -> int:
    """An OS-assigned free TCP port (tests/bench spawning local workers)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _send_frame(sock: socket.socket, payload: bytes, lock=None) -> int:
    data = _LEN.pack(len(payload)) + payload
    if lock is not None:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)
    return len(data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise DistributedError("exchange peer closed the connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, n)


# public spellings: the SAME 8-byte big-endian length-prefixed framing is
# the repo's one wire format — the exchange above and the serving fleet's
# replica transport (``serving.replica_proc``) share it
send_frame = _send_frame
recv_frame = _recv_frame


@dataclasses.dataclass(frozen=True)
class DistConfig:
    """One process's distributed coordinates."""

    coordinator: str            # "host:port" of rank 0's exchange server
    num_processes: int
    process_id: int
    jax_collectives: str = "0"  # "0" | "1" | "auto"

    @classmethod
    def from_env(cls, environ=None) -> Optional["DistConfig"]:
        """``FMRP_DIST_COORDINATOR`` + ``FMRP_DIST_PROCS`` +
        ``FMRP_DIST_PROC_ID``; None (single-process) unless the first two
        are both set."""
        env = os.environ if environ is None else environ
        coord = env.get("FMRP_DIST_COORDINATOR", "").strip()
        procs = env.get("FMRP_DIST_PROCS", "").strip()
        if not coord or not procs:
            return None
        return cls(
            coordinator=coord,
            num_processes=int(procs),
            process_id=int(env.get("FMRP_DIST_PROC_ID", "0")),
            jax_collectives=env.get("FMRP_DIST_JAX", "0").strip() or "0",
        )

    @property
    def host(self) -> str:
        return self.coordinator.rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        return int(self.coordinator.rsplit(":", 1)[1])


def worker_env(rank: int, world: int, port: int,
               host: str = "127.0.0.1", jax_collectives: str = "0",
               base: Optional[dict] = None) -> Dict[str, str]:
    """The child-process environment for one exchange worker — the one
    place the ``FMRP_DIST_*`` spelling lives for spawners (tests, bench,
    the spec-grid pool)."""
    env = dict(os.environ if base is None else base)
    env["FMRP_DIST_COORDINATOR"] = f"{host}:{port}"
    env["FMRP_DIST_PROCS"] = str(world)
    env["FMRP_DIST_PROC_ID"] = str(rank)
    env["FMRP_DIST_JAX"] = jax_collectives
    # an active FaultPlan crosses the boundary with the worker: the child
    # entrypoint installs it (install_plan_from_env), so chaos sites fire
    # inside grid workers with the parent plan's determinism
    from fm_returnprediction_tpu.resilience.faults import chaos_env

    env.update(chaos_env())
    # trace context crosses with it too (FMRP_TRACE_* / FMRP_TELEMETRY):
    # worker spans parent onto the spawning request span and export into
    # the shared trace dir under per-process filenames, so the timeline
    # merge shows grid workers as named rows beside the router
    from fm_returnprediction_tpu.telemetry.distributed import trace_env

    trace_env(env)
    return env


# retry-on allowlist for joining the exchange: a slow-starting rank 0 is
# the EXPECTED cold-start shape (connection refused until its listener
# binds), and the transient network errnos ride the same path
_CONNECT_RETRY_ON = (ConnectionError, socket.timeout, OSError)


# -- the exchange server (embedded in rank 0) --------------------------------


class _ExchangeServer:
    """Rank 0's round broker: accepts ``world`` rank connections, buffers
    each round until every rank posted, answers in strict seq order."""

    def __init__(self, host: str, port: int, world: int,
                 accept_timeout_s: float):
        self.world = int(world)
        self._listener = socket.create_server(
            (host, port), reuse_port=False
        )
        self._listener.settimeout(accept_timeout_s)
        self._conns: Dict[int, socket.socket] = {}
        self._wlocks: Dict[int, threading.Lock] = {}
        self._lock = threading.Lock()
        self._rounds: Dict[int, Dict[int, bytes]] = {}
        self._next_seq = 1
        self._fail: Optional[str] = None
        self._threads: List[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fmrp-exchange-accept", daemon=True
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        try:
            while len(self._conns) < self.world:
                conn, _ = self._listener.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                hello = pickle.loads(_recv_frame(conn))
                rank = int(hello["rank"])
                with self._lock:
                    if rank in self._conns:
                        raise DistributedError(f"duplicate rank {rank}")
                    self._conns[rank] = conn
                    self._wlocks[rank] = threading.Lock()
                # the monotonic-offset exchange rides the join hello:
                # rank 0 records every peer's epoch anchor, the evidence
                # the timeline merge uses to align clocks exactly
                if hello.get("anchor_ns") is not None:
                    from fm_returnprediction_tpu.telemetry.distributed import (
                        register_peer,
                    )

                    register_peer(
                        f"rank{rank}", pid=hello.get("pid"),
                        anchor_ns=hello.get("anchor_ns"), kind="rank",
                    )
            # all present: release everyone (the startup barrier)
            ok = pickle.dumps({"ok": True, "world": self.world})
            for rank, conn in self._conns.items():
                _send_frame(conn, ok, self._wlocks[rank])
                t = threading.Thread(
                    target=self._reader, args=(rank, conn),
                    name=f"fmrp-exchange-r{rank}", daemon=True,
                )
                t.start()
                self._threads.append(t)
        except Exception as exc:  # noqa: BLE001 — surfaced to every rank
            self._die(f"exchange server accept failed: {exc!r}")

    def _die(self, why: str) -> None:
        """One rank's death is everyone's: a blocked allgather can never
        complete, so every connection is torn down (peers see EOF and
        raise) rather than letting the fleet hang in recv.

        shutdown() BEFORE close(), and it is load-bearing: our own
        reader threads sit blocked in recv() on these sockets, and a
        bare close() only drops the fd-table entry — the kernel socket
        stays referenced by the blocked syscall, no FIN ever goes out,
        and every peer (including rank 0 itself) hangs its full recv
        timeout instead of failing in milliseconds. shutdown() tears the
        connection down immediately regardless of who is blocked on it."""
        with self._lock:
            if self._fail is None:
                self._fail = why
            conns = list(self._conns.values())
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _reader(self, rank: int, conn: socket.socket) -> None:
        try:
            while True:
                rank_in, seq, payload, root = pickle.loads(_recv_frame(conn))
                if seq == _BYE_SEQ:
                    # graceful leave: the client announced it is done
                    # (HostExchange.close) BEFORE closing its socket, so
                    # this EOF-to-come is a departure, not a death —
                    # tearing the world down here would race the fan-out
                    # of a round the leaver already received (its peers
                    # would see EOF in place of their real reply)
                    with self._lock:
                        self._conns.pop(rank, None)
                    try:
                        conn.shutdown(socket.SHUT_RDWR)
                    except OSError:
                        pass
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                # broker-death-mid-round chaos site: an injected failure
                # here is the broker dying AFTER a rank posted its round
                # and BEFORE the fan-out — _die() tears every rank down
                # (typed DistributedError, never a hang) and the topology
                # controller re-elects by respawning the world and
                # fanning the round out again
                fault_site("dist.broker_round")
                with self._lock:
                    bucket = self._rounds.setdefault(int(seq), {})
                    bucket[int(rank_in)] = (payload, root)
                    done = []
                    # complete strictly in seq order: seq k+1 can only be
                    # complete if every rank already posted seq k
                    while len(self._rounds.get(self._next_seq, {})) \
                            == self.world:
                        full = self._rounds.pop(self._next_seq)
                        roots = {r for _, r in full.values()}
                        if len(roots) != 1:
                            raise DistributedError(
                                f"round {self._next_seq} root skew: {roots}"
                            )
                        done.append((self._next_seq,
                                     [full[r][0] for r in range(self.world)],
                                     roots.pop()))
                        self._next_seq += 1
                for seq_done, ordered, root_done in done:
                    # root=None: allgather (everyone gets the list);
                    # root=k: gather (only rank k pays the fan-in
                    # bandwidth; the rest get a tiny completion ack)
                    full_reply = pickle.dumps((seq_done, ordered))
                    ack_reply = (pickle.dumps((seq_done, []))
                                 if root_done is not None else full_reply)
                    # rank 0 last: it embeds this server, and an error raised
                    # off ITS reply (e.g. barrier tag skew) may close() the
                    # exchange — every remote rank's reply must already be
                    # in the kernel by then or they see EOF instead of the
                    # real diagnostic
                    with self._lock:
                        conns = sorted(self._conns.items(),
                                       key=lambda kv: kv[0] == 0)
                    for r, c in conns:
                        reply = (full_reply
                                 if root_done is None or r == root_done
                                 else ack_reply)
                        _send_frame(c, reply, self._wlocks[r])
        except (DistributedError, OSError, EOFError, pickle.PickleError,
                InjectedFault):
            # InjectedFault: the dist.broker_round chaos site must keep
            # the site's contract — typed teardown via _die, never a
            # reader thread dying silently with every rank left blocked
            self._die(f"rank {rank} left the exchange")

    def close(self) -> None:
        self._die("server closed")
        try:
            self._listener.close()
        except OSError:
            pass


# -- the per-process exchange client -----------------------------------------


class HostExchange:
    """One process's handle on the host-merge transport.

    ``allgather`` is the primitive; ``sum_tree`` / ``barrier`` /
    ``broadcast_obj`` build on it client-side, so every rank computes the
    SAME rank-ordered result — the determinism that substitutes for the
    device ``psum``'s. Thread-safety: one round at a time per process
    (the round lock); concurrent rounds from one process would deadlock
    the seq ordering by construction, so they serialize here.
    """

    def __init__(self, config: DistConfig, timeout_s: Optional[float] = None):
        self.config = config
        self.rank = int(config.process_id)
        self.world = int(config.num_processes)
        if timeout_s is None:
            timeout_s = float(os.environ.get("FMRP_DIST_TIMEOUT_S", "120"))
        self.timeout_s = timeout_s
        self._server: Optional[_ExchangeServer] = None
        if self.rank == 0:
            self._server = _ExchangeServer(
                config.host, config.port, self.world, timeout_s
            )
        self._sock = self._connect()
        self._seq = 0
        self._round_lock = threading.Lock()
        self._wlock = threading.Lock()
        # transport accounting (the bench's multiproc_transport_* series)
        from fm_returnprediction_tpu import telemetry

        reg = telemetry.registry()
        self._m_bytes_out = reg.counter(
            "fmrp_dist_exchange_bytes_total",
            help="host-exchange payload bytes by direction",
            direction="sent",
        )
        self._m_bytes_in = reg.counter(
            "fmrp_dist_exchange_bytes_total",
            help="host-exchange payload bytes by direction",
            direction="received",
        )
        self._m_rounds = reg.counter(
            "fmrp_dist_exchange_rounds_total",
            help="completed host-exchange allgather rounds",
        )
        self.last_round_s = 0.0

    def _connect(self) -> socket.socket:
        """Join the exchange through the shared retry machinery
        (``resilience.call_with_retry``): deterministic exponential
        backoff seeded by rank (concurrent joiners spread out instead of
        hammering the listener in lockstep), an attempt budget derived
        from ``timeout_s`` by accumulating the policy's own backoff
        schedule, and exhaustion surfaced as the typed
        ``DistributedError`` with the retry evidence as ``__cause__`` —
        never a raw ``ConnectionRefusedError`` in a peer's log."""
        from fm_returnprediction_tpu.resilience.errors import (
            RetryExhaustedError,
        )
        from fm_returnprediction_tpu.resilience.retry import (
            RetryPolicy,
            call_with_retry,
        )

        label = f"dist.connect.r{self.rank}"
        policy = RetryPolicy(
            max_attempts=2, backoff_s=0.05, multiplier=1.5,
            max_backoff_s=2.0, jitter=0.1, retry_on=_CONNECT_RETRY_ON,
            seed=self.rank,
        )
        # attempt budget = as many retries as the backoff schedule fits
        # inside timeout_s (pure policy arithmetic — no clock reads, so
        # the budget is the same on every run)
        attempts, spent = 1, 0.0
        while attempts < 256:
            step = policy.delay_s(attempts, label)
            if spent + step > self.timeout_s:
                break
            spent += step
            attempts += 1
        policy = dataclasses.replace(policy, max_attempts=max(attempts, 2))

        def attempt() -> socket.socket:
            sock = socket.create_connection(
                (self.config.host, self.config.port),
                timeout=self.timeout_s,
            )
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                from fm_returnprediction_tpu.telemetry import spans as _spans

                _send_frame(sock, pickle.dumps({
                    "rank": self.rank, "pid": os.getpid(),
                    "anchor_ns": _spans.EPOCH_ANCHOR_NS,
                }))
                ok = pickle.loads(_recv_frame(sock))
                if not ok.get("ok") or ok.get("world") != self.world:
                    raise DistributedError(f"bad exchange handshake: {ok}")
                sock.settimeout(self.timeout_s)
            except BaseException:
                sock.close()
                raise
            return sock

        try:
            return call_with_retry(attempt, policy, label=label)
        except RetryExhaustedError as exc:
            raise DistributedError(
                f"rank {self.rank} could not join exchange at "
                f"{self.config.coordinator} within {self.timeout_s}s "
                f"({policy.max_attempts} attempts): {exc.__cause__!r}"
            ) from exc

    # -- primitives --------------------------------------------------------

    def allgather(self, payload: bytes, root: Optional[int] = None
                  ) -> List[bytes]:
        """One round: every rank contributes ``payload``. With
        ``root=None`` every rank receives the rank-ordered list of all
        contributions (allgather); with ``root=k`` only rank k receives
        the list and every other rank gets ``[]`` back (gather — the
        fan-in bandwidth lands on the one rank that needs it). All ranks
        of a round must agree on ``root`` (the broker raises on skew)."""
        with self._round_lock:
            self._seq += 1
            seq = self._seq
            t0 = time.perf_counter()
            msg = pickle.dumps((self.rank, seq, payload, root))
            sent = _send_frame(self._sock, msg, self._wlock)
            try:
                raw = _recv_frame(self._sock)
            except (OSError, socket.timeout) as exc:
                raise DistributedError(
                    f"exchange round {seq} failed on rank {self.rank}: "
                    f"{exc!r}"
                ) from exc
            seq_done, ordered = pickle.loads(raw)
            if seq_done != seq:
                raise DistributedError(
                    f"exchange answered round {seq_done}, expected {seq}"
                )
            self.last_round_s = time.perf_counter() - t0
            self._m_bytes_out.inc(sent)
            self._m_bytes_in.inc(len(raw))
            self._m_rounds.inc()
            return ordered

    def barrier(self, tag: str = "") -> None:
        """Rendezvous; mismatched tags raise (program-order divergence —
        the failure ``sync_global_devices`` hides as a hang)."""
        tags = self.allgather(tag.encode())
        if any(t != tags[0] for t in tags):
            raise DistributedError(
                f"barrier tag skew: {sorted(set(t.decode() for t in tags))}"
            )

    def allgather_obj(self, obj) -> list:
        return [pickle.loads(b) for b in self.allgather(pickle.dumps(obj))]

    def gather_obj(self, obj, root: int = 0) -> list:
        """Gather: rank ``root`` returns every rank's object in rank
        order; every other rank returns ``[]`` (contributing only). The
        merge shape for root-solves-everything patterns — the spec-grid
        pool's stats fan-in — where allgathering the full payload to
        every rank would square the broker's bandwidth bill."""
        parts = self.allgather(pickle.dumps(obj), root=root)
        return [pickle.loads(b) for b in parts]

    def broadcast_obj(self, obj, root: int = 0):
        """Every rank receives ``root``'s object (non-root contributions
        are ignored)."""
        parts = self.allgather(
            pickle.dumps(obj) if self.rank == root else b""
        )
        return pickle.loads(parts[root])

    def sum_tree(self, tree):
        """Allgather a pytree of numpy arrays and sum leaf-wise in RANK
        ORDER — the host-merge drop-in for a device ``psum`` over
        additive sufficient statistics. Deterministic: every rank
        computes the identical left-to-right fold, so all ranks hold the
        same merged stats bit-for-bit."""
        import jax
        import numpy as np

        trees = self.allgather_obj(jax.tree.map(np.asarray, tree))
        out = trees[0]
        for t in trees[1:]:
            out = jax.tree.map(lambda a, b: np.add(a, b), out, t)
        return out

    def close(self) -> None:
        # announce the departure before closing: the broker must be able
        # to tell a finished rank from a dead one, or a fast leaver's EOF
        # races the fan-out of the final round and surviving ranks read
        # EOF where their reply (or its diagnostic) should have been
        try:
            bye = pickle.dumps((self.rank, _BYE_SEQ, b"", None))
            _send_frame(self._sock, bye, self._wlock)
        except (OSError, pickle.PickleError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._server is not None:
            self._server.close()


# -- process-wide bootstrap --------------------------------------------------

_STATE_LOCK = threading.Lock()
_EXCHANGE: Optional[HostExchange] = None
_COORDS: Optional[tuple] = None  # (process_id, num_processes)


def dist_active() -> bool:
    """True when this process joined a multi-process run (host exchange
    up, or the ``jax.distributed`` runtime initialized through here)."""
    return _EXCHANGE is not None


def host_exchange() -> Optional[HostExchange]:
    """The process's exchange client, or None (single-process)."""
    return _EXCHANGE


def process_index() -> int:
    """This process's rank — WITHOUT touching jax (a ``jax.process_index``
    call initializes the XLA backends; see ``multihost``'s caveat)."""
    if _COORDS is not None:
        return _COORDS[0]
    cfg = DistConfig.from_env()
    return cfg.process_id if cfg is not None else 0


def process_count() -> int:
    if _COORDS is not None:
        return _COORDS[1]
    cfg = DistConfig.from_env()
    return cfg.num_processes if cfg is not None else 1


def _want_jax_collectives(cfg: DistConfig) -> bool:
    mode = cfg.jax_collectives.lower()
    if mode == "1":
        return True
    if mode == "auto":
        # without initializing a backend, the platform hint is the env:
        # the CPU backend refuses cross-process collectives (the named
        # gap), so auto only arms the device path off-CPU
        plat = os.environ.get("JAX_PLATFORMS", "").lower()
        return plat not in ("", "cpu")
    return False


def initialize_distributed(
    config: Optional[DistConfig] = None,
) -> tuple:
    """Join the multi-process run this process was launched into.

    Reads :class:`DistConfig` from ``FMRP_DIST_*`` when not given; a
    missing config is the single-process no-op ``(0, 1)``. Otherwise:

    1. brings up the host exchange (rank 0 embeds the server) — the
       startup rendezvous doubles as the cluster barrier;
    2. optionally wires ``jax.distributed.initialize`` through
       ``multihost.initialize_multihost`` (``FMRP_DIST_JAX``) so device
       collectives and global meshes work where the backend supports
       them;
    3. stamps the telemetry identity (``process_index`` label on metrics
       and trace meta).

    Idempotent; returns ``(process_index, process_count)``.
    """
    global _EXCHANGE, _COORDS
    with _STATE_LOCK:
        if _EXCHANGE is not None:
            return _COORDS
        cfg = config if config is not None else DistConfig.from_env()
        if cfg is None:
            return (0, 1)
        # a parent FaultPlan that rode the spawn env installs here, before
        # the exchange joins — chaos sites then fire inside this rank with
        # the parent's determinism (no-op without FMRP_CHAOS_PLAN)
        from fm_returnprediction_tpu.resilience.faults import (
            install_plan_from_env,
        )

        install_plan_from_env()
        exchange = HostExchange(cfg)
        if _want_jax_collectives(cfg):
            from fm_returnprediction_tpu.parallel.multihost import (
                initialize_multihost,
            )

            initialize_multihost(
                coordinator_address=(
                    f"{cfg.host}:{cfg.port + 1}"  # device runtime: own port
                ),
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
            )
        from fm_returnprediction_tpu.telemetry import identity

        identity.set_process_index(cfg.process_id)
        _EXCHANGE = exchange
        _COORDS = (cfg.process_id, cfg.num_processes)
        return _COORDS


def shutdown_distributed() -> None:
    """Leave the exchange (tests); the jax.distributed runtime — when it
    was armed — stays up for the process lifetime, as jax requires."""
    global _EXCHANGE, _COORDS
    with _STATE_LOCK:
        if _EXCHANGE is not None:
            _EXCHANGE.close()
        _EXCHANGE = None
        _COORDS = None


def apply_cpu_affinity_from_env() -> Optional[set]:
    """Pin this process to ``FMRP_PROC_CPUS`` ("0-3" or "4,5,6") BEFORE
    jax initializes — XLA's CPU thread pools size themselves from the
    schedulable-CPU count, so affinity is the one knob that bounds both
    scheduling and pool width. This is how a one-box bench models the
    pod's fixed-compute-per-process story (each worker = one "host" of K
    cores); unset = no pinning. Returns the applied set, or None."""
    spec = os.environ.get("FMRP_PROC_CPUS", "").strip()
    if not spec or not hasattr(os, "sched_setaffinity"):
        return None
    cpus: set = set()
    for part in spec.split(","):
        part = part.strip()
        if "-" in part:
            lo, hi = part.split("-", 1)
            cpus.update(range(int(lo), int(hi) + 1))
        elif part:
            cpus.add(int(part))
    if not cpus:
        return None
    os.sched_setaffinity(0, cpus)
    return cpus


def run_rounds(handler: Callable[[dict], Optional[dict]]) -> None:
    """Worker-side job loop over the exchange: rank 0 broadcasts job
    dicts; ``handler(job)`` runs each one; a ``{"op": "stop"}`` job ends
    the loop. (The spec-grid worker pool's protocol — kept here so the
    pool and any future worker kind share one loop shape.)"""
    ex = host_exchange()
    if ex is None:
        raise DistributedError("run_rounds needs an initialized exchange")
    while True:
        job = ex.broadcast_obj(None, root=0)
        if not isinstance(job, dict) or job.get("op") == "stop":
            return
        handler(job)
