"""Model layer: the Lewellen predictor sets and out-of-sample forecasting."""

from fm_returnprediction_tpu.models.forecast import (
    DecileSortResult,
    ForecastResult,
    decile_sorts,
    rolling_er_forecast,
)
from fm_returnprediction_tpu.models.lewellen import (
    FIGURE1_VARS,
    MODELS,
    ModelSpec,
    model_by_name,
)

__all__ = [
    "DecileSortResult",
    "ForecastResult",
    "decile_sorts",
    "rolling_er_forecast",
    "FIGURE1_VARS",
    "MODELS",
    "ModelSpec",
    "model_by_name",
]
