"""The Lewellen (2015) model zoo.

Three nested cross-sectional predictor sets (reference layout contract at
``src/calc_Lewellen_2014.py:714-745``), run over three size universes each.
Display names match the reference's ``variables_dict`` keys exactly (Table 2
row labels depend on them).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List

__all__ = ["ModelSpec", "MODELS", "FIGURE1_VARS", "model_by_name",
           "model_columns"]


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    name: str
    predictors: List[str]  # display names, in Table 2 row order


MODELS: List[ModelSpec] = [
    ModelSpec(
        "Model 1: Three Predictors",
        ["Log Size (-1)", "Log B/M (-1)", "Return (-2, -12)"],
    ),
    ModelSpec(
        "Model 2: Seven Predictors",
        [
            "Log Size (-1)",
            "Log B/M (-1)",
            "Return (-2, -12)",
            "Log Issues (-1,-36)",
            "Accruals (-1)",
            "ROA (-1)",
            "Log Assets Growth (-1)",
        ],
    ),
    ModelSpec(
        "Model 3: Fourteen Predictors",
        [
            "Log Size (-1)",
            "Log B/M (-1)",
            "Return (-2, -12)",
            "Log Issues (-1,-12)",
            "Accruals (-1)",
            "ROA (-1)",
            "Log Assets Growth (-1)",
            "Dividend Yield (-1,-12)",
            "Log Return (-13,-36)",
            "Log Issues (-1,-36)",
            "Beta (-1,-36)",
            "Std Dev (-1,-12)",
            "Debt/Price (-1)",
            "Sales/Price (-1)",
        ],
    ),
]

# Figure 1 plots Model-2 slopes but with its OWN 5-variable set
# (``src/calc_Lewellen_2014.py:882-883`` — not the 7-predictor Model 2).
FIGURE1_VARS: Dict[str, str] = {
    "log_bm": "B/M",
    "return_12_2": "Ret12",
    "log_issues_36": "Issue36",
    "accruals_final": "Accruals",
    "log_assets_growth": "Log AG",
}


def model_columns(model: ModelSpec, variables_dict: Dict[str, str]) -> List[str]:
    """Panel column names for a model's display-label predictors, validated
    — the ONE label→column resolution every route shares (Table 2's
    stacked/mesh paths and the spec-grid presets must agree on columns by
    construction, not by parallel lookups)."""
    xvars = []
    for label in model.predictors:
        if label not in variables_dict:
            raise ValueError(f"'{label}' not found in variables_dict!")
        xvars.append(variables_dict[label])
    return xvars


def model_by_name(name: str) -> ModelSpec:
    for model in MODELS:
        if model.name == name:
            return model
    raise KeyError(name)
