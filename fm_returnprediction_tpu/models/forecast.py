"""Rolling out-of-sample expected-return forecasts and decile sorts.

North-star config (BASELINE.json configs[3]): "Rolling 10-yr window E[r]
forecast + decile portfolio sorts". This is the out-of-sample half of
Lewellen (2015): at month t, average the previous ``window`` months of
Fama-MacBeth slopes (minimum ``min_periods``; STRICTLY past months — the
rolling mean is lagged one result row), project
``Ê[r]_{i,t} = ā + b̄' X_{i,t}`` for every firm with complete predictors,
sort the cross-section into deciles on the forecast, and track each
decile's realized equal-weighted return, plus the 10−1 spread with its
Newey-West t-statistic.

Everything after the panel is one jittable program: batched monthly OLS →
compacted rolling slope means (``lax`` windowed sums) → masked decile
breakpoints (batched sort) → one-hot decile averages (MXU einsum).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from fm_returnprediction_tpu.ops.compaction import rolling_over_valid_rows
from fm_returnprediction_tpu.ops.newey_west import nw_mean_se
from fm_returnprediction_tpu.ops.ols import (
    NormalStats,
    monthly_cs_ols,
    row_validity,
    sufficient_stats,
)
from fm_returnprediction_tpu.ops.quantiles import masked_quantile

__all__ = [
    "ForecastResult",
    "ForecastArtifacts",
    "DecileSortResult",
    "rolling_er_forecast",
    "fit_forecast_artifacts",
    "decile_sorts",
]


class ForecastResult(NamedTuple):
    er: jnp.ndarray            # (T, N) out-of-sample E[r]; NaN where unavailable
    er_valid: jnp.ndarray      # (T, N) bool
    slopes_bar: jnp.ndarray    # (T, P) lagged rolling mean slopes (NaN-gated)
    intercept_bar: jnp.ndarray # (T,)


class ForecastArtifacts(NamedTuple):
    """The fitted quantities an online server needs — per-month coefficients,
    their lagged rolling means, and the ADDITIVE normal-equation sufficient
    statistics (``XᵀX``, ``Xᵀy``, ``n``, … — the structure that makes
    incremental month ingest a cheap merge instead of a refit). Consumed by
    ``serving.state.ServingState``."""

    coef: jnp.ndarray          # (T, Q) per-month [intercept, slopes]
    month_valid: jnp.ndarray   # (T,) bool: month had >= Q valid rows
    slopes_bar: jnp.ndarray    # (T, P) lagged rolling mean slopes (NaN-gated)
    intercept_bar: jnp.ndarray # (T,)
    stats: NormalStats         # (T, ...) additive per-month sufficient stats


class DecileSortResult(NamedTuple):
    decile_returns: jnp.ndarray  # (T, D) equal-weighted realized return per decile
    decile_counts: jnp.ndarray   # (T, D)
    month_valid: jnp.ndarray     # (T,) months with a usable forecast cross-section
    mean_returns: jnp.ndarray    # (D,) time-series mean per decile
    spread: jnp.ndarray          # () mean top-minus-bottom decile return
    spread_tstat: jnp.ndarray    # () spread / NW SE
    n_months: jnp.ndarray        # ()


def _lagged_coef_means(cs, window: int, min_periods: int,
                       fill_invalid: bool = False):
    """Per-month [intercept, slopes] rows and their LAGGED rolling means.

    Rolling mean over CONSECUTIVE surviving months (row-based, the
    reference's Figure-1 convention, src/calc_Lewellen_2014.py:926),
    shifted one row so month t sees only strictly-prior estimates. Shared
    by the batch forecast and the serving-state refit hook — the serving
    differential contract (streamed queries == batch forecast) holds
    because both sides read the same program.

    ``fill_invalid=True`` (the serving hook) also fills months whose OWN
    cross-section produced no coefficient row: their lagged mean depends
    only on strictly-prior surviving months, so it is equally defined —
    and a serving system must quote E[r] for exactly such months (the
    current month's returns cannot exist yet). The batch forecast keeps
    the scatter convention (NaN at non-surviving months) because its rows
    feed decile sorts that need the month's own cross-section anyway.
    """
    coefs = jnp.concatenate([cs.intercept[:, None], cs.slopes], axis=1)  # (T, Q)
    bar = rolling_over_valid_rows(
        coefs, cs.month_valid, window, min_periods, row_lag=1,
        fill_invalid=fill_invalid,
    )
    return coefs, bar


@functools.partial(
    jax.jit, static_argnames=("window", "min_periods", "solver")
)
def fit_forecast_artifacts(
    y: jnp.ndarray,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    window: int = 120,
    min_periods: int = 60,
    solver: str = "qr",
    cs=None,
) -> ForecastArtifacts:
    """The serving refit hook: everything ``ServingState`` persists, in one
    compiled program.

    Same inputs and conventions as :func:`rolling_er_forecast` (pass ``cs``
    to reuse a precomputed batched OLS); additionally contracts the panel
    into per-month normal-equation sufficient statistics
    (``ops.ols.sufficient_stats``) so a later month can be ingested
    incrementally — stats for disjoint row sets ADD, so appending firms to
    a month is a merge, not a refit.

    The lagged means are the ``fill_invalid`` variant: a month whose own
    cross-section is too thin for a coefficient row still gets the lagged
    mean of its strictly-prior surviving months, so serving can quote
    E[r] there — a DELIBERATE superset of the batch forecast's coverage
    (see ``serving.executor``); everywhere the batch is defined the two
    agree exactly.
    """
    if cs is None:
        cs = monthly_cs_ols(y, x, mask, solver=solver)
    coefs, bar = _lagged_coef_means(cs, window, min_periods, fill_invalid=True)
    stats = sufficient_stats(y, x, row_validity(y, x, mask))
    return ForecastArtifacts(
        coefs, cs.month_valid, bar[:, 1:], bar[:, 0], stats
    )


@functools.partial(
    jax.jit, static_argnames=("window", "min_periods", "solver")
)
def rolling_er_forecast(
    y: jnp.ndarray,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    window: int = 120,
    min_periods: int = 60,
    solver: str = "qr",
    cs=None,
) -> ForecastResult:
    """Strictly out-of-sample Ê[r] from lagged rolling FM coefficients.

    y, x, mask: the dense panel as in ``ops.fama_macbeth`` (x already holds
    LAGGED characteristics, so coefficients from months ≤ t−1 applied to
    x_t use only information available at t). Pass a precomputed ``cs``
    (``CSRegressionResult`` for exactly these inputs) to reuse the figure
    path's batched OLS instead of re-running it.
    """
    if cs is None:
        cs = monthly_cs_ols(y, x, mask, solver=solver)

    coefs, bar = _lagged_coef_means(cs, window, min_periods)
    intercept_bar = bar[:, 0]
    slopes_bar = bar[:, 1:]

    rows = row_validity(y, x, mask)  # forecast needs complete predictors
    have_coef = jnp.isfinite(intercept_bar) & jnp.all(
        jnp.isfinite(slopes_bar), axis=1
    )
    # HIGHEST precision: on TPU the bf16 MXU default can flip marginal
    # decile assignments downstream vs the CPU parity run (ADVICE r1).
    er = intercept_bar[:, None] + jnp.einsum(
        "tnp,tp->tn",
        jnp.where(rows[..., None], x, 0.0),
        slopes_bar,
        precision=jax.lax.Precision.HIGHEST,
    )
    er_valid = rows & have_coef[:, None]
    er = jnp.where(er_valid, er, jnp.nan)
    return ForecastResult(er, er_valid, slopes_bar, intercept_bar)


@functools.partial(
    jax.jit, static_argnames=("n_deciles", "min_obs", "nw_lags", "weight")
)
def decile_sorts(
    er: jnp.ndarray,
    er_valid: jnp.ndarray,
    realized: jnp.ndarray,
    n_deciles: int = 10,
    min_obs: int = 50,
    nw_lags: int = 4,
    weight: str = "reference",
) -> DecileSortResult:
    """Monthly decile portfolios on the forecast, realized-return averages.

    er, er_valid, realized : (T, N). A month participates when it has at
    least ``min_obs`` firms with forecast AND realized return. Breakpoints
    are the masked 10th..90th percentiles (pandas-linear, matching the
    pipeline's other quantiles); decile d spans (q_d, q_{d+1}].
    """
    ok = er_valid & jnp.isfinite(realized)
    n = ok.sum(axis=1)
    month_valid = n >= min_obs

    qs = jnp.arange(1, n_deciles) / n_deciles
    breaks = masked_quantile(er, ok, qs)                      # (T, D-1)
    # decile index = number of interior breakpoints strictly below er
    er_z = jnp.where(ok, er, 0.0)
    dec = (er_z[:, :, None] > breaks[:, None, :]).sum(axis=-1)  # (T, N) in [0, D-1]

    onehot = jax.nn.one_hot(dec, n_deciles, dtype=er.dtype)   # (T, N, D)
    onehot = onehot * ok[:, :, None].astype(er.dtype)
    counts = onehot.sum(axis=1)                                # (T, D)
    ret_z = jnp.where(ok, realized, 0.0)
    sums = jnp.einsum(
        "tnd,tn->td", onehot, ret_z, precision=jax.lax.Precision.HIGHEST
    )
    dec_ret = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), jnp.nan)
    dec_ret = jnp.where(month_valid[:, None], dec_ret, jnp.nan)

    # Summary statistics use months where EVERY decile is populated, so the
    # 10−1 spread and per-decile means cover the same months.
    usable = month_valid & jnp.all(counts > 0, axis=1)
    mean_ret = jnp.where(
        usable.sum() > 0,
        jnp.where(usable[:, None], jnp.nan_to_num(dec_ret), 0.0).sum(axis=0)
        / jnp.maximum(usable.sum(), 1).astype(er.dtype),
        jnp.nan,
    )
    spread_series = dec_ret[:, -1] - dec_ret[:, 0]
    spread_valid = usable & jnp.isfinite(spread_series)
    spread = jnp.where(
        spread_valid.sum() > 0,
        jnp.where(spread_valid, spread_series, 0.0).sum()
        / jnp.maximum(spread_valid.sum(), 1).astype(er.dtype),
        jnp.nan,
    )
    se = nw_mean_se(spread_series, spread_valid, lags=nw_lags, weight=weight)
    return DecileSortResult(
        dec_ret, counts, month_valid, mean_ret, spread, spread / se,
        spread_valid.sum(),
    )
