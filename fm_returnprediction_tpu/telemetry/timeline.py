"""Merged multi-process timeline + per-hop request latency attribution.

Every process in a fleet run exports its own ``events[.pK].jsonl`` /
``trace[.pK].json`` into the shared ``FMRP_TRACE_DIR`` (per-process
filenames — see ``export.jsonl_name``). This module joins them:

- :func:`merge_traces` re-anchors every process's spans onto the
  ROUTER's epoch anchor and writes ONE Chrome/Perfetto document
  (``timeline.json``) with a named row per process. The alignment is
  exact, not statistical: ``perf_counter_ns`` is ``CLOCK_MONOTONIC``,
  shared across processes on one box, and each export's meta carries
  the process's private epoch anchor, so
  ``aligned_us = ts_us + (anchor_router - anchor_proc) / 1e3``
  recovers a single common clock.

- :func:`analyze` reduces the merged spans to a per-hop latency table:
  p50/p99 per hop name, each hop's share of end-to-end p50
  (``fleet.request``), the summed attribution, and the router-side
  share — the number ROADMAP item 2 wants before sharding the router.
  When a journal is given, its FSM records are joined for request
  coverage (admitted/done/requeued counts beside the span counts).

CLI::

    python -m fm_returnprediction_tpu.telemetry.timeline \
        <journal|-> <trace-dir> [--out timeline.json]

Exit status: 0 on a successful merge with e2e coverage, 2 when the
merge produced no ``fleet.request`` spans (the bench smoke treats that
as a broken plane, failing the round instead of a user)."""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path
from typing import Dict, List, Optional

__all__ = [
    "HOPS",
    "ROUTER_HOPS",
    "E2E_SPAN",
    "load_process_traces",
    "merge_traces",
    "analyze",
    "format_table",
    "main",
]

#: the per-request hop chain, in wire order — contiguous segments of
#: one request's life, so their p50s should (approximately) sum to the
#: e2e p50; the gap is unattributed time
HOPS = (
    "hop.admit",          # router: submit() entry → row handed to transport
    "hop.coalesce",       # router: row enqueued → frame flushed to ring
    "hop.transport_req",  # wire: frame t_send → child decoded it
    "hop.solve",          # child: rows decoded → service completion
    "hop.result_send",    # child: completion → result frame t_send
    "hop.transport_resp",  # wire: result t_send → router received it
    "hop.complete",       # router: result received → future resolved
)

#: hops whose cycles are spent on the router process (the GIL-bound
#: ceiling candidates); transport_resp is included because its time is
#: dominated by the router read-loop draining, not the wire
ROUTER_HOPS = ("hop.admit", "hop.coalesce", "hop.transport_resp",
               "hop.complete")

E2E_SPAN = "fleet.request"


def _pctl(values: List[float], q: float) -> float:
    if not values:
        return float("nan")
    xs = sorted(values)
    idx = min(len(xs) - 1, int(round(q / 100.0 * (len(xs) - 1))))
    return xs[idx]


def load_process_traces(trace_dir) -> List[dict]:
    """Parse every ``events*.jsonl`` under ``trace_dir`` into
    ``{"meta": ..., "records": [...]}`` — one entry per process."""
    trace_dir = Path(trace_dir)
    out = []
    for path in sorted(trace_dir.glob("events*.jsonl")):
        meta: dict = {}
        records: List[dict] = []
        try:
            lines = path.read_text().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("type") == "meta":
                meta = rec
            else:
                records.append(rec)
        out.append({"path": str(path), "meta": meta, "records": records})
    return out


def _pick_router(procs: List[dict]) -> Optional[dict]:
    """The router is the export WITHOUT a process_index (the parent
    never sets one); fall back to the first file."""
    for p in procs:
        if p["meta"].get("process_index") is None:
            return p
    return procs[0] if procs else None


def merge_traces(trace_dir, out_name: str = "timeline.json"):
    """Write ONE Perfetto-loadable document merging every process's
    spans onto the router's clock. Returns ``(path, doc)``; ``path`` is
    None when there was nothing to merge."""
    trace_dir = Path(trace_dir)
    procs = load_process_traces(trace_dir)
    router = _pick_router(procs)
    if router is None:
        return None, {"traceEvents": []}
    anchor_router = router["meta"].get("anchor_ns", 0)
    events: List[dict] = []
    for p in procs:
        meta = p["meta"]
        off_us = (anchor_router - meta.get("anchor_ns", anchor_router)) / 1e3
        pid = meta.get("pid", 0)
        k = meta.get("process_index")
        if p is router:
            pname = "fmrp-router"
        elif k is not None:
            pname = f"fmrp-child[p{k}]"
        else:  # pragma: no cover - children always carry an index
            pname = f"fmrp-proc-{pid}"
        events.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": pname},
        })
        threads: Dict[int, str] = {}
        for r in p["records"]:
            kind = r.get("type")
            if kind == "span":
                threads.setdefault(
                    r.get("thread_id", 0), r.get("thread_name", "thread")
                )
                events.append({
                    "ph": "X",
                    "name": r.get("name", "?"),
                    "cat": r.get("cat", "span"),
                    "ts": round(r.get("ts_us", 0.0) + off_us, 3),
                    "dur": r.get("dur_us", 0.0),
                    "pid": pid,
                    "tid": r.get("thread_id", 0),
                    "args": {
                        "trace_id": r.get("trace_id"),
                        "span_id": r.get("span_id"),
                        "parent_id": r.get("parent_id"),
                        **(r.get("attrs") or {}),
                    },
                })
            elif kind == "event":
                events.append({
                    "ph": "i",
                    "name": r.get("name", "?"),
                    "cat": r.get("cat", "event"),
                    "ts": round(r.get("ts_us", 0.0) + off_us, 3),
                    "pid": pid,
                    "tid": r.get("thread_id", 0),
                    "s": "t",
                    "args": r.get("attrs") or {},
                })
        for tid, tname in sorted(threads.items()):
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": tname},
            })
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "router_anchor_ns": anchor_router,
            "processes": len(procs),
        },
    }
    path = trace_dir / out_name
    tmp = path.with_name(f".{path.name}.tmp-{os.getpid()}")
    tmp.write_text(json.dumps(doc, sort_keys=True))
    os.replace(tmp, path)
    return path, doc


def _read_journal(journal_path) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    try:
        lines = Path(journal_path).read_text().splitlines()
    except OSError:
        return counts
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        ev = rec.get("ev")
        if ev:
            counts[ev] = counts.get(ev, 0) + 1
    return counts


def analyze(trace_dir, journal_path=None) -> dict:
    """The per-hop latency table over the merged traces: per hop name
    ``{n, p50_ms, p99_ms, share_pct}`` (share of e2e p50), plus
    ``attributed_pct`` (summed hop shares), ``router_share_pct``
    (router-side hops only), process/journal coverage."""
    procs = load_process_traces(trace_dir)
    durs: Dict[str, List[float]] = {}
    for p in procs:
        for r in p["records"]:
            if r.get("type") != "span":
                continue
            name = r.get("name", "")
            if name in HOPS or name == E2E_SPAN:
                durs.setdefault(name, []).append(
                    r.get("dur_us", 0.0) / 1e3
                )
    e2e = durs.get(E2E_SPAN, [])
    e2e_p50 = _pctl(e2e, 50)
    hops = {}
    attributed = 0.0
    router_share = 0.0
    for name in HOPS:
        vals = durs.get(name)
        if not vals:
            continue
        p50 = _pctl(vals, 50)
        share = (100.0 * p50 / e2e_p50) if e2e_p50 and e2e_p50 > 0 else 0.0
        hops[name] = {
            "n": len(vals),
            "p50_ms": round(p50, 4),
            "p99_ms": round(_pctl(vals, 99), 4),
            "share_pct": round(share, 2),
        }
        attributed += share
        if name in ROUTER_HOPS:
            router_share += share
    return {
        "processes": len(procs),
        "requests": len(e2e),
        "e2e_p50_ms": round(e2e_p50, 4) if e2e else None,
        "e2e_p99_ms": round(_pctl(e2e, 99), 4) if e2e else None,
        "hops": hops,
        "attributed_pct": round(attributed, 2),
        "router_share_pct": round(router_share, 2),
        "journal": _read_journal(journal_path) if journal_path else {},
    }


def format_table(report: dict) -> str:
    lines = [
        f"merged {report['processes']} process trace(s), "
        f"{report['requests']} e2e request span(s)"
    ]
    if report.get("journal"):
        jr = report["journal"]
        lines.append(
            "journal: " + ", ".join(
                f"{k}={v}" for k, v in sorted(jr.items())
            )
        )
    lines.append(
        f"{'hop':<20}{'n':>8}{'p50_ms':>10}{'p99_ms':>10}{'share%':>8}"
    )
    for name in HOPS:
        h = report["hops"].get(name)
        if not h:
            continue
        lines.append(
            f"{name:<20}{h['n']:>8}{h['p50_ms']:>10.3f}"
            f"{h['p99_ms']:>10.3f}{h['share_pct']:>8.1f}"
        )
    if report.get("e2e_p50_ms") is not None:
        lines.append(
            f"e2e p50 {report['e2e_p50_ms']:.3f} ms  "
            f"p99 {report['e2e_p99_ms']:.3f} ms  |  "
            f"attributed {report['attributed_pct']:.1f}%  "
            f"router hops {report['router_share_pct']:.1f}%"
        )
    else:
        lines.append("no e2e spans — merge has no request coverage")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m fm_returnprediction_tpu.telemetry.timeline",
        description="Merge per-process traces and print the per-hop "
                    "request latency table.",
    )
    parser.add_argument("journal", help="journal path, or '-' for none")
    parser.add_argument("trace_dir", help="directory of events*.jsonl")
    parser.add_argument("--out", default="timeline.json",
                        help="merged trace filename (in trace_dir)")
    ns = parser.parse_args(argv)
    journal = None if ns.journal == "-" else ns.journal
    path, doc = merge_traces(ns.trace_dir, out_name=ns.out)
    report = analyze(ns.trace_dir, journal_path=journal)
    print(format_table(report))
    if path is not None:
        n_rows = len({
            e["pid"] for e in doc["traceEvents"] if e.get("ph") == "M"
            and e.get("name") == "process_name"
        })
        print(f"wrote {path} ({n_rows} process row(s))")
    return 0 if report["requests"] else 2


if __name__ == "__main__":  # pragma: no cover - exercised via CLI smoke
    sys.exit(main())
