"""Performance accounting: program cost ledger, profiler hooks, flight
recorder, recompile sentinel.

The PR-5 telemetry substrate answers *where did the wall-clock go* (spans)
and *how often did things happen* (metrics). This module answers the
questions the ROADMAP turns on next: *what does each compiled program
cost* (FLOPs / bytes / device memory / compile seconds — the accounting a
tuned estimation stack is driven by, per the "high-performance routines"
reference in PAPERS.md), *what does the device itself see*
(``jax.profiler`` capture around the host spans), and *what was happening
right before a failure* (the flight recorder).

Four pieces:

- :class:`CostLedger` / :func:`record_compiled` — one record per
  ahead-of-time compiled program: ``Compiled.cost_analysis()`` FLOPs and
  bytes-accessed, ``memory_analysis()`` temp/argument/output bytes,
  lowering + compile wall time, shape-bucket/signature key, and
  persistent-cache provenance (did this compile land a new entry in the
  XLA compilation cache, or was it served from it). The serving
  :class:`BucketedExecutor` and the specgrid fused program record here;
  records export as ``type: "program"`` JSONL events, Chrome-trace
  counter tracks, and ``fmrp_program_*`` Prometheus families. Always on,
  like the metrics registry: the cost is paid at *compile* time (host
  side, once per program), never on the dispatch hot path, and nothing
  here enters a traced function — jaxprs stay byte-identical telemetry
  on or off.
- :func:`profiling` — arms a ``jax.profiler`` device trace around a
  region AND makes every armed host span also emit a
  ``jax.profiler.TraceAnnotation``, so Perfetto shows the device rows
  beside (and labelled by) the PR-5 host spans.
  ``run_pipeline(profile_dir=...)`` / ``--profile-dir`` and
  ``ERService.capture_profile`` wrap this.
- :func:`dump_flight` — the flight recorder: the last N collected
  spans/events plus the ledger tail and a metrics snapshot, written to
  ``flight.json`` in the trace dir. The resilience layer calls it on
  task failure/timeout and serving quarantine, so the ledger and the
  trace agree at crash time.
- :func:`recompile_watch` — diffs the persistent XLA compile cache
  around a region; growth during a region declared *warm* counts into
  ``fmrp_unexpected_recompiles_total`` and warns with the programs the
  ledger saw compile in the window (ROADMAP item 5's "the cache grew
  83→84 on the warm run" becomes an attributed warning instead of a
  silent diff).
"""

from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import itertools
import json
import os
import threading
import time
import warnings
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from fm_returnprediction_tpu.telemetry import metrics as _metrics
from fm_returnprediction_tpu.telemetry import spans as _spans

__all__ = [
    "ProgramRecord",
    "CostLedger",
    "cost_ledger",
    "record_compiled",
    "timed_aot_compile",
    "provenance_summary",
    "record_runtime",
    "peak_flops_estimate",
    "profiling",
    "profiler_active",
    "dump_flight",
    "FLIGHT_NAME",
    "recompile_watch",
    "CacheDelta",
]

FLIGHT_NAME = "flight.json"

_LEDGER_MAX = int(os.environ.get("FMRP_LEDGER_MAX", "4096"))
_FLIGHT_SPANS = int(os.environ.get("FMRP_FLIGHT_SPANS", "256"))


@dataclasses.dataclass(frozen=True)
class ProgramRecord:
    """One AOT-compiled program's cost accounting."""

    program: str  # logical name ("serving_bucket", "specgrid_program", ...)
    signature: str  # shape/dtype/static key the compile was for
    fingerprint: str  # short stable hash of (program, signature)
    backend: str
    lower_s: float
    compile_s: float
    flops: Optional[float]
    bytes_accessed: Optional[float]
    temp_bytes: Optional[int]
    argument_bytes: Optional[int]
    output_bytes: Optional[int]
    generated_code_bytes: Optional[int]
    provenance: str  # "fresh" | "persistent-cache" | "uncached" | "deserialized"
    cache_entries_delta: int
    bucket: Optional[int] = None
    t_ns: int = 0  # perf_counter_ns at record time (epoch-anchorable)
    seq: int = 0
    # "deserialized" records only: the ORIGINAL lowering+compile seconds
    # the registry entry recorded at store time — the seconds this fetch
    # did NOT pay (the bench's compile-seconds-saved series)
    saved_s: Optional[float] = None

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["lower_s"] = round(d["lower_s"], 6)
        d["compile_s"] = round(d["compile_s"], 6)
        return d


class CostLedger:
    """Process-wide, bounded, append-only store of :class:`ProgramRecord`."""

    def __init__(self, maxlen: int = _LEDGER_MAX) -> None:
        self._lock = threading.Lock()
        self._records: List[ProgramRecord] = []
        self._maxlen = maxlen
        self._dropped = 0
        self._seq = itertools.count(1)

    def add(self, record: ProgramRecord) -> ProgramRecord:
        record = dataclasses.replace(record, seq=next(self._seq))
        with self._lock:
            if len(self._records) >= self._maxlen:
                # evict OLDEST: the flight recorder and the recompile
                # sentinel both read the recent tail — dropping the newest
                # would blind them at exactly the failure they exist for
                self._records.pop(0)
                self._dropped += 1
            self._records.append(record)
        return record

    def records(self) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records)

    def tail(self, n: int) -> List[ProgramRecord]:
        with self._lock:
            return list(self._records[-n:])

    def since(self, seq: int) -> List[ProgramRecord]:
        """Records added after sequence number ``seq`` (the recompile
        sentinel's attribution window)."""
        with self._lock:
            return [r for r in self._records if r.seq > seq]

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._records[-1].seq if self._records else 0

    def stats(self) -> dict:
        with self._lock:
            return {"records": len(self._records), "dropped": self._dropped}

    def total(self, field: str, program: Optional[str] = None) -> float:
        """Sum of a numeric field over (optionally one program's) records."""
        out = 0.0
        for r in self.records():
            if program is not None and r.program != program:
                continue
            v = getattr(r, field)
            if v is not None:
                out += v
        return out

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._dropped = 0
            self._seq = itertools.count(1)


_LEDGER: Optional[CostLedger] = None
_LEDGER_LOCK = threading.Lock()
# serializes timed_aot_compile's measure-and-compile window (see there)
_AOT_MEASURE_LOCK = threading.Lock()


def cost_ledger() -> CostLedger:
    """The process-wide cost ledger (created on first use)."""
    global _LEDGER
    if _LEDGER is None:
        with _LEDGER_LOCK:
            if _LEDGER is None:
                _LEDGER = CostLedger()
    return _LEDGER


def _fingerprint(program: str, signature: str) -> str:
    return hashlib.sha256(f"{program}|{signature}".encode()).hexdigest()[:12]


def _cost_dict(compiled) -> dict:
    """``Compiled.cost_analysis()`` normalized to one flat dict; {} when
    the backend does not support it (never raises)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — optional backend feature
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca) if isinstance(ca, dict) else {}


def _memory_fields(compiled) -> dict:
    try:
        ma = compiled.memory_analysis()
        return {
            "temp_bytes": int(ma.temp_size_in_bytes),
            "argument_bytes": int(ma.argument_size_in_bytes),
            "output_bytes": int(ma.output_size_in_bytes),
            "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        }
    except Exception:  # noqa: BLE001 — optional backend feature
        return {
            "temp_bytes": None,
            "argument_bytes": None,
            "output_bytes": None,
            "generated_code_bytes": None,
        }


def _backend_name() -> str:
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — ledger must never break a compile
        return "unknown"


def record_compiled(
    program: str,
    compiled,
    signature: str,
    lower_s: float,
    compile_s: float,
    cache_entries_delta: int = 0,
    cache_enabled: bool = True,
    bucket: Optional[int] = None,
    provenance: Optional[str] = None,
    saved_s: Optional[float] = None,
) -> ProgramRecord:
    """Account one freshly AOT-compiled program into the ledger, the
    metrics registry, and (when tracing is armed) the current span.

    ``cache_entries_delta`` is the persistent XLA compile-cache growth
    measured around the ``compile()`` call: >0 means this compile paid
    full price and landed a new cache entry ("fresh"); 0 with the cache
    enabled means XLA served it from the persistent cache
    ("persistent-cache"); with no cache configured provenance is
    "uncached". An explicit ``provenance`` overrides that derivation —
    the registry's executable plane records its fetches as
    "deserialized" (``lower_s=0``, ``compile_s`` = verify+deserialize
    wall, ``saved_s`` = the store-time compile seconds the fetch did not
    pay)."""
    cost = _cost_dict(compiled)
    flops = cost.get("flops")
    bytes_accessed = cost.get("bytes accessed")
    if provenance is None:
        if not cache_enabled:
            provenance = "uncached"
        else:
            provenance = (
                "fresh" if cache_entries_delta > 0 else "persistent-cache"
            )
    record = cost_ledger().add(
        ProgramRecord(
            program=program,
            signature=signature,
            fingerprint=_fingerprint(program, signature),
            backend=_backend_name(),
            lower_s=float(lower_s),
            compile_s=float(compile_s),
            flops=float(flops) if flops is not None else None,
            bytes_accessed=(
                float(bytes_accessed) if bytes_accessed is not None else None
            ),
            provenance=provenance,
            cache_entries_delta=int(cache_entries_delta),
            bucket=bucket,
            t_ns=time.perf_counter_ns(),
            saved_s=float(saved_s) if saved_s is not None else None,
            **_memory_fields(compiled),
        )
    )
    reg = _metrics.registry()
    reg.counter(
        "fmrp_program_compiles_total",
        help="AOT programs compiled, by logical program and provenance",
        program=program, provenance=provenance,
    ).inc()
    if provenance == "deserialized":
        # a registry fetch's wall is verify+deserialize I/O, not compile —
        # keeping it out of the compile-seconds series is the whole point
        # of the fresh-vs-deserialized provenance split
        reg.counter(
            "fmrp_registry_fetch_seconds_total",
            help="wall seconds spent verifying+deserializing registry "
                 "executables, by program",
            program=program,
        ).inc(record.lower_s + record.compile_s)
    else:
        reg.counter(
            "fmrp_program_compile_seconds_total",
            help="wall seconds spent lowering+compiling, by program",
            program=program,
        ).inc(record.lower_s + record.compile_s)
    if record.flops is not None:
        reg.gauge(
            "fmrp_program_flops",
            help="XLA cost_analysis FLOPs of the last compile, by program",
            program=program,
        ).set(record.flops)
    if record.bytes_accessed is not None:
        reg.gauge(
            "fmrp_program_bytes_accessed",
            help="XLA cost_analysis bytes accessed of the last compile",
            program=program,
        ).set(record.bytes_accessed)
    if record.temp_bytes is not None:
        reg.gauge(
            "fmrp_program_temp_bytes",
            help="XLA memory_analysis temp allocation of the last compile",
            program=program,
        ).set(record.temp_bytes)
    _spans.event(
        "program_compiled", cat="compile",
        program=program, fingerprint=record.fingerprint,
        compile_s=round(record.compile_s, 4), provenance=provenance,
        **({"bucket": bucket} if bucket is not None else {}),
    )
    return record


def timed_aot_compile(jitted, *args, program: str,
                      signature: Optional[str] = None,
                      bucket: Optional[int] = None, **static_kwargs):
    """Lower + compile ``jitted`` ahead of time, timing both phases and
    accounting the result via :func:`record_compiled`. Returns the
    ``Compiled`` executable (call it with the array args only).

    The one AOT entry the serving executor, the specgrid program, and
    the panel characteristics program share, so every compiled program
    in those paths lands in the ledger with the same fields — and the
    one place the registry's EXECUTABLE PLANE rides: with
    ``FMRP_REGISTRY_DIR`` armed, the finished executable is fetched
    (zero traces, zero compiles; ledger provenance "deserialized")
    before any lowering happens, and a fresh compile is stored back for
    the next process. Registry failures of any kind degrade silently to
    the fresh-compile path."""
    if signature is None:
        signature = arg_signature(args, static_kwargs)
    fetched = _registry_fetch(program, signature, bucket)
    if fetched is not None:
        return fetched
    cache_enabled = _persistent_cache_enabled()
    # one compile-measurement window at a time: provenance comes from a
    # GLOBAL cache-dir entry diff, so two concurrent windows would
    # attribute each other's cache entries (thread A labelled "fresh" by
    # thread B's new entry). Serializing here costs parallelism only in
    # the rare concurrent-cold-compile case — warmups loop sequentially —
    # and buys a provenance split that is actually trustworthy.
    with _AOT_MEASURE_LOCK:
        entries_before = (
            _metrics.jax_cache_stats()["entries"] if cache_enabled else 0
        )
        t0 = time.perf_counter()
        lowered = jitted.lower(*args, **static_kwargs)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
        delta = (
            _metrics.jax_cache_stats()["entries"] - entries_before
            if cache_enabled else 0
        )
    record_compiled(
        program, compiled, signature,
        lower_s=t1 - t0, compile_s=t2 - t1,
        cache_entries_delta=delta,
        cache_enabled=cache_enabled,
        bucket=bucket,
    )
    _registry_store(program, signature, compiled, lowered=lowered,
                    bucket=bucket, compile_s=t2 - t0)
    return compiled


def _registry_fetch(program: str, signature: str, bucket: Optional[int]):
    """Executable-plane fetch for :func:`timed_aot_compile`: the loaded
    executable (ledger-recorded as provenance "deserialized"), or None —
    registry off, miss, skew, corruption — in which case the caller
    compiles fresh. Never raises."""
    try:
        from fm_returnprediction_tpu.registry import executables as _rexe
        from fm_returnprediction_tpu.registry.store import active_registry

        reg = active_registry()
        if reg is None:
            return None
        loaded = _rexe.load_executable(program, signature, registry=reg)
        outcome = "hit" if loaded is not None else "miss"
        _metrics.registry().counter(
            "fmrp_registry_executable_fetches_total",
            help="registry executable-plane lookups by program and outcome",
            program=program, outcome=outcome,
        ).inc()
        if loaded is None:
            return None
        record_compiled(
            program, loaded.compiled, signature,
            lower_s=0.0, compile_s=loaded.load_s,
            cache_entries_delta=0,
            bucket=bucket,
            provenance="deserialized",
            saved_s=loaded.meta.get("compile_s"),
        )
        return loaded.compiled
    except Exception:  # noqa: BLE001 — the registry must never break a
        return None    # compile; a broken tree reads as a miss


def _registry_store(program: str, signature: str, compiled, lowered,
                    bucket: Optional[int], compile_s: float) -> None:
    """Persist a fresh compile into the registry (no-op when off; store
    failures warn inside and never propagate)."""
    try:
        from fm_returnprediction_tpu.registry import executables as _rexe
        from fm_returnprediction_tpu.registry.store import active_registry

        reg = active_registry()
        if reg is None:
            return
        _rexe.store_executable(
            program, signature, compiled, registry=reg, bucket=bucket,
            lowered=lowered, compile_s=compile_s,
        )
    except Exception:  # noqa: BLE001 — persistence is an accelerant
        pass


def provenance_summary(records: Optional[List[ProgramRecord]] = None) -> dict:
    """Per-program fresh-vs-deserialized accounting over the ledger (or
    an explicit record window): compile counts by provenance, the wall
    seconds paid fresh, the verify+deserialize seconds paid on fetches,
    and the store-time compile seconds those fetches did NOT pay
    (``saved_s``) — the bench's ``registry_*`` series, so the registry's
    win is a tracked number instead of a one-off claim."""
    out: Dict[str, dict] = {}
    for r in (cost_ledger().records() if records is None else records):
        d = out.setdefault(r.program, {
            "fresh": 0, "persistent-cache": 0, "uncached": 0,
            "deserialized": 0,
            "fresh_compile_s": 0.0, "deserialize_s": 0.0, "saved_s": 0.0,
        })
        d[r.provenance] = d.get(r.provenance, 0) + 1
        if r.provenance == "deserialized":
            d["deserialize_s"] += r.lower_s + r.compile_s
            if r.saved_s is not None:
                d["saved_s"] += r.saved_s
        else:
            d["fresh_compile_s"] += r.lower_s + r.compile_s
    return out


def _persistent_cache_enabled() -> bool:
    """Whether THIS process armed the persistent XLA compilation cache —
    provenance must not claim a cache hit just because a previous run's
    cache directory exists on disk."""
    try:
        import jax

        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:  # noqa: BLE001 — unknown jax: claim nothing
        return False


def _sig_part(a) -> str:
    shape = getattr(a, "shape", None)
    if shape is not None:
        return f"{tuple(shape)}:{getattr(a, 'dtype', None)}"
    if isinstance(a, (list, tuple)):
        # pytree containers recurse (the panel characteristics program
        # passes a list of arrays) — repr of a container would embed full
        # array reprs into the key
        return "[" + ",".join(_sig_part(x) for x in a) + "]"
    return repr(a)


def arg_signature(args, static_kwargs=None) -> str:
    """Deterministic shape/dtype/static key for an AOT cache + the ledger."""
    parts = [_sig_part(a) for a in args]
    if static_kwargs:
        parts.append(
            "|".join(f"{k}={static_kwargs[k]!r}" for k in sorted(static_kwargs))
        )
    return ";".join(parts)


# -- roofline / achieved-FLOPs ---------------------------------------------

#: very rough per-core CPU peak (FMA × vector width × ~3 GHz); the point of
#: the roofline gauge is order-of-magnitude honesty, not vendor marketing
_CPU_PEAK_PER_CORE = 48e9
_TPU_PEAK_DEFAULT = 275e12  # one v4 chip, bf16 — override via FMRP_PEAK_FLOPS


def peak_flops_estimate() -> float:
    """Best-effort peak-FLOPs estimate for the roofline-utilization gauge.

    ``FMRP_PEAK_FLOPS`` overrides (set it when the exact part is known);
    otherwise a disclosed rough default per platform. Never raises."""
    env = os.environ.get("FMRP_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    if _backend_name() == "tpu":
        return _TPU_PEAK_DEFAULT
    return (os.cpu_count() or 1) * _CPU_PEAK_PER_CORE


def record_runtime(program: str, seconds: float,
                   flops: Optional[float] = None) -> dict:
    """Derive achieved FLOP/s (FLOPs ÷ measured runtime) and roofline
    utilization for a bench section's program; sets the
    ``fmrp_program_achieved_flops`` / ``fmrp_program_roofline_utilization``
    gauges and returns the numbers (empty dict when no FLOP count is
    known or the runtime is degenerate).

    ``flops`` defaults to the ledger total for ``program`` — correct when
    the process compiled exactly the program the runtime measures. A
    caller timing ONE execution in a process that compiled several
    signatures of the same program (the bench, after the pipeline
    sections) must pass the executed compile's own FLOPs explicitly or
    the gauge overstates."""
    if flops is None:
        flops = cost_ledger().total("flops", program=program)
    if not flops or seconds <= 0:
        return {}
    achieved = flops / seconds
    peak = peak_flops_estimate()
    util = achieved / peak if peak > 0 else 0.0
    reg = _metrics.registry()
    reg.gauge(
        "fmrp_program_achieved_flops",
        help="ledger FLOPs / measured wall seconds, by program",
        program=program,
    ).set(achieved)
    reg.gauge(
        "fmrp_program_roofline_utilization",
        help="achieved FLOP/s over the (rough) platform peak",
        program=program,
    ).set(util)
    return {
        "achieved_flops": achieved,
        "peak_flops_estimate": peak,
        "roofline_utilization": util,
    }


# -- profiler capture -------------------------------------------------------


def profiler_active() -> bool:
    return _spans.annotation_factory() is not None


@contextlib.contextmanager
def profiling(profile_dir=None):
    """Wrap a region in a ``jax.profiler`` device trace written to
    ``profile_dir`` (pass-through when None), and make every armed host
    span in the region also emit a ``jax.profiler.TraceAnnotation`` so
    the device timeline carries the span names.

    Nesting is refused rather than silently corrupting the outer capture
    (``jax.profiler`` keeps one global trace per process)."""
    if profile_dir is None:
        yield None
        return
    if profiler_active():
        raise RuntimeError(
            "a jax.profiler capture is already active in this process; "
            "stop it before starting another"
        )
    import jax

    profile_dir = str(profile_dir)
    Path(profile_dir).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(profile_dir)
    _spans.set_annotation_factory(jax.profiler.TraceAnnotation)
    try:
        # span collection must be ARMED for annotations to fire (span()
        # returns the shared no-op when telemetry is off): --profile-dir
        # alone promises named host rows on the device timeline, so the
        # capture region forces spans on even without a trace dir
        with _spans.enabled(True):
            yield profile_dir
    finally:
        _spans.set_annotation_factory(None)
        try:
            jax.profiler.stop_trace()
        except Exception:  # noqa: BLE001 — a dead backend must not mask
            pass  # the region's own exception with a profiler teardown one


# -- flight recorder --------------------------------------------------------


def flight_snapshot(reason: str, max_spans: int = _FLIGHT_SPANS) -> dict:
    """The flight-recorder payload: the last ``max_spans`` collected
    spans/events (ring-buffer tail), the ledger tail, and a metrics
    snapshot — everything needed to reconstruct the moments before a
    failure without waiting for the end-of-run export."""
    from fm_returnprediction_tpu.telemetry import export as _export

    cur = _spans.current_span()
    spans = _spans.finished_spans()[-max_spans:]
    events = _spans.standalone_events()[-max_spans:]
    return {
        "type": "flight",
        "schema": 1,
        "reason": reason,
        "pid": os.getpid(),
        "anchor_span_id": cur.span_id if cur is not None else None,
        "collector": _spans.collector_stats(),
        "spans": [_export.span_record(s) for s in spans],
        "events": [_export.event_record(e) for e in events],
        "programs": [r.to_json() for r in cost_ledger().tail(max_spans)],
        "metrics": _export.flat_metrics(),
    }


def dump_flight(reason: str, directory=None) -> Optional[Path]:
    """Write ``flight.json`` (see :func:`flight_snapshot`) into
    ``directory`` (default: the configured trace dir). No-op returning
    None when no directory is armed; never raises — the flight recorder
    runs on failure paths whose original exception must stay primary."""
    directory = directory or _spans.trace_dir()
    if directory is None:
        return None
    try:
        path = Path(directory) / FLIGHT_NAME
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f".{path.name}.tmp-{os.getpid()}")
        tmp.write_text(
            json.dumps(flight_snapshot(reason), sort_keys=True, default=repr)
        )
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — see docstring
        return None


# -- recompile sentinel -----------------------------------------------------


@dataclasses.dataclass
class CacheDelta:
    """Filled in when a :func:`recompile_watch` region exits."""

    label: str
    warm: bool
    entries_before: int = 0
    entries_after: int = 0
    culprits: Tuple[str, ...] = ()

    @property
    def grew(self) -> int:
        return max(0, self.entries_after - self.entries_before)


@contextlib.contextmanager
def recompile_watch(label: str, warm: bool = False):
    """Diff the persistent XLA compile cache around a region.

    Yields a :class:`CacheDelta`. Growth inside a region declared
    ``warm`` means something recompiled that a warm run should have
    reused: it counts into ``fmrp_unexpected_recompiles_total{section=}``
    and WARNS (never fails — ROADMAP item 5 wants the tax surfaced, not
    runs killed), naming the programs the cost ledger saw compile inside
    the window when it knows them."""
    delta = CacheDelta(label=label, warm=warm)
    delta.entries_before = _metrics.jax_cache_stats()["entries"]
    ledger_mark = cost_ledger().last_seq
    try:
        yield delta
    finally:
        delta.entries_after = _metrics.jax_cache_stats()["entries"]
        new_records = cost_ledger().since(ledger_mark)
        delta.culprits = tuple(
            f"{r.program}@{r.fingerprint}" for r in new_records
            if r.provenance == "fresh"
        )
        if delta.grew and warm:
            _metrics.registry().counter(
                "fmrp_unexpected_recompiles_total",
                help="persistent-cache growth observed during warm regions",
                section=label,
            ).inc(delta.grew)
            _spans.event(
                "unexpected_recompile", cat="compile", section=label,
                grew=delta.grew, culprits=",".join(delta.culprits) or "unknown",
            )
            warnings.warn(
                f"warm region {label!r} grew the persistent XLA compile "
                f"cache by {delta.grew} entr{'y' if delta.grew == 1 else 'ies'}"
                " (something recompiled that should have been reused); "
                + (
                    f"ledger-attributed compiles: {', '.join(delta.culprits)}"
                    if delta.culprits
                    else "the cost ledger saw no fresh AOT compile in this "
                         "window, so the culprit is a plain jit trace"
                ),
                stacklevel=3,
            )
