"""Perf-regression sentinel over the bench-history artifacts.

``bench.py`` prints one JSON line per round and the driver archives it as
``BENCH_rNN.json``; until this module the trajectory (r01→r05 in-repo)
lived only in eyeballed JSON diffs. This sentinel turns it into a typed,
gateable report:

    python -m fm_returnprediction_tpu.telemetry.regress BENCH_*.json

- every numeric leaf of each round's ``{metric, value, extra}`` payload
  becomes a **series** (nested dicts flatten to dotted keys:
  ``real_pipeline_stage_s.table_2``), qualified by the section's
  ``*_shape`` disclosure and the round's ``device``
  (``kernel_fm_boot_warm_s@T720_N6000_B10000@cpu``) — a resized section
  or a different platform is a DIFFERENT series, never gated against the
  old one (``_series_key``);
- series are classified by direction from their naming convention
  (``*_s``/``*_ms``/``*_mb``/``*_pct`` lower-is-better; ``*_qps``/
  ``*speedup*``/``*_per_s`` throughputs (rows_per_s, cells_per_s)/
  ``*_utilization`` roofline gauges/``vs_baseline`` higher-is-better —
  the throughput check precedes the ``*_s`` seconds check; anything else
  is reported but never gated);
- per series, the **noise band** is fitted from the history itself: the
  robust scale of the *worsening* consecutive steps (improvements are
  the expected trajectory, not noise), floored at ``floor_rel`` (25%).
  The latest round regresses when it is worse than the **best** round in
  history by more than the band (and by more than ``abs_floor`` in the
  metric's own units — a 0.001 s stage doubling to 0.002 s is not a
  finding); it improves when it sets a new best.

The report is a :class:`RegressionReport` of :class:`MetricVerdict` rows
— consumable as JSON (``--json``), as the CI gate (exit 1 on any
``regressed`` verdict; ``--no-fail`` reports only), by the ``obs``-marked
tier-2 pytest, and by ``bench.py`` itself, which runs the sentinel over
the in-repo history at the end of every round (to stderr, so the one-line
JSON artifact stays parseable).
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import json
import math
import re
import statistics
import sys
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "BenchRound",
    "MetricVerdict",
    "RegressionReport",
    "load_round",
    "load_rounds",
    "build_series",
    "direction",
    "analyze",
    "main",
]

#: statuses a verdict can carry; only "regressed" gates
STATUSES = ("regressed", "improved", "ok", "new", "missing", "skipped")

_ROUND_RE = re.compile(r"r(\d+)")


@dataclasses.dataclass(frozen=True)
class BenchRound:
    """One parsed bench artifact: its label, order key, and numeric leaves."""

    label: str
    order: Tuple[int, str]
    metric: str
    value: float
    values: Dict[str, float]  # flattened numeric leaves incl. the headline
    shapes: Dict[str, str] = dataclasses.field(default_factory=dict)
    # ``*_shape`` string leaves ("kernel_shape": "T720_N6000_B10000"):
    # the section-size disclosures every bench section publishes
    device: Optional[str] = None  # the round's ``extra.device`` platform
    # deliberately-disabled sections: ``{"<section>": {"disabled": why}}``
    # in the round meta — disclosed by the sentinel, never gated (the
    # r08/r09 noise-flappers were silently omitted; silence reads as
    # "covered", an explicit object reads as what it is)
    disabled: Dict[str, str] = dataclasses.field(default_factory=dict)


def _flatten(prefix: str, obj, out: Dict[str, float],
             shapes: Optional[Dict[str, str]] = None,
             disabled: Optional[Dict[str, str]] = None) -> None:
    if isinstance(obj, dict):
        why = obj.get("disabled")
        if isinstance(why, str) and disabled is not None and prefix:
            disabled[prefix] = why
        for k, v in obj.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out, shapes,
                     disabled)
    elif isinstance(obj, bool):
        return  # bools are flags, not measurements
    elif isinstance(obj, (int, float)) and math.isfinite(obj):
        out[prefix] = float(obj)
    elif (shapes is not None and isinstance(obj, str)
          and prefix.endswith("_shape")):
        shapes[prefix] = obj


def load_round(path) -> Optional[BenchRound]:
    """Parse one ``BENCH_*.json`` (the driver's wrapper with a ``parsed``
    payload, or a bare ``{metric, value, extra}`` line). None when the
    file holds no usable payload — the sentinel skips, not crashes, on a
    foreign file."""
    path = Path(path)
    try:
        doc = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    payload = doc.get("parsed", doc) if isinstance(doc, dict) else None
    if not isinstance(payload, dict) or "metric" not in payload:
        return None
    n = doc.get("n") if isinstance(doc, dict) else None
    if n is None:
        m = _ROUND_RE.search(path.stem)
        n = int(m.group(1)) if m else 10**9
    values: Dict[str, float] = {}
    shapes: Dict[str, str] = {}
    disabled: Dict[str, str] = {}
    _flatten("", payload.get("extra") or {}, values, shapes, disabled)
    value = payload.get("value")
    metric = str(payload["metric"])
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        values[metric] = float(value)
    device = (payload.get("extra") or {}).get("device")
    return BenchRound(
        label=path.stem,
        order=(int(n), path.name),
        metric=metric,
        value=float(value) if isinstance(value, (int, float)) else float("nan"),
        values=values,
        shapes=shapes,
        device=str(device) if isinstance(device, str) else None,
        disabled=disabled,
    )


def load_rounds(paths: Sequence) -> List[BenchRound]:
    """Parse + chronologically order the rounds (driver ``n``, falling
    back to the ``rNN`` in the filename)."""
    rounds = [r for r in (load_round(p) for p in paths) if r is not None]
    rounds.sort(key=lambda r: r.order)
    return rounds


def direction(key: str) -> Optional[str]:
    """"lower" / "higher" is-better, or None for untracked series."""
    key = key.split("@", 1)[0]  # drop the shape qualifier (_series_key)
    leaf = key.rsplit(".", 1)[-1]
    if (
        leaf.endswith("_qps")
        or "speedup" in leaf
        or leaf.endswith("_per_s")  # rows_per_s, cells_per_s, ... throughput
        or leaf.endswith("_utilization")  # roofline gauges (kernels ladder)
        or leaf.endswith("_over_thread")  # fleet process/thread ratio
        or leaf == "vs_baseline"
    ):
        return "higher"
    if "." in key:
        # nested breakdowns (per-stage seconds, cache-probe fields) are
        # ATTRIBUTION, not objectives: stage-accounting fixes legitimately
        # move seconds between stages while the total improves (r04→r05
        # did exactly that), so gating them would manufacture regressions
        return None
    if "compile" in leaf:
        # compile wall time swings with persistent-cache state (a fresh
        # CI machine pays full compiles a warmed one doesn't) — report,
        # never gate
        return None
    if leaf.endswith(("_s", "_ms", "_mb", "_bytes", "_pct")):
        return "lower"
    return None


def _series_key(key: str, shapes: Dict[str, str],
                device: Optional[str]) -> str:
    """Qualify a metric by its section's ``*_shape`` disclosure and the
    round's device platform.

    A series is only a series when it measures the same thing: a section
    that resizes (env overrides, new defaults) or a round on different
    hardware produces numbers that are NOT comparable with the history —
    r02/r04_self measured the FM kernel on TPU at T720_N6000_B10000,
    r03-r05 on CPU at T240_N2000_B500, and gating a CPU round against the
    TPU best manufactures a "regression" out of a platform change (the
    compile-key exclusion already acknowledges exactly this
    machine-dependence). Every bench section discloses its size as
    ``<section>_shape`` and every round its ``device``; the series key
    appends both (``kernel_fm_boot_warm_s@T720_N6000_B10000@cpu``), so
    same-shape/same-device history gates and everything else separates.
    Metrics without a shape sibling and rounds predating the disclosures
    keep the bare pieces."""
    best = ""
    for sk in shapes:
        prefix = sk[: -len("shape")]
        if key.startswith(prefix) and len(prefix) > len(best):
            best = sk
    if best:
        key = f"{key}@{shapes[best]}"
    if device:
        key = f"{key}@{device}"
    return key


def build_series(rounds: Sequence[BenchRound]) -> Dict[str, List[Tuple[str, float]]]:
    """series key → [(round label, value)] in round order. Keys are
    shape/device-qualified via :func:`_series_key`."""
    out: Dict[str, List[Tuple[str, float]]] = {}
    for r in rounds:
        for key, v in r.values.items():
            out.setdefault(_series_key(key, r.shapes, r.device), []).append(
                (r.label, v)
            )
    return out


@dataclasses.dataclass(frozen=True)
class MetricVerdict:
    key: str
    status: str  # one of STATUSES
    latest: Optional[float]
    baseline: Optional[float]  # direction-adjusted best of history
    band_ratio: Optional[float]  # worse-than-baseline ratio that gates
    direction: Optional[str]
    history: Tuple[Tuple[str, float], ...]
    note: str = ""

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class RegressionReport:
    rounds: Tuple[str, ...]
    latest: str
    verdicts: Tuple[MetricVerdict, ...]
    # latest round's deliberately-disabled sections: (section, why) —
    # disclosure only; nothing under a disabled section ever gates
    disabled: Tuple[Tuple[str, str], ...] = ()

    def by_status(self, status: str) -> List[MetricVerdict]:
        return [v for v in self.verdicts if v.status == status]

    @property
    def regressions(self) -> List[MetricVerdict]:
        return self.by_status("regressed")

    @property
    def ok(self) -> bool:
        return not self.regressions

    def to_json(self) -> dict:
        return {
            "schema": 1,
            "rounds": list(self.rounds),
            "latest": self.latest,
            "ok": self.ok,
            "counts": {s: len(self.by_status(s)) for s in STATUSES},
            "disabled": {k: v for k, v in self.disabled},
            "verdicts": [v.to_json() for v in self.verdicts],
        }

    def format_text(self, verbose: bool = False) -> str:
        lines = [
            f"perf-regression sentinel: {len(self.rounds)} rounds "
            f"({', '.join(self.rounds)}), latest {self.latest}"
        ]
        counts = {s: len(self.by_status(s)) for s in STATUSES}
        lines.append(
            "  " + "  ".join(f"{s}={n}" for s, n in counts.items() if n)
        )
        for section, why in self.disabled:
            lines.append(
                f"  - disabled  {section}: {why} (disclosed, never gated)"
            )
        show = {"regressed", "improved"} | ({"ok", "new", "missing", "skipped"}
                                            if verbose else set())
        for v in self.verdicts:
            if v.status not in show:
                continue
            arrow = {"regressed": "✗", "improved": "✓"}.get(v.status, "·")
            hist = " -> ".join(f"{x:.4g}" for _, x in v.history)
            extra = f" [{v.note}]" if v.note else ""
            lines.append(
                f"  {arrow} {v.status:<9s} {v.key}: {hist}{extra}"
            )
        if self.ok:
            lines.append("  PASS: no perf regressions beyond noise bands")
        else:
            lines.append(
                f"  FAIL: {counts['regressed']} metric(s) regressed "
                "beyond their fitted noise band"
            )
        return "\n".join(lines)


def _noise_band(history_vals: Sequence[float], dirn: str,
                floor_rel: float, k: float) -> float:
    """Worse-than-best ratio that gates: fitted from the magnitudes of
    the WORSENING consecutive steps in the history (log space), floored
    at ``floor_rel``."""
    worsening: List[float] = []
    for prev, cur in zip(history_vals, history_vals[1:]):
        if prev <= 0 or cur <= 0:
            continue
        step = math.log(cur / prev)
        if dirn == "higher":
            step = -step
        if step > 0:  # got worse — that fluctuation is the noise floor
            worsening.append(step)
    fitted = statistics.median(worsening) if worsening else 0.0
    return math.exp(max(math.log1p(floor_rel), k * fitted))


def analyze(
    rounds: Sequence[BenchRound],
    floor_rel: float = 0.25,
    k: float = 1.5,
    abs_floor: float = 0.05,
) -> RegressionReport:
    """Fit per-metric noise bands over all-but-the-latest round and judge
    the latest. See the module docstring for the model; ``k`` scales the
    fitted worsening-step noise, ``abs_floor`` suppresses regressions
    smaller than that in the metric's own units."""
    if not rounds:
        raise ValueError("no bench rounds to analyze")
    latest = rounds[-1]
    series = build_series(rounds)
    verdicts: List[MetricVerdict] = []

    def _disabled_why(key: str) -> Optional[str]:
        bare = key.split("@", 1)[0]
        for section, why in latest.disabled.items():
            if bare == section or bare.startswith((f"{section}.",
                                                   f"{section}_")):
                return why
        return None

    for key in sorted(series):
        points = series[key]
        history = tuple(points)
        dirn = direction(key)
        in_latest = points and points[-1][0] == latest.label
        prior = [v for label, v in points if label != latest.label]
        if dirn is None:
            verdicts.append(MetricVerdict(
                key, "skipped", points[-1][1] if in_latest else None,
                None, None, None, history, note="untracked (no direction)",
            ))
            continue
        if not in_latest:
            why = _disabled_why(key)
            if why is not None:
                # its section is explicitly disabled in the latest
                # round: disclosed absence, never a "missing" finding
                verdicts.append(MetricVerdict(
                    key, "skipped", None, None, None, dirn, history,
                    note=f"section disabled: {why}",
                ))
                continue
            verdicts.append(MetricVerdict(
                key, "missing", None,
                (min(prior) if dirn == "lower" else max(prior)) if prior else None,
                None, dirn, history,
                note="present in history, absent in latest round",
            ))
            continue
        latest_v = points[-1][1]
        if not prior:
            verdicts.append(MetricVerdict(
                key, "new", latest_v, None, None, dirn, history,
                note="first appearance",
            ))
            continue
        if latest_v <= 0 or any(v <= 0 for v in prior):
            verdicts.append(MetricVerdict(
                key, "skipped", latest_v, None, None, dirn, history,
                note="non-positive values; ratio bands undefined",
            ))
            continue
        best = min(prior) if dirn == "lower" else max(prior)
        band = _noise_band(prior, dirn, floor_rel, k)
        worse_ratio = (latest_v / best) if dirn == "lower" else (best / latest_v)
        if worse_ratio < 1.0:
            status, note = "improved", f"new best (prev {best:.4g})"
        elif worse_ratio > band and abs(latest_v - best) > abs_floor:
            status = "regressed"
            note = (f"{worse_ratio:.2f}x worse than best {best:.4g} "
                    f"(band {band:.2f}x)")
        else:
            status, note = "ok", f"within {band:.2f}x band of best {best:.4g}"
        verdicts.append(MetricVerdict(
            key, status, latest_v, best, round(band, 4), dirn, history,
            note=note,
        ))
    order = {s: i for i, s in enumerate(STATUSES)}
    verdicts.sort(key=lambda v: (order[v.status], v.key))
    return RegressionReport(
        rounds=tuple(r.label for r in rounds),
        latest=latest.label,
        verdicts=tuple(verdicts),
        disabled=tuple(sorted(latest.disabled.items())),
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m fm_returnprediction_tpu.telemetry.regress",
        description="Perf-regression sentinel over BENCH_*.json history.",
    )
    parser.add_argument(
        "files", nargs="*",
        help="bench artifacts in any order (default: ./BENCH_*.json)",
    )
    parser.add_argument("--json", action="store_true",
                        help="emit the full report as JSON on stdout")
    parser.add_argument("--verbose", action="store_true",
                        help="also list ok/new/missing/skipped verdicts")
    parser.add_argument("--no-fail", action="store_true",
                        help="always exit 0 (report-only mode)")
    parser.add_argument("--floor-rel", type=float, default=0.25,
                        help="minimum relative noise band (default 0.25)")
    parser.add_argument("--abs-floor", type=float, default=0.05,
                        help="minimum absolute move to count (default 0.05)")
    args = parser.parse_args(argv)

    files = args.files or sorted(_glob.glob("BENCH_*.json"))
    rounds = load_rounds(files)
    if len(rounds) < 2:
        print(
            f"regress: need >=2 parseable bench rounds, got {len(rounds)} "
            f"from {len(files)} file(s) — nothing to gate",
            file=sys.stderr,
        )
        return 0
    report = analyze(rounds, floor_rel=args.floor_rel,
                     abs_floor=args.abs_floor)
    if args.json:
        print(json.dumps(report.to_json(), indent=2, sort_keys=True))
    else:
        print(report.format_text(verbose=args.verbose))
    if not report.ok and not args.no_fail:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
