"""Per-process telemetry identity: the ``process_index`` label.

One process's metrics and traces are self-describing; N processes'
merged exports are not — a Prometheus scrape of four spec-grid workers
or a directory of four ``events.jsonl`` files needs every sample to say
WHICH process produced it. This module is the one home of that answer:

- ``process_index()`` — the process's rank, or None (single-process,
  the historical byte-identical export);
- set explicitly by ``parallel.distributed.initialize_distributed``
  (the bootstrap), or ambiently via ``FMRP_PROC_INDEX`` (the fleet sets
  it per replica child) / ``FMRP_DIST_PROC_ID`` (exchange workers).

Consumers: ``metrics.MetricsRegistry.to_prometheus`` stamps
``process_index="k"`` onto every exported series, ``export.write_jsonl``
carries it in the meta header, and the Chrome trace names the process
row ``fmrp-host[pK]`` — all ONLY when armed, so single-process exports
stay byte-identical to every prior release (the determinism tests pin
that).
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["process_index", "set_process_index", "process_suffix"]

_EXPLICIT: Optional[int] = None
_UNSET = object()


def set_process_index(index: Optional[int]) -> None:
    """Pin this process's identity (the distributed bootstrap's job);
    ``None`` re-disarms (tests)."""
    global _EXPLICIT
    _EXPLICIT = None if index is None else int(index)


def process_index() -> Optional[int]:
    """The process's rank for export labeling, or None when single-process.

    Precedence: explicit :func:`set_process_index` > ``FMRP_PROC_INDEX``
    (generic identity — fleet replica children) > ``FMRP_DIST_PROC_ID``
    (exchange workers). Resolved live — the repo-wide env-knob
    discipline."""
    if _EXPLICIT is not None:
        return _EXPLICIT
    for var in ("FMRP_PROC_INDEX", "FMRP_DIST_PROC_ID"):
        raw = os.environ.get(var, "").strip()
        if raw:
            try:
                return int(raw)
            except ValueError:
                continue
    return None


def process_suffix() -> str:
    """``"[pK]"`` when armed, ``""`` otherwise (trace process names)."""
    idx = process_index()
    return f"[p{idx}]" if idx is not None else ""
