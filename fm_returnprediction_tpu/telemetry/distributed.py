"""Distributed observability plane: trace propagation, metric
aggregation, and SIGKILL-surviving flight annexes.

The PR-5 telemetry plane (spans, registry, exporters) is process-local;
PRs 13–19 made the runtime multi-process. This module is the glue that
makes N processes observable as ONE system:

- **Trace context propagation** — :func:`trace_env` injects
  ``FMRP_TRACE_*`` into every spawned child's environment (fleet
  replicas, grid workers, brokers) and
  :func:`install_remote_context_from_env` installs it child-side, so a
  child's root spans carry ``remote_trace``/``remote_parent`` attrs
  naming the router span that spawned them. Per-request parenting rides
  the data plane itself: the shm frame header and the socket control
  frames carry ``(t_send_ns, trace_id, parent_span)`` stamps (see
  ``serving.shm.frame_meta``).

- **Clock alignment** — ``time.perf_counter_ns()`` on Linux is
  ``CLOCK_MONOTONIC``, shared by every process on the box, so raw
  monotonic stamps are directly comparable across processes. Each
  process additionally keeps its own epoch anchor
  (``spans.EPOCH_ANCHOR_NS``); children report theirs in the existing
  hello handshake (:func:`register_peer` records it router-side) and
  every export writes it into its meta, so the timeline merge
  (``telemetry.timeline``) can re-anchor all processes onto the
  router's anchor exactly: ``aligned_ts = ts - anchor_child/1e3 +
  anchor_router/1e3``.

- **Metric aggregation** — children ship delta-encoded registry
  snapshots (:func:`registry_delta`) on the existing stats-probe
  heartbeat; the router folds them into a :class:`MetricAggregator`
  keyed by ``{proc=}`` label. The PR-10 dead-replica fold rule applies:
  when a proc departs, its monotone series (``_total``/``_count``/
  ``_sum``/``_bucket`` suffixes) fold into a ``proc="departed"``
  accumulator, so exported fleet totals never move backwards across a
  kill/respawn. All aggregator mutation and every whole-registry
  snapshot share ``metrics.SNAPSHOT_LOCK`` — a scrape concurrent with a
  child delta can never render torn totals.

- **Flight annex** — a tiny double-buffered shm segment per fleet
  member (:class:`FlightAnnex`). The child mirrors its flight-recorder
  tail into the inactive slot and flips the ``active`` word LAST (the
  same commit-last discipline as the ring protocol), so whatever
  instant SIGKILL lands, the parent harvests a complete previous
  mirror. The topology controller attaches the harvest to its probe
  verdict and journal mark.

Imports of ``parallel.shm`` and ``resilience.faults`` are lazy —
``parallel.shm`` imports telemetry for its transport instruments, and
this module must stay importable underneath it.
"""

from __future__ import annotations

import json
import os
import struct
import threading
from pathlib import Path
from typing import Dict, Optional, Tuple

from fm_returnprediction_tpu.telemetry import export as _export
from fm_returnprediction_tpu.telemetry import metrics as _metrics
from fm_returnprediction_tpu.telemetry import spans as _spans

__all__ = [
    "trace_env",
    "install_remote_context_from_env",
    "register_peer",
    "peers",
    "clear_peers",
    "dump_peers",
    "registry_delta",
    "reset_delta_state",
    "metrics_enabled",
    "MetricAggregator",
    "FlightAnnex",
    "annex_enabled",
    "annex_bytes",
    "ANNEX_MIRROR_SITE",
]

_TRUE = ("1", "on", "true", "yes")
_FALSE = ("0", "off", "false", "no")


# ---------------------------------------------------------------------------
# trace context propagation
# ---------------------------------------------------------------------------


def trace_env(base: Optional[dict] = None) -> dict:
    """The ``FMRP_TRACE_*`` block for a spawned child's environment:
    telemetry arming + trace dir passthrough, plus the current span's
    ``(trace_id, span_id)`` as ``FMRP_TRACE_REMOTE`` so the child's root
    spans parent onto the router span doing the spawning. Updates and
    returns ``base`` when given; empty when telemetry is unarmed."""
    env: Dict[str, str] = {}
    for key in ("FMRP_TELEMETRY", "FMRP_TRACE_DIR"):
        val = os.environ.get(key)
        if val:
            env[key] = val
    if _spans.active():
        cur = _spans.current_span()
        if cur is not None:
            env["FMRP_TRACE_REMOTE"] = f"{cur.trace_id}:{cur.span_id}"
    if base is not None:
        base.update(env)
        return base
    return env


def install_remote_context_from_env(env=None) -> Optional[Tuple[int, int]]:
    """Child-side: parse ``FMRP_TRACE_REMOTE`` and install it as the
    remote span context (``spans.set_remote_context``). Returns the
    ``(trace_id, span_id)`` installed, or ``None``."""
    env = os.environ if env is None else env
    raw = env.get("FMRP_TRACE_REMOTE", "")
    if not raw:
        return None
    try:
        trace_id, _, span_id = raw.partition(":")
        ctx = (int(trace_id), int(span_id or 0))
    except ValueError:
        return None
    _spans.set_remote_context(*ctx)
    return ctx


# ---------------------------------------------------------------------------
# peer registry (router-side): who is out there, and on what clock
# ---------------------------------------------------------------------------

_PEERS: Dict[str, dict] = {}
_PEER_LOCK = threading.Lock()


def register_peer(ident, *, pid: Optional[int] = None,
                  anchor_ns: Optional[int] = None,
                  kind: str = "replica") -> dict:
    """Record a child process's identity and epoch anchor (shipped in
    its hello). ``offset_ns`` is the child's anchor minus OURS — the
    exact correction the timeline merge applies, kept here as harvested
    evidence that the clocks were exchanged."""
    entry = {
        "ident": str(ident),
        "kind": kind,
        "pid": None if pid is None else int(pid),
        "anchor_ns": None if anchor_ns is None else int(anchor_ns),
        "offset_ns": (
            None if anchor_ns is None
            else int(anchor_ns) - _spans.EPOCH_ANCHOR_NS
        ),
    }
    with _PEER_LOCK:
        _PEERS[str(ident)] = entry
    return entry


def peers() -> Dict[str, dict]:
    with _PEER_LOCK:
        return {k: dict(v) for k, v in _PEERS.items()}


def clear_peers() -> None:
    with _PEER_LOCK:
        _PEERS.clear()


def dump_peers(trace_dir) -> Path:
    """Write the peer registry as ``peers.json`` beside the trace
    exports (atomic; the timeline CLI reads it when present)."""
    trace_dir = Path(trace_dir)
    trace_dir.mkdir(parents=True, exist_ok=True)
    path = trace_dir / "peers.json"
    doc = {
        "router_pid": os.getpid(),
        "router_anchor_ns": _spans.EPOCH_ANCHOR_NS,
        "peers": peers(),
    }
    tmp = path.with_name(f".{path.name}.tmp-{os.getpid()}")
    tmp.write_text(json.dumps(doc, sort_keys=True, indent=1))
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# metric aggregation: child deltas → one scrape endpoint
# ---------------------------------------------------------------------------


def metrics_enabled() -> bool:
    """Child→router metric shipping knob (``FMRP_OBS_METRICS``, default
    on)."""
    return os.environ.get("FMRP_OBS_METRICS", "1").strip().lower() \
        not in _FALSE


def _numeric_flat() -> Dict[str, float]:
    """The registry as flat numeric series: histogram dict values
    explode into ``_sum``/``_count`` (bucket vectors stay process-local
    — edges aren't carried in the flat key), bools become 0/1, NaN and
    non-numerics drop."""
    flat: Dict[str, float] = {}
    for key, value in _export.flat_metrics().items():
        if isinstance(value, dict):
            name, sep, rest = key.partition("{")
            suffix = f"{{{rest}" if sep else ""
            count = value.get("count")
            total = value.get("sum")
            if isinstance(count, (int, float)):
                flat[f"{name}_count{suffix}"] = count
            if isinstance(total, (int, float)):
                flat[f"{name}_sum{suffix}"] = total
        elif isinstance(value, bool):
            flat[key] = int(value)
        elif isinstance(value, (int, float)) and value == value:
            flat[key] = value
    return flat


_DELTA_LOCK = threading.Lock()
_LAST_SHIPPED: Dict[str, float] = {}


def registry_delta() -> Dict[str, float]:
    """Child-side: the numeric registry series that changed since the
    last call — the delta-encoded payload the stats heartbeat ships.
    First call ships everything."""
    with _DELTA_LOCK:
        flat = _numeric_flat()
        delta = {
            k: v for k, v in flat.items() if _LAST_SHIPPED.get(k) != v
        }
        _LAST_SHIPPED.update(delta)
        return delta


def reset_delta_state() -> None:
    with _DELTA_LOCK:
        _LAST_SHIPPED.clear()


#: suffixes that mark a series monotone (fold-on-death candidates) —
#: the same rule the PR-10 fleet stats fold uses for its agg_* counters
_MONOTONE_SUFFIXES = ("_total", "_count", "_sum", "_bucket")


def _with_proc(key: str, proc: str) -> str:
    name, sep, rest = key.partition("{")
    labels = rest[:-1] if sep else ""
    merged = f"{labels},proc={proc}" if labels else f"proc={proc}"
    return f"{name}{{{merged}}}"


class MetricAggregator:
    """Router-side fold of child registry deltas into one exposition.

    ``ingest(proc, delta)`` accumulates the latest value per series per
    live proc; ``fold_dead(proc)`` retires a proc, folding its monotone
    series into a ``proc="departed"`` accumulator so fleet totals never
    move backwards across a kill/respawn (the replacement respawns
    under a NEW proc label and counts up from zero). Every mutation and
    read holds ``metrics.SNAPSHOT_LOCK`` — the same lock the registry
    flatten and the Prometheus render take — so one snapshot is one
    consistent instant."""

    def __init__(self) -> None:
        self._live: Dict[str, Dict[str, float]] = {}
        self._departed: Dict[str, float] = {}

    def ingest(self, proc, delta: Optional[dict]) -> int:
        if not delta:
            return 0
        with _metrics.SNAPSHOT_LOCK:
            bucket = self._live.setdefault(str(proc), {})
            n = 0
            for key, value in delta.items():
                if isinstance(value, bool):
                    value = int(value)
                if not isinstance(value, (int, float)) or value != value:
                    continue
                bucket[str(key)] = value
                n += 1
            return n

    def fold_dead(self, proc) -> None:
        with _metrics.SNAPSHOT_LOCK:
            last = self._live.pop(str(proc), None)
            if not last:
                return
            for key, value in last.items():
                name = key.partition("{")[0]
                if name.endswith(_MONOTONE_SUFFIXES):
                    self._departed[key] = self._departed.get(key, 0) + value

    def procs(self) -> Tuple[str, ...]:
        with _metrics.SNAPSHOT_LOCK:
            return tuple(sorted(self._live))

    def snapshot(self) -> Dict[str, float]:
        """Flat ``name{...,proc=K} → value`` across live procs plus the
        ``proc=departed`` fold — one consistent instant under the
        snapshot lock."""
        with _metrics.SNAPSHOT_LOCK:
            out: Dict[str, float] = {}
            for proc in sorted(self._live):
                for key, value in sorted(self._live[proc].items()):
                    out[_with_proc(key, proc)] = value
            for key, value in sorted(self._departed.items()):
                out[_with_proc(key, "departed")] = value
            return out

    def totals(self) -> Dict[str, float]:
        """Monotone series summed across live procs + the departed fold
        — the "fleet totals" the monotonicity acceptance watches."""
        with _metrics.SNAPSHOT_LOCK:
            out: Dict[str, float] = {}
            sources = list(self._live.values()) + [self._departed]
            for bucket in sources:
                for key, value in bucket.items():
                    name = key.partition("{")[0]
                    if name.endswith(_MONOTONE_SUFFIXES):
                        out[key] = out.get(key, 0) + value
            return out

    def prometheus_text(self) -> str:
        """The aggregated child series as exposition lines (untyped —
        the router's own registry already declares TYPE for its local
        twins of these names; proc labels keep the series distinct)."""
        lines = []
        for key, value in sorted(self.snapshot().items()):
            name, sep, rest = key.partition("{")
            labels = rest[:-1] if sep else ""
            parts = []
            for item in labels.split(","):
                if not item:
                    continue
                k, _, v = item.partition("=")
                parts.append(
                    f'{_metrics.sanitize(k)}='
                    f'"{_metrics.escape_label_value(v)}"'
                )
            rendered = f"{{{','.join(parts)}}}" if parts else ""
            lines.append(f"{_metrics.sanitize(name)}{rendered} {value}")
        return "\n".join(lines) + ("\n" if lines else "")


# ---------------------------------------------------------------------------
# flight annex: the tail that survives SIGKILL
# ---------------------------------------------------------------------------

#: chaos site between the payload write and the commit flip — a SIGKILL
#: injected here MUST leave the previous mirror harvestable
ANNEX_MIRROR_SITE = "obs.annex_mirror"

_ANNEX_MAGIC = 0x464D4F41  # "FMOA"
#: magic, slot_bytes, len0, len1, active (0/1 valid, other = none);
#: ``active`` is the LAST word a mirror writes — commit-last, like the
#: ring protocol, so a torn mirror is absent, never partial
_ANNEX_HDR = struct.Struct("<IIIII")
_ANNEX_NONE = 0xFFFFFFFF


def annex_enabled() -> bool:
    """Whether fleet members get a flight annex: ``FMRP_OBS_ANNEX``
    forces on/off; unset defaults to armed-telemetry-only so the
    unarmed hot path never pays for mirrors."""
    raw = os.environ.get("FMRP_OBS_ANNEX", "").strip().lower()
    if raw in _FALSE:
        return False
    if raw in _TRUE:
        return True
    return _spans.active()


def annex_bytes() -> int:
    try:
        n = int(os.environ.get("FMRP_OBS_ANNEX_BYTES", "16384"))
    except ValueError:
        n = 16384
    return max(1024, n)


class FlightAnnex:
    """A per-member double-buffered shm mailbox for flight-recorder
    tails. The parent creates and owns it (ledgered for the topology
    sweep); the child attaches and mirrors; the parent harvests after
    death — including death by SIGKILL, which skips atexit and takes
    the child's in-memory collector with it."""

    def __init__(self, seg, slot_bytes: int, owner: bool) -> None:
        self._seg = seg
        self.slot_bytes = int(slot_bytes)
        self.owner = owner
        self.name = seg.name

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def create(cls, ident: str, nbytes: Optional[int] = None
               ) -> "FlightAnnex":
        from multiprocessing import shared_memory

        from fm_returnprediction_tpu.parallel import shm as _pshm

        slot = (nbytes if nbytes is not None else annex_bytes())
        size = _ANNEX_HDR.size + 2 * slot
        safe = "".join(c if c.isalnum() else "-" for c in str(ident))
        name = f"fmrp-annex-{safe}-{os.getpid()}-{os.urandom(3).hex()}"
        seg = shared_memory.SharedMemory(name=name, create=True, size=size)
        _ANNEX_HDR.pack_into(
            seg.buf, 0, _ANNEX_MAGIC, slot, 0, 0, _ANNEX_NONE
        )
        _pshm._ledger_add(seg.name)
        return cls(seg, slot, owner=True)

    def describe(self) -> dict:
        return {"name": self.name, "slot_bytes": self.slot_bytes}

    @classmethod
    def attach(cls, spec: dict) -> "FlightAnnex":
        from multiprocessing import shared_memory

        from fm_returnprediction_tpu.parallel import shm as _pshm

        seg = shared_memory.SharedMemory(name=spec["name"])
        _pshm._unregister(seg.name)  # attacher must not unlink (bpo-38119)
        magic = _ANNEX_HDR.unpack_from(seg.buf, 0)[0]
        if magic != _ANNEX_MAGIC:
            seg.close()
            raise ValueError(f"not a flight annex: {spec['name']}")
        return cls(seg, int(spec["slot_bytes"]), owner=False)

    def close(self) -> None:
        try:
            self._seg.close()
        except (OSError, BufferError):
            pass

    def release(self) -> None:
        """Owner-side disposal through the shared ledger teardown."""
        if not self.owner:
            self.close()
            return
        from fm_returnprediction_tpu.parallel import shm as _pshm

        _pshm.release_segment(self._seg)

    # -- child side --------------------------------------------------------

    def mirror(self, payload: dict) -> bool:
        """Write ``payload`` into the inactive slot, then commit.
        Returns False (previous mirror untouched) when the payload
        doesn't fit. The chaos site fires BETWEEN payload write and
        commit — SIGKILL there must leave the previous mirror whole."""
        data = json.dumps(payload, sort_keys=True).encode()
        if len(data) > self.slot_bytes:
            return False
        buf = self._seg.buf
        active = _ANNEX_HDR.unpack_from(buf, 0)[4]
        target = 1 - active if active in (0, 1) else 0
        off = _ANNEX_HDR.size + target * self.slot_bytes
        buf[off:off + len(data)] = data
        struct.pack_into("<I", buf, 8 + 4 * target, len(data))
        try:
            from fm_returnprediction_tpu.resilience.faults import fault_site

            fault_site(ANNEX_MIRROR_SITE, payload=target)
        except ImportError:  # pragma: no cover - resilience always present
            pass
        struct.pack_into("<I", buf, 16, target)  # commit LAST
        return True

    def mirror_flight(self, reason: str, max_spans: int = 32) -> bool:
        """Mirror a compact flight snapshot, shedding weight until it
        fits the slot (full → no metrics → last-8 spans → vitals)."""
        from fm_returnprediction_tpu.telemetry import perf as _perf

        snap = _perf.flight_snapshot(reason, max_spans=max_spans)
        candidates = (
            snap,
            {**snap, "metrics": {}},
            {**snap, "metrics": {}, "spans": snap.get("spans", [])[-8:],
             "events": snap.get("events", [])[-8:]},
            {"type": "flight", "schema": snap.get("schema", 1),
             "reason": reason, "pid": os.getpid()},
        )
        for candidate in candidates:
            if self.mirror(candidate):
                return True
        return False

    # -- parent side -------------------------------------------------------

    def harvest(self) -> Optional[dict]:
        """Read the committed slot; None when no complete mirror exists
        (never raises on garbage — a half-written annex reads as
        absent)."""
        try:
            buf = self._seg.buf
            _, slot, len0, len1, active = _ANNEX_HDR.unpack_from(buf, 0)
        except (ValueError, struct.error):
            return None
        if active not in (0, 1):
            return None
        ln = (len0, len1)[active]
        if not 0 < ln <= self.slot_bytes:
            return None
        off = _ANNEX_HDR.size + active * self.slot_bytes
        try:
            return json.loads(bytes(buf[off:off + ln]).decode())
        except (ValueError, UnicodeDecodeError):
            return None
