"""Declarative SLOs + a sliding-window monitor over the serving metrics.

ROADMAP item 1 asks for "p99 SLO enforced via the existing Prometheus
endpoint". The batcher's latency ring already yields lifetime p50/p99;
what it cannot answer is *is the objective holding right now* — a
cumulative ring pools last hour's healthy samples into this minute's
incident (the same masking the degraded-mode bench comparison had to
work around). This module is the windowed view:

- an :class:`SLO` is a declarative objective: "no more than ``budget``
  of requests in the trailing ``window_s`` may be *bad*", where bad is
  either a failure (``kind="error_rate"``), a latency above
  ``threshold_ms`` (``kind="latency"`` — a classic "p99 < X" SLO is
  ``threshold_ms=X, budget=0.01``), or queue occupancy above a fraction
  (``kind="queue"``).
- :class:`SloMonitor` ingests per-request observations (the service's
  batcher feeds it), maintains one sliding window, and derives per-SLO
  **burn rate** (observed bad fraction ÷ budget) and **state**:
  ``ok`` (burn < ``warn_burn``), ``warn`` (< ``breach_burn``),
  ``breach`` (≥). Recovery is just the window draining — states are a
  pure function of the trailing window, so transitions are deterministic
  under an injected clock (unit-tested against a synthetic stream).

``ERService`` builds its monitor from explicit objectives or the
``FMRP_SLO_*`` env knobs (:func:`slos_from_env`), surfaces the state in
``stats()`` and as ``fmrp_slo_*`` gauges in ``/metrics``.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "SLO",
    "SloMonitor",
    "slos_from_env",
    "STATE_OK",
    "STATE_WARN",
    "STATE_BREACH",
    "STATE_CODES",
]

STATE_OK = "ok"
STATE_WARN = "warn"
STATE_BREACH = "breach"
#: numeric encoding for Prometheus gauges (alerts key off >=1 / >=2)
STATE_CODES = {STATE_OK: 0, STATE_WARN: 1, STATE_BREACH: 2}

_KINDS = ("latency", "error_rate", "queue")


@dataclasses.dataclass(frozen=True)
class SLO:
    """One objective over the trailing window.

    ``budget`` is the allowed bad fraction (0.01 = 1%); ``threshold_ms``
    only applies to ``kind="latency"`` (a request slower than it is bad)
    and, reinterpreted as an occupancy fraction in (0, 1], to
    ``kind="queue"``, whose burn is CONTINUOUS — occupancy over the
    ceiling — so pick ``warn_burn``/``breach_burn`` on that scale (the
    env-armed default warns at 0.8× the ceiling, breaches at it)."""

    name: str
    kind: str = "latency"
    threshold_ms: Optional[float] = None
    budget: float = 0.01
    warn_burn: float = 1.0
    breach_burn: float = 2.0

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"SLO kind must be one of {_KINDS}, got {self.kind!r}")
        if self.kind in ("latency", "queue") and self.threshold_ms is None:
            raise ValueError(f"SLO {self.name!r}: {self.kind} needs threshold_ms")
        if not 0 < self.budget <= 1:
            raise ValueError(f"SLO {self.name!r}: budget must be in (0, 1]")
        if self.breach_burn < self.warn_burn:
            raise ValueError(
                f"SLO {self.name!r}: breach_burn < warn_burn would make "
                "the warn state unreachable"
            )


class SloMonitor:
    """Sliding-window burn-rate evaluation of a set of :class:`SLO`\\ s.

    ``clock`` is injectable (monotonic seconds) so tests drive the
    window deterministically; production uses ``time.monotonic``."""

    def __init__(
        self,
        objectives: Tuple[SLO, ...],
        window_s: float = 60.0,
        max_samples: int = 65536,
        clock=time.monotonic,
    ) -> None:
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.objectives = tuple(objectives)
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()
        # (t, latency_s or nan, ok) — one deque, bounded: a flood beyond
        # max_samples ages out oldest-first, same shape as the batcher ring
        self._samples: deque = deque(maxlen=max_samples)
        self._queue_frac = 0.0  # latest queue occupancy (gauge-style)

    # -- ingestion ---------------------------------------------------------

    def observe(self, latency_s: Optional[float], ok: bool = True,
                now: Optional[float] = None) -> None:
        """One finished request: its latency (None for a request that
        never produced one, e.g. a backpressure reject) and whether it
        succeeded."""
        t = self._clock() if now is None else now
        lat = float("nan") if latency_s is None else float(latency_s)
        with self._lock:
            self._samples.append((t, lat, bool(ok)))

    def observe_queue(self, occupancy_fraction: float) -> None:
        """Latest queue occupancy (depth / max_queue), a point-in-time
        gauge rather than a windowed sample."""
        with self._lock:
            self._queue_frac = float(occupancy_fraction)

    # -- evaluation --------------------------------------------------------

    def _window(self, now: float) -> List[tuple]:
        cutoff = now - self.window_s
        with self._lock:
            # drop aged-out samples so a long-lived service's memory and
            # evaluation cost stay bounded by traffic, not uptime
            while self._samples and self._samples[0][0] < cutoff:
                self._samples.popleft()
            return list(self._samples)

    def snapshot(self, now: Optional[float] = None) -> dict:
        """Window stats + per-objective burn/state + the overall worst
        state. Deterministic given the sample stream and ``now``."""
        now = self._clock() if now is None else now
        window = self._window(now)
        lats = np.asarray(
            [s[1] for s in window if s[2] and s[1] == s[1]], dtype=np.float64
        )
        n = len(window)
        n_bad = sum(1 for s in window if not s[2])
        out: dict = {
            "window_s": self.window_s,
            "n": n,
            "error_rate": (n_bad / n) if n else 0.0,
            "p50_ms": float(np.percentile(lats, 50) * 1e3) if len(lats) else None,
            "p99_ms": float(np.percentile(lats, 99) * 1e3) if len(lats) else None,
            "qps": n / self.window_s,
            "queue_occupancy": self._queue_frac,
        }
        worst = STATE_OK
        objectives: Dict[str, dict] = {}
        for slo in self.objectives:
            if slo.kind == "error_rate":
                bad_frac = out["error_rate"]
            elif slo.kind == "latency":
                thresh_s = slo.threshold_ms / 1e3
                slow = sum(
                    1 for s in window if (not s[2]) or s[1] > thresh_s
                )
                bad_frac = (slow / n) if n else 0.0
            else:  # queue: continuous exceedance, not a binary trip — a
                # binary bad_frac caps burn at 1/budget and can leave the
                # breach threshold unreachable no matter how saturated
                # the queue is
                bad_frac = (
                    self._queue_frac / slo.threshold_ms
                    if slo.threshold_ms > 0 else 0.0
                )
            burn = bad_frac / slo.budget
            if burn >= slo.breach_burn:
                state = STATE_BREACH
            elif burn >= slo.warn_burn:
                state = STATE_WARN
            else:
                state = STATE_OK
            objectives[slo.name] = {
                "kind": slo.kind,
                "bad_fraction": bad_frac,
                "burn_rate": burn,
                "state": state,
                "state_code": STATE_CODES[state],
            }
            if STATE_CODES[state] > STATE_CODES[worst]:
                worst = state
        out["objectives"] = objectives
        out["state"] = worst
        out["state_code"] = STATE_CODES[worst]
        return out

    def worst_burn(self, now: Optional[float] = None) -> float:
        """The hottest objective's burn rate (0.0 with no objectives) —
        the scalar pressure signal the fleet autoscaler and brownout
        controller key off (``serving.supervisor``). Same snapshot, one
        number."""
        snap = self.snapshot(now=now)
        burns = [o["burn_rate"] for o in snap["objectives"].values()]
        return max(burns) if burns else 0.0


def slos_from_env(environ=None) -> Tuple[SLO, ...]:
    """Objectives from the ``FMRP_SLO_*`` knobs (empty tuple when none
    are set — the service then runs without a monitor):

    - ``FMRP_SLO_P99_MS``      → latency SLO, 1% budget ("p99 < X ms");
    - ``FMRP_SLO_P50_MS``      → latency SLO, 50% budget;
    - ``FMRP_SLO_ERROR_RATE``  → error-rate SLO with that budget;
    - ``FMRP_SLO_QUEUE``       → queue-occupancy ceiling (fraction);
    - ``FMRP_SLO_WINDOW_S``, ``FMRP_SLO_WARN_BURN``,
      ``FMRP_SLO_BREACH_BURN`` tune the latency/error objectives above.
      The QUEUE objective is excluded: its burn is occupancy/ceiling
      (bounded by 1/ceiling, a different scale from fraction-of-budget
      burns), so it pins warn=0.8×/breach=1× the ceiling — construct an
      explicit :class:`SLO` to tune it.
    """
    env = os.environ if environ is None else environ
    warn = float(env.get("FMRP_SLO_WARN_BURN", "1.0"))
    breach = float(env.get("FMRP_SLO_BREACH_BURN", "2.0"))
    out: List[SLO] = []
    p99 = env.get("FMRP_SLO_P99_MS")
    if p99:
        out.append(SLO("p99_latency", "latency", threshold_ms=float(p99),
                       budget=0.01, warn_burn=warn, breach_burn=breach))
    p50 = env.get("FMRP_SLO_P50_MS")
    if p50:
        out.append(SLO("p50_latency", "latency", threshold_ms=float(p50),
                       budget=0.50, warn_burn=warn, breach_burn=breach))
    err = env.get("FMRP_SLO_ERROR_RATE")
    if err:
        out.append(SLO("error_rate", "error_rate", budget=float(err),
                       warn_burn=warn, breach_burn=breach))
    queue = env.get("FMRP_SLO_QUEUE")
    if queue:
        # occupancy is bounded by 1.0, so the shared burn thresholds
        # (warn=1, breach=2) would make breach unreachable for any
        # ceiling above 0.5: the queue objective gets its own scale —
        # warn at 80% of the ceiling, breach at the ceiling itself
        out.append(SLO("queue_occupancy", "queue",
                       threshold_ms=float(queue), budget=1.0,
                       warn_burn=0.8, breach_burn=1.0))
    return tuple(out)


def env_window_s(environ=None) -> float:
    env = os.environ if environ is None else environ
    return float(env.get("FMRP_SLO_WINDOW_S", "60"))
