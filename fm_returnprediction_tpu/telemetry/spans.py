"""Span tracer: nested, context-propagated host spans with monotonic clocks.

The framework's headline metric is wall-clock, but until this module every
layer kept its own stopwatch: ``StageTimer`` flat duration dicts, the
serving batcher's latency rings, per-task seconds in the task-graph sqlite
state, ad-hoc ``time.perf_counter()`` pairs all over ``bench.py``. Round
4's mis-attribution bug (async dispatch let Table 1 absorb upstream panel
work at its first ``device_get`` — ``utils.timing.stage_sync``) is what a
flat-dict view of time costs: no nesting, no causality, no cross-thread
story. This tracer is the one clock:

- a **span** is a named interval with a ``trace_id``/``span_id``/
  ``parent_id`` triple; spans nest via a ``contextvars.ContextVar``, so
  ``run_pipeline`` → stage → sub-stage → retry attempt → device dispatch
  all share one trace and reconstruct as a tree;
- **cross-thread propagation is explicit**: a thread does not inherit its
  parent's context, so code that hops threads (the task graph's
  watchdogged action workers, the serving executor's dispatch watchdog,
  the microbatcher's flusher) captures the current span with
  :func:`capture` and re-enters it with :func:`attach`;
- **events** are point-in-time records (a retry backoff, a checkpoint
  hit, a quarantine) attached to the current span when one is open, else
  collected standalone — the structured twin of what previously only
  landed in private ledgers (the resilience sqlite ``failure_log``, the
  serving quarantine dict);
- :func:`device_sync` subsumes ``utils.timing.stage_sync``: the same
  ``FMRP_SYNC_STAGES``-gated execution barrier, now also recorded as a
  sync event with its measured wait, so the trace shows where device time
  was deliberately charged to its owner.

OFF BY DEFAULT, and off means *off*: :func:`span` costs one module-global
read and returns a shared no-op context manager — no allocation, no lock,
no clock read (the ``obs_overhead`` bench section bounds the ON cost
instead). Telemetry is host-side only: nothing here is ever traced into a
jitted program, so jaxprs are byte-identical with telemetry on or off
(pinned by ``tests/test_telemetry.py``, mirroring the guard property
tests). The switch is ``FMRP_TELEMETRY`` (or implicitly: a configured
trace dir, ``FMRP_TRACE_DIR`` / ``run_pipeline(trace_dir=...)``).

Span/trace IDs are small sequential integers (deterministic within a
process after :func:`reset`), timestamps are ``perf_counter_ns`` anchored
once to the epoch at import — monotonic durations, wall-clock placement,
so the exported Chrome trace lines up with a ``jax.profiler`` device
trace loaded alongside it in Perfetto.
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from contextvars import ContextVar
from typing import Dict, List, Optional

__all__ = [
    "Span",
    "active",
    "set_enabled",
    "enabled",
    "span",
    "event",
    "record_span",
    "capture",
    "attach",
    "device_sync",
    "timed",
    "current_span",
    "finished_spans",
    "standalone_events",
    "collector_stats",
    "reset",
    "trace_dir",
    "set_trace_dir",
    "remote_context",
    "set_remote_context",
    "annotation_factory",
    "set_annotation_factory",
]

_TRUE = ("1", "on", "true", "yes")


def _env_enabled() -> bool:
    if os.environ.get("FMRP_TRACE_DIR"):
        return True
    return os.environ.get("FMRP_TELEMETRY", "0").strip().lower() in _TRUE


_ENABLED: bool = _env_enabled()
_TRACE_DIR: Optional[str] = os.environ.get("FMRP_TRACE_DIR") or None

# wall-clock ns at perf_counter_ns()==0: monotonic timestamps inside the
# process, epoch placement in the exporters (one anchor per process keeps
# every span on the same timeline as jax.profiler's device trace)
EPOCH_ANCHOR_NS: int = time.time_ns() - time.perf_counter_ns()

_IDS = itertools.count(1)
_LOCK = threading.Lock()
_SPANS: List["Span"] = []  # finished spans, append order
_EVENTS: List[dict] = []  # standalone events (no enclosing span)
_MAX_RECORDS = int(os.environ.get("FMRP_TELEMETRY_MAX_SPANS", "200000"))
_DROPPED = 0

_CURRENT: ContextVar[Optional["Span"]] = ContextVar(
    "fmrp_current_span", default=None
)

# Remote trace context (telemetry.distributed): a CHILD process spawned
# inside a router request records the parent's (trace_id, span_id) here —
# every ROOT span this process opens then carries ``remote_trace``/
# ``remote_parent`` attrs, which is how the timeline merge parents child
# spans onto the router's request span without coordinating span-ID
# allocation across processes (Chrome X events are keyed by pid/tid/ts;
# the ids only need to be meaningful as a join key in ``args``).
_REMOTE_CTX: Optional[tuple] = None  # (trace_id, span_id) of the remote parent


def remote_context() -> Optional[tuple]:
    """The installed remote parent ``(trace_id, span_id)``, or None."""
    return _REMOTE_CTX


def set_remote_context(trace_id: Optional[int],
                       span_id: Optional[int] = None) -> None:
    """Install (or clear, with ``None``) the remote parent context — the
    child-side half of cross-process trace propagation
    (``telemetry.distributed.install_remote_context_from_env``)."""
    global _REMOTE_CTX
    _REMOTE_CTX = None if trace_id is None else (int(trace_id),
                                                 int(span_id or 0))


# When a jax.profiler capture is live (telemetry.perf.profiling), this is
# jax.profiler.TraceAnnotation: every armed span also annotates the device
# trace so Perfetto shows named device rows beside the host spans. None —
# the default — keeps jax entirely out of the span hot path.
_ANNOTATION_FACTORY = None


def annotation_factory():
    return _ANNOTATION_FACTORY


def set_annotation_factory(factory) -> None:
    global _ANNOTATION_FACTORY
    _ANNOTATION_FACTORY = factory


def active() -> bool:
    """Whether span collection is armed (one module-global read)."""
    return _ENABLED


def set_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


@contextlib.contextmanager
def enabled(flag: bool):
    """Force telemetry on/off for a block (the bench's off/on comparison
    and ``run_pipeline(trace_dir=...)`` both use this)."""
    global _ENABLED
    prev = _ENABLED
    _ENABLED = bool(flag)
    try:
        yield
    finally:
        _ENABLED = prev


def trace_dir() -> Optional[str]:
    """The configured export directory (``FMRP_TRACE_DIR`` / ``set_trace_dir``),
    or None when exports are unarmed."""
    return _TRACE_DIR


def set_trace_dir(path: Optional[str]) -> None:
    global _TRACE_DIR
    _TRACE_DIR = str(path) if path else None


class Span:
    """One finished-or-open interval. Times are ``perf_counter_ns``."""

    __slots__ = (
        "name",
        "cat",
        "trace_id",
        "span_id",
        "parent_id",
        "t0_ns",
        "t1_ns",
        "thread_id",
        "thread_name",
        "attrs",
        "events",
    )

    def __init__(self, name: str, cat: str, attrs: Dict[str, object]):
        parent = _CURRENT.get()
        self.name = name
        self.cat = cat
        self.span_id = next(_IDS)
        if parent is None:
            self.trace_id = self.span_id
            self.parent_id = None
            if _REMOTE_CTX is not None:
                # a root span in a child process parents onto the remote
                # (router-side) request span by ATTRIBUTE, not by id — see
                # the _REMOTE_CTX note above
                attrs = {**attrs, "remote_trace": _REMOTE_CTX[0],
                         "remote_parent": _REMOTE_CTX[1]}
        else:
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        t = threading.current_thread()
        self.thread_id = t.ident or 0
        self.thread_name = t.name
        self.attrs = attrs
        self.events: List[tuple] = []  # (name, t_ns, attrs)
        self.t0_ns = time.perf_counter_ns()
        self.t1_ns: Optional[int] = None

    @property
    def duration_s(self) -> float:
        end = self.t1_ns if self.t1_ns is not None else time.perf_counter_ns()
        return (end - self.t0_ns) / 1e9

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"Span({self.name!r}, id={self.span_id}, trace={self.trace_id}, "
            f"parent={self.parent_id}, {self.duration_s * 1e3:.3f}ms)"
        )


def _collect_span(s: Span) -> None:
    global _DROPPED
    with _LOCK:
        if len(_SPANS) >= _MAX_RECORDS:
            _DROPPED += 1
            return
        _SPANS.append(s)


class _SpanCtx:
    """Context manager for one live span (allocated only when armed)."""

    __slots__ = ("_name", "_cat", "_attrs", "_span", "_token", "_ann")

    def __init__(self, name: str, cat: str, attrs: Dict[str, object]):
        self._name = name
        self._cat = cat
        self._attrs = attrs

    def __enter__(self) -> Span:
        self._span = Span(self._name, self._cat, self._attrs)
        self._token = _CURRENT.set(self._span)
        factory = _ANNOTATION_FACTORY
        self._ann = None
        if factory is not None:
            # mirror the span into the live jax.profiler capture so the
            # device timeline carries the same names as the host trace
            try:
                self._ann = factory(self._name)
                self._ann.__enter__()
            except Exception:  # noqa: BLE001 — profiling must never break
                self._ann = None  # the instrumented code path
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._span
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:  # noqa: BLE001 — see __enter__
                pass
        s.t1_ns = time.perf_counter_ns()
        if exc is not None:
            s.attrs = {**s.attrs, "error": repr(exc)[:200]}
        _CURRENT.reset(self._token)
        _collect_span(s)
        return False


class _Noop:
    """Shared do-nothing context manager — the telemetry-off fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc) -> bool:
        return False


_NOOP = _Noop()


def span(name: str, cat: str = "span", **attrs):
    """Open a span named ``name`` for the ``with`` block. When telemetry is
    off this returns a shared no-op context manager — near-zero cost."""
    if not _ENABLED:
        return _NOOP
    return _SpanCtx(name, cat, attrs)


def event(name: str, cat: str = "event", **attrs) -> None:
    """Record a point-in-time event on the current span (standalone when no
    span is open). No-op when telemetry is off."""
    global _DROPPED
    if not _ENABLED:
        return
    t_ns = time.perf_counter_ns()
    cur = _CURRENT.get()
    if cur is not None:
        cur.events.append((name, t_ns, attrs))
        return
    t = threading.current_thread()
    rec = {
        "name": name,
        "cat": cat,
        "t_ns": t_ns,
        "thread_id": t.ident or 0,
        "thread_name": t.name,
        "attrs": attrs,
    }
    with _LOCK:
        if len(_EVENTS) >= _MAX_RECORDS:
            _DROPPED += 1
        else:
            _EVENTS.append(rec)


def record_span(name: str, t0_ns: int, t1_ns: Optional[int] = None,
                cat: str = "hop", **attrs) -> Optional[Span]:
    """Collect an ALREADY-FINISHED interval from explicit
    ``perf_counter_ns`` stamps — the distributed hop instrument: a stamp
    taken when a frame was packed on one side of a process boundary
    becomes a span when the frame is unpacked on the other (valid because
    ``perf_counter_ns`` is CLOCK_MONOTONIC, shared across processes on
    one box). No-op returning None when telemetry is off or the start
    stamp is unset (0 marks an unstamped frame from an unarmed peer)."""
    if not _ENABLED or not t0_ns:
        return None
    s = Span(name, cat, attrs)
    s.t0_ns = int(t0_ns)
    s.t1_ns = int(t1_ns if t1_ns is not None else time.perf_counter_ns())
    _collect_span(s)
    return s


def current_span() -> Optional[Span]:
    """The innermost open span on this thread/context, if any."""
    return _CURRENT.get()


def capture() -> Optional[Span]:
    """The current span, for handing to another thread (``attach``). None
    when telemetry is off or no span is open."""
    if not _ENABLED:
        return None
    return _CURRENT.get()


@contextlib.contextmanager
def attach(parent: Optional[Span]):
    """Re-enter ``parent`` as the current span in THIS thread's context —
    the explicit cross-thread propagation hop (threads do not inherit the
    spawning thread's contextvars)."""
    if parent is None:
        yield
        return
    token = _CURRENT.set(parent)
    try:
        yield
    finally:
        _CURRENT.reset(token)


def device_sync(values) -> None:
    """Block on a stage's device outputs — when ``FMRP_SYNC_STAGES=1`` —
    and record the sync point on the current span.

    Subsumes ``utils.timing.stage_sync`` (which now delegates here): JAX
    dispatch is async, so a stage that only ENQUEUES device work returns
    before it executes, and whichever later stage first blocks absorbs the
    wait (round-4's driver artifact charged Table 1 47 s of upstream panel
    work this way). Under ``FMRP_SYNC_STAGES=1`` the wait lands in the
    stage that OWNS the compute; with telemetry on, the measured wait is
    recorded as a ``device_sync`` event so the trace shows the charge."""
    synced = os.environ.get("FMRP_SYNC_STAGES", "0") == "1"
    if not synced:
        if _ENABLED:
            event("device_sync", cat="sync", synced=False)
        return
    if not _ENABLED:
        import jax

        jax.block_until_ready(values)
        return
    t0 = time.perf_counter_ns()
    import jax

    jax.block_until_ready(values)
    event(
        "device_sync",
        cat="sync",
        synced=True,
        wait_ms=round((time.perf_counter_ns() - t0) / 1e6, 3),
    )


class _TimedBox:
    __slots__ = ("s",)

    def __init__(self) -> None:
        self.s = 0.0


@contextlib.contextmanager
def timed(name: str = "timed", cat: str = "timer", **attrs):
    """Time a block: yields a box whose ``.s`` holds the elapsed seconds on
    exit, and records the block as a span when telemetry is armed. The
    one-stop replacement for the ``t0 = time.perf_counter(); ...`` pairs
    that used to be re-implemented per bench section."""
    box = _TimedBox()
    with span(name, cat=cat, **attrs):
        t0 = time.perf_counter()
        try:
            yield box
        finally:
            box.s = time.perf_counter() - t0


def finished_spans() -> List[Span]:
    """Snapshot of collected (closed) spans, in completion order."""
    with _LOCK:
        return list(_SPANS)


def standalone_events() -> List[dict]:
    """Snapshot of events recorded with no enclosing span."""
    with _LOCK:
        return list(_EVENTS)


def collector_stats() -> dict:
    with _LOCK:
        return {
            "spans": len(_SPANS),
            "events": len(_EVENTS),
            "dropped": _DROPPED,
        }


def reset() -> None:
    """Clear collected spans/events and restart the ID sequence (test
    isolation and export determinism)."""
    global _IDS, _DROPPED
    with _LOCK:
        _SPANS.clear()
        _EVENTS.clear()
        _DROPPED = 0
        _IDS = itertools.count(1)
