"""Trace/metrics exporters: JSONL event log, Chrome trace, Prometheus text.

Three consumers, three formats, one span store:

- :func:`write_jsonl` — the structured event log (``events.jsonl``): one
  JSON object per line, ``type`` in {``span``, ``event``, ``meta``,
  ``metrics``}, timestamps in epoch microseconds, deterministic key order
  and record order (sorted by start time then span id) so two exports of
  the same collector state are byte-identical — the diffable artifact the
  resilience differential test compares against the sqlite
  ``failure_log``.
- :func:`write_chrome_trace` — Chrome trace-event format (``trace.json``):
  ``X`` complete events for spans, ``i`` instants for events, ``M``
  metadata rows naming threads. Loads in Perfetto / ``chrome://tracing``;
  because span timestamps are epoch-anchored, a ``jax.profiler`` device
  trace of the same run lines up alongside the host spans on one
  timeline.
- :func:`prometheus_text` — the registry in Prometheus exposition format;
  :class:`ERService`'s metrics endpoint hook serves it.

:func:`export_all` writes both trace files into a directory (the
``FMRP_TRACE_DIR`` / ``--trace-dir`` sink). It rewrites whole files from
the collector on every call, so repeated flushes (end of ``run_pipeline``,
``ERService.close``, atexit) are idempotent and each one extends the
artifact with whatever ran since.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import List, Optional

from fm_returnprediction_tpu.telemetry import metrics as _metrics
from fm_returnprediction_tpu.telemetry import spans as _spans

__all__ = [
    "flat_metrics",
    "build_info",
    "span_record",
    "event_record",
    "program_record",
    "write_jsonl",
    "chrome_trace_events",
    "write_chrome_trace",
    "export_all",
    "prometheus_text",
    "serve_metrics_http",
    "JSONL_NAME",
    "CHROME_TRACE_NAME",
    "jsonl_name",
    "chrome_trace_name",
]

JSONL_NAME = "events.jsonl"
CHROME_TRACE_NAME = "trace.json"


def _proc_tag() -> str:
    from fm_returnprediction_tpu.telemetry import identity as _identity

    k = _identity.process_index()
    return "" if k is None else f".p{k}"


def jsonl_name() -> str:
    """``events.jsonl`` — or ``events.p{K}.jsonl`` under a multi-process
    identity, so N children sharing one ``FMRP_TRACE_DIR`` never
    overwrite each other's export (the timeline merge globs both)."""
    return f"events{_proc_tag()}.jsonl"


def chrome_trace_name() -> str:
    return f"trace{_proc_tag()}.json"


def _ts_us(t_ns: int) -> float:
    """perf_counter_ns → epoch microseconds (one anchor per process)."""
    return (t_ns + _spans.EPOCH_ANCHOR_NS) / 1e3


def flat_metrics() -> dict:
    """The registry snapshot as one flat ``name{k=v,...} → value`` dict —
    the shared shape of the JSONL ``metrics`` line and the flight
    recorder's ``metrics`` field. The whole flatten happens under
    ``metrics.SNAPSHOT_LOCK`` (shared with the fleet aggregator's fold)
    so a concurrent child delta can never render torn totals."""
    out = {}
    with _metrics.SNAPSHOT_LOCK:
        for name, series in _metrics.registry().collect().items():
            for key, value in sorted(series.items()):
                label = ",".join(f"{k}={v}" for k, v in key)
                out[f"{name}{{{label}}}" if label else name] = value
    return out


_BUILD_INFO: Optional[dict] = None


def build_info() -> dict:
    """Label set for the ``fmrp_build_info`` info-gauge: jax/jaxlib
    versions, backend, x64 flag, and a short sha of the package
    ``__init__.py`` (code salt) — enough to attribute a scrape from a
    mixed fleet to an exact environment. Computed once per process; the
    backend label is read from env so rendering a scrape never
    initializes a JAX backend."""
    global _BUILD_INFO
    if _BUILD_INFO is None:
        try:
            import jax

            jax_version = str(getattr(jax, "__version__", "unknown"))
            x64 = "1" if jax.config.jax_enable_x64 else "0"
        except Exception:  # pragma: no cover - jax always present in-repo
            jax_version = "unavailable"
            x64 = os.environ.get("JAX_ENABLE_X64", "0") or "0"
        try:
            import jaxlib

            jaxlib_version = str(getattr(jaxlib, "__version__", "unknown"))
        except Exception:  # pragma: no cover
            jaxlib_version = "unavailable"
        import hashlib

        try:
            pkg_init = Path(__file__).resolve().parents[1] / "__init__.py"
            salt = hashlib.sha256(pkg_init.read_bytes()).hexdigest()[:8]
        except OSError:  # pragma: no cover - package always readable
            salt = "unknown"
        _BUILD_INFO = {
            "jax": jax_version,
            "jaxlib": jaxlib_version,
            "backend": os.environ.get("JAX_PLATFORMS", "") or "default",
            "x64": x64,
            "code_salt": salt,
        }
    return _BUILD_INFO


def _clean(attrs: dict) -> dict:
    """JSON-safe attrs: anything non-primitive goes through repr."""
    out = {}
    for k, v in attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            out[str(k)] = v
        else:
            out[str(k)] = repr(v)[:200]
    return out


def span_record(s: "_spans.Span") -> dict:
    end_ns = s.t1_ns if s.t1_ns is not None else s.t0_ns
    return {
        "type": "span",
        "name": s.name,
        "cat": s.cat,
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "ts_us": round(_ts_us(s.t0_ns), 3),
        "dur_us": round((end_ns - s.t0_ns) / 1e3, 3),
        "thread_id": s.thread_id,
        "thread_name": s.thread_name,
        "attrs": _clean(s.attrs),
        "events": [
            {
                "name": name,
                "ts_us": round(_ts_us(t_ns), 3),
                "attrs": _clean(attrs),
            }
            for name, t_ns, attrs in s.events
        ],
    }


def event_record(e: dict) -> dict:
    return {
        "type": "event",
        "name": e["name"],
        "cat": e["cat"],
        "ts_us": round(_ts_us(e["t_ns"]), 3),
        "thread_id": e["thread_id"],
        "thread_name": e["thread_name"],
        "attrs": _clean(e["attrs"]),
    }


def program_record(r) -> dict:
    """One cost-ledger :class:`ProgramRecord` as a JSONL line (``type:
    "program"``): the per-compiled-program FLOP/byte/memory accounting
    beside the spans that paid for it."""
    out = r.to_json()
    out["type"] = "program"
    out["ts_us"] = round(_ts_us(r.t_ns), 3)
    del out["t_ns"]
    return out


def _ordered_records() -> List[dict]:
    """Every collected span/event as records, deterministically ordered
    (start time, then span id — ties cannot reorder across exports)."""
    spans = sorted(
        _spans.finished_spans(), key=lambda s: (s.t0_ns, s.span_id)
    )
    events = sorted(
        _spans.standalone_events(), key=lambda e: (e["t_ns"], e["name"])
    )
    return [span_record(s) for s in spans] + [event_record(e) for e in events]


def write_jsonl(path, include_metrics: bool = True) -> Path:
    """The structured event log: a ``meta`` header line, one line per
    span/standalone event, and (by default) a final ``metrics`` snapshot
    of the registry."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    stats = _spans.collector_stats()
    meta = {
        "type": "meta",
        "schema": 1,
        "pid": os.getpid(),
        "spans": stats["spans"],
        "events": stats["events"],
        "dropped": stats["dropped"],
        # this process's perf_counter→epoch anchor: the timeline merge
        # re-anchors every process's raw stamps onto ONE anchor with it
        "anchor_ns": _spans.EPOCH_ANCHOR_NS,
    }
    # per-process identity (multi-process runs): merged jsonl files stay
    # attributable; absent when unarmed, keeping exports byte-identical
    from fm_returnprediction_tpu.telemetry import identity as _identity

    if _identity.process_index() is not None:
        meta["process_index"] = _identity.process_index()
    lines = [json.dumps(meta, sort_keys=True)]
    lines += [json.dumps(r, sort_keys=True) for r in _ordered_records()]
    from fm_returnprediction_tpu.telemetry import perf as _perf

    lines += [
        json.dumps(program_record(r), sort_keys=True)
        for r in _perf.cost_ledger().records()
    ]
    if include_metrics:
        lines.append(
            json.dumps(
                {"type": "metrics", "values": flat_metrics()},
                sort_keys=True,
            )
        )
    tmp = path.with_name(f".{path.name}.tmp-{os.getpid()}")
    tmp.write_text("\n".join(lines) + "\n")
    os.replace(tmp, path)
    return path


def chrome_trace_events(pid: Optional[int] = None) -> List[dict]:
    """Chrome trace-event dicts for every collected span and event."""
    pid = os.getpid() if pid is None else pid
    from fm_returnprediction_tpu.telemetry import identity as _identity

    out: List[dict] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            # "[pK]" under a multi-process identity: N processes' traces
            # merged in Perfetto keep distinct, attributable rows
            "args": {"name": f"fmrp-host{_identity.process_suffix()}"},
        }
    ]
    threads = {}
    spans = sorted(
        _spans.finished_spans(), key=lambda s: (s.t0_ns, s.span_id)
    )
    for s in spans:
        threads.setdefault(s.thread_id, s.thread_name)
    for e in _spans.standalone_events():
        threads.setdefault(e["thread_id"], e["thread_name"])
    for tid, name in sorted(threads.items()):
        out.append(
            {
                "ph": "M",
                "name": "thread_name",
                "pid": pid,
                "tid": tid,
                "args": {"name": name},
            }
        )
    for s in spans:
        end_ns = s.t1_ns if s.t1_ns is not None else s.t0_ns
        out.append(
            {
                "ph": "X",
                "name": s.name,
                "cat": s.cat,
                "ts": round(_ts_us(s.t0_ns), 3),
                "dur": round((end_ns - s.t0_ns) / 1e3, 3),
                "pid": pid,
                "tid": s.thread_id,
                "args": {
                    "trace_id": s.trace_id,
                    "span_id": s.span_id,
                    "parent_id": s.parent_id,
                    **_clean(s.attrs),
                },
            }
        )
        for name, t_ns, attrs in s.events:
            out.append(
                {
                    "ph": "i",
                    "name": name,
                    "cat": "event",
                    "ts": round(_ts_us(t_ns), 3),
                    "pid": pid,
                    "tid": s.thread_id,
                    "s": "t",
                    "args": {"span_id": s.span_id, **_clean(attrs)},
                }
            )
    for e in sorted(
        _spans.standalone_events(), key=lambda e: (e["t_ns"], e["name"])
    ):
        out.append(
            {
                "ph": "i",
                "name": e["name"],
                "cat": e["cat"],
                "ts": round(_ts_us(e["t_ns"]), 3),
                "pid": pid,
                "tid": e["thread_id"],
                "s": "t",
                "args": _clean(e["attrs"]),
            }
        )
    out.extend(_program_trace_events(pid))
    return out


#: synthetic tid the compile rows live on — AOT compiles happen on real
#: threads, but a dedicated row keeps Perfetto's compile story scannable
_COMPILE_TID = 999_999


def _program_trace_events(pid: int) -> List[dict]:
    """Cost-ledger records as Chrome trace events: one ``X`` slice per
    compile (lowering+compile interval, on a dedicated "fmrp-compiles"
    row) plus ``C`` counter tracks for FLOPs and bytes-accessed so the
    per-program cost accounting rides the same timeline as the spans."""
    from fm_returnprediction_tpu.telemetry import perf as _perf

    records = _perf.cost_ledger().records()
    if not records:
        return []
    out: List[dict] = [
        {
            "ph": "M",
            "name": "thread_name",
            "pid": pid,
            "tid": _COMPILE_TID,
            "args": {"name": "fmrp-compiles"},
        }
    ]
    for r in records:
        dur_ns = int((r.lower_s + r.compile_s) * 1e9)
        out.append(
            {
                "ph": "X",
                "name": f"compile:{r.program}",
                "cat": "compile",
                "ts": round(_ts_us(r.t_ns - dur_ns), 3),
                "dur": round(dur_ns / 1e3, 3),
                "pid": pid,
                "tid": _COMPILE_TID,
                "args": {
                    k: v
                    for k, v in r.to_json().items()
                    if v is not None and k != "t_ns"
                },
            }
        )
        for counter, value in (
            ("flops", r.flops),
            ("bytes_accessed", r.bytes_accessed),
            ("temp_bytes", r.temp_bytes),
        ):
            if value is None:
                continue
            out.append(
                {
                    "ph": "C",
                    "name": f"program_{counter}",
                    "ts": round(_ts_us(r.t_ns), 3),
                    "pid": pid,
                    "tid": _COMPILE_TID,
                    "args": {r.program: value},
                }
            )
    return out


def write_chrome_trace(path) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    from fm_returnprediction_tpu.telemetry import identity as _identity

    doc = {
        "traceEvents": chrome_trace_events(),
        "displayTimeUnit": "ms",
        # Perfetto ignores otherData; the timeline merge reads it to
        # re-anchor this process's stamps onto the router's clock
        "otherData": {
            "anchor_ns": _spans.EPOCH_ANCHOR_NS,
            "pid": os.getpid(),
            "process_index": _identity.process_index(),
        },
    }
    tmp = path.with_name(f".{path.name}.tmp-{os.getpid()}")
    tmp.write_text(json.dumps(doc, sort_keys=True))
    os.replace(tmp, path)
    return path


def export_all(trace_dir) -> tuple:
    """Write ``events.jsonl`` + ``trace.json`` into ``trace_dir``; returns
    the two paths. Idempotent: whole-file rewrites from the collector."""
    trace_dir = Path(trace_dir)
    jsonl = write_jsonl(trace_dir / jsonl_name())
    chrome = write_chrome_trace(trace_dir / chrome_trace_name())
    return jsonl, chrome


def serve_metrics_http(render, port: int = 0, host: str = "127.0.0.1",
                       name: str = "fmrp-metrics"):
    """Serve ``render()`` (Prometheus text) over HTTP ``GET /metrics`` on
    a daemon thread — the ONE scrape-endpoint implementation behind
    ``ERService.start_metrics_server`` and the fleet's twin (two copies
    of an HTTP handler drift; content-type/path/shutdown fixes must land
    once). Returns the ``ThreadingHTTPServer``: ``.server_address`` is
    the bound ``(host, port)`` (``port=0`` picked a free one);
    ``.shutdown()`` + ``.server_close()`` stop it."""
    import http.server
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 - stdlib naming
            if self.path.rstrip("/") not in ("", "/metrics"):
                self.send_error(404)
                return
            body = render().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # quiet
            pass

    server = http.server.ThreadingHTTPServer((host, port), Handler)
    threading.Thread(
        target=server.serve_forever, name=name, daemon=True
    ).start()
    return server


def prometheus_text(extra: Optional[dict] = None,
                    extra_prefix: str = "") -> str:
    """The registry in Prometheus text format, followed by an
    ``fmrp_build_info`` info-gauge and optionally ``extra`` numeric
    gauges (an ``ERService`` renders its ``stats()`` dict through this —
    bools as 0/1, non-numerics skipped). The whole exposition renders
    under ``metrics.SNAPSHOT_LOCK`` so a scrape concurrent with a child
    delta ingest never shows torn fleet totals."""
    with _metrics.SNAPSHOT_LOCK:
        text = _metrics.registry().to_prometheus()
        lines = [text.rstrip("\n")] if text.strip() else []
        info = build_info()
        labels = ",".join(
            f'{k}="{_metrics.escape_label_value(v)}"'
            for k, v in sorted(info.items())
        )
        lines.append(
            "# HELP fmrp_build_info Build/environment identity"
            " (constant 1)."
        )
        lines.append("# TYPE fmrp_build_info gauge")
        lines.append(f"fmrp_build_info{{{labels}}} 1")
        if extra:
            for key in sorted(extra):
                value = extra[key]
                if isinstance(value, bool):
                    value = int(value)
                if not isinstance(value, (int, float)) or value != value:
                    continue  # skip None/lists/NaN
                name = _metrics.sanitize(f"{extra_prefix}{key}")
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {value}")
        return "\n".join(lines) + "\n"
