"""Typed metrics registry: counters, gauges, histograms — one per process.

Before this module the framework kept four ad-hoc counter stores: the
bucketed executor's ``hits``/``misses``/``compiles`` ints, the
microbatcher's ``_n_*`` ints plus latency rings, the guard layer's
process ``Counter`` of sentinel trips, and ``bench.py``'s private
``_jax_cache_stats`` probe. Each had its own read path and none were
exportable. This registry is the single process-wide store they all
register into; the existing ``stats()`` dict APIs stay bit-for-bit as thin
views over the same instruments.

Instrument model (small on purpose — three types, Prometheus-compatible):

- :class:`Counter` — monotonic float/int, ``inc(n)``;
- :class:`Gauge`   — settable value, or a callable sampled at collect time
  (``gauge_fn`` — e.g. the persistent XLA compile-cache size);
- :class:`Histogram` — fixed cumulative buckets + sum/count (latencies,
  batch occupancy).

Two ownership modes cover the codebase's two shapes:

- ``registry().counter(name, **labels)`` returns THE shared instrument for
  that (name, labels) — process-wide totals (retry attempts, guard
  sentinel trips, jit traces);
- ``registry().private_counter(name, **labels)`` returns a FRESH
  instrument aggregated under the same family — per-instance counters
  (one executor's cache hits) whose owner reads ``.value`` for its own
  ``stats()`` while the family export sums every live instance plus a
  retained base folded in when an instance is garbage-collected (a
  Prometheus counter must never go backwards because a retired executor
  was dropped).

``to_prometheus()`` renders the whole registry in Prometheus text
exposition format — the ``ERService`` metrics endpoint hook serves it.
:func:`jax_cache_stats` is the compile-cache probe promoted out of
``bench.py`` so the registry (and the bench) share one implementation.

Metrics are always on: an increment is one small lock — the counters here
replace plain-int bumps the hot paths already performed, and the
``obs_overhead`` bench section bounds the end-to-end cost. (Span
*tracing* is the part gated behind ``FMRP_TELEMETRY`` — see ``spans``.)
"""

from __future__ import annotations

import os
import re
import threading
import weakref
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "jax_cache_stats",
    "record_trace",
    "DEFAULT_LATENCY_BUCKETS",
    "SNAPSHOT_LOCK",
]

# The fleet-wide snapshot lock (re-entrant): every REGISTRY-WIDE read
# that will be rendered to a consumer — ``to_prometheus``, the exporters'
# ``flat_metrics``, the flight recorder — and every fold/ingest the
# distributed aggregator performs (``telemetry.distributed``) serializes
# here, so a scrape can never interleave with a child metric delta or a
# dead-replica fold and render torn fleet totals. It lives HERE (not in
# ``distributed``) so ``metrics``/``export`` need no import of the
# distributed plane; single increments never take it — only whole-registry
# snapshots and aggregator mutations do.
SNAPSHOT_LOCK = threading.RLock()

# seconds — tuned for host-side serving latencies (sub-ms to tens of s)
DEFAULT_LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic counter. ``value`` is an exact int when only ints were
    added — the serving ``stats()`` views rely on that."""

    __slots__ = ("_lock", "_cell", "__weakref__")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._cell = [0]  # one-element list: outlives the instance via the
        # registry's GC-fold finalizer closure

    def inc(self, n=1) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._cell[0] += n

    @property
    def value(self):
        return self._cell[0]


class Gauge:
    """Settable point-in-time value; ``fn`` variants are sampled lazily."""

    __slots__ = ("_lock", "_cell", "_fn", "__weakref__")

    def __init__(self, fn: Optional[Callable[[], float]] = None) -> None:
        self._lock = threading.Lock()
        self._cell = [0.0]
        self._fn = fn

    def set(self, v) -> None:
        with self._lock:
            self._cell[0] = v

    @property
    def value(self):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception:  # noqa: BLE001 — a broken probe reads as 0
                return 0.0
        return self._cell[0]


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: ``le`` upper
    bounds, plus ``sum`` and ``count``)."""

    __slots__ = ("_lock", "_cell", "bounds", "__weakref__")

    def __init__(self, bounds=DEFAULT_LATENCY_BUCKETS) -> None:
        self.bounds = tuple(float(b) for b in bounds)
        if list(self.bounds) != sorted(self.bounds):
            raise ValueError("histogram bounds must be sorted ascending")
        self._lock = threading.Lock()
        # counts per bucket (+inf last), then sum, then count
        self._cell = [[0] * (len(self.bounds) + 1), 0.0, 0]

    def observe(self, v) -> None:
        v = float(v)
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            self._cell[0][idx] += 1
            self._cell[1] += v
            self._cell[2] += 1

    @property
    def count(self) -> int:
        return self._cell[2]

    @property
    def sum(self) -> float:
        return self._cell[1]


def _zero_state(kind: str, bounds) -> object:
    if kind == "histogram":
        return [[0] * (len(bounds) + 1), 0.0, 0]
    return [0]


def _fold_state(kind: str, base, cell) -> None:
    """Fold a dead instrument's final cell into the series base (the cell
    outlives its instrument via the finalizer closure)."""
    if kind == "histogram":
        for i, c in enumerate(cell[0]):
            base[0][i] += c
        base[1] += cell[1]
        base[2] += cell[2]
    else:
        base[0] += cell[0]


class _Series:
    """One (family, labelset): retained base + live instruments."""

    __slots__ = ("labels", "base", "instruments", "shared")

    def __init__(self, labels: LabelKey, kind: str, bounds) -> None:
        self.labels = labels
        self.base = _zero_state(kind, bounds)
        self.instruments: List[weakref.ref] = []
        self.shared = None  # the singleton instrument for shared series


class _Family:
    __slots__ = ("name", "kind", "help", "bounds", "series")

    def __init__(self, name, kind, help_, bounds) -> None:
        self.name = name
        self.kind = kind
        self.help = help_
        self.bounds = bounds
        self.series: Dict[LabelKey, _Series] = {}


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize(name: str) -> str:
    """Coerce to a legal Prometheus metric/label name."""
    name = _NAME_RE.sub("_", name)
    if name and name[0].isdigit():
        name = "_" + name
    return name


def escape_label_value(value: object) -> str:
    """Escape a label VALUE per the Prometheus text exposition format:
    backslash, double-quote and newline must be escaped or a value like
    ``repr(exc)`` containing a quote splits the label set mid-line."""
    return (
        str(value)
        .replace("\\", r"\\")
        .replace('"', r"\"")
        .replace("\n", r"\n")
    )


def escape_help(text: str) -> str:
    """Escape a HELP string (backslash and newline only — quotes are
    legal there)."""
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


class MetricsRegistry:
    """Process-wide instrument store with families aggregated for export."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        # default derived gauges: the persistent XLA compile cache — the
        # artifact-side evidence for cross-process compile reuse, promoted
        # from bench.py's private probe
        self.gauge_fn(
            "fmrp_jax_compile_cache_entries",
            lambda: jax_cache_stats()["entries"],
            help="files in the persistent XLA compilation cache",
        )
        self.gauge_fn(
            "fmrp_jax_compile_cache_bytes",
            lambda: jax_cache_stats()["bytes"],
            help="bytes in the persistent XLA compilation cache",
        )

    # -- instrument creation ----------------------------------------------

    def _series(self, name, kind, help_, labels, bounds=None) -> _Series:
        key = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = _Family(name, kind, help_, bounds)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}"
                )
            series = fam.series.get(key)
            if series is None:
                series = fam.series[key] = _Series(key, kind, bounds or ())
            return series

    def _new_instrument(self, name, kind, help_, labels, bounds=None):
        series = self._series(name, kind, help_, labels, bounds)
        if kind == "counter":
            inst = Counter()
        elif kind == "gauge":
            inst = Gauge()
        else:
            inst = Histogram(bounds or DEFAULT_LATENCY_BUCKETS)
        with self._lock:
            series.instruments.append(weakref.ref(inst))
            # fold the final counts into the retained base when the owner
            # (a retired executor, a closed batcher) is collected — family
            # totals must never go backwards
            weakref.finalize(inst, _fold_state, kind, series.base, inst._cell)
        return inst, series

    def counter(self, name: str, help: str = "", **labels) -> Counter:
        """THE shared counter for (name, labels) — created once."""
        series = self._series(name, "counter", help, labels)
        with self._lock:
            if series.shared is None:
                series.shared = Counter()
                series.instruments.append(weakref.ref(series.shared))
            return series.shared

    def private_counter(self, name: str, help: str = "", **labels) -> Counter:
        """A fresh counter aggregated under the (name, labels) family —
        per-instance ownership, family-level export."""
        inst, _ = self._new_instrument(name, "counter", help, labels)
        return inst

    def gauge(self, name: str, help: str = "", **labels) -> Gauge:
        series = self._series(name, "gauge", help, labels)
        with self._lock:
            if series.shared is None:
                series.shared = Gauge()
                series.instruments.append(weakref.ref(series.shared))
            return series.shared

    def gauge_fn(self, name: str, fn: Callable[[], float],
                 help: str = "", **labels) -> None:
        """Register a derived gauge sampled at collect time."""
        series = self._series(name, "gauge", help, labels)
        g = Gauge(fn=fn)
        with self._lock:
            series.shared = g
            series.instruments.append(weakref.ref(g))

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_LATENCY_BUCKETS, **labels) -> Histogram:
        series = self._series(name, "histogram", help, labels,
                              bounds=tuple(buckets))
        with self._lock:
            if series.shared is None:
                series.shared = Histogram(tuple(buckets))
                series.instruments.append(weakref.ref(series.shared))
            return series.shared

    def private_histogram(self, name: str, help: str = "",
                          buckets=DEFAULT_LATENCY_BUCKETS,
                          **labels) -> Histogram:
        inst, _ = self._new_instrument(
            name, "histogram", help, labels, bounds=tuple(buckets)
        )
        return inst

    # -- collection --------------------------------------------------------

    def _live_instruments(self, series: _Series) -> list:
        """Strong refs to the series' live instruments, PRUNING dead
        weakrefs in place (their counts already folded into the base by
        the finalizer) — a long-lived process creating instruments per
        swap/ingest must not grow every collect() linearly forever."""
        with self._lock:
            live = [(r, r()) for r in series.instruments]
            if any(inst is None for _, inst in live):
                series.instruments[:] = [r for r, inst in live if inst is not None]
        return [inst for _, inst in live if inst is not None]

    def _series_value(self, fam: _Family, series: _Series):
        instruments = self._live_instruments(series)
        if fam.kind == "histogram":
            bounds = fam.bounds or DEFAULT_LATENCY_BUCKETS
            total = _zero_state("histogram", bounds)
            _fold_state("histogram", total, series.base)
            for inst in instruments:
                # fold under the instrument's own lock: a concurrent
                # observe() mutates bucket/sum/count non-atomically, and a
                # torn read would render count ≠ Σ buckets
                with inst._lock:
                    _fold_state("histogram", total, inst._cell)
            return {
                "buckets": list(total[0]),
                "sum": total[1],
                "count": total[2],
            }
        if fam.kind == "gauge":
            # gauges do not sum dead bases; sample the live instruments
            vals = [inst.value for inst in instruments]
            return vals[-1] if vals else 0.0
        total = series.base[0]
        for inst in instruments:
            total += inst._cell[0]
        return total

    def collect(self) -> Dict[str, Dict[LabelKey, object]]:
        """name → {labelkey → value} for every family.

        Held under :data:`SNAPSHOT_LOCK` for the whole walk: the
        distributed aggregator's ingest/fold mutations serialize on the
        same lock, so one collect is one consistent cut of the fleet."""
        with SNAPSHOT_LOCK:
            return self._collect_locked()

    def _collect_locked(self) -> Dict[str, Dict[LabelKey, object]]:
        with self._lock:
            fams = {
                name: (fam, list(fam.series.items()))
                for name, fam in self._families.items()
            }
        out: Dict[str, Dict[LabelKey, object]] = {}
        for name, (fam, series_items) in fams.items():
            out[name] = {
                key: self._series_value(fam, series)
                for key, series in series_items
            }
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format for every family.

        With a per-process identity armed (``telemetry.identity`` — the
        distributed bootstrap or a fleet replica child), every series
        additionally carries ``process_index="k"`` so scrapes merged
        from N processes stay attributable; unarmed output is
        byte-identical to the historical single-process export."""
        from fm_returnprediction_tpu.telemetry import identity as _identity

        proc_idx = _identity.process_index()
        lines: List[str] = []
        collected = self.collect()
        with self._lock:
            metas = {
                name: (fam.kind, fam.help, fam.bounds)
                for name, fam in self._families.items()
            }
        for name in sorted(collected):
            kind, help_, bounds = metas[name]
            pname = sanitize(name)
            if help_:
                lines.append(f"# HELP {pname} {escape_help(help_)}")
            lines.append(f"# TYPE {pname} {kind}")
            for key in sorted(collected[name]):
                value = collected[name][key]
                if proc_idx is not None and not any(
                    k == "process_index" for k, _ in key
                ):
                    key = tuple(sorted(
                        (*key, ("process_index", str(proc_idx)))
                    ))
                label_str = ",".join(
                    f'{sanitize(k)}="{escape_label_value(v)}"' for k, v in key
                )
                if kind == "histogram":
                    bnds = list(bounds or DEFAULT_LATENCY_BUCKETS)
                    cum = 0
                    for b, c in zip([*bnds, float("inf")], value["buckets"]):
                        cum += c
                        le = "+Inf" if b == float("inf") else repr(b)
                        extra = f'le="{le}"'
                        ls = f"{label_str},{extra}" if label_str else extra
                        lines.append(f"{pname}_bucket{{{ls}}} {cum}")
                    suffix = f"{{{label_str}}}" if label_str else ""
                    lines.append(f"{pname}_sum{suffix} {value['sum']}")
                    lines.append(f"{pname}_count{suffix} {value['count']}")
                else:
                    suffix = f"{{{label_str}}}" if label_str else ""
                    lines.append(f"{pname}{suffix} {value}")
        return "\n".join(lines) + "\n"


_REGISTRY: Optional[MetricsRegistry] = None
_REGISTRY_LOCK = threading.Lock()


def registry() -> MetricsRegistry:
    """The process-wide registry (created on first use)."""
    global _REGISTRY
    if _REGISTRY is None:
        with _REGISTRY_LOCK:
            if _REGISTRY is None:
                _REGISTRY = MetricsRegistry()
    return _REGISTRY


def jax_cache_stats(cache_dir=None) -> dict:
    """Entry count + bytes of the persistent XLA compilation cache —
    the artifact-side evidence for whether compiled programs survive
    across processes/rounds. Promoted from ``bench.py`` (which now
    imports it) so the registry's derived gauges and the bench artifact
    read one implementation. Resolution mirrors
    ``settings.enable_compilation_cache``: ``JAX_CACHE_DIR`` else
    ``BASE_DIR/_cache/jax``."""
    if cache_dir is None:
        cache_dir = os.environ.get("JAX_CACHE_DIR")
        if cache_dir is None:
            from fm_returnprediction_tpu.settings import config

            cache_dir = os.path.join(str(config("BASE_DIR")), "_cache", "jax")
    try:
        # filter to files ONCE and use that list for BOTH the count and
        # the byte sum — counting directories (or a transient non-file)
        # in `entries` but not `bytes` made "entries grew, bytes didn't"
        # read as zero-size cache entries and muddied the cross-process
        # compile-reuse evidence
        files = [
            p for p in (os.path.join(cache_dir, f)
                        for f in os.listdir(cache_dir))
            if os.path.isfile(p)
        ]
        return {"entries": len(files),
                "bytes": sum(os.path.getsize(p) for p in files)}
    except OSError:
        return {"entries": 0, "bytes": 0}


def record_trace(program: str) -> None:
    """Compile-event hook: the hot-path modules call this at their
    trace-time side-effect sites (``ops.ols.TRACES``,
    ``specgrid.solve.PROGRAM_TRACES``), so every jit trace lands in the
    registry (``fmrp_jit_traces_total{program=...}``) and — when tracing
    is armed — on the current span's timeline as a ``jit_trace`` event
    (a compile is exactly the kind of wall-clock spike a trace viewer
    must be able to attribute)."""
    registry().counter(
        "fmrp_jit_traces_total",
        help="jit traces (≈ compiles per shape signature) by program",
        program=program,
    ).inc()
    from fm_returnprediction_tpu.telemetry import spans

    spans.event("jit_trace", cat="compile", program=program)
