"""Unified telemetry: structured spans, a metrics registry, trace export.

The observability layer (L-obs) the rest of the stack instruments into:

- :mod:`.spans`   — nested, context-propagated host spans with explicit
  cross-thread hand-off (``capture``/``attach``), point events, and the
  ``device_sync`` barrier that subsumes ``utils.timing.stage_sync``.
  ``StageTimer`` is now a thin view over these spans; ``run_pipeline``
  stages, task-graph tasks (including watchdogged worker threads), retry
  attempts, and serving request→microbatch→bucket dispatch all emit them.
- :mod:`.metrics` — typed counters/gauges/histograms in one process-wide
  registry. The serving batcher/executor counters, the retry policy, the
  guard sentinels, jit-trace counts and the persistent XLA compile-cache
  probe (promoted from ``bench.py``) all register here; the pre-existing
  ``stats()`` dict APIs read the same instruments.
- :mod:`.export`  — a JSONL structured event log and a Chrome trace file
  (Perfetto-loadable, epoch-anchored so ``jax.profiler`` device traces
  line up beside the host spans), plus Prometheus text format for the
  ``ERService`` metrics endpoint hook.
- :mod:`.perf`    — the performance plane on top: a program COST LEDGER
  (``cost_analysis``/``memory_analysis`` + compile wall time for every
  AOT program in the serving/specgrid paths, exported as ``program``
  JSONL records, Chrome counter tracks and ``fmrp_program_*`` metric
  families), ``jax.profiler`` capture hooks (``run_pipeline
  (profile_dir=)`` / ``--profile-dir`` / ``ERService.capture_profile``),
  the ``flight.json`` crash-time flight recorder, and the warm-run
  recompile sentinel.
- :mod:`.slo`     — declarative ``SLO`` objectives + a sliding-window
  burn-rate monitor over the serving metrics (state in ``stats()`` and
  ``/metrics``).
- :mod:`.regress` — the perf-regression sentinel over the bench history
  (``python -m fm_returnprediction_tpu.telemetry.regress BENCH_*.json``).

Discipline (same stance as the guard layer's static flag): telemetry off —
the default — is near-zero overhead (one global read per instrumented
site) and changes nothing: jaxprs are byte-identical either way because
spans are host-side only, and pipeline artifacts are bit-identical
(pinned by ``tests/test_telemetry.py``). On, the cost is measured and
bounded <5% by ``bench.py``'s ``obs_overhead`` section.

Knobs: ``FMRP_TELEMETRY=1`` arms span collection; ``FMRP_TRACE_DIR=<dir>``
(or ``run_pipeline(trace_dir=...)`` / ``--trace-dir``) arms it AND exports
``events.jsonl`` + ``trace.json`` there on flush/exit.
"""

from __future__ import annotations

import atexit
import contextlib
from typing import Optional

from fm_returnprediction_tpu.telemetry.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    jax_cache_stats,
    record_trace,
    registry,
)
from fm_returnprediction_tpu.telemetry.perf import (
    CostLedger,
    ProgramRecord,
    cost_ledger,
    dump_flight,
    peak_flops_estimate,
    profiling,
    recompile_watch,
    record_compiled,
    record_runtime,
    timed_aot_compile,
)
from fm_returnprediction_tpu.telemetry.slo import (
    SLO,
    SloMonitor,
    slos_from_env,
)
from fm_returnprediction_tpu.telemetry.spans import (
    Span,
    active,
    attach,
    capture,
    collector_stats,
    current_span,
    device_sync,
    enabled,
    event,
    finished_spans,
    reset,
    set_enabled,
    set_trace_dir,
    span,
    standalone_events,
    timed,
    trace_dir,
)

__all__ = [
    "CostLedger",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "ProgramRecord",
    "SLO",
    "SloMonitor",
    "Span",
    "cost_ledger",
    "dump_flight",
    "peak_flops_estimate",
    "profiling",
    "recompile_watch",
    "record_compiled",
    "record_runtime",
    "slos_from_env",
    "timed_aot_compile",
    "active",
    "attach",
    "capture",
    "collector_stats",
    "current_span",
    "device_sync",
    "enabled",
    "event",
    "finished_spans",
    "flush",
    "jax_cache_stats",
    "prometheus_text",
    "record_trace",
    "registry",
    "reset",
    "set_enabled",
    "set_trace_dir",
    "span",
    "standalone_events",
    "timed",
    "trace_dir",
    "tracing",
]


def prometheus_text(extra=None, extra_prefix: str = "") -> str:
    """Registry (+ optional extra gauges) in Prometheus text format."""
    from fm_returnprediction_tpu.telemetry import export

    return export.prometheus_text(extra=extra, extra_prefix=extra_prefix)


def flush() -> Optional[tuple]:
    """Export the collector to the configured trace dir (``events.jsonl``
    + ``trace.json``); no-op returning None when no dir is armed. Safe to
    call repeatedly — whole-file rewrites, each flush extends the artifact
    with whatever ran since the last one."""
    directory = trace_dir()
    if directory is None:
        return None
    from fm_returnprediction_tpu.telemetry import export

    return export.export_all(directory)


@contextlib.contextmanager
def tracing(directory=None):
    """Arm telemetry for a block and flush exports on exit.

    ``directory`` (or, when None, the ambient ``FMRP_TRACE_DIR``) becomes
    the export sink. With neither set and telemetry not otherwise enabled
    this is a pure pass-through — ``run_pipeline`` wraps its whole body in
    it unconditionally."""
    prev_dir = trace_dir()
    directory = directory or prev_dir
    if directory is None and not active():
        yield
        return
    if directory is not None:
        set_trace_dir(directory)
    with enabled(True):
        try:
            yield
        finally:
            flush()
            # restore, don't leak: one traced run must not leave tracing
            # armed (and its export dir targeted) for every later run in
            # the process
            set_trace_dir(prev_dir)


@atexit.register
def _flush_at_exit() -> None:  # pragma: no cover - interpreter shutdown
    try:
        flush()
    except Exception:  # noqa: BLE001 — never fail shutdown over telemetry
        pass
