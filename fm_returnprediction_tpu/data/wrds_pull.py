"""WRDS data acquisition: CRSP stock/index, Compustat, CCM link table.

Re-provides the reference's pullers (``src/pull_crsp.py``,
``src/pull_compustat.py``) — same SQL against the CIZ-format tables, same
universe filters, same cache-file contract (existing reference caches drop
in unchanged) — with the reference's known defects fixed (SURVEY §2.2):

- #4: the cache-by-filters path used an undefined variable → works here;
- #5: a gvkey filter interpolated the VALUE where the column name belongs
  → ``gvkey IN (...)`` here;
- #6: the index cache name had a missing f-prefix (literal ``{table}``)
  → interpolated here;
- #7: cache hits returned the UNFILTERED frame while fresh pulls returned
  the filtered universe → both paths return the filtered universe here
  (the cache still stores the raw pull, so caches stay reusable).

The ``wrds`` package (and network access) is optional: import is deferred to
call time, so the whole framework works offline against caches or the
synthetic backend.
"""

from __future__ import annotations

from datetime import datetime
from pathlib import Path
from typing import List, Optional, Union

import numpy as np
import pandas as pd
from pandas.tseries.offsets import MonthEnd

from fm_returnprediction_tpu.utils.cache import (
    cache_filename,
    file_cached,
    flatten_dict_to_str,
    hash_cache_filename,
    read_cached_data,
    save_cache_data,
)

__all__ = [
    "pull_CRSP_stock",
    "pull_CRSP_index",
    "pull_Compustat",
    "pull_CRSP_Comp_link_table",
    "subset_to_common_stock_and_exchanges",
    "UNIVERSE_FLAGS",
    "build_crsp_stock_sql",
    "build_compustat_sql",
    "build_link_table_sql",
]

COMPUSTAT_DEFAULT_VARS = (
    "gvkey, datadate, fyear, sale AS sales, ni AS earnings, at AS assets, "
    "(act - che) - lct - dp AS accruals, "
    "act - che AS non_cash_current_assets,"
    "lct,"
    "dltt + dlc AS total_debt,"
    "dp AS depreciation, "
    "dvpd, dvc, dvt, pstk, pstkl, pstkrv, txditc, seq"
)


def _normalize_dates(start_date, end_date) -> tuple[str, str]:
    if start_date is None:
        start_date = "1959-01-01"
    elif isinstance(start_date, (pd.Timestamp, datetime)):
        start_date = start_date.strftime("%Y-%m-%d")
    if end_date is None:
        end_date = pd.Timestamp.now().strftime("%Y-%m-%d")
    elif isinstance(end_date, (pd.Timestamp, datetime)):
        end_date = end_date.strftime("%Y-%m-%d")
    return start_date, end_date


def _sql_list(values: Union[str, List[str]]) -> str:
    values = (values,) if isinstance(values, str) else tuple(values)
    return "(" + ", ".join(f"'{v}'" for v in values) + ")"


# The CIZ share-class flag columns the universe filter reads — single
# source of truth for the filter itself, the pipeline's pruned daily read,
# and the tests.
FLAG_COLUMNS = [
    "sharetype", "securitytype", "securitysubtype", "usincflg",
    "issuertype", "primaryexch", "conditionaltype", "tradingstatusflg",
]

# The admitted values per flag column — the ONE definition of the US
# common-stock NYSE/AMEX/NASDAQ universe, consumed by the pandas filter
# below AND by the columnar ingest route (``data.columnar``), so the two
# routes cannot drift.
UNIVERSE_FLAGS = {
    "conditionaltype": ("RW",),
    "tradingstatusflg": ("A",),
    "sharetype": ("NS",),
    "securitytype": ("EQTY",),
    "securitysubtype": ("COM",),
    "usincflg": ("Y",),
    "issuertype": ("ACOR", "CORP"),
    "primaryexch": ("N", "A", "Q"),
}


def subset_to_common_stock_and_exchanges(
    crsp: pd.DataFrame, columns: Optional[List[str]] = None
) -> pd.DataFrame:
    """US common-stock universe on NYSE/AMEX/NASDAQ (CIZ flags).

    sharetype NS ∧ securitytype EQTY ∧ securitysubtype COM ∧ usincflg Y ∧
    issuertype ∈ {ACOR, CORP} ∧ conditionaltype RW ∧ tradingstatusflg A ∧
    primaryexch ∈ {N, A, Q} (reference ``src/pull_crsp.py:255-295``; with the
    CIZ format delisting returns are already applied upstream).

    ``columns`` limits the RESULT to the named columns: at full CRSP daily
    scale the row-filter copy of a 16-column frame is ~80 s of pure memcpy
    while the 3 columns the daily stage consumes copy in seconds — callers
    that know their downstream needs should say so.
    """

    def flag_in(name, values):
        col = crsp[name]
        if isinstance(col.dtype, pd.CategoricalDtype):
            # compare int8 category codes, not 70M string/categorical rows
            # (~4x cheaper on the full-scale daily frame)
            wanted = [
                col.cat.categories.get_loc(v)
                for v in values
                if v in col.cat.categories
            ]
            code = col.cat.codes.to_numpy()
            keep = np.zeros(len(col), dtype=bool)
            for w in wanted:
                keep |= code == w
            return keep
        return col.isin(values).to_numpy()

    keep = None
    for name, values in UNIVERSE_FLAGS.items():
        m = flag_in(name, list(values))
        keep = m if keep is None else keep & m
    out = crsp if columns is None else crsp[columns]
    return out[keep]


def build_crsp_stock_sql(
    freq: str,
    start_date: str,
    end_date: str,
    filter_by: Optional[str] = None,
    filter_value=None,
) -> str:
    """The CIZ stock query (reference ``src/pull_crsp.py:217-235``)."""
    if freq.upper() == "M":
        table, date_col = "msf_v2", "mthcaldt"
        tot_ret, prc_ret, prc = "mthret", "mthretx", "mthprc"
        # mthvol (CIZ share volume) feeds the opt-in Turnover_{-1,-12}
        # characteristic — a column the reference never pulls because it
        # never computes turnover (SURVEY §6 note).
        extra = "mthvol AS vol,"
    elif freq.upper() == "D":
        table, date_col = "dsf_v2", "dlycaldt"
        tot_ret, prc_ret, prc = "dlyret", "dlyretx", "dlyprc"
        extra = ""
    else:
        raise ValueError("freq must be either 'D' or 'M'.")
    sql = f"""
        SELECT
            permno, permco, {date_col},
            issuertype, securitytype, securitysubtype, sharetype,
            usincflg,
            primaryexch, conditionaltype, tradingstatusflg,
            {tot_ret} AS totret,
            {prc_ret} AS retx,
            {prc} AS prc,
            {extra}
            shrout
        FROM crsp.{table}
        WHERE {date_col} >= '{start_date}'
          AND {date_col} <= '{end_date}'
    """
    if filter_by is not None and filter_value is not None:
        sql += f" AND {filter_by} IN {_sql_list(filter_value)}"
    return sql


def build_compustat_sql(
    vars_str: str, start_date: str, end_date: str, gvkey=None
) -> str:
    """Annual fundamentals with derived columns in SQL and the standard
    INDL/STD/D/C filters (reference ``src/pull_compustat.py:207-223``;
    defect #5 fixed: the filter names the COLUMN, not the value)."""
    sql = f"""
        SELECT
            {vars_str}
        FROM
            comp.funda
        WHERE
            indfmt='INDL' AND
            datafmt='STD' AND
            popsrc='D' AND
            consol='C' AND
            datadate >= '{start_date}' AND
            datadate <= '{end_date}'
        """
    if gvkey is not None:
        sql += f" AND gvkey IN {_sql_list(gvkey)}"
    return sql


def build_link_table_sql(gvkey=None) -> str:
    """CCM link table restricted to L*-type primary links
    (reference ``src/pull_compustat.py:312-321``)."""
    sql = """
        SELECT
            gvkey, lpermno AS permno, linktype, linkprim, linkdt, linkenddt
        FROM
            crsp.ccmxpf_linktable
        WHERE
            substr(linktype,1,1)='L'
            AND (linkprim ='C' OR linkprim='P')
            AND linktype NOT IN ('LX', 'LD', 'LN')
    """
    if gvkey is not None:
        sql += f" AND gvkey IN {_sql_list(gvkey)}"
    return sql


def _resolve_cache(
    code: str,
    filters: dict,
    data_dir,
    file_name: Optional[str],
    hash_file_name: bool,
):
    """Shared cache-path resolution (defect #4 fixed: the derived-name path
    uses the filter string it just built)."""
    if file_name is None:
        filter_str = flatten_dict_to_str(filters)
        namer = hash_cache_filename if hash_file_name else cache_filename
        cache_paths = namer(code, filter_str, data_dir)
        return cache_paths, file_cached(cache_paths), None
    if not any(file_name.endswith(f".{ext}") for ext in ("parquet", "csv", "zip")):
        cache_paths = [Path(data_dir) / f"{file_name}.{ext}" for ext in ("parquet", "csv", "zip")]
        return cache_paths, file_cached(cache_paths), file_name
    path = Path(data_dir, file_name)
    return None, (path if path.exists() else None), file_name


def _wrds_query(
    sql: str,
    wrds_username: str,
    date_cols: List[str],
    retries: int = 3,
    backoff_s: float = 5.0,
) -> pd.DataFrame:
    """Run one WRDS query under the shared retry policy.

    The WRDS Postgres connection is the pipeline's only network boundary
    (``src/pull_crsp.py:238``); the reference has no failure handling there
    at all — a transient drop loses a multi-minute pull. Each attempt opens
    a fresh connection; failures back off exponentially with deterministic
    jitter (``resilience.retry``). The allowlist is every ``Exception`` —
    the wrds client wraps transport errors in assorted library types, and
    the only non-retryable failures here (bad SQL, bad credentials) exhaust
    the budget in seconds against a healthy server.

    Fault site ``wrds.query`` fires before each connection attempt, so the
    chaos suite drives this exact loop without network access."""
    import wrds  # deferred: optional dependency, needs network

    from fm_returnprediction_tpu.resilience.faults import fault_site
    from fm_returnprediction_tpu.resilience.retry import (
        RetryPolicy,
        call_with_retry,
    )

    def attempt() -> pd.DataFrame:
        fault_site("wrds.query")
        db = None
        try:
            db = wrds.Connection(wrds_username=wrds_username)
            return db.raw_sql(sql, date_cols=date_cols)
        finally:
            if db is not None:
                db.close()

    return call_with_retry(
        attempt,
        RetryPolicy(
            max_attempts=retries + 1,
            backoff_s=backoff_s,
            retry_on=(Exception,),
        ),
        label="WRDS query",
        on_retry=lambda n, err: print(f"WRDS retry {n}/{retries} after: {err}"),
    )


def pull_CRSP_stock(
    wrds_username: str = "",
    start_date=None,
    end_date=None,
    freq: str = "D",
    filter_by: Optional[str] = None,
    filter_value=None,
    data_dir=None,
    file_name: Optional[str] = None,
    hash_file_name: bool = False,
    file_type: Optional[str] = None,
) -> pd.DataFrame:
    """CRSP stock data (CIZ), cached, returned as the FILTERED common-stock
    universe on both cache hits and fresh pulls (defect #7 fixed)."""
    start_date, end_date = _normalize_dates(start_date, end_date)
    freq_u = freq.upper()
    table = "msf_v2" if freq_u == "M" else "dsf_v2"
    date_col = "mthcaldt" if freq_u == "M" else "dlycaldt"

    filters = {"start_date": start_date, "end_date": end_date}
    if filter_by is not None and filter_value is not None:
        filters[filter_by] = filter_value
    cache_paths, cached_fp, file_name = _resolve_cache(
        f"crsp_{table}", filters, data_dir, file_name, hash_file_name
    )
    if cached_fp:
        return subset_to_common_stock_and_exchanges(read_cached_data(cached_fp))

    sql = build_crsp_stock_sql(freq, start_date, end_date, filter_by, filter_value)
    crsp = _wrds_query(sql, wrds_username, date_cols=[date_col])
    crsp[["permno", "permco"]] = crsp[["permno", "permco"]].astype(int, errors="ignore")
    crsp["jdate"] = crsp[date_col] + MonthEnd(0)
    save_cache_data(crsp, data_dir, cache_paths, file_name, file_type)
    return subset_to_common_stock_and_exchanges(crsp)


def pull_CRSP_index(
    wrds_username: str = "",
    start_date=None,
    end_date=None,
    freq: str = "D",
    data_dir=None,
    file_name: Optional[str] = None,
    hash_file_name: bool = False,
    file_type: Optional[str] = None,
) -> pd.DataFrame:
    """CRSP cap-based index files (msix/dsix), cached (defect #6 fixed:
    the cache code interpolates the table name)."""
    start_date, end_date = _normalize_dates(start_date, end_date)
    table = "msix" if freq.upper() == "M" else "dsix"
    filters = {"start_date": start_date, "end_date": end_date, "freq": freq}
    cache_paths, cached_fp, file_name = _resolve_cache(
        f"crsp_a_index_{table}", filters, data_dir, file_name, hash_file_name
    )
    if cached_fp:
        return read_cached_data(cached_fp)

    sql = f"""
        SELECT *
        FROM crsp_a_indexes.{table}
        WHERE caldt BETWEEN '{start_date}' AND '{end_date}'
    """
    df = _wrds_query(sql, wrds_username, date_cols=["caldt"])
    save_cache_data(df, data_dir, cache_paths, file_name, file_type)
    return df


def pull_Compustat(
    wrds_username: str = "",
    gvkey=None,
    vars_str=None,
    start_date=None,
    end_date=None,
    data_dir=None,
    file_name: Optional[str] = None,
    hash_file_name: bool = False,
    file_type: Optional[str] = None,
) -> pd.DataFrame:
    """Annual Compustat fundamentals with derived columns, cached."""
    start_date, end_date = _normalize_dates(start_date, end_date)
    if vars_str is not None and not isinstance(vars_str, str):
        vars_str = ", ".join(vars_str)
    vars_str = vars_str or COMPUSTAT_DEFAULT_VARS

    filters = {"vars_str": vars_str, "start_date": start_date, "end_date": end_date}
    if gvkey is not None:
        filters["gvkey"] = gvkey
    cache_paths, cached_fp, file_name = _resolve_cache(
        "comp_funda", filters, data_dir, file_name, hash_file_name
    )
    if cached_fp:
        return read_cached_data(cached_fp)

    sql = build_compustat_sql(vars_str, start_date, end_date, gvkey)
    comp = _wrds_query(sql, wrds_username, date_cols=["datadate"])
    save_cache_data(comp, data_dir, cache_paths, file_name, file_type)
    return comp


def pull_CRSP_Comp_link_table(
    wrds_username: str = "",
    gvkey=None,
    data_dir=None,
    file_name: Optional[str] = None,
    hash_file_name: bool = False,
    file_type: Optional[str] = None,
) -> pd.DataFrame:
    """CCM link table, cached."""
    filters = {"gvkey": gvkey} if gvkey is not None else {}
    cache_paths, cached_fp, file_name = _resolve_cache(
        "crsp_comp_link_table", filters, data_dir, file_name, hash_file_name
    )
    if cached_fp:
        return read_cached_data(cached_fp)

    sql = build_link_table_sql(gvkey)
    ccm = _wrds_query(sql, wrds_username, date_cols=["linkdt", "linkenddt"])
    save_cache_data(ccm, data_dir, cache_paths, file_name, file_type)
    return ccm
