"""Vectorized synthetic universe at real-CRSP scale for benchmarking.

``data.synthetic`` builds its fixtures row by row in Python — right for
hermetic tests, hopeless at the reference's real data volume (1964-2013:
~25k permnos, ~77M firm-day rows, SURVEY §3.5). This module generates the
same five cached datasets with pure numpy column construction (repeat /
cumsum-offset arithmetic, categorical codes for the flag columns), so a
full-scale universe materializes in tens of seconds and the END-TO-END
pipeline can be benchmarked at the shape the north-star budget describes
(round-2 VERDICT item 3) instead of a toy firm count.

Statistical content is minimal-but-coherent: firms have contiguous
lifetimes, daily returns load on a market factor (betas are recoverable),
monthly/fundamental/link tables share the firm vocabulary so every join in
the pipeline exercises at scale. It is NOT a parity fixture — the published
Table 1 oracle and the hermetic tests use ``data.synthetic``.

``write_benchscale_cache`` persists under the pipeline's canonical file
names next to a parameter marker, so repeated bench runs reuse the files.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict

import numpy as np
import pandas as pd
from pandas.tseries.offsets import MonthEnd

from fm_returnprediction_tpu.data.synthetic import FILE_NAMES as _FILE_NAMES

__all__ = ["flat_ranges", "generate_benchscale_wrds", "write_benchscale_cache"]


def flat_ranges(starts: np.ndarray, counts: np.ndarray) -> tuple:
    """Concatenated [starts[i], starts[i]+counts[i]) ranges without a Python
    loop: global arange minus each row's group offset. Returns
    ``(positions, within)`` — the flattened range values and each element's
    offset within its own group."""
    offsets = np.zeros(len(counts) + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    within = np.arange(offsets[-1], dtype=np.int64) - np.repeat(offsets[:-1], counts)
    return np.repeat(starts.astype(np.int64), counts) + within, within


def _flag_frame(n_rows: int, codes: Dict[str, tuple], rep_codes: Dict[str, np.ndarray]):
    """Share-class flag columns as categoricals (1 byte/row instead of an
    object pointer — at 77M rows this is the difference between 600 MB and
    6 GB of frame)."""
    out = {}
    for name, values in codes.items():
        c = rep_codes.get(name)
        if c is None:
            c = np.zeros(n_rows, dtype=np.int8)
        out[name] = pd.Categorical.from_codes(c, categories=list(values))
    return out


def generate_benchscale_wrds(
    n_permnos: int = 22000,
    n_months: int = 600,
    seed: int = 20140131,
    start: str = "1964-01-31",
    frac_nyse: float = 0.35,
    frac_noncommon: float = 0.08,
) -> Dict[str, pd.DataFrame]:
    rng = np.random.default_rng(seed)
    months = pd.date_range(start, periods=n_months, freq="ME")
    days = pd.bdate_range(months[0] - MonthEnd(1) + pd.Timedelta(days=1), months[-1])
    d_total = len(days)
    day_me = days + MonthEnd(0)
    day_month = np.searchsorted(months.values, day_me.values)
    month_day_lo = np.searchsorted(day_month, np.arange(n_months), side="left")
    month_day_hi = np.searchsorted(day_month, np.arange(n_months), side="right")

    mkt = rng.normal(3e-4, 0.008, d_total)

    # --- firm vocabulary and lifetimes (contiguous month spans) ----------
    permnos = (10000 + np.arange(n_permnos) * 2).astype(np.int64)
    min_life = min(24, max(n_months // 2, 1))
    m0 = rng.integers(0, max(n_months - min_life, 1), n_permnos)
    life = np.clip(rng.lognormal(5.1, 0.8, n_permnos).astype(np.int64), min_life, None)
    m1 = np.minimum(m0 + life, n_months - 1)

    betas = rng.uniform(0.3, 1.8, n_permnos)
    idio = rng.uniform(0.01, 0.03, n_permnos)
    base_prc = rng.uniform(5, 80, n_permnos)
    base_shr = rng.integers(1_000, 50_000, n_permnos).astype(np.float64)
    issue_rate = rng.uniform(0.0, 0.004, n_permnos)

    common = rng.random(n_permnos) >= frac_noncommon
    exch_code = np.where(
        rng.random(n_permnos) < frac_nyse, 0,
        np.where(rng.random(n_permnos) < 0.7, 1, 2),
    ).astype(np.int8)  # N / Q / A

    flag_values = {
        "issuertype": ("CORP", "ABS"),
        "securitytype": ("EQTY",),
        "securitysubtype": ("COM", "ADR"),
        "sharetype": ("NS",),
        "usincflg": ("Y", "N"),
        "primaryexch": ("N", "Q", "A"),
        "conditionaltype": ("RW",),
        "tradingstatusflg": ("A",),
    }
    noncommon_code = (~common).astype(np.int8)

    # --- daily ------------------------------------------------------------
    d0 = month_day_lo[m0]
    d1 = month_day_hi[m1]
    d_counts = (d1 - d0).astype(np.int64)
    day_idx, _ = flat_ranges(d0, d_counts)
    r_daily = len(day_idx)

    ret = np.repeat(betas, d_counts) * mkt[day_idx]
    ret += rng.standard_normal(r_daily) * np.repeat(idio, d_counts)
    retx = np.where(rng.random(r_daily) < 0.005, np.nan, ret)

    rep = {
        "issuertype": np.repeat(noncommon_code, d_counts),
        "securitysubtype": np.repeat(noncommon_code, d_counts),
        "usincflg": np.repeat(noncommon_code, d_counts),
        "primaryexch": np.repeat(exch_code, d_counts),
    }
    crsp_d = pd.DataFrame(
        {
            "permno": np.repeat(permnos, d_counts),
            "permco": np.repeat(permnos + 50000, d_counts),
            "dlycaldt": days.values[day_idx],
            "totret": retx + 2e-5,
            "retx": retx,
            "prc": np.repeat(base_prc, d_counts),
            "shrout": np.repeat(base_shr, d_counts),
            "jdate": day_me.values[day_idx],
            **_flag_frame(r_daily, flag_values, rep),
        }
    )

    # --- monthly ----------------------------------------------------------
    m_counts = (m1 - m0 + 1).astype(np.int64)
    month_idx, within_m = flat_ranges(m0, m_counts)
    r_m = len(month_idx)
    mretx = rng.normal(0.008, 0.07, r_m)
    shrout_m = np.repeat(base_shr, m_counts) * np.exp(
        within_m * np.log1p(np.repeat(issue_rate, m_counts))
    )
    prc_m = np.repeat(base_prc, m_counts) * np.exp(rng.normal(0.0, 0.15, r_m))
    rep_m = {
        "issuertype": np.repeat(noncommon_code, m_counts),
        "securitysubtype": np.repeat(noncommon_code, m_counts),
        "usincflg": np.repeat(noncommon_code, m_counts),
        "primaryexch": np.repeat(exch_code, m_counts),
    }
    jdate_m = months.values[month_idx]
    crsp_m = pd.DataFrame(
        {
            "permno": np.repeat(permnos, m_counts),
            "permco": np.repeat(permnos + 50000, m_counts),
            "mthcaldt": jdate_m,
            "totret": mretx + 2e-4,
            "retx": mretx,
            "prc": prc_m,
            "shrout": shrout_m,
            "vol": shrout_m * 1000.0
            * np.repeat(rng.uniform(0.02, 0.20, n_permnos), m_counts)
            * rng.lognormal(0.0, 0.4, r_m),
            "jdate": jdate_m,
            **_flag_frame(r_m, flag_values, rep_m),
        }
    )

    # --- index ------------------------------------------------------------
    crsp_index_d = pd.DataFrame(
        {
            "caldt": days,
            "vwretd": mkt + 1e-4,
            "vwretx": mkt,
            "ewretd": mkt * 1.1,
            "ewretx": mkt * 1.1,
            "sprtrn": mkt * 0.95,
        }
    )

    # --- Compustat annual (all fiscal years touching the firm's life) -----
    y0 = months.year.values[m0] - 1
    y1 = months.year.values[m1]
    y_counts = (y1 - y0 + 1).astype(np.int64)
    year_flat, _ = flat_ranges(y0, y_counts)
    r_y = len(year_flat)
    assets = np.repeat(rng.uniform(50, 5000, n_permnos), y_counts) * np.exp(
        rng.normal(0.08, 0.15, r_y)
    )
    earnings = assets * rng.normal(0.04, 0.05, r_y)
    comp = pd.DataFrame(
        {
            "gvkey": np.char.add("1", np.char.zfill(
                np.repeat(np.arange(n_permnos), y_counts).astype("U5"), 5)),
            "datadate": pd.to_datetime(
                {"year": year_flat, "month": 12, "day": 31}
            ),
            "fyear": year_flat,
            "sales": assets * rng.uniform(0.4, 1.5, r_y),
            "earnings": earnings,
            "assets": assets,
            "accruals": rng.normal(0, 0.05, r_y) * assets,
            "non_cash_current_assets": assets * 0.3,
            "lct": assets * 0.2,
            "total_debt": assets * rng.uniform(0.0, 0.6, r_y),
            "depreciation": assets * 0.04,
            "dvpd": earnings * 0.3,
            "dvc": np.maximum(earnings, 0.0) * 0.25,
            "dvt": earnings * 0.3,
            "pstk": np.where(rng.random(r_y) < 0.5, np.nan, assets * 0.01),
            "pstkl": np.where(rng.random(r_y) < 0.5, np.nan, assets * 0.012),
            "pstkrv": np.where(rng.random(r_y) < 0.5, np.nan, assets * 0.011),
            "txditc": np.where(rng.random(r_y) < 0.3, np.nan, assets * 0.02),
            "seq": assets * rng.uniform(0.2, 0.7, r_y),
        }
    )

    # --- CCM links --------------------------------------------------------
    open_link = rng.random(n_permnos) < 0.2
    linkend = months.values[m1].copy()
    ccm = pd.DataFrame(
        {
            "gvkey": np.char.add("1", np.char.zfill(
                np.arange(n_permnos).astype("U5"), 5)),
            "permno": permnos,
            "linktype": "LU",
            "linkprim": "P",
            "linkdt": months.values[m0] - np.timedelta64(370, "D"),
            "linkenddt": pd.Series(linkend).mask(open_link, pd.NaT),
        }
    )
    return {
        "crsp_m": crsp_m,
        "crsp_d": crsp_d,
        "crsp_index_d": crsp_index_d,
        "comp": comp,
        "ccm": ccm,
    }


def write_benchscale_cache(
    raw_data_dir, n_permnos: int = 22000, n_months: int = 600, seed: int = 20140131
) -> Path:
    """Generate-once cache: reuses existing files when the parameter marker
    matches, so only the first bench run pays generation + parquet I/O."""
    raw_data_dir = Path(raw_data_dir)
    marker = raw_data_dir / "benchscale.json"
    # bump "v" whenever the generated schema changes (v2: monthly volume
    # column for the opt-in turnover characteristic) so pre-change caches
    # regenerate instead of silently lacking columns
    params = {"n_permnos": n_permnos, "n_months": n_months, "seed": seed, "v": 2}
    if marker.is_file():
        try:
            if json.loads(marker.read_text()) == params and all(
                (raw_data_dir / name).is_file() for name in _FILE_NAMES.values()
            ):
                return raw_data_dir
        except (ValueError, OSError):
            pass
    data = generate_benchscale_wrds(n_permnos=n_permnos, n_months=n_months, seed=seed)
    raw_data_dir.mkdir(parents=True, exist_ok=True)
    for key, name in _FILE_NAMES.items():
        data[key].to_parquet(raw_data_dir / name, index=False)
    marker.write_text(json.dumps(params))
    return raw_data_dir
