"""Chunked Arrow/columnar ingest: raw parquet → numpy columns, no pandas.

The legacy ingest route materializes every raw cache as a full pandas
DataFrame (object headers, block consolidation, categorical rebuild) and
then row-filters it — at real CRSP shape that is most of the cold wall
(BENCH_r05: ``load_raw_data`` 37.5 s + ``panel/universe_filter`` 33.5 s for
frames whose useful payload is a handful of numeric columns). This module
reads the SAME parquet files as columnar batches straight into numpy
arrays:

- value columns decode once per batch (zero-copy where arrow allows);
- the share-class universe filter evaluates on the batches' DICTIONARY
  CODES (int8/int32 compares against the handful of admitted categories,
  the same trick the legacy filter plays on pandas categoricals) and only
  surviving rows are ever materialized;
- batches stream — peak memory is one batch of flag codes plus the
  filtered value columns, never the 11-column 77M-row daily frame.

Semantics match ``data.wrds_pull.subset_to_common_stock_and_exchanges``
exactly: a row survives iff every flag column's value is in the admitted
set (nulls never match, as with ``Series.isin``). Anything structurally
unservable (pyarrow missing, non-parquet cache, absent columns) raises the
typed :class:`ColumnarIngestError` so the caller can fall back to the
legacy pandas route instead of crashing the pipeline.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

__all__ = [
    "ColumnarIngestError",
    "read_filtered_columns",
    "read_table_columns",
]

# Streaming batch size (rows) for the chunked reader: ~4M rows keeps a
# batch's flag codes + values in tens of MB while amortizing per-batch
# decode overhead over the 77M-row daily file.
_BATCH_ROWS = 1 << 22


class ColumnarIngestError(RuntimeError):
    """The columnar reader cannot service this request (missing pyarrow,
    non-parquet cache, absent columns). The pipeline catches this and
    falls back to the legacy pandas ingest route."""


def _pyarrow():
    try:
        import pyarrow
        import pyarrow.parquet
    except ImportError as exc:  # pragma: no cover - pyarrow is baked in
        raise ColumnarIngestError(
            "pyarrow is unavailable; use FMRP_PANEL_ROUTE=legacy"
        ) from exc
    return pyarrow, pyarrow.parquet


def _to_numpy(arr) -> np.ndarray:
    """One arrow array/chunked-array → numpy, decoding dictionaries.

    Numeric/temporal columns convert zero-copy when null-free; dictionary
    (categorical) columns decode to their value type first — only the few
    SMALL columns that need values (e.g. ``gvkey``) should take this path,
    the flag filter never does.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_dictionary(arr.type):
        arr = pc.cast(arr, arr.type.value_type)
    return arr.to_numpy(zero_copy_only=False)


def _flag_keep_mask(columns: Mapping[str, object], spec) -> np.ndarray:
    """Row-keep mask for one batch: AND over every flag column's membership
    in its admitted set. Dictionary columns compare CODES (nulls are code
    -1 after ``fill_null``, matching nothing); plain columns fall back to
    value ``isin`` — both reproduce ``Series.isin`` semantics."""
    import pyarrow as pa

    keep: Optional[np.ndarray] = None
    for name, wanted in spec.items():
        col = columns[name]
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        if pa.types.is_dictionary(col.type):
            cats = col.dictionary.to_pylist()
            codes = col.indices.fill_null(-1).to_numpy(zero_copy_only=False)
            admitted = [i for i, c in enumerate(cats) if c in wanted]
            m = np.zeros(len(codes), dtype=bool)
            for code in admitted:
                m |= codes == code
        else:
            values = col.to_numpy(zero_copy_only=False)
            m = np.isin(values, np.asarray(list(wanted), dtype=object))
        keep = m if keep is None else keep & m
    if keep is None:
        raise ValueError("empty filter spec")
    return keep


def _require_columns(schema_names: Sequence[str], needed, path) -> None:
    missing = [c for c in needed if c not in schema_names]
    if missing:
        raise ColumnarIngestError(
            f"{Path(path).name} lacks columns {missing} needed by the "
            "columnar ingest route; use FMRP_PANEL_ROUTE=legacy"
        )


def read_filtered_columns(
    path,
    value_columns: Sequence[str],
    flag_spec: Mapping[str, Sequence[str]],
    bool_columns: Optional[Mapping[str, Sequence[str]]] = None,
    batch_rows: int = _BATCH_ROWS,
) -> Dict[str, np.ndarray]:
    """Stream a parquet file and return the ``value_columns`` (plus derived
    ``bool_columns``) of the rows passing the flag filter, as numpy arrays.

    ``flag_spec``: column → admitted values (ANDed). ``bool_columns``:
    column → values, yielding a derived boolean output named after the
    column (evaluated on dictionary codes like the filter — used for
    ``is_nyse`` without materializing 13M exchange strings).
    """
    pa_, pq_ = _pyarrow()
    path = Path(path)
    if path.suffix != ".parquet":
        raise ColumnarIngestError(
            f"columnar ingest reads parquet only, got {path.name}"
        )
    if not path.exists():
        raise FileNotFoundError(f"File {path.name} not found in {path.parent}.")
    bool_columns = dict(bool_columns or {})
    pf = pq_.ParquetFile(path)
    names = pf.schema_arrow.names
    read_cols = list(dict.fromkeys(
        [*value_columns, *flag_spec, *bool_columns]
    ))
    _require_columns(names, read_cols, path)

    parts: Dict[str, List[np.ndarray]] = {
        c: [] for c in [*value_columns, *bool_columns]
    }
    import pyarrow as pa

    for batch in pf.iter_batches(batch_size=batch_rows, columns=read_cols):
        cols = {n: batch.column(i) for i, n in enumerate(batch.schema.names)}
        keep = _flag_keep_mask(cols, flag_spec)
        idx = np.flatnonzero(keep)
        take = pa.array(idx, type=pa.int64())
        for c in value_columns:
            # take-then-decode: only surviving rows ever materialize to
            # numpy (decode-then-mask would copy the full batch first)
            parts[c].append(_to_numpy(cols[c].take(take)))
        for c, wanted in bool_columns.items():
            m = _flag_keep_mask({c: cols[c]}, {c: wanted})
            parts[c].append(m[idx])
    out: Dict[str, np.ndarray] = {}
    for c, chunks in parts.items():
        out[c] = np.concatenate(chunks) if chunks else np.empty(0)
    return out


def read_table_columns(path, columns: Sequence[str]) -> Dict[str, np.ndarray]:
    """Read the named columns of a (small) parquet table as numpy arrays —
    the non-streaming sibling for Compustat / CCM / the daily index."""
    pa_, pq_ = _pyarrow()
    path = Path(path)
    if path.suffix != ".parquet":
        raise ColumnarIngestError(
            f"columnar ingest reads parquet only, got {path.name}"
        )
    if not path.exists():
        raise FileNotFoundError(f"File {path.name} not found in {path.parent}.")
    pf = pq_.ParquetFile(path)
    _require_columns(pf.schema_arrow.names, columns, path)
    table = pq_.read_table(path, columns=list(columns))
    return {c: _to_numpy(table.column(c)) for c in columns}
