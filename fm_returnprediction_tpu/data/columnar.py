"""Chunked Arrow/columnar ingest: raw parquet → numpy columns, no pandas.

The legacy ingest route materializes every raw cache as a full pandas
DataFrame (object headers, block consolidation, categorical rebuild) and
then row-filters it — at real CRSP shape that is most of the cold wall
(BENCH_r05: ``load_raw_data`` 37.5 s + ``panel/universe_filter`` 33.5 s for
frames whose useful payload is a handful of numeric columns). This module
reads the SAME parquet files as columnar batches straight into numpy
arrays:

- value columns decode once per batch (zero-copy where arrow allows);
- the share-class universe filter evaluates on the batches' DICTIONARY
  CODES (int8/int32 compares against the handful of admitted categories,
  the same trick the legacy filter plays on pandas categoricals) and only
  surviving rows are ever materialized;
- batches stream — peak memory is one batch of flag codes plus the
  filtered value columns, never the 11-column 77M-row daily frame.

Semantics match ``data.wrds_pull.subset_to_common_stock_and_exchanges``
exactly: a row survives iff every flag column's value is in the admitted
set (nulls never match, as with ``Series.isin``). Anything structurally
unservable (pyarrow missing, non-parquet cache, absent columns) raises the
typed :class:`ColumnarIngestError` so the caller can fall back to the
legacy pandas route instead of crashing the pipeline.

Overlapped cold ingest (PR 11): the chunked read is a strict
read→filter→decode serial loop by default construction, but its two
halves live on different sides of the GIL — ``ParquetFile.iter_batches``
decompresses/decodes in arrow's C++ thread (GIL released), while the flag
filter, gather and downstream consumption (the dense scatter, the device
transfer of finished strips) run in Python/XLA. ``_prefetched`` overlaps
them: a reader thread pulls batches ahead into a BOUNDED queue (depth =
``FMRP_INGEST_PREFETCH``, default 2 — a double buffer plus the batch in
flight; 0 restores the serial loop) while the consumer drains it, so the
cold wall pays max(read, consume) per batch instead of their sum. Batch
ORDER is preserved (the filter/scatter contract is order-sensitive) and a
reader-side exception re-raises at the consumer's next pull.
"""

from __future__ import annotations

import os
import queue
import threading
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Mapping, Optional, Sequence

import numpy as np

__all__ = [
    "ColumnarIngestError",
    "read_filtered_columns",
    "read_table_columns",
    "resolve_prefetch_depth",
]

# Streaming batch size (rows) for the chunked reader: ~4M rows keeps a
# batch's flag codes + values in tens of MB while amortizing per-batch
# decode overhead over the 77M-row daily file.
_BATCH_ROWS = 1 << 22

#: default read-ahead depth of the cold-ingest overlap queue
_PREFETCH_DEPTH = 2


def resolve_prefetch_depth(prefetch: Optional[int] = None) -> int:
    """Read-ahead depth for the chunked reader: explicit argument >
    ``FMRP_INGEST_PREFETCH`` env > 2. ``0`` (or anything unparseable,
    conservatively) disables the reader thread entirely — the serial loop
    is the differential oracle for the overlap."""
    if prefetch is not None:
        return max(int(prefetch), 0)
    raw = os.environ.get("FMRP_INGEST_PREFETCH", "").strip()
    if not raw:
        return _PREFETCH_DEPTH
    try:
        return max(int(raw), 0)
    except ValueError:
        return 0


def _prefetched(batches: Iterable, depth: int) -> Iterator:
    """Yield from ``batches`` through a bounded read-ahead queue.

    A daemon reader thread advances the source iterator up to ``depth``
    items ahead; items come out in source order. If the reader raises, the
    exception surfaces at the consumer's next pull. If the CONSUMER stops
    early (exception upstream, generator close), the reader is told to
    stop and its pending ``put`` is drained so the thread never deadlocks
    on a full queue."""
    if depth <= 0:
        yield from batches
        return
    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _END = object()

    def _reader():
        try:
            for item in batches:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
            q.put(_END)
        except BaseException as exc:  # noqa: BLE001 - re-raised consumer-side
            if not stop.is_set():
                q.put(exc)

    t = threading.Thread(target=_reader, name="fmrp-ingest-prefetch",
                         daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # unblock a reader waiting on a full queue, then let it exit
        try:
            q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5.0)


class ColumnarIngestError(RuntimeError):
    """The columnar reader cannot service this request (missing pyarrow,
    non-parquet cache, absent columns). The pipeline catches this and
    falls back to the legacy pandas ingest route."""


def _pyarrow():
    try:
        import pyarrow
        import pyarrow.parquet
    except ImportError as exc:  # pragma: no cover - pyarrow is baked in
        raise ColumnarIngestError(
            "pyarrow is unavailable; use FMRP_PANEL_ROUTE=legacy"
        ) from exc
    return pyarrow, pyarrow.parquet


def _to_numpy(arr) -> np.ndarray:
    """One arrow array/chunked-array → numpy, decoding dictionaries.

    Numeric/temporal columns convert zero-copy when null-free; dictionary
    (categorical) columns decode to their value type first — only the few
    SMALL columns that need values (e.g. ``gvkey``) should take this path,
    the flag filter never does.
    """
    import pyarrow as pa
    import pyarrow.compute as pc

    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_dictionary(arr.type):
        arr = pc.cast(arr, arr.type.value_type)
    return arr.to_numpy(zero_copy_only=False)


def _flag_keep_mask(columns: Mapping[str, object], spec) -> np.ndarray:
    """Row-keep mask for one batch: AND over every flag column's membership
    in its admitted set. Dictionary columns compare CODES (nulls are code
    -1 after ``fill_null``, matching nothing); plain columns fall back to
    value ``isin`` — both reproduce ``Series.isin`` semantics."""
    import pyarrow as pa

    keep: Optional[np.ndarray] = None
    for name, wanted in spec.items():
        col = columns[name]
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        if pa.types.is_dictionary(col.type):
            cats = col.dictionary.to_pylist()
            codes = col.indices.fill_null(-1).to_numpy(zero_copy_only=False)
            admitted = [i for i, c in enumerate(cats) if c in wanted]
            m = np.zeros(len(codes), dtype=bool)
            for code in admitted:
                m |= codes == code
        else:
            values = col.to_numpy(zero_copy_only=False)
            m = np.isin(values, np.asarray(list(wanted), dtype=object))
        keep = m if keep is None else keep & m
    if keep is None:
        raise ValueError("empty filter spec")
    return keep


def _require_columns(schema_names: Sequence[str], needed, path) -> None:
    missing = [c for c in needed if c not in schema_names]
    if missing:
        raise ColumnarIngestError(
            f"{Path(path).name} lacks columns {missing} needed by the "
            "columnar ingest route; use FMRP_PANEL_ROUTE=legacy"
        )


def read_filtered_columns(
    path,
    value_columns: Sequence[str],
    flag_spec: Mapping[str, Sequence[str]],
    bool_columns: Optional[Mapping[str, Sequence[str]]] = None,
    batch_rows: int = _BATCH_ROWS,
    prefetch: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Stream a parquet file and return the ``value_columns`` (plus derived
    ``bool_columns``) of the rows passing the flag filter, as numpy arrays.

    ``flag_spec``: column → admitted values (ANDed). ``bool_columns``:
    column → values, yielding a derived boolean output named after the
    column (evaluated on dictionary codes like the filter — used for
    ``is_nyse`` without materializing 13M exchange strings).
    ``prefetch``: read-ahead depth of the overlap queue (None resolves
    ``FMRP_INGEST_PREFETCH``; 0 = the serial oracle loop) — batch k+1
    decodes in arrow's C++ thread while batch k filters/gathers here.
    """
    pa_, pq_ = _pyarrow()
    path = Path(path)
    if path.suffix != ".parquet":
        raise ColumnarIngestError(
            f"columnar ingest reads parquet only, got {path.name}"
        )
    if not path.exists():
        raise FileNotFoundError(f"File {path.name} not found in {path.parent}.")
    bool_columns = dict(bool_columns or {})
    pf = pq_.ParquetFile(path)
    names = pf.schema_arrow.names
    read_cols = list(dict.fromkeys(
        [*value_columns, *flag_spec, *bool_columns]
    ))
    _require_columns(names, read_cols, path)

    parts: Dict[str, List[np.ndarray]] = {
        c: [] for c in [*value_columns, *bool_columns]
    }
    import pyarrow as pa

    batches = _prefetched(
        pf.iter_batches(batch_size=batch_rows, columns=read_cols),
        resolve_prefetch_depth(prefetch),
    )
    for batch in batches:
        cols = {n: batch.column(i) for i, n in enumerate(batch.schema.names)}
        keep = _flag_keep_mask(cols, flag_spec)
        idx = np.flatnonzero(keep)
        take = pa.array(idx, type=pa.int64())
        for c in value_columns:
            # take-then-decode: only surviving rows ever materialize to
            # numpy (decode-then-mask would copy the full batch first)
            parts[c].append(_to_numpy(cols[c].take(take)))
        for c, wanted in bool_columns.items():
            m = _flag_keep_mask({c: cols[c]}, {c: wanted})
            parts[c].append(m[idx])
    out: Dict[str, np.ndarray] = {}
    for c, chunks in parts.items():
        out[c] = np.concatenate(chunks) if chunks else np.empty(0)
    return out


def read_table_columns(path, columns: Sequence[str]) -> Dict[str, np.ndarray]:
    """Read the named columns of a (small) parquet table as numpy arrays —
    the non-streaming sibling for Compustat / CCM / the daily index."""
    pa_, pq_ = _pyarrow()
    path = Path(path)
    if path.suffix != ".parquet":
        raise ColumnarIngestError(
            f"columnar ingest reads parquet only, got {path.name}"
        )
    if not path.exists():
        raise FileNotFoundError(f"File {path.name} not found in {path.parent}.")
    pf = pq_.ParquetFile(path)
    _require_columns(pf.schema_arrow.names, columns, path)
    table = pq_.read_table(path, columns=list(columns))
    return {c: _to_numpy(table.column(c)) for c in columns}
