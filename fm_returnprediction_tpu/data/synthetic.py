"""Deterministic synthetic CRSP/Compustat-shaped data — the fake-WRDS backend.

The reference has no test fixtures or fake backend; offline work relies on a
previously-populated parquet cache (SURVEY §4). This module generates small,
seeded DataFrames with the exact schemas the WRDS pullers produce
(``src/pull_crsp.py:217-235``, ``src/pull_compustat.py:168-219,312-321``), so
the full pipeline runs hermetically: multiple permnos per permco (exercises
ME aggregation), non-NYSE/ADR/non-common rows (exercises universe filters),
listing gaps, fiscal years ending both Dec 31 and Jun 30 (exercises the
4-month report lag and monthly expansion), link windows with gaps, and a
daily return history aligned with a market index (exercises the beta and
volatility kernels).

``write_synthetic_cache`` persists everything under the same file names the
pipeline loads (``CRSP_stock_d/m.parquet`` etc.,
``src/calc_Lewellen_2014.py:1236-1240``).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict

import numpy as np
import pandas as pd
from pandas.tseries.offsets import MonthEnd

__all__ = ["FILE_NAMES", "SyntheticConfig", "generate_synthetic_wrds", "write_synthetic_cache"]

# Canonical cache file names (reference ``src/calc_Lewellen_2014.py:1236-1240``)
# — the single definition shared by the pipeline loader and both synthetic
# backends.
FILE_NAMES = {
    "crsp_m": "CRSP_stock_m.parquet",
    "crsp_d": "CRSP_stock_d.parquet",
    "crsp_index_d": "CRSP_index_d.parquet",
    "comp": "Compustat_fund.parquet",
    "ccm": "CRSP_Comp_Link_Table.parquet",
}


class SyntheticConfig:
    """Knobs for the synthetic universe (kept tiny for CI, scalable for bench)."""

    def __init__(
        self,
        n_firms: int = 40,
        n_months: int = 72,
        start: str = "1964-01-31",
        seed: int = 20140131,
        frac_nyse: float = 0.4,
        frac_noncommon: float = 0.1,
        frac_multishare: float = 0.1,
    ) -> None:
        self.n_firms = n_firms
        self.n_months = n_months
        self.start = start
        self.seed = seed
        self.frac_nyse = frac_nyse
        self.frac_noncommon = frac_noncommon
        self.frac_multishare = frac_multishare


def _trading_days(months: pd.DatetimeIndex) -> pd.DatetimeIndex:
    start = months[0] - MonthEnd(1) + pd.Timedelta(days=1)
    days = pd.bdate_range(start, months[-1])
    return days


def generate_synthetic_wrds(cfg: SyntheticConfig | None = None) -> Dict[str, pd.DataFrame]:
    """Generate the five datasets the pipeline consumes.

    Returns dict with keys ``crsp_m``, ``crsp_d``, ``crsp_index_d``, ``comp``,
    ``ccm`` (schemas matching the reference pullers' SQL output).
    """
    cfg = cfg or SyntheticConfig()
    rng = np.random.default_rng(cfg.seed)
    months = pd.date_range(cfg.start, periods=cfg.n_months, freq="ME")
    days = _trading_days(months)
    day_month_end = days + MonthEnd(0)

    # --- market index (daily) -------------------------------------------
    mkt_ret = rng.normal(3e-4, 0.008, len(days))
    crsp_index_d = pd.DataFrame(
        {
            "caldt": days,
            "vwretd": mkt_ret + 1e-4,
            "vwretx": mkt_ret,
            "ewretd": mkt_ret * 1.1,
            "ewretx": mkt_ret * 1.1,
            "sprtrn": mkt_ret * 0.95,
        }
    )

    # --- firms -----------------------------------------------------------
    monthly_rows, daily_rows, comp_rows, link_rows = [], [], [], []
    for firm in range(cfg.n_firms):
        permco = 5000 + firm
        permno = 10000 + firm * 2
        gvkey = f"{100000 + firm}"
        is_nyse = rng.random() < cfg.frac_nyse
        exch = "N" if is_nyse else ("Q" if rng.random() < 0.7 else "A")
        common = rng.random() > cfg.frac_noncommon

        # listing window (firms enter/exit)
        m0 = int(rng.integers(0, max(cfg.n_months // 4, 1)))
        m1 = int(rng.integers(3 * cfg.n_months // 4, cfg.n_months))

        beta_true = rng.uniform(0.3, 1.8)
        idio = rng.uniform(0.01, 0.03)
        price = float(rng.uniform(5, 80))
        shrout = float(rng.integers(1_000, 50_000))

        firm_days = days[(day_month_end >= months[m0]) & (day_month_end <= months[m1])]
        firm_mkt = mkt_ret[
            (day_month_end >= months[m0]) & (day_month_end <= months[m1])
        ]
        dly_ret = beta_true * firm_mkt + rng.normal(0, idio, len(firm_days))
        # sprinkle missing daily returns (rows exist, retx null — CRSP has
        # these; they must poison beta windows but not break price paths)
        nan_days = rng.random(len(firm_days)) < 0.01
        dly_ret_obs = np.where(nan_days, np.nan, dly_ret)

        shared = dict(
            permco=permco,
            issuertype="CORP" if common else "ABS",
            securitytype="EQTY",
            securitysubtype="COM" if common else "ADR",
            sharetype="NS",
            usincflg="Y" if common else "N",
            primaryexch=exch,
            conditionaltype="RW",
            tradingstatusflg="A",
        )

        # daily rows
        prices = price * np.cumprod(1 + dly_ret)
        for d, r, p in zip(firm_days, dly_ret_obs, prices):
            daily_rows.append(
                dict(
                    permno=permno,
                    dlycaldt=d,
                    totret=r + 2e-5,
                    retx=r,
                    prc=p,
                    shrout=shrout,
                    **shared,
                )
            )

        # monthly rows aggregated from daily; firm-specific share issuance
        # with occasional jumps so cross-sections of issuance are non-degenerate
        fd = pd.DataFrame({"d": firm_days, "r": dly_ret, "p": prices})
        fd["m"] = fd["d"] + MonthEnd(0)
        grouped = fd.groupby("m")
        issue_rate = float(rng.uniform(0.0, 0.005))
        # monthly share volume for the opt-in turnover characteristic:
        # per-firm turnover level ~ the published 0.08/month scale, with
        # lognormal month-to-month variation (vol is in shares, shrout in
        # thousands — the CRSP unit convention turnover = vol/(shrout·1e3))
        turn_level = float(rng.uniform(0.02, 0.20))
        sh = shrout
        for m, grp in grouped:
            mret = float(np.prod(1 + grp["r"].to_numpy()) - 1)
            sh = sh * (1 + issue_rate)
            if rng.random() < 0.03:
                sh *= float(rng.uniform(1.05, 1.3))  # seasoned offering
            monthly_rows.append(
                dict(
                    permno=permno,
                    mthcaldt=m,
                    totret=mret + 2e-4,
                    retx=mret,
                    prc=float(grp["p"].iloc[-1]),
                    shrout=sh,
                    vol=turn_level * sh * 1000.0 * float(rng.lognormal(0, 0.4)),
                    **shared,
                )
            )
        # occasional second share class (same permco) to exercise ME dedup
        if rng.random() < cfg.frac_multishare:
            for m, grp in grouped:
                monthly_rows.append(
                    dict(
                        permno=permno + 1,
                        mthcaldt=m,
                        totret=float(rng.normal(0.01, 0.05)),
                        retx=float(rng.normal(0.01, 0.05)),
                        prc=float(grp["p"].iloc[-1] * 0.5),
                        shrout=shrout * 0.2,
                        vol=turn_level * shrout * 200.0,
                        **shared,
                    )
                )

        # --- Compustat annual fundamentals ------------------------------
        fy_end_month = 12 if rng.random() < 0.8 else 6
        assets = float(rng.uniform(50, 5000))
        first_year = months[m0].year - 1
        last_year = months[m1].year
        for year in range(first_year, last_year + 1):
            datadate = pd.Timestamp(year=year, month=fy_end_month, day=1) + MonthEnd(0)
            growth = float(rng.normal(0.08, 0.15))
            assets *= 1 + growth
            sales = assets * float(rng.uniform(0.4, 1.5))
            earnings = assets * float(rng.normal(0.04, 0.05))
            comp_rows.append(
                dict(
                    gvkey=gvkey,
                    datadate=datadate,
                    fyear=year,
                    sales=sales,
                    earnings=earnings,
                    assets=assets,
                    accruals=float(rng.normal(0, 0.05)) * assets,
                    non_cash_current_assets=assets * 0.3,
                    lct=assets * 0.2,
                    total_debt=assets * float(rng.uniform(0.0, 0.6)),
                    depreciation=assets * 0.04,
                    dvpd=earnings * 0.3,
                    dvc=max(earnings, 0.0) * 0.25,
                    dvt=earnings * 0.3,
                    pstk=np.nan if rng.random() < 0.5 else assets * 0.01,
                    pstkl=np.nan if rng.random() < 0.5 else assets * 0.012,
                    pstkrv=np.nan if rng.random() < 0.5 else assets * 0.011,
                    txditc=np.nan if rng.random() < 0.3 else assets * 0.02,
                    seq=assets * float(rng.uniform(0.2, 0.7)),
                )
            )

        # --- CCM link ----------------------------------------------------
        link_start = months[m0] - MonthEnd(12)
        link_end = months[m1] if rng.random() < 0.8 else pd.NaT  # open link
        link_rows.append(
            dict(
                gvkey=gvkey,
                permno=permno,
                linktype="LU",
                linkprim="P",
                linkdt=link_start,
                linkenddt=link_end,
            )
        )

    crsp_m = pd.DataFrame(monthly_rows)
    crsp_m["jdate"] = crsp_m["mthcaldt"] + MonthEnd(0)
    crsp_d = pd.DataFrame(daily_rows)
    crsp_d["jdate"] = crsp_d["dlycaldt"] + MonthEnd(0)

    return {
        "crsp_m": crsp_m,
        "crsp_d": crsp_d,
        "crsp_index_d": crsp_index_d,
        "comp": pd.DataFrame(comp_rows),
        "ccm": pd.DataFrame(link_rows),
    }


def write_synthetic_cache(
    raw_data_dir: Path, cfg: SyntheticConfig | None = None
) -> Dict[str, Path]:
    """Persist the synthetic datasets under the pipeline's cache file names."""
    data = generate_synthetic_wrds(cfg)
    raw_data_dir = Path(raw_data_dir)
    raw_data_dir.mkdir(parents=True, exist_ok=True)
    paths = {}
    for key, name in FILE_NAMES.items():
        path = raw_data_dir / name
        data[key].to_parquet(path, index=False)
        paths[key] = path
    return paths
