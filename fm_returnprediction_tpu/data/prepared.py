"""Prepared-inputs checkpoint: skip the host ingest path on warm runs.

At real 1964-2013 CRSP shape, the cold wall is host-side ingest work a TPU
cannot touch: reading the 77M-row daily parquet, the universe filter, the
relational transforms, the long→compact daily ingest, and the long→dense
monthly scatter (BENCH_r03-r05 ``real_pipeline_*stage_s``). All of it is a
pure function of the five raw cache files (plus the compute dtype and the
INCLUDE_TURNOVER column set), so the pipeline checkpoints its two host
products:

- the dense monthly BASE panel (``panel.dense.DensePanel`` over
  BASE_COLUMNS + is_nyse): the direct input to the device characteristic
  engine;
- the per-firm compacted daily strips + shared calendar vectors
  (``panel.daily.CompactDaily``): the input to the daily vol/beta kernels.

Layout v3 is COLUMNAR: one raw ``.npy`` file per array under
``<raw_dir>/_prepared/`` instead of the v2 npz bundles. npz is a zip
container — every load decompresses/copies each member through Python —
while bare ``.npy`` files load with ``np.load(mmap_mode="r")``: the warm
run maps the checkpoint ZERO-COPY in milliseconds (v2 cost 1.3-2.9 s at
real shape) and pages flow from the OS cache straight into the consumers
(the device push, the daily strip assembly) without an intermediate heap
copy.

Integrity: ``meta.json`` carries a sha256 + byte-size manifest over every
payload file (the same guard-manifest shape as the audit/drift layer and
``utils.cache.save_array_bundle``). Loads always verify structure and
sizes; a mismatch — or any structurally unreadable payload — surfaces as
the typed :class:`CorruptArtifactError` internally and degrades to a
rebuild (warning, never a crash), preserving the v2 semantics. Full
content re-hash on load costs what the mmap saves, so it is opt-in:
``FMRP_PREPARED_VERIFY=1``.

Validity is a fingerprint over the raw files' (name, size, mtime) plus
the compute dtype, a caller salt (the resolved INCLUDE_TURNOVER flag) and
the layout version — the make-style staleness contract. One slot per raw
directory, overwritten in place; ``meta.json`` is written last (tmp +
rename), so a crashed writer leaves a stale fingerprint, never a
half-valid checkpoint. ``PREPARED_CACHE=0`` disables reading and writing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from fm_returnprediction_tpu.panel.daily import CompactDaily
from fm_returnprediction_tpu.panel.dense import DensePanel
from fm_returnprediction_tpu.registry import integrity as _integrity
from fm_returnprediction_tpu.resilience.errors import CorruptArtifactError

__all__ = [
    "PREPARED_DIRNAME",
    "prepared_enabled",
    "prepared_candidates",
    "raw_fingerprint",
    "save_prepared",
    "load_prepared",
]

PREPARED_DIRNAME = "_prepared"
# Bump when the prepared LAYOUT or the ingest semantics feeding it change —
# an old checkpoint must not satisfy a new pipeline. v3: columnar per-array
# .npy files with a sha256 manifest, memory-mapped on load (v2 was two npz
# bundles; v1 stored the merged long frame).
_VERSION = 3

_META_FILE = "meta.json"
# v2 payloads a version upgrade orphans — removed by the next save
_STALE_FILES = ("dense_base.npz", "compact_daily.npz", "monthly_merged.parquet")

_BASE_ARRAYS = ("values", "mask", "months", "ids", "var_names")
_DAILY_ARRAYS = (
    "row_values", "row_pos", "offsets", "ids", "mkt", "mkt_present",
    "days", "day_month_id", "week_id", "week_month_id",
)


def prepared_enabled() -> bool:
    """The PREPARED_CACHE switch (default on), env/.env overridable."""
    from fm_returnprediction_tpu.settings import config

    return bool(int(config("PREPARED_CACHE")))


def raw_fingerprint(raw_dir, dtype, salt: str = "") -> str:
    """Staleness key for the checkpoint under ``raw_dir``.

    Hashes each raw cache file's (name, size, mtime_ns) — the make
    contract: content re-reads would cost a large fraction of what the
    checkpoint saves. ``dtype`` is in the key because the payload arrays
    are materialized in the compute dtype; ``salt`` carries caller
    settings that change the payload layout (the resolved INCLUDE_TURNOVER
    flag, which adds a base column).
    """
    from fm_returnprediction_tpu.pipeline import RAW_FILE_NAMES

    h = hashlib.sha256()
    h.update(f"v{_VERSION}|{np.dtype(dtype).str}|{salt}".encode())
    for name in sorted(RAW_FILE_NAMES.values()):
        path = Path(raw_dir) / name
        st = path.stat()  # missing raw file: let the error surface here
        h.update(f"|{name}:{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()


def prepared_candidates(raw_dir) -> list:
    """The checkpoint slots to try, preference order. With the registry
    armed (``FMRP_REGISTRY_DIR``) the slot lives under the registry root
    — keyed by the raw directory's absolute path, so two raw caches do
    not share a slot — and the legacy ``<raw_dir>/_prepared`` location
    stays as a read fallback (a user arming the registry must not re-pay
    the full ingest their legacy checkpoint already covers). Saves go to
    the FIRST candidate."""
    from fm_returnprediction_tpu.registry.store import active_registry

    legacy = Path(raw_dir) / PREPARED_DIRNAME
    reg = active_registry()
    if reg is None:
        return [legacy]
    slot = hashlib.sha256(
        str(Path(raw_dir).resolve()).encode()
    ).hexdigest()[:16]
    return [reg.prepared_root(slot), legacy]


def _write_npy(prepared_dir: Path, name: str, arr: np.ndarray, manifest: dict):
    """One atomic .npy write (tmp + rename) + its manifest entry."""
    path = prepared_dir / f"{name}.npy"
    tmp = prepared_dir / f".{name}.tmp{os.getpid()}.npy"
    try:
        with open(tmp, "wb") as f:
            np.lib.format.write_array(
                f, np.ascontiguousarray(arr), allow_pickle=False
            )
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)
    manifest[f"{name}.npy"] = _integrity.manifest_entry(path)


def save_prepared(
    prepared_dir, fingerprint: str, base: DensePanel, cd: CompactDaily
) -> None:
    """Write the v3 columnar checkpoint; meta (fingerprint + manifest) goes
    LAST so a partial write is indistinguishable from a stale one. Failures
    degrade to a warning — the checkpoint is an accelerant, never a
    correctness gate."""
    prepared_dir = Path(prepared_dir)
    try:
        prepared_dir.mkdir(parents=True, exist_ok=True)
        meta_path = prepared_dir / _META_FILE
        meta_path.unlink(missing_ok=True)  # invalidate before payloads
        for stale in _STALE_FILES:
            (prepared_dir / stale).unlink(missing_ok=True)

        manifest: dict = {}
        months_unit = np.datetime_data(base.months.dtype)[0]
        days_unit = np.datetime_data(cd.days.dtype)[0]
        base_arrays = {
            "values": np.asarray(base.values),
            "mask": np.asarray(base.mask),
            "months": base.months.astype(np.int64),
            "ids": np.asarray(base.ids),
            # fixed-width unicode, NOT object dtype: loadable with
            # allow_pickle off (no pickle surface in a shared artifact)
            "var_names": np.asarray(base.var_names, dtype=np.str_),
        }
        for name, arr in base_arrays.items():
            _write_npy(prepared_dir, f"base.{name}", arr, manifest)
        for field in dataclasses.fields(cd):
            value = getattr(cd, field.name)
            if not isinstance(value, np.ndarray):
                continue
            if field.name == "days":
                value = value.astype(np.int64)  # datetime64 needs a unit
            _write_npy(prepared_dir, f"daily.{field.name}", value, manifest)

        tmp = meta_path.with_suffix(f".tmp{os.getpid()}")  # per-writer tmp
        tmp.write_text(json.dumps({
            "fingerprint": fingerprint,
            "version": _VERSION,
            "months_unit": months_unit,
            "days_unit": days_unit,
            "n_weeks": cd.n_weeks,
            "n_months": cd.n_months,
            "manifest": manifest,
        }))
        os.replace(tmp, meta_path)
    except OSError as exc:  # read-only raw dir, disk full, ...
        import warnings

        warnings.warn(f"prepared-inputs checkpoint not written: {exc!r}",
                      stacklevel=2)


def _verify_on_load() -> bool:
    return os.environ.get("FMRP_PREPARED_VERIFY", "0") == "1"


def _load_payload(prepared_dir: Path, name: str, meta: dict) -> np.ndarray:
    """One payload, memory-mapped, checked against the manifest.

    Size + npy-header structure always verify (free); the full content
    sha256 re-read is opt-in (``FMRP_PREPARED_VERIFY=1``) because it costs
    the IO the mmap exists to avoid. Verification is the shared
    ``registry.integrity`` layer — any mismatch or unreadable file is a
    :class:`CorruptArtifactError` and the caller degrades to a rebuild."""
    fname = f"{name}.npy"
    entry = meta.get("manifest", {}).get(fname)
    path = prepared_dir / fname
    if entry is None:
        raise CorruptArtifactError(f"{fname} missing from manifest")
    _integrity.verify_entry(path, entry, deep=_verify_on_load())
    try:
        return np.load(path, mmap_mode="r", allow_pickle=False)
    except (OSError, ValueError) as exc:
        raise CorruptArtifactError(f"{fname} unreadable: {exc!r}") from exc


def load_prepared(
    prepared_dir, fingerprint: str
) -> Optional[Tuple[DensePanel, CompactDaily]]:
    """The checkpoint contents iff present and fingerprint-valid, else None.

    Payload arrays come back MEMORY-MAPPED (read-only views): the load
    itself is header reads + size checks in milliseconds, and bytes page
    in lazily where they are consumed — the device push, the daily strip
    assembly — straight from the OS cache with no intermediate copy."""
    prepared_dir = Path(prepared_dir)
    meta_path = prepared_dir / _META_FILE
    try:
        meta = json.loads(meta_path.read_text())
    except (OSError, ValueError):
        return None
    if meta.get("version") != _VERSION or meta.get("fingerprint") != fingerprint:
        return None
    try:
        b = {n: _load_payload(prepared_dir, f"base.{n}", meta)
             for n in _BASE_ARRAYS}
        d = {n: _load_payload(prepared_dir, f"daily.{n}", meta)
             for n in _DAILY_ARRAYS}
        base = DensePanel(
            values=b["values"],
            mask=b["mask"],
            months=np.asarray(b["months"]).view(
                f"datetime64[{meta['months_unit']}]"
            ),
            ids=b["ids"],
            var_names=[str(v) for v in b["var_names"]],
        )
        cd = CompactDaily(
            row_values=d["row_values"],
            row_pos=d["row_pos"],
            offsets=d["offsets"],
            ids=d["ids"],
            mkt=d["mkt"],
            mkt_present=d["mkt_present"],
            days=np.asarray(d["days"]).view(f"datetime64[{meta['days_unit']}]"),
            day_month_id=d["day_month_id"],
            week_id=d["week_id"],
            n_weeks=int(meta["n_weeks"]),
            week_month_id=d["week_month_id"],
            n_months=int(meta["n_months"]),
        )
    except (CorruptArtifactError, KeyError, ValueError, OSError) as exc:
        import warnings

        warnings.warn(
            f"prepared-inputs checkpoint unreadable, rebuilding: {exc!r}",
            stacklevel=2,
        )
        return None
    return base, cd
