"""Prepared-inputs checkpoint: skip the host ingest path on warm runs.

At real 1964-2013 CRSP shape, ~98 s of the end-to-end wall-clock is
host-side pandas/parquet work a TPU cannot touch: reading the 77M-row daily
parquet, the common-stock/exchange universe filter, the monthly relational
transforms, the long→compact daily ingest, and the long→dense monthly
scatter (BENCH_r03/r04 ``real_pipeline_stage_s``). All of it is a pure
function of the five raw cache files (plus the compute dtype and the
INCLUDE_TURNOVER column set), so the pipeline checkpoints its two host
products:

- ``dense_base.npz``    — the scattered dense monthly base panel
  (``panel.dense.DensePanel`` over BASE_COLUMNS + is_nyse): the direct
  input to the device characteristic engine. v1 stored the merged long
  frame instead and re-scattered it every warm run (~11 s at real shape);
  the dense base is the same information one stage later, host-numpy at
  capture time (no device pull to save it), and loads in the time the
  parquet read alone used to take.
- ``compact_daily.npz`` — the per-firm compacted daily strips + the
  shared calendar vectors (``panel.daily.CompactDaily``): the input to the
  daily vol/beta kernels.

A warm run loads these two files (IO-bound, seconds) instead of redoing the
ingest, which is the difference between the <60 s north-star budget being
reachable and not. This extends the reference's cache-as-checkpoint role
(``/root/reference/src/utils.py:183-218`` caches raw pulls; every transform
recomputes each run) one stage further, the same way the task graph's
dense-panel npz does between build and report stages.

Validity is a fingerprint over the raw files' (name, size, mtime) plus the
compute dtype, a caller salt (the resolved INCLUDE_TURNOVER flag — it
changes the base column set), and a layout version — the make-style
staleness contract: any re-pull or re-generation of the raw caches
invalidates the checkpoint. One slot per raw directory
(``<raw_dir>/_prepared/``), overwritten in place; ``meta.json`` is written
last (tmp + rename), so a crashed writer leaves a stale fingerprint, never
a half-valid checkpoint. Set ``PREPARED_CACHE=0`` to disable both reading
and writing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from fm_returnprediction_tpu.panel.daily import CompactDaily
from fm_returnprediction_tpu.panel.dense import DensePanel

__all__ = [
    "PREPARED_DIRNAME",
    "prepared_enabled",
    "raw_fingerprint",
    "save_prepared",
    "load_prepared",
]

PREPARED_DIRNAME = "_prepared"
# Bump when the prepared LAYOUT or the ingest semantics feeding it change —
# an old checkpoint must not satisfy a new pipeline. v2: dense base panel
# replaced the merged long frame (long_to_dense moved inside the
# checkpoint boundary).
_VERSION = 2

_BASE_FILE = "dense_base.npz"
_DAILY_FILE = "compact_daily.npz"
_META_FILE = "meta.json"


def prepared_enabled() -> bool:
    """The PREPARED_CACHE switch (default on), env/.env overridable."""
    from fm_returnprediction_tpu.settings import config

    return bool(int(config("PREPARED_CACHE")))


def raw_fingerprint(raw_dir, dtype, salt: str = "") -> str:
    """Staleness key for the checkpoint under ``raw_dir``.

    Hashes each raw cache file's (name, size, mtime_ns) — the make
    contract: content re-reads would cost a large fraction of what the
    checkpoint saves. ``dtype`` is in the key because the payload arrays
    are materialized in the compute dtype; ``salt`` carries caller
    settings that change the payload layout (the resolved INCLUDE_TURNOVER
    flag, which adds a base column).
    """
    from fm_returnprediction_tpu.pipeline import RAW_FILE_NAMES

    h = hashlib.sha256()
    h.update(f"v{_VERSION}|{np.dtype(dtype).str}|{salt}".encode())
    for name in sorted(RAW_FILE_NAMES.values()):
        path = Path(raw_dir) / name
        st = path.stat()  # missing raw file: let the error surface here
        h.update(f"|{name}:{st.st_size}:{st.st_mtime_ns}".encode())
    return h.hexdigest()


def save_prepared(
    prepared_dir, fingerprint: str, base: DensePanel, cd: CompactDaily
) -> None:
    """Write the checkpoint; meta (with the fingerprint) goes LAST so a
    partial write is indistinguishable from a stale one. Failures degrade to
    a warning — the checkpoint is an accelerant, never a correctness gate.

    Both payloads are savez UNcompressed: they are hundreds of MB of
    near-incompressible floats at real shape, and zlib would cost more
    than the ingest the checkpoint skips."""
    prepared_dir = Path(prepared_dir)
    try:
        prepared_dir.mkdir(parents=True, exist_ok=True)
        meta = prepared_dir / _META_FILE
        meta.unlink(missing_ok=True)  # invalidate before touching payloads
        # drop the v1 payload a version upgrade orphans (~0.2 GB at real
        # shape); nothing references it once meta is v2
        (prepared_dir / "monthly_merged.parquet").unlink(missing_ok=True)
        months_unit = np.datetime_data(base.months.dtype)[0]
        np.savez(
            prepared_dir / _BASE_FILE,
            values=np.asarray(base.values),
            mask=np.asarray(base.mask),
            months=base.months.astype(np.int64),
            ids=np.asarray(base.ids),
            # fixed-width unicode, NOT object dtype: loadable with
            # allow_pickle off (no pickle surface in a shared artifact)
            var_names=np.asarray(base.var_names, dtype=np.str_),
        )
        arrays = {
            f.name: getattr(cd, f.name)
            for f in dataclasses.fields(cd)
            if isinstance(getattr(cd, f.name), np.ndarray)
        }
        # datetime64 won't survive npz without a unit side-channel
        days_unit = np.datetime_data(cd.days.dtype)[0]
        arrays["days"] = cd.days.astype(np.int64)
        np.savez(prepared_dir / _DAILY_FILE, **arrays)
        tmp = meta.with_suffix(f".tmp{os.getpid()}")  # per-writer tmp name
        tmp.write_text(json.dumps({
            "fingerprint": fingerprint,
            "version": _VERSION,
            "months_unit": months_unit,
            "days_unit": days_unit,
            "n_weeks": cd.n_weeks,
            "n_months": cd.n_months,
        }))
        os.replace(tmp, meta)
    except OSError as exc:  # read-only raw dir, disk full, ...
        import warnings

        warnings.warn(f"prepared-inputs checkpoint not written: {exc!r}",
                      stacklevel=2)


def load_prepared(
    prepared_dir, fingerprint: str
) -> Optional[Tuple[DensePanel, CompactDaily]]:
    """The checkpoint contents iff present and fingerprint-valid, else None."""
    prepared_dir = Path(prepared_dir)
    meta_path = prepared_dir / _META_FILE
    try:
        meta = json.loads(meta_path.read_text())
    except (OSError, ValueError):
        return None
    if meta.get("version") != _VERSION or meta.get("fingerprint") != fingerprint:
        return None
    try:
        with np.load(prepared_dir / _BASE_FILE, allow_pickle=False) as z:
            base = DensePanel(
                values=z["values"],
                mask=z["mask"],
                months=z["months"].astype(
                    f"datetime64[{meta['months_unit']}]"
                ),
                ids=z["ids"],
                var_names=[str(v) for v in z["var_names"]],
            )
        with np.load(prepared_dir / _DAILY_FILE, allow_pickle=False) as z:
            cd = CompactDaily(
                row_values=z["row_values"],
                row_pos=z["row_pos"],
                offsets=z["offsets"],
                ids=z["ids"],
                mkt=z["mkt"],
                mkt_present=z["mkt_present"],
                days=z["days"].astype(f"datetime64[{meta['days_unit']}]"),
                day_month_id=z["day_month_id"],
                week_id=z["week_id"],
                n_weeks=int(meta["n_weeks"]),
                week_month_id=z["week_month_id"],
                n_months=int(meta["n_months"]),
            )
    except (OSError, KeyError, ValueError) as exc:
        import warnings

        warnings.warn(
            f"prepared-inputs checkpoint unreadable, rebuilding: {exc!r}",
            stacklevel=2,
        )
        return None
    return base, cd
