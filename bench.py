"""North-star benchmark: full-panel Fama-MacBeth + 10k block bootstrap.

Workload (BASELINE.json): a full-scale synthetic Lewellen panel — 720 months
(1964-2023) × 6,000 firm slots × 14 predictors — run through all three
Lewellen models over three size universes (9 FM sweeps, the reference's
~5,400 serial statsmodels fits, SURVEY §3.4) plus a 10,000-replicate
moving-block bootstrap of the Model-3 slope series. The reference publishes
no wall-clock numbers (BASELINE.md), so ``vs_baseline`` is measured against
the driver-set 60 s north-star budget: value >1 means faster than target.

Prints ONE JSON line:
    {"metric": "...", "value": <seconds>, "unit": "s", "vs_baseline": <60/s>}

Env knobs (for CPU smoke runs): FMRP_BENCH_MONTHS / _FIRMS / _REPLICATES.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def _make_panel(t, n, p, dtype=np.float32, seed=2014):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(dtype)
    beta = (rng.standard_normal(p) * 0.05).astype(dtype)
    y = (x @ beta + 0.15 * rng.standard_normal((t, n))).astype(dtype)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(dtype)
    # Three nested universes (All / All-but-tiny / Large), as NYSE-breakpoint
    # subsets are downstream masks of the same panel (calc_Lewellen_2014.py:44).
    size = rng.random(n)
    subsets = [mask, mask & (size > 0.4)[None, :], mask & (size > 0.7)[None, :]]
    return y, x, subsets


def main() -> None:
    import jax
    import jax.numpy as jnp

    from fm_returnprediction_tpu.settings import enable_compilation_cache

    enable_compilation_cache()

    from fm_returnprediction_tpu.models.lewellen import MODELS
    from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
    from fm_returnprediction_tpu.parallel import block_bootstrap_se, make_mesh

    t = int(os.environ.get("FMRP_BENCH_MONTHS", 720))
    n = int(os.environ.get("FMRP_BENCH_FIRMS", 6000))
    b = int(os.environ.get("FMRP_BENCH_REPLICATES", 10_000))
    p = 14

    y, x, subsets = _make_panel(t, n, p)
    y = jnp.asarray(y)
    x = jnp.asarray(x)
    subsets = [jnp.asarray(s) for s in subsets]
    n_models = len(MODELS)
    model_sizes = [len(m.predictors) for m in MODELS]  # 3, 7, 14

    n_dev = len(jax.devices())
    mesh = make_mesh(axis_name="boot") if n_dev > 1 else None

    fm_jit = jax.jit(fama_macbeth, static_argnames=("solver",))

    def sweep():
        results = []
        for k in model_sizes:
            for sub in subsets:
                cs, summary = fm_jit(y, x[..., :k], sub, solver="normal")
                results.append((cs, summary))
        cs3 = results[-1][0]  # Model 3, Large — bootstrap target
        slope_valid = cs3.month_valid[:, None] & jnp.isfinite(cs3.slopes)
        boot = block_bootstrap_se(
            cs3.slopes, slope_valid, jax.random.key(0), n_replicates=b, mesh=mesh
        )
        return results, boot

    # Warm-up: compile everything once (first TPU compile is ~20-40 s and is
    # not part of the steady-state metric; the reference re-runs its pipeline
    # on cached data the same way).
    results, boot = sweep()
    jax.block_until_ready(boot.se)

    start = time.perf_counter()
    results, boot = sweep()
    jax.block_until_ready([boot.se] + [s.coef for _, s in results])
    elapsed = time.perf_counter() - start

    budget = 60.0
    print(
        json.dumps(
            {
                "metric": f"fm_{n_models}models_3subsets_{b}boot_T{t}_N{n}_wall_s",
                "value": round(elapsed, 4),
                "unit": "s",
                "vs_baseline": round(budget / elapsed, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
