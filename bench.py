"""North-star benchmark — honest end-to-end + kernel + scale metrics.

Headline metric (the ``value`` field): WARM wall-clock of the full synthetic
pipeline at REAL 1964-2013 CRSP shape (~600 months × ~22k permnos, ~77M
firm-day rows) — relational transforms, dense panel build, daily vol/beta
stage, all three Lewellen models over three size universes (9 FM sweeps),
Table 1, Table 2, Figure 1 cross-sections, and decile sorts — the workload
the north-star budget describes ("full panel … < 60 s", BASELINE.json).
``vs_baseline`` is the 60 s budget over that number (>1 = faster than
target; the reference publishes no wall-clock numbers, BASELINE.md).
``*_stage_s`` breakdowns attribute the wall-clock to pipeline stages
(round-2 VERDICT items 3/5: no more unexplained totals).

The ``extra`` dict carries the supporting evidence the headline used to
over-claim without (round-1 VERDICT "What's weak" #1-2):

- ``pipeline_cold_s``        — same pipeline including jit compiles.
- ``kernel_fm_boot_warm_s``  — the 9-sweep FM + 10k-replicate block
  bootstrap alone on a prebuilt device panel (the round-1 headline).
- ``daily_fullscale_*``      — the daily stage at REAL 1964-2013 CRSP shape
  (~12.6k trading days × 25k permnos, ~85M firm-day rows at realistic
  lifetimes) through the compact-ingest chunked driver: the "runs on real
  CRSP scale on one chip" demonstration.
- ``rolling_std_pallas_ms`` / ``rolling_std_xla_ms`` — the fused pallas
  kernel vs the XLA cumsum path on a (12608, 4096) strip, recording the
  speedup claimed at ``ops/rolling.py`` (TPU only; route-disclosing
  structured skip on CPU).
- ``kernels_*``              — the raw-kernel ladder (ISSUE 11): the
  MXU-tiled pallas Gram contraction vs the XLA oracle, the bf16
  contraction route with its promotion disclosure, the fused rolling
  sum/mean/std family, cold-ingest overlap (serial vs prefetched chunked
  read), per-kernel roofline-utilization gauges from the cost ledger, and
  a warm repeat under ``recompile_watch``.
- ``specgrid_*``             — the spec-grid subsystem: the Table-2-shaped
  3×3 grid from Gram sufficient statistics (one fused program) vs the
  per-cell batched-QR route, with compiled-program/referee counts and the
  Gram-vs-stacked footprint estimates.
- ``specgrid_scale_*``       — the pod-scale tile engine: a 1e3→1e5
  cell-count ladder through the lazy CellSpace tiling and the streaming
  top-k sink, ``cells_per_s`` per rung (higher-is-better series), warm
  repeats under ``recompile_watch``, and the tracemalloc peak vs the
  one-tile memory bound.
- ``grid_factorized_*`` / ``grid_boot_*`` — the month-axis reuse layer
  (ISSUE 14): factorized (unique-pair) vs legacy (per-spec) contraction
  cells/s at the same window-swept shape with the contraction-work
  ledger's pairs-vs-specs disclosure, device vs host bootstrap-draw
  aggregation, frame parity pins, and the Gram-bank build/query leg
  (new-window + new-bootstrap scenario queries answered with zero
  (T, N, P) panel reads).

All timings synchronize by pulling a result to the host (``np.asarray``
or a scalar device-side reduction), not ``block_until_ready`` alone — on
the tunneled axon backend the latter has been observed to return before
execution completes, which is exactly the over-claim this bench exists to
avoid. (History: BENCH_r01's 3.1 ms "kernel" figure for the same
T720_N6000_B10000 sweep was a dispatch-only measurement artifact — no
execution barrier — superseded by the honest sync here; the ~600x gap
between r01 and r02 kernel numbers is that artifact, not a regression.)

Prints ONE JSON line. Env knobs: FMRP_BENCH_FAST=1 shrinks every shape for
CPU smoke runs; FMRP_BENCH_MONTHS/_FIRMS/_REPLICATES (kernel),
FMRP_BENCH_PIPE_MONTHS/_FIRMS (pipeline), FMRP_BENCH_DAILY=0 (skip the
full-scale daily stage).
"""

from __future__ import annotations

import functools
import json
import os
import threading
import time
from contextlib import nullcontext

import numpy as np

from fm_returnprediction_tpu.telemetry import timed as _timed

# Section timing goes through the telemetry span API (`timed`): one
# implementation instead of a re-derived perf_counter pair per section,
# and a bench run under FMRP_TRACE_DIR exports its own sections as spans.

# The live full-scale child pipeline, if any (CPU rescue or mesh8) —
# published so the deadline watchdog can kill it before hard-exiting the
# parent: an orphaned real-shape run would burn the host into the next
# round's measurements.
_CHILD_PROC = None


def _make_panel(t, n, p, dtype=np.float32, seed=2014):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, n, p)).astype(dtype)
    beta = (rng.standard_normal(p) * 0.05).astype(dtype)
    y = (x @ beta + 0.15 * rng.standard_normal((t, n))).astype(dtype)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(dtype)
    # Three nested universes (All / All-but-tiny / Large), as NYSE-breakpoint
    # subsets are downstream masks of the same panel (calc_Lewellen_2014.py:44).
    size = rng.random(n)
    subsets = [mask, mask & (size > 0.4)[None, :], mask & (size > 0.7)[None, :]]
    return y, x, subsets


def _bench_kernel(fast: bool):
    """9-sweep FM + block bootstrap on a prebuilt device panel (cold+warm)."""
    import jax
    import jax.numpy as jnp

    from fm_returnprediction_tpu.models.lewellen import MODELS
    from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
    from fm_returnprediction_tpu.parallel import block_bootstrap_se, make_mesh

    t = int(os.environ.get("FMRP_BENCH_MONTHS", 120 if fast else 720))
    n = int(os.environ.get("FMRP_BENCH_FIRMS", 500 if fast else 6000))
    b = int(os.environ.get("FMRP_BENCH_REPLICATES", 200 if fast else 10_000))
    p = 14

    y, x, subsets = _make_panel(t, n, p)
    y = jnp.asarray(y)
    x = jnp.asarray(x)
    subsets = [jnp.asarray(s) for s in subsets]
    model_sizes = [len(m.predictors) for m in MODELS]  # 3, 7, 14

    mesh = make_mesh(axis_name="boot") if len(jax.devices()) > 1 else None
    fm_jit = jax.jit(fama_macbeth, static_argnames=("solver",))

    # The library-default solver (TSQR-compressed "qr" from round 3 on) so
    # the kernel number measures the PRODUCTION parity path; earlier rounds'
    # kernel figures used the Gram "normal" fast path and are not directly
    # comparable.
    def sweep():
        results = []
        for k in model_sizes:
            for sub in subsets:
                cs, summary = fm_jit(y, x[..., :k], sub)
                results.append(summary)
        cs3, _ = fm_jit(y, x, subsets[-1])
        slope_valid = cs3.month_valid[:, None] & jnp.isfinite(cs3.slopes)
        boot = block_bootstrap_se(
            cs3.slopes, slope_valid, jax.random.key(0), n_replicates=b, mesh=mesh
        )
        # host pull = true execution barrier
        return np.asarray(boot.se), [np.asarray(s.coef) for s in results]

    with _timed("bench.kernel_cold") as cold:
        sweep()
    with _timed("bench.kernel_warm") as warm:
        sweep()
    return {"kernel_fm_boot_cold_s": round(cold.s, 4),
            "kernel_fm_boot_warm_s": round(warm.s, 4),
            "kernel_shape": f"T{t}_N{n}_B{b}"}


def _run_pipeline_timed(raw_dir, warm_label=None):
    """One pipeline run → (wall seconds, per-stage seconds).

    Enables the persistent compilation cache HERE, not only in ``main``:
    this helper is also the entry the CPU-rescue and mesh8 CHILD processes
    call, and cross-process compile reuse (the per-cell reporting
    programs) only happens if every process points at the same
    ``_cache/jax``.

    ``warm_label`` declares the run WARM to the recompile sentinel
    (``telemetry.recompile_watch``): persistent-cache growth during a warm
    run means something recompiled that should have been reused — r05 saw
    the cache grow 83→84 on the "warm" run with no attribution — and now
    counts into ``fmrp_unexpected_recompiles_total`` and warns with the
    ledger's culprit programs. The per-stage dict also carries the stages
    the run explicitly SKIPPED (``{"skipped": reason}`` instead of an
    absent key or a 0.0 that reads as free)."""
    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.pipeline import run_pipeline
    from fm_returnprediction_tpu.settings import enable_compilation_cache

    enable_compilation_cache()
    with telemetry.recompile_watch(
        warm_label or "pipeline_run", warm=warm_label is not None
    ) as cache_delta:
        with _timed("bench.pipeline_run") as wall:
            res = run_pipeline(
                raw_data_dir=raw_dir, make_figure=True,
                make_deciles=True, compile_pdf=False, output_dir=None,
            )
    stages = {k: round(v, 3) for k, v in res.timer.durations.items()}
    stages.update(
        {k: {"skipped": v} for k, v in res.timer.skipped.items()}
    )
    if warm_label is not None and cache_delta.grew:
        stages["unexpected_recompiles"] = {
            "cache_entries_grew": cache_delta.grew,
            "culprits": list(cache_delta.culprits) or ["unattributed-jit"],
        }
    return wall.s, stages


def _bench_pipeline(fast: bool):
    """Full pipeline from cached parquet, cold (compiles) and warm.

    Synthetic data generation is NOT in the timed region: it is written to a
    parquet cache first and the pipeline loads it like the reference loads
    its WRDS cache (``src/calc_Lewellen_2014.py:1236-1240``) — the
    north-star workload is "cached raw data → tables", not fixture
    generation."""
    import tempfile

    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        write_synthetic_cache,
    )

    t = int(os.environ.get("FMRP_BENCH_PIPE_MONTHS", 120 if fast else 600))
    n = int(os.environ.get("FMRP_BENCH_PIPE_FIRMS", 100 if fast else 800))

    with tempfile.TemporaryDirectory() as raw_dir:
        write_synthetic_cache(raw_dir, SyntheticConfig(n_firms=n, n_months=t))
        cold, _ = _run_pipeline_timed(raw_dir)
        warm, stages = _run_pipeline_timed(raw_dir, warm_label="pipeline_warm")
    return {"pipeline_cold_s": round(cold, 4),
            "pipeline_warm_s": round(warm, 4),
            "pipeline_stage_s": stages,
            "pipeline_shape": f"T{t}_N{n}"}


def _bench_pipeline_real(fast: bool):
    """END-TO-END pipeline at real 1964-2013 CRSP shape (round-2 VERDICT
    item 3): ~600 months × ~22k permnos with realistic lifetimes → ~77M
    firm-day rows through compact ingest, all 9 FM sweeps, tables, figure,
    deciles. The per-stage breakdown names the wall-clock owner.

    The generated universe is cached under ``_cache/`` (gitignored), so
    only the first run on a machine pays generation. FMRP_BENCH_REAL=0
    skips; FMRP_BENCH_REAL_FIRMS/_MONTHS resize."""
    if fast or os.environ.get("FMRP_BENCH_REAL", "1") == "0":
        return {}
    from fm_returnprediction_tpu.data.benchscale import write_benchscale_cache

    t = int(os.environ.get("FMRP_BENCH_REAL_MONTHS", 600))
    n = int(os.environ.get("FMRP_BENCH_REAL_FIRMS", 22000))
    # parse BEFORE the expensive runs: a malformed value must fail fast,
    # not throw away a completed full-scale cold measurement
    budget = float(os.environ.get("FMRP_BENCH_REAL_BUDGET_S", 1500))
    # Honest stage attribution: JAX dispatch is async, so without barriers
    # whichever stage first pulls to host absorbs every queued upstream
    # device computation (r4's artifact charged Table 1 47 s at real shape;
    # its true warm compute is ~5 s). The barriers cost ~a round trip per
    # coarse stage — disclosed here rather than silently skewing the
    # breakdown (utils.timing.stage_sync).
    os.environ.setdefault("FMRP_SYNC_STAGES", "1")
    raw_dir = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "_cache", f"benchscale_T{t}_N{n}"
    )
    t0 = time.perf_counter()
    write_benchscale_cache(raw_dir, n_permnos=n, n_months=t)
    gen = time.perf_counter() - t0

    # Honest cold semantics: "cold" is what a first-time user pays, so the
    # prepared-inputs checkpoint (data.prepared) must not carry over from a
    # previous bench run — clear it; the cold run then ingests from raw AND
    # writes the checkpoint, and the warm run exercises it (the production
    # repeat-run path).
    import shutil

    from fm_returnprediction_tpu.data.prepared import PREPARED_DIRNAME

    shutil.rmtree(os.path.join(raw_dir, PREPARED_DIRNAME), ignore_errors=True)

    try:
        cold, cold_stages = _run_pipeline_timed(raw_dir)
    except Exception as exc:  # noqa: BLE001 - backend fault → disclosed rescue
        # Observed r04 run 1: a remote-compile failure killed the real-shape
        # section mid-run and the round recorded NO real-shape number while
        # the host was perfectly able to produce a disclosed CPU one. After
        # a backend fault the in-process JAX client is wedged, so the rescue
        # runs in a FRESH subprocess, CPU-pinned and with the relay-dialing
        # sitecustomize dropped from PYTHONPATH (it blocks interpreter
        # start-up when the tunnel grant is down).
        rescue = _real_cpu_rescue(raw_dir, budget)
        rescue["real_pipeline_gen_s"] = round(gen, 2)
        rescue["real_pipeline_shape"] = f"T{t}_N{n}"
        rescue["real_pipeline_accel_error"] = repr(exc)[:300]
        rescue["real_pipeline_accel_error_frames"] = _error_frames(exc)
        return rescue
    out = {
        "real_pipeline_cold_s": round(cold, 4),
        "real_pipeline_gen_s": round(gen, 2),
        "real_pipeline_shape": f"T{t}_N{n}",
        "real_pipeline_sync_stages":
            os.environ.get("FMRP_SYNC_STAGES") == "1",
    }
    # Soft budget: on a slow interconnect a second full-scale run can blow
    # the driver's bench window — better a recorded cold number + breakdown
    # than a timeout that loses the whole artifact.
    # the cold breakdown is evidence in its own right: it shows the raw
    # ingest + checkpoint write the warm run then skips
    out["real_pipeline_cold_stage_s"] = cold_stages
    if cold <= budget:
        try:
            warm, stages = _run_pipeline_timed(
                raw_dir, warm_label="real_pipeline_warm"
            )
        except Exception as exc:  # noqa: BLE001 - keep the completed cold
            # a fault in the warm repeat must not throw away the completed
            # full-scale cold measurement (the invariant stated above); the
            # cold number is a genuine accelerator result, so no CPU rescue
            # — the headline falls back to it
            out["real_pipeline_warm_error"] = repr(exc)[:300]
            out["real_pipeline_warm_error_frames"] = _error_frames(exc)
            return out
        out["real_pipeline_warm_s"] = round(warm, 4)
        out["real_pipeline_stage_s"] = stages
    else:
        out["real_pipeline_warm_skipped"] = f"cold {cold:.0f}s > budget {budget:.0f}s"
    return out


def _error_frames(exc: BaseException) -> list:
    """Deepest repo-local traceback frames (fall back to the raw tail).

    The ONE home for failure attribution — used by ``main``'s section
    handler and the real-section rescue alike (r04 run 1: a remote-compile
    500 was unattributable from the exception repr alone)."""
    import traceback

    repo_root = os.path.dirname(os.path.abspath(__file__))
    tb = traceback.extract_tb(exc.__traceback__)
    frames = [
        f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno}:{f.name}"
        for f in tb
        if f.filename.startswith(repo_root)
        or "fm_returnprediction" in f.filename
    ] or [f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno}:{f.name}" for f in tb]
    return frames[-6:]


def _child_env(repo_root: str) -> dict:
    """Env for a CPU-pinned child: drop relay-dialing sitecustomize dirs
    from PYTHONPATH (same idiom as tests/test_graft_entry.py) but keep any
    other entries the deployment needs, and put the repo root first."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    parts = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))
    ]
    env["PYTHONPATH"] = os.pathsep.join([repo_root, *parts])
    return env


def _real_cpu_rescue(raw_dir: str, budget: float) -> dict:
    """Disclosed CPU re-run of the real-shape pipeline after a backend fault.

    One run in a fresh CPU-pinned subprocess (the in-process client is
    wedged after a backend fault). The result is keyed warm vs cold by
    whether the child actually hit the prepared-inputs checkpoint, and
    labelled ``real_pipeline_device: cpu-fallback``; ``main`` additionally
    renames the headline metric so the artifact can never pass a host
    number off as an accelerator one."""
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.abspath(__file__))
    # no import-time side channel: pass raw_dir via argv
    child = (
        "import json, sys, bench\n"
        "wall, stages = bench._run_pipeline_timed(sys.argv[1])\n"
        "print('RESCUE ' + json.dumps({'wall': wall, 'stages': stages}))\n"
    )
    global _CHILD_PROC
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", child, raw_dir],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=_child_env(repo_root), cwd=repo_root,
        )
        # published so the deadline watchdog can kill the child before
        # hard-exiting — an orphaned full-scale CPU run would otherwise
        # burn the host for up to `budget` seconds into the next round
        _CHILD_PROC = proc
        try:
            stdout, stderr = proc.communicate(timeout=budget)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            return {"real_pipeline_rescue_error":
                    f"rescue exceeded budget {budget:.0f}s"}
        finally:
            _CHILD_PROC = None
        line = [l for l in stdout.splitlines() if l.startswith("RESCUE ")]
        if proc.returncode != 0 or not line:
            return {"real_pipeline_rescue_error": (stderr or stdout)[-300:]}
        got = json.loads(line[-1][len("RESCUE "):])
    except Exception as exc:  # noqa: BLE001 - rescue is best-effort
        return {"real_pipeline_rescue_error": repr(exc)[:300]}
    # warm only if the child really took the checkpoint path — a fault
    # before save_prepared leaves no checkpoint and the child pays the full
    # cold ingest, which must not masquerade as the repeat-run number. The
    # timer records the load_prepared ATTEMPT even on a miss, so the
    # discriminator is the raw ingest not actually RUNNING (on a
    # checkpoint hit it appears as an explicit {"skipped": ...} entry
    # rather than being absent). Both ingest routes count: the legacy
    # route records load_raw_data, the columnar route streams its reads
    # inside panel/monthly_ingest.
    warm_like = not any(
        isinstance(got["stages"].get(k), (int, float))
        for k in ("load_raw_data", "panel/monthly_ingest")
    )
    kind = "warm" if warm_like else "cold"
    stage_key = ("real_pipeline_stage_s" if warm_like
                 else "real_pipeline_cold_stage_s")
    return {
        f"real_pipeline_{kind}_s": round(got["wall"], 4),
        stage_key: _round_stages(got["stages"]),
        "real_pipeline_device": "cpu-fallback",
    }


def _round_stages(stages: dict) -> dict:
    """Round the numeric stage entries; skip markers pass through."""
    return {
        k: round(v, 3) if isinstance(v, (int, float)) else v
        for k, v in stages.items()
    }


def _bench_panel_build(fast: bool):
    """Panel-build routes head to head: columnar vs legacy ingest.

    The tentpole evidence for the device-resident columnar panel build
    (ISSUE 7): both routes ingest the SAME benchscale cache cold (raw
    parquet → enriched device panel, prepared checkpoint disabled so the
    ingest actually runs), recording per-stage wall and raw-rows/s
    throughput (``*_rows_per_s`` — a higher-is-better series for the
    perf-regression sentinel), then repeat warm under ``recompile_watch``
    so any re-trace of the new jitted panel programs (the fused
    characteristics+winsorize program, the gather-reconstruction daily
    strips) is flagged and counted into
    ``fmrp_unexpected_recompiles_total``. FMRP_BENCH_PANEL=0 skips;
    FMRP_BENCH_PANEL_MONTHS/_FIRMS resize (default a mid shape — the
    real-shape section already times the default route end to end)."""
    if os.environ.get("FMRP_BENCH_PANEL", "1") == "0":
        return {}
    from fm_returnprediction_tpu import settings, telemetry
    from fm_returnprediction_tpu.data.benchscale import write_benchscale_cache
    from fm_returnprediction_tpu.pipeline import load_or_build_panel, resolve_dtype
    from fm_returnprediction_tpu.utils.timing import StageTimer

    t = int(os.environ.get("FMRP_BENCH_PANEL_MONTHS", 60 if fast else 240))
    n = int(os.environ.get("FMRP_BENCH_PANEL_FIRMS", 400 if fast else 8000))
    os.environ.setdefault("FMRP_SYNC_STAGES", "1")  # honest attribution
    repo_root = os.path.dirname(os.path.abspath(__file__))
    raw_dir = os.path.join(repo_root, "_cache", f"benchscale_T{t}_N{n}")
    write_benchscale_cache(raw_dir, n_permnos=n, n_months=t)

    # raw-row volume from parquet metadata (free): the throughput
    # denominator counts what the ingest actually has to chew through
    import pyarrow.parquet as pq

    from fm_returnprediction_tpu.data.synthetic import FILE_NAMES

    raw_rows = sum(
        pq.ParquetFile(os.path.join(raw_dir, name)).metadata.num_rows
        for name in FILE_NAMES.values()
    )

    out = {"panel_build_shape": f"T{t}_N{n}", "panel_build_raw_rows": raw_rows}
    prev_route = os.environ.get("FMRP_PANEL_ROUTE")
    prev_prepared = settings.d.get("PREPARED_CACHE")
    try:
        settings.d["PREPARED_CACHE"] = 0  # measure the ingest, not the skip
        # Pre-warm the SHARED device programs (fused characteristics
        # program, daily strip kernels) with one untimed build: both
        # routes run the same programs at the same shapes, so whichever
        # route ran first would otherwise pay the traces/compiles inside
        # its "cold" number and flatter the other — this section compares
        # INGEST routes, so cold_s means ingest-cold / program-warm (the
        # real-shape section still measures true compile-cold).
        os.environ["FMRP_PANEL_ROUTE"] = "columnar"
        warm_panel, _ = load_or_build_panel(
            raw_dir, dtype=resolve_dtype(), timer=StageTimer()
        )
        np.asarray(warm_panel.values[0, 0])
        del warm_panel
        for route in ("columnar", "legacy"):
            os.environ["FMRP_PANEL_ROUTE"] = route
            timer = StageTimer()
            with _timed(f"bench.panel_build_{route}_cold") as cold:
                panel, _ = load_or_build_panel(
                    raw_dir, dtype=resolve_dtype(), timer=timer
                )
                np.asarray(panel.values[0, 0])  # host pull = barrier
            out[f"panel_build_{route}_cold_s"] = round(cold.s, 4)
            out[f"panel_build_{route}_stage_s"] = _round_stages({
                **timer.durations,
                **{k: {"skipped": v} for k, v in timer.skipped.items()},
            })
            out[f"panel_build_{route}_rows_per_s"] = round(raw_rows / cold.s, 1)
            # warm repeat: same ingest, programs already compiled — cache
            # growth here means a panel program re-traced and is flagged
            with telemetry.recompile_watch(
                f"panel_build_{route}_warm", warm=True
            ) as cache_delta:
                with _timed(f"bench.panel_build_{route}_warm") as warm:
                    panel, _ = load_or_build_panel(
                        raw_dir, dtype=resolve_dtype(), timer=StageTimer()
                    )
                    np.asarray(panel.values[0, 0])
            out[f"panel_build_{route}_warm_s"] = round(warm.s, 4)
            if cache_delta.grew:
                out[f"panel_build_{route}_warm_recompiles"] = {
                    "cache_entries_grew": cache_delta.grew,
                    "culprits": list(cache_delta.culprits) or ["unattributed-jit"],
                }
            del panel
    finally:
        if prev_route is None:
            os.environ.pop("FMRP_PANEL_ROUTE", None)
        else:
            os.environ["FMRP_PANEL_ROUTE"] = prev_route
        if prev_prepared is None:
            settings.d.pop("PREPARED_CACHE", None)
        else:
            settings.d["PREPARED_CACHE"] = prev_prepared
    return out


def _bench_daily_fullscale(fast: bool):
    """Daily vol+beta at real 1964-2013 CRSP shape via compact ingest."""
    from fm_returnprediction_tpu.ops.daily_chunked import (
        daily_characteristics_compact_chunked,
    )

    from fm_returnprediction_tpu.data.benchscale import flat_ranges

    d_days = 1024 if fast else 12608
    n_firms = 2000 if fast else 25000
    m = 60 if fast else 600
    rng = np.random.default_rng(0)
    counts = np.clip(rng.geometric(1 / max(d_days // 4, 1), n_firms), 60, d_days)
    r = int(counts.sum())
    starts = rng.integers(0, d_days - counts + 1)
    offsets = np.zeros(n_firms + 1, dtype=np.int64)
    np.cumsum(counts, out=offsets[1:])
    row_pos = flat_ranges(starts, counts)[0].astype(np.int16)
    args = dict(
        row_values=(rng.standard_normal(r) * 0.02).astype(np.float32),
        row_pos=row_pos,
        offsets=offsets,
        mkt_d=(rng.standard_normal(d_days) * 0.01).astype(np.float32),
        mkt_present=np.ones(d_days, bool),
        day_month_id=np.minimum(np.arange(d_days) // 21, m - 1).astype(np.int32),
        week_id=(np.arange(d_days) // 5).astype(np.int32),
        week_month_id=None,
        n_days=d_days,
        n_weeks=int(d_days // 5) + 1,
        n_months=m,
    )
    args["week_month_id"] = np.minimum(
        np.arange(args["n_weeks"]) // 4, m - 1
    ).astype(np.int32)

    with _timed("bench.daily_cold") as cold:
        daily_characteristics_compact_chunked(**args)
    with _timed("bench.daily_warm") as warm:
        daily_characteristics_compact_chunked(**args)
    out = {
        "daily_fullscale_cold_s": round(cold.s, 4),
        "daily_fullscale_warm_s": round(warm.s, 4),
        "daily_fullscale_rows": r,
        "daily_fullscale_rows_per_s": int(r / warm.s),
        "daily_shape": f"D{d_days}_N{n_firms}",
    }
    # In-situ pallas contribution (TPU only, where pallas is the default):
    # the same stage with the XLA cumsum vol path isolates what the fused
    # rolling-std kernel buys INSIDE the production chunked pipeline —
    # the number the weekly-beta-kernel decision needs (a beta pallas
    # variant only pays if the vol kernel's in-situ win is material).
    import jax

    if jax.devices()[0].platform == "tpu":
        daily_characteristics_compact_chunked(**args, use_pallas=False)
        t0 = time.perf_counter()
        daily_characteristics_compact_chunked(**args, use_pallas=False)
        out["daily_fullscale_warm_xla_s"] = round(time.perf_counter() - t0, 4)
    return out


def _bench_pallas(fast: bool):
    """Fused pallas rolling-moments kernel vs the XLA cumsum path (TPU only).

    Two shapes each round (round-4 VERDICT item 6: the default flipped ON
    from ONE shape's measurement): the original wide strip and the ACTUAL
    chunked production strip — D=12608 days × the ``auto_firm_chunk``
    width (``ops/daily_chunked.py``: ``(1<<25)//12608 // 128*128`` = 2560
    columns), the shape ``daily_characteristics_compact_chunked`` really
    dispatches at real scale."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform != "tpu":
        # a structured skip reason, not a silent null: a null in the
        # artifact reads as "measured nothing for unknown reasons", and
        # the regression sentinel can't tell it from a parse bug. The skip
        # also records WHICH route-knob resolution produced it — a TPU
        # round that silently fell back to XLA (FMRP_ROLLING_ROUTE=xla /
        # FMRP_PALLAS=0 left over in the environment) must be
        # distinguishable from a genuine CPU skip
        from fm_returnprediction_tpu.ops.rolling import resolve_rolling_route

        skip = _kernels_skip(
            jax.devices()[0].platform, resolve_rolling_route(),
            "FMRP_ROLLING_ROUTE", "FMRP_PALLAS",
        )
        return {"rolling_std_pallas_ms": skip, "rolling_std_xla_ms": skip}

    from fm_returnprediction_tpu.ops.rolling import rolling_std

    shapes = ([(1024, 512)] if fast
              else [(12608, 4096), (12608, 2560)])  # wide strip, prod strip
    out = {}
    rng = np.random.default_rng(0)
    for d, n in shapes:
        x = jnp.asarray((rng.standard_normal((d, n)) * 0.02).astype(np.float32))

        def run(use_pallas, x=x):
            # The timed region syncs by pulling a SCALAR device-side
            # reduction: pulling the full (D, N) result would time the
            # tunnel/PCIe transfer of ~200 MB, not the kernel (the r2
            # bench's 0.95x was polluted exactly this way). jnp.sum depends
            # on every output element, so the scalar pull is a true
            # execution barrier.
            f = jax.jit(
                lambda v: jnp.nansum(
                    rolling_std(v, 252, 100, use_pallas=use_pallas)
                )
            )
            float(f(x))  # compile + warm
            t0 = time.perf_counter()
            for _ in range(10):
                s = f(x)
            float(s)
            return (time.perf_counter() - t0) / 10 * 1000

        xla_ms = run(False)
        pallas_ms = run(True)
        suffix = "" if (d, n) == shapes[0] else f"_{d}x{n}"
        out.update({
            f"rolling_std_pallas_ms{suffix}": round(pallas_ms, 3),
            f"rolling_std_xla_ms{suffix}": round(xla_ms, 3),
            f"rolling_std_pallas_speedup{suffix}": round(xla_ms / pallas_ms, 2),
        })
    return out


def _kernels_skip(platform: str, resolved: str, *knob_envs: str) -> dict:
    """Structured TPU-only skip carrying the route-knob resolution that
    produced it — the ONE home for the disclosure contract (`_bench_pallas`
    and the kernels ladder share it): a TPU round that silently fell back
    to XLA via a leftover knob must be distinguishable from a genuine CPU
    skip."""
    route = {"resolved": resolved}
    for env in knob_envs:
        route[env] = os.environ.get(env)
    route["platform"] = platform
    return {
        "skipped": f"pallas kernel is TPU-only; device is {platform}",
        "route": route,
    }


def _bench_kernels(fast: bool):
    """The raw-kernel ladder (ISSUE 11): pallas vs XLA for the Gram
    contraction and the fused rolling family, the bf16 contraction route,
    and the overlapped cold ingest.

    - ``kernels_gram_*_ms`` / ``kernels_gram*_rows_per_s`` — the masked
      per-month Gram contraction at a small and a near-real shape: the
      XLA oracle, the pallas route (TPU; structured route-disclosing skip
      on CPU), and the bf16 route with its conditioning-referee promotion
      count (``kernels_gram_bf16_promoted_months``).
    - ``kernels_rolling_{std,sum,mean}_*`` — the fused rolling family at
      the production strip shape, both routes, ``*_melems_per_s``
      throughputs.
    - ``kernels_ingest_{serial,overlap}_s`` — the SAME chunked filtered
      parquet read with the prefetch queue off vs on: the measured
      cold-ingest overlap fact.
    - roofline-utilization gauges from the cost ledger for every AOT-timed
      kernel program (``*_roofline_utilization``), and one warm repeat of
      the whole ladder under ``recompile_watch`` so a re-trace in any
      kernel program is flagged (``kernels_warm_recompiles``).

    All ``*_ms``/``*_s`` keys are lower-is-better and ``*_per_s``/
    ``*speedup*`` higher-is-better under the regress sentinel's naming
    rules. FMRP_BENCH_KERNELS=0 skips the section.
    """
    if os.environ.get("FMRP_BENCH_KERNELS", "1") == "0":
        return {}
    import jax
    import jax.numpy as jnp

    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.ops.rolling import (
        resolve_rolling_route,
        rolling_mean,
        rolling_std,
        rolling_sum,
    )
    from fm_returnprediction_tpu.specgrid.grams import (
        contract_spec_grams,
        resolve_gram_route,
    )
    from fm_returnprediction_tpu.telemetry import perf as _perf

    platform = jax.devices()[0].platform
    out = {}
    reps = 2 if fast else 3
    warm_runners = []  # (label, thunk) — re-run under the recompile watch

    def _timed_ms(thunk, warm=True):
        if warm:
            thunk()
        t0 = time.perf_counter()
        for _ in range(reps):
            thunk()
        return (time.perf_counter() - t0) / reps * 1000

    # -- Gram contraction ladder -------------------------------------------
    rng = np.random.default_rng(7)
    gram_shapes = ([("", 40, 512, 6, 4)] if fast
                   else [("", 60, 1024, 6, 4), ("_real", 240, 8192, 14, 9)])
    gram_route = resolve_gram_route()
    out["kernels_gram_route"] = gram_route
    # shape disclosures: the regress sentinel qualifies every series by its
    # section's ``*_shape`` sibling, so a fast-mode round never gates a
    # full-shape round (each family gets its own key; the gram value joins
    # both ladder rungs — any rung resizing separates the whole family)
    out["kernels_gram_shape"] = "+".join(
        f"T{t}_N{n}_P{p}_S{s}" for _, t, n, p, s in gram_shapes
    )
    for sfx, t, n, p, s in gram_shapes:
        x = rng.standard_normal((t, n, p)).astype(np.float32)
        x[rng.random(x.shape) < 0.1] = np.nan
        y = np.where(rng.random((t, n)) > 0.15,
                     rng.standard_normal((t, n)), np.nan).astype(np.float32)
        universes = rng.random((2, t, n)) > 0.3
        args = tuple(jnp.asarray(a) for a in (
            y, x, universes, np.arange(s) % 2,
            rng.random((s, p)) > 0.3, np.ones((s, t), bool),
        ))

        def _runner(program, **static):
            exe = _perf.timed_aot_compile(
                contract_spec_grams, *args, program=program, **static
            )
            def run(exe=exe, args=args):
                np.asarray(exe(*args).n)  # host pull = execution barrier
            return run

        variants = [(f"kernels_gram_xla{sfx}", dict(route="xla")),
                    (f"kernels_gram_bf16{sfx}",
                     dict(route=gram_route, precision="bf16"))]
        if platform == "tpu":
            variants.insert(1, (f"kernels_gram_pallas{sfx}",
                                dict(route="pallas")))
        else:
            out[f"kernels_gram_pallas{sfx}_ms"] = _kernels_skip(
                platform, gram_route, "FMRP_GRAM_ROUTE"
            )
        ms_of = {}
        for program, static in variants:
            run = _runner(program, **static)
            ms = _timed_ms(run)
            ms_of[program] = ms
            out[f"{program}_ms"] = round(ms, 3)
            roof = _perf.record_runtime(program, ms / 1000)
            if roof:
                out[f"{program}_roofline_utilization"] = round(
                    roof["roofline_utilization"], 6
                )
            warm_runners.append((program, run))
        if platform == "tpu":
            out[f"kernels_gram_pallas{sfx}_speedup"] = round(
                ms_of[f"kernels_gram_xla{sfx}"]
                / ms_of[f"kernels_gram_pallas{sfx}"], 2)
        out[f"kernels_gram_bf16{sfx}_speedup"] = round(
            ms_of[f"kernels_gram_xla{sfx}"]
            / ms_of[f"kernels_gram_bf16{sfx}"], 2)
        # throughput of the route a production sweep would take here
        prod = (f"kernels_gram_pallas{sfx}" if platform == "tpu"
                else f"kernels_gram_xla{sfx}")
        out[f"kernels_gram{sfx}_rows_per_s"] = round(
            t * n * s / (ms_of[prod] / 1000), 1
        )
        if sfx == "":
            # bf16 promotion disclosure on the small shape: how many
            # (spec, month) systems the conditioning referee flags for
            # promotion back to full precision
            from fm_returnprediction_tpu.specgrid.solve import (
                solve_spec_stats,
            )

            stats = contract_spec_grams(
                *args, route="xla", precision="bf16"
            )
            sel_aug = jnp.concatenate(
                [jnp.ones((s, 1), bool), args[4]], axis=1
            )
            sol = solve_spec_stats(
                stats, sel_aug,
                contracted_eps=float(jnp.finfo(jnp.bfloat16).eps),
            )
            out["kernels_gram_bf16_promoted_months"] = int(
                np.asarray(sol.suspect).sum()
            )

    # -- fused rolling family at the production strip shape ----------------
    d_days, n_cols = (1024, 512) if fast else (12608, 2560)
    strip = (rng.standard_normal((d_days, n_cols)) * 0.02).astype(np.float32)
    strip[rng.random(strip.shape) < 0.05] = np.nan
    xs = jnp.asarray(strip)
    rolling_route = resolve_rolling_route()
    out["kernels_rolling_route"] = rolling_route
    out["kernels_rolling_shape"] = f"D{d_days}_N{n_cols}"
    for kind, fn, window, mp in (
        ("std", rolling_std, 252, 100),
        ("sum", rolling_sum, 24, 12),
        ("mean", rolling_mean, 12, 1),
    ):
        ms_of = {}
        routes = [("xla", False)] + ([("pallas", True)]
                                     if platform == "tpu" else [])
        for label, use_pallas in routes:
            f = jax.jit(functools.partial(
                lambda v, _fn, _up: jnp.nansum(_fn(v, window, mp,
                                                   use_pallas=_up)),
                _fn=fn, _up=use_pallas,
            ))
            run = (lambda f=f: float(f(xs)))  # scalar pull = barrier
            ms = _timed_ms(run)
            ms_of[label] = ms
            out[f"kernels_rolling_{kind}_{label}_ms"] = round(ms, 3)
            warm_runners.append((f"kernels_rolling_{kind}_{label}", run))
        if platform == "tpu":
            out[f"kernels_rolling_{kind}_pallas_speedup"] = round(
                ms_of["xla"] / ms_of["pallas"], 2)
        else:
            out[f"kernels_rolling_{kind}_pallas_ms"] = _kernels_skip(
                platform, rolling_route, "FMRP_ROLLING_ROUTE", "FMRP_PALLAS"
            )
        best = min(ms_of.values())
        out[f"kernels_rolling_{kind}_melems_per_s"] = round(
            d_days * n_cols / (best / 1000) / 1e6, 1
        )

    # -- overlapped cold ingest: serial vs prefetched chunked read ---------
    from fm_returnprediction_tpu.data.benchscale import write_benchscale_cache
    from fm_returnprediction_tpu.data.columnar import read_filtered_columns
    from fm_returnprediction_tpu.data.synthetic import FILE_NAMES
    from fm_returnprediction_tpu.data.wrds_pull import UNIVERSE_FLAGS

    t_m, n_f = (24, 300) if fast else (120, 4000)
    out["kernels_ingest_shape"] = f"T{t_m}_N{n_f}"
    repo_root = os.path.dirname(os.path.abspath(__file__))
    raw_dir = os.path.join(repo_root, "_cache", f"benchscale_T{t_m}_N{n_f}")
    write_benchscale_cache(raw_dir, n_permnos=n_f, n_months=t_m)
    daily = os.path.join(raw_dir, FILE_NAMES["crsp_d"])
    batch_rows = 1 << (14 if fast else 20)  # ≥ ~8 batches through the queue
    read_kw = dict(
        value_columns=["permno", "dlycaldt", "retx"],
        flag_spec=UNIVERSE_FLAGS, batch_rows=batch_rows,
    )
    rows = None
    for label, depth in (("serial", 0), ("overlap", None)):
        def run(depth=depth):
            return read_filtered_columns(daily, prefetch=depth, **read_kw)
        rows = len(run()["retx"])  # touch the file once untimed (page cache)
        sec = _timed_ms(run, warm=False) / 1000
        out[f"kernels_ingest_{label}_s"] = round(sec, 4)
        out[f"kernels_ingest_{label}_rows_per_s"] = round(rows / sec, 1)
    out["kernels_ingest_rows"] = rows
    out["kernels_ingest_overlap_speedup"] = round(
        out["kernels_ingest_serial_s"] / out["kernels_ingest_overlap_s"], 2
    )

    # -- warm repeat of the whole ladder under the recompile sentinel ------
    with telemetry.recompile_watch("bench.kernels_warm", warm=True) as delta:
        for _, run in warm_runners:
            run()
    if delta.grew:
        out["kernels_warm_recompiles"] = {
            "cache_entries_grew": delta.grew,
            "culprits": list(delta.culprits) or ["unattributed-jit"],
        }
    return out


_FUSEPROBE_CHILD = """
import sys
import numpy as np
import jax, jax.numpy as jnp
n = int(sys.argv[1])
expected = sys.argv[2] if len(sys.argv) > 2 else ""
platform = jax.devices()[0].platform
if expected == "tpu" and platform != "tpu":
    # a silent CPU fallback in the child would chart XLA:CPU compile
    # cost as the TPU fusion boundary and corrupt the
    # FMRP_FUSE_SUBSETS_MB calibration evidence — fail LOUDLY with a
    # distinct marker the parent records as invalid, never "ok"
    print("FUSEPROBE_WRONG_BACKEND " + platform)
    sys.exit(3)
t, p = 600, 14
rng = np.random.default_rng(0)
x_all = jnp.asarray(rng.standard_normal((t, n, p)).astype(np.float32))
y = jnp.asarray(
    np.where(rng.random((t, n)) > 0.2,
             rng.standard_normal((t, n)), np.nan).astype(np.float32))
masks = jnp.asarray(rng.random((3, t, n)) > 0.3)
from fm_returnprediction_tpu.reporting import table2 as t2
out = t2._fm_sweep(y, x_all, masks, (tuple(range(3)), tuple(range(7)),
                                     tuple(range(14))),
                   nw_lags=t2.TABLE2_NW_LAGS, solver=t2.TABLE2_SOLVER,
                   min_months=t2.TABLE2_MIN_MONTHS, weight=t2.TABLE2_WEIGHT)
jax.block_until_ready(out)
print("FUSEPROBE_OK")
"""


def _bench_fuseprobe(fast: bool):
    """Measure the fused-program compile boundary the 512 MB fusion budget
    guesses at (round-4 VERDICT weak #4: "calibrated from one crash, not
    measured compiler headroom").

    Compiles the FULL fused Table 2 sweep (all three models, subset-vmapped)
    at increasing firm counts, each in a crash-isolated child process — the
    observed failure mode wedges the in-process client, which is exactly
    why the production policy exists. On TPU the probe covers the
    real-shape ladder up to the N22k crash shape. On CPU rounds (r5
    VERDICT weak #3: a measurement that only runs under conditions that
    never occur is not a measurement) a SMALL-shape ladder runs instead —
    the XLA:CPU compiler does not share the TPU failure mode, so the CPU
    numbers chart compile cost vs footprint, labelled
    ``fuseprobe_device: cpu`` / ``fuseprobe_scale: small`` so they can
    never be read as the TPU boundary."""
    import subprocess
    import sys

    import jax

    if fast or os.environ.get("FMRP_BENCH_FUSEPROBE", "1") == "0":
        return {}
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        ladder = (2000, 5000, 10000, 16000, 22000)
        budget = float(os.environ.get("FMRP_BENCH_FUSEPROBE_BUDGET_S", 900))
        per_probe = float(os.environ.get("FMRP_BENCH_FUSEPROBE_PROBE_S", 240))
    else:
        ladder = (500, 1000, 2000)
        budget = float(os.environ.get("FMRP_BENCH_FUSEPROBE_BUDGET_S", 360))
        per_probe = float(os.environ.get("FMRP_BENCH_FUSEPROBE_PROBE_S", 150))
    repo_root = os.path.dirname(os.path.abspath(__file__))
    # stacked_design_bytes(3, 600, n, 14, 4) = 115200·n: 2k ≈ 230 MB …
    # 22k ≈ 2.5 GB (the shape that crashed the r4 compile helper)
    results = {}
    probe_s = {}
    t_start = time.perf_counter()
    global _CHILD_PROC
    wrong_backend = False
    for n in ladder:
        if time.perf_counter() - t_start > budget - per_probe:
            results[str(n)] = "budget-exhausted"
            break
        try:
            t0 = time.perf_counter()
            # Popen + _CHILD_PROC (the _real_cpu_rescue/_bench_mesh8
            # discipline): the global-deadline watchdog's os._exit must
            # be able to kill a live compile child — subprocess.run
            # would orphan it to burn the host for up to per_probe
            proc = subprocess.Popen(
                [sys.executable, "-c", _FUSEPROBE_CHILD, str(n),
                 "tpu" if on_tpu else "cpu"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                cwd=repo_root, env=None if on_tpu else _child_env(repo_root),
            )
            _CHILD_PROC = proc
            try:
                stdout, stderr = proc.communicate(timeout=per_probe)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.communicate()
                results[str(n)] = f"timeout>{per_probe:.0f}s"
                break  # larger shapes only get worse; save the window
            finally:
                _CHILD_PROC = None
            probe_s[str(n)] = round(time.perf_counter() - t0, 2)
            if "FUSEPROBE_WRONG_BACKEND" in stdout:
                # the probe ran, but not on the backend it claims to
                # calibrate — record INVALID (a distinct verdict the
                # ladder's "ok" consumers can never mistake), and stop:
                # every later rung would be equally invalid
                results[str(n)] = "invalid: wrong-backend (" + \
                    stdout.split("FUSEPROBE_WRONG_BACKEND", 1)[1].strip()[:40] + ")"
                wrong_backend = True
                break
            ok = proc.returncode == 0 and "FUSEPROBE_OK" in stdout
            results[str(n)] = "ok" if ok else (
                "fail: " + (stderr or stdout)[-150:])
        except Exception as exc:  # noqa: BLE001 — a probe is best-effort
            results[str(n)] = f"spawn-error: {exc!r}"[:160]
        if results[str(n)] != "ok":
            break  # larger shapes only get worse; save the window
    from fm_returnprediction_tpu.reporting.fusion import stacked_design_bytes

    ok_ns = [int(k) for k, v in results.items() if v == "ok"]
    return {
        "fuseprobe_results": results,
        "fuseprobe_probe_s": probe_s,
        "fuseprobe_device": "tpu" if on_tpu else "cpu",
        "fuseprobe_scale": "real" if on_tpu else "small",
        "fuseprobe_backend_valid": not wrong_backend,
        "fuseprobe_largest_ok_mb": (
            round(stacked_design_bytes(3, 600, max(ok_ns), 14, 4) / 2**20)
            if ok_ns else 0
        ),
    }


def _bench_specgrid(fast: bool):
    """The spec-grid estimation subsystem (``fm_returnprediction_tpu/
    specgrid``): the full Table-2-shaped 3×3 grid (3 nested models × 3
    nested universes) solved from shared Gram sufficient statistics as ONE
    fused program, vs the same 9 cells through the per-cell batched-QR
    route — on the same synthetic panel. Records both wall-clocks, the
    grid's compiled-program count (the subsystem's trace counters: the
    acceptance evidence for "≤2 programs for the 3×3 grid"), the QR
    referee fallback count, the max |coef| disagreement between the two
    routes, and the Gram-vs-stacked peak-footprint estimate at both the
    bench shape and real CRSP shape. FMRP_BENCH_SPECGRID=0 skips."""
    if os.environ.get("FMRP_BENCH_SPECGRID", "1") == "0":
        return {}
    import jax
    import jax.numpy as jnp

    from fm_returnprediction_tpu import specgrid
    from fm_returnprediction_tpu.models.lewellen import MODELS
    from fm_returnprediction_tpu.ops.fama_macbeth import fama_macbeth
    from fm_returnprediction_tpu.reporting.fusion import stacked_design_bytes

    t = int(os.environ.get("FMRP_BENCH_SPECGRID_MONTHS", 120 if fast else 600))
    n = int(os.environ.get("FMRP_BENCH_SPECGRID_FIRMS", 300 if fast else 4000))
    p = 14
    y, x, subsets = _make_panel(t, n, p)
    masks = dict(zip(("All", "All-but-tiny", "Large"), subsets))
    names = [f"x{i:02d}" for i in range(p)]
    model_sizes = [len(m.predictors) for m in MODELS]  # 3, 7, 14
    grid = specgrid.SpecGrid(tuple(
        specgrid.Spec(f"m{k} | {u}", tuple(names[:k]), u)
        for k in model_sizes for u in masks
    ))

    from fm_returnprediction_tpu import telemetry as _telemetry

    ledger_mark = _telemetry.cost_ledger().last_seq
    before = specgrid.program_trace_counts()
    with _timed("bench.specgrid_grid_cold") as grid_cold_t:
        res = specgrid.run_spec_grid(y, x, masks, grid)
    with _timed("bench.specgrid_grid_warm") as grid_warm_t:
        res = specgrid.run_spec_grid(y, x, masks, grid)
    grid_cold, grid_warm = grid_cold_t.s, grid_warm_t.s
    after = specgrid.program_trace_counts()
    programs = (after.get("specgrid_program", 0)
                - before.get("specgrid_program", 0))
    referee = (after.get("specgrid_referee_calls", 0)
               - before.get("specgrid_referee_calls", 0))

    # the incumbent: per-cell batched-QR dispatches (the split route)
    yd, xd = jnp.asarray(y), jnp.asarray(x)
    subs = [jnp.asarray(m) for m in masks.values()]
    fm_jit = jax.jit(fama_macbeth, static_argnames=("solver",))

    def percell():
        out = []
        for k in model_sizes:
            for sub in subs:
                _, fm = fm_jit(yd, xd[..., :k], sub)
                out.append(np.asarray(fm.coef))  # host pull = sync
        return out

    with _timed("bench.specgrid_percell_cold") as percell_cold_t:
        qr_coefs = percell()
    with _timed("bench.specgrid_percell_warm") as percell_warm_t:
        qr_coefs = percell()
    percell_cold, percell_warm = percell_cold_t.s, percell_warm_t.s

    diffs = []
    nan_mismatches = 0
    for s, spec in enumerate(grid.specs):
        pos = grid.column_positions(spec)
        a, b = res.coef[s, pos], qr_coefs[s]
        # a one-sided NaN is a ROUTE DISAGREEMENT (the month/min_months
        # gates diverged) — counted in its own key (inf inside the max
        # would serialize as non-RFC 'Infinity' and break strict JSON
        # consumers of the one-line artifact)
        nan_mismatches += int((np.isnan(a) != np.isnan(b)).sum())
        d = np.abs(a - b)
        diffs.append(np.max(np.where(np.isnan(a) | np.isnan(b), 0.0, d)))
    itemsize = x.dtype.itemsize
    q = p + 1
    p_sum = sum(k + 2 for k in model_sizes)
    gram_mb = len(grid) * t * q * q * itemsize / 2**20
    real_gram_mb = len(grid) * 600 * q * q * itemsize / 2**20
    # roofline: the cost ledger knows the fused program's FLOPs from its
    # AOT compile; warm wall over that gives achieved FLOP/s and the
    # (rough, disclosed) platform-peak utilization gauge. Only THIS
    # section's compiles count — earlier pipeline sections compile other
    # specgrid_program signatures whose FLOPs must not inflate the gauge.
    section_flops = sum(
        r.flops or 0.0
        for r in _telemetry.cost_ledger().since(ledger_mark)
        if r.program == "specgrid_program"
    )
    roofline = (
        _telemetry.record_runtime(
            "specgrid_program", grid_warm, flops=section_flops
        )
        if section_flops else {}
    )
    roofline_keys = {
        f"specgrid_{k}": (round(v, 6) if k == "roofline_utilization"
                          else round(v, 1))
        for k, v in roofline.items()
    }
    return {
        **roofline_keys,
        "specgrid_grid_cold_s": round(grid_cold, 4),
        "specgrid_grid_warm_s": round(grid_warm, 4),
        "specgrid_percell_cold_s": round(percell_cold, 4),
        "specgrid_percell_warm_s": round(percell_warm, 4),
        "specgrid_speedup_warm": round(percell_warm / grid_warm, 2),
        "specgrid_programs": programs,
        "specgrid_referee_cells": referee,
        "specgrid_suspect_months": int(res.suspect_months.sum()),
        "specgrid_max_abs_coef_diff": float(np.max(diffs)),
        "specgrid_nan_pattern_mismatches": nan_mismatches,
        "specgrid_gram_mb": round(gram_mb, 2),
        "specgrid_stacked_mb": round(
            stacked_design_bytes(3, t, n, p_sum - 2, itemsize) / 2**20, 1
        ),
        "specgrid_real_gram_mb": round(real_gram_mb, 2),
        "specgrid_real_stacked_mb": round(
            stacked_design_bytes(3, 600, 22000, p_sum - 2, itemsize) / 2**20, 1
        ),
        "specgrid_shape": f"T{t}_N{n}_S{len(grid)}",
    }


def _bench_multiproc(fast: bool):
    """Cross-process execution (ISSUE 13): process count as a measured
    deployment knob.

    - ``multiproc_specgrid_cells_per_s_p{1,2,4}`` — the Table-2-shaped
      3×3 grid through the spec-grid route at 1/2/4 processes. p1 is the
      in-process fused program (the incumbent, whole box); p2/p4 spawn
      that many firm-shard contraction workers, each PINNED to
      ``multiproc_cpus_per_proc`` cores (the pod's fixed-compute-per-
      process model on one box: a process = a "host" of K cores), merged
      over the host exchange and solved by the existing vmapped tail.
      ``multiproc_specgrid_speedup_p4`` (p4/p1 cells/s, higher-better)
      is the regress-tracked series — the acceptance floor is ≥1.5×.
    - ``multiproc_transport_*`` — host-merge bytes and wall per grid at
      p4 (the gather fan-in the broker carries), plus the differential
      guard ``multiproc_max_abs_coef_diff`` (p4 vs p1 coef; the tier-1
      pin is ≤1e-6 f32 rtol in tests/test_multiprocess.py).
    - ``multiproc_fleet_rows_per_s_{thread,process}`` — the same fleet
      drive with replicas as in-process threads vs REAL child processes
      behind the socket transport; the ratio discloses the per-query
      IPC bill the process boundary adds on one box (on a pod the
      boundary buys isolation + real parallelism; here it is priced).

    FMRP_BENCH_MULTIPROC=0 skips; _MULTIPROC_QUERIES resizes the fleet
    phase; FMRP_SPECGRID_CPUS_PER_PROC re-pins the worker core budget."""
    if os.environ.get("FMRP_BENCH_MULTIPROC", "1") == "0":
        return {}
    import tempfile
    import threading as _threading

    from fm_returnprediction_tpu import specgrid
    from fm_returnprediction_tpu.specgrid import multiproc

    t = 120 if fast else 240
    n = 1500 if fast else 4000
    p = 14
    y, x, subsets = _make_panel(t, n, p)
    masks = dict(zip(("All", "All-but-tiny", "Large"), subsets))
    names = [f"x{i:02d}" for i in range(p)]
    grid = specgrid.SpecGrid(tuple(
        specgrid.Spec(f"m{k} | {u}", tuple(names[:k]), u)
        for k in (3, 7, 14) for u in masks
    ))
    s_cells = len(grid)
    cpw = int(os.environ.get("FMRP_SPECGRID_CPUS_PER_PROC", "6"))
    out = {
        "multiproc_shape": f"T{t}_N{n}_S{s_cells}",
        "multiproc_cpus_per_proc": cpw,
    }
    reps = 2 if fast else 4
    coef_by_procs = {}
    for procs in (1, 2, 4):
        try:
            with _timed(f"bench.multiproc_p{procs}_cold") as cold_t:
                res = specgrid.run_spec_grid(
                    y, x, masks, grid, procs=procs,
                ) if procs == 1 else _mp_grid_run(
                    specgrid, y, x, masks, grid, procs, cpw
                )
            with _timed(f"bench.multiproc_p{procs}_warm") as warm_t:
                for _ in range(reps):
                    res = specgrid.run_spec_grid(
                        y, x, masks, grid, procs=procs,
                    ) if procs == 1 else _mp_grid_run(
                        specgrid, y, x, masks, grid, procs, cpw
                    )
            warm = warm_t.s / reps
            coef_by_procs[procs] = np.asarray(res.coef, float)
            out[f"multiproc_specgrid_cold_s_p{procs}"] = round(cold_t.s, 4)
            out[f"multiproc_specgrid_warm_s_p{procs}"] = round(warm, 4)
            out[f"multiproc_specgrid_cells_per_s_p{procs}"] = round(
                s_cells / warm, 2
            )
            if procs > 1 and multiproc._POOL_CACHE is not None:
                pool = multiproc._POOL_CACHE[2]
                out[f"multiproc_transport_bytes_per_grid_p{procs}"] = int(
                    pool.last_merge_bytes
                )
                out[f"multiproc_merge_s_p{procs}"] = round(
                    pool.last_merge_s, 4
                )
                out["multiproc_grid_transport"] = pool.transport
                if pool.transport == "shm":
                    # mapped-segment bytes are disclosed SEPARATELY from
                    # exchange bytes: the stats still move (one memcpy
                    # into the segment, summed in place by the parent),
                    # they just never ride a pickle frame
                    out[f"multiproc_shm_mapped_bytes_per_grid_p{procs}"] \
                        = int(pool.last_shm_bytes)
        finally:
            multiproc._close_cached_pool()
    # the frames ORACLE at p4, one grid: what the same contraction costs
    # in exchange bytes without the mapped segments — the denominator of
    # the ISSUE-15 "≥10× down" claim (skipped in fast mode: it spawns a
    # second 4-worker pool purely for a byte measurement)
    if not fast and os.environ.get("FMRP_GRID_TRANSPORT", "") == "":
        os.environ["FMRP_GRID_TRANSPORT"] = "frames"
        try:
            _mp_grid_run(specgrid, y, x, masks, grid, 4, cpw)
            if multiproc._POOL_CACHE is not None:
                pool = multiproc._POOL_CACHE[2]
                out["multiproc_transport_bytes_per_grid_p4_frames"] = int(
                    pool.last_merge_bytes
                )
        finally:
            os.environ.pop("FMRP_GRID_TRANSPORT", None)
            multiproc._close_cached_pool()
    if 1 in coef_by_procs and 4 in coef_by_procs:
        a, b = coef_by_procs[1], coef_by_procs[4]
        both_nan = np.isnan(a) & np.isnan(b)
        out["multiproc_max_abs_coef_diff"] = float(np.max(np.abs(
            np.where(both_nan, 0.0, a) - np.where(both_nan, 0.0, b)
        )))
        p1 = out.get("multiproc_specgrid_cells_per_s_p1")
        p4 = out.get("multiproc_specgrid_cells_per_s_p4")
        if p1 and p4:
            out["multiproc_specgrid_speedup_p4"] = round(p4 / p1, 2)

    # -- fleet: thread vs process replica boundary -------------------------
    # NB: the process fleet runs on the DEFAULT transport (shm since
    # ISSUE 15, disclosed in multiproc_fleet_transport) — this series is
    # "the process fleet as deployed", so the auto-default improvement
    # lands here like specgrid_scale did under PR 14's new defaults; the
    # per-transport split (socket oracle included) lives in the
    # transport_* section
    from fm_returnprediction_tpu.serving import ServingFleet, replay_journal

    state, have, (_, _, pf), per_mode, n_workers = _fleet_bench_fixture(
        fast, "FMRP_BENCH_MULTIPROC_QUERIES"
    )
    rngq = np.random.default_rng(2017)
    with tempfile.TemporaryDirectory() as root:
        for mode in ("thread", "process"):
            journal = os.path.join(root, f"journal_{mode}.jsonl")
            fleet = ServingFleet(
                state, 2, replica_mode=mode, max_batch=64,
                max_latency_ms=1.0, journal=journal,
            )
            try:
                mon = have[rngq.integers(0, len(have), per_mode)]
                rows = rngq.standard_normal(
                    (per_mode, pf)
                ).astype(np.float32)
                # warm the path before timing (first queries pay dispatch
                # warm-up either side of the boundary)
                fleet.query(int(mon[0]), rows[0])
                rps, errors = _drive_fleet_blocking(
                    fleet, mon, rows, n_workers
                )
                fleet.drain()
                out[f"multiproc_fleet_rows_per_s_{mode}"] = round(rps, 1)
                out[f"multiproc_fleet_query_errors_{mode}"] = len(errors)
            finally:
                fleet.close()
            replay = replay_journal(journal)
            out[f"multiproc_fleet_journal_clean_{mode}"] = bool(replay.clean)
    thr = out.get("multiproc_fleet_rows_per_s_thread")
    prc = out.get("multiproc_fleet_rows_per_s_process")
    if thr and prc:
        out["multiproc_fleet_process_over_thread"] = round(prc / thr, 3)
    from fm_returnprediction_tpu.serving.shm import resolve_fleet_transport

    out["multiproc_fleet_transport"] = resolve_fleet_transport()
    return out


def _fleet_bench_fixture(fast: bool, queries_env: str):
    """The ONE fleet bench shape (the r08 series' fixture), shared by
    the multiproc and transport sections so the comparable series can
    never drift apart: returns (state, quotable months, (T, N, P),
    per_mode, n_workers)."""
    from fm_returnprediction_tpu.serving import build_serving_state

    tf, nf, pf = (60, 200, 5) if fast else (120, 600, 5)
    rngf = np.random.default_rng(2016)
    xf = rngf.standard_normal((tf, nf, pf)).astype(np.float32)
    betaf = (rngf.standard_normal(pf) * 0.05).astype(np.float32)
    yf = (xf @ betaf + 0.1 * rngf.standard_normal((tf, nf))).astype(
        np.float32
    )
    maskf = rngf.random((tf, nf)) > 0.2
    yf = np.where(maskf, yf, np.nan).astype(np.float32)
    state = build_serving_state(
        yf, xf, maskf, window=min(60, tf // 2), min_periods=min(24, tf // 4)
    )
    per_mode = int(os.environ.get(
        queries_env, 400 if fast else 2000
    ))
    have = np.nonzero(state.have_coef())[0]
    return state, have, (tf, nf, pf), per_mode, 8


def _drive_fleet_blocking(fleet, mon, rows, n_workers: int):
    """The blocking 8-worker drive both fleet sections time: each worker
    issues its chunk of synchronous queries; returns (rows/s, errors)."""
    import threading as _threading

    per = len(mon)
    errors = []
    t0 = time.perf_counter()

    def worker(k0, k1):
        for k in range(k0, k1):
            try:
                fleet.query(int(mon[k]), rows[k])
            except Exception as exc:  # noqa: BLE001
                errors.append(repr(exc))

    chunk = per // n_workers
    threads = [
        _threading.Thread(
            target=worker,
            args=(w * chunk, per if w == n_workers - 1 else (w + 1) * chunk),
        )
        for w in range(n_workers)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return per / (time.perf_counter() - t0), errors


def _mp_grid_run(specgrid, y, x, masks, grid, procs, cpw):
    """One multi-process grid run with the worker core budget pinned for
    this section (restored after; the pool reads it at spawn)."""
    prev = os.environ.get("FMRP_SPECGRID_CPUS_PER_PROC")
    os.environ["FMRP_SPECGRID_CPUS_PER_PROC"] = str(cpw)
    try:
        return specgrid.run_spec_grid(y, x, masks, grid, procs=procs)
    finally:
        if prev is None:
            os.environ.pop("FMRP_SPECGRID_CPUS_PER_PROC", None)
        else:
            os.environ["FMRP_SPECGRID_CPUS_PER_PROC"] = prev


def _transport_counter_delta(before: dict, after: dict, transport: str
                             ) -> dict:
    """Sum the ``fmrp_transport_*`` counter families for one transport
    label across replicas, as after−before deltas (bytes by direction,
    frames, ring-full stalls, batch-occupancy mean)."""
    def total(metrics, name, must=()):
        tot = 0.0
        for k, v in metrics.items():
            if not k.startswith(name):
                continue
            if f"transport={transport}" not in k:
                continue
            if any(m not in k for m in must):
                continue
            if isinstance(v, dict):
                continue
            tot += float(v)
        return tot

    def occupancy(metrics):
        s = c = 0.0
        for k, v in metrics.items():
            if (k.startswith("fmrp_transport_batch_rows")
                    and f"transport={transport}" in k
                    and isinstance(v, dict)):
                s += float(v.get("sum", 0.0))
                c += float(v.get("count", 0.0))
        return s, c

    d = {
        "bytes_sent": total(after, "fmrp_transport_bytes_total",
                            ("direction=sent",))
        - total(before, "fmrp_transport_bytes_total", ("direction=sent",)),
        "bytes_received": total(after, "fmrp_transport_bytes_total",
                                ("direction=received",))
        - total(before, "fmrp_transport_bytes_total",
                ("direction=received",)),
        "frames": total(after, "fmrp_transport_frames_total")
        - total(before, "fmrp_transport_frames_total"),
        "ring_full_stalls": total(
            after, "fmrp_transport_ring_full_stalls_total")
        - total(before, "fmrp_transport_ring_full_stalls_total"),
    }
    s1, c1 = occupancy(after)
    s0, c0 = occupancy(before)
    d["batch_rows_mean"] = (
        round((s1 - s0) / (c1 - c0), 2) if c1 > c0 else None
    )
    return d


def _transport_timeline(state, mon, rows, n_workers, root, shape):
    """Distributed-observability drive (ISSUE 20): one telemetry-ARMED
    pipelined shm fleet pass at the transport shape. Every process —
    router + each replica child — exports into one shared trace dir
    (per-process ``events.pK.jsonl`` filenames); the timeline CLI merges
    them into ONE Perfetto document and prints the per-hop table; the
    router-side hop share becomes the regress-gated series
    (``fleet_router_hop_share_pct`` @ shape @ device — lower = the
    router ceiling receding, the number ROADMAP item 2 wants before
    sharding the router). The rest of the table rides as nested
    attribution (reported, never gated)."""
    import glob as _glob
    import subprocess
    import sys as _sys
    import threading as _threading

    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.serving import ServingFleet
    from fm_returnprediction_tpu.telemetry import timeline as _tl

    trace_dir = os.path.join(root, "obs_trace")
    journal = os.path.join(root, "journal_obs.jsonl")
    n_q = min(len(mon), 512)
    # arming rides the ENV so the spawned children inherit it through
    # trace_env(); the router arms through the same knobs
    os.environ["FMRP_TELEMETRY"] = "1"
    os.environ["FMRP_TRACE_DIR"] = trace_dir
    try:
        with telemetry.tracing(trace_dir):
            fleet = ServingFleet(
                state, 2, replica_mode="process", transport="shm",
                max_batch=64, max_latency_ms=1.0, journal=journal,
            )
            try:
                fleet.query(int(mon[0]), rows[0])  # warm the path

                def worker(k0, k1):
                    futs = []
                    for k in range(k0, k1):
                        try:
                            futs.append(fleet.submit(int(mon[k]), rows[k]))
                        except Exception:  # noqa: BLE001 — sheds pass
                            pass
                        if len(futs) >= 64:
                            for f in futs:
                                try:
                                    f.result(timeout=30)
                                except Exception:  # noqa: BLE001
                                    pass
                            futs = []
                    for f in futs:
                        try:
                            f.result(timeout=30)
                        except Exception:  # noqa: BLE001
                            pass

                chunk = max(n_q // n_workers, 1)
                threads = [
                    _threading.Thread(
                        target=worker,
                        args=(w * chunk,
                              n_q if w == n_workers - 1
                              else min((w + 1) * chunk, n_q)),
                    )
                    for w in range(n_workers)
                ]
                for th in threads:
                    th.start()
                for th in threads:
                    th.join()
                fleet.drain()
            finally:
                fleet.close()
    finally:
        os.environ.pop("FMRP_TELEMETRY", None)
        os.environ.pop("FMRP_TRACE_DIR", None)
    # the children flush their events.pK.jsonl from their atexit hooks;
    # close() reaped the processes, but give the writes a beat to land
    deadline = time.perf_counter() + 10.0
    while (len(_glob.glob(os.path.join(trace_dir, "events*.jsonl"))) < 3
           and time.perf_counter() < deadline):
        time.sleep(0.05)
    # the operator command, end to end: merged timeline.json + table
    cli = subprocess.run(
        [_sys.executable, "-m",
         "fm_returnprediction_tpu.telemetry.timeline", journal, trace_dir],
        capture_output=True, text=True, timeout=180,
    )
    report = _tl.analyze(trace_dir, journal_path=journal)
    return {
        "fleet_router_hop_shape": shape,
        "fleet_router_hop_share_pct": report["router_share_pct"],
        "fleet_timeline": {
            "attributed_pct": report["attributed_pct"],
            "processes": report["processes"],
            "requests": report["requests"],
            "e2e_p50_ms": report["e2e_p50_ms"],
            "hop_p50_ms": {
                name.split(".", 1)[1]: h["p50_ms"]
                for name, h in report["hops"].items()
            },
            "cli_rc": cli.returncode,
        },
    }


def _bench_transport(fast: bool):
    """The process fleet's data plane, socket vs shared-memory rings
    (ISSUE 15): the same blocking 8-worker drive as the
    ``multiproc_fleet_*`` series (the BENCH_r08 fleet shape) through

    - ``transport_fleet_rows_per_s_{thread,socket,shm}`` — thread
      replicas (the incumbent ceiling), process replicas over the
      pickle socket (the ISSUE-13 transport, kept as the differential
      oracle), and process replicas over the shm rings;
    - ``fleet_process_over_thread`` — shm-process over thread, THE
      regress-gated series (≥1.0 = the process boundary no longer
      taxes the data plane; r08's socket measured 0.643);
    - ``transport_{socket,shm}_*`` — per-mode byte/frame/stall counter
      deltas and the shm batch-occupancy mean (how many rows each ring
      frame coalesced);
    - ``transport_{thread,shm}_pipelined_rows_per_s`` — a bounded
      64-deep submit pipeline per worker: the throughput-oriented
      drive. DISCLOSED asymmetry: the shm path stays router-GIL-bound
      here (every result crosses one reader thread); the blocking
      drive above is the gated series;
    - a mid-load ``hard_crash`` on the SHM path whose journal, after
      ``ServingFleet.recover``, replays CLEAN — 0 dropped / 0
      duplicated (``transport_crash_*``) — the exactly-once proof
      composed with the zero-copy data plane.

    FMRP_BENCH_TRANSPORT=0 skips; _TRANSPORT_QUERIES resizes."""
    if os.environ.get("FMRP_BENCH_TRANSPORT", "1") == "0":
        return {}
    import tempfile
    import threading as _threading

    from fm_returnprediction_tpu.serving import ServingFleet, replay_journal
    from fm_returnprediction_tpu.telemetry.export import flat_metrics

    state, have, shape, per_mode, n_workers = _fleet_bench_fixture(
        fast, "FMRP_BENCH_TRANSPORT_QUERIES"
    )
    tf, nf, pf = shape
    rngq = np.random.default_rng(2016)
    mon = have[rngq.integers(0, len(have), per_mode)]
    rows = rngq.standard_normal((per_mode, pf)).astype(np.float32)
    out = {
        "transport_shape": (
            f"T{tf}_N{nf}_P{pf}_q{per_mode}_w{n_workers}"
        ),
    }

    def drive_blocking(fleet):
        return _drive_fleet_blocking(fleet, mon, rows, n_workers)

    def drive_pipelined(fleet):
        t0 = time.perf_counter()

        def worker(k0, k1):
            futs = []
            for k in range(k0, k1):
                try:
                    futs.append(fleet.submit(int(mon[k]), rows[k]))
                except Exception:  # noqa: BLE001 — sheds don't stall it
                    pass
                if len(futs) >= 64:
                    for f in futs:
                        try:
                            f.result(timeout=30)
                        except Exception:  # noqa: BLE001
                            pass
                    futs = []
            for f in futs:
                try:
                    f.result(timeout=30)
                except Exception:  # noqa: BLE001
                    pass

        chunk = per_mode // n_workers
        threads = [
            _threading.Thread(
                target=worker,
                args=(w * chunk,
                      per_mode if w == n_workers - 1 else (w + 1) * chunk),
            )
            for w in range(n_workers)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        return per_mode / (time.perf_counter() - t0)

    modes = (
        ("thread", "thread", None),
        ("socket", "process", "socket"),
        ("shm", "process", "shm"),
    )
    with tempfile.TemporaryDirectory() as root:
        for label, rmode, transport in modes:
            journal = os.path.join(root, f"journal_{label}.jsonl")
            before = mid = flat_metrics()
            fleet = ServingFleet(
                state, 2, replica_mode=rmode, transport=transport,
                max_batch=64, max_latency_ms=1.0, journal=journal,
            )
            try:
                fleet.query(int(mon[0]), rows[0])  # warm the path
                rps, errors = drive_blocking(fleet)
                # counter window closes HERE: the per-query byte/frame
                # deltas must cover exactly the blocking drive every
                # mode runs, not the extra pipelined drive below (which
                # only thread/shm run — including it would double shm's
                # bytes-per-query against socket's)
                mid = flat_metrics()
                out[f"transport_fleet_rows_per_s_{label}"] = round(rps, 1)
                out[f"transport_fleet_query_errors_{label}"] = len(errors)
                if label in ("thread", "shm"):
                    out[f"transport_{label}_pipelined_rows_per_s"] = round(
                        drive_pipelined(fleet), 1
                    )
                fleet.drain()
            finally:
                fleet.close()
            out[f"transport_fleet_journal_clean_{label}"] = bool(
                replay_journal(journal).clean
            )
            if transport is not None:
                delta = _transport_counter_delta(before, mid, transport)
                out[f"transport_{label}_bytes_per_query"] = round(
                    (delta["bytes_sent"] + delta["bytes_received"])
                    / max(per_mode, 1), 1
                )
                out[f"transport_{label}_frames"] = int(delta["frames"])
                if transport == "shm":
                    out["transport_shm_ring_full_stalls"] = int(
                        delta["ring_full_stalls"]
                    )
                    out["transport_shm_batch_rows_mean"] = (
                        delta["batch_rows_mean"]
                    )

        thr = out.get("transport_fleet_rows_per_s_thread")
        shm = out.get("transport_fleet_rows_per_s_shm")
        sock = out.get("transport_fleet_rows_per_s_socket")
        if thr and shm:
            out["fleet_process_over_thread"] = round(shm / thr, 3)
        if thr and sock:
            out["transport_socket_over_thread"] = round(sock / thr, 3)

        # -- replica-count ladder on the shm path --------------------------
        ladder = (1, 2) if fast else (1, 2, 4)
        for r in ladder:
            fleet = ServingFleet(
                state, r, replica_mode="process", transport="shm",
                max_batch=64, max_latency_ms=1.0,
            )
            try:
                fleet.query(int(mon[0]), rows[0])
                rps, _ = drive_blocking(fleet)
                out[f"transport_shm_r{r}_rows_per_s"] = round(rps, 1)
            finally:
                fleet.close()

        # -- mid-load hard crash on the shm path ---------------------------
        journal = os.path.join(root, "journal_crash.jsonl")
        fleet = ServingFleet(
            state, 2, replica_mode="process", transport="shm",
            max_batch=64, max_latency_ms=1.0, journal=journal,
        )
        crash_at = per_mode // 3

        def crash_worker(k0, k1):
            for k in range(k0, k1):
                try:
                    fleet.query(int(mon[k]), rows[k])
                except Exception:  # noqa: BLE001 — post-crash submits fail
                    pass

        fleet.query(int(mon[0]), rows[0])
        chunk = per_mode // n_workers
        threads = [
            _threading.Thread(target=crash_worker,
                              args=(w * chunk, (w + 1) * chunk))
            for w in range(n_workers)
        ]
        for th in threads:
            th.start()
        # crash mid-load: wait until roughly a third of the queries are
        # journaled, then die the way a SIGKILLed router dies
        deadline = time.perf_counter() + 30.0
        while (fleet.journal is not None
               and fleet._req_counter < crash_at
               and time.perf_counter() < deadline):
            time.sleep(0.002)
        fleet.hard_crash()
        for th in threads:
            th.join()
        # the crashed session is dirty by construction; recovery must
        # close out every in-flight request and replay CLEAN
        recovered, report = ServingFleet.recover(
            journal, state=state, replica_mode="thread",
            max_batch=64, max_latency_ms=1.0,
        )
        try:
            final = replay_journal(journal)
            rotated = (replay_journal(report.rotated_to)
                       if report.rotated_to is not None else None)
            out["transport_crash_journal_clean"] = bool(
                report.journal.replay_clean
                and final.clean
                and (rotated is None or rotated.clean)
            )
            out["transport_crash_closed_out"] = len(
                report.journal.recovered
            )
            out["transport_crash_dropped"] = (
                len(rotated.dropped) if rotated is not None else 0
            )
            out["transport_crash_duplicated"] = (
                len(rotated.duplicated) if rotated is not None else 0
            )
        finally:
            recovered.close()

        # -- distributed observability: merged timeline + per-hop table ----
        out.update(_transport_timeline(
            state, mon, rows, n_workers, root, out["transport_shape"]
        ))
    return out


def _bench_specgrid_scale(fast: bool):
    """Pod-scale spec-grid: a CELL-COUNT LADDER through the lazy tile
    engine (``specgrid.cellspace``/``specgrid.engine``) and the streaming
    top-k sink — the ISSUE-8 acceptance evidence that a 1e5-cell scenario
    sweep completes on this box with peak incremental host memory bounded
    by one tile. Each rung scales the bootstrap-draw dimension over a
    fixed 432-spec product (48 predictor subsets × 3 universes × 3
    windows), so the ladder spans both regimes: solve-dominated (few
    draws) and aggregation-dominated (many draws). Per rung: cold sweep,
    then a warm repeat under ``recompile_watch`` (a warm re-sweep must
    reuse the tile program — any growth lands in
    ``fmrp_unexpected_recompiles_total``), ``cells_per_s`` from the warm
    wall (a higher-is-better series for the PR-6 regression sentinel),
    tracemalloc peak across the warm sweep, and the one-tile byte
    estimate it is bounded against. FMRP_BENCH_SPECGRID_SCALE=0 skips."""
    if os.environ.get("FMRP_BENCH_SPECGRID_SCALE", "1") == "0":
        return {}
    import tracemalloc

    from fm_returnprediction_tpu.specgrid import (
        CellSpace,
        TopKSink,
        run_cellspace,
    )
    from fm_returnprediction_tpu.specgrid.cellspace import resolve_tile_cells
    from fm_returnprediction_tpu.telemetry import recompile_watch

    t = int(os.environ.get("FMRP_BENCH_SPECGRID_SCALE_MONTHS", 60))
    n = int(os.environ.get("FMRP_BENCH_SPECGRID_SCALE_FIRMS", 400))
    p = 8
    y, x, subsets = _make_panel(t, n, p)
    masks = dict(zip(("All", "All-but-tiny", "Large"), subsets))
    names = [f"x{i:02d}" for i in range(p)]
    # 48 deterministic predictor subsets; the FIRST is the full set so the
    # space's union order equals the panel's column order
    rng = np.random.default_rng(2014)
    sets = [("s00_full", tuple(names))]
    while len(sets) < 48:
        k = 2 + (len(sets) % (p - 2))
        cols = np.sort(rng.choice(p, size=k, replace=False))
        sets.append((f"s{len(sets):02d}_{k}", tuple(names[c] for c in cols)))
    windows = (("full", None), ("half1", (0, t // 2)), ("half2", (t // 2, t)))

    ladder = [1_000, 10_000] if fast else [1_000, 10_000, 100_000]
    ladder = [int(c) for c in os.environ.get(
        "FMRP_BENCH_SPECGRID_SCALE_CELLS", ""
    ).split(",") if c] or ladder
    base = len(sets) * len(masks) * len(windows)
    tile = resolve_tile_cells(None)
    out = {"specgrid_scale_shape": f"T{t}_N{n}_P{p}_S{base}",
           "specgrid_scale_tile_cells": tile,
           "specgrid_scale_ladder": {}}
    import math as _math

    for target in ladder:
        draws = max(1, _math.ceil(target / base))
        space = CellSpace(
            regressor_sets=tuple(sets), universes=tuple(masks),
            windows=windows, bootstrap=draws,
        )
        label = f"{target:.0e}".replace("e+0", "e")
        if label in out["specgrid_scale_ladder"]:
            # env-configured targets can collide at one significant digit
            # (120000 and 140000 are both "1e5") — fall back to the exact
            # count rather than silently overwriting a rung
            label = str(target)
        with _timed(f"bench.specgrid_scale_{label}_cold") as cold_t:
            _, cold_stats = run_cellspace(
                y, x, masks, space, sink=TopKSink(k=64), mask=masks["All"],
            )
        # timing pass: warm repeat under the recompile sentinel ONLY —
        # tracemalloc hooks every allocation and has been measured to
        # double this sweep's wall, so the memory pass runs separately
        with recompile_watch(f"specgrid_scale_{label}", warm=True) as delta:
            with _timed(f"bench.specgrid_scale_{label}_warm") as warm_t:
                frame, stats = run_cellspace(
                    y, x, masks, space, sink=TopKSink(k=64),
                    mask=masks["All"],
                )
        # memory pass: same sweep under tracemalloc; only the peak is read
        tracemalloc.start()
        run_cellspace(y, x, masks, space, sink=TopKSink(k=64),
                      mask=masks["All"])
        _, peak_bytes = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        # the bound: one tile's Gram stats + one tile's result rows — at
        # the ENGINE's effective (draw-aligned) tile width, not the knob
        q = p + 1
        eff_tile = stats["tile_cells"]
        tile_mb = (
            stats["spec_pad"] * t * q * q * x.dtype.itemsize  # Gram stats
            + eff_tile * (p + 1) * 200                        # frame rows
        ) / 2**20
        rung = {
            "cells": len(space),
            "draws": draws,
            "tile_cells": eff_tile,
            "cold_s": round(cold_t.s, 4),
            "warm_s": round(warm_t.s, 4),
            "cells_per_s": round(len(space) / warm_t.s, 1),
            "tiles": stats["tiles"],
            "spec_pad": stats["spec_pad"],
            "topk_rows": len(frame),
            "peak_host_mb": round(peak_bytes / 2**20, 2),
            "tile_bound_mb": round(tile_mb, 2),
            "warm_cache_growth": delta.grew if delta is not None else None,
        }
        out["specgrid_scale_ladder"][label] = rung
        top = rung  # the last (largest) rung feeds the flat gated series
    # flat leaves = the gated series; the nested ladder is attribution
    out["specgrid_scale_cells_per_s"] = top["cells_per_s"]
    out["specgrid_scale_peak_host_mb"] = top["peak_host_mb"]
    out["specgrid_scale_tile_bound_mb"] = top["tile_bound_mb"]
    out["specgrid_scale_cells"] = top["cells"]
    return out


def _bench_grid_factorized(fast: bool):
    """Month-axis factorization + device bootstrap + banked queries (the
    ISSUE-14 acceptance evidence). One window-swept CellSpace (8 windows,
    32 bootstrap draws) runs three warm routes at the SAME shape:

    - legacy: per-spec contraction (``factorize="off"``), per-draw host
      numpy aggregation (``boot_route="host"``) — the incumbent;
    - factorized: unique-pair contraction + device-batched draw
      aggregation (the new default resolution for this space);
    - factorized/host: isolates the boot route's share of the win.

    Gated series: ``grid_factorized_cells_per_s`` (higher-better),
    ``grid_factorized_speedup`` (factorized / legacy, the ≥2× acceptance
    floor), ``grid_boot_speedup`` (device / host draw aggregation at
    fixed contraction route). The contraction-work ledger discloses that
    the factorized route contracted PAIRS, not S
    (``grid_factorized_pairs_per_tile`` vs ``_specs_per_tile``), and the
    two frames' max |Δcoef| pins parity in every round. The bank leg
    times ``grambank.scenario_query`` answering a NEW window split + a
    NEW bootstrap depth from banked stats — the ledger staying flat
    proves zero (T, N, P) panel reads. FMRP_BENCH_GRID_FACTORIZED=0
    skips."""
    if os.environ.get("FMRP_BENCH_GRID_FACTORIZED", "1") == "0":
        return {}
    from fm_returnprediction_tpu.specgrid import CellSpace, run_cellspace
    from fm_returnprediction_tpu.specgrid.grambank import (
        build_bank,
        scenario_query,
    )
    from fm_returnprediction_tpu.specgrid.scenarios import subperiod_windows
    from fm_returnprediction_tpu.specgrid.solve import contraction_counts

    t = int(os.environ.get("FMRP_BENCH_GRID_FACT_MONTHS", 60))
    # the firm axis is the lever that makes the contraction the dominant
    # cost (the regime the factorization targets): N=8000 measured 2.1x
    # on the bench box vs 1.9x at N=4000 (rows_for + per-run dispatch are
    # route-independent floors)
    n = int(os.environ.get("FMRP_BENCH_GRID_FACT_FIRMS",
                           200 if fast else 8000))
    p = 8
    draws = int(os.environ.get("FMRP_BENCH_GRID_FACT_DRAWS",
                               8 if fast else 32))
    n_sets = 4 if fast else 12
    y, x, subsets = _make_panel(t, n, p)
    masks = dict(zip(("All", "All-but-tiny", "Large"), subsets))
    names = [f"x{i:02d}" for i in range(p)]
    rng = np.random.default_rng(2014)
    sets = [("s00_full", tuple(names))]
    while len(sets) < n_sets:
        k = 2 + (len(sets) % (p - 2))
        cols = np.sort(rng.choice(p, size=k, replace=False))
        sets.append((f"s{len(sets):02d}_{k}", tuple(names[c] for c in cols)))
    # 8 windows (full + 7 staggered subsamples): windows are the axis the
    # factorization collapses, and they also shrink the tile's pair pad —
    # at spec_pad=16 a tile spans ≤3 pairs vs 16 legacy spec rows
    # (measured 2.7x on the bench box vs 2.1x with 4 windows)
    n_wins = 4 if fast else 8
    windows = (("full", None),) + tuple(
        (f"w{i}", (i * t // 10, t - (n_wins - 2 - i) * t // 30))
        for i in range(n_wins - 1)
    )
    space = CellSpace(
        regressor_sets=tuple(sets), universes=tuple(masks),
        windows=windows, bootstrap=draws,
    )
    routes = {
        "legacy": dict(factorize="off", boot_route="host"),
        "fact": dict(factorize="on", boot_route="device"),
        "fact_host": dict(factorize="on", boot_route="host"),
    }
    out = {
        "grid_factorized_shape":
            f"T{t}_N{n}_P{p}_S{space.n_specs}_D{draws}",
        "grid_factorized_cells": len(space),
        "grid_factorized_pairs": space.n_pairs,
    }
    frames, warm = {}, {}
    for label, kw in routes.items():
        # cold pass compiles; the warm repeat is the gated wall
        run_cellspace(y, x, masks, space, mask=masks["All"], **kw)
        before = contraction_counts()
        with _timed(f"bench.grid_factorized_{label}_warm") as warm_t:
            frame, stats = run_cellspace(
                y, x, masks, space, mask=masks["All"], **kw,
            )
        delta = {
            k: contraction_counts().get(k, 0) - before.get(k, 0)
            for k in ("specs_solved", "specs_contracted", "pairs_unique",
                      "pairs_contracted")
        }
        frames[label], warm[label] = frame, warm_t.s
        if label != "fact_host":
            out[f"grid_factorized_{label}_warm_s"] = round(warm_t.s, 4)
            out[f"grid_factorized_{label}_cells_per_s"] = round(
                len(space) / warm_t.s, 1)
        if label == "fact":
            assert stats["gram_factorize"] == "on"
            tiles = stats["tiles"]
            # the acceptance ledger: contraction rows per tile track the
            # tile's unique (universe, col_sel) pairs, not its spec count
            out["grid_factorized_specs_per_tile"] = round(
                delta["specs_solved"] / tiles, 1)
            out["grid_factorized_pairs_per_tile"] = round(
                delta["pairs_contracted"] / tiles, 1)
            out["grid_factorized_pairs_unique_per_tile"] = round(
                delta["pairs_unique"] / tiles, 1)
        elif label == "legacy":
            assert stats["gram_factorize"] == "off"
            out["grid_factorized_legacy_specs_per_tile"] = round(
                delta["specs_contracted"] / stats["tiles"], 1)
    out["grid_factorized_cells_per_s"] = out[
        "grid_factorized_fact_cells_per_s"]
    out["grid_factorized_speedup"] = round(
        warm["legacy"] / warm["fact"], 2)
    out["grid_boot_shape"] = out["grid_factorized_shape"]
    out["grid_boot_device_warm_s"] = round(warm["fact"], 4)
    out["grid_boot_host_warm_s"] = round(warm["fact_host"], 4)
    out["grid_boot_speedup"] = round(warm["fact_host"] / warm["fact"], 2)
    # parity pin: same cells, same draws, two routes (device draws carry
    # ~1e-9 of f32 gather/aggregation reordering vs the host loop)
    key = ["cell", "predictor"]
    a = frames["legacy"].sort_values(key).reset_index(drop=True)
    b = frames["fact"].sort_values(key).reset_index(drop=True)
    diffs = (a["coef"] - b["coef"]).abs()
    out["grid_factorized_max_abs_coef_diff"] = float(diffs.max())
    out["grid_factorized_nan_pattern_mismatches"] = int(
        (a["coef"].isna() != b["coef"].isna()).sum())

    # the bank leg: contract once, then answer a NEW window split and a
    # NEW bootstrap depth from banked stats — zero panel reads
    with _timed("bench.grid_factorized_bank_build") as build_t:
        bank = build_bank(y, x, masks, space, fingerprint="bench")
    boot_d = 8 if fast else 16
    bank_windows = subperiod_windows(t, 3)
    # cold pass compiles BOTH query programs (the window solve tail and
    # the D-draw pairs-batched aggregator); the timed repeat is warm
    scenario_query(bank, windows=bank_windows, bootstrap=boot_d)
    before = contraction_counts()
    with _timed("bench.grid_factorized_bank_query") as query_t:
        qframe = scenario_query(
            bank, windows=bank_windows, bootstrap=boot_d,
        )
    out["grid_factorized_bank_build_s"] = round(build_t.s, 4)
    out["grid_factorized_bank_query_s"] = round(query_t.s, 4)
    out["grid_factorized_bank_query_rows_per_s"] = round(
        len(qframe) / query_t.s, 1)
    out["grid_factorized_bank_query_panel_contractions"] = sum(
        contraction_counts().get(k, 0) - before.get(k, 0)
        for k in ("specs_contracted", "pairs_contracted")
    )
    return out


def _bench_estimators(fast: bool):
    """Estimator subsystem ladder (the ISSUE-16 acceptance evidence):
    the SAME cell space runs warm under each estimator kind — OLS (the
    incumbent tile route), FWL partialling-out (Schur complement on the
    per-month Grams), absorbed FE (alternating projections on per-month
    cell stats), and IV/2SLS (two Gram solves) — so the per-kind
    ``estimators_*_cells_per_s`` series price exactly the transform each
    kind adds on top of the shared contraction. The FWL warm repeat runs
    under ``recompile_watch``: every estimator rides the one jitted
    estimator program, so a warm re-sweep must compile nothing.

    The bank leg times ``grambank.estimator_query`` answering an FWL
    cell from banked stats; the contraction ledger staying flat across
    the query pins the zero-panel-reads acceptance criterion. Series are
    shape-qualified via ``estimators_shape`` (device-dependent walls).
    FMRP_BENCH_ESTIMATORS=0 skips."""
    if os.environ.get("FMRP_BENCH_ESTIMATORS", "1") == "0":
        return {}
    from fm_returnprediction_tpu.specgrid import CellSpace, run_cellspace
    from fm_returnprediction_tpu.specgrid.estimators import (
        EST_OLS,
        Estimator,
    )
    from fm_returnprediction_tpu.specgrid.grambank import (
        build_bank,
        estimator_query,
    )
    from fm_returnprediction_tpu.specgrid.solve import contraction_counts
    from fm_returnprediction_tpu.telemetry import recompile_watch

    t = int(os.environ.get("FMRP_BENCH_ESTIMATORS_MONTHS", 48))
    n = int(os.environ.get("FMRP_BENCH_ESTIMATORS_FIRMS",
                           300 if fast else 4000))
    p = 6
    y, x, subsets = _make_panel(t, n, p)
    masks = dict(zip(("All", "All-but-tiny", "Large"), subsets))
    names = [f"x{i:02d}" for i in range(p)]
    rng = np.random.default_rng(2016)
    fe_codes = {"ind": rng.integers(0, 12, size=(t, n))}
    # focal sets over the first 5 columns; the 6th is the estimator's
    # auxiliary column (FWL control / excluded instrument), appended to
    # the union by the estimator dimension itself
    sets = tuple(
        (f"s{k}", tuple(names[:2 + k])) for k in range(2 if fast else 4)
    )
    windows = (("full", None), ("late", (t // 2, t)))
    ladder = {
        "ols": EST_OLS,
        "fwl": Estimator(kind="fwl", controls=(names[-1],)),
        "absorb": Estimator(kind="absorb", absorb=("ind",)),
        "iv": Estimator(kind="iv", endog=(names[1],),
                        instruments=(names[-1],)),
    }
    out = {"estimators_shape": f"T{t}_N{n}_P{p}_S{len(sets)}"}
    warm = {}
    for label, est in ladder.items():
        space = CellSpace(regressor_sets=sets, universes=tuple(masks),
                          windows=windows, estimators=(est,))
        # the union is the focal sets plus the estimator's aux columns —
        # slice the panel tensor into space.union_predictors order
        xs = x[:, :, [names.index(c) for c in space.union_predictors]]
        kw = dict(fe_codes=fe_codes) if label == "absorb" else {}
        run_cellspace(y, xs, masks, space, **kw)  # compile
        ctx = (recompile_watch("estimators_fwl_warm", warm=True)
               if label == "fwl" else nullcontext())
        with ctx as delta, _timed(f"bench.estimators_{label}_warm") as w:
            run_cellspace(y, xs, masks, space, **kw)
        warm[label] = w.s
        out[f"estimators_{label}_warm_s"] = round(w.s, 4)
        out[f"estimators_{label}_cells_per_s"] = round(len(space) / w.s, 1)
        if label == "fwl":
            out["estimators_fwl_warm_cache_growth"] = (
                delta.entries_after - delta.entries_before)
    for label in ("fwl", "absorb", "iv"):
        # the transform tax relative to the shared-contraction OLS floor
        out[f"estimators_{label}_vs_ols"] = round(
            warm[label] / warm["ols"], 2)

    # bank leg: one contraction, then FWL cells answered from the bank
    bank_space = CellSpace(regressor_sets=(("full", tuple(names)),),
                           universes=tuple(masks), windows=(("full", None),))
    with _timed("bench.estimators_bank_build") as build_t:
        bank = build_bank(y, x, masks, bank_space)
    estimator_query(bank, f"fwl:{names[-1]}")  # compile the query program
    reps = 5
    before = contraction_counts()
    with _timed("bench.estimators_bank_query") as q:
        for _ in range(reps):
            estimator_query(bank, f"fwl:{names[-1]}")
    out["estimators_bank_build_s"] = round(build_t.s, 4)
    out["estimators_bank_query_ms"] = round(q.s / reps * 1e3, 2)
    out["estimators_bank_query_panel_contractions"] = sum(
        contraction_counts().get(k, 0) - before.get(k, 0)
        for k in ("specs_contracted", "pairs_contracted")
    )
    return out


def _bench_backtest(fast: bool):
    """Rolling-origin backtest subsystem (the ISSUE-18 acceptance
    evidence), four legs:

    - **origins/s ladder** — the warm prefix-sum scan program
      (``backtest.paths``) per scheme: one batched per-month solve plus a
      masked prefix sum answers EVERY origin at once, so the series
      prices origins per second, not solves per origin. The warm repeat
      runs under ``recompile_watch`` — a re-trace of the path program is
      a regression.
    - **bank-vs-refit speedup** — the same paths through the per-origin
      full-refit differential oracle (``route="refit"``): the ratio is
      the factorization win the scan route exists for
      (``backtest_scan_vs_refit_speedup``, higher is better).
    - **zero-contraction pin** — a full sweep (2 schemes × EW/VW through
      ``run_backtest``) answered from the bank with the panel-contraction
      ledger delta reported; 0 is the acceptance criterion
      (``backtest_sweep_panel_contractions``).
    - **portfolio consumer vs a live fleet** — the ``loadgen``
      ``portfolio_consumer`` phase forms portfolios from E[r] quotes
      served THROUGH a 2-replica fleet (admission, routing,
      microbatching), with rows/s + p99 disclosed and the request
      journal replayed clean (``backtest_consumer_journal_clean``).

    Series are shape-qualified via ``backtest_shape`` (device-dependent
    walls). FMRP_BENCH_BACKTEST=0 skips."""
    if os.environ.get("FMRP_BENCH_BACKTEST", "1") == "0":
        return {}
    import tempfile

    from fm_returnprediction_tpu.backtest import (
        backtest_paths,
        backtest_space,
        run_backtest,
    )
    from fm_returnprediction_tpu.serving import (
        ServingFleet,
        build_serving_state,
        portfolio_consumer,
        replay_journal,
    )
    from fm_returnprediction_tpu.specgrid.cellspace import CellSpace
    from fm_returnprediction_tpu.specgrid.grambank import build_bank
    from fm_returnprediction_tpu.specgrid.solve import contraction_counts
    from fm_returnprediction_tpu.telemetry import recompile_watch

    t = int(os.environ.get("FMRP_BENCH_BACKTEST_MONTHS",
                           48 if fast else 240))
    n = int(os.environ.get("FMRP_BENCH_BACKTEST_FIRMS",
                           160 if fast else 2000))
    p = 4
    y, x, subsets = _make_panel(t, n, p)
    masks = dict(zip(("All", "Big"), subsets[:2]))
    names = tuple(f"x{i:02d}" for i in range(p))
    window = max(t // 4, 6)
    schemes = ("expanding", f"rolling{window}")
    space = CellSpace(
        regressor_sets=(("m2", names[:2]), ("full", names)),
        universes=tuple(masks), windows=(("full", None),),
    )
    out = {"backtest_shape": f"T{t}_N{n}_P{p}_K{2 * len(masks)}"}

    with _timed("bench.backtest_bank_build") as build_t:
        bank = build_bank(y, x, masks, space)
    out["backtest_bank_build_s"] = round(build_t.s, 4)

    # origins/s ladder: warm scan program per scheme, the warm repeat of
    # the first scheme under the recompile sentinel
    for i, scheme in enumerate(schemes):
        backtest_paths(bank, scheme, route="scan")  # compile
        ctx = (recompile_watch("backtest_scan_warm", warm=True)
               if i == 0 else nullcontext())
        with ctx as delta, _timed(f"bench.backtest_scan_{scheme}") as w:
            backtest_paths(bank, scheme, route="scan")
        key = "expanding" if i == 0 else "rolling"
        out[f"backtest_{key}_warm_s"] = round(w.s, 4)
        out[f"backtest_{key}_origins_per_s"] = round(t / w.s, 1)
        if i == 0:
            out["backtest_scan_warm_cache_growth"] = (
                delta.entries_after - delta.entries_before)

    # the refit oracle prices what the scan route replaced: T origins,
    # each a masked Gram re-aggregation + fresh solve
    backtest_paths(bank, "expanding", route="refit")  # compile
    with _timed("bench.backtest_refit") as refit_t:
        backtest_paths(bank, "expanding", route="refit")
    out["backtest_refit_s"] = round(refit_t.s, 4)
    out["backtest_scan_vs_refit_speedup"] = round(
        refit_t.s / out["backtest_expanding_warm_s"], 1)

    # full sweep from the bank — the ledger delta is the acceptance pin
    bt_space = backtest_space(
        bank, schemes=",".join(schemes), weightings=("ew", "vw"),
        n_quantiles=5, min_obs=min(30, max(n // 8, 5)),
    )
    rng = np.random.default_rng(2018)
    weights = np.abs(rng.lognormal(size=(t, n))) + 0.1  # synthetic ME
    run_backtest(bank, x, y, masks, space=bt_space,
                 weights_var=weights)  # compile
    before = contraction_counts()
    with _timed("bench.backtest_sweep") as sweep_t:
        _, stats = run_backtest(bank, x, y, masks, space=bt_space,
                                weights_var=weights)
    after = contraction_counts()
    out["backtest_sweep_cells"] = stats["cells"]
    out["backtest_sweep_warm_s"] = round(sweep_t.s, 4)
    out["backtest_sweep_cells_per_s"] = round(stats["cells"] / sweep_t.s, 1)
    out["backtest_sweep_panel_contractions"] = sum(
        after.get(k, 0) - before.get(k, 0)
        for k in ("specs_contracted", "pairs_contracted")
    )

    # portfolio consumer vs a live fleet: E[r] quotes through the front
    # door, portfolios formed host-side, journal replayed clean
    state = build_serving_state(
        y, x, np.isfinite(y), window=min(120, t // 2),
        min_periods=min(60, t // 4),
    )
    q_months = int(os.environ.get("FMRP_BENCH_BACKTEST_CONSUMER_MONTHS", 3))
    q_firms = min(n, 48 if fast else 128)
    have = np.nonzero(state.have_coef())[0]
    pick = have[-q_months:] if len(have) >= q_months else have
    with tempfile.TemporaryDirectory() as root:
        journal = os.path.join(root, "journal.jsonl")
        with ServingFleet(state, 2, max_batch=64, max_latency_ms=1.0,
                          journal=journal) as fleet:
            report = portfolio_consumer(
                fleet, pick, x[pick][:, :q_firms], n_quantiles=5,
            )
        replay = replay_journal(journal)
    out["backtest_consumer_rows_per_s"] = report["rows_per_s"]
    out["backtest_consumer_p99_ms"] = report["p99_ms"]
    out["backtest_consumer_quotes"] = report["n"]
    out["backtest_consumer_months_formed"] = report["months_formed"]
    out["backtest_consumer_shed"] = report["shed"]
    out["backtest_consumer_journal_clean"] = bool(replay.clean)
    return out


def _bench_serving(fast: bool):
    """Warm microbatched serving path on a synthetic state (the online
    E[r] query service, ``fm_returnprediction_tpu/serving``): build a
    fitted state from a synthetic panel, warm every query bucket (so the
    stream pays zero compiles — asserted by the cache counters), then push
    a threaded stream of single-firm queries through the microbatcher and
    record qps and tail latency from the service's own instrumentation.
    FMRP_BENCH_SERVING=0 skips; _QUERIES resizes the stream."""
    import concurrent.futures

    from fm_returnprediction_tpu.serving import ERService, build_serving_state

    t, n, p = (60, 200, 5) if fast else (600, 2000, 5)
    n_queries = int(os.environ.get(
        "FMRP_BENCH_SERVING_QUERIES", 200 if fast else 1000
    ))
    rng = np.random.default_rng(2015)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = (rng.standard_normal(p) * 0.05).astype(np.float32)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)

    state = build_serving_state(
        y, x, mask, window=min(120, t // 2), min_periods=min(60, t // 4)
    )
    months = rng.integers(t // 2, t, n_queries)
    firms = rng.integers(0, n, n_queries)
    with ERService(state, max_batch=64, max_latency_ms=1.0, warm=True) as svc:
        base_hits, base_misses = svc.executor.hits, svc.executor.misses
        with _timed("bench.serving_stream") as wall_t:
            with concurrent.futures.ThreadPoolExecutor(max_workers=8) as pool:
                futs = list(pool.map(
                    lambda q: svc.query(int(months[q]), x[months[q], firms[q]]),
                    range(n_queries),
                ))
        wall = wall_t.s
        stats = svc.stats()
        assert len(futs) == n_queries
    # the cost ledger's view of what warm-up bought: every bucket
    # program's compile seconds and FLOPs are accounted per compile
    from fm_returnprediction_tpu import telemetry as _telemetry

    ledger = _telemetry.cost_ledger()
    bucket_records = [
        r for r in ledger.records() if r.program == "serving_bucket"
    ]
    return {
        "serving_qps": round(n_queries / wall, 1),
        "serving_p50_ms": round(stats["p50_ms"], 3),
        "serving_p99_ms": round(stats["p99_ms"], 3),
        "serving_batch_occupancy": round(stats["batch_occupancy"], 4),
        "serving_cache_misses_after_warm": svc.executor.misses - base_misses,
        "serving_dispatches": svc.executor.hits - base_hits,
        "serving_ledger_programs": len(bucket_records),
        "serving_ledger_compile_s": round(
            sum(r.lower_s + r.compile_s for r in bucket_records), 4
        ),
        "serving_shape": f"T{t}_P{p}_Q{n_queries}",
    }


def _bench_fleet(fast: bool):
    """Resilient serving fleet under sustained multi-worker load
    (``serving.fleet``, ISSUE 10): 3 replicas behind the admission-
    controlled front tier, driven by 8 query workers through three
    phases —

    - ``fleet_rows_per_s`` / ``fleet_p99_ms_steady``  — (a) steady state
      (the higher-is-better throughput series the PR-6 regress sentinel
      gates; a warm repeat runs under ``recompile_watch`` so any fleet
      re-trace is flagged);
    - ``fleet_rows_per_s_swap`` / ``fleet_p99_ms_swap`` — (b) THROUGH a
      two-phase zero-downtime state rollover fired mid-phase;
    - ``fleet_rows_per_s_kill`` / ``fleet_p99_ms_kill`` — (c) THROUGH a
      replica kill + supervisor failover fired mid-phase, the
      replacement starting compile-free from the registry warm pool
      (``fleet_failover_*`` = its WarmReport evidence).

    ``fleet_journal`` is the write-ahead journal's replay verdict over
    ALL phases: zero dropped / zero duplicated is the exactly-once proof
    demanded by the acceptance criteria, reported (not asserted) here
    and asserted in ``tests/test_fleet.py``. FMRP_BENCH_FLEET=0 skips;
    _FLEET_QUERIES resizes each phase."""
    if os.environ.get("FMRP_BENCH_FLEET", "1") == "0":
        return {}
    import tempfile
    import threading as _threading

    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.registry.store import using_registry
    from fm_returnprediction_tpu.serving import (
        ERService,
        ServingFleet,
        build_serving_state,
        ingest_month,
        replay_journal,
    )

    t, n, p = (60, 200, 5) if fast else (240, 1000, 5)
    per_phase = int(os.environ.get(
        "FMRP_BENCH_FLEET_QUERIES", 300 if fast else 2000
    ))
    n_workers = 8
    rng = np.random.default_rng(2015)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = (rng.standard_normal(p) * 0.05).astype(np.float32)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(
        y, x, mask, window=min(120, t // 2), min_periods=min(60, t // 4)
    )
    new_state = ingest_month(
        state, y[-1], x[-1], mask[-1], np.datetime64("2035-01-31", "ns")
    )

    out = {}
    with tempfile.TemporaryDirectory() as root:
        reg_dir = os.path.join(root, "registry")
        # populate the warm pool for BOTH versions: one process compiles,
        # every replica (incl. the failover replacement and the rollover
        # prepare) fetches — the registry story applied to the fleet
        with using_registry(reg_dir):
            ERService(state, max_batch=64, auto_flush=False).close()
            ERService(new_state, max_batch=64, auto_flush=False).close()
        journal = os.path.join(root, "journal.jsonl")
        fleet = ServingFleet(
            state, 3, max_batch=64, max_latency_ms=1.0,
            registry_dir=reg_dir, journal=journal,
        )
        out["fleet_zero_compile_starts"] = sum(
            1 for r in fleet.warm_reports.values() if r.zero_compile
        )

        errors = []

        def drive(action=None):
            """One phase: n_workers blocking-query threads; ``action``
            fires from the driver thread once roughly half the phase has
            completed (the swap/kill lands genuinely mid-load). A failed
            query must not poison the quantiles with an uninitialized
            slot OR silently kill its worker — it records NaN and an
            error entry, disclosed as ``fleet_query_errors``."""
            mon = rng.integers(t // 2, t, per_phase)
            frm = rng.integers(0, n, per_phase)
            lat = np.full(per_phase, np.nan)
            chunk = per_phase // n_workers

            def worker(k0, k1):
                for k in range(k0, k1):
                    t0 = time.perf_counter()
                    try:
                        fleet.query(int(mon[k]), x[mon[k], frm[k]])
                    except Exception as exc:  # noqa: BLE001 - disclosed
                        errors.append(repr(exc)[:200])
                        continue
                    lat[k] = time.perf_counter() - t0

            # mid-phase trigger keys off COMPLETED queries THIS phase:
            # done + failed, both baselined at phase start, so neither a
            # shed storm (stalled poll) nor prior-phase errors (premature
            # trigger under zero load) can misplace the swap/kill
            base_done = fleet.stats()["agg_n_done"]
            base_errors = len(errors)
            t0 = time.perf_counter()
            threads = [
                _threading.Thread(target=worker, args=(
                    k * chunk,
                    (k + 1) * chunk if k < n_workers - 1 else per_phase,
                ))
                for k in range(n_workers)
            ]
            for th in threads:
                th.start()
            if action is not None:
                while (
                    fleet.stats()["agg_n_done"] - base_done
                    + len(errors) - base_errors
                    < per_phase // 2
                ):
                    time.sleep(0.002)
                action()
            for th in threads:
                th.join()
            wall = time.perf_counter() - t0
            ok = int(np.isfinite(lat).sum())
            return (
                round(ok / wall, 1),
                round(float(np.nanpercentile(lat, 99) * 1e3), 3)
                if ok else None,
            )

        # (a) steady state + warm repeat under the recompile sentinel
        out["fleet_rows_per_s"], out["fleet_p99_ms_steady"] = drive()
        with telemetry.recompile_watch("fleet_steady", warm=True):
            out["fleet_rows_per_s_warm"], _ = drive()

        # (b) through a zero-downtime state swap
        out["fleet_rows_per_s_swap"], out["fleet_p99_ms_swap"] = drive(
            action=lambda: fleet.rollover(new_state)
        )
        out["fleet_version_after_swap"] = fleet.version

        # (c) through a replica kill + supervised warm-pool failover
        victim = sorted(fleet.replica_states())[0]

        def kill_and_failover():
            fleet.kill_replica(victim, reason="bench chaos")
            fleet.supervisor.tick()   # replace immediately

        out["fleet_rows_per_s_kill"], out["fleet_p99_ms_kill"] = drive(
            action=kill_and_failover
        )
        stats = fleet.stats()
        out["fleet_requeues"] = stats["requeues_total"]
        out["fleet_failovers"] = stats["failovers_total"]
        replacement = max(
            fleet.warm_reports, key=lambda rid: int(rid.lstrip("r"))
        )
        report = fleet.warm_reports[replacement]
        out["fleet_failover_fresh_compiles"] = report.fresh_compiles
        out["fleet_failover_deserialized"] = report.deserialized
        out["fleet_failover_wall_s"] = round(report.wall_s, 4)
        out["fleet_failover_compile_s_saved"] = round(report.saved_s, 4)

        fleet.drain(timeout=30)
        fleet.close()
        out["fleet_query_errors"] = len(errors)
        if errors:
            out["fleet_query_error_sample"] = errors[0]
        replay = replay_journal(journal)
        out["fleet_journal"] = {
            "admitted": replay.n_admitted,
            "done": replay.n_done,
            "requeues": replay.n_requeues,
            "shed": replay.n_shed,
            "dropped": len(replay.dropped),
            "duplicated": len(replay.duplicated),
            "clean": bool(replay.clean),
        }
    out["fleet_shape"] = f"T{t}_P{p}_R3_Q{per_phase}x4"
    return out


def _bench_fleet_capacity(fast: bool):
    """Overload-survival layer (``serving.loadgen``/``brownout``, ISSUE
    12): the capacity curve and the bench-demonstrated overload episode.

    - ``fleet_capacity_rR_bB_rows_per_s`` / ``_p99_ms`` — measured
      replicas × max_batch capacity curve under closed-loop bursts from
      the adversarial load harness (higher-is-better series the PR-6
      regress sentinel gates, shape-qualified by
      ``fleet_capacity_shape``).
    - ``fleet_capacity_model_*`` — the predicted per-replica rows/s from
      the PR-6 cost ledger (serving-bucket FLOPs/row) + a measured
      full-bucket dispatch probe, and ``fleet_capacity_model_ratio`` =
      measured / predicted at the top configuration (the validation the
      capacity model owes; the dispatch ceiling binds on CPU).
    - ``fleet_overload_*`` — one sustained-ramp overload episode against
      a deliberately small-capacity fleet: the autoscaler scales out
      (compile-free, WarmReport evidence), the brownout ladder steps to
      disclosed degraded routes once scale-out is exhausted, p99 over the
      episode stays bounded (degraded answers bypass the saturated
      queues), and after the ramp the ladder recovers hysteretically to
      full service. The journal replay verdict covers the whole episode.

    FMRP_BENCH_FLEET_CAPACITY=0 skips; _FLEET_QUERIES scales the curve."""
    if os.environ.get("FMRP_BENCH_FLEET_CAPACITY", "1") == "0":
        return {}
    import tempfile

    from fm_returnprediction_tpu.registry import artifacts
    from fm_returnprediction_tpu.registry.store import using_registry
    from fm_returnprediction_tpu.serving import (
        AdmissionPolicy,
        AutoscalePolicy,
        BrownoutPolicy,
        ERService,
        LoadGen,
        LoadPhase,
        ServingFleet,
        build_serving_state,
        capacity_model,
        replay_journal,
    )

    t, n, p = (60, 200, 5) if fast else (240, 1000, 5)
    per_config = int(os.environ.get(
        "FMRP_BENCH_FLEET_QUERIES", 300 if fast else 2000
    ))
    rng = np.random.default_rng(2016)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    beta = (rng.standard_normal(p) * 0.05).astype(np.float32)
    y = (x @ beta + 0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(
        y, x, mask, window=min(120, t // 2), min_periods=min(60, t // 4)
    )
    months = rng.integers(t // 2, t, 4096)
    rows = x[months, rng.integers(0, n, 4096)]

    out = {}
    with tempfile.TemporaryDirectory() as root:
        reg_dir = os.path.join(root, "registry")
        with using_registry(reg_dir) as reg:
            # one process compiles + publishes; every fleet below (and
            # every autoscaler spawn inside the episode) fetches
            for b in (32, 128):
                ERService(state, max_batch=b, auto_flush=False).close()
            artifacts.put_serving_state(state, "bench-capacity",
                                        registry=reg)

        # -- the capacity curve: replicas × batch → rows/s, p99 ----------
        replica_ladder = (1, 2) if fast else (1, 2, 4)
        model_ratio = None
        for r in replica_ladder:
            for b in (32, 128):
                fleet = ServingFleet(
                    state, r, max_batch=b, max_latency_ms=1.0,
                    registry_dir=reg_dir,
                )
                try:
                    gen = LoadGen(fleet, months, rows, seed=12)
                    rep = gen.run([LoadPhase(
                        f"burst_r{r}_b{b}", n_requests=per_config,
                        workers=8,
                    )])["phases"][0]
                    out[f"fleet_capacity_r{r}_b{b}_rows_per_s"] = (
                        rep["rows_per_s"]
                    )
                    out[f"fleet_capacity_r{r}_b{b}_p99_ms"] = rep["p99_ms"]
                    out[f"fleet_capacity_r{r}_b{b}_shed_rate"] = (
                        rep["shed_rate"]
                    )
                    if (r, b) == (replica_ladder[-1], 128):
                        model = capacity_model(fleet)
                        out["fleet_capacity_model"] = model
                        if rep["rows_per_s"] and model[
                                "predicted_rows_per_s"]:
                            model_ratio = round(
                                rep["rows_per_s"]
                                / model["predicted_rows_per_s"], 4
                            )
                    fleet.drain(timeout=30)
                finally:
                    fleet.close()
        out["fleet_capacity_model_ratio"] = model_ratio

        # -- the overload episode: ramp → scale-out → brownout → recover -
        # A modern CPU answers these tiny projections too fast to
        # saturate honestly, so the ADVERSARIAL part is injected: the
        # ``serving.dispatch`` chaos site stalls every device dispatch
        # 10 ms (a slow/tunneled backend), pinning per-replica capacity
        # near max_batch/stall ≈ 800 rows/s on ANY box — which the ramp
        # then deliberately overruns. Disclosed as
        # ``fleet_overload_stall_ms``; the brownout's host-side degraded
        # routes bypass the stalled dispatch, which is exactly the
        # mechanism under demonstration.
        from fm_returnprediction_tpu.resilience.faults import (
            FaultPlan,
            FaultSpec,
        )

        stall_s = 0.010
        journal = os.path.join(root, "overload.jsonl")
        fleet = ServingFleet(
            state, 1, max_batch=8, max_latency_ms=5.0, max_queue=32,
            registry_dir=reg_dir, journal=journal,
            admission=AdmissionPolicy(max_occupancy=1.01),
            autoscale=AutoscalePolicy(
                min_replicas=1, max_replicas=2, cooldown_s=0.15,
                out_occupancy=0.4, in_occupancy=0.05, in_ticks=4,
            ),
            brownout=BrownoutPolicy(
                ladder=("full", "coreset", "shed"),
                enter_burn=1e9, exit_burn=1.0,
                enter_occupancy=0.5, exit_occupancy=0.1,
                dwell_ticks=1, recover_ticks=2,
            ),
        )
        try:
            gen = LoadGen(fleet, months, rows, seed=13, tick_s=0.05)
            with FaultPlan({
                "serving.dispatch": FaultSpec(times=-1, delay_s=stall_s),
            }):
                # 64 submitting workers: a blocking worker caps its own
                # in-flight at 1, so concurrency IS the queue-depth
                # adversary (8 workers can never fill a 64-slot queue)
                report = gen.run([
                    LoadPhase("ramp", n_requests=per_config, workers=64,
                              rate_per_s=400.0, ramp=True),
                    LoadPhase("sustain", n_requests=3 * per_config,
                              workers=96, rate_per_s=2500.0),
                ])
            out["fleet_overload_stall_ms"] = stall_s * 1e3
            stats = fleet.stats()
            out["fleet_overload_scale_outs"] = stats["scale_out_total"]
            out["fleet_overload_degraded"] = stats["degraded_total"]
            out["fleet_overload_shed"] = stats["shed_total"]
            sustain = report["phases"][1]
            out["fleet_overload_p99_ms_sustain"] = sustain["p99_ms"]
            out["fleet_overload_p99_ms_degraded_sustain"] = (
                sustain["p99_ms_degraded"]
            )
            out["fleet_overload_degraded_frac_sustain"] = (
                sustain["degraded_frac"]
            )
            out["fleet_overload_shed_rate_sustain"] = sustain["shed_rate"]
            # scale-out evidence: every autoscaler spawn started through
            # the warm pool with zero fresh compiles
            scaled = [
                rid for rid in fleet.warm_reports
                if rid not in ("r0",)
            ]
            out["fleet_overload_scale_out_zero_compile"] = all(
                fleet.warm_reports[rid].zero_compile for rid in scaled
            ) if scaled else None
            # hysteretic recovery: drain, then tick until the ladder is
            # back at full service (bounded wait, disclosed on timeout)
            fleet.drain(timeout=30)
            recovered = False
            for _ in range(80):
                fleet.supervisor.tick()
                if fleet.brownout is not None and not fleet.brownout.active:
                    recovered = True
                    break
                time.sleep(0.02)
            out["fleet_overload_recovered"] = recovered
            out["fleet_overload_final_rung"] = (
                fleet.stats()["brownout_rung"]
            )
        finally:
            fleet.close()
        replay = replay_journal(journal)
        out["fleet_overload_journal"] = {
            "admitted": replay.n_admitted,
            "done": replay.n_done,
            "shed": replay.n_shed,
            "dropped": len(replay.dropped),
            "duplicated": len(replay.duplicated),
            "clean": bool(replay.clean),
            "brownout_marks": sum(
                1 for m in replay.marks if m.get("label") == "brownout"
            ),
            "scale_marks": sum(
                1 for m in replay.marks
                if m.get("label") in ("scale_out", "scale_in", "retire")
            ),
        }
    out["fleet_capacity_shape"] = f"T{t}_P{p}_Q{per_config}"
    out["fleet_overload_shape"] = f"T{t}_P{p}_Q{per_config}x2_R1to2_B8"
    return out


def _bench_resilience(fast: bool):
    """The fault-tolerance layer's numbers (``resilience`` subsystem):

    - ``resilience_retry_*``        — a transiently failing call retried to
      success under the shared policy (attempt counts from the plan's own
      ledger, zero-wall-clock backoff).
    - ``serving_p50_degraded_*``    — quote latency with the service in
      DEGRADED mode (a quarantined ingest month) vs healthy, on the same
      warmed state: degradation must cost visibility, not latency.
    - ``resume_stage_s``            — checkpoint-resume wall-clock: the
      pipeline crashed (injected) at each reporting stage, then resumed;
      each entry is the resume run's wall vs the full run's. The pipeline
      shapes are intentionally small — the section measures the MACHINERY
      (what fraction of a run a resume pays), not device throughput.

    FMRP_BENCH_RESIL=0 skips."""
    if os.environ.get("FMRP_BENCH_RESIL", "1") == "0":
        return {}
    import tempfile

    from fm_returnprediction_tpu.resilience import (
        FaultPlan,
        FaultSpec,
        RetryPolicy,
        call_with_retry,
        fault_site,
    )

    out = {}

    # -- retry counts ------------------------------------------------------
    with FaultPlan({"bench.flaky": FaultSpec(times=2)}) as plan:
        call_with_retry(
            lambda: fault_site("bench.flaky") or True,
            RetryPolicy(max_attempts=4, backoff_s=0.0),
            sleep=lambda s: None,
        )
    out["resilience_retry_attempts"] = int(plan.calls["bench.flaky"])
    out["resilience_retry_faults_injected"] = int(plan.fired["bench.flaky"])

    # -- degraded-mode quote latency vs healthy ----------------------------
    from fm_returnprediction_tpu.serving import ERService, build_serving_state

    t, n, p = (48, 80, 5) if fast else (120, 400, 5)
    n_queries = 200 if fast else 600
    rng = np.random.default_rng(2016)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    y = (0.1 * rng.standard_normal((t, n))).astype(np.float32)
    mask = rng.random((t, n)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(
        y, x, mask, window=t // 2, min_periods=t // 4
    )
    months = rng.integers(t * 3 // 4, t, n_queries)
    firms = rng.integers(0, n, n_queries)

    def p50(svc):
        # per-phase samples, NOT svc.stats()["p50_ms"]: the batcher's
        # latency ring is cumulative, so the post-quarantine read there
        # would pool healthy samples into the degraded median and mask
        # exactly the regression this comparison exists to catch
        lat = np.empty(n_queries)
        for q in range(n_queries):
            t0 = time.perf_counter()
            svc.query(int(months[q]), x[months[q], firms[q]])
            lat[q] = time.perf_counter() - t0
        return float(np.percentile(lat, 50) * 1e3)

    with ERService(state, max_batch=64, max_latency_ms=0.5, warm=True) as svc:
        healthy = p50(svc)
        # poison an ingest: all-NaN cross-section for the next month →
        # quarantined, service keeps quoting from last-known-good
        bad_x = np.full((n, p), np.nan, dtype=np.float32)
        bad_month = np.datetime64("2070-01-31", "ns")
        accepted = svc.ingest_month(
            np.full(n, np.nan), bad_x, np.ones(n, bool), bad_month
        )
        degraded = p50(svc)
        stats = svc.stats()
    out["serving_p50_healthy_ms"] = round(healthy, 3)
    out["serving_p50_degraded_ms"] = round(degraded, 3)
    out["serving_degraded_mode"] = bool(stats["degraded"]) and not accepted
    out["serving_quarantined_months"] = len(stats["quarantined_months"])

    # -- checkpoint-resume wall-clock savings ------------------------------
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.pipeline import run_pipeline

    cfg = SyntheticConfig(*( (20, 36) if fast else (40, 72) ))
    stages = ("table_1", "table_2", "decile_table", "serving_state")
    resume_s = {}
    with tempfile.TemporaryDirectory() as root:
        kw = dict(
            synthetic=True, synthetic_config=cfg, make_figure=False,
            make_deciles=True, make_serving=True, compile_pdf=False,
        )
        t0 = time.perf_counter()
        run_pipeline(**kw, checkpoint_dir=os.path.join(root, "warmref"))
        full = time.perf_counter() - t0
        for stage in stages:
            ck = os.path.join(root, f"crash_{stage}")
            try:
                with FaultPlan({f"pipeline.{stage}": FaultSpec()}):
                    run_pipeline(**kw, checkpoint_dir=ck)
            except OSError:
                pass  # the injected crash
            t0 = time.perf_counter()
            run_pipeline(**kw, checkpoint_dir=ck)  # resume
            resume_s[stage] = round(time.perf_counter() - t0, 3)
    out["resilience_pipeline_full_s"] = round(full, 3)
    out["resilience_resume_stage_s"] = resume_s
    return out


def _bench_guard(fast: bool):
    """The guardrail layer's price tag (``guard`` subsystem) — the numbers
    the README quotes for "free to leave on":

    - ``guard_panel_check_s``     — the whole per-run panel-stage guard
      cost (one fused probe program + host rule evaluation) vs the warm
      panel build it guards → ``guard_overhead_panel_pct``.
    - ``guard_table2_{on,off}_s`` — warm ``build_table_2`` wall-clock with
      sentinels armed vs disarmed (each configuration pre-compiled; the
      armed programs carry the counter reductions as extra outputs) →
      ``guard_overhead_table2_pct``. Acceptance bound: <5%.
    - ``guard_drift_check_s``     — summarize + tolerance-band compare of
      Table 2 against a committed audit manifest (the per-artifact drift
      sentinel cost).

    FMRP_BENCH_GUARD=0 skips."""
    if os.environ.get("FMRP_BENCH_GUARD", "1") == "0":
        return {}
    import tempfile

    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.guard import checks, contracts, drift
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.pipeline import build_panel, resolve_dtype
    from fm_returnprediction_tpu.reporting.table2 import build_table_2
    from fm_returnprediction_tpu.utils.timing import stage_sync

    t, n = (60, 80) if fast else (240, 800)
    data = generate_synthetic_wrds(SyntheticConfig(n_firms=n, n_months=t))
    with _timed("bench.guard_panel_build") as build_t:
        panel, factors = build_panel(data, dtype=resolve_dtype())
        stage_sync(panel.values)
    build_s = build_t.s
    masks = compute_subset_masks(panel)

    contracts.check_panel(panel)  # warm the probe program
    with _timed("bench.guard_panel_check") as check_t:
        contracts.check_panel(panel)
    check_s = check_t.s

    def timed_table2(guard_on: bool):
        with checks.guards(guard_on):
            build_table_2(panel, masks, factors)  # warm this configuration
            with _timed("bench.guard_table2", guard=guard_on) as tt:
                tab = build_table_2(panel, masks, factors)
            return tt.s, tab

    off_s, table_2 = timed_table2(False)
    on_s, _ = timed_table2(True)

    with tempfile.TemporaryDirectory() as d:
        base = drift.DriftSentinel(d, "bench")
        base.check("table_2", drift.summarize_frame(table_2))
        base.commit()
        with _timed("bench.guard_drift_check") as drift_t:
            probe = drift.DriftSentinel(d, "bench")
            drifted = probe.check("table_2", drift.summarize_frame(table_2))
        drift_s = drift_t.s
        assert drifted == []  # identical table: sha short-circuit

    return {
        "guard_panel_build_s": round(build_s, 4),
        "guard_panel_check_s": round(check_s, 4),
        "guard_overhead_panel_pct": round(100.0 * check_s / build_s, 2),
        "guard_table2_off_s": round(off_s, 4),
        "guard_table2_on_s": round(on_s, 4),
        "guard_overhead_table2_pct": round(
            100.0 * (on_s - off_s) / off_s, 2
        ),
        "guard_drift_check_s": round(drift_s, 4),
        "guard_shape": f"T{t}_N{n}",
    }


def _bench_registry(fast: bool):
    """The registry's executable plane as a tracked series (ROADMAP item
    5): cold compile-and-store vs cold-WITH-registry fetch for the same
    program set, and the ledger's fresh-vs-deserialized provenance split
    so the compile seconds the registry saves are a number the regress
    sentinel watches, not a one-off claim.

    - ``registry_cold_compile_s``   — warm-up of every serving bucket with
      an EMPTY registry armed (lower+compile, entries stored). Contains
      "compile" so the regress sentinel reports without gating (the wall
      swings with persistent-cache state, like every compile series).
    - ``registry_warm_fetch_s``     — the same warm-up in a fresh executor
      against the POPULATED registry: every program deserializes, nothing
      traces or compiles (asserted: fresh==0, trace growth==0, and a
      repeat under ``recompile_watch(warm=True)`` growing nothing).
    - ``registry_programs_per_s``   — fetch throughput (higher-is-better
      series for the sentinel).
    - ``registry_compile_s_saved``  — store-time compile seconds the fetch
      did NOT pay (the ledger's ``saved_s`` sum).
    - ``registry_provenance``       — per-program fresh/deserialized counts
      and seconds (``telemetry.perf.provenance_summary``).

    FMRP_BENCH_REGISTRY=0 skips."""
    if os.environ.get("FMRP_BENCH_REGISTRY", "1") == "0":
        return {}
    import tempfile

    from fm_returnprediction_tpu.registry import Registry, warm_from_registry
    from fm_returnprediction_tpu.registry.store import using_registry
    from fm_returnprediction_tpu.serving.executor import BucketedExecutor
    from fm_returnprediction_tpu.serving.state import build_serving_state
    from fm_returnprediction_tpu.telemetry import cost_ledger, recompile_watch
    from fm_returnprediction_tpu.telemetry.perf import provenance_summary

    t, n, p = (60, 64, 3) if fast else (240, 512, 5)
    rng = np.random.default_rng(2014)
    y = rng.standard_normal((t, n)).astype(np.float32)
    x = rng.standard_normal((t, n, p)).astype(np.float32)
    mask = np.ones((t, n), bool)
    state = build_serving_state(y, x, mask, window=24, min_periods=12)

    ledger = cost_ledger()
    with tempfile.TemporaryDirectory() as td:
        with using_registry(td):
            seq0 = ledger.last_seq
            with _timed("bench.registry_cold") as cold:
                BucketedExecutor(state).warmup()  # compile + store
            with _timed("bench.registry_fetch") as fetch:
                svc, report = warm_from_registry(state=state)
            svc.close()
            # the warm repeat must not compile: deserialized executables
            # never touch the XLA compile path, so cache growth is zero
            with recompile_watch("registry_warm_repeat", warm=True) as delta:
                svc2, repeat = warm_from_registry(state=state)
            svc2.close()
            store_bytes = sum(r["bytes"] for r in Registry(td).ls())
            summary = provenance_summary(ledger.since(seq0))
    out = {}
    if not fast and os.environ.get("FMRP_BENCH_REGISTRY_PIPE", "1") == "1":
        out.update(_registry_pipeline_children())
    return {
        **out,
        "registry_cold_compile_s": round(cold.s, 4),
        "registry_warm_fetch_s": round(fetch.s, 4),
        "registry_cold_vs_fetch_ratio": (
            round(cold.s / fetch.s, 2) if fetch.s > 0 else None
        ),
        "registry_programs_per_s": (
            round(report.deserialized / fetch.s, 2) if fetch.s > 0 else None
        ),
        "registry_deserialized": report.deserialized,
        "registry_fresh_compiles_on_fetch": report.fresh_compiles,
        "registry_trace_growth_on_fetch": report.trace_growth,
        "registry_repeat_zero_compile": repeat.zero_compile,
        "registry_warm_repeat_cache_growth": delta.grew,
        "registry_compile_s_saved": round(report.saved_s + repeat.saved_s, 4),
        "registry_store_bytes": store_bytes,
        "registry_provenance": {
            prog: {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in d.items()
            }
            for prog, d in summary.items()
        },
        "registry_shape": f"T{t}_N{n}_P{p}",
    }


_REGISTRY_CHILD_CODE = """
import json, sys, time
t0 = time.time()
from fm_returnprediction_tpu.pipeline import run_pipeline
from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
res = run_pipeline(
    synthetic=True, synthetic_config=SyntheticConfig(n_firms=48, n_months=60),
    make_figure=False, compile_pdf=False, make_deciles=False,
)
from fm_returnprediction_tpu.telemetry.perf import provenance_summary
print(json.dumps({
    "wall_s": round(time.time() - t0, 3),
    "provenance": provenance_summary(),
}))
"""


def _registry_pipeline_children() -> dict:
    """Cold-PROCESS pipeline walls, the acceptance comparison shape: a
    plain cold process vs a cold process with a populated registry (+ the
    persistent XLA cache the registry layers on). Three children at a
    small synthetic shape (process wall includes interpreter + jax
    import, identically on both sides):

    - ``registry_pipeline_cold_without_s`` — fresh XLA cache, no registry;
    - a populate child (fresh XLA cache B + empty registry) — its wall is
      reported as ``registry_pipeline_populate_s`` (disclosure: includes
      serialize+store);
    - ``registry_pipeline_cold_with_s`` — XLA cache B (warm) + the
      populated registry: the AOT programs deserialize, the rest rides
      the XLA cache."""
    import json
    import subprocess
    import sys
    import tempfile

    repo_root = os.path.dirname(os.path.abspath(__file__))

    def child(xla_dir: str, registry_dir: str | None) -> dict:
        env = dict(_child_env(repo_root))
        env["JAX_CACHE_DIR"] = xla_dir
        env.pop("FMRP_REGISTRY_DIR", None)
        if registry_dir is not None:
            env["FMRP_REGISTRY_DIR"] = registry_dir
        code = (
            "from fm_returnprediction_tpu.settings import "
            "enable_compilation_cache\nenable_compilation_cache()\n"
            + _REGISTRY_CHILD_CODE
        )
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, timeout=600, cwd=repo_root,
        )
        if proc.returncode != 0:
            return {"error": (proc.stderr or "")[-300:]}
        return json.loads(proc.stdout.strip().splitlines()[-1])

    out = {}
    with tempfile.TemporaryDirectory() as td:
        xla_a, xla_b = os.path.join(td, "xla_a"), os.path.join(td, "xla_b")
        reg = os.path.join(td, "registry")
        without = child(xla_a, None)
        populate = child(xla_b, reg)
        with_reg = child(xla_b, reg)
        for label, res in (("without", without), ("populate", populate),
                           ("with", with_reg)):
            if "error" in res:
                out[f"registry_pipeline_{label}_error"] = res["error"]
        if "wall_s" in without:
            out["registry_pipeline_cold_without_s"] = without["wall_s"]
        if "wall_s" in populate:
            out["registry_pipeline_populate_s"] = populate["wall_s"]
        if "wall_s" in with_reg:
            out["registry_pipeline_cold_with_s"] = with_reg["wall_s"]
            prov = with_reg.get("provenance", {})
            out["registry_pipeline_fetched_programs"] = sum(
                d.get("deserialized", 0) for d in prov.values()
            )
            out["registry_pipeline_fresh_aot_compiles"] = sum(
                d.get("fresh", 0) + d.get("uncached", 0)
                + d.get("persistent-cache", 0) for d in prov.values()
            )
        if "wall_s" in without and "wall_s" in with_reg and with_reg["wall_s"]:
            out["registry_pipeline_cold_with_vs_without"] = round(
                without["wall_s"] / with_reg["wall_s"], 3
            )
        # disclosure: at this synthetic shape the child walls are
        # dominated by interpreter+jax import (~4 s) and the per-program
        # compiles are sub-second, so the ratio is a mechanism check; the
        # real-shape cold−warm gap closure is the TPU/real-cache rounds'
        # number. On CPU the specgrid program is deliberately NOT stored
        # (custom-call pointer hazard — registry.executables) and rides
        # the persistent XLA cache instead, counted under
        # registry_pipeline_fresh_aot_compiles.
        out["registry_pipeline_shape"] = "T60_N48_synthetic_process_walls"
    return out


def _jax_cache_stats() -> dict:
    """Entry count + bytes of the persistent XLA compilation cache
    (``_cache/jax``) — the artifact-side evidence for whether the split
    reporting routes' per-cell programs survive across processes/rounds
    (round-4 VERDICT item 4). Promoted into the package
    (``telemetry.jax_cache_stats``, where it also feeds the registry's
    derived gauges); this thin alias keeps the bench's historical name."""
    from fm_returnprediction_tpu.telemetry import jax_cache_stats

    return jax_cache_stats()


def _bench_obs(fast: bool):
    """The telemetry layer's price tag (``telemetry`` subsystem) — the
    numbers the README quotes for "off is free, on is <5%":

    - ``obs_table2_{off,on}_s``      — warm ``build_table_2`` wall-clock
      with telemetry disarmed vs armed (spans around every stage/dispatch;
      the jitted programs are untouched either way — telemetry is
      host-side only) → ``obs_overhead_table2_pct``. Bound: <5%, same
      acceptance shape as ``guard_*``.
    - ``obs_serving_p50_{off,on}_ms`` — sequential single-query p50 on the
      same warmed service, telemetry off vs on (per-phase samples, not the
      batcher's cumulative ring — same discipline as the degraded-mode
      comparison) → ``obs_overhead_serving_p50_pct``.
    - ``obs_spans_recorded``          — how many spans the armed phases
      produced (the collector-side evidence the ON phase measured
      something real).

    FMRP_BENCH_OBS=0 skips."""
    if os.environ.get("FMRP_BENCH_OBS", "1") == "0":
        return {}
    from fm_returnprediction_tpu import telemetry
    from fm_returnprediction_tpu.data.synthetic import (
        SyntheticConfig,
        generate_synthetic_wrds,
    )
    from fm_returnprediction_tpu.panel.subsets import compute_subset_masks
    from fm_returnprediction_tpu.pipeline import build_panel, resolve_dtype
    from fm_returnprediction_tpu.reporting.table2 import build_table_2
    from fm_returnprediction_tpu.serving import ERService, build_serving_state

    spans_before = telemetry.collector_stats()["spans"]

    # -- warm table_2, telemetry off vs on ---------------------------------
    t, n = (60, 80) if fast else (240, 800)
    data = generate_synthetic_wrds(SyntheticConfig(n_firms=n, n_months=t))
    panel, factors = build_panel(data, dtype=resolve_dtype())
    masks = compute_subset_masks(panel)

    def timed_table2(tel_on: bool) -> float:
        with telemetry.enabled(tel_on):
            build_table_2(panel, masks, factors)  # warm
            with _timed("bench.obs_table2", telemetry_on=tel_on) as tt:
                build_table_2(panel, masks, factors)
            return tt.s

    off_s = timed_table2(False)
    on_s = timed_table2(True)

    # -- serving p50, telemetry off vs on ----------------------------------
    ts, ns, p = (48, 80, 5) if fast else (120, 400, 5)
    n_queries = 200 if fast else 600
    rng = np.random.default_rng(2017)
    x = rng.standard_normal((ts, ns, p)).astype(np.float32)
    y = (0.1 * rng.standard_normal((ts, ns))).astype(np.float32)
    mask = rng.random((ts, ns)) > 0.2
    y = np.where(mask, y, np.nan).astype(np.float32)
    state = build_serving_state(
        y, x, mask, window=ts // 2, min_periods=ts // 4
    )
    months = rng.integers(ts * 3 // 4, ts, n_queries)
    firms = rng.integers(0, ns, n_queries)

    def p50(svc) -> float:
        lat = np.empty(n_queries)
        for q in range(n_queries):
            t0 = time.perf_counter()
            svc.query(int(months[q]), x[months[q], firms[q]])
            lat[q] = time.perf_counter() - t0
        return float(np.percentile(lat, 50) * 1e3)

    with ERService(state, max_batch=64, max_latency_ms=0.5, warm=True) as svc:
        with telemetry.enabled(False):
            p50_off = p50(svc)
        with telemetry.enabled(True):
            p50_on = p50(svc)

    return {
        "obs_table2_off_s": round(off_s, 4),
        "obs_table2_on_s": round(on_s, 4),
        "obs_overhead_table2_pct": round(100.0 * (on_s - off_s) / off_s, 2),
        "obs_serving_p50_off_ms": round(p50_off, 3),
        "obs_serving_p50_on_ms": round(p50_on, 3),
        "obs_overhead_serving_p50_pct": round(
            100.0 * (p50_on - p50_off) / p50_off, 2
        ),
        "obs_spans_recorded": (
            telemetry.collector_stats()["spans"] - spans_before
        ),
        "obs_shape": f"T{t}_N{n}_Q{n_queries}",
    }


def _bench_mesh8(fast: bool):
    """Full pipeline over a VIRTUAL 8-device CPU mesh — the multi-chip
    perf story as a durable artifact (round-4 VERDICT item 7: narrated in
    architecture.md but recorded in no ``BENCH_r*.json``).

    Runs in a fresh subprocess: ``xla_force_host_platform_device_count``
    must be set before backend init, and the parent may hold a TPU
    client. ``FMRP_BENCH_MESH8=1`` runs the REAL-shape pipeline off the
    benchscale cache (defaulted on for TPU rounds by ``main``, where the
    host-CPU child is cheap relative to the window); unset on a CPU round
    it runs a SMALL-shape synthetic pipeline instead — labelled
    ``mesh8_scale: small`` — so CPU rounds emit sharded-path artifact
    data rather than zero data (r5 VERDICT weak #3). ``0`` skips."""
    mode = os.environ.get("FMRP_BENCH_MESH8", "")
    if fast or mode == "0":
        return {}
    if mode == "1":
        return _mesh8_child_run(real_shape=True)
    return _mesh8_child_run(real_shape=False)


def _mesh8_specgrid_probe():
    """Sharded-vs-single-device spec-grid ladder — runs INSIDE the mesh8
    child (8 virtual CPU devices). The PR-7 ``shard_map`` shim un-broke
    this path (BENCH_r03-r05 disclosed its AttributeError); this probe is
    the re-verification artifact: a real sharded solve through the
    declarative ``parallel.partition`` rules, its wall against the
    single-device route at the same small shape, and the route
    differential. Called by ``_mesh8_child_run``'s child script."""
    import jax

    from fm_returnprediction_tpu import specgrid

    t = int(os.environ.get("FMRP_BENCH_MESH8_SPECGRID_MONTHS", 120))
    n = int(os.environ.get("FMRP_BENCH_MESH8_SPECGRID_FIRMS", 2048))
    p = 8
    y, x, subsets = _make_panel(t, n, p)
    masks = dict(zip(("All", "All-but-tiny", "Large"), subsets))
    names = [f"x{i:02d}" for i in range(p)]
    grid = specgrid.SpecGrid(tuple(
        specgrid.Spec(f"m{k} | {u}", tuple(names[:k]), u)
        for k in (3, 8) for u in masks
    ))
    n_dev = len(jax.devices())
    mesh = specgrid.specgrid_mesh(n_dev)
    with _timed("bench.mesh8_specgrid_single_cold"):
        res_single = specgrid.run_spec_grid(y, x, masks, grid)
    with _timed("bench.mesh8_specgrid_single_warm") as single_t:
        res_single = specgrid.run_spec_grid(y, x, masks, grid)
    with _timed("bench.mesh8_specgrid_sharded_cold") as shard_cold_t:
        res_shard = specgrid.run_spec_grid(y, x, masks, grid, mesh=mesh)
    with _timed("bench.mesh8_specgrid_sharded_warm") as shard_t:
        res_shard = specgrid.run_spec_grid(y, x, masks, grid, mesh=mesh)
    a, b = res_single.coef, res_shard.coef
    both_nan = np.isnan(a) & np.isnan(b)
    diff = float(np.max(np.abs(np.where(both_nan, 0.0, a)
                               - np.where(both_nan, 0.0, b))))
    return {
        "devices": n_dev,
        "shape": f"T{t}_N{n}_S{len(grid)}",
        "single_warm_s": round(single_t.s, 4),
        "sharded_cold_s": round(shard_cold_t.s, 4),
        "sharded_warm_s": round(shard_t.s, 4),
        "max_coef_diff": diff,
    }


def _mesh8_child_run(real_shape: bool):
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.abspath(__file__))
    if real_shape:
        t = int(os.environ.get("FMRP_BENCH_REAL_MONTHS", 600))
        n = int(os.environ.get("FMRP_BENCH_REAL_FIRMS", 22000))
        budget = float(os.environ.get("FMRP_BENCH_MESH8_BUDGET_S", 900))
        raw_dir = os.path.join(repo_root, "_cache", f"benchscale_T{t}_N{n}")
        if not os.path.isdir(raw_dir):
            return {"mesh8_skipped": "no benchscale cache (real section ran?)"}
        child = (
            "import json, sys, bench\n"
            "wall, stages = bench._run_pipeline_timed(sys.argv[1])\n"
            "probe = bench._mesh8_specgrid_probe()\n"
            "print('MESH8 ' + json.dumps({'wall': wall, 'stages': stages,"
            " 'specgrid': probe}))\n"
        )
        argv = [sys.executable, "-c", child, raw_dir]
    else:
        t = int(os.environ.get("FMRP_BENCH_MESH8_MONTHS", 120))
        n = int(os.environ.get("FMRP_BENCH_MESH8_FIRMS", 400))
        budget = float(os.environ.get("FMRP_BENCH_MESH8_BUDGET_S", 600))
        child = (
            "import json, sys, tempfile, bench\n"
            "from fm_returnprediction_tpu.data.synthetic import (\n"
            "    SyntheticConfig, write_synthetic_cache)\n"
            "t, n = int(sys.argv[1]), int(sys.argv[2])\n"
            "with tempfile.TemporaryDirectory() as raw:\n"
            "    write_synthetic_cache(raw, SyntheticConfig(\n"
            "        n_firms=n, n_months=t))\n"
            "    wall, stages = bench._run_pipeline_timed(raw)\n"
            "probe = bench._mesh8_specgrid_probe()\n"
            "print('MESH8 ' + json.dumps({'wall': wall, 'stages': stages,"
            " 'specgrid': probe}))\n"
        )
        argv = [sys.executable, "-c", child, str(t), str(n)]

    env = _child_env(repo_root)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["MESH_DEVICES"] = "8"
    global _CHILD_PROC
    try:
        proc = subprocess.Popen(
            argv,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=repo_root,
        )
        # published so the deadline watchdog kills this full-scale child
        # too (same invariant as the CPU rescue: an orphaned real-shape
        # run must not outlive the bench into the next round)
        _CHILD_PROC = proc
        try:
            stdout, stderr = proc.communicate(timeout=budget)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            return {"mesh8_error": f"exceeded budget {budget:.0f}s"}
        finally:
            _CHILD_PROC = None
    except Exception as exc:  # noqa: BLE001 - section is best-effort
        return {"mesh8_error": repr(exc)[:300]}
    lines = [l for l in stdout.splitlines() if l.startswith("MESH8 ")]
    if proc.returncode != 0 or not lines:
        return {"mesh8_error": (stderr or stdout)[-300:]}
    got = json.loads(lines[-1][len("MESH8 "):])
    out = {
        "mesh8_pipeline_wall_s": round(got["wall"], 4),
        "mesh8_pipeline_stage_s": _round_stages(got["stages"]),
        "mesh8_shape": f"T{t}_N{n}",
        "mesh8_scale": "real" if real_shape else "small",
        "mesh8_device": "cpu-virtual-8",
    }
    # the sharded spec-grid ladder the child probed (the re-verification
    # of the path PR 7's shard_map shim un-broke)
    for k, v in got.get("specgrid", {}).items():
        out[f"mesh8_specgrid_{k}"] = v
    return out


def _cpu_fallback_possible(timeout_s: int) -> bool:
    """Probe whether a CPU-pinned JAX comes up on this host.

    ``jax.config.update("jax_platforms", "cpu")`` BEFORE backend init is
    the recipe the dryrun/test-suite use to sidestep a dead accelerator
    relay (env vars alone are not enough where a sitecustomize PJRT hook
    dials the relay at default-backend resolution)."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; jax.config.update('jax_platforms', 'cpu'); "
             "jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        return probe.returncode == 0
    except Exception:
        return False


def _devices_or_die(timeout_s: int = 150):
    """Initialize the JAX backend, but probe it in a SUBPROCESS first.

    A broken accelerator relay makes ``jax.devices()`` hang FOREVER inside a
    C call (observed: the tunneled axon backend mid-outage) — SIGALRM cannot
    interrupt that, and without a deadline the driver's whole bench window
    dies with no artifact. A throwaway subprocess with a hard timeout proves
    the backend comes up before this process commits to initializing it.

    When the accelerator does NOT come up but a CPU-pinned client does, the
    bench falls back to CPU at reduced shapes rather than recording nothing:
    the artifact discloses the outage (``extra.device: cpu`` +
    ``accelerator_unavailable``), and an honest host-only measurement beats
    a dead round. Hard failure (parseable ``bench_failed`` line) only when
    neither backend comes up."""
    import subprocess
    import sys

    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, text=True,
        )
        if probe.returncode != 0:
            raise RuntimeError(
                f"backend probe rc={probe.returncode}: {probe.stderr[-200:]}"
            )
        # The probe is TOCTOU: an intermittent outage can start between the
        # probe and the parent's own init, which then hangs in the same
        # uninterruptible C call. A watchdog thread prints the artifact and
        # hard-exits if the parent init misses its own deadline.
        done = threading.Event()

        def _watchdog():
            if not done.wait(timeout_s):
                print(json.dumps({
                    "metric": "bench_failed", "value": -1.0, "unit": "s",
                    "vs_baseline": 0.0,
                    "extra": {"backend_init_error":
                              f"in-process init exceeded {timeout_s}s"},
                }), flush=True)
                os._exit(0)

        threading.Thread(target=_watchdog, daemon=True).start()
        import jax

        devices = jax.devices()
        done.set()
        return devices, None
    except Exception as exc:  # noqa: BLE001 - recorded, then fall back or exit
        # typed outage record, not a raw repr string: consumers (and the
        # regression sentinel) get probe/timeout/error as separate fields
        reason = {
            "probe": "import jax; jax.devices()",
            "timeout_s": timeout_s,
            "error": repr(exc)[:300],
        }
        if _cpu_fallback_possible(min(timeout_s, 90)):
            import jax

            jax.config.update("jax_platforms", "cpu")
            return jax.devices(), reason
        print(json.dumps({
            "metric": "bench_failed", "value": -1.0, "unit": "s",
            "vs_baseline": 0.0,
            "extra": {"backend_init_error": reason},
        }))
        raise SystemExit(0)


def _headline(extra: dict):
    """(metric name, value) for this run's headline, or None when every
    pipeline section errored. A rescued real-shape number is a HOST
    number: the metric name itself must say so — a consumer reading only
    metric/value/device must not be able to record it as an accelerator
    result."""
    fell_back = extra.get("real_pipeline_device") == "cpu-fallback"
    disclose = "_cpu_fallback" if fell_back else ""
    if "real_pipeline_warm_s" in extra:
        return (f"e2e_pipeline_{extra['real_pipeline_shape']}"
                f"_warm{disclose}_wall_s", extra["real_pipeline_warm_s"])
    if "real_pipeline_cold_s" in extra:
        return (f"e2e_pipeline_{extra['real_pipeline_shape']}"
                f"_cold{disclose}_wall_s", extra["real_pipeline_cold_s"])
    if "pipeline_warm_s" in extra:
        return (f"e2e_pipeline_{extra['pipeline_shape']}_warm_wall_s",
                extra["pipeline_warm_s"])
    return None


_EMIT_LOCK = threading.Lock()


def _emit_line(extra: dict) -> None:
    """Compute the headline and print the ONE JSON line — at most once.

    Shared by the normal end-of-run path and the global watchdog: a wedged
    in-process JAX client can hang a later section forever inside a C call
    (observed r04 run 1: the backend died mid-run), and an emitted
    partial artifact beats a killed process that recorded nothing. The
    once-guard makes the watchdog and the main path race-safe. The PRINT
    happens under the lock too: the watchdog hard-exits the process right
    after its own (possibly no-op) call returns, and must not be able to
    truncate a competing emit mid-write."""
    with _EMIT_LOCK:
        if getattr(_emit_line, "_done", False):
            return
        _emit_line._done = True

        budget = 60.0
        headline = _headline(extra)
        if headline is None:  # every pipeline section errored — emit a
            # parseable line
            print(json.dumps({"metric": "bench_failed", "value": -1.0,
                              "unit": "s", "vs_baseline": 0.0,
                              "extra": extra}),
                  flush=True)
            return
        metric, warm = headline
        print(
            json.dumps(
                {
                    "metric": metric,
                    "value": warm,
                    "unit": "s",
                    "vs_baseline": round(budget / warm, 2),
                    "extra": extra,
                }
            ),
            flush=True,
        )


def _bench_topology(fast: bool):
    """Topology-controller repair economics (ISSUE 19): what a member
    death COSTS, measured on real OS processes.

    - ``topology_detect_s`` — SIGKILL→classified-``killed`` latency
      through the controller's probe ladder (pid poll; lower-better).
    - ``topology_respawn_mttr_s`` — classification→serving-again wall
      for one warm-pool respawn (kill_replica + replace + journal mark;
      the registry makes it compile-free, which is what keeps MTTR in
      the sub-second regime; lower-better, the regress-tracked series).
    - ``topology_degraded_grid_cells_per_s`` — contraction throughput
      on the DISCLOSED N-1 world after one of three grid workers dies
      (the degraded merge is an exact partial sum over survivors;
      higher-better) plus ``topology_degrade_recover_s``, the one-time
      kill→respawn-world→first-merge cost.

    FMRP_BENCH_TOPOLOGY=0 skips."""
    if os.environ.get("FMRP_BENCH_TOPOLOGY", "1") == "0":
        return {}
    import signal as _signal
    import tempfile
    from pathlib import Path

    from fm_returnprediction_tpu.serving import ServingFleet, \
        build_serving_state
    from fm_returnprediction_tpu.specgrid import multiproc
    from fm_returnprediction_tpu.topology import (
        TopologyController,
        TopologySpec,
    )

    out = {}
    tmp = Path(tempfile.mkdtemp(prefix="fmrp_bench_topo_"))
    t, n, p = (36, 60, 4) if fast else (48, 120, 4)
    y, x, subsets = _make_panel(t, n, p)
    state = build_serving_state(y, x, subsets[0], window=t // 2,
                                min_periods=t // 4)
    month = int(np.nonzero(state.have_coef())[0][0])
    qx = np.zeros(p, np.float32)
    spec = TopologySpec(replicas=2, replica_mode="process",
                        transport="shm")
    fleet = ServingFleet(state, 2, replica_mode="process",
                         transport="shm", journal=str(tmp / "j.jsonl"),
                         registry_dir=str(tmp / "registry"),
                         max_batch=16, max_latency_ms=2.0)
    ctl = TopologyController(spec, fleet=fleet, ping_timeout_s=1.0)
    try:
        ctl.probe()  # arm the ring marks
        victim = sorted(fleet.replica_states())[0]
        pid = fleet.replica(victim).service.pid
        with _timed("bench.topology_detect") as det:
            os.kill(pid, _signal.SIGKILL)
            while ctl.probe().get(victim) != "killed":
                time.sleep(0.005)
        with _timed("bench.topology_respawn") as rsp:
            ctl.repair()
            fleet.query(month, qx)  # serving again = repair complete
        out["topology_detect_s"] = round(det.s, 4)
        out["topology_respawn_mttr_s"] = round(rsp.s, 4)
    finally:
        ctl.close(close_pool=False)
    leaked = ctl.sweep()
    out["topology_leaked_segments"] = len(leaked["segments"])

    # the degraded N-1 grid: price the disclosed world, not just prove it
    gt, gn, gp = (48, 400, 6) if fast else (96, 1200, 6)
    gy, gx, gsub = _make_panel(gt, gn, gp)
    uni = np.stack([gsub[0]]).astype(bool)
    uidx = np.zeros(1, np.int64)
    col_sel = np.ones((1, gp), bool)
    window = np.ones((1, gt), bool)
    pool = multiproc.SpecGridWorkerPool(3, gy, gx, uni)
    try:
        pool.contract(uidx, col_sel, window)  # warm full world
        reps = 2 if fast else 4
        with _timed("bench.topology_degrade_recover") as rec:
            pool.workers[1].kill()
            pool.contract(uidx, col_sel, window)  # detect+respawn+merge
        with _timed("bench.topology_degraded_warm") as wt:
            for _ in range(reps):
                pool.contract(uidx, col_sel, window)
        out["topology_degrade_recover_s"] = round(rec.s, 4)
        out["topology_degraded_grid_cells_per_s"] = round(
            reps / wt.s, 3)
        out["topology_degraded_ranks"] = list(pool.degraded_ranks)
    finally:
        pool.close()
    return out


# env gate → the metric-key prefix that section publishes: a round that
# turns a section off records ``{"<section>": {"disabled": "<why>"}}`` in
# the artifact so the regress sentinel can tell a decision from a hole
_SECTION_GATES = {
    "FMRP_BENCH_PIPE": "pipeline",
    "FMRP_BENCH_REAL": "real_pipeline",
    "FMRP_BENCH_PANEL": "panel_build",
    "FMRP_BENCH_KERNEL": "kernel",
    "FMRP_BENCH_KERNELS": "kernels",
    "FMRP_BENCH_DAILY": "daily",
    "FMRP_BENCH_PALLAS": "pallas",
    "FMRP_BENCH_SERVING": "serving",
    "FMRP_BENCH_FLEET": "fleet",
    "FMRP_BENCH_FLEET_CAPACITY": "fleet_capacity",
    "FMRP_BENCH_SPECGRID": "specgrid",
    "FMRP_BENCH_SPECGRID_SCALE": "specgrid_scale",
    "FMRP_BENCH_GRID_FACTORIZED": "grid_factorized",
    "FMRP_BENCH_ESTIMATORS": "estimators",
    "FMRP_BENCH_BACKTEST": "backtest",
    "FMRP_BENCH_MULTIPROC": "multiproc",
    "FMRP_BENCH_TRANSPORT": "transport",
    "FMRP_BENCH_TOPOLOGY": "topology",
    "FMRP_BENCH_RESIL": "resilience",
    "FMRP_BENCH_GUARD": "guard",
    "FMRP_BENCH_REGISTRY": "registry",
    "FMRP_BENCH_OBS": "obs",
    "FMRP_BENCH_FUSEPROBE": "fuseprobe",
    "FMRP_BENCH_MESH8": "mesh8",
}


def main() -> None:
    from fm_returnprediction_tpu.settings import enable_compilation_cache
    from fm_returnprediction_tpu.utils.timing import trace

    devices, accel_down = _devices_or_die()
    enable_compilation_cache()
    fast = os.environ.get("FMRP_BENCH_FAST", "0") == "1"

    extra = {
        "device": devices[0].platform,
        "n_devices": len(devices),
        # before/after pair quantifies what this run ADDED to the
        # persistent XLA compilation cache — the cross-process compile
        # bill evidence for the split per-cell reporting programs
        "jax_cache_before": _jax_cache_stats(),
    }
    if devices[0].platform == "tpu":
        # a TPU round also records the virtual-mesh multi-chip pipeline
        # (a CPU-subprocess measurement — cheap relative to the TPU
        # window, durable in the artifact); on CPU-only rounds the host
        # is the sole compute and a second real-shape run could blow the
        # driver's bench window, so it stays opt-in there
        os.environ.setdefault("FMRP_BENCH_MESH8", "1")
    if accel_down is not None:
        # Accelerator outage, CPU fallback: disclose it, and shrink the
        # kernel section (a 10k-replicate bootstrap sweep is a TPU shape —
        # on a 1-core host it would eat the whole bench window). The
        # real-shape pipeline keeps its own soft budget.
        extra["accelerator_unavailable"] = accel_down
        os.environ.setdefault("FMRP_BENCH_REPLICATES", "500")
        os.environ.setdefault("FMRP_BENCH_MONTHS", "240")
        os.environ.setdefault("FMRP_BENCH_FIRMS", "2000")
        # The budget guards the warm repeat: it must comfortably fit the
        # driver's bench window (a killed bench records NO artifact), but
        # the warm run is the HEADLINE — it is the one that exercises the
        # prepared-inputs checkpoint, the production repeat-run path — so
        # the ceiling sits above the observed host-only cold (~250-290 s
        # with the checkpoint write) rather than below it. The standalone
        # daily section stays off (redundant with the real pipeline's daily
        # stage numbers).
        os.environ.setdefault("FMRP_BENCH_REAL_BUDGET_S", "450")
        os.environ.setdefault("FMRP_BENCH_DAILY", "0")
    # Every section has an off switch so a short accelerator window can be
    # spent on exactly the missing measurement (the tunnel comes and goes;
    # a full run is ~45 min, the real-shape section alone ~10): FMRP_BENCH_
    # PIPE / _REAL / _KERNEL / _DAILY / _PALLAS / _SERVING / _FLEET /
    # _SPECGRID / _RESIL / _FUSEPROBE / _MESH8 = 0.
    # Default: all on. mesh8 and fuseprobe run their real-shape ladders on
    # TPU rounds and disclosed small-shape variants on CPU rounds.
    sections = []
    if os.environ.get("FMRP_BENCH_PIPE", "1") == "1":
        sections.append(_bench_pipeline)
    sections.append(_bench_pipeline_real)  # _REAL=0 handled in-section
    sections.append(_bench_panel_build)  # _PANEL=0 handled in-section
    if os.environ.get("FMRP_BENCH_KERNEL", "1") == "1":
        sections.append(_bench_kernel)
    if os.environ.get("FMRP_BENCH_DAILY", "1") == "1":
        sections.append(_bench_daily_fullscale)
    if os.environ.get("FMRP_BENCH_PALLAS", "1") == "1":
        sections.append(_bench_pallas)
    sections.append(_bench_kernels)  # _KERNELS=0 handled in-section
    if os.environ.get("FMRP_BENCH_SERVING", "1") == "1":
        sections.append(_bench_serving)
    sections.append(_bench_fleet)  # _FLEET=0 handled in-section
    sections.append(_bench_fleet_capacity)  # _FLEET_CAPACITY=0 in-section
    sections.append(_bench_specgrid)  # _SPECGRID=0 handled in-section
    sections.append(_bench_specgrid_scale)  # _SPECGRID_SCALE=0 in-section
    sections.append(_bench_grid_factorized)  # _GRID_FACTORIZED=0 in-section
    sections.append(_bench_estimators)  # _ESTIMATORS=0 handled in-section
    sections.append(_bench_backtest)  # _BACKTEST=0 handled in-section
    sections.append(_bench_multiproc)  # _MULTIPROC=0 handled in-section
    sections.append(_bench_transport)  # _TRANSPORT=0 handled in-section
    sections.append(_bench_topology)  # _TOPOLOGY=0 handled in-section
    sections.append(_bench_resilience)  # _RESIL=0 handled in-section
    sections.append(_bench_guard)  # _GUARD=0 handled in-section
    sections.append(_bench_obs)  # _OBS=0 handled in-section
    sections.append(_bench_registry)  # _REGISTRY=0 handled in-section
    sections.append(_bench_fuseprobe)  # real ladder on TPU, small on CPU
    sections.append(_bench_mesh8)  # real shape when _MESH8=1, small else

    # Global deadline: a section hanging in an uninterruptible C call (a
    # backend that died mid-run) must cost only the REMAINING sections, not
    # the whole artifact — the watchdog emits whatever has been measured so
    # far and hard-exits. The section try/except cannot do this: it never
    # regains control from a hung call.
    deadline = float(os.environ.get("FMRP_BENCH_DEADLINE_S", 3000))
    bench_done = threading.Event()

    def _watchdog():
        if not bench_done.wait(deadline):
            try:
                # dict(extra) is a single atomic C-level copy under the
                # GIL — safe against the main thread's section updates
                _emit_line({**extra, "bench_deadline_exceeded_s": deadline})
                # a still-running CPU rescue child must not outlive the
                # bench into the next round's measurements
                child = _CHILD_PROC
                if child is not None:
                    child.kill()
            finally:
                # serialize with a competing emit so the hard exit cannot
                # truncate a JSON line mid-write
                with _EMIT_LOCK:
                    os._exit(0)

    threading.Thread(target=_watchdog, daemon=True).start()

    # FMRP_TRACE=<dir> wraps the whole bench in a jax.profiler trace
    # (round-2 VERDICT item 8) — open with TensorBoard/xprof.
    from fm_returnprediction_tpu.telemetry import recompile_watch

    section_cache_growth = {}
    with trace(os.environ.get("FMRP_TRACE")):
        for section in sections:
            # fault isolation: one section failing must not lose the whole
            # JSON artifact (the driver records exactly one line)
            delta = None
            try:
                # per-section compile-cache diff: which section paid (or
                # re-paid) compiles is part of the accounting story
                with recompile_watch(section.__name__) as delta:
                    extra.update(section(fast))
            except Exception as exc:  # noqa: BLE001 - recorded, not hidden
                extra[f"{section.__name__}_error"] = repr(exc)[:300]
                extra[f"{section.__name__}_error_frames"] = _error_frames(exc)
            if delta is not None and delta.grew:
                section_cache_growth[section.__name__] = delta.grew
    if section_cache_growth:
        extra["section_cache_growth"] = section_cache_growth

    # deliberately-disabled sections land in the artifact as EXPLICIT
    # objects, not silence — the regress sentinel discloses them (never
    # gates) instead of reading absence as coverage (the r08/r09
    # noise-flappers were env-gated off with no record of the decision)
    for env_key, section in _SECTION_GATES.items():
        if os.environ.get(env_key, "1") == "0":
            extra[section] = {
                "disabled": f"{env_key}=0 (deliberately disabled "
                            "this round)"
            }

    bench_done.set()
    extra["jax_cache_after"] = _jax_cache_stats()
    _emit_line(extra)
    _regress_report(extra)


def _regress_report(extra: dict) -> None:
    """End-of-round perf-regression sentinel: the archived bench history
    PLUS the round that just ran (its artifact is only archived by the
    driver after this process exits, so ``extra`` is appended as a
    synthetic latest round — otherwise the report would re-judge last
    round). To STDERR (the stdout artifact must stay one JSON line),
    report-only (the CI gate is the tier-2 pytest / the regress CLI).
    FMRP_BENCH_REGRESS=0 skips."""
    if os.environ.get("FMRP_BENCH_REGRESS", "1") == "0":
        return
    import glob
    import sys
    import tempfile

    try:
        from fm_returnprediction_tpu.telemetry import regress

        repo_root = os.path.dirname(os.path.abspath(__file__))
        files = sorted(glob.glob(os.path.join(repo_root, "BENCH_*.json")))
        rounds = regress.load_rounds(files)
        headline = _headline(extra)
        this_round = None
        if headline is not None and rounds:
            metric, value = headline
            payload = {
                "n": max(r.order[0] for r in rounds) + 1,
                "parsed": {"metric": metric, "value": value,
                           "extra": extra},
            }
            with tempfile.NamedTemporaryFile(
                "w", suffix=".json", prefix="BENCH_current_",
                delete=False,
            ) as fh:
                json.dump(payload, fh)
                this_round = fh.name
        all_rounds = regress.load_rounds(
            [*files, *( [this_round] if this_round else [] )]
        )
        if this_round:
            os.unlink(this_round)
        if len(all_rounds) < 2:
            return
        report = regress.analyze(all_rounds)
        print(report.format_text(), file=sys.stderr, flush=True)
    except Exception as exc:  # noqa: BLE001 — advisory only, never fatal
        print(f"regress sentinel failed: {exc!r}", file=sys.stderr)


if __name__ == "__main__":
    main()
