# Keep the LaTeX build tidy when compiling _output/research_report.tex with
# latexmk (reporting/latex.py runs plain pdflatex twice; this file serves
# users who prefer latexmk, as the reference's .latexmkrc does).
$clean_ext = "synctex.gz nav snm thm soc loc glg acn vrb";
$bibtex_use = 2;
