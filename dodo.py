"""doit-compatible entry point (drop-in parity with the reference's
``dodo.py`` / ``README.md`` "run `doit`" workflow).

The native runner is ``python -m fm_returnprediction_tpu.taskgraph`` — the
in-repo engine reimplements doit's file_dep/targets/uptodate semantics
because doit is not part of this environment. When doit IS installed (a
user coming from the reference toolchain), this shim exposes the SAME task
graph to it: every ``taskgraph.tasks`` Task maps 1:1 onto a doit task dict,
so ``doit``, ``doit list``, ``doit reports`` etc. behave like the
reference's build (reference ``dodo.py:115-206``).

Environment knobs (same settings layer as the native runner):

- ``FMRP_SYNTHETIC=1`` — build from the hermetic synthetic universe instead
  of WRDS pulls (no credentials needed);
- the usual ``.env`` keys (DATA_DIR, OUTPUT_DIR, BACKEND, ...).

Run directly (``python dodo.py``) it prints the native-runner pointer
rather than silently doing nothing.
"""

from __future__ import annotations

import os


def _doit_dict(task) -> dict:
    """One ``taskgraph.engine.Task`` → a doit task dict.

    The field names already match (the engine mirrors doit's contract);
    only Path coercion and doit's basename/doc conventions are added.
    """
    d = {
        "actions": list(task.actions),
        "file_dep": [str(p) for p in task.file_dep],
        "targets": [str(p) for p in task.targets],
        "task_dep": list(task.task_dep),
        "doc": task.doc,
        "verbosity": 2,
    }
    if task.uptodate:
        d["uptodate"] = list(task.uptodate)
    return d


def _all_tasks():
    from fm_returnprediction_tpu.settings import apply_backend
    from fm_returnprediction_tpu.taskgraph.tasks import (
        build_notebook_tasks,
        build_tasks,
    )

    apply_backend()
    synthetic = os.environ.get("FMRP_SYNTHETIC", "0") == "1"
    return build_tasks(synthetic=synthetic) + build_notebook_tasks()


def _make_creator(task):
    def creator():
        return _doit_dict(task)

    creator.__name__ = f"task_{task.name}"
    creator.__doc__ = task.doc
    return creator


# doit discovers module-level ``task_*`` callables; generate one per graph
# node so ``doit list`` shows the same task names as the native runner.
for _t in _all_tasks():
    globals()[f"task_{_t.name}"] = _make_creator(_t)
del _t


def task_perf_regress():
    """Run the perf-regression sentinel over the in-repo bench history
    (``telemetry.regress``): exits non-zero when the latest round
    regressed a tracked metric beyond its fitted noise band, so a perf
    regression fails the build instead of living only in JSON diffs."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m "
            "fm_returnprediction_tpu.telemetry.regress"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "perf-regression sentinel over BENCH_*.json "
               "(telemetry.regress; fails on regressions beyond band)",
        "verbosity": 2,
        "uptodate": [False],  # history-dependent: always re-evaluate
    }


def task_robustness_smoke():
    """The robustness suite as one named target: every ``fleet`` and
    ``chaos`` marked test (supervision, autoscale, brownout, crash
    recovery, fault-injection) in one fast pytest invocation — the
    pre-merge smoke for anything touching the overload-survival layer.
    Pairs with ``perf_regress`` (below), which gates the bench series
    the same layer produces (``fleet_capacity_*`` / ``fleet_overload_*``
    included since BENCH_r07)."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m 'fleet or chaos' -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "fleet+chaos marker smoke suite (overload survival, "
               "failover, fault injection) — exit-1 on any failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }


def task_grid_parity():
    """The spec-grid differential suite as one named exit-1 gate: the
    factorized-vs-legacy contraction parity (``tests/test_grid_factorize``),
    the device-vs-host bootstrap aggregation (``tests/test_boot_device``),
    the banked-query-vs-engine differential (``tests/test_grambank``) and
    every other ``specgrid``-marked Gram-route pin — the pre-merge gate
    for anything touching the month-axis factorization or the solve tail.
    Complements ``perf_regress`` (which gates the ``grid_factorized_*`` /
    ``grid_boot_*`` bench series the same layer produces, archived since
    BENCH_r08) and ``robustness_smoke``/``multiprocess_smoke``."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m specgrid -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "specgrid marker differential suite (factorized Gram "
               "parity, device bootstrap, gram bank) — exit-1 on any "
               "failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }


def task_estimator_parity():
    """The estimator subsystem's differential suite as one named exit-1
    gate (``tests/test_estimators.py``): FWL-via-Schur vs the explicit-
    controls solve (exact), absorbed-FE alternating projections vs the
    dummy-variable within oracle, IV/2SLS vs the closed-form two-stage
    host solve, every pooled sandwich-SE family vs the numpy oracle,
    clustered FM means, streaming-bootstrap draw-0 ≡ point + exact
    Chan merge, the estimator CellSpace dimension's OLS-cell parity,
    and the bank-served ``estimator_query`` zero-contraction pin — the
    pre-merge gate for anything touching ``specgrid/estimators/`` or
    the bank/solve tails it rides. Sits alongside ``grid_parity``
    (Gram routes) and ``transport_parity``."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m estimators -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "estimators marker differential suite (FWL/FE/IV vs host "
               "oracles, sandwich SEs, streaming bootstrap, banked "
               "estimator queries) — exit-1 on any failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }


def task_backtest_parity():
    """The backtest subsystem's differential suite as one named exit-1
    gate (``tests/test_backtest.py``): the scan-route prefix-sum paths
    vs the per-origin full-refit oracle (f64 ≤ 1e-13 / f32 ≤ 1e-6, OLS
    and FWL), OOS R²/IC/rank-IC vs their numpy host oracles, quantile
    assignment vs the pandas-qcut-style oracle incl. tie months,
    bootstrap draw-0 ≡ point, the fleet-served portfolio consumer's
    quotes bit-identical to the batch executor, and the zero-panel-
    contraction sweep ledger — the pre-merge gate for anything touching
    ``backtest/`` or the bank/solve/serving tails it rides. Sits
    alongside ``grid_parity`` and ``estimator_parity``."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m backtest -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "backtest marker differential suite (scan-vs-refit paths, "
               "OOS R2/IC/decile oracles, consumer quote parity, "
               "zero-contraction ledger) — exit-1 on any failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }


if __name__ == "__main__":
    try:
        from doit.doit_cmd import DoitMain

        raise SystemExit(DoitMain().run(["run"]))
    except ImportError:
        print(
            "doit is not installed. Use the native runner instead:\n"
            "    python -m fm_returnprediction_tpu.taskgraph [task ...]\n"
            "(same DAG, same semantics; this dodo.py is a doit-compat shim)."
        )


def task_multiprocess_smoke():
    """The cross-process suite as one named exit-1 gate: every spawned-
    subprocess test in ``tests/test_multiprocess.py`` — host-exchange
    collectives, the 2-process taskgraph DAG, the multi-process
    spec-grid differential, the process-replica fleet kill/replay —
    plus anything else carrying the ``multiprocess`` marker. Pairs with
    ``robustness_smoke`` (fleet+chaos) and ``perf_regress`` (bench
    history): the three named pre-merge gates for the serving/dist
    planes."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m multiprocess -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "multiprocess marker smoke suite (spawned-subprocess "
               "bootstrap, spec-grid, process fleet) — exit-1 on any "
               "failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }


def task_transport_parity():
    """The shm data plane's differential suite as one named exit-1 gate
    (``tests/test_transport.py``): ring seq/commit protocol (torn frame
    = absent), frame-grammar round-trips incl. the DegradedQuote
    columns, shm-vs-socket-vs-thread bit-identical fleet quotes,
    ring-full backpressure as the typed retriable overload, the
    hard-crash journal replay on the shm path, and the multiproc grid's
    mapped-segment stats against the pickled-frames oracle — the
    pre-merge gate for anything touching ``parallel/shm.py``,
    ``serving/shm.py``, or the replica/grid transports. Sits alongside
    ``grid_parity`` (Gram routes) and ``multiprocess_smoke``."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m transport -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "transport marker differential suite (shm ring protocol, "
               "fleet shm-vs-socket, grid shm-vs-frames) — exit-1 on "
               "any failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }


def task_topology_smoke():
    """The topology controller's suite as one named exit-1 gate
    (``tests/test_topology.py``): declarative spec round-trips,
    cross-process chaos propagation (proc-targeted ``FMRP_CHAOS_*``
    env, 30/30 deterministic triggers), the shm commit seam (torn frame
    = absent), fd/segment hygiene sweeps, broker connect retry +
    rank-0-last fan-out repeats, the killed/hung/ring-stalled
    classification ladder on real OS processes, SIGKILL-mid-send
    exactly-once on both transports, any-shape journal recovery, the
    degraded N-1 grid with its refusal knob, and broker re-election —
    the pre-merge gate for anything touching ``topology/``, the
    supervised fleet/pool lifecycles, or the chaos campaign. Sits
    alongside ``robustness_smoke`` (fleet+chaos) and
    ``multiprocess_smoke`` / ``transport_parity``."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m topology -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "topology marker suite (inventory supervision, chaos "
               "campaign, degraded grid, any-shape recovery) — exit-1 "
               "on any failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }


def task_obs_smoke():
    """The distributed-observability plane's suite as one named exit-1
    gate (``-m obs``): cross-process trace propagation with shm-vs-socket
    span parity, fleet-wide metric aggregation staying monotone across a
    kill + respawn, the SIGKILL-surviving flight annex (commit-last
    double buffer, 30/30 deterministic chaos rounds), the torn-totals
    snapshot lock on ``/metrics``, regress.py's disabled-section
    disclosure, and the per-hop timeline merge/analyze path the bench's
    router-ceiling series rides on. The pre-merge gate for anything
    touching ``telemetry/`` or the process seams it instruments. Sits
    alongside ``robustness_smoke`` and ``topology_smoke``."""
    import sys

    repo = os.path.dirname(os.path.abspath(__file__))
    return {
        "actions": [
            f"cd {repo} && {sys.executable} -m pytest tests/ -q "
            "-m obs -p no:cacheprovider"
        ],
        "file_dep": [],
        "targets": [],
        "doc": "obs marker suite (trace propagation, metric aggregation "
               "monotonicity, annex harvest, timeline merge) — exit-1 "
               "on any failure",
        "verbosity": 2,
        "uptodate": [False],  # test-suite target: always re-run
    }
