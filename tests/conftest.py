"""Test harness configuration.

Must run before any ``jax`` import:

- Force the CPU platform with 8 virtual devices so ``Mesh``/``shard_map``
  code paths are exercised without TPU hardware (SURVEY §4d).
- Enable x64 so JAX kernels match the float64 numpy/pandas oracles bit-close
  (parity tolerance 1e-4 per BASELINE.md; tests assert far tighter).
"""

import os

os.environ["JAX_PLATFORMS"] = os.environ.get("FMRP_TEST_PLATFORM", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# Plugins (e.g. jaxtyping's) may import jax before this conftest runs, so the
# env vars alone are not enough; config.update works until the backend
# initializes, which only happens at the first device query/computation.
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
jax.config.update("jax_enable_x64", os.environ["JAX_ENABLE_X64"] == "1")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng() -> np.random.Generator:
    return np.random.default_rng(20140131)
