"""Prepared-inputs checkpoint (``data.prepared``): the warm-run host-ingest
skip. Contracts under test:

- save/load roundtrip preserves the dense base panel and every compact
  daily strip exactly;
- the fingerprint follows the make-style staleness rule (stable for
  untouched raw files, changed on any size/mtime change, dtype- and
  salt-sensitive);
- ``run_pipeline`` transparently writes the checkpoint on the first run and
  loads it on the second — skipping load_raw_data/universe_filter/
  daily_ingest/long_to_dense — with BIT-IDENTICAL tables;
- a corrupt or half-written checkpoint degrades to a rebuild, never an
  error (meta-last write ordering);
- ``PREPARED_CACHE=0`` disables the path entirely.
"""

import json
import os
import time

import numpy as np
import pytest

from fm_returnprediction_tpu.data.prepared import (
    PREPARED_DIRNAME,
    load_prepared,
    raw_fingerprint,
    save_prepared,
)
from fm_returnprediction_tpu.data.synthetic import (
    SyntheticConfig,
    write_synthetic_cache,
)
from fm_returnprediction_tpu.pipeline import run_pipeline

CFG = SyntheticConfig(n_firms=60, n_months=48)


@pytest.fixture(scope="module")
def raw_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("raw")
    write_synthetic_cache(d, CFG)
    return d


def test_fingerprint_staleness_contract(raw_dir):
    fp = raw_fingerprint(raw_dir, np.float64)
    assert fp == raw_fingerprint(raw_dir, np.float64)  # stable
    assert fp != raw_fingerprint(raw_dir, np.float32)  # dtype-sensitive
    # salt-sensitive: the turnover flag changes the base column set
    assert fp != raw_fingerprint(raw_dir, np.float64, salt="turnover=1")

    victim = next(raw_dir.glob("*.parquet"))
    st = victim.stat()
    os.utime(victim, ns=(st.st_atime_ns, st.st_mtime_ns + 1))
    assert fp != raw_fingerprint(raw_dir, np.float64)  # mtime-sensitive
    os.utime(victim, ns=(st.st_atime_ns, st.st_mtime_ns))
    assert fp == raw_fingerprint(raw_dir, np.float64)  # restored


def test_roundtrip_and_corruption(raw_dir, tmp_path):
    from fm_returnprediction_tpu.pipeline import build_panel, load_raw_data

    capture = {}
    build_panel(load_raw_data(raw_dir), capture=capture)
    base, cd = capture["dense_base"], capture["compact_daily"]

    fp = raw_fingerprint(raw_dir, np.float64)
    save_prepared(tmp_path, fp, base, cd)

    assert load_prepared(tmp_path, "not-the-fingerprint") is None
    got = load_prepared(tmp_path, fp)
    assert got is not None
    base2, cd2 = got
    np.testing.assert_array_equal(base2.values, np.asarray(base.values))
    np.testing.assert_array_equal(base2.mask, np.asarray(base.mask))
    np.testing.assert_array_equal(base2.months, base.months)
    np.testing.assert_array_equal(base2.ids, base.ids)
    assert base2.var_names == base.var_names
    np.testing.assert_array_equal(cd2.row_values, cd.row_values)
    np.testing.assert_array_equal(cd2.row_pos, cd.row_pos)
    np.testing.assert_array_equal(cd2.offsets, cd.offsets)
    np.testing.assert_array_equal(cd2.ids, cd.ids)
    np.testing.assert_array_equal(cd2.mkt, cd.mkt)
    np.testing.assert_array_equal(cd2.mkt_present, cd.mkt_present)
    np.testing.assert_array_equal(
        cd2.days.astype("datetime64[s]"), cd.days.astype("datetime64[s]")
    )
    np.testing.assert_array_equal(cd2.day_month_id, cd.day_month_id)
    np.testing.assert_array_equal(cd2.week_id, cd.week_id)
    np.testing.assert_array_equal(cd2.week_month_id, cd.week_month_id)
    assert (cd2.n_weeks, cd2.n_months) == (cd.n_weeks, cd.n_months)

    # valid meta + missing payload (a torn checkpoint) → miss with a
    # warning, never an exception
    (tmp_path / "daily.row_values.npy").unlink()
    with pytest.warns(UserWarning, match="unreadable"):
        assert load_prepared(tmp_path, fp) is None


def test_old_layout_checkpoint_upgrade(raw_dir, tmp_path):
    """An older-layout slot (v1 merged frame / v2 npz bundles) is a clean
    miss, and the next save removes the orphaned payloads."""
    from fm_returnprediction_tpu.pipeline import build_panel, load_raw_data

    v1_payload = tmp_path / "monthly_merged.parquet"
    v1_payload.write_bytes(b"stale v1 payload")
    v2_payload = tmp_path / "dense_base.npz"
    v2_payload.write_bytes(b"stale v2 payload")
    fp = raw_fingerprint(raw_dir, np.float64)
    (tmp_path / "meta.json").write_text(
        json.dumps({"fingerprint": fp, "version": 2})
    )
    assert load_prepared(tmp_path, fp) is None  # version mismatch → miss

    capture = {}
    build_panel(load_raw_data(raw_dir), capture=capture)
    save_prepared(tmp_path, fp, capture["dense_base"],
                  capture["compact_daily"])
    assert not v1_payload.exists()
    assert not v2_payload.exists()
    assert load_prepared(tmp_path, fp) is not None


def _tables(res):
    return res.table_1.to_string() + res.table_2.to_string()


def _ingested_raw(timer) -> bool:
    """Did the run ingest from raw parquet (either route)? The columnar
    route streams the reads inside ``panel/monthly_ingest``; the legacy
    route records ``load_raw_data``."""
    return ("load_raw_data" in timer.durations
            or "panel/monthly_ingest" in timer.durations)


def test_pipeline_warm_run_uses_checkpoint(raw_dir):
    cold = run_pipeline(raw_data_dir=raw_dir, make_figure=False,
                        make_deciles=False, compile_pdf=False)
    assert "build_panel/save_prepared" in cold.timer.durations
    assert _ingested_raw(cold.timer)
    assert (raw_dir / PREPARED_DIRNAME / "meta.json").exists()

    warm = run_pipeline(raw_data_dir=raw_dir, make_figure=False,
                        make_deciles=False, compile_pdf=False)
    assert "load_prepared" in warm.timer.durations
    for skipped in ("load_raw_data", "panel/universe_filter",
                    "panel/monthly_ingest",
                    "panel/market_equity", "panel/ccm_merge",
                    "factors/daily_ingest", "factors/long_to_dense",
                    "build_panel/save_prepared"):
        assert skipped not in warm.timer.durations, skipped
    # the short-circuited raw ingest is an EXPLICIT skip with a reason —
    # not a 0.0 that reads as "free" in the per-stage breakdowns
    assert warm.timer.skipped["load_raw_data"] == "prepared checkpoint hit"
    assert cold.timer.skipped.get("load_raw_data") != "prepared checkpoint hit"
    assert _tables(warm) == _tables(cold)  # bit-identical reporting

    # staleness: re-pulling a raw file invalidates the checkpoint
    victim = next(raw_dir.glob("*.parquet"))
    st = victim.stat()
    os.utime(victim, ns=(st.st_atime_ns, st.st_mtime_ns + 1))
    try:
        rebuilt = run_pipeline(raw_data_dir=raw_dir, make_figure=False,
                               make_deciles=False, compile_pdf=False)
        assert _ingested_raw(rebuilt.timer)
        assert "build_panel/save_prepared" in rebuilt.timer.durations
        assert _tables(rebuilt) == _tables(cold)
    finally:
        os.utime(victim, ns=(st.st_atime_ns, st.st_mtime_ns))


def test_prepared_cache_setting_disables(raw_dir, monkeypatch):
    from fm_returnprediction_tpu import settings

    monkeypatch.setitem(settings.d, "PREPARED_CACHE", 0)
    res = run_pipeline(raw_data_dir=raw_dir, make_figure=False,
                       make_deciles=False, compile_pdf=False)
    assert _ingested_raw(res.timer)
    assert "load_prepared" not in res.timer.durations
    assert "build_panel/save_prepared" not in res.timer.durations
