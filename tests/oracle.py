"""Slow numpy/pandas oracle transcribing the REFERENCE's exact formulas.

This is test infrastructure, not framework code: an independent, loop-based
implementation of the reference pipeline's numerical behavior
(``/root/reference/src/regressions.py`` and the rolling kernels in
``calc_Lewellen_2014.py``), written from the formulas — including the quirks
the framework must reproduce (SURVEY §2.2): the ``1 - k/T`` Bartlett weight,
complete-case dropna before the monthly loop, the min-10-months rule, and
skipping months with fewer than P+1 observations.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def oracle_monthly_cs_ols(
    df: pd.DataFrame,
    return_col: str,
    predictor_cols: list,
    date_col: str = "mthcaldt",
) -> pd.DataFrame:
    """Per-month OLS loop (reference ``run_monthly_cs_regressions``,
    ``src/regressions.py:9-76``). One output row per month that ran."""
    data = df[[return_col, date_col] + predictor_cols].sort_values(date_col).dropna()
    rows = []
    for month, grp in data.groupby(date_col):
        if len(grp) < len(predictor_cols) + 1:
            continue
        y = grp[return_col].to_numpy(dtype=float)
        x = np.column_stack(
            [np.ones(len(grp)), grp[predictor_cols].to_numpy(dtype=float)]
        )
        beta, *_ = np.linalg.lstsq(x, y, rcond=None)
        resid = y - x @ beta
        sst = float(((y - y.mean()) ** 2).sum())
        r2 = 1.0 - float((resid**2).sum()) / sst if sst > 0 else 0.0
        row = {date_col: month, "N": len(grp), "R2": r2}
        for i, col in enumerate(predictor_cols):
            row[f"slope_{col}"] = beta[1 + i]
        rows.append(row)
    return pd.DataFrame(rows)


def oracle_nw_mean_se(series: np.ndarray, lags: int = 4) -> float:
    """Reference ``newey_west_mean_se`` (``src/regressions.py:78-100``):
    Bartlett weight ``1 - k/T`` with T the series length, variance scaled by
    ``T²``, loop broken once a weight would go negative."""
    x = np.asarray(series, dtype=float)
    T = x.size
    if T < 2:
        return np.nan
    u = x - x.mean()
    gamma0 = float(np.sum(u * u))
    acc = 0.0
    for k in range(1, lags + 1):
        weight = 1.0 - k / T
        if weight < 0:
            break
        acc += weight * float(np.sum(u[k:] * u[:-k]))
    return float(np.sqrt((gamma0 + 2.0 * acc) / T**2))


def oracle_fama_macbeth_summary(
    cs_results: pd.DataFrame,
    predictor_cols: list,
    nw_lags: int = 4,
) -> dict:
    """Reference ``fama_macbeth_summary`` (``src/regressions.py:102-130``)."""
    out = {}
    for col in predictor_cols:
        slopes = cs_results[f"slope_{col}"].dropna()
        if len(slopes) < 10:
            out[f"{col}_coef"] = np.nan
            out[f"{col}_tstat"] = np.nan
            continue
        mean_slope = float(slopes.mean())
        se = oracle_nw_mean_se(slopes.to_numpy(), lags=nw_lags)
        out[f"{col}_coef"] = mean_slope
        out[f"{col}_tstat"] = mean_slope / se
    out["mean_R2"] = float(cs_results["R2"].mean())
    out["mean_N"] = float(cs_results["N"].mean())
    return out


def make_synthetic_long_panel(
    rng: np.random.Generator,
    n_months: int = 48,
    n_firms: int = 60,
    n_predictors: int = 3,
    missing_frac: float = 0.15,
    absent_frac: float = 0.10,
) -> tuple[pd.DataFrame, list]:
    """A small long firm-month panel with realistic raggedness: firms enter
    and exit (absent rows) and surviving rows have scattered missing values,
    so complete-case and skip-month paths are exercised."""
    months = pd.date_range("1980-01-31", periods=n_months, freq="ME")
    pred_cols = [f"x{i}" for i in range(n_predictors)]
    records = []
    for firm in range(n_firms):
        start = rng.integers(0, n_months // 3)
        stop = rng.integers(2 * n_months // 3, n_months)
        for t in range(start, stop):
            if rng.random() < absent_frac:
                continue  # firm-month row absent entirely (gap)
            row = {"permno": 10000 + firm, "mthcaldt": months[t]}
            row["retx"] = rng.normal(0.01, 0.08)
            for col in pred_cols:
                row[col] = np.nan if rng.random() < missing_frac else rng.normal()
            records.append(row)
    return pd.DataFrame(records), pred_cols
