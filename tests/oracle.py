"""Slow numpy/pandas oracle transcribing the REFERENCE's exact formulas.

This is test infrastructure, not framework code: an independent, loop-based
implementation of the reference pipeline's numerical behavior
(``/root/reference/src/regressions.py`` and the rolling kernels in
``calc_Lewellen_2014.py``), written from the formulas — including the quirks
the framework must reproduce (SURVEY §2.2): the ``1 - k/T`` Bartlett weight,
complete-case dropna before the monthly loop, the min-10-months rule, and
skipping months with fewer than P+1 observations.
"""

from __future__ import annotations

import numpy as np
import pandas as pd


def oracle_monthly_cs_ols(
    df: pd.DataFrame,
    return_col: str,
    predictor_cols: list,
    date_col: str = "mthcaldt",
) -> pd.DataFrame:
    """Per-month OLS loop (reference ``run_monthly_cs_regressions``,
    ``src/regressions.py:9-76``). One output row per month that ran."""
    data = df[[return_col, date_col] + predictor_cols].sort_values(date_col).dropna()
    rows = []
    for month, grp in data.groupby(date_col):
        if len(grp) < len(predictor_cols) + 1:
            continue
        y = grp[return_col].to_numpy(dtype=float)
        x = np.column_stack(
            [np.ones(len(grp)), grp[predictor_cols].to_numpy(dtype=float)]
        )
        beta, *_ = np.linalg.lstsq(x, y, rcond=None)
        resid = y - x @ beta
        sst = float(((y - y.mean()) ** 2).sum())
        r2 = 1.0 - float((resid**2).sum()) / sst if sst > 0 else 0.0
        row = {date_col: month, "N": len(grp), "R2": r2}
        for i, col in enumerate(predictor_cols):
            row[f"slope_{col}"] = beta[1 + i]
        rows.append(row)
    return pd.DataFrame(rows)


def oracle_nw_mean_se(series: np.ndarray, lags: int = 4) -> float:
    """Reference ``newey_west_mean_se`` (``src/regressions.py:78-100``):
    Bartlett weight ``1 - k/T`` with T the series length, variance scaled by
    ``T²``, loop broken once a weight would go negative."""
    x = np.asarray(series, dtype=float)
    T = x.size
    if T < 2:
        return np.nan
    u = x - x.mean()
    gamma0 = float(np.sum(u * u))
    acc = 0.0
    for k in range(1, lags + 1):
        weight = 1.0 - k / T
        if weight < 0:
            break
        acc += weight * float(np.sum(u[k:] * u[:-k]))
    return float(np.sqrt((gamma0 + 2.0 * acc) / T**2))


def oracle_fama_macbeth_summary(
    cs_results: pd.DataFrame,
    predictor_cols: list,
    nw_lags: int = 4,
) -> dict:
    """Reference ``fama_macbeth_summary`` (``src/regressions.py:102-130``)."""
    out = {}
    for col in predictor_cols:
        slopes = cs_results[f"slope_{col}"].dropna()
        if len(slopes) < 10:
            out[f"{col}_coef"] = np.nan
            out[f"{col}_tstat"] = np.nan
            continue
        mean_slope = float(slopes.mean())
        se = oracle_nw_mean_se(slopes.to_numpy(), lags=nw_lags)
        out[f"{col}_coef"] = mean_slope
        out[f"{col}_tstat"] = mean_slope / se
    out["mean_R2"] = float(cs_results["R2"].mean())
    out["mean_N"] = float(cs_results["N"].mean())
    return out


def make_synthetic_long_panel(
    rng: np.random.Generator,
    n_months: int = 48,
    n_firms: int = 60,
    n_predictors: int = 3,
    missing_frac: float = 0.15,
    absent_frac: float = 0.10,
) -> tuple[pd.DataFrame, list]:
    """A small long firm-month panel with realistic raggedness: firms enter
    and exit (absent rows) and surviving rows have scattered missing values,
    so complete-case and skip-month paths are exercised."""
    months = pd.date_range("1980-01-31", periods=n_months, freq="ME")
    pred_cols = [f"x{i}" for i in range(n_predictors)]
    records = []
    for firm in range(n_firms):
        start = rng.integers(0, n_months // 3)
        stop = rng.integers(2 * n_months // 3, n_months)
        for t in range(start, stop):
            if rng.random() < absent_frac:
                continue  # firm-month row absent entirely (gap)
            row = {"permno": 10000 + firm, "mthcaldt": months[t]}
            row["retx"] = rng.normal(0.01, 0.08)
            for col in pred_cols:
                row[col] = np.nan if rng.random() < missing_frac else rng.normal()
            records.append(row)
    return pd.DataFrame(records), pred_cols


# ---------------------------------------------------------------------------
# Characteristic oracles (reference formulas in pandas, loop-based and slow)
# ---------------------------------------------------------------------------


def _groupby_rolling(df, col, window, min_periods, fn):
    out = (
        df.groupby("permno")[col]
        .rolling(window=window, min_periods=min_periods)
        .apply(fn, raw=True)
        if fn is not None
        else df.groupby("permno")[col].rolling(window=window, min_periods=min_periods).sum()
    )
    return out.reset_index(level=0, drop=True)


def oracle_monthly_characteristics(crsp_comp: pd.DataFrame) -> pd.DataFrame:
    """The 12 monthly characteristics, transcribing the reference formulas
    (src/calc_Lewellen_2014.py:137-341)."""
    df = crsp_comp.sort_values(["permno", "mthcaldt"], kind="stable").copy()
    g = lambda col: df.groupby("permno")[col]

    df["log_size"] = np.log(g("me").shift(1))
    df["log_bm"] = np.log(g("be").shift(1)) - np.log(g("me").shift(1))

    df["_one_plus"] = 1 + g("retx").shift(2)
    df["return_12_2"] = _groupby_rolling(df, "_one_plus", 11, 11, np.prod) - 1

    df["accruals_final"] = df["accruals"] - df["depreciation"]
    df["roa"] = df["earnings"] / df["assets"]
    df["log_assets_growth"] = np.log(df["assets"] / g("assets").shift(12))

    df["_div12"] = _groupby_rolling(df, "dvc", 12, 1, None)
    df["dy"] = df["_div12"] / g("prc").shift(1)

    df["_l13"] = df.groupby("permno")["retx"].transform(lambda s: np.log1p(s).shift(13))
    # .rolling().sum() (the reference's call), NOT .apply(np.sum): they
    # differ when a window holds -inf from a -100% return (sum -> NaN).
    df["log_return_13_36"] = _groupby_rolling(df, "_l13", 24, 24, None)

    df["log_issues_12"] = np.log(g("shrout").shift(1)) - np.log(g("shrout").shift(12))
    df["log_issues_36"] = np.log(g("shrout").shift(1)) - np.log(g("shrout").shift(36))
    df["debt_price"] = df["total_debt"] / g("me").shift(1)
    df["sales_price"] = df["sales"] / g("me").shift(1)

    return df.drop(columns=["_one_plus", "_div12", "_l13"])


def oracle_std_12(crsp_d: pd.DataFrame, crsp_comp: pd.DataFrame) -> pd.DataFrame:
    """252-day rolling std sampled at month end (src/calc_Lewellen_2014.py:438-465)."""
    d = crsp_d.sort_values(["permno", "dlycaldt"], kind="stable").copy()
    d["rolling_std_252"] = (
        d.groupby("permno")["retx"]
        .rolling(window=252, min_periods=100)
        .std()
        .reset_index(level=0, drop=True)
        * np.sqrt(252)
    )
    d["jdate"] = d["dlycaldt"].dt.to_period("M").dt.to_timestamp("M")
    d = d.drop_duplicates(subset=["permno", "jdate"], keep="last")
    return crsp_comp.merge(
        d[["permno", "jdate", "rolling_std_252"]], on=["permno", "jdate"], how="left"
    )


def oracle_weekly_beta(crsp_d: pd.DataFrame, crsp_index_d: pd.DataFrame,
                       crsp_comp: pd.DataFrame) -> pd.DataFrame:
    """Weekly-grid forward-window rolling beta, loop transcription of the
    polars group_by_dynamic semantics (src/calc_Lewellen_2014.py:344-434):
    Monday-lattice window starts per firm from first to last observation,
    window [start, start + 156 weeks), label = start, month-end stamp of the
    label, keep-last per (permno, month)."""
    joined = crsp_d[["permno", "dlycaldt", "retx"]].merge(
        crsp_index_d[["caldt", "vwretx"]].rename(columns={"caldt": "dlycaldt"}),
        on="dlycaldt",
    )
    joined["ri"] = np.log1p(joined["retx"])
    joined["rm"] = np.log1p(joined["vwretx"])
    joined = joined.sort_values(["permno", "dlycaldt"], kind="stable")

    rows = []
    for permno, grp in joined.groupby("permno"):
        dates = grp["dlycaldt"]
        week_start = dates - pd.to_timedelta(dates.dt.weekday, unit="D")
        starts = pd.date_range(week_start.min(), week_start.max(), freq="7D")
        for start in starts:
            win = grp[(dates >= start) & (dates < start + pd.Timedelta(weeks=156))]
            n = len(win)
            if n == 0:
                continue
            # polars semantics: pl.DataFrame(pandas_df) converts NaN->null
            # (nan_to_null=True default), aggregate sums SKIP nulls, but
            # pl.count() counts ALL rows in the window -> null-skipping sums
            # over a row-count denominator (pandas skipna sums match).
            s_ri, s_rm = win["ri"].sum(), win["rm"].sum()
            s_rirm = (win["ri"] * win["rm"]).sum()
            s_rm2 = (win["rm"] ** 2).sum()
            denom = s_rm2 - s_rm**2 / n
            beta = (s_rirm - s_ri * s_rm / n) / denom if denom != 0 else np.nan
            rows.append({"permno": permno, "date": start, "beta": beta})

    b = pd.DataFrame(rows)
    b["jdate"] = b["date"].dt.to_period("M").dt.to_timestamp("M")
    b = b.drop_duplicates(subset=["permno", "jdate"], keep="last")
    return crsp_comp.merge(b[["permno", "jdate", "beta"]], on=["permno", "jdate"], how="left")


def oracle_winsorize(crsp_comp: pd.DataFrame, varlist) -> pd.DataFrame:
    """Per-month [1%, 99%] clip, skipping months with <5 valid obs
    (src/calc_Lewellen_2014.py:505-529)."""
    df = crsp_comp.sort_values(["mthcaldt", "permno"], kind="stable").copy()
    for var in varlist:
        parts = []
        for _, sub in df.groupby("mthcaldt"):
            vals = sub[var].dropna()
            if len(vals) >= 5:
                low = np.percentile(vals, 1)
                high = np.percentile(vals, 99)
                sub = sub.copy()
                sub[var] = sub[var].clip(lower=low, upper=high)
            parts.append(sub)
        df = pd.concat(parts)
    return df
