"""L1 DataFrame helpers + the published-oracle module."""

import numpy as np
import pandas as pd
import pytest

from fm_returnprediction_tpu.reporting.published import (
    PUBLISHED_TABLE_1,
    compare_table_1,
    published_table_1,
)
from fm_returnprediction_tpu.utils.frames import (
    filter_columns_and_indexes,
    fix_dates_index,
    time_series_to_df,
)


# -- frames ---------------------------------------------------------------

def test_time_series_to_df_variants():
    s1 = pd.Series([1, 2], index=[0, 1], name="a")
    s2 = pd.Series([3.0, 4.0], index=[1, 2], name="b")
    df = time_series_to_df([s1, s2])
    assert list(df.columns) == ["a", "b"]
    assert len(df) == 3 and np.isnan(df.loc[0, "b"])
    assert time_series_to_df(s1).shape == (2, 1)
    pd.testing.assert_frame_equal(time_series_to_df(df), df)
    with pytest.raises(TypeError):
        time_series_to_df([s1, "not-a-series"])
    with pytest.raises(TypeError):
        time_series_to_df(42)


def test_fix_dates_index_promotes_date_column():
    df = pd.DataFrame({"Date": ["2020-01-31", "2020-02-29"], "x": ["1", "2"]})
    out = fix_dates_index(df)
    assert out.index.name == "date"
    assert isinstance(out.index, pd.DatetimeIndex)
    assert out["x"].dtype == float


def test_fix_dates_index_existing_datetime_index():
    idx = pd.to_datetime(["2020-01-31", "2020-02-29"])
    df = pd.DataFrame({"x": [1, 2]}, index=idx)
    out = fix_dates_index(df)
    assert out.index.name == "date"


def test_filter_columns_and_indexes():
    df = pd.DataFrame(
        np.arange(12).reshape(3, 4),
        columns=["alpha", "beta", "gamma", "Beta2"],
        index=["row_a", "row_b", "other"],
    )
    kept = filter_columns_and_indexes(df, keep_columns=["beta"])
    assert list(kept.columns) == ["beta", "Beta2"]  # case-insensitive substring
    dropped = filter_columns_and_indexes(df, drop_columns=["beta"])
    assert list(dropped.columns) == ["alpha", "gamma"]
    kept_rows = filter_columns_and_indexes(df, keep_indexes=["row"])
    assert list(kept_rows.index) == ["row_a", "row_b"]
    # the reference's drop_indexes branch is broken (src/utils.py:462-464);
    # ours must actually drop
    dropped_rows = filter_columns_and_indexes(df, drop_indexes=["row"])
    assert list(dropped_rows.index) == ["other"]
    assert filter_columns_and_indexes("not a frame") == "not a frame"


# -- published oracle -----------------------------------------------------

def test_published_layout_matches_reference_contract():
    """16 rows × 9 cols, publication row order, (Subset, Statistic) columns
    (``src/test_calc_Lewellen_2014.py:20-66``)."""
    t = published_table_1()
    assert t.shape == (16, 9)
    assert list(t.index[:4]) == [
        "Return (%)", "LogSize_{-1}", "LogB/M_{-1}", "Return_{-2,-12}",
    ]
    assert t.columns.names == ["Subset", "Statistic"]
    assert float(t.loc["Return (%)", ("All stocks", "Avg")]) == 1.27
    assert float(t.loc["Sales/Price_{yr-1}", ("Large stocks", "N")]) == 865


def test_published_computed_scope_excludes_turnover():
    t = published_table_1(computed_only=True)
    assert t.shape == (15, 9)
    assert "Turnover_{-1,-12}" not in t.index
    assert not PUBLISHED_TABLE_1["Turnover_{-1,-12}"][0]


def test_compare_table_1_detects_mismatch():
    oracle = published_table_1(computed_only=True)
    diff = compare_table_1(oracle)          # oracle vs itself → all ok
    assert len(diff) == 15 * 9 and diff["ok"].all()

    perturbed = oracle.copy()
    perturbed.loc["ROA_{yr-1}", ("All stocks", "Avg")] += 1.0
    diff = compare_table_1(perturbed)
    bad = diff[~diff["ok"]]
    assert len(bad) == 1
    assert bad.iloc[0]["variable"] == "ROA_{yr-1}" and bad.iloc[0]["stat"] == "Avg"


def test_compare_table_1_label_map_and_missing_rows():
    oracle = published_table_1(computed_only=True)
    renamed = oracle.rename(index={"ROA_{yr-1}": "ROA (-1)"})
    diff = compare_table_1(renamed, label_map={"ROA (-1)": "ROA_{yr-1}"})
    assert set(diff["variable"]) == set(oracle.index)
    # rows absent from the produced table are skipped, not errors
    partial = oracle.iloc[:3]
    diff = compare_table_1(partial)
    assert set(diff["variable"]) == set(oracle.index[:3])


def test_filter_series_input():
    s = pd.Series([1, 2, 3], index=["alpha", "beta", "gamma"])
    # column filters are no-ops on a Series; index filters apply
    out = filter_columns_and_indexes(s, drop_columns=["alp"])
    pd.testing.assert_series_equal(out, s)
    out = filter_columns_and_indexes(s, drop_indexes=["alp"])
    assert list(out.index) == ["beta", "gamma"]
