"""Firm-sharded daily kernels: parity with the single-device path and the
zero-communication guarantee (no collectives in the compiled program)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fm_returnprediction_tpu.ops.daily_kernels import (
    rolling_vol_252_monthly,
    weekly_rolling_beta_monthly,
)
from fm_returnprediction_tpu.parallel import make_mesh
from fm_returnprediction_tpu.parallel.daily_sharded import (
    _jitted_daily,
    daily_characteristics_sharded,
)


@pytest.fixture(scope="module")
def daily_inputs():
    rng = np.random.default_rng(17)
    n_days, n_firms, n_months = 400, 52, 19
    n_weeks = 60
    ret = 0.02 * rng.standard_normal((n_days, n_firms))
    mask = rng.random((n_days, n_firms)) > 0.15
    ret = np.where(rng.random((n_days, n_firms)) > 0.02, ret, np.nan)
    mkt = 0.01 * rng.standard_normal(n_days)
    mkt[rng.random(n_days) < 0.03] = np.nan
    month_id = np.minimum(np.arange(n_days) // 21, n_months - 1)
    week_id = np.minimum(np.arange(n_days) // 7, n_weeks - 1)
    week_month_id = np.minimum(np.arange(n_weeks) * 7 // 21, n_months - 1)
    return dict(
        ret_d=ret, mask_d=mask, mkt_d=mkt,
        month_id=month_id, week_id=week_id, week_month_id=week_month_id,
        n_months=n_months, n_weeks=n_weeks,
    )


def test_sharded_daily_matches_single_device(daily_inputs):
    d = daily_inputs
    mesh = make_mesh(axis_name="firms")
    vol_s, beta_s = daily_characteristics_sharded(mesh=mesh, **d)
    n = d["ret_d"].shape[1]
    vol_s = np.asarray(vol_s)[:, :n]
    beta_s = np.asarray(beta_s)[:, :n]

    vol_1 = np.asarray(rolling_vol_252_monthly(
        jnp.asarray(d["ret_d"]), jnp.asarray(d["mask_d"]),
        jnp.asarray(d["month_id"]), d["n_months"],
    ))
    beta_1 = np.asarray(weekly_rolling_beta_monthly(
        jnp.asarray(d["ret_d"]), jnp.asarray(d["mask_d"]),
        jnp.asarray(d["mkt_d"]), jnp.asarray(d["week_id"]), d["n_weeks"],
        jnp.asarray(d["week_month_id"]), d["n_months"],
    ))
    np.testing.assert_allclose(vol_s, vol_1, rtol=1e-12, atol=0, equal_nan=True)
    np.testing.assert_allclose(beta_s, beta_1, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_sharded_daily_outputs_stay_firm_sharded(daily_inputs):
    d = daily_inputs
    mesh = make_mesh(axis_name="firms")
    vol_s, beta_s = daily_characteristics_sharded(mesh=mesh, **d)
    assert vol_s.sharding.spec[1] == "firms"
    assert beta_s.sharding.spec[1] == "firms"


def test_sharded_daily_compiles_without_collectives(daily_inputs):
    """Firms are independent: the partitioned program must contain no
    cross-device communication at all."""
    d = daily_inputs
    mesh = make_mesh(axis_name="firms")
    run = _jitted_daily(mesh, "firms", d["n_months"], d["n_weeks"], 252, 100, 156)

    from jax.sharding import NamedSharding, PartitionSpec as P

    strip = NamedSharding(mesh, P(None, "firms"))
    rep = NamedSharding(mesh, P())
    n_firms = d["ret_d"].shape[1]
    pad = (-n_firms) % 8
    ret = jnp.pad(jnp.asarray(d["ret_d"]), ((0, 0), (0, pad)),
                  constant_values=jnp.nan)
    mask = jnp.pad(jnp.asarray(d["mask_d"]), ((0, 0), (0, pad)))
    args = (
        jax.device_put(ret, strip),
        jax.device_put(mask, strip),
        jax.device_put(jnp.asarray(d["mkt_d"]), rep),
        jax.device_put(jnp.isfinite(jnp.asarray(d["mkt_d"])), rep),
        jax.device_put(jnp.asarray(d["month_id"]), rep),
        jax.device_put(jnp.asarray(d["week_id"]), rep),
        jax.device_put(jnp.asarray(d["week_month_id"]), rep),
    )
    hlo = run.lower(*args).compile().as_text()
    for op in ("all-reduce", "all-gather", "collective-permute", "all-to-all",
               "reduce-scatter"):
        assert op not in hlo, f"unexpected collective {op} in daily program"
