"""Differential test of the weekly beta against REAL polars.

Round-1 VERDICT item 3: the beta kernel and its loop oracle
(``tests/oracle.py::oracle_weekly_beta``) both encode the same author's
reading of the reference's polars call
(``group_by_dynamic(every="1w", period="156w", by="permno")``,
``src/calc_Lewellen_2014.py:396-410``) — a shared misreading would pass
every in-repo test. This test runs the reference's ACTUAL polars pipeline
(transcribed call-for-call from ``src/calc_Lewellen_2014.py:368-430``) on
synthetic daily data and asserts the kernel reproduces it: lattice
anchoring, window direction, label/month stamping, null semantics.

polars is not installed in the build image (zero egress — wheel cannot be
vendored), so the test gates on importability and SKIPS there; it runs
wherever polars 1.x is present (the reference pins polars==1.22.0).
"""

import numpy as np
import pandas as pd
import pytest

pl = pytest.importorskip("polars")

import jax.numpy as jnp

from fm_returnprediction_tpu.data.synthetic import SyntheticConfig, generate_synthetic_wrds
from fm_returnprediction_tpu.ops.daily_kernels import weekly_rolling_beta_monthly
from fm_returnprediction_tpu.panel.daily import build_daily_panel


def _reference_polars_beta(crsp_d: pd.DataFrame, crsp_index_d: pd.DataFrame) -> pd.DataFrame:
    """The reference's beta computation, verbatim semantics
    (src/calc_Lewellen_2014.py:368-430): inner join on date, log1p,
    group_by_dynamic 1w/156w by permno, closed-form beta from partial sums,
    month-end label stamp, keep-last dedup per (permno, month)."""
    df = crsp_d[["permno", "dlycaldt", "retx"]].rename(
        columns={"retx": "Ri", "dlycaldt": "date"}
    )
    mkt = crsp_index_d[["caldt", "vwretx"]].rename(
        columns={"vwretx": "Rm", "caldt": "date"}
    )
    df_joined = pl.DataFrame(df).join(pl.DataFrame(mkt), on="date")
    df_joined = df_joined.with_columns(
        [
            (pl.col("Ri") + 1).log().alias("log_Ri"),
            (pl.col("Rm") + 1).log().alias("log_Rm"),
        ]
    ).sort(["permno", "date"])
    out = (
        df_joined.lazy()
        .group_by_dynamic(index_column="date", every="1w", period="156w", by="permno")
        .agg(
            [
                pl.col("log_Ri").sum().alias("sum_Ri"),
                pl.col("log_Rm").sum().alias("sum_Rm"),
                (pl.col("log_Ri") * pl.col("log_Rm")).sum().alias("sum_RiRm"),
                (pl.col("log_Rm") ** 2).sum().alias("sum_Rm2"),
                pl.count().alias("count_obs"),
            ]
        )
        .with_columns(
            [
                (
                    (pl.col("sum_RiRm") - pl.col("sum_Ri") * pl.col("sum_Rm") / pl.col("count_obs"))
                    / (pl.col("sum_Rm2") - pl.col("sum_Rm") ** 2 / pl.col("count_obs"))
                ).alias("beta")
            ]
        )
        .collect()
        .to_pandas()
    )
    out["jdate"] = pd.to_datetime(out["date"]).dt.to_period("M").dt.to_timestamp("M")
    out = out.drop_duplicates(subset=["permno", "jdate"], keep="last")
    return out[["permno", "jdate", "beta"]]


def test_weekly_beta_matches_real_polars():
    data = generate_synthetic_wrds(SyntheticConfig(n_firms=40, n_months=50))
    crsp_d, crsp_index_d = data["crsp_d"], data["crsp_index_d"]
    # exercise null semantics: null some returns, drop some index days
    crsp_d = crsp_d.copy()
    rng = np.random.default_rng(0)
    null_rows = rng.random(len(crsp_d)) < 0.02
    crsp_d.loc[null_rows, "retx"] = np.nan
    crsp_index_d = crsp_index_d[rng.random(len(crsp_index_d)) > 0.01]

    months = np.sort(data["crsp_m"]["jdate"].unique())
    expected = _reference_polars_beta(crsp_d, crsp_index_d)

    daily = build_daily_panel(crsp_d, crsp_index_d, months)
    beta = np.asarray(
        weekly_rolling_beta_monthly(
            jnp.asarray(daily.ret), jnp.asarray(daily.mask), jnp.asarray(daily.mkt),
            jnp.asarray(daily.week_id), daily.n_weeks,
            jnp.asarray(daily.week_month_id), daily.n_months,
            mkt_present=jnp.asarray(daily.mkt_present),
        )
    )

    month_pos = {pd.Timestamp(m): i for i, m in enumerate(months)}
    id_pos = {p: i for i, p in enumerate(daily.ids)}
    checked = 0
    for _, row in expected.iterrows():
        m = month_pos.get(pd.Timestamp(row["jdate"]))
        f = id_pos.get(row["permno"])
        if m is None or f is None:
            continue  # label outside the monthly panel window
        got = beta[m, f]
        want = row["beta"]
        if pd.isna(want):
            assert np.isnan(got), (row["permno"], row["jdate"], got)
        else:
            assert np.isfinite(got), (row["permno"], row["jdate"], want)
            np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)
        checked += 1
    assert checked > 200, f"only {checked} (permno, month) cells compared"
