"""Property-based differential tests: rolling ops vs pandas, random shapes.

The fixed-case oracles (`tests/test_rolling_ops.py`) pin the pipeline's
window/min_periods combinations; these hypothesis sweeps cover the space
between them — arbitrary windows, min_periods, NaN densities and series
lengths — against pandas ``rolling`` as the semantics oracle (the reference
is pandas, SURVEY §2.1 ★ rows). Small example counts keep the 1-core suite
fast; failures shrink to minimal cases.
"""

import numpy as np
import pandas as pd
import pytest

pytest.importorskip("hypothesis")  # tier-1 must COLLECT cleanly without the optional dep
from hypothesis import given, settings, strategies as st

import jax.numpy as jnp

from fm_returnprediction_tpu.ops.rolling import (
    rolling_mean,
    rolling_prod,
    rolling_std,
    rolling_sum,
)

@st.composite
def _cases(draw):
    t = draw(st.integers(min_value=1, max_value=40))
    window = draw(st.integers(min_value=1, max_value=12))
    # pandas requires min_periods <= window
    min_periods = draw(st.integers(min_value=1, max_value=window))
    nan_frac = draw(st.floats(min_value=0.0, max_value=0.6))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    return t, window, min_periods, nan_frac, seed


_CASE = _cases()


def _series(t, nan_frac, seed):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((t, 2))
    x[rng.random((t, 2)) < nan_frac] = np.nan
    return x


def _check(op, pandas_op, t, window, min_periods, nan_frac, seed):
    x = _series(t, nan_frac, seed)
    got = np.asarray(op(jnp.asarray(x), window, min_periods))
    want = pandas_op(pd.DataFrame(x)).to_numpy()
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-9)


@settings(max_examples=25, deadline=None)
@given(_CASE)
def test_rolling_sum_matches_pandas(case):
    t, w, mp, nf, seed = case
    _check(rolling_sum, lambda df: df.rolling(w, min_periods=mp).sum(),
           t, w, mp, nf, seed)


@settings(max_examples=25, deadline=None)
@given(_CASE)
def test_rolling_mean_matches_pandas(case):
    t, w, mp, nf, seed = case
    _check(rolling_mean, lambda df: df.rolling(w, min_periods=mp).mean(),
           t, w, mp, nf, seed)


@settings(max_examples=25, deadline=None)
@given(_CASE)
def test_rolling_std_matches_pandas(case):
    t, w, mp, nf, seed = case
    _check(rolling_std, lambda df: df.rolling(w, min_periods=mp).std(),
           t, w, mp, nf, seed)


@settings(max_examples=25, deadline=None)
@given(_CASE)
def test_rolling_prod_matches_pandas(case):
    """pandas .apply(np.prod) propagates NaN once min_periods non-NaN rows
    are present (np.prod of a window containing NaN is NaN)."""
    t, w, mp, nf, seed = case
    _check(
        rolling_prod,
        lambda df: df.rolling(w, min_periods=mp).apply(np.prod, raw=True),
        t, w, mp, nf, seed,
    )
