"""Buffer donation through the panel→specgrid chain, asserted at the
LOWERING level: a donated buffer must actually alias an output
(``tf.aliasing_output`` in the stablehlo), not merely be marked donated —
an unusable donation silently keeps both generations live, which is
exactly the failure mode this PR removes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.kernels


def _aliased_params(lowered_text: str):
    """Zero-based positions of parameters carrying an aliasing attribute."""
    import re

    # main signature: %argN: tensor<...> {..tf.aliasing_output = M..}
    return [
        int(m.group(1))
        for m in re.finditer(
            r"%arg(\d+): tensor<[^>]+> \{[^}]*tf\.aliasing_output",
            lowered_text,
        )
    ]


def test_rewinsorize_into_aliases_scratch():
    from fm_returnprediction_tpu.specgrid.scenarios import _rewinsorize_into

    x = jnp.asarray(np.random.default_rng(0).standard_normal((6, 40, 3)),
                    jnp.float32)
    mask = jnp.ones((6, 40), bool)
    scratch = jnp.zeros_like(x)
    txt = _rewinsorize_into.lower(scratch, x, mask, 5.0, 95.0).as_text()
    assert 0 in _aliased_params(txt), (
        "the donated scratch must alias the re-clipped output"
    )


def test_scatter_winsorized_aliases_panel():
    from fm_returnprediction_tpu.panel.characteristics import (
        _scatter_winsorized,
    )

    values = jnp.zeros((4, 16, 5), jnp.float32)
    win = jnp.ones((4, 16, 2), jnp.float32)
    txt = _scatter_winsorized.lower(values, win, jnp.asarray([1, 3])).as_text()
    assert 0 in _aliased_params(txt)


def test_rewinsorize_into_matches_undonated_and_consumes_scratch():
    from fm_returnprediction_tpu.specgrid.scenarios import winsor_variant

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 60, 4)), jnp.float32)
    mask = jnp.asarray(rng.random((8, 60)) > 0.2)
    plain = winsor_variant(x, mask, 5.0)
    scratch = jnp.zeros_like(x) + 1.0
    donated = winsor_variant(x, mask, 5.0, scratch=scratch)
    # the donated variant is a separately-compiled program: values agree to
    # FMA-level fusion drift (the documented behavior of every
    # reorganization of a winsorize program — see `_enrich_winsorized`)
    np.testing.assert_allclose(np.asarray(plain), np.asarray(donated),
                               rtol=1e-6, atol=1e-7)
    # the scratch buffer is CONSUMED: a donated array is deleted after use
    assert scratch.is_deleted()
    # shape/dtype-mismatched scratch falls back to the undonated program
    bad = jnp.zeros((8, 60, 3), jnp.float32)
    again = winsor_variant(x, mask, 5.0, scratch=bad)
    np.testing.assert_array_equal(np.asarray(plain), np.asarray(again))
    assert not bad.is_deleted()


def test_engine_reclip_double_buffers_across_winsor_groups():
    """The tile engine's winsor ladder re-clips into the previous level's
    buffer: values identical to fresh re-clips, old generation consumed."""
    from fm_returnprediction_tpu.specgrid.engine import _Engine
    from fm_returnprediction_tpu.specgrid.cellspace import scenario_space
    from fm_returnprediction_tpu.specgrid.scenarios import winsor_variant

    rng = np.random.default_rng(3)
    t, n = 6, 50
    y = rng.standard_normal((t, n)).astype(np.float32)
    x = rng.standard_normal((t, n, 2)).astype(np.float32)
    mask = np.ones((t, n), bool)
    masks = {"all": mask}

    class _M:
        name = "m1"
        predictors = ("A", "B")               # display labels, per MODELS

    space = scenario_space({"A": "c0", "B": "c1"}, ["all"], t, models=[_M()],
                           subperiods=1, winsor_levels=(1.0, 5.0, 10.0))
    engine = _Engine(y, x, masks, space, mask=mask, route="gram", mesh=None,
                     referee=True, firm_chunk=None, label_of=None, seed=0,
                     coreset_m=None, coreset_budget_mb=None, tile_cells=64)
    x5 = engine.x_at_level(5.0)
    want10 = np.asarray(winsor_variant(engine.x_base, jnp.asarray(mask), 10.0))
    x10 = engine.x_at_level(10.0)           # re-clips INTO x5's buffer
    np.testing.assert_allclose(np.asarray(x10), want10, rtol=1e-6, atol=1e-7)
    assert x5.is_deleted()                  # the old generation was donated
    assert not engine.x_base.is_deleted()   # the base is never donated
    # returning to the base level must not donate anything either
    assert engine.x_at_level(1.0) is engine.x_base
