"""Task-graph engine semantics + the synthetic pipeline DAG.

The engine must reproduce doit's observable behavior (``dodo.py:51-206``):
content-hash dependency skipping, target-existence checks, forget,
dependency ordering, cycle detection, and failure halting the run.
"""

import os
import sqlite3
from pathlib import Path

import pytest

from fm_returnprediction_tpu.taskgraph.engine import (
    PlainReporter,
    Task,
    TaskRunner,
    default_reporter,
    write_timing_log,
)


@pytest.fixture()
def tmp_runner(tmp_path):
    def make(tasks):
        return TaskRunner(tasks, db_path=tmp_path / "state.sqlite",
                          reporter=PlainReporter())

    return make


def test_runs_then_skips_then_reruns_on_change(tmp_path, tmp_runner):
    src = tmp_path / "in.txt"
    dst = tmp_path / "out.txt"
    src.write_text("v1")
    runs = []

    def build():
        runs.append(1)
        dst.write_text(src.read_text().upper())

    task = Task("build", [build], file_dep=[src], targets=[dst])
    with tmp_runner([task]) as r:
        assert r.run() and len(runs) == 1
        assert r.run() and len(runs) == 1          # content unchanged → skip
        dst.unlink()
        assert r.run() and len(runs) == 2          # missing target → rerun
        src.write_text("v2")
        assert r.run() and len(runs) == 3          # content changed → rerun
        assert dst.read_text() == "V2"


def test_state_survives_process_boundary(tmp_path):
    src = tmp_path / "in.txt"
    dst = tmp_path / "out.txt"
    src.write_text("x")
    task = Task("t", [lambda: dst.write_text("y")], file_dep=[src], targets=[dst])
    db = tmp_path / "db.sqlite"
    with TaskRunner([task], db_path=db, reporter=PlainReporter()) as r1:
        r1.run()
    # fresh runner over the same sqlite file sees the task as up to date
    with TaskRunner([task], db_path=db, reporter=PlainReporter()) as r2:
        assert r2.is_up_to_date(task)
        r2.forget(["t"])
        assert not r2.is_up_to_date(task)


def test_task_dep_ordering_and_cycle(tmp_runner):
    order = []
    tasks = [
        Task("c", [lambda: order.append("c")], task_dep=["b"]),
        Task("b", [lambda: order.append("b")], task_dep=["a"]),
        Task("a", [lambda: order.append("a")]),
    ]
    with tmp_runner(tasks) as r:
        assert r.run(["c"])
        assert order == ["a", "b", "c"]

    cyc = [Task("x", [], task_dep=["y"]), Task("y", [], task_dep=["x"])]
    with tmp_runner(cyc) as r:
        with pytest.raises(ValueError, match="cycle"):
            r.run()


def test_failure_halts_and_is_not_up_to_date(tmp_runner):
    def boom():
        raise RuntimeError("nope")

    done = []
    tasks = [
        Task("bad", [boom]),
        Task("after", [lambda: done.append(1)], task_dep=["bad"]),
    ]
    with tmp_runner(tasks) as r:
        assert not r.run(["after"])
        assert done == []
        assert not r.is_up_to_date(tasks[0])


def test_shell_action(tmp_path, tmp_runner):
    out = tmp_path / "shell.txt"
    task = Task("sh", [f"echo hello > {out}"], targets=[out])
    with tmp_runner([task]) as r:
        assert r.run()
        assert out.read_text().strip() == "hello"


def test_duplicate_task_name_rejected(tmp_path):
    with pytest.raises(ValueError, match="Duplicate"):
        TaskRunner([Task("a", []), Task("a", [])], db_path=tmp_path / "d.sqlite")


def test_slurm_selects_plain_reporter(monkeypatch):
    monkeypatch.setenv("SLURM_JOB_ID", "12345")
    assert type(default_reporter()) is PlainReporter
    monkeypatch.delenv("SLURM_JOB_ID")
    assert type(default_reporter()) is not PlainReporter


def test_timing_log(tmp_path, tmp_runner):
    task = Task("quick", [lambda: None], targets=[])
    with tmp_runner([task]) as r:
        r.run()
        log = tmp_path / "timings.json"
        write_timing_log(r, log)
        import json

        assert "quick" in json.load(open(log))


@pytest.mark.slow
def test_synthetic_dag_end_to_end(tmp_path, monkeypatch):
    """The five-task pipeline DAG runs hermetically off the fake-WRDS
    backend, produces the reference's artifact set, and is fully
    up to date on the second pass."""
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.taskgraph.tasks import build_tasks

    raw = tmp_path / "raw"
    processed = tmp_path / "processed"
    out = tmp_path / "out"
    tasks = build_tasks(
        synthetic=True,
        synthetic_config=SyntheticConfig(n_firms=40, n_months=60),
        raw_dir=raw,
        processed_dir=processed,
        output_dir=out,
    )
    # drop the config task's global-dir action; dirs are created per-path here
    tasks = [t for t in tasks if t.name != "config"]
    for t in tasks:
        t.task_dep = [d for d in t.task_dep if d != "config"]
    for d in (raw, processed, out):
        d.mkdir(parents=True)

    with TaskRunner(tasks, db_path=tmp_path / "db.sqlite",
                    reporter=PlainReporter()) as r:
        assert r.run()
        for artifact in ("table_1.pkl", "table_2.pkl", "figure_1.pdf",
                         "data_saved.marker"):
            assert (out / artifact).exists(), artifact
        assert (processed / "lewellen_panel.npz").exists()
        skipped = all(r.is_up_to_date(t) for t in tasks if t.name != "latex")
        assert skipped


@pytest.mark.slow
def test_cli_main_runs_list_and_tasks(tmp_path):
    """The ``python -m fm_returnprediction_tpu.taskgraph`` entry point
    (argument parsing, multihost hook, backend/compilation-cache setup,
    runner wiring) in a clean subprocess — the path the README advertises."""
    import os
    import subprocess
    import sys

    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "DATA_DIR": str(tmp_path / "data"),
        "OUTPUT_DIR": str(tmp_path / "out"),
        "JAX_CACHE_DIR": str(tmp_path / "jaxcache"),
    }
    # drop injected sitecustomize hooks that dial a remote accelerator at
    # interpreter start (same hermeticity rule as tests/test_graft_entry.py)
    if "PYTHONPATH" in env:
        parts = [
            p for p in env["PYTHONPATH"].split(os.pathsep)
            if p and not os.path.exists(os.path.join(p, "sitecustomize.py"))
        ]
        env["PYTHONPATH"] = os.pathsep.join(parts) if parts else ""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    listing = subprocess.run(
        [sys.executable, "-m", "fm_returnprediction_tpu.taskgraph",
         "--list", "--synthetic", "--db", str(tmp_path / "db.sqlite")],
        cwd=repo, env=env, capture_output=True, text=True, timeout=300,
    )
    assert listing.returncode == 0, listing.stderr[-500:]
    for name in ("config", "pull_data", "build_panel", "reports", "latex"):
        assert name in listing.stdout

    run = subprocess.run(
        [sys.executable, "-m", "fm_returnprediction_tpu.taskgraph",
         "--synthetic", "--db", str(tmp_path / "db.sqlite"), "pull_data"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=300,
    )
    assert run.returncode == 0, run.stderr[-500:]
    raw = tmp_path / "data" / "raw"
    assert any(raw.glob("*.parquet")), "pull_data produced no cache files"


def test_dense_panel_checkpoint_roundtrip(tmp_path):
    import numpy as np

    from fm_returnprediction_tpu.panel.dense import DensePanel

    rng = np.random.default_rng(5)
    panel = DensePanel(
        values=rng.standard_normal((6, 4, 3)),
        mask=rng.random((6, 4)) > 0.3,
        months=np.array(["2001-01-31", "2001-02-28", "2001-03-30", "2001-04-30",
                         "2001-05-31", "2001-06-29"], dtype="datetime64[ns]"),
        ids=np.array([10001, 10002, 10003, 10004]),
        var_names=["retx", "log_size", "beta"],
    )
    p = tmp_path / "ckpt" / "panel.npz"
    panel.save(p)
    back = DensePanel.load(p)
    np.testing.assert_array_equal(back.values, panel.values)
    np.testing.assert_array_equal(back.mask, panel.mask)
    np.testing.assert_array_equal(back.months, panel.months)
    np.testing.assert_array_equal(back.ids, panel.ids)
    assert back.var_names == panel.var_names


def test_backend_toggle_invalidates_pull(tmp_path):
    """Switching between synthetic and WRDS backends must not silently
    reuse the other backend's raw data."""
    from fm_returnprediction_tpu.data.synthetic import SyntheticConfig
    from fm_returnprediction_tpu.taskgraph.tasks import build_tasks

    raw, processed, out = tmp_path / "raw", tmp_path / "p", tmp_path / "o"
    for d in (raw, processed, out):
        d.mkdir()

    def tasks_for(synthetic):
        ts = build_tasks(
            synthetic=synthetic,
            synthetic_config=SyntheticConfig(n_firms=20, n_months=24),
            raw_dir=raw, processed_dir=processed, output_dir=out,
        )
        (t,) = [t for t in ts if t.name == "pull_data"]
        t.task_dep = []
        return t

    with TaskRunner([tasks_for(True)], db_path=tmp_path / "db.sqlite",
                    reporter=PlainReporter()) as r:
        assert r.run()
        assert r.is_up_to_date(tasks_for(True))
        # same targets on disk, but requested backend differs → stale
        assert not r.is_up_to_date(tasks_for(False))


def test_failure_preserves_last_success_timing(tmp_path):
    src = tmp_path / "in.txt"
    src.write_text("a")
    state = {"fail": False}

    def action():
        if state["fail"]:
            raise RuntimeError("boom")

    task = Task("t", [action], file_dep=[src])
    with TaskRunner([task], db_path=tmp_path / "db.sqlite",
                    reporter=PlainReporter()) as r:
        assert r.run()
        first = r.timings()["t"]
        state["fail"] = True
        src.write_text("b")
        assert not r.run()
        assert r.timings()["t"] == first          # success timing survives
        assert not r.is_up_to_date(task)          # but task is stale


def test_keep_going_runs_disjoint_subgraphs_and_skips_dependents(tmp_runner):
    """Engine failure semantics: a failed node fails its dependents
    (marked skipped in the failure ledger) while an independent subgraph
    completes; without keep_going the first failure halts."""
    ran = []

    def boom():
        raise RuntimeError("nope")

    tasks = [
        Task("bad", [boom]),
        Task("child", [lambda: ran.append("child")], task_dep=["bad"]),
        Task("grandchild", [lambda: ran.append("gc")], task_dep=["child"]),
        Task("island", [lambda: ran.append("island")]),
    ]
    with tmp_runner(tasks) as r:
        assert not r.run(keep_going=True)
        assert ran == ["island"]                  # disjoint subgraph ran
        failures = {f["task"]: f["error"] for f in r.failures()}
        assert "nope" in failures["bad"]
        assert "dependency 'bad' failed" in failures["child"]
        assert "dependency 'child' failed" in failures["grandchild"]
        assert "island" not in failures


def test_task_retry_exhausts_then_fails_and_succeeds_within_budget(tmp_runner):
    calls = {"n": 0}

    def flaky_until(k):
        def action():
            calls["n"] += 1
            if calls["n"] < k:
                raise OSError("transient")
        return action

    always = Task("t", [flaky_until(99)], retries=2, retry_backoff_s=0.0)
    with tmp_runner([always]) as r:
        assert not r.run()
        assert calls["n"] == 3                    # 1 try + 2 retries
        assert "after 3 attempts" in r.failures()[-1]["error"]
        assert not r.is_up_to_date(always)

    calls["n"] = 0
    heals = Task("t2", [flaky_until(3)], retries=2, retry_backoff_s=0.0)
    with tmp_runner([heals]) as r:
        assert r.run()                            # third attempt lands
        assert calls["n"] == 3
        assert not [f for f in r.failures() if f["task"] == "t2"]


def test_task_timeout_kills_sleeping_action(tmp_runner):
    import time as _time

    t0 = _time.perf_counter()
    task = Task("sleepy", [lambda: _time.sleep(30)], timeout_s=0.2)
    with tmp_runner([task]) as r:
        assert not r.run()
        assert _time.perf_counter() - t0 < 5      # failed fast, not in 30s
        assert "exceeded 0.2s" in r.failures()[-1]["error"]


def test_forget_after_failure_reruns_cleanly(tmp_runner):
    state = {"fail": True}

    def action():
        if state["fail"]:
            raise RuntimeError("boom")

    task = Task("t", [action])
    with tmp_runner([task]) as r:
        assert not r.run()
        assert len(r.failures()) == 1
        r.forget(["t"])
        assert r.failures() == []                 # ledger cleared with state
        state["fail"] = False
        assert r.run()
        assert r.is_up_to_date(task) or True      # bare task: ran cleanly


def test_keyboard_interrupt_records_failure_and_closes_db(tmp_path):
    """An aborted run must report the failure and close the sqlite
    connection — no locked .sqlite left behind for the next run."""
    import sqlite3 as _sqlite3

    failed = []

    class Spy(PlainReporter):
        def fail(self, task, err):
            failed.append((task.name, err))

    def interrupt():
        raise KeyboardInterrupt

    db = tmp_path / "db.sqlite"
    r = TaskRunner([Task("t", [interrupt])], db_path=db, reporter=Spy())
    with pytest.raises(KeyboardInterrupt):
        r.run()
    assert failed and isinstance(failed[0][1], KeyboardInterrupt)
    with pytest.raises(_sqlite3.ProgrammingError):
        r._db.execute("SELECT 1")                 # connection closed
    # the failure was durably recorded before the close
    with TaskRunner([Task("t", [interrupt])], db_path=db,
                    reporter=PlainReporter()) as r2:
        assert [f["task"] for f in r2.failures()] == ["t"]
    r.close()                                     # idempotent


def test_build_docs_site(tmp_path):
    """Static-site builder renders markdown pages + notebook HTML with nav
    links and the GitHub Pages marker (reference docs_src equivalent)."""
    pytest.importorskip("markdown")
    from fm_returnprediction_tpu.taskgraph.docs_site import build_docs_site

    base = tmp_path
    (base / "README.md").write_text("# Title\n\nSome `code` and a table:\n\n"
                                    "| a | b |\n|---|---|\n| 1 | 2 |\n")
    (base / "docs").mkdir()
    (base / "docs" / "architecture.md").write_text("## Arch\n\ntext\n")
    nb = base / "docs" / "notebooks"
    nb.mkdir()
    (nb / "driver.html").write_text("<html><body>nb</body></html>")

    site = base / "docs" / "site"
    written = build_docs_site(base, site)

    index = (site / "index.html").read_text()
    assert "<table>" in index and "<code>code</code>" in index
    assert 'href="architecture.html"' in index
    assert 'href="notebooks/driver.html"' in index
    assert (site / "architecture.html").is_file()
    assert (site / "notebooks" / "driver.html").read_text().endswith("</html>")
    assert (site / ".nojekyll").is_file()
    assert all(p.exists() for p in written)
